#!/usr/bin/env python3
"""Experiment driver entry point — `python3 attack.py [flags]`, same surface
as the reference's `attack.py` (smoke test by convention: run with no flags,
reference `README.md:148-149`)."""

import sys

from byzantinemomentum_tpu.cli.attack import main

if __name__ == "__main__":
    sys.exit(main())
