"""Unit + differential tests for the GAR kernels.

Strategy (SURVEY.md §4): hand-computable small cases, NaN fault injection,
convex-hull properties, and differential tests against independent
PyTorch-CPU oracles on random matrices.
"""

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from byzantinemomentum_tpu import ops

from . import reference_oracles as oracle

RNG = np.random.default_rng(42)


def rand_grads(n, d, nan_rows=0):
    g = RNG.normal(size=(n, d)).astype(np.float32)
    for i in range(nan_rows):
        g[n - 1 - i] = np.nan
    return g


ORACLES = {
    "average": (oracle.gar_average, {}),
    "median": (oracle.gar_median, {}),
    "native-median": (oracle.gar_median, {}),
    "trmean": (oracle.gar_trmean, {"f": True}),
    "phocas": (oracle.gar_phocas, {"f": True}),
    "meamed": (oracle.gar_meamed, {"f": True}),
    "krum": (oracle.gar_krum, {"f": True}),
    "native-krum": (oracle.gar_krum, {"f": True}),
    "bulyan": (oracle.gar_bulyan, {"f": True}),
    "native-bulyan": (oracle.gar_bulyan, {"f": True}),
    "aksel": (oracle.gar_aksel, {"f": True}),
    "cge": (oracle.gar_cge, {"f": True}),
    "brute": (oracle.gar_brute, {"f": True}),
    "native-brute": (oracle.gar_brute, {"f": True}),
}


def test_registry_complete():
    """Every reference GAR (SURVEY.md §2.1) is registered, plus the four
    native fast tiers (reference §2.9)."""
    expected = {"average", "median", "trmean", "phocas", "meamed", "krum",
                "bulyan", "aksel", "cge", "brute",
                "native-median", "native-krum", "native-bulyan", "native-brute"}
    assert expected <= set(ops.gars)


def test_template_registered():
    """The extension skeletons register runnable `"template"` entries whose
    check always declines, exactly like the reference
    (`aggregators/template.py:59`, `attacks/template.py:48`): the name
    resolves, the checked path reports template code."""
    from byzantinemomentum_tpu import attacks as attacks_mod
    from byzantinemomentum_tpu.utils import UserException

    g = jnp.zeros((5, 3))
    assert "template" in ops.gars
    with pytest.raises(UserException, match="template code"):
        ops.gars["template"].checked(g, f=1)
    assert "template" in attacks_mod.attacks
    with pytest.raises(UserException, match="template code"):
        attacks_mod.attacks["template"].checked(g, f_decl=1, f_real=1)


@pytest.mark.parametrize("name", sorted(ORACLES))
@pytest.mark.parametrize("n,f,d", [(11, 2, 13), (15, 3, 7),
                                   pytest.param(25, 5, 4, marks=pytest.mark.slow)])
def test_differential_vs_torch(name, n, f, d):
    fn, kw = ORACLES[name]
    g = rand_grads(n, d)
    kwargs = {"f": f} if kw.get("f") else {}
    got = np.asarray(ops.gars[name](jnp.asarray(g), **kwargs))
    want = fn(torch.from_numpy(g.copy()), **kwargs).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5, err_msg=name)


@pytest.mark.parametrize("name", ["median", "trmean", "phocas", "meamed",
                                  "krum", "bulyan", "aksel", "cge", "brute"])
def test_nan_resilience(name):
    """With f NaN rows, the aggregate must stay finite (the reference's core
    robustness claim; `nan` attack doubles as fault injection)."""
    n, f, d = 11, 2, 9
    g = rand_grads(n, d, nan_rows=f)
    out = np.asarray(ops.gars[name](jnp.asarray(g), f=f))
    assert np.isfinite(out).all(), f"{name} leaked NaN"


@pytest.mark.parametrize("name", ["median", "trmean", "phocas", "meamed",
                                  "krum", "bulyan", "aksel", "cge", "brute"])
def test_nan_differential(name):
    fn, kw = ORACLES[name]
    n, f, d = (15, 3, 6) if name == "bulyan" else (13, 3, 6)  # bulyan needs n >= 4f+3
    g = rand_grads(n, d, nan_rows=f)
    got = np.asarray(ops.gars[name](jnp.asarray(g), f=f))
    want = fn(torch.from_numpy(g.copy()), f=f).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5, err_msg=name)


def test_beyond_contract_nan_propagates():
    """With more non-finite rows than the selection margin tolerates
    (nb_real_byz > nb_decl_byz), the weight-matmul selection must surface
    NaN like the reference's gather-mean would — not a silently finite
    wrong value (round-2 advisor finding)."""
    n, f, d = 11, 2, 5
    m = n - f - 2
    g = rand_grads(n, d, nan_rows=n - m + 1)  # fewer than m finite rows
    out = np.asarray(ops.gars["krum"](jnp.asarray(g), f=f))
    assert np.isnan(out).all(), "krum masked a selected non-finite row"
    # Bulyan stage 1: round 0 averages m_max rows; with fewer finite rows a
    # NaN row enters that round's average
    from byzantinemomentum_tpu.ops import bulyan
    n2, f2 = 15, 3
    m_max = n2 - f2 - 2
    g = rand_grads(n2, d, nan_rows=n2 - m_max + 1)
    sel = np.asarray(bulyan.selected_stack(jnp.asarray(g), f2))
    assert np.isnan(sel[0]).all(), "bulyan masked a selected non-finite row"


def test_beyond_contract_nan_is_per_coordinate():
    """The propagation is per coordinate, as a row-gather mean's would be:
    rows that are NaN only at coordinate 0 poison coordinate 0 of the
    aggregate and leave the other coordinates finite."""
    n, f, d = 11, 2, 5
    m = n - f - 2
    g = rand_grads(n, d)
    for i in range(n - m + 1):  # more bad rows than the margin tolerates
        g[n - 1 - i, 0] = np.nan
    out = np.asarray(ops.gars["krum"](jnp.asarray(g), f=f))
    assert np.isnan(out[0]), "NaN coordinate of a selected row was masked"
    assert np.isfinite(out[1:]).all(), \
        "NaN propagation poisoned unaffected coordinates"


def test_median_hand_values():
    g = jnp.asarray(np.array([[1., 5.], [3., 1.], [2., 9.]], dtype=np.float32))
    np.testing.assert_allclose(np.asarray(ops.gars["median"](g)), [2., 5.])
    # Even n -> lower median
    g4 = jnp.asarray(np.array([[1.], [4.], [2.], [3.]], dtype=np.float32))
    np.testing.assert_allclose(np.asarray(ops.gars["median"](g4)), [2.])


def test_trmean_hand_values():
    g = jnp.asarray(np.array([[0.], [1.], [2.], [3.], [100.]], dtype=np.float32))
    np.testing.assert_allclose(np.asarray(ops.gars["trmean"](g, f=1)), [2.])


def test_krum_rejects_outlier():
    """An extreme outlier must never be selected."""
    n, f, d = 9, 2, 5
    g = rand_grads(n, d)
    g[-1] = 1e6
    sel = np.asarray(__import__("byzantinemomentum_tpu.ops.krum", fromlist=["selection"]).selection(jnp.asarray(g), f))
    assert n - 1 not in sel


def test_convex_hull_coordinate_rules():
    """Coordinate-wise rules stay within per-coordinate honest min/max when
    all inputs are honest."""
    g = rand_grads(9, 6)
    arr = jnp.asarray(g)
    for name in ("median", "trmean", "phocas", "meamed"):
        kwargs = {} if name == "median" else {"f": 2}
        out = np.asarray(ops.gars[name](arr, **kwargs))
        assert (out >= g.min(axis=0) - 1e-6).all() and (out <= g.max(axis=0) + 1e-6).all(), name


def test_checked_contract_errors():
    g = jnp.zeros((4, 3))
    with pytest.raises(Exception):
        ops.gars["krum"].checked(g, f=1)  # needs n >= 2f+3 = 5
    with pytest.raises(Exception):
        ops.gars["bulyan"].checked(g, f=1)  # needs n >= 4f+3 = 7
    with pytest.raises(Exception):
        ops.gars["trmean"].checked(g, f=2)  # needs n >= 2f+1 = 5


def test_upper_bounds_match_reference_formulas():
    import math
    n, f, d = 25, 5, 1000
    assert ops.gars["median"].upper_bound(n, f, d) == pytest.approx(1 / math.sqrt(n - f))
    assert ops.gars["brute"].upper_bound(n, f, d) == pytest.approx((n - f) / (math.sqrt(8) * f))
    krum_ub = 1 / math.sqrt(2 * (n - f + f * (n + f * (n - f - 2) - 2) / (n - 2 * f - 2)))
    assert ops.gars["krum"].upper_bound(n, f, d) == pytest.approx(krum_ub)
    assert ops.gars["bulyan"].upper_bound(n, f, d) == pytest.approx(krum_ub)


def test_influence_range_and_zero_for_honest_only():
    n, f = 11, 2
    honests = jnp.asarray(rand_grads(n - f, 5))
    byz = jnp.asarray(np.full((f, 5), 1e6, dtype=np.float32))
    for name in ("average", "krum", "aksel", "cge", "brute"):
        gar = ops.gars[name]
        assert gar.influence is not None, name
        ratio = float(gar.influence(honests, byz, f=f))
        assert 0.0 <= ratio <= 1.0, name
        if name != "average":
            # A huge-norm outlier should be rejected by the robust rules
            assert ratio == 0.0, name


def test_distance_methods_agree():
    from byzantinemomentum_tpu.ops._common import pairwise_distances
    g = jnp.asarray(rand_grads(12, 33))
    d_dot = np.asarray(pairwise_distances(g, method="dot"))
    d_diff = np.asarray(pairwise_distances(g, method="diff"))
    off = ~np.eye(12, dtype=bool)
    np.testing.assert_allclose(d_dot[off], d_diff[off], rtol=1e-4, atol=1e-5)


def test_gar_list_input_compat():
    """GARs also accept the reference-style list-of-flat-gradients input."""
    rows = [np.float32(r) for r in rand_grads(5, 3)]
    out = ops.gars["average"]([jnp.asarray(r) for r in rows])
    np.testing.assert_allclose(np.asarray(out), np.stack(rows).mean(axis=0), rtol=1e-6)


def test_brute_unranking_matches_itertools():
    """The in-graph combinatorial unranking enumerates subsets in exactly
    `itertools.combinations` (lexicographic) order — the order the
    reference's Python loop iterates in, which the first-minimum tie-break
    depends on."""
    import itertools
    import jax
    from byzantinemomentum_tpu.ops.brute import _binom_table, _unrank_masks
    n, k = 9, 5
    tbl = jnp.asarray(_binom_table(n, k).astype(np.int32))
    total = int(_binom_table(n, k)[n, k])
    ranks = jnp.arange(total, dtype=jnp.int32)
    masks = np.asarray(_unrank_masks(ranks, n, k, tbl))
    got = [tuple(np.nonzero(m)[0]) for m in masks]
    want = list(itertools.combinations(range(n), k))
    assert got == want


def test_brute_tie_break_first_minimum():
    """Duplicated rows create diameter ties; the selected subset must be the
    lexicographically first (= reference iteration order)."""
    base = rand_grads(3, 4)
    # 5 rows: rows 0,1,2 distinct, rows 3,4 copies of rows 0,1 — many
    # size-3 subsets share the minimal diameter
    g = np.concatenate([base, base[:2]], axis=0)
    from byzantinemomentum_tpu.ops.brute import selection
    sel = sorted(int(i) for i in np.asarray(selection(jnp.asarray(g), 1)))
    import itertools
    dist = np.full((5, 5), 0.0)
    for i in range(5):
        for j in range(5):
            dist[i, j] = np.linalg.norm(g[i] - g[j])
    best_set, best_diam = None, None
    for combo in itertools.combinations(range(5), 4):
        diam = max(dist[x][y] for x, y in itertools.combinations(combo, 2))
        if best_set is None or diam < best_diam - 1e-12:
            best_set, best_diam = combo, diam
    assert sel == sorted(best_set)


@pytest.mark.slow
def test_brute_paper_scale_streams():
    """n=25, f=11 — C(25,14) = 4,457,400 subsets, the config the reference
    grid actually runs brute-class diameters at. The streaming enumeration
    must complete in bounded memory and agree with a numpy oracle computed
    from the same distance matrix."""
    n, f, d = 25, 11, 64
    g = rand_grads(n, d)
    got = np.asarray(ops.gars["brute"](jnp.asarray(g), f=f))
    # Oracle: stream the same enumeration in numpy (vectorized per block)
    import itertools
    dist = np.linalg.norm(g[:, None, :] - g[None, :, :], axis=-1)
    best_diam, best_combo = np.inf, None
    block, cur = [], []
    for combo in itertools.combinations(range(n), n - f):
        cur.append(combo)
        if len(cur) == 65536:
            block = np.asarray(cur, np.int32)
            diams = dist[block[:, :, None], block[:, None, :]].max(axis=(1, 2))
            i = int(np.argmin(diams))
            if diams[i] < best_diam:
                best_diam, best_combo = float(diams[i]), tuple(block[i])
            cur = []
    if cur:
        block = np.asarray(cur, np.int32)
        diams = dist[block[:, :, None], block[:, None, :]].max(axis=(1, 2))
        i = int(np.argmin(diams))
        if diams[i] < best_diam:
            best_diam, best_combo = float(diams[i]), tuple(block[i])
    want = g[list(best_combo)].mean(axis=0)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
