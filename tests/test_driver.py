"""End-to-end driver tests: the seeded smoke run and the result-directory
contract (the reference's test strategy relies on exactly this smoke run,
reference `README.md:148-149`; the CSV schema is consumed by
`study.Session`, reference `study.py:216-229`)."""

import json
import os

import numpy as np
import pytest

from byzantinemomentum_tpu.cli.attack import main
from byzantinemomentum_tpu.engine import STUDY_COLUMNS


@pytest.fixture(autouse=True)
def small_synth(monkeypatch):
    monkeypatch.setenv("BMT_SYNTH_TRAIN", "512")
    monkeypatch.setenv("BMT_SYNTH_TEST", "128")


BASE = ["--nb-steps", "3", "--batch-size", "8", "--batch-size-test", "32",
        "--batch-size-test-reps", "2", "--evaluation-delta", "2",
        "--model", "simples-full", "--seed", "11"]


@pytest.mark.slow
def test_smoke_run_with_study(tmp_path):
    resdir = tmp_path / "run"
    rc = main(BASE + ["--gar", "median", "--attack", "empire",
                      "--attack-args", "factor:1.1", "--nb-real-byz", "4",
                      "--nb-for-study", "11", "--nb-for-study-past", "2",
                      "--result-directory", str(resdir)])
    assert rc == 0
    # Result-directory layout (reference `attack.py:549-591`)
    assert (resdir / "config").is_file()
    assert (resdir / "config.json").is_file()
    cfg = json.loads((resdir / "config.json").read_text())
    assert cfg["gar"] == "median" and cfg["nb_honests"] == 7
    # Study CSV: '# '-prefixed tab-separated header + 25 columns per row
    lines = (resdir / "study").read_text().split(os.linesep)
    header = lines[0]
    assert header == "# " + "\t".join(STUDY_COLUMNS)
    rows = [l for l in lines[1:] if l]
    assert len(rows) == 3
    for row in rows:
        fields = row.split("\t")
        assert len(fields) == len(STUDY_COLUMNS)
        # Attack columns must be populated (f_real > 0)
        assert not np.isnan(float(fields[6]))
    # Eval CSV
    lines = (resdir / "eval").read_text().split(os.linesep)
    assert lines[0] == "# Step number\tCross-accuracy"
    assert len([l for l in lines[1:] if l]) == 2  # steps 0 and 2


@pytest.mark.slow
def test_seeded_runs_are_reproducible(tmp_path):
    out = []
    for sub in ("a", "b"):
        resdir = tmp_path / sub
        main(BASE + ["--gar", "trmean", "--nb-real-byz", "0",
                     "--nb-for-study", "11",
                     "--result-directory", str(resdir)])
        out.append((resdir / "study").read_text())
    assert out[0] == out[1]


@pytest.mark.slow
def test_resume_continues_exactly(tmp_path):
    """A 2-step run checkpointed at step 2 resumes at exactly step 2 and
    reproduces the uninterrupted run's remaining study rows AND evaluations
    byte-for-byte: the checkpoint carries device PRNG state plus the host
    sampler snapshots (the dataloader-state gap the reference documents as
    unfixed, reference `README.md:105`)."""
    full = tmp_path / "full"
    main(BASE + ["--nb-steps", "4", "--gar", "average",
                 "--nb-for-study", "11",
                 "--result-directory", str(full),
                 "--evaluation-delta", "2"])
    part = tmp_path / "part"
    main(BASE + ["--nb-steps", "2", "--gar", "average",
                 "--nb-for-study", "11",
                 "--result-directory", str(part),
                 "--evaluation-delta", "2", "--checkpoint-delta", "2"])
    resumed = tmp_path / "resumed"
    main(["--nb-steps", "2", "--batch-size", "8", "--batch-size-test", "32",
          "--batch-size-test-reps", "2", "--model", "simples-full",
          "--gar", "average", "--nb-for-study", "11",
          "--result-directory", str(resumed), "--evaluation-delta", "2",
          "--load-checkpoint", str(part / "checkpoint-2")])
    full_rows = [l for l in (full / "study").read_text().split(os.linesep)[1:] if l]
    res_rows = [l for l in (resumed / "study").read_text().split(os.linesep)[1:] if l]
    # The resumed run's rows must continue at steps 2..3 with every metric
    # field identical to the uninterrupted run's
    assert [r.split("\t")[0] for r in res_rows] == ["2", "3"]
    assert res_rows == [r for r in full_rows if int(r.split("\t")[0]) >= 2]
    # The evaluations after the resume point must match exactly too (test
    # sampler position is restored from the checkpoint)
    full_eval = [l for l in (full / "eval").read_text().split(os.linesep)[1:] if l]
    res_eval = [l for l in (resumed / "eval").read_text().split(os.linesep)[1:] if l]
    assert res_eval == [r for r in full_eval if int(r.split("\t")[0]) >= 2]


def test_gars_mixture_flag(tmp_path):
    resdir = tmp_path / "mix"
    rc = main(BASE + ["--gars", "average,1;median,2",
                      "--result-directory", str(resdir),
                      "--nb-for-study", "11"])
    assert rc == 0
    assert (resdir / "study").is_file()


def test_local_steps_capability(tmp_path):
    """Multi-local-step SGD works here (the reference hard-fatals,
    `attack.py:796-798`)."""
    resdir = tmp_path / "local"
    rc = main(BASE + ["--nb-local-steps", "2", "--gar", "average",
                      "--result-directory", str(resdir),
                      "--nb-for-study", "11"])
    assert rc == 0
    rows = [l for l in (resdir / "study").read_text().split(os.linesep)[1:] if l]
    # datapoints advance by batch * honests * local steps per step
    assert int(rows[1].split("\t")[1]) == 8 * 11 * 2


@pytest.mark.slow
def test_steps_per_program_trajectory_identical(tmp_path):
    """Fusing M steps into one dispatch (lax.scan) must not change the
    trajectory: study/eval CSVs byte-identical to single-step dispatch."""
    outs = []
    for spp in ("1", "4"):
        resdir = tmp_path / f"spp{spp}"
        rc = main(BASE + ["--nb-steps", "7", "--gar", "krum",
                          "--attack", "empire", "--attack-args", "factor:1.1",
                          "--nb-real-byz", "3", "--evaluation-delta", "3",
                          "--nb-for-study", "11", "--nb-for-study-past", "2",
                          "--steps-per-program", spp,
                          "--result-directory", str(resdir)])
        assert rc == 0
        outs.append(((resdir / "study").read_text(),
                     (resdir / "eval").read_text()))
    assert outs[0][0] == outs[1][0]
    assert outs[0][1] == outs[1][1]


@pytest.mark.slow
def test_transformer_model_via_cli(tmp_path):
    """The sequence-model family trains through the standard driver: MNIST
    rows tokenize as a length-28 sequence (models/transformer.py)."""
    resdir = tmp_path / "tr"
    rc = main(BASE + ["--model", "transformer-classifier",
                      "--model-args", "depth:1", "dim:32", "heads:2",
                      "--gar", "median", "--nb-real-byz", "2",
                      "--attack", "little", "--attack-args", "factor:1.5",
                      "--nb-for-study", "11", "--nb-for-study-past", "2",
                      "--result-directory", str(resdir)])
    assert rc == 0
    rows = [l for l in (resdir / "study").read_text().split(os.linesep)[1:] if l]
    assert len(rows) == 3
    assert all(np.isfinite(float(r.split("\t")[2])) for r in rows)


def test_phishing_logit_sigmoid_via_cli(tmp_path):
    """The LIBSVM binary-classification path: phishing dataset, logit model,
    bce loss, sigmoid criterion (reference `reproduce.py` uses top-k/nll;
    the binary path mirrors reference `loss.py:236-252`)."""
    resdir = tmp_path / "ph"
    rc = main(["--nb-steps", "3", "--batch-size", "16",
               "--batch-size-test", "50", "--batch-size-test-reps", "2",
               "--evaluation-delta", "3", "--seed", "2",
               "--dataset", "phishing", "--model", "simples-logit",
               "--model-args", "din:68", "--loss", "bce",
               "--criterion", "sigmoid", "--gar", "trmean",
               "--nb-workers", "9", "--nb-decl-byz", "2", "--nb-real-byz", "2",
               "--attack", "empire-strict", "--attack-args", "factor:1.1",
               "--result-directory", str(resdir)])
    assert rc == 0
    lines = [l for l in (resdir / "eval").read_text().split(os.linesep)[1:] if l]
    accs = [float(l.split("\t")[1]) for l in lines]
    assert all(0.0 <= a <= 1.0 for a in accs)


def test_nan_attack_resilient_gar_via_cli(tmp_path):
    """The numerical-fault injection path: f_real NaN gradients against the
    NaN-resilient median — training must stay finite (reference
    `attacks/nan.py`, `aggregators/median.py:13`)."""
    resdir = tmp_path / "nan"
    rc = main(BASE + ["--gar", "median", "--attack", "nan",
                      "--nb-real-byz", "4", "--nb-for-study", "11",
                      "--nb-for-study-past", "2",
                      "--result-directory", str(resdir)])
    assert rc == 0
    rows = [l for l in (resdir / "study").read_text().split(os.linesep)[1:] if l]
    defense_idx = STUDY_COLUMNS.index("Defense gradient norm")
    for row in rows:
        fields = row.split("\t")
        assert np.isfinite(float(fields[2]))            # Average loss
        assert np.isfinite(float(fields[defense_idx]))  # Defense output


def test_trace_dir_writes_profile(tmp_path):
    """--trace-dir captures a jax.profiler trace of the run (the opt-in
    tracing subsystem, SURVEY §5.1)."""
    trace = tmp_path / "trace"
    rc = main(BASE + ["--gar", "average", "--trace-dir", str(trace)])
    assert rc == 0
    assert any(trace.rglob("*.xplane.pb")) or any(trace.rglob("*.json.gz"))


def test_anticge_vs_cge_via_cli(tmp_path):
    """The CGE-specific adaptive attack through the driver (reference
    `attacks/anticge.py`): runs and reports a finite influence."""
    resdir = tmp_path / "acge"
    rc = main(BASE + ["--gar", "cge", "--attack", "anticge",
                      "--nb-real-byz", "4", "--nb-for-study", "11",
                      "--nb-for-study-past", "2",
                      "--result-directory", str(resdir)])
    assert rc == 0
    rows = [l for l in (resdir / "study").read_text().split(os.linesep)[1:] if l]
    ratios = [float(r.split("\t")[-1]) for r in rows]
    assert all(np.isfinite(v) and 0.0 <= v <= 1.0 for v in ratios)


@pytest.mark.slow
def test_bulyan_attack_adaptive_via_cli(tmp_path):
    """The 'Hidden Vulnerability' attack with an adaptive (negative) factor
    against the Bulyan defense: the in-graph line search evaluates the live
    GAR inside the step (reference `attacks/identical.py:66-77, 114-127`)."""
    resdir = tmp_path / "bul"
    rc = main(BASE + ["--gar", "bulyan", "--attack", "bulyan",
                      "--attack-args", "factor:-8", "negative:True",
                      "--nb-workers", "11", "--nb-decl-byz", "2",
                      "--nb-real-byz", "2", "--nb-for-study", "11",
                      "--nb-for-study-past", "2",
                      "--result-directory", str(resdir)])
    assert rc == 0
    rows = [l for l in (resdir / "study").read_text().split(os.linesep)[1:] if l]
    defense_idx = STUDY_COLUMNS.index("Defense gradient norm")
    assert all(np.isfinite(float(r.split("\t")[defense_idx])) for r in rows)


@pytest.mark.slow
def test_device_gar_cpu_matches_fused(tmp_path):
    """`--device-gar cpu` (reference heterogeneous placement,
    `attack.py:811-827`): the defense phase runs as a separate program on
    the GAR device with per-step gradient hops — and the trajectory matches
    the fused path through an adaptive line search, up to the last-ulp
    rounding that moving the XLA fusion boundaries allows."""
    out = {}
    for name, extra in (("fused", []), ("hop", ["--device-gar", "cpu"])):
        resdir = tmp_path / name
        rc = main(BASE + extra
                  + ["--gar", "median", "--attack", "empire",
                     "--attack-args", "factor:-8",
                     "--nb-real-byz", "4", "--nb-for-study", "11",
                     "--nb-for-study-past", "2",
                     "--result-directory", str(resdir)])
        assert rc == 0
        out[name] = ((resdir / "study").read_text(),
                     (resdir / "eval").read_text())
    srows = {k: [l.split("\t") for l in v[0].split(os.linesep)[1:] if l]
             for k, v in out.items()}
    assert len(srows["hop"]) == len(srows["fused"]) == 3
    for rf, rh in zip(srows["fused"], srows["hop"]):
        assert rf[:2] == rh[:2]  # step + datapoint counters exact
        a = np.array([float(x) for x in rf[2:]])
        b = np.array([float(x) for x in rh[2:]])
        np.testing.assert_allclose(b, a, rtol=1e-4, atol=1e-6)
    erows = {k: [l.split("\t") for l in v[1].split(os.linesep)[1:] if l]
             for k, v in out.items()}
    assert len(erows["hop"]) == len(erows["fused"]) > 0
    for rf, rh in zip(erows["fused"], erows["hop"]):
        assert rf[0] == rh[0]
        # 64 evaluation samples; tolerate a single borderline flip
        assert abs(float(rf[1]) - float(rh[1])) <= 1.0 / 64 + 1e-9
