"""Test configuration: force JAX onto a virtual 8-device CPU platform so
multi-chip sharding tests run without TPU hardware (the driver validates the
real-TPU path separately via `__graft_entry__.dryrun_multichip`).

Note: this environment's sitecustomize force-registers the `axon` TPU
platform and overrides JAX_PLATFORMS, so the env var alone is not enough —
`jax.config.update('jax_platforms', 'cpu')` after import is what actually
keeps backend init off the (possibly absent) TPU tunnel.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Download-retry backoff (`faults/retry.py` via `data/sources.py:_fetch`)
# must not sleep between mocked-failure attempts in tests
os.environ.setdefault("BMT_FETCH_BACKOFF", "0")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="also run tests marked slow (the full suite; the default run "
             "skips them to stay under ~5 minutes — see README)")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running E2E/mesh/oracle test, skipped unless "
                   "--runslow is given")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow test: pass --runslow to include")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
