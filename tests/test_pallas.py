"""Pallas TPU kernel tests (`ops/pallas_sort.py`): the sorting-network
kernels behind median/trmean/phocas/meamed/Bulyan-stage-2 must reproduce the
jnp oracles EXACTLY — NaN placement (NaN-last, the median GAR's resilience
contract) and index-order tie selection included. Off-TPU the kernels run in
interpret mode; on TPU the dispatch in `ops/_common.py` / `ops/trmean.py`
routes through them automatically (kill-switch: BMT_NO_PALLAS=1)."""

import jax.numpy as jnp
import numpy as np
import pytest

from byzantinemomentum_tpu.ops import pallas_sort


def _mat(n, d, seed=0, nan_frac=0.0, dup_frac=0.0):
    rng = np.random.default_rng(seed)
    g = rng.standard_normal((n, d)).astype(np.float32)
    if dup_frac:
        # Duplicate values across rows to exercise tie-breaking
        mask = rng.random((n, d)) < dup_frac
        g = np.where(mask, np.round(g), g).astype(np.float32)
    if nan_frac:
        g[rng.random((n, d)) < nan_frac] = np.nan
    return g


NS = (1, 2, 3, 13, 25, 51)


@pytest.mark.parametrize("n", NS[:4] + tuple(
    pytest.param(n, marks=pytest.mark.slow) for n in NS[4:]))
@pytest.mark.parametrize("nan_frac", (0.0, 0.05, 0.6))
def test_colsort_matches_jnp_sort(n, nan_frac):
    g = jnp.asarray(_mat(n, 1000, seed=n, nan_frac=nan_frac))
    want = np.asarray(jnp.sort(g, axis=0))
    got = np.asarray(pallas_sort.colsort(g, interpret=True))
    np.testing.assert_array_equal(np.nan_to_num(got, nan=7e9),
                                  np.nan_to_num(want, nan=7e9))


@pytest.mark.parametrize("n", NS)
def test_lower_median_matches(n):
    g = jnp.asarray(_mat(n, 1000, seed=n + 10, nan_frac=0.1))
    want = np.asarray(jnp.sort(g, axis=0)[(n - 1) // 2])
    got = np.asarray(pallas_sort.lower_median(g, interpret=True))
    np.testing.assert_array_equal(np.nan_to_num(got, nan=7e9),
                                  np.nan_to_num(want, nan=7e9))


@pytest.mark.parametrize("n,f", ((5, 1), (13, 4), (25, 5), (51, 12)))
def test_trimmed_mean_matches(n, f):
    g = jnp.asarray(_mat(n, 1000, seed=n, nan_frac=0.02))
    want = np.asarray(jnp.mean(jnp.sort(g, axis=0)[f:n - f], axis=0))
    got = np.asarray(pallas_sort.trimmed_mean(g, f, interpret=True))
    np.testing.assert_allclose(np.nan_to_num(got, nan=7e9),
                               np.nan_to_num(want, nan=7e9),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("n,m", ((5, 3), (13, 9), (25, 20)))
@pytest.mark.parametrize("dup", (0.0, 0.5))
def test_closest_mean_matches_oracle(n, m, dup):
    """Against the stable-argsort oracle (the reference's selection,
    `aggregators/trmean.py:35-50`), with heavy ties."""
    g = jnp.asarray(_mat(n, 500, seed=n + m, dup_frac=dup))
    c = jnp.asarray(_mat(1, 500, seed=99)[0])
    dev = jnp.abs(g - c[None, :])
    order = jnp.argsort(dev, axis=0, stable=True)[:m]
    want = np.asarray(jnp.mean(jnp.take_along_axis(g, order, axis=0), axis=0))
    got = np.asarray(pallas_sort.closest_mean(g, c, m, interpret=True))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_closest_mean_nan_overflow():
    """More NaN rows than n - m: the stable argsort would select a NaN, so
    the kernel must yield NaN for those coordinates."""
    g = _mat(7, 100, seed=3)
    g[3:, :50] = np.nan  # 4 NaN rows in the first 50 coords; m=5 > 3 finite
    g = jnp.asarray(g)
    c = jnp.zeros((100,), jnp.float32)
    got = np.asarray(pallas_sort.closest_mean(g, c, 5, interpret=True))
    assert np.isnan(got[:50]).all()
    assert np.isfinite(got[50:]).all()


def test_supported_gate(monkeypatch):
    g32 = jnp.zeros((8, 64), jnp.float32)
    assert pallas_sort.supported(g32, interpret=True)
    assert not pallas_sort.supported(jnp.zeros((80, 64)), interpret=True)
    assert not pallas_sort.supported(jnp.zeros((8, 64), jnp.int32),
                                     interpret=True)
    monkeypatch.setenv("BMT_NO_PALLAS", "1")
    assert not pallas_sort.supported(g32, interpret=True)


def test_bf16_kernels():
    g = jnp.asarray(_mat(9, 400, seed=5)).astype(jnp.bfloat16)
    want = np.asarray(jnp.sort(g, axis=0)[(9 - 1) // 2].astype(jnp.float32))
    got = np.asarray(pallas_sort.lower_median(g, interpret=True)
                     .astype(jnp.float32))
    np.testing.assert_array_equal(got, want)
