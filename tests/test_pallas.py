"""Pallas TPU kernel tests (`ops/pallas_sort.py`): the sorting-network
kernels behind median/trmean/phocas/meamed/Bulyan-stage-2 must reproduce the
jnp oracles EXACTLY — NaN placement (NaN-last, the median GAR's resilience
contract) and index-order tie selection included. Off-TPU the kernels run in
interpret mode; on TPU the dispatch in `ops/_common.py` / `ops/trmean.py`
routes through them automatically (kill-switch: BMT_NO_PALLAS=1)."""

import jax.numpy as jnp
import numpy as np
import pytest

from byzantinemomentum_tpu.ops import pallas_sort


def _mat(n, d, seed=0, nan_frac=0.0, dup_frac=0.0):
    rng = np.random.default_rng(seed)
    g = rng.standard_normal((n, d)).astype(np.float32)
    if dup_frac:
        # Duplicate values across rows to exercise tie-breaking
        mask = rng.random((n, d)) < dup_frac
        g = np.where(mask, np.round(g), g).astype(np.float32)
    if nan_frac:
        g[rng.random((n, d)) < nan_frac] = np.nan
    return g


NS = (1, 2, 3, 13, 25, 51)


@pytest.mark.parametrize("n", NS[:4] + tuple(
    pytest.param(n, marks=pytest.mark.slow) for n in NS[4:]))
@pytest.mark.parametrize("nan_frac", (0.0, 0.05, 0.6))
def test_colsort_matches_jnp_sort(n, nan_frac):
    g = jnp.asarray(_mat(n, 1000, seed=n, nan_frac=nan_frac))
    want = np.asarray(jnp.sort(g, axis=0))
    got = np.asarray(pallas_sort.colsort(g, interpret=True))
    np.testing.assert_array_equal(np.nan_to_num(got, nan=7e9),
                                  np.nan_to_num(want, nan=7e9))


@pytest.mark.parametrize("n", NS)
def test_lower_median_matches(n):
    g = jnp.asarray(_mat(n, 1000, seed=n + 10, nan_frac=0.1))
    want = np.asarray(jnp.sort(g, axis=0)[(n - 1) // 2])
    got = np.asarray(pallas_sort.lower_median(g, interpret=True))
    np.testing.assert_array_equal(np.nan_to_num(got, nan=7e9),
                                  np.nan_to_num(want, nan=7e9))


@pytest.mark.parametrize("n,f", ((5, 1), (13, 4), (25, 5), (51, 12)))
def test_trimmed_mean_matches(n, f):
    g = jnp.asarray(_mat(n, 1000, seed=n, nan_frac=0.02))
    want = np.asarray(jnp.mean(jnp.sort(g, axis=0)[f:n - f], axis=0))
    got = np.asarray(pallas_sort.trimmed_mean(g, f, interpret=True))
    np.testing.assert_allclose(np.nan_to_num(got, nan=7e9),
                               np.nan_to_num(want, nan=7e9),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("n,m", ((5, 3), (13, 9), (25, 20)))
@pytest.mark.parametrize("dup", (0.0, 0.5))
def test_closest_mean_matches_oracle(n, m, dup):
    """Against the stable-argsort oracle (the reference's selection,
    `aggregators/trmean.py:35-50`), with heavy ties."""
    g = jnp.asarray(_mat(n, 500, seed=n + m, dup_frac=dup))
    c = jnp.asarray(_mat(1, 500, seed=99)[0])
    dev = jnp.abs(g - c[None, :])
    order = jnp.argsort(dev, axis=0, stable=True)[:m]
    want = np.asarray(jnp.mean(jnp.take_along_axis(g, order, axis=0), axis=0))
    got = np.asarray(pallas_sort.closest_mean(g, c, m, interpret=True))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_closest_mean_nan_overflow():
    """More NaN rows than n - m: the stable argsort would select a NaN, so
    the kernel must yield NaN for those coordinates."""
    g = _mat(7, 100, seed=3)
    g[3:, :50] = np.nan  # 4 NaN rows in the first 50 coords; m=5 > 3 finite
    g = jnp.asarray(g)
    c = jnp.zeros((100,), jnp.float32)
    got = np.asarray(pallas_sort.closest_mean(g, c, 5, interpret=True))
    assert np.isnan(got[:50]).all()
    assert np.isfinite(got[50:]).all()


def test_supported_gate(monkeypatch):
    # tier-agnostic: this test asserts both sides of the gate itself, so
    # an outer BMT_NO_PALLAS tier must not pre-disable it
    monkeypatch.delenv("BMT_NO_PALLAS", raising=False)
    g32 = jnp.zeros((8, 64), jnp.float32)
    assert pallas_sort.supported(g32, interpret=True)
    assert not pallas_sort.supported(jnp.zeros((80, 64)), interpret=True)
    assert not pallas_sort.supported(jnp.zeros((8, 64), jnp.int32),
                                     interpret=True)
    monkeypatch.setenv("BMT_NO_PALLAS", "1")
    assert not pallas_sort.supported(g32, interpret=True)


def test_bf16_kernels():
    g = jnp.asarray(_mat(9, 400, seed=5)).astype(jnp.bfloat16)
    want = np.asarray(jnp.sort(g, axis=0)[(9 - 1) // 2].astype(jnp.float32))
    got = np.asarray(pallas_sort.lower_median(g, interpret=True)
                     .astype(jnp.float32))
    np.testing.assert_array_equal(got, want)


# --------------------------------------------------------------------------- #
# Fused GAR pipeline (`ops/pallas_gar.py`): one-pass Gram + selection +
# streamed selected-row averages for krum / bulyan / brute. The (n, n)
# geometry — and therefore every diagnostics aux — must match the jnp
# reference BIT FOR BIT on the oracle grid; the averaged outputs match to
# reduce-fusion rounding with identical NaN/inf placement.

import jax

from byzantinemomentum_tpu import ops
from byzantinemomentum_tpu.ops import _common, pallas_gar

from . import reference_oracles as oracle


def _norm(x):
    """NaN/inf-comparable view (distinct sentinels so placement is part of
    the equality)."""
    return np.nan_to_num(np.asarray(x), nan=7e9, posinf=8e9, neginf=-8e9)


@pytest.fixture
def fused_routing(monkeypatch):
    """Route the GAR kernels through the fused pipeline in interpret mode
    (and make sure an outer BMT_NO_PALLAS tier cannot turn it off — the
    point of these tests is the kernel path itself)."""
    monkeypatch.delenv("BMT_NO_PALLAS", raising=False)
    monkeypatch.setenv("BMT_PALLAS_INTERPRET", "1")


def _jnp_reference(fn, monkeypatch_env=None):
    """Run `fn` with the fused tier killed (the jnp fallback paths)."""
    import os
    prior = os.environ.get("BMT_NO_PALLAS")
    os.environ["BMT_NO_PALLAS"] = "1"
    try:
        return fn()
    finally:
        if prior is None:
            os.environ.pop("BMT_NO_PALLAS", None)
        else:
            os.environ["BMT_NO_PALLAS"] = prior


@pytest.mark.parametrize("n", (1, 2, 5, 11, 25))
@pytest.mark.parametrize("nan_frac", (0.0, 0.1))
def test_sq_gram_matches_matmul_bitwise(n, nan_frac):
    """Single-tile streamed Gram == `jnp.matmul(g, g.T, HIGHEST)` bit for
    bit (the pinned `pairwise_distances` semantics), NaN/inf poisoning
    included."""
    g = _mat(n, 1000, seed=n, nan_frac=nan_frac)
    if n > 7:
        g[7, 5] = np.inf
    g = jnp.asarray(g)
    want = jnp.matmul(g, g.T, precision=jax.lax.Precision.HIGHEST)
    got = pallas_gar.sq_gram(g, interpret=True)
    np.testing.assert_array_equal(_norm(got), _norm(want))


def test_sq_gram_multi_tile_accumulation(monkeypatch):
    """Forcing a small tile exercises the grid accumulation and the
    final-partial-block zero masking (d deliberately not a tile
    multiple)."""
    monkeypatch.setattr(pallas_sort, "_tile_for", lambda n, b, i: 192)
    g = jnp.asarray(_mat(9, 1000, seed=3, nan_frac=0.05))
    want = np.asarray(jnp.matmul(g, g.T, precision=jax.lax.Precision.HIGHEST))
    got = np.asarray(pallas_gar.sq_gram(g, interpret=True))
    assert np.array_equal(np.isnan(got), np.isnan(want))
    mask = np.isfinite(want)
    np.testing.assert_allclose(got[mask], want[mask], rtol=1e-5, atol=1e-4)


def test_routed_pairwise_distances_bitwise(fused_routing):
    """`ops._common.pairwise_distances` routed through the streamed Gram
    equals the jnp path bit for bit (shared (n, n) post-processing)."""
    g = jnp.asarray(_mat(11, 800, seed=4, nan_frac=0.08))
    got = _common.pairwise_distances(g)
    want = _jnp_reference(lambda: _common.pairwise_distances(g))
    np.testing.assert_array_equal(_norm(got), _norm(want))


def test_weighted_rows_mean_kernel_semantics():
    """The streamed average reproduces `_common.weighted_rows_mean`'s
    non-finite contract exactly: unselected non-finite rows excluded,
    selected non-finite entries -> NaN at their coordinates."""
    g = _mat(7, 500, seed=9)
    g[6, :] = np.nan           # unselected NaN row: must not poison
    g[2, 17] = np.inf          # selected inf entry: NaN at column 17
    g = jnp.asarray(g)
    w = np.zeros((7,), np.float32)
    w[[0, 2, 4]] = 1.0 / 3.0
    w = jnp.asarray(w)
    want = np.asarray(_common.weighted_rows_mean(w, g))
    got = np.asarray(pallas_gar.weighted_rows_mean(w, g, interpret=True))
    np.testing.assert_array_equal(_norm(got), _norm(want))
    assert np.isnan(got[17]) and np.isfinite(got[:17]).all()
    # 2-D weight stacks (bulyan stage 1 / masked-quorum rounds)
    W = jnp.asarray(np.stack([np.asarray(w)] * 3))
    wantW = np.asarray(_common.weighted_rows_mean(W, g))
    gotW = np.asarray(pallas_gar.weighted_rows_mean(W, g, interpret=True))
    np.testing.assert_array_equal(_norm(gotW), _norm(wantW))


def test_masked_rows_mean_keeps_brute_inf_contract():
    """Brute's subset mean is where+sum, NOT the normalized
    weighted-mean: a selected +inf coordinate stays +inf (only NaN rows
    among the excluded are zeroed)."""
    g = _mat(6, 64, seed=2)
    g[5, :] = np.nan        # excluded row
    g[1, 3] = np.inf        # selected entry
    g = jnp.asarray(g)
    mask = jnp.asarray(np.array([True, True, True, True, False, False]))
    kept = jnp.where(mask[:, None], g, 0)
    want = np.asarray(jnp.sum(kept, axis=0) / 4)
    got = np.asarray(pallas_gar.masked_rows_mean(mask, g, 4, interpret=True))
    assert np.isposinf(got[3])
    np.testing.assert_allclose(_norm(got), _norm(want), rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("f", (1, 2, 3))
@pytest.mark.parametrize("gar_name", ("krum", "bulyan", "brute"))
def test_fused_gar_aux_bit_exact(fused_routing, gar_name, f):
    """Acceptance: `diagnostics=True` aux from the fused path matches the
    jnp reference BIT FOR BIT across the oracle grid (f in {1,2,3}),
    planted-NaN rows and duplicate-row distance ties included; the
    aggregate matches to reduce-fusion rounding with identical NaN
    placement."""
    n = 4 * f + 3  # bulyan's tightest contract; valid for all three
    g = _mat(n, 700, seed=10 * f, dup_frac=0.3)
    g[0] = g[1]              # exact duplicate rows: distance ties at 0
    if n > 4:
        g[4, :5] = np.nan    # planted NaN row
    g = jnp.asarray(g)
    gar = ops.gars[gar_name]
    agg, aux = gar.diagnosed(g, f=f)
    agg_ref, aux_ref = _jnp_reference(lambda: gar.diagnosed(g, f=f))
    for key in aux:
        np.testing.assert_array_equal(
            _norm(aux[key]), _norm(aux_ref[key]),
            err_msg=f"{gar_name} aux[{key!r}] diverged from jnp reference")
    assert np.array_equal(np.isnan(np.asarray(agg)),
                          np.isnan(np.asarray(agg_ref)))
    np.testing.assert_allclose(_norm(agg), _norm(agg_ref),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("f", (1, 2, 3))
def test_fused_krum_selection_matches_torch_oracle(fused_routing, f):
    """Fused krum diag vs `tests/reference_oracles.py`: the m = n-f-2
    lowest-score workers under stable tie order (the reference's Python
    stable sort)."""
    torch = pytest.importorskip("torch")
    n = 11
    g = _mat(n, 12, seed=f)
    scores = oracle.krum_scores(torch.tensor(g), f)
    order = sorted(range(n), key=lambda i: scores[i])  # stable
    expected = set(order[: n - f - 2])
    _, aux = ops.gars["krum"](jnp.asarray(g), f=f, diagnostics=True)
    selected = set(np.nonzero(np.asarray(aux["selection"]) > 0)[0].tolist())
    assert selected == expected
    np.testing.assert_allclose(np.asarray(aux["scores"]),
                               np.asarray(scores, dtype=np.float32),
                               rtol=1e-4)


def test_fused_bulyan_matches_torch_oracle(fused_routing):
    """Fused bulyan aggregate vs the PyTorch reference oracle (full
    two-stage rule, f32 tolerance)."""
    torch = pytest.importorskip("torch")
    n, f = 11, 2
    g = _mat(n, 40, seed=21)
    want = np.asarray(oracle.gar_bulyan(torch.tensor(g), f))
    got = np.asarray(ops.gars["bulyan"](jnp.asarray(g), f=f))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_fused_supported_gate(monkeypatch):
    monkeypatch.delenv("BMT_NO_PALLAS", raising=False)
    g32 = jnp.zeros((8, 64), jnp.float32)
    assert pallas_gar.supported(g32, interpret=True)
    # bf16 stacks keep the jnp path (f32 distance-ordering contract)
    assert not pallas_gar.supported(g32.astype(jnp.bfloat16), interpret=True)
    assert not pallas_gar.supported(jnp.zeros((80, 64), jnp.float32),
                                    interpret=True)
    # shares pallas_sort's kill switches: env var AND the disabled() trace
    # context (auto-partitioned meshes, non-TPU --device-gar hops)
    with pallas_sort.disabled():
        assert not pallas_gar.supported(g32, interpret=True)
        with pallas_sort.allowed():
            assert pallas_gar.supported(g32, interpret=True)
    monkeypatch.setenv("BMT_NO_PALLAS", "1")
    assert not pallas_gar.supported(g32, interpret=True)


def test_masked_quorum_composes_with_fused_kernels(fused_routing):
    """PR 1 masked-quorum variants ride the fused tier: the streamed Gram
    feeds `selection_weights_masked` and the streamed average consumes the
    pre-zeroed rows — results match the jnp path."""
    from byzantinemomentum_tpu.faults import quorum

    g = jnp.asarray(_mat(13, 900, seed=6))
    active = jnp.asarray(np.array([True] * 10 + [False] * 3))
    for name in ("krum", "bulyan", "brute"):
        gar = ops.gars[name]
        agg, f_eff = quorum.masked_aggregate(gar, g, active, f_decl=2,
                                             dynamic=True)
        agg_ref, f_ref = _jnp_reference(lambda: quorum.masked_aggregate(
            gar, g, active, f_decl=2, dynamic=True))
        assert int(f_eff) == int(f_ref)
        np.testing.assert_allclose(_norm(agg), _norm(agg_ref),
                                   rtol=1e-6, atol=1e-6,
                                   err_msg=f"{name} masked aggregate")


def test_max_rows_boundary_routes_to_fused_kernel(fused_routing):
    """`n == MAX_ROWS` is the LAST shape the fused pipeline accepts: the
    routed `pairwise_distances` takes the kernel and its result is
    bit-identical to the jnp Gram reference (tile clamp included)."""
    n, d = pallas_gar.MAX_ROWS, 300
    g = jnp.asarray(_mat(n, d, seed=64, nan_frac=0.02))
    assert pallas_gar.supported(g)  # env interpret-mode engages routing
    got = _common.pairwise_distances(g)
    ref = _jnp_reference(lambda: _common.pairwise_distances(g))
    np.testing.assert_array_equal(_norm(got), _norm(ref))
    # the averaging kernel takes the boundary shape too
    w = jnp.zeros((n,), jnp.float32).at[:5].set(0.2)
    got_avg = _common.weighted_rows_mean(w, g)
    ref_avg = _jnp_reference(lambda: _common.weighted_rows_mean(w, g))
    np.testing.assert_allclose(_norm(got_avg), _norm(ref_avg),
                               rtol=1e-6, atol=1e-6)


def test_max_rows_plus_one_falls_back_bit_identically(fused_routing):
    """`n == MAX_ROWS + 1` must NOT route to the kernel (the resident
    (n, n) block budget is the cap) and the jnp fallback it lands on is
    bit-identical to the `BMT_NO_PALLAS=1` reference path."""
    n, d = pallas_gar.MAX_ROWS + 1, 300
    g = jnp.asarray(_mat(n, d, seed=65, nan_frac=0.02))
    assert not pallas_gar.supported(g)
    assert not pallas_gar.supported(g, interpret=True)
    got = _common.pairwise_distances(g)
    ref = _jnp_reference(lambda: _common.pairwise_distances(g))
    np.testing.assert_array_equal(_norm(got), _norm(ref))
    w = jnp.zeros((n,), jnp.float32).at[:5].set(0.2)
    np.testing.assert_array_equal(
        _norm(_common.weighted_rows_mean(w, g)),
        _norm(_jnp_reference(lambda: _common.weighted_rows_mean(w, g))))
    # the full GAR kernels agree across the boundary pair: one row above
    # the cap aggregates identically to the fallback tier
    for name in ("krum", "median"):
        agg = ops.gars[name].unchecked(g, f=2)
        agg_ref = _jnp_reference(lambda: ops.gars[name].unchecked(g, f=2))
        np.testing.assert_array_equal(_norm(agg), _norm(agg_ref),
                                      err_msg=f"{name} at MAX_ROWS + 1")
