"""Raw-archive parser tests (`data/sources.py`): this environment only ever
exercises the synthetic fallback, so the real-data paths (idx ubyte files,
CIFAR pickle batches, the .tar.gz route) are pinned here against files
synthesized in the published formats."""

import gzip
import io
import pickle
import struct
import tarfile

import numpy as np
import pytest

from byzantinemomentum_tpu.data import sources


def _write_idx_images(path, arr):
    with open(path, "wb") as fd:
        fd.write(struct.pack(">I", 0x00000803))  # ubyte, 3 dims
        fd.write(struct.pack(">3I", *arr.shape))
        fd.write(arr.tobytes())


def _write_idx_labels(path, arr):
    with open(path, "wb") as fd:
        fd.write(struct.pack(">I", 0x00000801))  # ubyte, 1 dim
        fd.write(struct.pack(">I", arr.shape[0]))
        fd.write(arr.tobytes())


@pytest.fixture
def data_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("BMT_DATA_DIR", str(tmp_path))
    return tmp_path


def test_mnist_idx_files(data_dir):
    rng = np.random.default_rng(0)
    tr_x = rng.integers(0, 256, (20, 28, 28)).astype(np.uint8)
    tr_y = rng.integers(0, 10, 20).astype(np.uint8)
    te_x = rng.integers(0, 256, (8, 28, 28)).astype(np.uint8)
    te_y = rng.integers(0, 10, 8).astype(np.uint8)
    raw = data_dir / "MNIST" / "raw"
    raw.mkdir(parents=True)
    _write_idx_images(raw / "train-images-idx3-ubyte", tr_x)
    _write_idx_labels(raw / "train-labels-idx1-ubyte", tr_y)
    _write_idx_images(raw / "t10k-images-idx3-ubyte", te_x)
    _write_idx_labels(raw / "t10k-labels-idx1-ubyte", te_y)
    out = sources.load_mnist("mnist")
    assert "synthetic" not in out
    np.testing.assert_array_equal(out["train_x"][..., 0], tr_x)
    np.testing.assert_array_equal(out["train_y"], tr_y.astype(np.int32))
    np.testing.assert_array_equal(out["test_x"][..., 0], te_x)
    assert out["train_x"].shape == (20, 28, 28, 1)
    assert out["train_y"].dtype == np.int32


def test_mnist_gzipped_idx(data_dir):
    rng = np.random.default_rng(1)
    arrs = {
        "train-images-idx3-ubyte": rng.integers(0, 256, (6, 28, 28)).astype(np.uint8),
        "t10k-images-idx3-ubyte": rng.integers(0, 256, (4, 28, 28)).astype(np.uint8),
    }
    labels = {
        "train-labels-idx1-ubyte": rng.integers(0, 10, 6).astype(np.uint8),
        "t10k-labels-idx1-ubyte": rng.integers(0, 10, 4).astype(np.uint8),
    }
    for name, arr in arrs.items():
        buf = io.BytesIO()
        buf.write(struct.pack(">I", 0x00000803))
        buf.write(struct.pack(">3I", *arr.shape))
        buf.write(arr.tobytes())
        (data_dir / (name + ".gz")).write_bytes(gzip.compress(buf.getvalue()))
    for name, arr in labels.items():
        buf = io.BytesIO()
        buf.write(struct.pack(">I", 0x00000801))
        buf.write(struct.pack(">I", arr.shape[0]))
        buf.write(arr.tobytes())
        (data_dir / (name + ".gz")).write_bytes(gzip.compress(buf.getvalue()))
    out = sources.load_mnist("mnist")
    assert "synthetic" not in out
    np.testing.assert_array_equal(out["train_x"][..., 0],
                                  arrs["train-images-idx3-ubyte"])


def _cifar10_batch(rng, count):
    # Published layout: rows of 3072 uint8, channel-major (RRR..GGG..BBB)
    data = rng.integers(0, 256, (count, 3072)).astype(np.uint8)
    labels = [int(v) for v in rng.integers(0, 10, count)]
    return {b"data": data, b"labels": labels}


def test_cifar10_extracted_batches(data_dir):
    rng = np.random.default_rng(2)
    d = data_dir / "cifar-10-batches-py"
    d.mkdir()
    batches = []
    for i in range(1, 6):
        b = _cifar10_batch(rng, 4)
        batches.append(b)
        (d / f"data_batch_{i}").write_bytes(pickle.dumps(b))
    test_b = _cifar10_batch(rng, 4)
    (d / "test_batch").write_bytes(pickle.dumps(test_b))
    out = sources.load_cifar(10)
    assert "synthetic" not in out
    assert out["train_x"].shape == (20, 32, 32, 3)
    assert out["test_x"].shape == (4, 32, 32, 3)
    # Channel-major rows -> HWC: pixel (0,0) red channel = row byte 0
    np.testing.assert_array_equal(
        out["train_x"][0, 0, 0, 0], batches[0][b"data"][0, 0])
    np.testing.assert_array_equal(
        out["train_x"][0, 0, 0, 1], batches[0][b"data"][0, 1024])
    np.testing.assert_array_equal(out["test_y"],
                                  np.asarray(test_b[b"labels"], np.int32))


def test_cifar100_targz(data_dir):
    rng = np.random.default_rng(3)

    def entry(count):
        return {b"data": rng.integers(0, 256, (count, 3072)).astype(np.uint8),
                b"fine_labels": [int(v) for v in rng.integers(0, 100, count)]}

    train, test = entry(6), entry(3)
    tar_path = data_dir / "cifar-100-python.tar.gz"
    with tarfile.open(tar_path, "w:gz") as tar:
        for name, obj in (("cifar-100-python/train", train),
                          ("cifar-100-python/test", test)):
            blob = pickle.dumps(obj)
            info = tarfile.TarInfo(name)
            info.size = len(blob)
            tar.addfile(info, io.BytesIO(blob))
    out = sources.load_cifar(100)
    assert "synthetic" not in out
    assert out["train_x"].shape == (6, 32, 32, 3)
    np.testing.assert_array_equal(out["train_y"],
                                  np.asarray(train[b"fine_labels"], np.int32))


def test_fallback_when_no_files(data_dir, monkeypatch):
    monkeypatch.setenv("BMT_SYNTH_TRAIN", "32")
    monkeypatch.setenv("BMT_SYNTH_TEST", "16")
    out = sources.load_mnist("mnist")
    assert out.get("synthetic") is True
    assert out["train_x"].shape == (32, 28, 28, 1)
    # Deterministic across calls (crc32-seeded, not hash())
    again = sources.load_mnist("mnist")
    np.testing.assert_array_equal(out["train_x"], again["train_x"])


def test_mnist_family_does_not_cross_load(data_dir):
    """The MNIST family shares idx filenames; a cached MNIST tree must NOT
    satisfy a kmnist request (and vice versa) — each family member loads
    only from its own subdir, falling back to synthetic otherwise. Gzipped
    subdir files load for every member."""
    rng = np.random.default_rng(7)
    tr_x = rng.integers(0, 256, (12, 28, 28)).astype(np.uint8)
    tr_y = rng.integers(0, 10, 12).astype(np.uint8)
    te_x = rng.integers(0, 256, (4, 28, 28)).astype(np.uint8)
    te_y = rng.integers(0, 10, 4).astype(np.uint8)
    raw = data_dir / "MNIST" / "raw"
    raw.mkdir(parents=True)
    _write_idx_images(raw / "train-images-idx3-ubyte", tr_x)
    _write_idx_labels(raw / "train-labels-idx1-ubyte", tr_y)
    _write_idx_images(raw / "t10k-images-idx3-ubyte", te_x)
    _write_idx_labels(raw / "t10k-labels-idx1-ubyte", te_y)
    # kmnist must not pick up the MNIST files
    out = sources.load_mnist("kmnist")
    assert out.get("synthetic"), "kmnist silently loaded MNIST raw files"
    # and mnist itself must not pick up a KMNIST-only tree
    out = sources.load_mnist("mnist")
    np.testing.assert_array_equal(out["train_x"][..., 0], tr_x)

    kraw = data_dir / "KMNIST" / "raw"
    kraw.mkdir(parents=True)
    ktr_x = rng.integers(0, 256, (10, 28, 28)).astype(np.uint8)
    for name, arr, writer in (
            ("train-images-idx3-ubyte", ktr_x, _write_idx_images),
            ("train-labels-idx1-ubyte", tr_y[:10], _write_idx_labels),
            ("t10k-images-idx3-ubyte", te_x, _write_idx_images),
            ("t10k-labels-idx1-ubyte", te_y, _write_idx_labels)):
        # gzipped variant: subdir .gz candidates must load
        import gzip as _gz
        buf = io.BytesIO()
        tmp = kraw / (name + ".tmp")
        writer(tmp, arr)
        with open(tmp, "rb") as fd, _gz.open(kraw / (name + ".gz"), "wb") as gz:
            gz.write(fd.read())
        tmp.unlink()
    out = sources.load_mnist("kmnist")
    assert "synthetic" not in out
    np.testing.assert_array_equal(out["train_x"][..., 0], ktr_x)


def _write_idx2_int(path, arr):
    """QMNIST-style idx2-int label records: big-endian int32, 2 dims."""
    with open(path, "wb") as fd:
        fd.write(struct.pack(">I", 0x00000C02))  # int32, 2 dims
        fd.write(struct.pack(">2I", *arr.shape))
        fd.write(arr.astype(">i4").tobytes())


def test_emnist_split_idx_files(data_dir):
    rng = np.random.default_rng(21)
    tr_x = rng.integers(0, 256, (10, 28, 28)).astype(np.uint8)
    tr_y = rng.integers(0, 47, 10).astype(np.uint8)
    te_x = rng.integers(0, 256, (4, 28, 28)).astype(np.uint8)
    te_y = rng.integers(0, 47, 4).astype(np.uint8)
    raw = data_dir / "EMNIST" / "raw"
    raw.mkdir(parents=True)
    _write_idx_images(raw / "emnist-balanced-train-images-idx3-ubyte", tr_x)
    _write_idx_labels(raw / "emnist-balanced-train-labels-idx1-ubyte", tr_y)
    _write_idx_images(raw / "emnist-balanced-test-images-idx3-ubyte", te_x)
    _write_idx_labels(raw / "emnist-balanced-test-labels-idx1-ubyte", te_y)
    out = sources.load_emnist(split="balanced")
    assert "synthetic" not in out
    np.testing.assert_array_equal(out["train_x"][..., 0], tr_x)
    np.testing.assert_array_equal(out["train_y"], tr_y.astype(np.int32))
    assert out["test_x"].shape == (4, 28, 28, 1)
    # A different split must NOT pick up the balanced files
    other = sources.load_emnist(split="letters")
    assert other.get("synthetic"), "letters silently loaded balanced files"


def test_emnist_unknown_split_rejected(data_dir):
    from byzantinemomentum_tpu import utils
    with pytest.raises(utils.UserException, match="split"):
        sources.load_emnist(split="nope")


def test_emnist_fallback_class_counts(data_dir, monkeypatch):
    monkeypatch.setenv("BMT_SYNTH_TRAIN", "64")
    monkeypatch.setenv("BMT_SYNTH_TEST", "16")
    out = sources.load_emnist(split="byclass")
    assert out.get("synthetic") is True
    assert out["train_x"].shape == (64, 28, 28, 1)
    assert int(out["train_y"].max()) >= 10  # 62-class split, not 10

def test_qmnist_idx2_int_labels(data_dir):
    rng = np.random.default_rng(22)
    tr_x = rng.integers(0, 256, (10, 28, 28)).astype(np.uint8)
    te_x = rng.integers(0, 256, (6, 28, 28)).astype(np.uint8)
    # 8-column extended label records; class label in column 0
    tr_rec = np.zeros((10, 8), np.int64)
    tr_rec[:, 0] = rng.integers(0, 10, 10)
    tr_rec[:, 1] = 999  # metadata columns must be ignored
    te_rec = np.zeros((6, 8), np.int64)
    te_rec[:, 0] = rng.integers(0, 10, 6)
    raw = data_dir / "QMNIST" / "raw"
    raw.mkdir(parents=True)
    _write_idx_images(raw / "qmnist-train-images-idx3-ubyte", tr_x)
    _write_idx2_int(raw / "qmnist-train-labels-idx2-int", tr_rec)
    # The test side ships gzipped like torchvision's cache
    with gzip.open(raw / "qmnist-test-images-idx3-ubyte.gz", "wb") as fd:
        fd.write(struct.pack(">I", 0x00000803))
        fd.write(struct.pack(">3I", *te_x.shape))
        fd.write(te_x.tobytes())
    buf = io.BytesIO()
    buf.write(struct.pack(">I", 0x00000C02))
    buf.write(struct.pack(">2I", *te_rec.shape))
    buf.write(te_rec.astype(">i4").tobytes())
    with gzip.open(raw / "qmnist-test-labels-idx2-int.gz", "wb") as fd:
        fd.write(buf.getvalue())
    out = sources.load_qmnist()
    assert "synthetic" not in out
    np.testing.assert_array_equal(out["train_x"][..., 0], tr_x)
    np.testing.assert_array_equal(out["train_y"], tr_rec[:, 0].astype(np.int32))
    np.testing.assert_array_equal(out["test_y"], te_rec[:, 0].astype(np.int32))
    assert out["train_y"].dtype == np.int32


def test_emnist_qmnist_registered_plain_totensor():
    """Both names resolve through `make_datasets`, and (like the reference's
    datasets without a `transforms` entry) get plain ToTensor semantics: no
    normalization, no flips — batches land in [0, 1]."""
    import os
    from byzantinemomentum_tpu import data as data_mod
    os.environ["BMT_SYNTH_TRAIN"] = "32"
    os.environ["BMT_SYNTH_TEST"] = "16"
    try:
        for name, kw in (("emnist", {"split": "digits"}), ("qmnist", {})):
            tr, te = data_mod.make_datasets(name, 8, 8, **kw)
            assert tr.synthetic and te.synthetic
            x, y = tr.sample()
            assert x.dtype == np.float32
            assert x.min() >= 0.0 and x.max() <= 1.0  # no normalization
            assert not tr.sample_flips().any()        # no flips
    finally:
        os.environ.pop("BMT_SYNTH_TRAIN", None)
        os.environ.pop("BMT_SYNTH_TEST", None)


# --------------------------------------------------------------------------- #
# Opt-in checksummed download path (reference `dataset.py:296`,
# `datasets/svm.py:68-76`): mocked fetches only — this environment has no
# network egress, so the real URLs are exercised outside it.

def _fake_opener(payloads):
    """opener(url) -> file-like serving payloads[url] (records the calls)."""
    calls = []

    class _Resp(io.BytesIO):
        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    def opener(url):
        calls.append(url)
        if url not in payloads:
            raise OSError(f"unexpected URL {url}")
        return _Resp(payloads[url])

    opener.calls = calls
    return opener


def test_download_disabled_by_default(data_dir, monkeypatch):
    monkeypatch.delenv("BMT_DOWNLOAD", raising=False)
    assert not sources.download_enabled()
    assert sources.ensure_downloaded("mnist") is False


def test_download_fetches_verifies_and_installs(data_dir, monkeypatch):
    import hashlib
    monkeypatch.setenv("BMT_DOWNLOAD", "1")
    payload = gzip.compress(b"not really mnist but checksummed")
    digest = hashlib.md5(payload).hexdigest()
    url = "https://example.invalid/file.gz"
    monkeypatch.setitem(
        sources.DOWNLOADS, "testset",
        [(url, f"md5:{digest}", "TestSet/raw/file.gz")])
    opener = _fake_opener({url: payload})
    assert sources.ensure_downloaded("testset", opener=opener) is True
    installed = data_dir / "TestSet" / "raw" / "file.gz"
    assert installed.read_bytes() == payload
    assert not installed.with_name("file.gz.part").exists()
    # Second call: already on disk, no re-fetch
    assert sources.ensure_downloaded("testset", opener=opener) is False
    assert len(opener.calls) == 1


def test_download_checksum_mismatch_refuses_install(data_dir, monkeypatch):
    from byzantinemomentum_tpu import utils
    monkeypatch.setenv("BMT_DOWNLOAD", "1")
    url = "https://example.invalid/bad.gz"
    monkeypatch.setitem(
        sources.DOWNLOADS, "testset",
        [(url, "md5:" + "0" * 32, "TestSet/raw/bad.gz")])
    with pytest.raises(utils.UserException, match="Checksum mismatch"):
        sources.ensure_downloaded(
            "testset", opener=_fake_opener({url: b"corrupted"}))
    target = data_dir / "TestSet" / "raw"
    # Neither the file nor the temp partial landed
    assert not (target / "bad.gz").exists()
    assert not (target / "bad.gz.part").exists()


def test_download_retries_transient_failures_with_backoff(data_dir,
                                                          monkeypatch):
    """The fetch path retries transient OSErrors with backoff
    (`faults/retry.py`): a source that fails once then recovers still
    installs; the retry observes the configured attempt budget."""
    import hashlib
    monkeypatch.setenv("BMT_DOWNLOAD", "1")
    monkeypatch.setenv("BMT_FETCH_ATTEMPTS", "3")
    monkeypatch.setenv("BMT_FETCH_BACKOFF", "0")
    payload = gzip.compress(b"recovers on the second attempt")
    url = "https://example.invalid/flaky.gz"
    monkeypatch.setitem(
        sources.DOWNLOADS, "testset",
        [(url, "md5:" + hashlib.md5(payload).hexdigest(),
          "TestSet/raw/flaky.gz")])
    inner = _fake_opener({url: payload})

    def flaky(u):
        if len(inner.calls) < 1:
            inner.calls.append(u)
            raise OSError("connection reset")
        return inner(u)

    assert sources.ensure_downloaded("testset", opener=flaky) is True
    assert len(inner.calls) == 2  # one failure + one success
    assert (data_dir / "TestSet" / "raw" / "flaky.gz").read_bytes() \
        == payload


def test_download_does_not_retry_checksum_mismatch(data_dir, monkeypatch):
    """A checksum mismatch is content corruption, not a transient fault:
    the same payload would come back, so it raises on the FIRST attempt
    (no retry burns the budget re-downloading garbage)."""
    from byzantinemomentum_tpu import utils
    monkeypatch.setenv("BMT_DOWNLOAD", "1")
    monkeypatch.setenv("BMT_FETCH_ATTEMPTS", "5")
    monkeypatch.setenv("BMT_FETCH_BACKOFF", "0")
    url = "https://example.invalid/corrupt.gz"
    monkeypatch.setitem(
        sources.DOWNLOADS, "testset",
        [(url, "md5:" + "0" * 32, "TestSet/raw/corrupt.gz")])
    opener = _fake_opener({url: b"corrupted"})
    with pytest.raises(utils.UserException, match="Checksum mismatch"):
        sources.ensure_downloaded("testset", opener=opener)
    assert len(opener.calls) == 1


def test_kmnist_qmnist_pin_torchvision_digests():
    """KMNIST/QMNIST carry torchvision's published MD5s, so neither needs
    the BMT_DOWNLOAD_UNVERIFIED escape hatch anymore."""
    for name in ("kmnist", "qmnist"):
        for url, checksum, rel in sources.DOWNLOADS[name]:
            assert checksum is not None and checksum.startswith("md5:"), url
            assert len(checksum) == len("md5:") + 32, url


def test_download_unverified_requires_explicit_optin(data_dir, monkeypatch):
    monkeypatch.setenv("BMT_DOWNLOAD", "1")
    monkeypatch.delenv("BMT_DOWNLOAD_UNVERIFIED", raising=False)
    url = "https://example.invalid/nodigest"
    monkeypatch.setitem(
        sources.DOWNLOADS, "testset", [(url, None, "TestSet/raw/nodigest")])
    opener = _fake_opener({url: b"payload"})
    # Without the extra opt-in: skipped with a warning, nothing fetched
    assert sources.ensure_downloaded("testset", opener=opener) is False
    assert opener.calls == []
    # With it: fetched
    monkeypatch.setenv("BMT_DOWNLOAD_UNVERIFIED", "1")
    assert sources.ensure_downloaded("testset", opener=opener) is True
    assert (data_dir / "TestSet" / "raw" / "nodigest").read_bytes() == b"payload"


def test_download_installs_loadable_mnist(data_dir, monkeypatch):
    """End-to-end through a loader: a mocked fetch of all four gzipped idx
    files makes `load_mnist` pick them up instead of the synthetic
    fallback."""
    import hashlib
    monkeypatch.setenv("BMT_DOWNLOAD", "1")
    rng = np.random.default_rng(33)
    arrays = {
        "train-images-idx3-ubyte": rng.integers(0, 256, (6, 28, 28)).astype(np.uint8),
        "train-labels-idx1-ubyte": rng.integers(0, 10, 6).astype(np.uint8),
        "t10k-images-idx3-ubyte": rng.integers(0, 256, (3, 28, 28)).astype(np.uint8),
        "t10k-labels-idx1-ubyte": rng.integers(0, 10, 3).astype(np.uint8),
    }
    payloads, entries = {}, []
    for fname, arr in arrays.items():
        buf = io.BytesIO()
        if arr.ndim == 3:
            buf.write(struct.pack(">I", 0x00000803))
            buf.write(struct.pack(">3I", *arr.shape))
        else:
            buf.write(struct.pack(">I", 0x00000801))
            buf.write(struct.pack(">I", arr.shape[0]))
        buf.write(arr.tobytes())
        payload = gzip.compress(buf.getvalue())
        url = f"https://example.invalid/{fname}.gz"
        payloads[url] = payload
        entries.append((url, "md5:" + hashlib.md5(payload).hexdigest(),
                        f"MNIST/raw/{fname}.gz"))
    monkeypatch.setitem(sources.DOWNLOADS, "mnist", entries)
    orig = sources.ensure_downloaded
    monkeypatch.setattr(
        sources, "ensure_downloaded",
        lambda name, opener=None: orig(name, opener=_fake_opener(payloads)))
    out = sources.load_mnist("mnist")
    assert "synthetic" not in out
    np.testing.assert_array_equal(out["train_x"][..., 0],
                                  arrays["train-images-idx3-ubyte"])
    np.testing.assert_array_equal(out["test_y"],
                                  arrays["t10k-labels-idx1-ubyte"].astype(np.int32))


def test_download_network_failure_degrades_to_fallback(data_dir, monkeypatch):
    """An unreachable source warns and degrades (disk probe -> synthetic);
    only a reachable-but-corrupt source raises."""
    monkeypatch.setenv("BMT_DOWNLOAD", "1")
    monkeypatch.setenv("BMT_SYNTH_TRAIN", "16")
    monkeypatch.setenv("BMT_SYNTH_TEST", "8")

    def opener(url):
        raise OSError("no route to host")

    orig = sources.ensure_downloaded
    monkeypatch.setattr(
        sources, "ensure_downloaded",
        lambda name, op=None: orig(name, opener=opener))
    out = sources.load_mnist("mnist")
    assert out.get("synthetic") is True


def test_svhn_mat_files(data_dir):
    """SVHN's .mat containers parse with torchvision's exact semantics:
    (32,32,3,N) -> NHWC and label 10 -> digit 0."""
    from scipy.io import savemat
    rng = np.random.default_rng(41)
    def make(n):
        x = rng.integers(0, 256, (32, 32, 3, n)).astype(np.uint8)
        y = rng.integers(1, 11, (n, 1)).astype(np.uint8)  # 1..10, 10 = '0'
        return x, y
    d = data_dir / "SVHN"
    d.mkdir()
    tr_x, tr_y = make(6)
    te_x, te_y = make(3)
    savemat(d / "train_32x32.mat", {"X": tr_x, "y": tr_y})
    savemat(d / "test_32x32.mat", {"X": te_x, "y": te_y})
    out = sources.load_svhn()
    assert "synthetic" not in out
    assert out["train_x"].shape == (6, 32, 32, 3)
    np.testing.assert_array_equal(out["train_x"][0], tr_x[..., 0])
    expect = tr_y.reshape(-1).astype(np.int32)
    expect[expect == 10] = 0
    np.testing.assert_array_equal(out["train_y"], expect)
    assert out["train_y"].max() < 10


def test_svhn_fallback_and_registry(data_dir, monkeypatch):
    monkeypatch.setenv("BMT_SYNTH_TRAIN", "16")
    monkeypatch.setenv("BMT_SYNTH_TEST", "8")
    from byzantinemomentum_tpu import data as data_mod
    tr, te = data_mod.make_datasets("svhn", 4, 4)
    assert tr.synthetic and te.synthetic
    x, y = tr.sample()
    assert x.shape == (4, 32, 32, 3) and x.max() <= 1.0  # plain ToTensor
    assert not tr.sample_flips().any()


def test_download_probe_does_not_cross_match_sibling_family(data_dir,
                                                            monkeypatch):
    """Presence probing uses the subdir-qualified path only: a cached MNIST
    tree must not satisfy a KMNIST download probe (the family shares bare
    idx filenames)."""
    monkeypatch.setenv("BMT_DOWNLOAD", "1")
    raw = data_dir / "MNIST" / "raw"
    raw.mkdir(parents=True)
    (raw / "train-images-idx3-ubyte.gz").write_bytes(b"mnist bytes")
    import hashlib
    payload = b"kmnist payload"
    url = "https://example.invalid/k/train-images-idx3-ubyte.gz"
    monkeypatch.setitem(
        sources.DOWNLOADS, "kmnist",
        [(url, "md5:" + hashlib.md5(payload).hexdigest(),
          "KMNIST/raw/train-images-idx3-ubyte.gz")])
    opener = _fake_opener({url: payload})
    assert sources.ensure_downloaded("kmnist", opener=opener) is True
    assert opener.calls == [url]
    assert (data_dir / "KMNIST" / "raw"
            / "train-images-idx3-ubyte.gz").read_bytes() == payload


def test_worker_pack_kill_switch_value_semantics(monkeypatch):
    """BMT_NO_WORKER_PACK parses values like the other env knobs: '0' and
    'false' keep packing ON (ADVICE-style regression for the A/B
    workflow)."""
    from byzantinemomentum_tpu.models.core import _worker_packing
    monkeypatch.delenv("BMT_NO_WORKER_PACK", raising=False)
    assert _worker_packing(4, 64) == 2
    monkeypatch.setenv("BMT_NO_WORKER_PACK", "0")
    assert _worker_packing(4, 64) == 2
    monkeypatch.setenv("BMT_NO_WORKER_PACK", "false")
    assert _worker_packing(4, 64) == 2
    monkeypatch.setenv("BMT_NO_WORKER_PACK", "1")
    assert _worker_packing(4, 64) == 1
