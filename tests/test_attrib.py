"""Phase-attributed device profiling tests (obs/attrib, PR 6): the
scope/op-class bucketers, the HLO-text scope join, xplane parsing of a
real CPU capture, the attribution artifact's invariants, the driver's
`--attribution` window (acceptance: per-phase ms/step sum within 15% of
the telemetry `device_step_ms` gauge on the CPU smoke config), the
SIGUSR1 live window's subprocess regression, and the trace_opstats CLI."""

import json
import os
import pathlib
import signal
import subprocess
import sys
import time

import pytest

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu import obs
from byzantinemomentum_tpu.obs import attrib
from byzantinemomentum_tpu.obs.attrib import phases, xplane

ROOT = pathlib.Path(__file__).resolve().parent.parent


# --------------------------------------------------------------------------- #
# Bucketers (pure, no trace needed)

def test_phase_of_segment_matching():
    assert phases.phase_of("jit(f)/jit(main)/honest/dot_general") == "honest"
    assert phases.phase_of("jit(f)/while/body/gar/jit(sort)/sort") == "gar"
    assert phases.phase_of("jit(f)/gar_masked/reduce") == "gar_masked"
    assert phases.phase_of("jit(f)/gar_diag/scores") == "gar_diag"
    # Outermost wins: an attack's inner line-search defense belongs to
    # the attack (PERF_NOTES' "attack incl. its defense call" convention)
    assert phases.phase_of("jit(f)/attack/probe/gar/krum") == "attack"
    # Segment match, not substring: a user scope named "gargle" is no GAR
    assert phases.phase_of("jit(f)/gargle/add") is None
    assert phases.phase_of("jit(f)/transpose/relayout") is None
    assert phases.phase_of(None) is None


def test_op_class_of():
    assert phases.op_class_of("dot.7") == "mxu"
    assert phases.op_class_of("convolution.12") == "mxu"
    assert phases.op_class_of("loop_convolution_fusion") == "mxu"
    assert phases.op_class_of("copy.3") == "relayout"
    assert phases.op_class_of("reshape.1") == "relayout"
    assert phases.op_class_of("transpose") == "relayout"
    assert phases.op_class_of("bitcast.2") == "relayout"
    assert phases.op_class_of("broadcast_add_fusion") == "memory"
    assert phases.op_class_of("reduce-window") == "memory"
    assert phases.op_class_of("sort.0") == "memory"


def test_scope_map_from_hlo_text():
    text = """
ENTRY %main.18 (Arg_0.1: f32[256,256]) -> f32[] {
  %Arg_0.1 = f32[256,256]{1,0} parameter(0), metadata={op_name="x"}
  %dot.7 = f32[256,256]{1,0} dot(...), metadata={op_name="jit(f)/honest/dot_general" source_file="a.py"}
  ROOT %fusion.1 = f32[] fusion(...), kind=kLoop, metadata={op_name="jit(f)/update/add"}
  %no_meta = f32[] constant(0)
}
"""
    scopes = phases.scope_map_from_hlo(text)
    assert scopes["dot.7"] == "jit(f)/honest/dot_general"
    assert scopes["fusion.1"] == "jit(f)/update/add"
    assert "no_meta" not in scopes


# --------------------------------------------------------------------------- #
# A real CPU capture of a phase-annotated program (shared by the xplane
# and attribution tests; one trace, module-scoped)

@pytest.fixture(scope="module")
def traced_program(tmp_path_factory):
    pytest.importorskip("tensorflow.tsl.profiler.protobuf")

    @jax.jit
    def step(x):
        with jax.named_scope("honest"):
            y = x @ x
        with jax.named_scope("gar"):
            z = jnp.sort(y, axis=0)
        with jax.named_scope("update"):
            w = z * 2.0 + 1.0
        return w.sum()

    x = jnp.ones((128, 128), jnp.float32)
    step(x).block_until_ready()  # compile outside the window
    hlo_text = step.lower(x).compile().as_text()
    trace_dir = tmp_path_factory.mktemp("attrib") / "trace"
    jax.profiler.start_trace(str(trace_dir))
    for _ in range(4):
        step(x).block_until_ready()
    jax.profiler.stop_trace()
    return trace_dir, hlo_text, 4


def test_xplane_parses_cpu_capture(traced_program):
    trace_dir, _, _ = traced_program
    assert xplane.find_xplane(trace_dir) is not None
    space = xplane.load_xspace(trace_dir)
    events = xplane.op_events(space)
    assert events, "no HLO op events parsed from the CPU capture"
    assert all(e.dur_ms >= 0.0 for e in events)
    totals = xplane.aggregate_ops(space)
    assert any(name.startswith("dot") for name in totals)
    # Aggregation conserves time and counts every event
    assert sum(c for _, c in totals.values()) == len(events)
    assert sum(ms for ms, _ in totals.values()) == pytest.approx(
        sum(e.dur_ms for e in events))
    busy, span = xplane.window_span(events)
    assert 0.0 < busy <= span


def test_load_xspace_missing_capture(tmp_path):
    with pytest.raises(FileNotFoundError):
        xplane.load_xspace(tmp_path)


def test_load_xspace_size_cap(tmp_path, monkeypatch):
    """A capture past the size cap (a window that traced a compile) is
    refused instead of stalling the pure-python proto parser for
    minutes; the cap is env-overridable."""
    fat = tmp_path / "plugins" / "profile" / "x"
    fat.mkdir(parents=True)
    (fat / "vm.xplane.pb").write_bytes(b"\0" * 4096)
    monkeypatch.setenv("BMT_XPLANE_MAX_MB", "0.001")
    with pytest.raises(ValueError, match="cap"):
        xplane.load_xspace(tmp_path)


def test_attribute_trace_invariants(traced_program):
    trace_dir, hlo_text, steps = traced_program
    att = attrib.attribute_trace(trace_dir, steps, hlo_text=hlo_text,
                                 flops_per_step=2 * 128 ** 3,
                                 peak_flops=1e12, backend="cpu",
                                 device_kind="cpu")
    assert att["kind"] == "attribution"
    assert att["steps"] == steps
    # The engine phases the program annotates all get device time
    for name in ("honest", "gar", "update"):
        assert att["phases"][name]["ms"] > 0.0, att["phases"]
    # Phase buckets (incl. other + host) tile the window exactly —
    # the invariant the driver acceptance check leans on
    total = sum(p["ms"] for p in att["phases"].values())
    assert total == pytest.approx(att["total_ms"], rel=1e-9)
    assert att["device_ms"] + att["host_gap_ms"] == pytest.approx(
        att["total_ms"])
    classes = sum(att["op_classes"].values())
    assert classes == pytest.approx(att["device_ms"], rel=1e-9)
    assert att["phases"]["honest"]["ms"] == pytest.approx(
        att["op_classes"]["mxu"], rel=0.5)  # the matmul IS the honest phase
    assert 0.0 <= att["host_gap_fraction"] < 1.0
    assert att["mxu_floor_ms"] == pytest.approx(2 * 128 ** 3 / 1e12 * 1e3)
    assert att["mfu"] is not None and att["distance_to_floor"] > 1.0


def test_attribution_artifact_roundtrip(traced_program, tmp_path):
    trace_dir, hlo_text, steps = traced_program
    att = attrib.attribute_trace(trace_dir, steps, hlo_text=hlo_text)
    path = attrib.write_attribution(tmp_path, att)
    assert path.name == attrib.ATTRIBUTION_NAME
    assert attrib.load_attribution(tmp_path) == json.loads(path.read_text())
    assert attrib.load_attribution(tmp_path / "absent") is None
    (tmp_path / "torn.json").write_text("{not json")
    assert attrib.load_attribution(tmp_path / "torn.json") is None
    # The one-pager renders the artifact even without telemetry records
    from byzantinemomentum_tpu.obs.report import render_report
    report = render_report(tmp_path)
    assert "perf attribution" in report
    assert "honest" in report and "gar" in report


def test_trace_opstats_cli(traced_program):
    trace_dir, _, _ = traced_program
    proc = subprocess.run(
        [sys.executable, "scripts/trace_opstats.py", str(trace_dir),
         "--steps", "4", "--top", "5", "--device", "auto"],
        cwd=ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "total op time" in proc.stdout
    assert "ms/step" in proc.stdout
    # The TPU plane is not in a CPU capture: the explicit default errors
    # out with the available planes listed, as the original script did
    proc = subprocess.run(
        [sys.executable, "scripts/trace_opstats.py", str(trace_dir)],
        cwd=ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode != 0
    assert "not in trace" in proc.stderr


# --------------------------------------------------------------------------- #
# Driver end to end: the --attribution window on the CPU smoke config

DRIVER_BASE = ["--batch-size", "8", "--batch-size-test", "32",
               "--batch-size-test-reps", "2", "--evaluation-delta", "0",
               "--model", "simples-full", "--seed", "11", "--gar", "median",
               "--nb-for-study", "11", "--nb-for-study-past", "2",
               "--telemetry-interval", "4", "--steps-per-program", "8"]


@pytest.fixture(autouse=True)
def small_synth(monkeypatch):
    monkeypatch.setenv("BMT_SYNTH_TRAIN", "512")
    monkeypatch.setenv("BMT_SYNTH_TEST", "128")


def test_driver_attribution_acceptance(tmp_path):
    """`--attribution` on the CPU smoke config writes `attribution.json`
    whose per-phase ms/step sum lands within 15% of the `device_step_ms`
    gauge sampled on the SAME traced chunk, stamps the `attribution`
    telemetry event, and the one-pager grows its section."""
    pytest.importorskip("tensorflow.tsl.profiler.protobuf")
    from byzantinemomentum_tpu.cli.attack import main
    resdir = tmp_path / "run"
    rc = main(DRIVER_BASE + ["--nb-steps", "24", "--attribution",
                             "--result-directory", str(resdir)])
    assert rc == 0
    att = attrib.load_attribution(resdir)
    assert att is not None, "attribution.json was not written"
    assert att["steps"] == 8  # one steps-per-program chunk
    phase_sum = sum(p["ms"] for p in att["phases"].values())
    assert phase_sum == pytest.approx(att["total_ms"], rel=1e-9)
    # The honest phase and the GAR must both carry device time
    assert att["phases"]["honest"]["ms"] > 0.0
    assert att["phases"]["gar"]["ms"] > 0.0
    # No attack rows in this config: the attack phase stays empty
    assert att["phases"]["attack"]["ms"] == 0.0

    records = obs.load_records(resdir)
    events = [r for r in records if r["kind"] == "event"
              and r["name"] == "attribution"]
    assert len(events) == 1
    data = events[0]["data"]
    assert data["steps"] == 8
    assert data["total_ms"] == pytest.approx(att["total_ms"])

    # ACCEPTANCE: the traced chunk (steps 8..16 — warm-up chunk first)
    # was force-sampled, so a device_step_ms gauge covers exactly it;
    # the attribution's phase sum must agree within 15%
    gauges = [r for r in records if r["kind"] == "gauge"
              and r["name"] == "device_step_ms"
              and (r.get("data") or {}).get("step") == 16]
    assert gauges, "no device_step_ms sample on the traced chunk"
    device_step_ms = gauges[-1]["value"]
    assert phase_sum == pytest.approx(device_step_ms, rel=0.15)

    from byzantinemomentum_tpu.obs.report import render_report
    report = render_report(resdir)
    assert "perf attribution" in report

    # The window directory keeps the raw capture for trace_opstats drills
    assert xplane.find_xplane(resdir / "attribution-trace") is not None


def test_driver_attribution_off_leaves_no_artifacts(tmp_path):
    """Flag off: no trace window, no artifact — the hot path is the
    pre-PR-6 program (the zero-recompile budget over it is asserted in
    tests/test_analysis.py)."""
    from byzantinemomentum_tpu.cli.attack import main
    resdir = tmp_path / "run"
    rc = main(DRIVER_BASE + ["--nb-steps", "8",
                             "--result-directory", str(resdir)])
    assert rc == 0
    assert attrib.load_attribution(resdir) is None
    assert not (resdir / "attribution-trace").exists()
    assert not [r for r in obs.load_records(resdir)
                if r["kind"] == "event" and r["name"] == "attribution"]


def test_driver_attribution_requires_result_directory():
    from byzantinemomentum_tpu.cli.attack import main
    # Warns + disables (and the run still completes without writing)
    rc = main(DRIVER_BASE + ["--nb-steps", "0", "--attribution"])
    assert rc == 0


# --------------------------------------------------------------------------- #
# SIGUSR1 live profiler window — subprocess regression (previously only
# exercised manually): the window directory is populated, the
# profiler_window event lands, the window auto-attributes, and the run
# completes unharmed.

def test_sigusr1_window_subprocess(tmp_path):
    pytest.importorskip("tensorflow.tsl.profiler.protobuf")
    if not hasattr(signal, "SIGUSR1"):
        pytest.skip("platform without SIGUSR1")
    resdir = tmp_path / "live"
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "BMT_SYNTH_TRAIN": "512", "BMT_SYNTH_TEST": "128"}
    proc = subprocess.Popen(
        [sys.executable, "attack.py", *DRIVER_BASE,
         "--nb-steps", "24", "--steps-per-program", "4",
         "--result-directory", str(resdir)],
        cwd=ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        # Wait for the driver's first heartbeat (written before the first
        # dispatch), then signal: the window opens at the next loop top
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if obs.read_heartbeat(resdir) is not None:
                break
            if proc.poll() is not None:
                break
            time.sleep(0.2)
        assert proc.poll() is None, (
            "driver exited before its first heartbeat:\n"
            + proc.communicate()[0])
        proc.send_signal(signal.SIGUSR1)
        out, _ = proc.communicate(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, out

    windows = sorted(resdir.glob("profile-*"))
    assert windows, f"no profiler window directory:\n{out}"
    assert xplane.find_xplane(windows[0]) is not None, (
        "window directory not populated with an xplane capture")
    records = obs.load_records(resdir)
    events = [r for r in records if r["kind"] == "event"
              and r["name"] == "profiler_window"]
    assert events, "profiler_window event missing from the timeline"
    assert events[0]["data"]["directory"] == str(windows[0])
    assert events[0]["data"]["to_step"] > events[0]["data"]["from_step"]
    # The live window auto-attributes into its own directory
    att = attrib.load_attribution(windows[0])
    assert att is not None, f"SIGUSR1 window was not attributed:\n{out}"
    assert att["total_ms"] > 0.0
    # The run itself was unharmed: it reached its step budget
    end = [r for r in records if r["kind"] == "event"
           and r["name"] == "run_end"][-1]
    assert end["data"]["status"] == "completed"
    assert end["data"]["step"] == 24
