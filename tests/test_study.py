"""L5 analysis tests: Session loading/derived columns and the Jobs
scheduler's idempotency/failure contracts (reference `study.py:185-396`,
`tools/jobs.py:107-248`)."""

import os
import sys

import numpy as np
import pytest

import study
from byzantinemomentum_tpu.cli.attack import main
from byzantinemomentum_tpu.utils.jobs import Jobs, dict_to_cmdlist


@pytest.fixture(autouse=True)
def small_synth(monkeypatch):
    monkeypatch.setenv("BMT_SYNTH_TRAIN", "512")
    monkeypatch.setenv("BMT_SYNTH_TEST", "128")


@pytest.fixture(scope="module")
def result_dir(tmp_path_factory):
    resdir = tmp_path_factory.mktemp("results") / "run"
    os.environ.setdefault("BMT_SYNTH_TRAIN", "512")
    os.environ.setdefault("BMT_SYNTH_TEST", "128")
    main(["--nb-steps", "4", "--batch-size", "8", "--batch-size-test", "32",
          "--batch-size-test-reps", "2", "--evaluation-delta", "2",
          "--model", "simples-full", "--seed", "4", "--gar", "krum",
          "--nb-decl-byz", "3", "--nb-real-byz", "3", "--attack", "empire",
          "--attack-args", "factor:1.1", "--nb-for-study", "11",
          "--nb-for-study-past", "2", "--result-directory", str(resdir)])
    return resdir


def test_session_loads_and_joins(result_dir):
    sess = study.Session(result_dir)
    assert sess.json["gar"] == "krum"
    assert "Average loss" in sess.data.columns
    assert "Cross-accuracy" in sess.data.columns  # joined from eval
    assert sess.data.index.name == "Step number"


def test_session_derived_columns(result_dir):
    sess = study.Session(result_dir).compute_all()
    data = sess.data
    # Epoch = points / 60000 (mnist hardcoded size, reference study.py:309)
    row = data.dropna(subset=["Training point count"]).iloc[1]
    np.testing.assert_allclose(row["Epoch number"],
                               row["Training point count"] / 60000)
    # Hyperbolic lr reconstruction
    assert "Learning rate" in data.columns
    # Ratio columns + the bound check (krum has an upper_bound)
    assert "Honest ratio" in data.columns
    assert "Ratio enough for GAR?" in data.columns
    assert sess.has_known_ratio()
    np.testing.assert_allclose(
        row["Honest ratio"],
        (row["Honest gradient deviation"] / row["Honest gradient norm"]) ** 2)


def test_session_missing_directory():
    from byzantinemomentum_tpu import utils
    with pytest.raises(utils.UserException):
        study.Session("/nonexistent/result/dir")


def test_line_and_box_plots(result_dir, tmp_path):
    sess = study.Session(result_dir)
    plot = study.LinePlot()
    plot.include(sess, "Average loss")
    plot.finalize("t", "step", "loss")
    plot.save(tmp_path / "line.png")
    plot.close()
    box = study.BoxPlot()
    box.include(sess.data["Average loss"], "run")
    box.hline(1.0)
    box.finalize("t", "loss")
    box.save(tmp_path / "box.png")
    box.close()
    assert (tmp_path / "line.png").stat().st_size > 0
    assert (tmp_path / "box.png").stat().st_size > 0


def test_dict_to_cmdlist():
    cmd = dict_to_cmdlist({
        "nb-steps": 3, "momentum-nesterov": True, "skip-me": None,
        "off": False, "attack-args": ("factor:1.5", "negative:True")})
    assert cmd == ["--nb-steps", "3", "--momentum-nesterov",
                   "--attack-args", "factor:1.5", "negative:True"]


def test_jobs_run_skip_and_fail(tmp_path):
    jobs = Jobs(tmp_path, devices=("auto",), seeds=(1,))
    ok = [sys.executable, "-c",
          "import sys, pathlib; "
          "pathlib.Path(sys.argv[sys.argv.index('--result-directory')+1], "
          "'out.txt').write_text('done')"]
    bad = [sys.executable, "-c", "import sys; sys.exit(3)"]
    jobs.submit("good", ok)
    jobs.submit("bad", bad)
    jobs.wait()
    assert (tmp_path / "good-1" / "out.txt").read_text() == "done"
    assert (tmp_path / "bad-1.failed" / "stderr.log").exists()
    # Idempotency: resubmitting the completed job must skip it
    marker = tmp_path / "good-1" / "out.txt"
    marker.write_text("untouched")
    jobs2 = Jobs(tmp_path, devices=("auto",), seeds=(1,))
    jobs2.submit("good", ok)
    jobs2.wait()
    assert marker.read_text() == "untouched"


@pytest.fixture(scope="module")
def analysis_grid(tmp_path_factory):
    """A tiny grid following the reproduce.py naming convention: unattacked
    baseline + (at_update, at_worker) pair for one GAR, single seed."""
    data_dir = tmp_path_factory.mktemp("grid")
    base = ["--nb-steps", "4", "--batch-size", "8", "--batch-size-test", "32",
            "--batch-size-test-reps", "2", "--evaluation-delta", "2",
            "--model", "simples-full", "--seed", "5",
            "--nb-for-study-past", "2", "--learning-rate", "0.5"]
    main(base + ["--nb-workers", "7", "--nb-for-study", "7",
                 "--result-directory",
                 str(data_dir / "mnist-average-n_7-lr_0.5-1")])
    for at in ("update", "worker"):
        main(base + ["--nb-workers", "9", "--nb-for-study", "9",
                     "--nb-decl-byz", "2", "--nb-real-byz", "2",
                     "--gar", "median", "--attack", "empire",
                     "--attack-args", "factor:1.1", "--momentum-at", at,
                     "--result-directory",
                     str(data_dir / f"mnist-empire-median-f_2-lr_0.5-at_{at}-1")])
    return data_dir


@pytest.mark.slow
def test_reproduce_analysis_buckets_and_plots(analysis_grid, tmp_path, capsys):
    """The ported reference analysis (reproduce.py:258-366, :459-635):
    bucket statistics printed per subset, comparison + ratio plots saved."""
    import reproduce
    plot_dir = tmp_path / "plots"
    reproduce.analyze(analysis_grid, plot_dir)
    out = capsys.readouterr().out
    assert "#experiments with effective attack (10%):" in out
    assert "#experiments with defense gain above 40%:" in out
    assert '#experiments with >10% "optimality" loss:' in out
    assert "/   1 (" in out  # one at_worker experiment classified
    # Comparison plots: accuracy + loss per momentum placement, per-GAR ratio
    for name in ("mnist-empire-f_2-lr_0.5-at_update.png",
                 "mnist-empire-f_2-lr_0.5-at_update-loss.png",
                 "mnist-empire-f_2-lr_0.5-at_worker.png",
                 "mnist-empire-f_2-lr_0.5-at_worker-loss.png",
                 "mnist-empire-median-f_2-lr_0.5-ratio.png",
                 "overview-mnist-empire-f_2-lr_0.5.png"):
        assert (plot_dir / name).is_file(), name
    # Per-run ratio-condition counting on the analysis output
    assert "ratio ok" in out


def _write_faulted_run(directory, rate, nb_steps=4):
    """Handcraft one result directory in the driver's exact file format,
    with the `--fault-plan` study schema (FAULT_COLUMNS appended) — no
    training needed to exercise the analysis layer."""
    from byzantinemomentum_tpu.engine import FAULT_COLUMNS, STUDY_COLUMNS
    directory.mkdir(parents=True)
    columns = STUDY_COLUMNS + FAULT_COLUMNS
    lines = ["# " + "\t".join(columns)]
    for step in range(nb_steps):
        row = [str(step), str(step * 88)]
        row += ["%.8e" % (1.0 / (step + 1 + rate))] * (len(STUDY_COLUMNS) - 3)
        row.append("0.5")                      # Attack acceptation ratio
        row += [str(int(rate)), str(11 - int(rate)), "2"]  # fault columns
        lines.append("\t".join(row))
    (directory / "study").write_text(os.linesep.join(lines))
    (directory / "eval").write_text(os.linesep.join(
        ["# Step number\tCross-accuracy", "0\t0.1",
         f"{nb_steps - 1}\t{0.9 - 0.1 * rate}"]))
    (directory / "config").write_text("Configuration:")
    import json
    (directory / "config.json").write_text(json.dumps(
        {"gar": "median", "dataset": "mnist", "nb_workers": 11,
         "nb_decl_byz": 2, "learning_rate": 0.01}))


def test_fault_timeline_plot(tmp_path):
    """`study.fault_timeline`: degradation timeline off the PR 1 fault
    columns (ROADMAP open item), refusing fault-free sessions."""
    from byzantinemomentum_tpu import utils
    _write_faulted_run(tmp_path / "faulted", rate=2)
    sess = study.Session(tmp_path / "faulted")
    plot = study.fault_timeline(sess)
    plot.save(tmp_path / "timeline.png")
    plot.close()
    assert (tmp_path / "timeline.png").stat().st_size > 0
    _write_faultless = tmp_path / "clean"
    _write_faulted_run(_write_faultless, rate=0)
    clean = study.Session(_write_faultless)
    clean.data = clean.data.drop(columns=["Faults injected", "Workers active"])
    with pytest.raises(utils.UserException, match="fault columns"):
        study.fault_timeline(clean)


def test_fault_rate_sweep_plot(tmp_path):
    """`study.fault_rate_sweep`: one (rate, metric) point per run, sorted
    by observed rate, for both reducers; returns frame + saveable plot."""
    sessions = []
    for rate in (2, 0, 1):
        _write_faulted_run(tmp_path / f"rate{rate}", rate=rate)
        sessions.append(study.Session(tmp_path / f"rate{rate}"))
    frame, plot = study.fault_rate_sweep(sessions, metric="Average loss")
    assert list(frame.index) == sorted(frame.index)
    assert len(frame) == 3
    plot.save(tmp_path / "sweep.png")
    plot.close()
    assert (tmp_path / "sweep.png").stat().st_size > 0
    frame_mean, plot_mean = study.fault_rate_sweep(
        sessions, metric="Cross-accuracy", reducer="mean")
    plot_mean.close()
    # higher fault rate -> lower final accuracy in the synthetic fixtures
    accs = list(frame_mean["Cross-accuracy"])
    assert accs == sorted(accs, reverse=True)


def _write_telemetry_run(resdir, *, rate=10.0, rollback_at=None):
    """A synthetic run directory with just enough telemetry for the
    run-health plot family (no training needed)."""
    from byzantinemomentum_tpu import obs
    resdir.mkdir(parents=True, exist_ok=True)
    with obs.Telemetry(resdir) as telem:
        telem.event("run_start", seed=1)
        for step in range(10, 60, 10):
            telem.gauge("device_step_ms", 1000.0 / rate, step=step)
            telem.gauge("steps_per_sec", rate, step=step)
        if rollback_at is not None:
            telem.counter("rollbacks")
            telem.event("rollback", step=rollback_at, restored="checkpoint-0")
        telem.counter("faults_injected", 4)
        telem.event("run_end", step=50, status="completed")
        telem.heartbeat(step=50, steps_per_sec=rate)
    return resdir


def test_run_health_plot(tmp_path):
    """`study.run_health`: step-time/throughput timeline off the obs
    telemetry, with rollback overlays; refuses telemetry-less runs."""
    from byzantinemomentum_tpu import utils
    _write_telemetry_run(tmp_path / "healthy", rollback_at=30)
    frame = study.load_telemetry(tmp_path / "healthy")
    assert set(frame["kind"]) == {"event", "gauge", "counter"}
    assert 30 in list(frame[frame["name"] == "rollback"]["step"].dropna())
    plot = study.run_health(tmp_path / "healthy")
    plot.save(tmp_path / "health.png")
    plot.close()
    assert (tmp_path / "health.png").stat().st_size > 0
    (tmp_path / "empty").mkdir()
    with pytest.raises(utils.UserException, match="telemetry"):
        study.run_health(tmp_path / "empty")


def test_run_health_from_real_run(result_dir):
    """The plot family works off an actual driver run's telemetry (the
    default-on recording), not just synthetic fixtures."""
    plot = study.run_health(study.Session(result_dir))
    plot.close()


def test_throughput_sweep(tmp_path):
    rates = {"slow": 5.0, "fast": 20.0}
    sessions = []
    for name, rate in rates.items():
        _write_telemetry_run(tmp_path / name, rate=rate)
        sessions.append(study.Session(tmp_path / name))
    frame, plot = study.throughput_sweep(sessions)
    plot.close()
    assert dict(zip(frame.index, frame["Steps/s"])) == pytest.approx(rates)
    # Runs without telemetry are skipped, not fatal
    bare = tmp_path / "bare"
    bare.mkdir()
    (bare / "config.json").write_text("{}")
    frame2, plot2 = study.throughput_sweep(sessions + [study.Session(bare)])
    plot2.close()
    assert len(frame2) == 2


def test_display_fallback(result_dir, capsys):
    """`study.display` degrades gracefully without GTK: warning + text
    rendering (reference `study.py:72-78`)."""
    if not hasattr(study, "_gtk_reason"):
        pytest.skip("GTK 3.0 available: display opens a real window")
    sess = study.Session(result_dir)
    study.display(sess)
    out = capsys.readouterr()
    text = out.out + out.err
    assert "GTK 3.0 is unavailable" in text
    assert "Average loss" in text


def test_select_and_discard(result_dir):
    """Substring column selection helpers (reference `study.py:83-126`)."""
    sess = study.Session(result_dir).compute_ratio(nowarn=True)
    ratios = study.select(sess, "ratio")
    assert all("ratio" in c.lower() for c in ratios.columns)
    assert "Sampled ratio" in ratios.columns
    assert study.select(sess).equals(sess.data)
    rest = study.discard(sess, "ratio")
    assert not any("ratio" in c.lower() for c in rest.columns)
    assert "Average loss" in rest.columns


# --------------------------------------------------------------------------- #
# reproduce-appendix.py (reference `reproduce-appendix.py:122-158`): grid
# submission against a stub Jobs — run-name tokens, exclusion logic, flag
# validity, and compatibility with reproduce.analyze's grouping.

def _load_appendix_module():
    import importlib.util
    import pathlib
    path = pathlib.Path(__file__).resolve().parent.parent / "reproduce-appendix.py"
    spec = importlib.util.spec_from_file_location("reproduce_appendix", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _StubJobs:
    def __init__(self):
        self.submitted = []

    def submit(self, name, command):
        self.submitted.append((name, command))


def test_appendix_grid_names_and_flags():
    """The appendix grid submits exactly the reference's 22 runs (2
    unattacked baselines + 8 f=4 runs with bulyan excluded + 12 f=2 runs),
    every name carries the lr_pow/at_*/nesterov tokens, and every command's
    flags parse through the real CLI."""
    from byzantinemomentum_tpu.cli.attack import process_commandline
    mod = _load_appendix_module()
    jobs = _StubJobs()
    mod.submit(jobs)
    names = [n for n, _ in jobs.submitted]
    assert len(names) == 22 and len(set(names)) == 22
    assert "cifar10-average-n_7-lr_pow-nesterov" in names
    assert "cifar10-average-n_9-lr_pow-nesterov" in names
    # Bulyan needs n >= 4f+3: excluded at f=4 (n=11), present at f=2
    assert not any("bulyan-f_4" in n for n in names)
    assert any("bulyan-f_2" in n for n in names)
    assert sum("-f_4-" in n for n in names) == 8
    assert sum("-f_2-" in n for n in names) == 12
    for name, command in jobs.submitted:
        if "average" in name:
            continue
        assert "-lr_pow-" in name and name.endswith("-nesterov")
        assert "-at_update-" in name or "-at_worker-" in name
        # Flags must be acceptable to the driver CLI (catches grid/CLI drift)
        args = process_commandline(command[2:])
        assert args.model == "wide_resnet-Wide_ResNet"
        assert args.nb_workers == 11
        assert args.nb_decl_byz == args.nb_real_byz
        assert args.learning_rate_schedule == "0.02,8000,0.004,16000,0.0008"
        assert args.momentum_nesterov is True
        assert (f"-at_{args.momentum_at}-" in name
                and f"-f_{args.nb_real_byz}-" in name
                and f"-{args.gar}-" in name and f"-{args.attack}-" in name)


def test_appendix_names_group_with_reproduce_analyze():
    """reproduce.analyze groups runs by config.json plus the lr NAME token
    and looks the unattacked baseline up by `_baseline_name`
    (reproduce.py:210-239); every attacked appendix run must resolve its
    baseline to one the appendix grid actually submitted."""
    import re
    import reproduce
    mod = _load_appendix_module()
    jobs = _StubJobs()
    mod.submit(jobs)
    names = [n for n, _ in jobs.submitted]
    baselines = {n for n in names if "average" in n}
    for name, command in jobs.submitted:
        if "average" in name:
            continue
        f = int(command[command.index("--nb-real-byz") + 1])
        lr = re.search(r"-lr_([^-]+)", name).group(1)
        assert lr == "pow"
        info = {"dataset": "cifar10", "lr": lr, "nesterov": True,
                "honests": 11 - f, "seed": "1"}
        base = reproduce._baseline_name(info)
        assert base.rsplit("-", 1)[0] in baselines, (name, base)


def test_tournament_scoreboard_heatmap(tmp_path):
    """The attack x GAR protection-ratio heatmap over a tournament
    scoreboard artifact (`study.tournament_scoreboard`)."""
    import json

    from byzantinemomentum_tpu import utils

    cells = []
    for gar in ("krum", "median"):
        for attack in ("alie", "framing"):
            for quarantine, err in ((True, 0.5), (False, 1.5)):
                cells.append({"gar": gar, "attack": attack,
                              "quarantine": quarantine,
                              "agg_err_last10": err})
    artifact = tmp_path / "TOURNAMENT_r99.json"
    artifact.write_text(json.dumps(
        {"kind": "tournament", "train_cells": cells}))
    matrix, attacks, gars, plot = study.tournament_scoreboard(artifact)
    try:
        assert attacks == ["alie", "framing"] and gars == ["krum", "median"]
        np.testing.assert_allclose(matrix, 3.0)  # off/on = 1.5/0.5
        out = tmp_path / "scoreboard.png"
        plot.save(out)
        assert out.stat().st_size > 0
    finally:
        plot.close()
    with pytest.raises(utils.UserException):
        study.tournament_scoreboard(tmp_path / "missing.json")
    bogus = tmp_path / "bogus.json"
    bogus.write_text("{}")
    with pytest.raises(utils.UserException):
        study.tournament_scoreboard(bogus)
