"""Engine algebra tests: the three momentum placements, Nesterov lookahead,
clipping, weight decay and the study metrics, all differentially checked
against a plain-numpy simulation of the reference's training loop
(reference `attack.py:752-882`).

Technique: a linear probe model whose per-worker gradient equals the mean of
its batch rows — `loss = <theta, mean(batch)>` — so every placement's
parameter trajectory is exactly predictable in float32 numpy.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from byzantinemomentum_tpu import losses, ops
from byzantinemomentum_tpu.engine import EngineConfig, build_engine
from byzantinemomentum_tpu.engine.state import init_state
from byzantinemomentum_tpu.models import ModelDef

D = 6


def probe_model():
    """Model whose gradient w.r.t. theta is exactly mean(batch rows)."""
    def init(key):
        return {"w": jnp.zeros((D,), jnp.float32)}, {}

    def apply(params, state, x, train=False, rng=None):
        return x, state

    return ModelDef("probe", init, apply, (D,))


def probe_loss():
    return losses.Loss(lambda output, target, params:
                       jnp.dot(params, jnp.mean(output, axis=0)))


def make_engine(**cfg_kwargs):
    cfg = EngineConfig(**cfg_kwargs)
    return cfg, build_engine(
        cfg=cfg, model_def=probe_model(), loss=probe_loss(),
        criterion=losses.Criterion("sigmoid"),
        defenses=[(ops.gars["average"], 1.0, {})])


def run_steps(engine, cfg, batches, lr, study=True):
    """batches: list per step of f32[S, B, D]."""
    state = engine.init(jax.random.PRNGKey(0), params={"w": jnp.zeros((D,))},
                        net_state={}, study=study)
    metrics = None
    for xs in batches:
        ys = jnp.zeros(xs.shape[:2], jnp.float32)
        state, metrics = engine.train_step(state, jnp.asarray(xs), ys,
                                           jnp.float32(lr))
    return state, metrics


def numpy_reference(batches, lr, *, momentum_at, mu=0.9, damp=0.1,
                    nesterov=False, clip=None, wd=0.0, h=None):
    """Plain-numpy transcription of the reference loop semantics
    (`attack.py:752-839`), average GAR, no attack."""
    S = batches[0].shape[0]
    h = S if h is None else h
    theta = np.zeros(D, np.float32)
    m_server = np.zeros(D, np.float32)
    m_workers = np.zeros((h, D), np.float32)
    for xs in batches:
        grads = xs.mean(axis=1)  # (S, D): gradient independent of theta
        if clip is not None:
            for i in range(S):
                n = np.linalg.norm(grads[i])
                if n > clip:
                    grads[i] = grads[i] * (clip / n)
        if momentum_at == "worker":
            m_workers = mu * m_workers + (1 - damp) * grads[:h]
            honest = m_workers
        elif momentum_at == "server":
            honest = (1 - damp) * grads[:h] + mu * m_server
        else:
            honest = grads[:h]
        d_agg = honest.mean(axis=0)
        if momentum_at == "worker":
            update = d_agg
        elif momentum_at == "server":
            m_server = d_agg
            update = d_agg
        else:
            m_server = mu * m_server + (1 - damp) * d_agg
            update = m_server
        theta = theta - lr * (update + wd * theta)
    return theta


@pytest.mark.parametrize("momentum_at", ["update", "server", "worker"])
@pytest.mark.parametrize("nesterov", [False, True])
def test_momentum_placements_match_reference_algebra(momentum_at, nesterov):
    rng = np.random.default_rng(3)
    batches = [rng.normal(size=(5, 4, D)).astype(np.float32) for _ in range(4)]
    cfg, engine = make_engine(
        nb_workers=5, nb_decl_byz=1, nb_real_byz=0, nb_for_study=0,
        momentum=0.9, dampening=0.1, momentum_at=momentum_at,
        nesterov=nesterov)
    state, _ = run_steps(engine, cfg, batches, 0.05, study=False)
    # The probe gradient is theta-independent, so Nesterov's lookahead must
    # not change the trajectory — both variants hit the same algebra.
    expected = numpy_reference(batches, 0.05, momentum_at=momentum_at)
    np.testing.assert_allclose(np.asarray(state.theta), expected,
                               rtol=1e-5, atol=1e-6)


def quad_loss():
    """Quadratic probe: loss = 0.5*||theta - mean(batch)||^2, so the
    gradient theta - mean(batch) DEPENDS on theta — Nesterov's lookahead
    measurably changes the trajectory (unlike the linear probe above)."""
    return losses.Loss(lambda output, target, params:
                       0.5 * jnp.sum((params - jnp.mean(output, axis=0)) ** 2))


def make_quad_engine(**cfg_kwargs):
    cfg = EngineConfig(**cfg_kwargs)
    return cfg, build_engine(
        cfg=cfg, model_def=probe_model(), loss=quad_loss(),
        criterion=losses.Criterion("sigmoid"),
        defenses=[(ops.gars["average"], 1.0, {})])


def numpy_reference_quad(batches, lr, *, momentum_at, mu=0.9, damp=0.1,
                         nesterov=False, h=None):
    """Numpy transcription of the reference loop for the quadratic probe,
    including the exact Nesterov lookahead: theta shifted by
    -momentum*lr*buffer before each backprop and restored after — per-worker
    buffers for worker placement, the server buffer otherwise (reference
    `attack.py:757-783`); study extras beyond the h worker buffers get zero
    lookahead (the engine's defined behavior where the reference would index
    out of bounds)."""
    S = batches[0].shape[0]
    h = S if h is None else h
    theta = np.zeros(D, np.float32)
    m_server = np.zeros(D, np.float32)
    m_workers = np.zeros((h, D), np.float32)
    for xs in batches:
        means = xs.mean(axis=1)  # (S, D)
        grads = np.empty((S, D), np.float32)
        for i in range(S):
            if not nesterov:
                lookahead = theta
            elif momentum_at == "worker":
                buf = m_workers[i] if i < h else np.zeros(D, np.float32)
                lookahead = theta - mu * lr * buf
            else:
                lookahead = theta - mu * lr * m_server
            grads[i] = lookahead - means[i]
        if momentum_at == "worker":
            m_workers = mu * m_workers + (1 - damp) * grads[:h]
            honest = m_workers
        elif momentum_at == "server":
            honest = (1 - damp) * grads[:h] + mu * m_server
        else:
            honest = grads[:h]
        d_agg = honest.mean(axis=0)
        if momentum_at == "worker":
            update = d_agg
        elif momentum_at == "server":
            m_server = d_agg
            update = d_agg
        else:
            m_server = mu * m_server + (1 - damp) * d_agg
            update = m_server
        theta = theta - lr * update
    return theta


@pytest.mark.parametrize("momentum_at", ["update", "server", "worker"])
@pytest.mark.parametrize("nesterov", [False, True])
def test_nesterov_lookahead_matches_reference_algebra(momentum_at, nesterov):
    """Theta-dependent probe: the lookahead path is discriminated from plain
    momentum (the trajectories provably differ), and each variant matches
    the reference's exact lookahead algebra (`attack.py:757-783`)."""
    rng = np.random.default_rng(13)
    batches = [rng.normal(size=(5, 4, D)).astype(np.float32) for _ in range(5)]
    cfg, engine = make_quad_engine(
        nb_workers=5, nb_decl_byz=1, nb_real_byz=0, nb_for_study=0,
        momentum=0.9, dampening=0.1, momentum_at=momentum_at,
        nesterov=nesterov)
    state, _ = run_steps(engine, cfg, batches, 0.3, study=False)
    expected = numpy_reference_quad(batches, 0.3, momentum_at=momentum_at,
                                    nesterov=nesterov)
    np.testing.assert_allclose(np.asarray(state.theta), expected,
                               rtol=1e-5, atol=1e-6)
    # The test can fail: flipping nesterov must move the trajectory
    other = numpy_reference_quad(batches, 0.3, momentum_at=momentum_at,
                                 nesterov=not nesterov)
    assert np.linalg.norm(expected - other) > 1e-4


def test_nesterov_worker_study_extras_zero_lookahead():
    """Worker placement with S > h study extras: the extras' gradients use
    zero lookahead while the h honest workers use their own buffers."""
    rng = np.random.default_rng(14)
    S, h = 6, 3
    batches = [rng.normal(size=(S, 2, D)).astype(np.float32)
               for _ in range(4)]
    cfg, engine = make_quad_engine(
        nb_workers=h, nb_decl_byz=1, nb_real_byz=0, nb_for_study=S,
        nb_for_study_past=1, momentum=0.9, dampening=0.0,
        momentum_at="worker", nesterov=True)
    assert cfg.nb_sampled == S and cfg.nb_honests == h
    state, _ = run_steps(engine, cfg, batches, 0.3)
    expected = numpy_reference_quad(batches, 0.3, momentum_at="worker",
                                    damp=0.0, nesterov=True, h=h)
    np.testing.assert_allclose(np.asarray(state.theta), expected,
                               rtol=1e-5, atol=1e-6)


def test_clipping_and_weight_decay():
    rng = np.random.default_rng(4)
    batches = [10.0 * rng.normal(size=(3, 2, D)).astype(np.float32)
               for _ in range(3)]
    cfg, engine = make_engine(
        nb_workers=3, nb_decl_byz=1, nb_real_byz=0, nb_for_study=0,
        momentum=0.5, dampening=0.0, momentum_at="update",
        gradient_clip=1.5, weight_decay=0.1)
    state, _ = run_steps(engine, cfg, batches, 0.1, study=False)
    expected = numpy_reference(batches, 0.1, momentum_at="update", mu=0.5,
                               damp=0.0, clip=1.5, wd=0.1)
    np.testing.assert_allclose(np.asarray(state.theta), expected,
                               rtol=1e-5, atol=1e-6)


def test_study_extras_do_not_train():
    """nb_for_study > nb_honests: extra gradients feed metrics only
    (reference `attack.py:764, 786`)."""
    rng = np.random.default_rng(5)
    S, h = 6, 3
    batches = [rng.normal(size=(S, 2, D)).astype(np.float32)
               for _ in range(2)]
    cfg, engine = make_engine(
        nb_workers=3, nb_decl_byz=1, nb_real_byz=0, nb_for_study=S,
        nb_for_study_past=2, momentum=0.9, dampening=0.0,
        momentum_at="update")
    assert cfg.nb_sampled == S
    state, metrics = run_steps(engine, cfg, batches, 0.05)
    expected = numpy_reference(batches, 0.05, momentum_at="update",
                               damp=0.0, h=h)
    np.testing.assert_allclose(np.asarray(state.theta), expected,
                               rtol=1e-5, atol=1e-6)
    # Sampled stats cover all S gradients, honest stats only the first h
    g = batches[-1].mean(axis=1)
    s_avg = g.mean(axis=0)
    h_avg = g[:h].mean(axis=0)
    np.testing.assert_allclose(float(metrics["Sampled gradient norm"]),
                               np.linalg.norm(s_avg), rtol=1e-5)
    np.testing.assert_allclose(float(metrics["Honest gradient norm"]),
                               np.linalg.norm(h_avg), rtol=1e-5)


def test_metrics_formulas_match_reference():
    """Deviation (sample std of L2 deviations), max coordinate, cosines and
    curvature (reference `tools/pytorch.py:97-125`, `attack.py:842-866`)."""
    rng = np.random.default_rng(6)
    mu = 0.9
    batches = [rng.normal(size=(4, 2, D)).astype(np.float32)
               for _ in range(3)]
    cfg, engine = make_engine(
        nb_workers=4, nb_decl_byz=1, nb_real_byz=0, nb_for_study=4,
        nb_for_study_past=2, momentum=mu, dampening=0.0, momentum_at="update")
    state, metrics = run_steps(engine, cfg, batches, 0.05)

    grads = [b.mean(axis=1) for b in batches]  # per-step (S, D)
    g = grads[-1]
    avg = g.mean(axis=0)
    na = np.linalg.norm(avg)
    dev = np.sqrt(sum(np.linalg.norm(gi - avg) ** 2 for gi in g) / (len(g) - 1))
    np.testing.assert_allclose(float(metrics["Sampled gradient deviation"]),
                               dev, rtol=1e-5)
    np.testing.assert_allclose(float(metrics["Sampled max coordinate"]),
                               np.abs(avg).max(), rtol=1e-5)
    # Defense = average of honest = the same avg here; cosine normalized by
    # the average-norms (reference quirk)
    np.testing.assert_allclose(float(metrics["Sampled-defense cosine"]),
                               np.dot(avg, avg) / na / na, rtol=1e-4)
    # Past ring: pasts are step-1 then step-0 averages ('appendleft' order)
    past = [grads[1].mean(axis=0), grads[0].mean(axis=0)]
    cos_prev = np.dot(avg, past[0]) / na / np.linalg.norm(past[0])
    np.testing.assert_allclose(float(metrics["Sampled-prev cosine"]),
                               cos_prev, rtol=1e-4)
    curv = mu * sum(mu ** i * np.dot(avg, p) for i, p in enumerate(past))
    np.testing.assert_allclose(float(metrics["Sampled composite curvature"]),
                               curv, rtol=1e-4)
    # Attack columns are NaN with f_real == 0
    assert np.isnan(float(metrics["Attack gradient norm"]))
    assert np.isnan(float(metrics["Honest-attack cosine"]))


def test_checkpoint_roundtrip(tmp_path):
    from byzantinemomentum_tpu import checkpoint as ck
    rng = np.random.default_rng(7)
    batches = [rng.normal(size=(3, 2, D)).astype(np.float32)]
    cfg, engine = make_engine(
        nb_workers=3, nb_decl_byz=1, nb_real_byz=0, nb_for_study=3,
        nb_for_study_past=2, momentum_at="worker")
    state, _ = run_steps(engine, cfg, batches, 0.1)
    path = ck.save(tmp_path / "checkpoint-1", state)
    template = engine.init(jax.random.PRNGKey(9))
    restored = ck.load(path, template)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_shape_mismatch_rejected(tmp_path):
    from byzantinemomentum_tpu import checkpoint as ck
    from byzantinemomentum_tpu import utils
    cfg, engine = make_engine(nb_workers=3, nb_decl_byz=1, nb_real_byz=0,
                              nb_for_study=0, momentum_at="update")
    state = engine.init(jax.random.PRNGKey(0))
    path = ck.save(tmp_path / "checkpoint-0", state)
    cfg2, engine2 = make_engine(nb_workers=3, nb_decl_byz=1, nb_real_byz=0,
                                nb_for_study=0, momentum_at="worker")
    template = engine2.init(jax.random.PRNGKey(0))
    with pytest.raises(utils.UserException):
        ck.load(path, template)


def test_gar_mixture_draws_all_branches():
    """A 50/50 average/median mixture must exercise both branches over many
    steps (reference `attack.py:467-517` random per-step draw)."""
    cfg = EngineConfig(nb_workers=3, nb_decl_byz=1, nb_real_byz=0,
                       nb_for_study=0, momentum=0.0, momentum_at="update")
    engine = build_engine(
        cfg=cfg, model_def=probe_model(), loss=probe_loss(),
        criterion=losses.Criterion("sigmoid"),
        defenses=[(ops.gars["average"], 1.0, {}),
                  (ops.gars["median"], 2.0, {})])
    # Asymmetric gradients: average != median, so the drawn branch is
    # observable from the parameter delta.
    xs = np.zeros((3, 1, D), np.float32)
    xs[0, 0, 0] = 3.0  # gradients per worker: e0*3, 0, 0
    state = engine.init(jax.random.PRNGKey(0))
    deltas = set()
    theta_prev = np.zeros(D, np.float32)
    for _ in range(30):
        state, _ = engine.train_step(state, jnp.asarray(xs),
                                     jnp.zeros((3, 1), jnp.float32),
                                     jnp.float32(1.0))
        th = np.asarray(state.theta)
        deltas.add(round(float(theta_prev[0] - th[0]), 6))
        theta_prev = th
    # average branch moves coord0 by 1.0, median branch by 0.0
    assert 1.0 in deltas and 0.0 in deltas


def test_gars_per_call_redraws_inside_line_search():
    """`--gars-per-call` (reference semantics, `attack.py:504-509`): every
    defense invocation re-draws the mixture GAR. The traceable mechanism is
    operand-derived entropy, so the distinct stacked matrices an adaptive
    attack's line-search probes present must produce independent draws that
    cover both mixture members, while identical operands draw identically
    (determinism under the step PRNG)."""
    from byzantinemomentum_tpu import attacks
    cfg = EngineConfig(nb_workers=7, nb_decl_byz=2, nb_real_byz=2,
                       nb_for_study=0, momentum=0.0, momentum_at="update",
                       gars_per_call=True)
    engine = build_engine(
        cfg=cfg, model_def=probe_model(), loss=probe_loss(),
        criterion=losses.Criterion("sigmoid"),
        defenses=[(ops.gars["average"], 1.0, {}),
                  (ops.gars["median"], 2.0, {})],
        attack=attacks.attacks["empire"], attack_kwargs={"factor": -8})
    rng = np.random.default_rng(0)
    key = jax.random.PRNGKey(7)
    G = jnp.asarray(rng.normal(size=(7, D)).astype(np.float32))
    # Emulate line-search probes: same honest rows, varying Byzantine factor
    probes = [jnp.concatenate([G, (1.0 + 0.25 * i) * G[:2]]) for i in range(16)]
    us = [float(engine._per_call_uniform(key, p)) for p in probes]
    idxs = {int(engine._mixture_index(jnp.float32(u))) for u in us}
    assert idxs == {0, 1}, f"line-search probes never re-drew: {us}"
    # Same operand, same draw (deterministic under the step key)
    assert (float(engine._per_call_uniform(key, G))
            == float(engine._per_call_uniform(key, G)))
    # E2E: a full step with the adaptive line-search attack compiles and
    # stays finite under per-call dispatch
    state = engine.init(jax.random.PRNGKey(0),
                        params={"w": jnp.zeros((D,))}, net_state={},
                        study=False)
    xs = jnp.asarray(rng.normal(size=(5, 4, D)).astype(np.float32))
    state, _ = engine.train_step(state, xs, jnp.zeros((5, 4), jnp.float32),
                                 jnp.float32(0.05))
    assert np.isfinite(np.asarray(state.theta)).all()


def test_per_call_mixture_draw_counts_one_step():
    """QUANTIFIES the per-call mixture semantics (VERDICT r3 weak #5): an
    adaptive attack probing the live defense 12 times with distinct operands
    inside ONE step draws both mixture members at roughly the configured
    frequency (the reference re-draws `random.random()` per call,
    `attack.py:504-509`), while two invocations on byte-identical operands
    draw the SAME member — the documented residual divergence of
    operand-derived entropy (`engine/step.py::_per_call_uniform`)."""
    from byzantinemomentum_tpu.attacks import Attack

    K = 12  # distinct probes

    def lo_gar(G, f=0, **kw):
        return jnp.mean(G, axis=0)

    def hi_gar(G, f=0, **kw):
        return jnp.mean(G, axis=0) + 1000.0

    def probe_attack(grad_honests, f_decl=0, f_real=0, defense=None, **kw):
        rows = [defense(gradients=grad_honests * (1.0 + 0.1 * i), f=f_decl)
                for i in range(K)]
        # Two invocations on byte-identical operands (the caveat under test)
        rows.append(defense(gradients=grad_honests, f=f_decl))
        rows.append(defense(gradients=grad_honests, f=f_decl))
        return jnp.stack(rows)

    cfg = EngineConfig(nb_workers=6 + K + 2, nb_decl_byz=1,
                       nb_real_byz=K + 2, nb_for_study=0, momentum=0.0,
                       momentum_at="update", gars_per_call=True)
    engine = build_engine(
        cfg=cfg, model_def=probe_model(), loss=probe_loss(),
        criterion=losses.Criterion("sigmoid"),
        defenses=[(ops.GAR("lo", lo_gar, lambda **kw: None), 1.0, {}),
                  (ops.GAR("hi", hi_gar, lambda **kw: None), 2.0, {})],
        attack=Attack("probe", probe_attack, lambda **kw: None))

    rng = np.random.default_rng(3)
    G_honest = jnp.asarray(rng.normal(size=(6, D)).astype(np.float32))
    G_attack, _, _, _, _, _ = engine._phase_defense(G_honest,
                                                    jax.random.PRNGKey(11))
    G_attack = np.asarray(G_attack)
    # Classify each invocation's draw by its distinguishable offset
    draws = []
    for i in range(K):
        expect_lo = np.asarray(jnp.mean(G_honest * (1.0 + 0.1 * i), axis=0))
        off = float(np.mean(G_attack[i] - expect_lo))
        assert abs(off) < 1.0 or abs(off - 1000.0) < 1.0
        draws.append(off > 500.0)
    n_hi = sum(draws)
    # Both members drawn; frequency near the configured 50/50 (12 draws,
    # p=.5: P(outside [2,10]) < 0.7%) — the per-call redraw is REAL, not a
    # single per-step draw replicated
    assert 2 <= n_hi <= 10, f"per-call draws degenerate: {draws}"
    # Identical operands: identical draw (the documented caveat — the
    # reference's impure random.random() would redraw here too)
    np.testing.assert_array_equal(G_attack[K], G_attack[K + 1])


def test_optimizer_registry_adam_roundtrip(tmp_path):
    """Adam via the optimizer registry: trains, and its moment buffers
    survive a checkpoint roundtrip."""
    from byzantinemomentum_tpu import checkpoint as ck
    from byzantinemomentum_tpu import optim
    rng = np.random.default_rng(8)
    batches = [rng.normal(size=(3, 2, D)).astype(np.float32)
               for _ in range(2)]
    cfg = EngineConfig(nb_workers=3, nb_decl_byz=1, nb_real_byz=0,
                       nb_for_study=0, momentum=0.0, momentum_at="update")
    engine = build_engine(
        cfg=cfg, model_def=probe_model(), loss=probe_loss(),
        criterion=losses.Criterion("sigmoid"),
        defenses=[(ops.gars["average"], 1.0, {})],
        optimizer=optim.build("adam"))
    state, _ = run_steps(engine, cfg, batches, 0.05, study=False)
    assert jax.tree.leaves(state.opt_state)  # adam moments exist
    path = ck.save(tmp_path / "checkpoint-adam", state)
    restored = ck.load(path, engine.init(jax.random.PRNGKey(0)))
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_optimizer_sgd_matches_manual_update():
    """The default optimizer reproduces theta -= lr*(g + wd*theta) exactly
    (torch-SGD semantics, reference attack.py:543-545)."""
    from byzantinemomentum_tpu import optim
    opt = optim.build("sgd", weight_decay=0.1)
    theta = jnp.arange(4, dtype=jnp.float32)
    grad = jnp.ones(4, jnp.float32)
    new, st = opt.update(grad, opt.init(theta), theta, 0.5)
    np.testing.assert_allclose(np.asarray(new),
                               np.asarray(theta - 0.5 * (grad + 0.1 * theta)))


def test_optimizer_tail_registered_and_descends():
    """The optimizer registry tail (adamax/adadelta/radam/amsgrad — the
    reference name-resolves every torch.optim subclass, reference
    `experiments/optimizer.py:32-51`): each builds, takes finite steps, and
    reduces a simple quadratic."""
    from byzantinemomentum_tpu import optim
    for name in ("adamax", "adadelta", "radam", "amsgrad"):
        opt = optim.build(name)
        theta = jnp.asarray([3.0, -2.0, 1.0, 0.5], jnp.float32)
        st = opt.init(theta)
        loss0 = float(jnp.sum(theta * theta))
        for _ in range(60):
            theta, st = opt.update(2.0 * theta, st, theta, 0.05)
        assert np.isfinite(np.asarray(theta)).all(), name
        # Adadelta's unit-fixing accumulator makes its first steps ~sqrt(eps)
        # (that IS torch's adadelta too): require monotone progress only
        bar = 0.999 if name == "adadelta" else 0.5
        assert float(jnp.sum(theta * theta)) < loss0 * bar, name
