"""Device-resident input path: the in-graph gather must reproduce the host
transform exactly (same indices + flip decisions -> same batch)."""

import numpy as np
import jax.numpy as jnp
import pytest

from byzantinemomentum_tpu import data
from byzantinemomentum_tpu.data.device import DeviceData


@pytest.fixture(autouse=True)
def small_synth(monkeypatch):
    monkeypatch.setenv("BMT_SYNTH_TRAIN", "256")
    monkeypatch.setenv("BMT_SYNTH_TEST", "64")


@pytest.mark.parametrize("name", ["mnist", "cifar10", "phishing"])
def test_gather_matches_host_transform(name):
    trainset, _ = data.make_datasets(name, 16, 16, seed=3)
    dd = DeviceData(trainset)
    idx = trainset.sample_indices()
    flips = trainset.sample_flips()
    x_dev, y_dev = dd.gather(jnp.asarray(idx.astype(np.int32)),
                             jnp.asarray(flips))
    # Host reference: same indices, same flip mask, same normalization
    x_host = trainset._inputs[idx]
    transform = trainset._transform
    if transform is not None:
        x_host = x_host.astype(np.float32) / 255.0
        if transform.flip:
            x_host[flips] = x_host[flips, :, ::-1, :]
        if transform.norm is not None:
            mean = np.asarray(transform.norm[0], np.float32)
            std = np.asarray(transform.norm[1], np.float32)
            x_host = (x_host - mean) / std
    np.testing.assert_allclose(np.asarray(x_dev), x_host, rtol=1e-6,
                               atol=1e-6)
    np.testing.assert_array_equal(np.asarray(y_dev), trainset._labels[idx])


def test_gather_multi_batch_shapes():
    trainset, _ = data.make_datasets("cifar10", 8, 8, seed=1)
    dd = DeviceData(trainset)
    idx, flips = dd.sample_indices(6)
    x, y = dd.gather(jnp.asarray(idx), jnp.asarray(flips))
    assert x.shape == (6, 8, 32, 32, 3)
    assert y.shape == (6, 8)
    # Local-steps layout (S, k, B)
    x2, y2 = dd.gather(jnp.asarray(idx.reshape(3, 2, 8)),
                       jnp.asarray(flips.reshape(3, 2, 8)))
    assert x2.shape == (3, 2, 8, 32, 32, 3)


def test_supports_detection():
    trainset, _ = data.make_datasets("mnist", 8, 8)
    assert DeviceData.supports(trainset)
    custom = data.Dataset(np.zeros((10, 4), np.float32),
                          np.zeros((10,), np.int32), 2, train=True,
                          transform=lambda x, rng: x)
    assert not DeviceData.supports(custom)
