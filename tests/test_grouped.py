"""Grouped (merged-batch) honest phase: equivalence with the vmapped path.

The grouped execution (`models/core.py` grouped helpers,
`engine/step.py:_workers_grad_grouped`) is a pure re-expression of
`vmap(apply)` — per-worker BN batch statistics and the per-worker-key
dropout draws are bit-identical by construction, so entire training
trajectories must agree to float tolerance.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from byzantinemomentum_tpu import attacks, losses, models, ops
from byzantinemomentum_tpu.engine import EngineConfig, build_engine


def stacked(params, S):
    return jax.tree.map(lambda p: jnp.broadcast_to(p, (S,) + p.shape), params)


@pytest.mark.parametrize("name,shape", [
    pytest.param("empire-cnn", (32, 32, 3), marks=pytest.mark.slow),
    ("simples-conv", (28, 28, 1)),
    ("simples-full", (28, 28, 1)),
    ("simples-logit", (68,)),
    ("simples-linear", (68,)),
])
def test_apply_grouped_matches_vmap(name, shape):
    S, B = 3, 4
    model = models.build(name)
    params, state = model.init(jax.random.PRNGKey(0))
    xs = jax.random.normal(jax.random.PRNGKey(1), (S, B) + shape, jnp.float32)
    keys = jax.random.split(jax.random.PRNGKey(2), S)

    out_v, ns_v = jax.vmap(
        lambda x, k: model.apply(params, state, x, train=True, rng=k))(
            xs, keys)
    out_g, ns_g = model.apply_grouped(
        stacked(params, S), state, xs, train=True, rng=keys)

    np.testing.assert_allclose(np.asarray(out_g, np.float32),
                               np.asarray(out_v, np.float32),
                               rtol=2e-5, atol=2e-5)
    for a, b in zip(jax.tree.leaves(ns_g), jax.tree.leaves(ns_v)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_apply_grouped_matches_vmap_wrn():
    """Tiny WRN (depth 10, widen 2): blocks with strided + shortcut convs,
    BN everywhere, per-block dropout."""
    S, B = 2, 3
    model = models.build("wide_resnet-Wide_ResNet", depth=10, widen_factor=2,
                         dropout_rate=0.3)
    params, state = model.init(jax.random.PRNGKey(0))
    xs = jax.random.normal(jax.random.PRNGKey(1), (S, B, 32, 32, 3))
    keys = jax.random.split(jax.random.PRNGKey(2), S)
    out_v, ns_v = jax.vmap(
        lambda x, k: model.apply(params, state, x, train=True, rng=k))(
            xs, keys)
    out_g, ns_g = model.apply_grouped(stacked(params, S), state, xs,
                                      train=True, rng=keys)
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_v),
                               rtol=2e-4, atol=2e-4)
    for a, b in zip(jax.tree.leaves(ns_g), jax.tree.leaves(ns_v)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_apply_grouped_eval_mode_uses_running_stats():
    S, B = 2, 3
    model = models.build("empire-cnn")
    params, state = model.init(jax.random.PRNGKey(0))
    # Perturb the running stats away from init so eval actually reads them
    state = jax.tree.map(lambda x: x + 0.25, state)
    xs = jax.random.normal(jax.random.PRNGKey(1), (S, B, 32, 32, 3))
    out_v, _ = jax.vmap(
        lambda x: model.apply(params, state, x, train=False,
                              rng=jax.random.PRNGKey(0)))(xs)
    out_g, ns_g = model.apply_grouped(stacked(params, S), state, xs,
                                      train=False)
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_v),
                               rtol=2e-5, atol=2e-5)
    # Eval must not touch the running stats
    for a, b in zip(jax.tree.leaves(ns_g), jax.tree.leaves(state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def _build(grouped, momentum_at="update", nesterov=False):
    cfg = EngineConfig(
        nb_workers=5, nb_decl_byz=1, nb_real_byz=1,
        nb_for_study=4, nb_for_study_past=2,
        momentum=0.9, momentum_at=momentum_at, nesterov=nesterov,
        gradient_clip=2.0, grouped_workers=grouped)
    engine = build_engine(
        cfg=cfg, model_def=models.build("empire-cnn"),
        loss=losses.Loss("nll"), criterion=losses.Criterion("top-k"),
        defenses=[(ops.gars["median"], 1.0, {})],
        attack=attacks.attacks["empire"], attack_kwargs={"factor": 1.1})
    return cfg, engine


@pytest.mark.slow
@pytest.mark.parametrize("momentum_at,nesterov",
                         [("update", False), ("worker", True)])
def test_engine_trajectory_grouped_vs_vmap(momentum_at, nesterov):
    """Whole-step trajectories (theta, BN state, study metrics) agree
    between the grouped and vmapped phases — same PRNG stream, so the
    dropout masks and attack/defense inputs are identical."""
    cfg_g, eng_g = _build(True, momentum_at, nesterov)
    cfg_v, eng_v = _build(False, momentum_at, nesterov)
    assert eng_g.model_def.apply_grouped is not None

    S, B = cfg_g.nb_sampled, 3
    key = jax.random.PRNGKey(3)
    state_g = eng_g.init(jax.random.PRNGKey(0))
    state_v = eng_v.init(jax.random.PRNGKey(0))

    for step in range(2):
        xs = jax.random.normal(jax.random.fold_in(key, step),
                               (S, B, 32, 32, 3), jnp.float32)
        ys = jax.random.randint(jax.random.fold_in(key, 100 + step),
                                (S, B), 0, 10)
        state_g, met_g = eng_g.train_step(state_g, xs, ys, jnp.float32(0.05))
        state_v, met_v = eng_v.train_step(state_v, xs, ys, jnp.float32(0.05))

    # Two steps of conv backward accumulate different summation orders
    # (grouped conv vs vmap's batch-group conv): pure float noise, bounded
    # in absolute terms but large relatively on near-zero coordinates
    np.testing.assert_allclose(np.asarray(state_g.theta),
                               np.asarray(state_v.theta),
                               rtol=1e-3, atol=2e-4)
    for a, b in zip(jax.tree.leaves(state_g.net_state),
                    jax.tree.leaves(state_v.net_state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)
    for name in ("Average loss", "Defense gradient norm",
                 "Attack acceptation ratio"):
        np.testing.assert_allclose(np.asarray(met_g[name]),
                                   np.asarray(met_v[name]),
                                   rtol=5e-4, atol=5e-5)


@pytest.mark.slow
def test_grouped_respects_config_off():
    """grouped_workers=False traces the vmapped phase even when the model
    provides apply_grouped (the --no-grouped-workers escape hatch)."""
    from byzantinemomentum_tpu.engine import step as step_mod

    calls = []
    cfg, engine = _build(False)
    orig = engine._workers_grad_grouped
    engine._workers_grad_grouped = (
        lambda *a, **k: calls.append(1) or orig(*a, **k))
    state = engine.init(jax.random.PRNGKey(0))
    xs = jnp.zeros((cfg.nb_sampled, 2, 32, 32, 3), jnp.float32)
    ys = jnp.zeros((cfg.nb_sampled, 2), jnp.int32)
    engine.train_step(state, xs, ys, jnp.float32(0.01))
    assert not calls

    # And the module-level context disables it for a grouped-enabled engine
    # THROUGH THE JITTED PUBLIC ENTRY: the mode is a static jit argument
    # read at call time, so leaving the context retraces with the grouped
    # phase back on instead of reusing the disabled trace (ADVICE r3)
    cfg2, engine2 = _build(True)
    orig2 = engine2._workers_grad_grouped
    engine2._workers_grad_grouped = (
        lambda *a, **k: calls.append(1) or orig2(*a, **k))
    state2 = engine2.init(jax.random.PRNGKey(0))
    with step_mod.grouped_disabled():
        engine2.train_step(state2, xs, ys, jnp.float32(0.01))
    assert not calls
    state2b = engine2.init(jax.random.PRNGKey(0))
    engine2.train_step(state2b, xs, ys, jnp.float32(0.01))
    assert calls


def test_apply_grouped_matches_vmap_when_packing_engages():
    """Worker packing within the P <= 4 cap (empire-cnn's C=64 packs at
    P=2 for even S): the packed grouped path must still match vmap exactly
    — in particular the flatten stages must unpack before building
    per-worker rows (a missing unpack reshapes other workers' channels
    into the fc input with NO shape error)."""
    from byzantinemomentum_tpu.models.core import _MAX_WORKER_PACK, _worker_packing
    S, B = 4, 2
    assert _worker_packing(S, 64) == 2  # the scenario actually packs
    # Lane-aligning C=50 would need P=64 — past the cap, so packing (and
    # its zero-block FLOP blowup) must NOT silently auto-engage there
    assert _worker_packing(64, 50) == 1
    assert _worker_packing(8 * _MAX_WORKER_PACK, 32) == _MAX_WORKER_PACK
    model = models.build("empire-cnn")
    params, state = model.init(jax.random.PRNGKey(0))
    xs = jax.random.normal(jax.random.PRNGKey(1), (S, B, 32, 32, 3),
                           jnp.float32)
    keys = jax.random.split(jax.random.PRNGKey(2), S)
    out_v, _ = jax.vmap(
        lambda x, k: model.apply(params, state, x, train=True, rng=k))(
            xs, keys)
    out_g, _ = model.apply_grouped(
        stacked(params, S), state, xs, train=True, rng=keys)
    np.testing.assert_allclose(np.asarray(out_g, np.float32),
                               np.asarray(out_v, np.float32),
                               rtol=2e-5, atol=2e-5)


# --------------------------------------------------------------------------- #
# WRN packing escapes (PR 7): batch-slot packing (`BMT_BATCH_PACK`,
# models/core.py) and engine-level worker padding (`BMT_WORKER_PAD`,
# engine/step.py) — the two ROADMAP escapes for worker counts that admit
# no packing P (WRN's S = 9).


def test_batch_packing_gate():
    """`_batch_packing` is opt-in, never composes with worker packing,
    and honors the cap / divisibility like `_worker_packing`."""
    from byzantinemomentum_tpu.models.core import _batch_packing

    assert _batch_packing(20, 9, 160) == 1  # off by default (env unset)
    os.environ["BMT_BATCH_PACK"] = "1"
    try:
        assert _batch_packing(20, 9, 160) == 4   # 4*160 = 640, 20 % 4 == 0
        assert _batch_packing(20, 9, 320) == 2   # 2*320 = 640
        assert _batch_packing(20, 9, 640) == 1   # already lane-aligned
        assert _batch_packing(20, 4, 64) == 1    # worker packing wins (P=2)
        assert _batch_packing(6, 9, 160) == 1    # no Q <= 4 divides 6 works
        os.environ["BMT_BATCH_PACK"] = "2"       # forced Q
        assert _batch_packing(20, 9, 320) == 2
        assert _batch_packing(20, 9, 160) == 1   # 2*160 misaligned: refuse
    finally:
        os.environ.pop("BMT_BATCH_PACK", None)


def test_batch_slot_packing_matches_vmap(monkeypatch):
    """Tiny WRN with `BMT_BATCH_PACK=1`: C=32 packs at Q=4 and C=64 at
    Q=2 (with a 4 -> 2 repack transition), dropout draws the vmapped
    path's exact masks, BN folds statistics across the slots — forward,
    BN states and parameter gradients all match the unpacked path to
    reduction rounding."""
    S, B = 3, 8
    model = models.build("wide_resnet-Wide_ResNet", depth=10, widen_factor=1,
                         dropout_rate=0.25)
    params, state = model.init(jax.random.PRNGKey(0))
    params_s = stacked(params, S)
    xs = jax.random.normal(jax.random.PRNGKey(1), (S, B, 32, 32, 3))
    keys = jax.random.split(jax.random.PRNGKey(2), S)

    out_v, ns_v = jax.vmap(
        lambda x, k: model.apply(params, state, x, train=True, rng=k))(
            xs, keys)

    def grad_fn(ps):
        out, _ = model.apply_grouped(ps, state, xs, train=True, rng=keys)
        return jnp.sum(out * 0.01)

    g_plain = jax.grad(grad_fn)(params_s)
    monkeypatch.setenv("BMT_BATCH_PACK", "1")
    out_g, ns_g = model.apply_grouped(params_s, state, xs, train=True,
                                      rng=keys)
    g_packed = jax.grad(grad_fn)(params_s)

    np.testing.assert_allclose(np.asarray(out_g, np.float32),
                               np.asarray(out_v, np.float32),
                               rtol=2e-5, atol=2e-5)
    for a, b in zip(jax.tree.leaves(ns_g), jax.tree.leaves(ns_v)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree.leaves(g_packed), jax.tree.leaves(g_plain)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-5)


def test_worker_pad_rows_parsing(monkeypatch):
    from byzantinemomentum_tpu.engine.step import _worker_pad_rows

    monkeypatch.delenv("BMT_WORKER_PAD", raising=False)
    assert _worker_pad_rows(9) == 0
    monkeypatch.setenv("BMT_WORKER_PAD", "12")
    assert _worker_pad_rows(9) == 3
    assert _worker_pad_rows(12) == 0     # already there
    assert _worker_pad_rows(20) == 0     # target below S: no-op
    monkeypatch.setenv("BMT_WORKER_PAD", "99")
    assert _worker_pad_rows(9) == 9      # clamped to 2S
    monkeypatch.setenv("BMT_WORKER_PAD", "not-a-number")
    assert _worker_pad_rows(9) == 0


@pytest.mark.slow
def test_worker_pad_trajectory_matches(monkeypatch):
    """`BMT_WORKER_PAD=12` on a WRN-shaped cell (S = 9): the padded
    grouped phase engages P = 4/2 worker packing on the dummy-extended
    stack, and the kept rows' trajectory matches the unpadded run to
    packing-reduction rounding (no dummy-row value feeds a kept row)."""
    def build():
        cfg = EngineConfig(
            nb_workers=11, nb_decl_byz=2, nb_real_byz=2,
            nb_for_study=1, nb_for_study_past=1,
            momentum=0.9, momentum_at="update", nesterov=True,
            gradient_clip=5.0)
        model = models.build("wide_resnet-Wide_ResNet", depth=10,
                             widen_factor=1, dropout_rate=0.3)
        engine = build_engine(
            cfg=cfg, model_def=model, loss=losses.Loss("crossentropy"),
            criterion=losses.Criterion("top-k"),
            defenses=[(ops.gars["bulyan"], 1.0, {})],
            attack=attacks.attacks["empire"], attack_kwargs={"factor": 1.1})
        return cfg, engine

    monkeypatch.delenv("BMT_WORKER_PAD", raising=False)
    cfg, eng0 = build()
    S = cfg.nb_sampled
    assert S == 9
    xs = jax.random.normal(jax.random.PRNGKey(5), (S, 4, 32, 32, 3))
    ys = jax.random.randint(jax.random.PRNGKey(6), (S, 4), 0, 10)
    st0, met0 = eng0.train_step(eng0.init(jax.random.PRNGKey(0)), xs, ys,
                                jnp.float32(0.05))

    monkeypatch.setenv("BMT_WORKER_PAD", "12")
    _, eng1 = build()
    st1, met1 = eng1.train_step(eng1.init(jax.random.PRNGKey(0)), xs, ys,
                                jnp.float32(0.05))

    np.testing.assert_allclose(np.asarray(st0.theta), np.asarray(st1.theta),
                               rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree.leaves(st0.net_state),
                    jax.tree.leaves(st1.net_state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    for name in ("Average loss", "Defense gradient norm"):
        np.testing.assert_allclose(np.asarray(met0[name]),
                                   np.asarray(met1[name]),
                                   rtol=1e-4, atol=1e-5)
