"""Fleet metrics plane (PR 18): streaming histograms, pull-based
exposition, and SLO burn-rate alerting.

Covers the ISSUE 18 acceptance surface: bucket-wise histogram merging is
associative/commutative and an N-shard merge reports BIT-IDENTICAL
quantiles to the single-process oracle; counters stay monotonic under a
live scrape race; the on-disk `metrics.jsonl` ring tolerates a torn tail
and stays bounded under rotation; the burn-rate evaluator fires
`slo_burn` within one slow window of a planted error burst and stays
silent over a 300-snapshot clean stream; the batcher's queue-depth gauge
edge stream folds to the same distribution as its
`serve_queue_depth_dist` histogram (the identical-edge contract); the
quarantine-threshold calibration (`scripts/quarantine_rates.py`) and its
`resolve_anomaly_polls` precedence ladder; and the `bench_compare`
metrics-overhead gate.
"""

import importlib.util
import json
import pathlib
import sys
import threading

import numpy as np
import pytest

from byzantinemomentum_tpu import obs
from byzantinemomentum_tpu.cluster.straggler import (DEFAULT_ANOMALY_POLLS,
                                                     resolve_anomaly_polls)
from byzantinemomentum_tpu.obs.health import HealthMonitor
from byzantinemomentum_tpu.obs.metrics import (DEPTH_BOUNDS,
                                               LATENCY_MS_BOUNDS,
                                               BurnRateEvaluator, Histogram,
                                               MetricsEndpoint,
                                               MetricsRegistry,
                                               MetricsScraper, NullRegistry,
                                               SLO, append_snapshot,
                                               load_snapshots,
                                               merge_payloads,
                                               quantile_from_buckets,
                                               scrape_target)
from byzantinemomentum_tpu.serve.batching import MicroBatcher, ServeRequest

_SCRIPTS = pathlib.Path(__file__).resolve().parent.parent / "scripts"


def _load_script(name):
    spec = importlib.util.spec_from_file_location(name,
                                                  _SCRIPTS / f"{name}.py")
    module = importlib.util.module_from_spec(spec)
    sys.modules.setdefault(name, module)
    spec.loader.exec_module(module)
    return module


bench_compare = _load_script("bench_compare")
quarantine_rates = _load_script("quarantine_rates")


# --------------------------------------------------------------------------- #
# Registry primitives


def test_counter_monotonic_and_rejects_negative():
    reg = MetricsRegistry()
    c = reg.counter("requests")
    assert c.inc() == 1
    assert c.inc(41) == 42
    with pytest.raises(ValueError):
        c.inc(-1)
    assert c.value == 42
    # Idempotent get-or-create: same object, same running total
    assert reg.counter("requests") is c


def test_registry_type_collisions_raise():
    reg = MetricsRegistry()
    reg.counter("depth")
    with pytest.raises(TypeError):
        reg.gauge("depth")
    reg.histogram("lat", bounds=LATENCY_MS_BOUNDS)
    with pytest.raises(ValueError):
        reg.histogram("lat", bounds=DEPTH_BOUNDS)  # different ladder
    with pytest.raises(ValueError):
        Histogram("bad", bounds=(1.0, 1.0, 2.0))   # non-increasing


def test_histogram_quantiles_nearest_rank():
    h = Histogram("lat", bounds=(1.0, 2.0, 4.0))
    assert h.quantile(0.5) is None                 # empty
    for v in (0.5, 1.5, 1.5, 3.0):
        h.observe(v)
    # ranks over cumulative counts resolve to bucket upper bounds
    assert h.quantile(0.25) == 1.0
    assert h.quantile(0.75) == 2.0
    assert h.quantile(1.0) == 4.0
    h.observe(99.0)                                # overflow bucket
    assert h.quantile(1.0) == 99.0                 # resolves to tracked max
    assert h.count == 5


def test_null_registry_is_inert():
    reg = NullRegistry(source="off")
    assert reg.enabled is False
    reg.counter("c").inc(5)
    reg.gauge("g").set(3.0)
    reg.histogram("h").observe(1.0)
    assert reg.counter("c").value == 0
    assert reg.histogram("h").quantile(0.5) is None
    dump = reg.dump()
    assert dump["metrics"] == {} and dump["source"] == "off"


# --------------------------------------------------------------------------- #
# Merging: associativity, commutativity, N-shard parity


def _sharded(samples, shards):
    """An oracle registry that saw every sample, plus `shards` registries
    that split them round-robin."""
    oracle = MetricsRegistry(source="oracle")
    parts = [MetricsRegistry(source=f"shard-{i}") for i in range(shards)]
    for i, value in enumerate(samples):
        oracle.histogram("lat").observe(value)
        oracle.counter("requests").inc()
        parts[i % shards].histogram("lat").observe(value)
        parts[i % shards].counter("requests").inc()
    return oracle, parts


def test_nshard_merge_matches_single_process_oracle_bitwise():
    rng = np.random.default_rng(7)
    samples = np.exp(rng.normal(1.5, 1.2, size=2000)).tolist()
    oracle, parts = _sharded(samples, shards=5)
    merged = merge_payloads([p.dump() for p in parts])
    want = oracle.dump()["metrics"]["lat"]
    got = merged["metrics"]["lat"]
    assert got["counts"] == want["counts"]
    assert got["count"] == want["count"] == len(samples)
    assert got["min"] == want["min"] and got["max"] == want["max"]
    for q in (0.5, 0.9, 0.99, 1.0):
        assert quantile_from_buckets(
            tuple(got["bounds"]), got["counts"], q, got["max"]
        ) == quantile_from_buckets(
            tuple(want["bounds"]), want["counts"], q, want["max"])
    assert merged["metrics"]["requests"]["value"] == len(samples)
    assert merged["sources"] == [f"shard-{i}" for i in range(5)]


def test_merge_associative_and_commutative():
    rng = np.random.default_rng(3)
    _, parts = _sharded(rng.uniform(0.0, 50.0, size=300).tolist(), 3)
    a, b, c = (p.dump() for p in parts)
    left = merge_payloads([merge_payloads([a, b]), c])
    right = merge_payloads([a, merge_payloads([b, c])])
    shuffled = merge_payloads([c, a, b])
    for other in (right, shuffled):
        assert left["metrics"] == other["metrics"]


def test_merge_refuses_schema_ladder_and_type_drift():
    good = MetricsRegistry().dump()
    bad_schema = dict(good, schema=99)
    with pytest.raises(ValueError):
        merge_payloads([good, bad_schema])
    with pytest.raises(ValueError):
        merge_payloads([{"kind": "not-metrics"}])

    r1, r2 = MetricsRegistry(), MetricsRegistry()
    r1.histogram("lat", bounds=(1.0, 2.0))
    r2.histogram("lat", bounds=(1.0, 4.0))
    with pytest.raises(ValueError):
        merge_payloads([r1.dump(), r2.dump()])

    r3, r4 = MetricsRegistry(), MetricsRegistry()
    r3.counter("x")
    r4.gauge("x")
    with pytest.raises(ValueError):
        merge_payloads([r3.dump(), r4.dump()])


def test_counter_monotonic_under_scrape_race():
    """Writers bump while a reader dumps: every successive exposition
    value is non-decreasing and the final dump sees every increment."""
    reg = MetricsRegistry()
    counter = reg.counter("requests")
    seen = []
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            seen.append(reg.dump()["metrics"]["requests"]["value"])

    def writer():
        for _ in range(5000):
            counter.inc()

    reader = threading.Thread(target=scraper)
    writers = [threading.Thread(target=writer) for _ in range(4)]
    reader.start()
    for t in writers:
        t.start()
    for t in writers:
        t.join()
    stop.set()
    reader.join()
    seen.append(reg.dump()["metrics"]["requests"]["value"])
    assert seen == sorted(seen)            # monotone exposition
    assert seen[-1] == 4 * 5000            # nothing torn, nothing lost


# --------------------------------------------------------------------------- #
# The on-disk ring + the scrape loop


def test_load_snapshots_skips_torn_tail(tmp_path):
    append_snapshot(tmp_path, {"t": 1.0, "kind": "metrics_snapshot"})
    append_snapshot(tmp_path, {"t": 2.0, "kind": "metrics_snapshot"})
    path = tmp_path / "metrics.jsonl"
    with path.open("a", encoding="utf-8") as fd:
        fd.write('{"t": 3.0, "kind": "metr')     # SIGKILL mid-append
    snapshots = load_snapshots(tmp_path)
    assert [s["t"] for s in snapshots] == [1.0, 2.0]
    assert load_snapshots(tmp_path / "absent") == []


def test_ring_rotation_keeps_newest_half(tmp_path):
    for i in range(25):
        append_snapshot(tmp_path, {"t": float(i)}, max_lines=20)
    snapshots = load_snapshots(tmp_path)
    assert len(snapshots) <= 20
    # Rotation kept the NEWEST half and appends continued after it
    assert snapshots[-1]["t"] == 24.0
    assert [s["t"] for s in snapshots] == sorted(s["t"] for s in snapshots)


def test_endpoint_scrape_and_dead_target_gap(tmp_path):
    reg = MetricsRegistry(source="svc")
    reg.counter("serve_requests").inc(10)
    endpoint = MetricsEndpoint(("127.0.0.1", 0), reg.dump)
    endpoint.serve_background()
    try:
        assert scrape_target("127.0.0.1", endpoint.port) == reg.dump()
        scraper = MetricsScraper(
            {"svc": ("127.0.0.1", endpoint.port),
             "dead": ("127.0.0.1", 1)},           # nothing listens there
            tmp_path, timeout=0.5)
        snapshot = scraper.scrape_once(now=100.0)
    finally:
        endpoint.shutdown()
        endpoint.server_close()
    assert snapshot["reached"] == ["svc"]
    assert snapshot["missed"] == ["dead"]         # a gap, not an error
    merged = snapshot["merged"]["metrics"]
    assert merged["serve_requests"]["value"] == 10
    assert load_snapshots(tmp_path)[-1]["t"] == 100.0


# --------------------------------------------------------------------------- #
# SLO burn-rate alerting


def _snapshot(t, total, bad):
    reg = MetricsRegistry()
    reg.counter("serve_requests").inc(total)
    reg.counter("serve_rejected").inc(bad)
    return {"t": float(t), "kind": "metrics_snapshot",
            "merged": reg.dump()}


_AVAIL = SLO("avail", kind="availability", objective=0.999,
             total="serve_requests", bad=("serve_rejected",),
             fast_s=30.0, slow_s=300.0, burn_threshold=10.0)


def test_planted_burst_fires_within_one_slow_window():
    """100% errors burn the 0.1% budget at rate 1000 >> 10: the alert
    must rise before one slow window of bad traffic has elapsed."""
    evaluator = BurnRateEvaluator([_AVAIL])
    events, fired_at = [], None
    total = bad = 0
    for i in range(120):                  # 10 s cadence, 20 min stream
        t = 10.0 * i
        total += 100
        if t >= 600.0:                    # burst starts at t=600
            bad += 100
        for event in evaluator.observe(_snapshot(t, total, bad)):
            events.append(event)
            if event["event"] == "slo_burn" and fired_at is None:
                fired_at = t
    assert fired_at is not None
    assert fired_at - 600.0 <= _AVAIL.slow_s       # within one slow window
    assert evaluator.burn_events == 1              # edge, not a level


def test_clean_stream_fires_nothing():
    evaluator = BurnRateEvaluator([_AVAIL])
    events = []
    total = 0
    for i in range(300):
        total += 50
        events.extend(evaluator.observe(_snapshot(2.0 * i, total, 0)))
    assert events == []
    assert evaluator.burn_events == 0 and evaluator.ok_events == 0
    summary = evaluator.summary()
    row = summary["slos"][0]
    assert row["alerting"] is False and row["burn_slow"] == 0.0


def test_burst_then_recovery_emits_slo_ok():
    evaluator = BurnRateEvaluator([_AVAIL])
    names = []
    total = bad = 0
    for i in range(200):
        t = 10.0 * i
        total += 100
        if 300.0 <= t < 700.0:
            bad += 100
        names.extend(e["event"]
                     for e in evaluator.observe(_snapshot(t, total, bad)))
    assert names.count("slo_burn") == 1
    assert names.count("slo_ok") == 1
    assert names.index("slo_burn") < names.index("slo_ok")


def test_latency_slo_counts_buckets_above_threshold():
    slo = SLO("lat", kind="latency", objective=0.9,
              total="serve_request_ms", threshold_ms=10.0,
              fast_s=30.0, slow_s=60.0, burn_threshold=5.0)
    evaluator = BurnRateEvaluator([slo])

    def snap(t, fast_n, slow_n):
        reg = MetricsRegistry()
        h = reg.histogram("serve_request_ms")
        for _ in range(fast_n):
            h.observe(1.0)
        for _ in range(slow_n):
            h.observe(400.0)              # above the 10 ms cut
        return {"t": float(t), "merged": reg.dump()}

    events = []
    for i in range(20):
        # cumulative totals: all-slow traffic from the start
        events.extend(evaluator.observe(snap(10.0 * i, 5 * (i + 1),
                                             20 * (i + 1))))
    assert any(e["event"] == "slo_burn" for e in events)


def test_slo_validation():
    with pytest.raises(ValueError):
        SLO("x", kind="latency")          # latency needs threshold_ms
    with pytest.raises(ValueError):
        SLO("x", kind="unknown")
    with pytest.raises(ValueError):
        SLO("x", objective=1.0)


# --------------------------------------------------------------------------- #
# Gauge-edge vs histogram cross-check (the batcher's identical-edge
# contract)


def test_queue_depth_gauge_stream_folds_to_depth_histogram(tmp_path):
    """Every telemetry `serve_queue_depth` gauge edge pairs with a
    `serve_queue_depth_dist` observation of the SAME value: folding the
    recorded gauge stream into a fresh histogram reproduces the
    registry histogram's bucket counts exactly."""
    reg = MetricsRegistry(source="svc")
    done = threading.Event()

    def dispatch(cell, batch):
        return batch

    def resolve(handle, batch):
        for r in handle:
            r.future.set_result(None)
        done.set()

    telemetry = obs.activate(obs.Telemetry(tmp_path))
    try:
        batcher = MicroBatcher(dispatch, resolve, max_batch=4,
                               max_delay=0.005, metrics=reg)
        matrix = np.zeros((3, 8), np.float32)
        futures = [batcher.submit(ServeRequest("cell", 3, matrix, None))
                   for _ in range(12)]
        for f in futures:
            f.result(timeout=30)
        done.wait(timeout=30)
        batcher.close()
    finally:
        obs.deactivate()
        telemetry.close()

    depths = [r["value"] for r in obs.load_records(tmp_path)
              if r.get("kind") == "gauge"
              and r.get("name") == "serve_queue_depth"]
    assert depths                                   # the stream exists
    folded = Histogram("check", bounds=DEPTH_BOUNDS)
    for depth in depths:
        folded.observe(depth)
    cell = reg.dump()["metrics"]["serve_queue_depth_dist"]
    assert cell["counts"] == folded.snapshot()["counts"]
    assert cell["count"] == len(depths)


def test_health_monitor_edges_bump_metrics_counters():
    reg = MetricsRegistry()
    monitor = HealthMonitor(warmup=5, metrics=reg)
    base = {"var_ratio": 0.5, "update_ratio": 1e-3, "weight_norm": 6.0}
    # The non-finite rule is warmup-exempt: a planted burst is an
    # anomaly edge, its clearance a cleared edge
    monitor.update(1, dict(base, nonfinite=0))
    monitor.update(2, dict(base, nonfinite=3))
    monitor.update(3, dict(base, nonfinite=0))
    assert reg.counter("health_anomaly_edges").value == 1
    assert reg.counter("health_cleared_edges").value == 1


# --------------------------------------------------------------------------- #
# Quarantine-threshold calibration (`scripts/quarantine_rates.py`)


def _edge(t, name, channel):
    return {"t": t, "kind": "event", "name": name,
            "data": {"channel": channel, "step": 1, "value": 1.0}}


def test_anomaly_episode_folding_spans_channels():
    """Overlapping channel edges fold into ONE monitor-level episode
    (the heartbeat flag is up while ANY channel is anomalous); an
    episode still open at stream end is persistent."""
    records = [
        _edge(10.0, "health_anomaly", "var_ratio"),
        _edge(10.4, "health_anomaly", "weight_norm"),   # extends, no nest
        _edge(10.8, "health_cleared", "var_ratio"),
        _edge(11.2, "health_cleared", "weight_norm"),   # closes at 1.2 s
        _edge(20.0, "health_anomaly", "update_ratio"),
        _edge(20.3, "health_cleared", "update_ratio"),  # 0.3 s transient
        _edge(30.0, "health_anomaly", "var_ratio"),     # never cleared
    ]
    episodes = quarantine_rates.anomaly_episodes(records)
    assert episodes["persistent"] == 1
    assert [round(d, 3) for d in episodes["cleared"]] == [0.3, 1.2]


def test_recommendation_thresholds():
    polls = quarantine_rates.episode_polls
    assert polls(0.0, 0.2) == 1
    assert polls(1.1, 0.2) == 6
    # p95 of the cleared spans + one poll of margin, floored at 2
    episodes = {"cleared": [0.3, 0.5, 1.1], "persistent": 1}
    assert quarantine_rates.recommend_polls(episodes, 0.2) == 7
    assert quarantine_rates.recommend_polls(
        {"cleared": [], "persistent": 2}, 0.2) == quarantine_rates.FLOOR_POLLS
    assert quarantine_rates.recommend_polls(
        {"cleared": [], "persistent": 0}, 0.2) is None
    rec = quarantine_rates.recommendation(episodes, 0.2)
    assert rec["anomaly_polls"] == 7 and rec["basis"] == "fp_rate<=0.05"
    assert rec["cost_per_sick_host_s"] == pytest.approx(1.4)


def test_summarize_and_resolve_precedence(tmp_path):
    run = tmp_path / "run"
    run.mkdir()
    lines = [json.dumps(_edge(t0, "health_anomaly", "var_ratio"))
             + "\n" + json.dumps(_edge(t1, "health_cleared", "var_ratio"))
             for t0, t1 in ((10.0, 10.3), (20.0, 20.5), (30.0, 31.1))]
    (run / "telemetry.jsonl").write_text("\n".join(lines) + "\n")
    summary = quarantine_rates.summarize([run], poll_s=0.2)
    assert summary["kind"] == "quarantine_rates"
    assert summary["recommended_anomaly_polls"] == 7
    rates_path = tmp_path / "rates.json"
    rates_path.write_text(json.dumps(summary))

    # Precedence: explicit flag > rates file > default
    assert resolve_anomaly_polls(5, str(rates_path)) == (5, "flag")
    assert resolve_anomaly_polls(None, str(rates_path)) == (
        7, "quarantine-rates:fp_rate<=0.05")
    assert resolve_anomaly_polls(None, None) == (DEFAULT_ANOMALY_POLLS,
                                                 "default")
    # Legacy top-level field (no recommendation block) still resolves
    legacy = tmp_path / "legacy.json"
    legacy.write_text(json.dumps({"recommended_anomaly_polls": 4}))
    polls, source = resolve_anomaly_polls(None, str(legacy))
    assert polls == 4 and source.startswith("quarantine-rates:")
    # An empty recommendation (no episodes observed) is an error, not a
    # silent default
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps(
        quarantine_rates.summarize([run / "absent"], poll_s=0.2)))
    with pytest.raises(ValueError):
        resolve_anomaly_polls(None, str(empty))


# --------------------------------------------------------------------------- #
# The bench_compare metrics-overhead gate


def _metrics_artifact(tmp_path, name, *, on=100.0, off=102.0,
                      overhead=0.02, within=True, smoke=False,
                      backend="cpu", kind="metrics_overhead"):
    payload = {"kind": kind, "backend": backend,
               "agg_per_sec_metrics_on": on,
               "agg_per_sec_metrics_off": off,
               "overhead_frac": overhead, "bound_frac": 0.02,
               "within_bound": within}
    if smoke:
        payload["smoke"] = True
    path = tmp_path / name
    path.write_text(json.dumps({"n": 1, "rc": 0, "parsed": payload}))
    return path


def test_compare_metrics_pass_and_overhead_regression(tmp_path, capsys):
    old = _metrics_artifact(tmp_path, "old.json", overhead=0.010)
    good = _metrics_artifact(tmp_path, "good.json", overhead=0.012)
    # +20% relative but only +0.002 absolute: under the floor, passes
    assert bench_compare.main([str(old), str(good),
                               "--tolerance", "0.05"]) == 0
    bad = _metrics_artifact(tmp_path, "bad.json", overhead=0.019)
    assert bench_compare.main([str(old), str(bad),
                               "--tolerance", "0.05"]) == 1
    assert "overhead_frac" in capsys.readouterr().out


def test_compare_metrics_rate_drop_and_bound_flip(tmp_path, capsys):
    old = _metrics_artifact(tmp_path, "old.json")
    slow = _metrics_artifact(tmp_path, "slow.json", on=80.0, off=82.0)
    assert bench_compare.main([str(old), str(slow),
                               "--tolerance", "0.05"]) == 1
    flipped = _metrics_artifact(tmp_path, "flip.json", overhead=0.021,
                                within=False)
    # within_bound True -> False fails regardless of tolerance
    assert bench_compare.main([str(old), str(flipped),
                               "--tolerance", "0.5"]) == 1
    out = capsys.readouterr().out
    assert "within_bound" in out


def test_compare_metrics_incomparable_cases(tmp_path, capsys):
    old = _metrics_artifact(tmp_path, "old.json")
    smoke = _metrics_artifact(tmp_path, "smoke.json", smoke=True)
    assert bench_compare.main([str(old), str(smoke)]) == 0
    other_backend = _metrics_artifact(tmp_path, "tpu.json", backend="tpu")
    assert bench_compare.main([str(old), str(other_backend)]) == 0
    serve = _metrics_artifact(tmp_path, "serve.json", kind="serve")
    assert bench_compare.main([str(old), str(serve)]) == 0
    assert capsys.readouterr().out.count("INCOMPARABLE") == 3
