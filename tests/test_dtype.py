"""Dtype-knob tests (VERDICT r1 items 2/9): the `--dtype` flag mirrors the
reference Configuration's dtype (reference
`experiments/configuration.py:26-101`); `--compute-dtype` adds TPU mixed
precision (bf16 forward/backward, f32 master weights/momentum/GAR space).

GAR differentials at bf16 tolerances, engine state-dtype invariants, and a
CLI smoke run per dtype."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byzantinemomentum_tpu import models as models_mod
from byzantinemomentum_tpu import losses as losses_mod
from byzantinemomentum_tpu import ops as ops_mod
from byzantinemomentum_tpu.cli.attack import main
from byzantinemomentum_tpu.engine import EngineConfig, build_engine

# bf16 has an 8-bit mantissa: kernels on bf16 inputs should agree with the
# f32 kernel on the same values to ~1e-2 relative
BF16_TOL = dict(rtol=2e-2, atol=2e-2)


def _rand(n, d, seed=0):
    return np.random.default_rng(seed).standard_normal((n, d)).astype(np.float32)


@pytest.mark.parametrize("name", ["average", "median", "trmean", "phocas",
                                  "meamed", "krum", "bulyan", "aksel", "cge"])
def test_gar_bf16_matches_f32(name):
    # Compare the kernel at bf16 against the f32 kernel on the SAME
    # bf16-rounded values: identical selection decisions, so the remaining
    # difference is pure kernel arithmetic precision (input-rounding-induced
    # selection flips are the dtype's semantics, not a kernel defect)
    Gbf = jnp.asarray(_rand(11, 40)).astype(jnp.bfloat16)
    G32 = Gbf.astype(jnp.float32)
    gar = ops_mod.gars[name]
    out32 = np.asarray(gar.unchecked(G32, f=2))
    outbf = np.asarray(gar.unchecked(Gbf, f=2).astype(jnp.float32))
    np.testing.assert_allclose(outbf, out32, **BF16_TOL)


def test_gar_bf16_output_dtype_follows_input():
    G = jnp.asarray(_rand(9, 16)).astype(jnp.bfloat16)
    for name in ("average", "median", "krum"):
        out = ops_mod.gars[name].unchecked(G, f=2)
        assert out.dtype == jnp.bfloat16, name


def _build(dtype=None, compute_dtype=None, momentum_at="update"):
    cfg = EngineConfig(
        nb_workers=5, nb_decl_byz=1, nb_real_byz=0, momentum=0.9,
        momentum_at=momentum_at,
        dtype=dtype or "float32", compute_dtype=compute_dtype)
    model = models_mod.build("simples-full")
    loss = losses_mod.Loss("nll")
    crit = losses_mod.Criterion("top-k")
    return build_engine(cfg=cfg, model_def=model, loss=loss, criterion=crit,
                        defenses=[(ops_mod.gars["median"], 1.0, {})])


def _batches(cfg, model, seed=3):
    rng = np.random.default_rng(seed)
    xs = rng.standard_normal(
        (cfg.nb_sampled, 4) + model.input_shape).astype(np.float32)
    ys = rng.integers(0, 10, (cfg.nb_sampled, 4)).astype(np.int32)
    return jnp.asarray(xs), jnp.asarray(ys)


def test_full_bf16_state_dtypes_stable():
    eng = _build(dtype="bfloat16")
    state = eng.init(jax.random.PRNGKey(0))
    assert state.theta.dtype == jnp.bfloat16
    assert state.momentum_server.dtype == jnp.bfloat16
    xs, ys = _batches(eng.cfg, eng.model_def)
    for _ in range(2):
        state, _ = eng.train_step(state, xs, ys, jnp.float32(0.05))
    assert state.theta.dtype == jnp.bfloat16
    assert bool(jnp.all(jnp.isfinite(state.theta.astype(jnp.float32))))


def test_mixed_precision_master_stays_f32_and_tracks_f32_run():
    eng32 = _build()
    engmp = _build(dtype="float32", compute_dtype="bfloat16")
    s32 = eng32.init(jax.random.PRNGKey(0))
    smp = engmp.init(jax.random.PRNGKey(0))
    assert smp.theta.dtype == jnp.float32
    xs, ys = _batches(eng32.cfg, eng32.model_def)
    for _ in range(3):
        s32, _ = eng32.train_step(s32, xs, ys, jnp.float32(0.05))
        smp, _ = engmp.train_step(smp, xs, ys, jnp.float32(0.05))
    assert smp.theta.dtype == jnp.float32
    assert smp.momentum_server.dtype == jnp.float32
    # Same trajectory up to bf16 forward/backward rounding
    np.testing.assert_allclose(np.asarray(smp.theta), np.asarray(s32.theta),
                               rtol=5e-2, atol=5e-3)
    # ... but not bit-identical (the bf16 path must actually engage)
    assert not np.array_equal(np.asarray(smp.theta), np.asarray(s32.theta))


def test_full_bf16_with_attack_and_worker_momentum():
    """Attack line-search + worker momentum buffers keep the bf16 dtype
    (donation requires stable state dtypes across steps)."""
    from byzantinemomentum_tpu import attacks as attacks_mod
    cfg = EngineConfig(
        nb_workers=7, nb_decl_byz=2, nb_real_byz=2, momentum=0.9,
        momentum_at="worker", dtype="bfloat16")
    model = models_mod.build("simples-full")
    eng = build_engine(
        cfg=cfg, model_def=model, loss=losses_mod.Loss("nll"),
        criterion=losses_mod.Criterion("top-k"),
        defenses=[(ops_mod.gars["median"], 1.0, {})],
        attack=attacks_mod.attacks["empire"], attack_kwargs={"factor": 1.1})
    state = eng.init(jax.random.PRNGKey(1))
    xs, ys = _batches(cfg, model)
    for _ in range(2):
        state, _ = eng.train_step(state, xs, ys, jnp.float32(0.05))
    assert state.theta.dtype == jnp.bfloat16
    assert state.momentum_workers.dtype == jnp.bfloat16


@pytest.fixture
def small_synth(monkeypatch):
    monkeypatch.setenv("BMT_SYNTH_TRAIN", "512")
    monkeypatch.setenv("BMT_SYNTH_TEST", "128")


def test_mixed_precision_eval_on_bn_model():
    """Regression: under `--compute-dtype bfloat16`, evaluation on a
    BatchNorm model normalizes with the f32 running stats but must keep the
    activation stream in bf16 — the f32 promotion used to reach the next
    conv as a dtype mismatch (caught on a real-TPU driver run; CPU suites
    only evaluated BN-free models in mixed precision)."""
    from byzantinemomentum_tpu import attacks
    cfg = EngineConfig(nb_workers=5, nb_decl_byz=1, nb_real_byz=1,
                       momentum=0.9, momentum_at="update",
                       compute_dtype="bfloat16")
    engine = build_engine(
        cfg=cfg, model_def=models_mod.build("empire-cnn"),
        loss=losses_mod.Loss("nll"), criterion=losses_mod.Criterion("top-k"),
        defenses=[(ops_mod.gars["median"], 1.0, {})],
        attack=attacks.attacks["empire"], attack_kwargs={"factor": 1.1})
    state = engine.init(jax.random.PRNGKey(0))
    x = jnp.asarray(_rand(4, 32 * 32 * 3, seed=3).reshape(4, 32, 32, 3))
    y = jnp.zeros((4,), jnp.int32)
    res = np.asarray(engine.eval_step(state.theta, state.net_state, x, y))
    assert res.shape == (2,) and res[1] == 4


@pytest.mark.parametrize("dtype,fmt_digits",
                         [("bfloat16", 4), ("float32", 8), ("float16", 4)])
def test_cli_dtype_smoke(tmp_path, small_synth, dtype, fmt_digits):
    """Smoke run at each dtype: finite study metrics, dtype-dependent CSV
    precision (reference `attack.py:870`)."""
    resdir = tmp_path / dtype
    rc = main(["--nb-steps", "2", "--batch-size", "8",
               "--batch-size-test", "32", "--batch-size-test-reps", "1",
               "--evaluation-delta", "2", "--model", "simples-full",
               "--seed", "7", "--gar", "median", "--nb-workers", "7",
               "--nb-decl-byz", "2", "--nb-for-study", "7",
               "--nb-for-study-past", "2", "--dtype", dtype,
               "--result-directory", str(resdir)])
    assert rc == 0
    lines = (resdir / "study").read_text().split(os.linesep)
    rows = [l for l in lines[1:] if l]
    assert len(rows) == 2
    field = rows[-1].split("\t")[2]  # "Average loss"
    assert np.isfinite(float(field))
    mantissa = field.split("e")[0].split(".")[1]
    assert len(mantissa) == fmt_digits


@pytest.mark.slow
def test_cli_mixed_precision_smoke(tmp_path, small_synth):
    resdir = tmp_path / "mp"
    rc = main(["--nb-steps", "2", "--batch-size", "8",
               "--batch-size-test", "32", "--batch-size-test-reps", "1",
               "--evaluation-delta", "0", "--model", "simples-conv",
               "--seed", "7", "--gar", "krum", "--nb-workers", "9",
               "--nb-decl-byz", "2", "--nb-real-byz", "2",
               "--attack", "little", "--attack-args", "factor:1.5",
               "--dtype", "float32", "--compute-dtype", "bfloat16",
               "--nb-for-study", "9", "--nb-for-study-past", "2",
               "--result-directory", str(resdir)])
    assert rc == 0
    lines = (resdir / "study").read_text().split(os.linesep)
    rows = [l for l in lines[1:] if l]
    assert all(np.isfinite(float(r.split("\t")[2])) for r in rows)


def test_f64_without_x64_refused():
    """Library callers requesting float64 without x64 mode get a hard error
    instead of a silently-f32 run mislabeled as f64."""
    if jax.config.jax_enable_x64:
        pytest.skip("x64 already enabled in this process")
    with pytest.raises(ValueError, match="x64"):
        _build(dtype="float64")



def test_cross_dtype_checkpoint_resume(tmp_path, small_synth):
    """A checkpoint written at one dtype loads into a run configured at
    another: stored arrays are cast to the new state template's dtypes
    (checkpoint.py casts to the template), and training continues finitely."""
    base = ["--batch-size", "8", "--batch-size-test", "32",
            "--batch-size-test-reps", "1", "--evaluation-delta", "2",
            "--model", "simples-full", "--seed", "21", "--gar", "median",
            "--nb-workers", "7", "--nb-decl-byz", "2",
            "--nb-for-study", "7", "--nb-for-study-past", "2"]
    part = tmp_path / "bf16"
    rc = main(base + ["--nb-steps", "2", "--checkpoint-delta", "2",
                      "--dtype", "bfloat16",
                      "--result-directory", str(part)])
    assert rc == 0
    resumed = tmp_path / "f32"
    rc = main(base + ["--nb-steps", "2", "--dtype", "float32",
                      "--load-checkpoint", str(part / "checkpoint-2"),
                      "--result-directory", str(resumed)])
    assert rc == 0
    rows = [l for l in (resumed / "study").read_text().split(os.linesep)[1:] if l]
    assert [r.split("\t")[0] for r in rows] == ["2", "3"]
    # f32 precision restored in the CSV format, values finite
    assert all(np.isfinite(float(r.split("\t")[2])) for r in rows)
    assert len(rows[0].split("\t")[2].split("e")[0].split(".")[1]) == 8
