"""`resnet18` registry extension: torchvision architecture parity (param
count) and a training-step smoke (reference exposes every torchvision model
by name, `experiments/model.py:40-90`; this pins the registry extending the
same way)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byzantinemomentum_tpu import attacks, losses, models, ops
from byzantinemomentum_tpu.engine import EngineConfig, build_engine


def test_resnet18_param_count_matches_torchvision():
    # torchvision resnet18 has 11,689,512 parameters with the 1000-class fc
    # (BN running stats are buffers, not parameters — same split here)
    model_def = models.build("resnet18", num_classes=1000)
    assert model_def.param_count() == 11_689_512
    assert models.build("resnet18").param_count() == 11_181_642  # 10-class


def test_resnet34_param_count_matches_torchvision():
    assert models.build("resnet34",
                        num_classes=1000).param_count() == 21_797_672


def test_resnet50_param_count_and_forward():
    # torchvision resnet50 (Bottleneck [3,4,6,3], expansion 4)
    assert models.build("resnet50",
                        num_classes=1000).param_count() == 25_557_032
    # The deeper Bottleneck variants pin the same way (torchvision counts)
    assert models.build("resnet101",
                        num_classes=1000).param_count() == 44_549_160
    assert models.build("resnet152",
                        num_classes=1000).param_count() == 60_192_808
    model_def = models.build("resnet50")
    params, state = model_def.init(jax.random.PRNGKey(0))
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    out, _ = model_def.apply(params, state, x, train=False,
                             rng=jax.random.PRNGKey(0))
    assert out.shape == (2, 10)


@pytest.mark.slow
def test_resnet18_forward_and_step():
    model_def = models.build("resnet18")
    params, state = model_def.init(jax.random.PRNGKey(0))
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 32, 32, 3)),
                    jnp.float32)
    out, _ = model_def.apply(params, state, x, train=False,
                             rng=jax.random.PRNGKey(0))
    assert out.shape == (2, 10)
    out_t, new_state = model_def.apply(params, state, x, train=True,
                                       rng=jax.random.PRNGKey(1))
    assert out_t.shape == (2, 10)
    # Train mode updates every BN running stat
    changed = jax.tree.map(
        lambda a, b: bool(np.any(np.asarray(a) != np.asarray(b))),
        state, new_state)
    assert any(jax.tree.leaves(changed))

    cfg = EngineConfig(nb_workers=5, nb_decl_byz=1, nb_real_byz=1,
                       nb_for_study=1, nb_for_study_past=1,
                       momentum=0.9, momentum_at="update", gradient_clip=2.0)
    engine = build_engine(
        cfg=cfg, model_def=model_def, loss=losses.Loss("crossentropy"),
        criterion=losses.Criterion("top-k"),
        defenses=[(ops.gars["median"], 1.0, {})],
        attack=attacks.attacks["empire"], attack_kwargs={"factor": 1.1})
    st = engine.init(jax.random.PRNGKey(0))
    xs = jnp.zeros((cfg.nb_sampled, 2, 32, 32, 3), jnp.float32)
    ys = jnp.zeros((cfg.nb_sampled, 2), jnp.int32)
    st, metrics = engine.train_step(st, xs, ys, jnp.float32(0.01))
    assert int(st.steps) == 1
    assert np.isfinite(float(metrics["Defense gradient norm"]))
