"""Data-layer tests: sampler contracts, determinism, splits, transforms.

Models the reference's implicit dataset contracts (reference
`experiments/dataset.py`): infinite sampling, fixed batch shapes, shuffled
train / ordered test, normalization constants.
"""

import numpy as np
import pytest

from byzantinemomentum_tpu import data


@pytest.fixture(autouse=True)
def small_synth(monkeypatch):
    monkeypatch.setenv("BMT_SYNTH_TRAIN", "256")
    monkeypatch.setenv("BMT_SYNTH_TEST", "64")


def test_fixed_batch_shapes_across_epoch_wrap():
    tr, te = data.make_datasets("mnist", 100, 30)
    # 256 train samples, batch 100: the third batch wraps the epoch boundary
    for _ in range(10):
        x, y = tr.sample()
        assert x.shape == (100, 28, 28, 1)
        assert y.shape == (100,)
        assert x.dtype == np.float32
    for _ in range(5):
        x, y = te.sample()
        assert x.shape == (30, 28, 28, 1)


def test_train_epoch_covers_all_samples():
    tr, _ = data.make_datasets("mnist", 64, 32)
    seen = set()
    # One epoch = 4 batches of 64 over 256 samples; identify samples by bytes
    all_x = []
    for _ in range(4):
        x, _ = tr.sample()
        all_x.append(x)
    stack = np.concatenate(all_x)
    uniq = {a.tobytes() for a in stack}
    assert len(uniq) == 256  # a full shuffled epoch, no repeats


def test_test_set_cycles_in_order():
    _, te = data.make_datasets("mnist", 64, 64)
    a1, _ = te.sample()
    for _ in range(0):  # 64/64: next sample starts a new cycle
        pass
    b1, _ = te.sample()
    np.testing.assert_array_equal(a1, b1)


def test_determinism_across_instances():
    tr1, _ = data.make_datasets("cifar10", 16, 16, seed=5)
    tr2, _ = data.make_datasets("cifar10", 16, 16, seed=5)
    for _ in range(3):
        x1, y1 = tr1.sample()
        x2, y2 = tr2.sample()
        np.testing.assert_array_equal(x1, x2)
        np.testing.assert_array_equal(y1, y2)


def test_mnist_normalization_constants():
    tr, _ = data.make_datasets("mnist", 256, 16)
    x, _ = tr.sample()
    # Raw uint8 128 maps to (128/255 - 0.1307) / 0.3081
    raw, _ = data.make_datasets("mnist", 256, 16, no_transform=True)
    xr, _ = raw.sample()
    assert xr.min() >= 0.0 and xr.max() <= 1.0
    assert x.min() < -0.3  # normalization shifts below zero


def test_phishing_split_and_shapes():
    tr, te = data.make_datasets("phishing", 32, 32)
    x, y = tr.sample()
    assert x.shape == (32, 68)
    assert y.shape == (32, 1)
    assert set(np.unique(y)).issubset({0.0, 1.0})


def test_batch_dataset_split_semantics():
    inputs = np.arange(40, dtype=np.float32).reshape(20, 2)
    labels = np.arange(20, dtype=np.float32).reshape(20, 1)
    # Fractional split (reference `dataset.py:303-354`)
    tr = data.batch_dataset(inputs, labels, train=True, batch_size=5, split=0.75)
    te = data.batch_dataset(inputs, labels, train=False, batch_size=5, split=0.75)
    assert len(tr) == 15 and len(te) == 5
    # Absolute split
    tr = data.batch_dataset(inputs, labels, train=True, batch_size=4, split=8)
    assert len(tr) == 8
    x, y = tr.sample()
    assert x.shape == (4, 2)


def test_kmnist_registered_with_own_normalization():
    """KMNIST extends the dataset registry through the existing idx parser
    (the reference exposes every torchvision dataset by name,
    `dataset.py:100-163`); torchvision's KMNIST normalization constants
    apply and no flip is in the default transform."""
    assert "kmnist" in data.datasets
    assert data.normalizations["kmnist"] == ((0.1918,), (0.3483,))
    assert "kmnist" not in data.flip_train
    tr, te = data.make_datasets("kmnist", 16, 16)
    x, y = tr.sample()
    assert x.shape == (16, 28, 28, 1)
    # Normalized around the KMNIST mean, not raw [0, 1]
    assert float(x.min()) < -0.4
    assert set(np.unique(y)) <= set(range(10))
