"""Request-scoped serve tracing + fleet-wide attribution (`obs/trace/`,
PR 13): span stamps that tile the measured serve latency, the bounded
completed-trace ring, trace-id propagation through the line-JSON
protocol (malformed ids answer without severing), the queue-depth gauge
emitted on every queue transition, the heartbeat-handshake clock-offset
estimator, and the joined fleet timeline that reorders skewed host
streams correctly."""

import json
import socket
import threading

import numpy as np
import pytest

from byzantinemomentum_tpu import obs
from byzantinemomentum_tpu.obs.trace import (
    ClockOffsetTracker, RequestTrace, TraceBuffer, estimate_offsets,
    fleet_timeline, percentile, render_fleet_report)
from byzantinemomentum_tpu.obs.trace.fleet import host_telemetry_path
from byzantinemomentum_tpu.obs.trace.request import LATENCY_PHASES


# --------------------------------------------------------------------------- #
# RequestTrace: span computation

def test_request_trace_spans_and_total():
    trace = RequestTrace("req-1")
    base = 1000.0
    for name, at in (("recv", 0.000), ("accept", 0.001), ("submit", 0.002),
                     ("done", 0.012)):
        trace.stamp(name, at=base + at)
    trace.batch_stamps = {"flush": base + 0.004, "packed": base + 0.005,
                          "dispatched": base + 0.006,
                          "resolver": base + 0.008, "device": base + 0.010,
                          "batch_size": 4, "batch_occupancy": 0.5}
    spans = trace.spans_ms()
    assert spans["parse"] == pytest.approx(1.0, rel=1e-6)
    assert spans["validate"] == pytest.approx(1.0, rel=1e-6)
    assert spans["queue"] == pytest.approx(2.0, rel=1e-6)
    assert spans["pack"] == pytest.approx(1.0, rel=1e-6)
    assert spans["dispatch"] == pytest.approx(1.0, rel=1e-6)
    assert spans["resolver_wake"] == pytest.approx(2.0, rel=1e-6)
    assert spans["device"] == pytest.approx(2.0, rel=1e-6)
    assert spans["resolve"] == pytest.approx(2.0, rel=1e-6)
    # The tiling identity: latency phases sum to submit->done
    assert sum(spans[p] for p in LATENCY_PHASES) == pytest.approx(
        trace.total_ms(), rel=1e-9)
    record = trace.as_dict()
    assert record["trace_id"] == "req-1"
    assert record["batch_size"] == 4 and record["batch_occupancy"] == 0.5


def test_request_trace_partial_stamps_and_auto_id():
    trace = RequestTrace()  # auto id, accept stamped at creation
    assert trace.trace_id.startswith("t")
    spans = trace.spans_ms()  # nothing else stamped: no complete phase
    assert spans == {}
    assert trace.total_ms() is None
    # A numeric wire id round-trips as its string form, verbatim
    assert RequestTrace(17).trace_id == "17"
    assert RequestTrace(17).as_dict()["trace_id"] == "17"


def test_request_trace_negative_span_clamps():
    trace = RequestTrace("x")
    trace.stamp("submit", at=10.0)
    trace.batch_stamps = {"flush": 9.9}  # cross-thread stamp inversion
    assert trace.spans_ms()["queue"] == 0.0


# --------------------------------------------------------------------------- #
# TraceBuffer: bounding + summary

def test_trace_buffer_bounds_and_counts():
    buffer = TraceBuffer(maxlen=8)
    for i in range(50):
        trace = RequestTrace(f"t{i}")
        trace.stamp("submit", at=float(i))
        trace.stamp("done", at=float(i) + 0.001 * (i + 1))
        buffer.add(trace)
    assert len(buffer) == 8               # the ring is BOUNDED
    assert buffer.completed == 50         # ...but the count is total
    records = buffer.snapshot()
    assert [r["trace_id"] for r in records] == [f"t{i}" for i in
                                                range(42, 50)]
    summary = buffer.summary()
    assert summary["buffered"] == 8 and summary["completed"] == 50
    assert summary["total_ms"]["max"] == pytest.approx(50.0, rel=1e-6)
    with pytest.raises(ValueError, match="maxlen"):
        TraceBuffer(maxlen=0)


def test_percentile_nearest_rank():
    values = list(range(1, 101))
    assert percentile(values, 50) in (50, 51)
    assert percentile(values, 99) == 100 or percentile(values, 99) == 99
    assert percentile([7.0], 99) == 7.0
    with pytest.raises(ValueError):
        percentile([], 50)


# --------------------------------------------------------------------------- #
# Service end-to-end: spans tile latency, gauge transitions, snapshot

def test_service_traces_tile_latency_and_ride_responses(tmp_path):
    from byzantinemomentum_tpu.serve import AggregationService

    rng = np.random.default_rng(0)
    with AggregationService(max_batch=4, max_delay_ms=2.0) as service:
        service.warmup([("krum", 7, 1, 32, False)])
        futures = [service.submit(
            rng.standard_normal((7, 32)).astype(np.float32),
            gar="krum", f=1, diagnostics=False, trace_id=f"req-{k}")
            for k in range(12)]
        results = [fut.result(timeout=60) for fut in futures]
    for k, result in enumerate(results):
        assert result.trace.trace_id == f"req-{k}"
        spans = result.trace.spans_ms()
        tiled = sum(spans[p] for p in LATENCY_PHASES if p in spans)
        # The span sum IS the measured latency (same stamps)
        assert tiled == pytest.approx(result.latency_ms, rel=0.01)
        record = result.trace.as_dict()
        assert record["gar"] == "krum" and record["n"] == 7
        assert record["depth_at_submit"] >= 1
    # The ring buffer saw every request
    assert len(results) == 12


def test_service_tracing_off_skips_everything():
    from byzantinemomentum_tpu.serve import AggregationService

    rng = np.random.default_rng(0)
    with AggregationService(max_batch=2, max_delay_ms=1.0,
                            tracing=False) as service:
        result = service.aggregate(
            rng.standard_normal((5, 16)).astype(np.float32),
            gar="median", f=1, diagnostics=False, timeout=60)
        assert result.trace is None
        assert "trace" not in result.as_dict()
        assert service.stats()["tracing"] == {"enabled": False}
        assert service.traces.completed == 0


def test_queue_depth_gauge_emitted_on_every_transition(tmp_path):
    """The satellite fix: `serve_queue_depth` lands on submit, flush AND
    resolver drain — an idle-then-burst queue is visible as a rise-fall
    sequence, not only the post-flush residue."""
    from byzantinemomentum_tpu.serve import AggregationService

    telemetry = obs.activate(obs.Telemetry(tmp_path))
    try:
        rng = np.random.default_rng(0)
        with AggregationService(max_batch=4, max_delay_ms=50.0) as service:
            service.warmup([("median", 5, 1, 16, False)])
            futures = [service.submit(
                rng.standard_normal((5, 16)).astype(np.float32),
                gar="median", f=1, diagnostics=False) for _ in range(4)]
            for fut in futures:
                fut.result(timeout=60)
    finally:
        obs.deactivate()
        telemetry.close()
    gauges = [(r["data"]["edge"], r["value"])
              for r in obs.load_records(tmp_path)
              if r.get("kind") == "gauge"
              and r.get("name") == "serve_queue_depth"]
    edges = [e for e, _ in gauges]
    assert "submit" in edges and "flush" in edges and "drain" in edges
    # The burst builds depth on submit edges...
    submit_depths = [v for e, v in gauges if e == "submit"]
    assert max(submit_depths) >= 2
    # ...and the queue is drained by the end
    assert [v for e, v in gauges if e == "drain"][-1] == 0


def test_trace_snapshot_file(tmp_path):
    from byzantinemomentum_tpu.serve import AggregationService

    rng = np.random.default_rng(0)
    with AggregationService(max_batch=2, max_delay_ms=1.0,
                            directory=tmp_path / "run") as service:
        service.aggregate(rng.standard_normal((5, 16)).astype(np.float32),
                          gar="median", f=1, diagnostics=False, timeout=60)
        path = service.write_trace_snapshot()
    payload = json.loads(path.read_text())
    assert payload["kind"] == "serve_traces"
    assert payload["summary"]["completed"] >= 1
    assert payload["traces"] and "spans_ms" in payload["traces"][0]


# --------------------------------------------------------------------------- #
# Frontend: trace-id propagation + malformed ids

def _roundtrip_lines(server_port, lines):
    out = []
    with socket.create_connection(("127.0.0.1", server_port),
                                  timeout=30) as conn:
        fd = conn.makefile("rwb")
        for line in lines:
            fd.write(json.dumps(line).encode() + b"\n")
            fd.flush()
            out.append(json.loads(fd.readline()))
    return out


def test_frontend_trace_id_roundtrip_and_malformed(tmp_path):
    from byzantinemomentum_tpu.serve import AggregationService
    from byzantinemomentum_tpu.serve.frontend import AggregationServer

    rng = np.random.default_rng(0)
    cohort = rng.standard_normal((5, 16)).astype(np.float32).tolist()
    with AggregationService(max_batch=2, max_delay_ms=1.0) as service:
        with AggregationServer(("127.0.0.1", 0), service) as server:
            server.serve_background()
            responses = _roundtrip_lines(server.port, [
                {"op": "aggregate", "gar": "median", "f": 1,
                 "vectors": cohort, "trace": "wire-7"},
                # malformed id: answers an error WITHOUT severing
                {"op": "aggregate", "gar": "median", "f": 1,
                 "vectors": cohort, "trace": {"bad": 1}},
                # absent id: auto-assigned, trace still rides back
                {"op": "aggregate", "gar": "median", "f": 1,
                 "vectors": cohort},
                {"op": "ping"},
            ])
            server.shutdown()
    assert responses[0]["ok"] and responses[0]["trace"]["trace_id"] == \
        "wire-7"
    assert responses[0]["trace"]["spans_ms"]["parse"] >= 0.0
    assert not responses[1]["ok"] and "trace id" in responses[1]["error"]
    assert responses[2]["ok"] and responses[2]["trace"]["trace_id"]
    assert responses[3] == {"ok": True, "op": "ping"}


def test_frontend_tracing_off_omits_trace_key():
    from byzantinemomentum_tpu.serve import AggregationService
    from byzantinemomentum_tpu.serve.frontend import AggregationServer

    rng = np.random.default_rng(0)
    cohort = rng.standard_normal((5, 16)).astype(np.float32).tolist()
    with AggregationService(max_batch=2, max_delay_ms=1.0,
                            tracing=False) as service:
        with AggregationServer(("127.0.0.1", 0), service) as server:
            server.serve_background()
            (response,) = _roundtrip_lines(server.port, [
                {"op": "aggregate", "gar": "median", "f": 1,
                 "vectors": cohort, "trace": "ignored"}])
            server.shutdown()
    assert response["ok"] and "trace" not in response


# --------------------------------------------------------------------------- #
# Clock-offset estimator

def test_clock_offset_tracker_takes_the_minimum_skew():
    tracker = ClockOffsetTracker()
    # Host 1 runs 5.0s BEHIND the launcher; poll delay varies 0.1-0.9s
    for delay in (0.9, 0.3, 0.1, 0.5):
        host_wall = 100.0
        tracker.observe(1, host_wall, host_wall + 5.0 + delay)
    est = tracker.estimate()
    assert est[1] == pytest.approx(5.1, abs=1e-9)  # min(5.0 + delay)
    assert tracker.samples[1] == 4
    # A host AHEAD of the launcher estimates negative
    tracker.observe(2, 200.0, 197.0)
    assert tracker.estimate()[2] == pytest.approx(-3.0)
    # None host stamps are ignored, not fatal
    tracker.observe(3, None, 100.0)
    assert 3 not in tracker.estimate()
    data = tracker.as_event_data()
    assert data["offsets"]["1"] == pytest.approx(5.1, abs=1e-6)
    assert data["samples"]["2"] == 1


def test_estimate_offsets_reads_newest_event():
    records = [
        {"kind": "event", "name": "clock_offsets",
         "data": {"offsets": {"0": 1.0, "1": 2.0}}},
        {"kind": "event", "name": "other"},
        {"kind": "event", "name": "clock_offsets",
         "data": {"offsets": {"0": 0.5, "1": 1.5, "bad": "x"}}},
    ]
    assert estimate_offsets(records) == {0: 0.5, 1: 1.5}
    assert estimate_offsets([]) == {}


# --------------------------------------------------------------------------- #
# Fleet timeline: skewed synthetic host streams reorder correctly

def _write_jsonl(path, records):
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


def _synthetic_cluster_run(tmp_path, *, skew=30.0):
    """A 2-host run dir whose host-1 clock runs `skew` seconds BEHIND
    the launcher: naively merged, its events would sort before the
    launch. The launcher's clock_offsets event carries the estimate."""
    t0 = 1000.0
    _write_jsonl(tmp_path / "telemetry.jsonl", [
        {"t": t0 + 0.0, "kind": "event", "name": "cluster_start",
         "data": {"hosts": 2}},
        {"t": t0 + 0.1, "kind": "event", "name": "fleet_launch",
         "data": {"attempt": 1}},
        {"t": t0 + 3.0, "kind": "event", "name": "fault_injected",
         "data": {"kind": "device_loss", "host": 1, "at_step": 3}},
        {"t": t0 + 3.5, "kind": "event", "name": "liveness_transition",
         "data": {"from": "alive", "to": "dead", "host": 1}},
        {"t": t0 + 4.0, "kind": "event", "name": "host_dead",
         "data": {"host": 1, "at_step": 3}},
        {"t": t0 + 5.0, "kind": "event", "name": "clock_offsets",
         "data": {"offsets": {"0": 0.0, "1": skew}}},
        {"t": t0 + 6.0, "kind": "event", "name": "restart_agreed",
         "data": {"step": 2, "hosts": 2}},
        {"t": t0 + 9.0, "kind": "event", "name": "cluster_end",
         "data": {"status": "ok"}},
    ])
    _write_jsonl(host_telemetry_path(tmp_path, 0), [
        {"t": t0 + 1.0, "kind": "event", "name": "host_start",
         "data": {"host": 0}},
        {"t": t0 + 2.0, "kind": "gauge", "name": "host_step", "value": 1},
        {"t": t0 + 8.0, "kind": "event", "name": "host_end",
         "data": {"host": 0, "steps": 6}},
    ])
    # Host 1's clock: launcher time minus skew
    _write_jsonl(host_telemetry_path(tmp_path, 1), [
        {"t": t0 + 1.2 - skew, "kind": "event", "name": "host_start",
         "data": {"host": 1}},
        {"t": t0 + 2.5 - skew, "kind": "gauge", "name": "host_step",
         "value": 2},
    ])
    return t0


def test_fleet_timeline_reorders_skewed_host_streams(tmp_path):
    t0 = _synthetic_cluster_run(tmp_path, skew=30.0)
    timeline = fleet_timeline(tmp_path)
    names = [(e["source"], e["name"]) for e in timeline]
    # Host 1's start sorts AFTER the launch despite its skewed stamps
    assert names.index(("launcher", "fleet_launch")) \
        < names.index(("host-1", "host_start"))
    # The supervision story is ordered: fault -> death -> restart
    assert names.index(("launcher", "fault_injected")) \
        < names.index(("launcher", "host_dead")) \
        < names.index(("launcher", "restart_agreed"))
    # Clock shift applied exactly: host-1 host_start at t0+1.2
    start = next(e for e in timeline
                 if e["source"] == "host-1" and e["name"] == "host_start")
    assert start["t"] == pytest.approx(t0 + 1.2, abs=1e-6)
    # Without offsets the skewed stream would sort FIRST — prove the
    # counterfactual the estimator exists for
    naive = fleet_timeline(tmp_path, offsets={})
    assert naive[0]["source"] == "host-1"


def test_fleet_report_renders_ordered_events(tmp_path):
    _synthetic_cluster_run(tmp_path, skew=30.0)
    (tmp_path / "cluster.json").write_text(json.dumps({
        "hosts": 2, "status": "ok", "attempt": 2,
        "restart_step": 2, "fired_faults": [0],
        "recoveries": [{"host": 1, "died_at_step": 3, "restart_step": 2,
                        "recovery_steps": 1}]}))
    lines = render_fleet_report(tmp_path)
    text = "\n".join(lines)
    assert "fleet: hosts=2" in text and "fired_faults=[0]" in text
    assert "recovery: host 1 died at step 3" in text
    assert "clock offsets" in text and "host-1" in text
    assert text.index("fault_injected") < text.index("host_dead") \
        < text.index("restart_agreed")
    # The obs one-pager appends the same section for cluster dirs
    from byzantinemomentum_tpu.obs.report import render_report
    report = render_report(tmp_path)
    assert "fleet timeline" in report and "fault_injected" in report


def test_fleet_report_empty_for_plain_run_dir(tmp_path):
    assert render_fleet_report(tmp_path) == []


def test_study_fleet_timeline_frame(tmp_path):
    _synthetic_cluster_run(tmp_path, skew=10.0)
    import study

    frame = study.load_fleet_timeline(tmp_path)
    assert set(frame["source"]) >= {"launcher", "host-0", "host-1"}
    assert (frame["t"].diff().dropna() >= 0).all()  # causally ordered
    with pytest.raises(Exception, match="No fleet telemetry"):
        study.load_fleet_timeline(tmp_path / "empty")


# --------------------------------------------------------------------------- #
# Loadgen trace-collection mode (the ATTRIB_serve.json payload)

@pytest.mark.slow
def test_loadgen_trace_mode_payload():
    import importlib.util
    import pathlib
    import sys as _sys

    script = (pathlib.Path(__file__).resolve().parent.parent
              / "scripts" / "serve_loadgen.py")
    spec = importlib.util.spec_from_file_location("serve_loadgen", script)
    loadgen = importlib.util.module_from_spec(spec)
    _sys.modules.setdefault("serve_loadgen", loadgen)
    spec.loader.exec_module(loadgen)

    payload = loadgen.run_trace(requests=80, n=7, d=32, f=1,
                                overhead_pairs=1)
    assert payload["kind"] == "serve_attribution"
    phases = payload["phases"]
    for phase in ("queue", "pack", "dispatch", "resolver_wake", "device",
                  "resolve", "validate"):
        assert phase in phases and phases[phase]["p99_ms"] >= 0.0
    assert payload["tile"]["within_tolerance"], payload["tile"]
    assert payload["queue_depth"]["max"] >= 1
    assert 0.0 < payload["batch_occupancy"]["max"] <= 1.0
    assert "frac" in payload["overhead"]


def _serve_loadgen():
    import importlib.util
    import pathlib
    import sys
    script = (pathlib.Path(__file__).resolve().parent.parent
              / "scripts" / "serve_loadgen.py")
    spec = importlib.util.spec_from_file_location("serve_loadgen", script)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("serve_loadgen", mod)
    spec.loader.exec_module(mod)
    return mod


def test_loadgen_flash_scenario_keys_are_uniform():
    """The flash-crowd scenario stresses the ARRIVAL pattern (trickle
    then a connection burst in `_drive_flash`), deliberately NOT the key
    distribution — its routing keys stay uniform so a latency cliff in
    the burst phase can only come from arrival concentration."""
    loadgen = _serve_loadgen()
    assert loadgen.FLEET_SCENARIOS == ("rotation", "zipf", "churn",
                                       "flash")
    rng = np.random.default_rng(0)
    bases = loadgen._scenario_bases("flash", 16, 4, rng)
    assert bases == [f"fl{k % 4}" for k in range(16)]
    with pytest.raises(ValueError, match="unknown fleet scenario"):
        loadgen._scenario_bases("stampede", 16, 4, rng)


# --------------------------------------------------------------------------- #
# stale_edges (scripts/stale_edges.py, PR 15): the data-driven input the
# straggler-host bounded-wait policy needs

def _stale_edges():
    import importlib.util
    import pathlib
    import sys
    script = (pathlib.Path(__file__).resolve().parent.parent
              / "scripts" / "stale_edges.py")
    spec = importlib.util.spec_from_file_location("stale_edges", script)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("stale_edges", mod)
    spec.loader.exec_module(mod)
    return mod


def _liveness_stream(tmp_path, edges):
    """Write a synthetic launcher telemetry stream of liveness
    transitions: edges = [(t, host, from, to)]."""
    lines = [json.dumps({"t": t, "kind": "event",
                         "name": "liveness_transition",
                         "data": {"host": host, "from": frm, "to": to,
                                  "step": 1}})
             for t, host, frm, to in edges]
    (tmp_path / "telemetry.jsonl").write_text("\n".join(lines) + "\n")
    return tmp_path


def test_stale_edges_skewed_timeline(tmp_path, capsys):
    """The synthetic skewed timeline: fast recoveries (0.5-2 s) vs one
    slow death (12 s) plus a censored episode — the recommended bounded
    wait is p95(recoveries) * 1.25, and the censored episode is counted,
    never guessed."""
    stale_edges = _stale_edges()
    t = 100.0
    edges = [(t, h, None, "alive") for h in range(3)]
    for dt in (0.5, 1.0, 2.0):
        edges += [(t, 0, "alive", "stale"), (t + dt, 0, "stale", "alive")]
        t += 5.0
    edges += [(t, 1, "alive", "stale"), (t + 12.0, 1, "stale", "dead")]
    t += 20.0
    edges += [(t, 2, "alive", "stale")]  # unresolved at end of stream
    run = _liveness_stream(tmp_path, edges)

    episodes = stale_edges.stale_episodes(
        __import__("byzantinemomentum_tpu.obs.recorder",
                   fromlist=["load_records"]).load_records(run))
    assert episodes["recovered"] == [0.5, 1.0, 2.0]
    assert episodes["died"] == [12.0]
    assert episodes["censored"] == 1

    summary = stale_edges.summarize([run])
    assert summary["stale_to_alive"]["count"] == 3
    assert summary["stale_to_alive"]["p95_s"] == 2.0
    assert summary["stale_to_dead"]["median_s"] == 12.0
    assert summary["recommended_wait_s"] == 2.5  # p95 * 1.25

    assert stale_edges.main([str(run)]) == 0
    out = capsys.readouterr().out
    assert "recommended bounded wait: 2.5s" in out
    assert "stale-edges: " in out


def test_stale_edges_death_only_and_empty(tmp_path, capsys):
    """With only deaths on record there is nothing worth waiting for:
    the window stays strictly below the fastest observed death; an empty
    stream exits non-zero with no recommendation."""
    stale_edges = _stale_edges()
    run = _liveness_stream(tmp_path, [
        (10.0, 1, "alive", "stale"), (18.0, 1, "stale", "dead")])
    summary = stale_edges.summarize([run])
    assert summary["stale_to_alive"] is None
    assert summary["recommended_wait_s"] == 4.0  # min(death)/2

    empty = tmp_path / "empty"
    empty.mkdir()
    assert stale_edges.main([str(empty)]) == 1
    assert "no telemetry records" in capsys.readouterr().out


def test_stale_edges_machine_recommendation_block(tmp_path, capsys):
    """The `recommendation` block is what the straggler policy's
    `resolve_wait_bound` consumes: the window, its BASIS, and the
    evidence counts — censored episodes reported next to the p95 they
    were excluded from. `--json` prints exactly the machine line."""
    stale_edges = _stale_edges()
    t = 100.0
    edges = [(t, h, None, "alive") for h in range(3)]
    for dt in (0.5, 1.0, 2.0):
        edges += [(t, 0, "alive", "stale"), (t + dt, 0, "stale", "alive")]
        t += 5.0
    edges += [(t, 1, "alive", "stale"), (t + 12.0, 1, "stale", "dead")]
    t += 20.0
    edges += [(t, 2, "alive", "stale")]  # censored
    run = _liveness_stream(tmp_path, edges)
    assert stale_edges.summarize([run])["recommendation"] == {
        "wait_s": 2.5, "basis": "p95_recoveries", "recoveries": 3,
        "deaths": 1, "censored": 1, "margin": 1.25, "p95_recovery_s": 2.0}
    assert stale_edges.main(["--json", str(run)]) == 0
    out = capsys.readouterr().out
    assert out.startswith("stale-edges: ") and out.count("\n") == 1
    payload = json.loads(out[len("stale-edges: "):])
    assert payload["recommendation"]["wait_s"] == 2.5
    # Death-only record: half the fastest death, no margin fields
    death = tmp_path / "death"
    death.mkdir()
    _liveness_stream(death, [(10.0, 1, "alive", "stale"),
                             (18.0, 1, "stale", "dead")])
    assert stale_edges.summarize([death])["recommendation"] == {
        "wait_s": 4.0, "basis": "half_fastest_death", "recoveries": 0,
        "deaths": 1, "censored": 0}
    # No resolved episodes at all: explicit Nones, --json exits non-zero
    empty = tmp_path / "empty"
    empty.mkdir()
    rec = stale_edges.summarize([empty])["recommendation"]
    assert rec["wait_s"] is None and rec["basis"] is None
    assert stale_edges.main(["--json", str(empty)]) == 1


def test_stale_edges_unknown_edge_censors(tmp_path):
    stale_edges = _stale_edges()
    run = _liveness_stream(tmp_path, [
        (10.0, 0, "alive", "stale"), (15.0, 0, "stale", "unknown")])
    from byzantinemomentum_tpu.obs.recorder import load_records
    episodes = stale_edges.stale_episodes(load_records(run))
    assert episodes["recovered"] == [] and episodes["died"] == []
    assert episodes["censored"] == 1


# --------------------------------------------------------------------------- #
# Cross-process span join (r19): shard records splice into the router
# envelope clock-free

def _router_stamps(**extra):
    """recv -> routed -> reply at 0/1/21 ms (route 1 ms, rtt 20 ms)."""
    base = 500.0
    stamps = {"recv": base, "routed": base + 0.001, "reply": base + 0.021}
    stamps.update({k: base + v for k, v in extra.items()})
    return stamps


def _shard_record(**overrides):
    spans = {"parse": 0.5, "validate": 0.5, "queue": 5.0, "pack": 1.0,
             "dispatch": 1.0, "resolver_wake": 1.0, "device": 2.0,
             "resolve": 1.0}
    spans.update(overrides)
    return {"trace_id": "jt-1", "spans_ms": spans, "total_ms": 12.0}


def test_join_shard_trace_tiles_exactly():
    from byzantinemomentum_tpu.obs.trace import join_shard_trace
    joined = join_shard_trace(_router_stamps(), _shard_record())
    assert joined is not None
    spans = joined["spans_ms"]
    # parse+validate fold into one shard_frontend hop
    assert spans["shard_frontend"] == pytest.approx(1.0, abs=1e-4)
    assert spans["shard_queue"] == pytest.approx(5.0, abs=1e-4)
    assert spans["route"] == pytest.approx(1.0, abs=1e-4)
    # residual = rtt(20) - nested(12) = 8; spans tile recv->reply
    assert spans["wire_residual"] == pytest.approx(8.0, abs=1e-4)
    assert sum(spans.values()) == pytest.approx(joined["total_ms"],
                                                abs=1e-3)
    assert joined["dominant"] == "wire_residual"
    assert joined["trace_id"] == "jt-1"


def test_join_parked_stamp_pair_becomes_its_own_hop():
    from byzantinemomentum_tpu.obs.trace import join_shard_trace
    stamps = _router_stamps(parked=0.002, unparked=0.006)
    joined = join_shard_trace(stamps, _shard_record())
    spans = joined["spans_ms"]
    assert spans["parked"] == pytest.approx(4.0, abs=1e-4)
    # The park comes OUT of the wire residual, not the shard columns
    assert spans["wire_residual"] == pytest.approx(4.0, abs=1e-4)
    assert sum(spans.values()) == pytest.approx(joined["total_ms"],
                                                abs=1e-3)
    # No parked hop without both stamps / with zero dwell
    assert "parked" not in join_shard_trace(
        _router_stamps(parked=0.002), _shard_record())["spans_ms"]


def test_join_wire_residual_clamps_nonnegative():
    from byzantinemomentum_tpu.obs.trace import join_shard_trace
    # Shard timers sum past the envelope (scheduler quantum): clamp
    joined = join_shard_trace(_router_stamps(),
                              _shard_record(device=40.0))
    assert joined["spans_ms"]["wire_residual"] == 0.0


def test_join_malformed_records_degrade_to_none():
    from byzantinemomentum_tpu.obs.trace import join_shard_trace
    stamps = _router_stamps()
    assert join_shard_trace(stamps, None) is None
    assert join_shard_trace(stamps, "not-a-dict") is None
    assert join_shard_trace(stamps, {"spans_ms": [1, 2]}) is None
    assert join_shard_trace(stamps, _shard_record(queue=-1.0)) is None
    assert join_shard_trace(stamps, _shard_record(queue="5ms")) is None
    # No recognizable phase at all
    assert join_shard_trace(stamps, {"spans_ms": {"zstd": 1.0}}) is None
    # Incomplete router envelope tiles nothing
    partial = {"recv": 500.0, "reply": 500.021}
    assert join_shard_trace(partial, _shard_record()) is None


def test_join_unknown_phases_pass_through():
    from byzantinemomentum_tpu.obs.trace import JOINED_HOPS, join_shard_trace
    joined = join_shard_trace(_router_stamps(),
                              _shard_record(zstd=3.0))
    assert joined is not None
    # The unknown phase is skipped, not summed and not a column
    assert "zstd" not in joined["spans_ms"]
    assert joined["spans_ms"]["wire_residual"] == pytest.approx(
        8.0, abs=1e-4)
    assert set(joined["spans_ms"]) <= set(JOINED_HOPS)
    # Non-str trace ids are dropped rather than propagated
    record = _shard_record()
    record["trace_id"] = 7
    assert "trace_id" not in join_shard_trace(_router_stamps(), record)


def test_dominant_hop_deterministic():
    from byzantinemomentum_tpu.obs.trace import dominant_hop
    assert dominant_hop({}) is None
    assert dominant_hop({"a": 1.0, "b": 3.0, "c": 2.0}) == "b"
    # Ties break to the earliest-inserted name
    assert dominant_hop({"x": 2.0, "y": 2.0}) == "x"


def test_trace_buffer_summary_counts_critical_path():
    from byzantinemomentum_tpu.obs.trace import join_shard_trace
    buf = TraceBuffer(maxlen=16)
    for _ in range(3):
        buf.add(join_shard_trace(_router_stamps(), _shard_record()))
    buf.add(join_shard_trace(_router_stamps(),
                             _shard_record(queue=30.0)))
    summary = buf.summary()
    assert summary["critical_path"] == {"wire_residual": 3,
                                        "shard_queue": 1}
    assert summary["phases_ms"]["shard_queue"]["max"] >= 30.0
