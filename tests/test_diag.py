"""Aggregation-forensics tests: the in-jit GAR diagnostics path
(`ops/diag.py` + per-rule kernels), its engine threading
(`engine/step.py` / `engine/metrics.py::FORENSIC_COLUMNS`), the host-side
suspicion tracker (`obs/forensics.py`) and the `study.worker_heatmap`
rendering — including the two hard guarantees: the krum selection mask
agrees with the brute-force reference oracle, and `diagnostics=False`
lowers to the identical StableHLO as the pre-diagnostics kernels.
"""

import os

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu import losses, obs, ops
from byzantinemomentum_tpu.engine import (
    EngineConfig, FORENSIC_COLUMNS, STUDY_COLUMNS, build_engine)
from byzantinemomentum_tpu.ops import diag

from . import reference_oracles as oracle

RNG = np.random.default_rng(7)

# Every registered first-tier rule (the native tiers share the same
# diagnose kernels; 'template' deliberately declines its check)
DIAG_GARS = ("average", "median", "trmean", "phocas", "meamed", "krum",
             "bulyan", "aksel", "cge", "brute")


def rand_grads(n, d, outliers=0, shift=25.0):
    g = RNG.normal(size=(n, d)).astype(np.float32)
    for i in range(outliers):
        g[n - 1 - i] += shift
    return g


# --------------------------------------------------------------------------- #
# ops-level: schema, aggregate equality, oracle parity


@pytest.mark.parametrize("name", DIAG_GARS)
def test_diagnostics_aggregate_matches_plain(name):
    """`gar(..., diagnostics=True)[0]` computes the same aggregate as the
    plain call (the diagnostics kernel shares the math, it never forks the
    rule's semantics)."""
    G = rand_grads(11, 16, outliers=2)
    gar = ops.gars[name]
    agg0 = np.asarray(gar(G, f=2))
    agg1, _ = gar(G, f=2, diagnostics=True)
    np.testing.assert_allclose(np.asarray(agg1), agg0, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("name", DIAG_GARS)
def test_diagnostics_aux_schema_uniform(name):
    """One aux schema across every rule — the mixture-`lax.switch`
    requirement: same keys, shapes and dtypes."""
    n, d = 11, 8
    G = rand_grads(n, d)
    _, aux = ops.gars[name](G, f=2, diagnostics=True)
    assert set(aux) == set(diag.AUX_KEYS)
    assert aux["scores"].shape == (n,) and aux["scores"].dtype == jnp.float32
    assert aux["selection"].shape == (n,)
    assert aux["dist"].shape == (n, n)
    assert aux["trim_frac"].shape == (n,)
    # The distance geometry is real, not zero-filled, for every rule
    offdiag = ~np.eye(n, dtype=bool)
    assert np.all(np.asarray(aux["dist"])[offdiag] > 0)


@pytest.mark.parametrize("f", (1, 2, 3))
def test_krum_diag_selection_matches_oracle(f):
    """The krum diagnostics selection mask equals the brute-force
    selection from the PyTorch reference oracle: the m = n-f-2
    lowest-score workers under stable tie order, for f in {1, 2, 3}."""
    n, d = 11, 12
    G = rand_grads(n, d, outliers=f)
    scores = oracle.krum_scores(torch.tensor(G), f)
    order = sorted(range(n), key=lambda i: scores[i])  # stable
    expected = set(order[: n - f - 2])

    _, aux = ops.gars["krum"](G, f=f, diagnostics=True)
    selected = set(np.nonzero(np.asarray(aux["selection"]) > 0)[0].tolist())
    assert selected == expected
    # Scores agree with the oracle too (same metric, f32 tolerance)
    np.testing.assert_allclose(np.asarray(aux["scores"]),
                               np.asarray(scores, dtype=np.float32),
                               rtol=1e-4)


def test_brute_diag_selection_matches_oracle():
    """The brute diagnostics selection mask is the oracle's
    minimum-diameter subset."""
    import itertools
    import math

    n, d, f = 9, 6, 2
    G = rand_grads(n, d, outliers=2)
    dist = oracle.pairwise_dist_matrix(torch.tensor(G))
    best, best_diam = None, math.inf
    for combo in itertools.combinations(range(n), n - f):
        diam = max(dist[x, y].item()
                   for x, y in itertools.combinations(combo, 2))
        if diam < best_diam:
            best, best_diam = combo, diam
    _, aux = ops.gars["brute"](G, f=f, diagnostics=True)
    selected = tuple(np.nonzero(np.asarray(aux["selection"]) > 0)[0].tolist())
    assert selected == best


def test_trmean_trim_frac_flags_outlier():
    """A planted coordinate-wise outlier is trimmed on (almost) every
    coordinate; the central workers keep most of theirs."""
    G = rand_grads(9, 64, outliers=1, shift=50.0)
    _, aux = ops.gars["trmean"](G, f=2, diagnostics=True)
    trim = np.asarray(aux["trim_frac"])
    assert trim[8] > 0.95          # the outlier row: trimmed ~everywhere
    assert np.all(trim[:8] < 0.9)  # honest rows keep most coordinates
    # Clip fraction is a mean over bounded per-worker fractions
    assert 0.0 <= float(np.mean(trim)) <= 1.0


def test_distance_summary_and_ratio_helpers():
    """`diag.distance_summary` matches a numpy median over the
    honest-vs-all off-diagonal; `diag.var_norm_ratio` matches the study
    pipeline's (deviation/norm)² composition."""
    n, h, d = 9, 7, 16
    G = rand_grads(n, d, outliers=2)
    dist = np.asarray(ops._common.pairwise_distances(jnp.asarray(G)))
    vals = [dist[i, j] for i in range(h) for j in range(n) if j != i]
    vals.sort()
    dmin, dmed, dmax = diag.distance_summary(jnp.asarray(dist), rows=h)
    assert float(dmin) == pytest.approx(vals[0], rel=1e-6)
    assert float(dmed) == pytest.approx(vals[(len(vals) - 1) // 2], rel=1e-6)
    assert float(dmax) == pytest.approx(vals[-1], rel=1e-6)

    avg = G.mean(axis=0)
    dev2 = float(((G - avg) ** 2).sum() / (n - 1))
    expected = dev2 / float((avg ** 2).sum())
    assert float(diag.var_norm_ratio(jnp.asarray(G))) == pytest.approx(
        expected, rel=1e-5)


# --------------------------------------------------------------------------- #
# HLO identity: diagnostics OFF is byte-identical to the pre-change kernels


@pytest.mark.parametrize("name,f", (("krum", 2), ("bulyan", 2),
                                    ("brute", 2), ("trmean", 2),
                                    ("median", 2), ("cge", 2), ("aksel", 2)))
def test_hlo_identity_diagnostics_off_ops(name, f):
    """A `diagnostics=False` checked call lowers to the same StableHLO
    text as the raw kernel — the diagnostics machinery cannot perturb the
    hot path."""
    gar = ops.gars[name]
    spec = jax.ShapeDtypeStruct((11, 16), jnp.float32)
    raw = jax.jit(lambda G: gar.unchecked(G, f=f)).lower(spec).as_text()
    off = jax.jit(
        lambda G: gar(G, f=f, diagnostics=False)).lower(spec).as_text()
    assert raw == off


def _probe_engine(gar_diagnostics, defenses=("krum",), strip_diagnose=False):
    """A tiny 6-d engine over the probe model (same scheme as
    `test_engine.py`) for step-lowering comparisons."""
    from byzantinemomentum_tpu.models import ModelDef

    D = 6

    def init(key):
        return {"w": jnp.zeros((D,), jnp.float32)}, {}

    def apply(params, state, x, train=False, rng=None):
        return x, state

    loss = losses.Loss(lambda output, target, params:
                       jnp.dot(params, jnp.mean(output, axis=0)))
    defense_list = []
    freq = 0.0
    for name in defenses:
        gar = ops.gars[name]
        if strip_diagnose:
            gar = ops.GAR(gar.name, gar.unchecked, gar.check,
                          upper_bound=gar.upper_bound,
                          influence=gar.influence, diagnose=None)
        freq += 1.0
        defense_list.append((gar, freq, {}))
    cfg = EngineConfig(nb_workers=8, nb_decl_byz=1, nb_real_byz=0,
                       nb_for_study=8, nb_for_study_past=2,
                       gar_diagnostics=gar_diagnostics)
    engine = build_engine(cfg=cfg, model_def=ModelDef("probe", init, apply,
                                                      (D,)),
                          loss=loss, criterion=losses.Criterion("sigmoid"),
                          defenses=defense_list)
    return cfg, engine


def _lower_step_text(engine, cfg):
    S = cfg.nb_sampled
    state = engine.init(jax.random.PRNGKey(0),
                        params={"w": jnp.zeros((6,))}, net_state={})
    xs = jnp.zeros((S, 4, 6), jnp.float32)
    ys = jnp.zeros((S, 4), jnp.float32)
    return engine.train_step.lower(state, xs, ys,
                                   jnp.float32(0.1)).as_text()


def test_hlo_identity_diagnostics_off_engine_step():
    """The full train step with `gar_diagnostics=False` lowers to the same
    StableHLO as an engine whose GARs carry NO diagnose kernels at all
    (i.e. the pre-change program); turning diagnostics ON changes the
    lowering (the aux outputs exist)."""
    cfg_off, engine_off = _probe_engine(False)
    _, engine_pre = _probe_engine(False, strip_diagnose=True)
    assert _lower_step_text(engine_off, cfg_off) == \
        _lower_step_text(engine_pre, cfg_off)

    cfg_on, engine_on = _probe_engine(True)
    assert _lower_step_text(engine_on, cfg_on) != \
        _lower_step_text(engine_off, cfg_off)


# --------------------------------------------------------------------------- #
# Engine threading


def test_engine_step_emits_forensic_metrics():
    """With diagnostics on, the step's metric dict carries the forensic
    keys; the selection mask sums to the selected count and the scalar
    columns are finite."""
    cfg, engine = _probe_engine(True)
    state = engine.init(jax.random.PRNGKey(0),
                        params={"w": jnp.zeros((6,))}, net_state={})
    S = cfg.nb_sampled
    xs = jnp.asarray(RNG.normal(size=(S, 4, 6)).astype(np.float32))
    ys = jnp.zeros((S, 4), jnp.float32)
    _, metrics = engine.train_step(state, xs, ys, jnp.float32(0.1))
    for key in ("Sel mask", "Worker dist", "Dist honest med",
                "Var/norm ratio", "Clip frac"):
        assert key in metrics, key
    sel = np.asarray(metrics["Sel mask"])
    assert sel.shape == (cfg.nb_workers,)
    # krum at n=8, f=1 selects m = n-f-2 = 5 rows
    assert int((sel > 0).sum()) == 5
    assert np.isfinite(float(metrics["Dist honest med"]))
    assert np.isfinite(float(metrics["Var/norm ratio"]))


def test_engine_mixture_diagnostics_switch():
    """A --gars mixture with diagnostics on works through `lax.switch`
    (uniform aux schema across rules with different native kernels)."""
    cfg, engine = _probe_engine(True, defenses=("krum", "median"))
    state = engine.init(jax.random.PRNGKey(0),
                        params={"w": jnp.zeros((6,))}, net_state={})
    S = cfg.nb_sampled
    xs = jnp.asarray(RNG.normal(size=(S, 4, 6)).astype(np.float32))
    ys = jnp.zeros((S, 4), jnp.float32)
    _, metrics = engine.train_step(state, xs, ys, jnp.float32(0.1))
    assert np.asarray(metrics["Sel mask"]).shape == (cfg.nb_workers,)


def test_device_gar_hop_with_diagnostics():
    """The heterogeneous-placement step (`--device-gar`) threads the
    5-tuple defense output — diag metrics hop back with the Byzantine
    rows."""
    from byzantinemomentum_tpu.engine.step import make_device_gar_step

    cfg, engine = _probe_engine(True)
    step = make_device_gar_step(engine, "cpu")
    state = engine.init(jax.random.PRNGKey(0),
                        params={"w": jnp.zeros((6,))}, net_state={})
    S = cfg.nb_sampled
    xs = jnp.asarray(RNG.normal(size=(S, 4, 6)).astype(np.float32))
    ys = jnp.zeros((S, 4), jnp.float32)
    _, metrics = step(state, xs, ys, jnp.float32(0.1))
    assert np.asarray(metrics["Sel mask"]).shape == (cfg.nb_workers,)


def test_mesh_sharded_step_with_diagnostics():
    """`--mesh` composes with diagnostics: the sharded step (whose GARs
    are swapped for `_ShardedGar` facades) emits the forensic metrics —
    natively psum'd-Gram aux for the selection rules, the generic
    geometry fallback otherwise (oracle parity in `tests/test_lattice.py`)."""
    from byzantinemomentum_tpu.parallel import make_mesh, sharded_train_step

    cfg, engine = _probe_engine(True)
    mesh = make_mesh(2)
    state = engine.init(jax.random.PRNGKey(0),
                        params={"w": jnp.zeros((6,))}, net_state={})
    step = sharded_train_step(engine, mesh, state)
    S = cfg.nb_sampled
    xs = jnp.asarray(RNG.normal(size=(S, 4, 6)).astype(np.float32))
    ys = jnp.zeros((S, 4), jnp.float32)
    _, metrics = step(state, xs, ys, jnp.float32(0.1))
    sel = np.asarray(metrics["Sel mask"])
    assert sel.shape == (cfg.nb_workers,)
    assert np.isfinite(float(metrics["Var/norm ratio"]))


# --------------------------------------------------------------------------- #
# Suspicion tracker (obs/forensics.py)


def test_suspicion_tracker_flags_planted_byzantine(tmp_path):
    """A worker that is never selected and sits far from the cloud crosses
    the threshold and lands a `suspect_worker` event naming it on the
    active recorder; nobody else is flagged."""
    telemetry = obs.Telemetry(tmp_path)
    obs.activate(telemetry)
    try:
        tracker = obs.SuspicionTracker(6, min_steps=5)
        sel = np.array([1, 1, 1, 1, 1, 0], dtype=float)
        dist = np.array([1.0, 1.1, 0.9, 1.0, 1.05, 8.0])
        for step in range(50):
            tracker.update(step, sel, distances=dist)
    finally:
        obs.deactivate()
        telemetry.close()
    assert tracker.suspects == [5]
    assert tracker.max() == pytest.approx(tracker.suspicion[5])
    events = [r for r in obs.load_records(tmp_path)
              if r["kind"] == "event" and r["name"] == "suspect_worker"]
    assert [e["data"]["worker"] for e in events] == [5]


def test_suspicion_tracker_clears_on_recovery(tmp_path):
    """A flagged worker whose behavior normalizes decays below the clear
    threshold and emits `suspect_cleared` (hysteresis edge)."""
    telemetry = obs.Telemetry(tmp_path)
    obs.activate(telemetry)
    try:
        tracker = obs.SuspicionTracker(4, min_steps=5, alpha=0.2)
        bad = np.array([1, 1, 1, 0], dtype=float)
        good = np.ones(4)
        dist_bad = np.array([1.0, 1.0, 1.0, 9.0])
        dist_good = np.ones(4)
        for step in range(30):
            tracker.update(step, bad, distances=dist_bad)
        assert tracker.suspects == [3]
        for step in range(30, 120):
            tracker.update(step, good, distances=dist_good)
    finally:
        obs.deactivate()
        telemetry.close()
    assert tracker.suspects == []
    names = [r["name"] for r in obs.load_records(tmp_path)
             if r["kind"] == "event"]
    assert "suspect_worker" in names and "suspect_cleared" in names


def test_suspicion_tracker_quarantine_component():
    """The quarantine EWMA contributes: a worker repeatedly reported
    inactive accrues suspicion even while selected and central."""
    tracker = obs.SuspicionTracker(4, min_steps=1)
    sel = np.ones(4)
    active = np.array([1, 1, 1, 0], dtype=float)
    for step in range(60):
        tracker.update(step, sel, active=active)
    assert tracker.suspicion[3] > tracker.suspicion[:3].max()


def test_suspicion_tracker_validation():
    with pytest.raises(ValueError):
        obs.SuspicionTracker(4, alpha=0.0)
    with pytest.raises(ValueError):
        obs.SuspicionTracker(4, threshold=0.3, clear=0.5)


# --------------------------------------------------------------------------- #
# Driver e2e (the ISSUE acceptance criterion) + plots


@pytest.fixture
def small_synth(monkeypatch):
    monkeypatch.setenv("BMT_SYNTH_TRAIN", "512")
    monkeypatch.setenv("BMT_SYNTH_TEST", "128")


def test_driver_forensics_krum_empire_worker_momentum(tmp_path, small_synth):
    """CPU smoke config, empire attack under krum, momentum at the
    workers: once the worker momentum has warmed up, 'Sel workers' never
    includes an attacking worker (the paper's mechanism), the suspicion
    column is populated, and `worker_heatmap`/`suspicion_timeline` render
    from the run's output without error."""
    import matplotlib
    matplotlib.use("Agg")
    import pandas

    from byzantinemomentum_tpu.cli.attack import main

    resdir = tmp_path / "run"
    rc = main(["--nb-steps", "16", "--batch-size", "8",
               "--batch-size-test", "32", "--batch-size-test-reps", "2",
               "--evaluation-delta", "0", "--model", "simples-full",
               "--seed", "11", "--nb-workers", "9", "--nb-decl-byz", "2",
               "--nb-real-byz", "2", "--gar", "krum",
               "--attack", "empire", "--attack-args", "factor:1.1",
               "--momentum-at", "worker", "--nb-for-study", "7",
               "--nb-for-study-past", "2", "--gar-diagnostics",
               "--result-directory", str(resdir)])
    assert rc == 0
    header = (resdir / "study").read_text().split(os.linesep)[0]
    assert header == "# " + "\t".join(STUDY_COLUMNS + FORENSIC_COLUMNS)

    data = pandas.read_csv(resdir / "study", sep="\t", index_col=0)
    attackers = {7, 8}  # rows >= nb_honests = 7
    warm = [s for s in data.index if s >= 8]  # momentum warmed up
    assert warm
    for step in warm:
        cell = str(data.loc[step, "Sel workers"])
        selected = {int(t) for t in cell.split(";")} if cell != "-" else set()
        assert not (selected & attackers), (step, cell)
    # The headline ratio drops as worker momentum accumulates
    ratio = data["Var/norm ratio"].astype(float)
    assert float(ratio.iloc[-1]) < float(ratio.iloc[0])
    assert data["Suspicion max"].astype(float).between(0, 1).all()

    import study
    sess = study.Session(resdir)
    plot = study.worker_heatmap(sess)
    plot.save(tmp_path / "heatmap.png")
    plot.close()
    assert (tmp_path / "heatmap.png").stat().st_size > 0
    plot = study.suspicion_timeline(sess)
    plot.save(tmp_path / "suspicion.png")
    plot.close()
    assert (tmp_path / "suspicion.png").stat().st_size > 0
