"""`vgg*`/`densenet*` registry-tail extensions: torchvision architecture
parity via exact parameter-count pins plus forward/step smokes (the
reference exposes every torchvision model by name, reference
`experiments/model.py:40-90`; these pin the registry extending the same way
as `tests/test_resnet.py` does for the resnets)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byzantinemomentum_tpu import attacks, losses, models, ops
from byzantinemomentum_tpu.engine import EngineConfig, build_engine


@pytest.mark.parametrize("name,count1000", [
    ("vgg11", 132_863_336),
    ("vgg13", 133_047_848),
    ("vgg16", 138_357_544),
    ("vgg19", 143_667_240),
])
def test_vgg_param_counts_match_torchvision(name, count1000):
    assert models.build(name, num_classes=1000).param_count() == count1000


@pytest.mark.parametrize("name,count1000", [
    ("densenet121", 7_978_856),
    ("densenet169", 14_149_480),
    ("densenet201", 20_013_928),
])
def test_densenet_param_counts_match_torchvision(name, count1000):
    assert models.build(name, num_classes=1000).param_count() == count1000


def test_densenet121_forward_shapes_and_bn_state():
    model_def = models.build("densenet121")
    params, state = model_def.init(jax.random.PRNGKey(0))
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 32, 32, 3)),
                    jnp.float32)
    out, _ = model_def.apply(params, state, x, train=False,
                             rng=jax.random.PRNGKey(0))
    assert out.shape == (2, 10)
    out_t, new_state = model_def.apply(params, state, x, train=True,
                                       rng=jax.random.PRNGKey(1))
    assert np.isfinite(np.asarray(out_t)).all()
    changed = jax.tree.map(
        lambda a, b: bool(np.any(np.asarray(a) != np.asarray(b))),
        state, new_state)
    assert any(jax.tree.leaves(changed))


@pytest.mark.slow
def test_vgg11_forward_and_dropout():
    model_def = models.build("vgg11")
    params, state = model_def.init(jax.random.PRNGKey(0))
    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 32, 32, 3)),
                    jnp.float32)
    out, _ = model_def.apply(params, state, x, train=False,
                             rng=jax.random.PRNGKey(0))
    assert out.shape == (2, 10)
    # Train mode engages the classifier dropout: different keys, different
    # outputs; eval mode is deterministic
    a, _ = model_def.apply(params, state, x, train=True,
                           rng=jax.random.PRNGKey(1))
    b, _ = model_def.apply(params, state, x, train=True,
                           rng=jax.random.PRNGKey(2))
    assert not np.allclose(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_densenet121_training_step():
    model_def = models.build("densenet121")
    cfg = EngineConfig(nb_workers=3, nb_decl_byz=1, nb_real_byz=1,
                       nb_for_study=1, nb_for_study_past=1,
                       momentum=0.9, momentum_at="update", gradient_clip=2.0)
    engine = build_engine(
        cfg=cfg, model_def=model_def, loss=losses.Loss("crossentropy"),
        criterion=losses.Criterion("top-k"),
        defenses=[(ops.gars["median"], 1.0, {})],
        attack=attacks.attacks["empire"], attack_kwargs={"factor": 1.1})
    st = engine.init(jax.random.PRNGKey(0))
    xs = jnp.zeros((cfg.nb_sampled, 2, 32, 32, 3), jnp.float32)
    ys = jnp.zeros((cfg.nb_sampled, 2), jnp.int32)
    st, metrics = engine.train_step(st, xs, ys, jnp.float32(0.01))
    assert int(st.steps) == 1
    assert np.isfinite(float(metrics["Defense gradient norm"]))


def test_vgg_adaptive_avg_pool_matches_torch():
    """The adaptive pool underpinning the VGG classifier head equals
    torch.nn.AdaptiveAvgPool2d on both the replicating (input smaller than
    output) and averaging (larger, non-divisible) regimes."""
    import torch
    from byzantinemomentum_tpu.models.vgg import adaptive_avg_pool
    rng = np.random.default_rng(5)
    for hw in ((1, 1), (5, 5), (14, 14), (10, 13)):
        x = rng.normal(size=(2, *hw, 3)).astype(np.float32)
        got = np.asarray(adaptive_avg_pool(jnp.asarray(x), (7, 7)))
        ref = torch.nn.functional.adaptive_avg_pool2d(
            torch.from_numpy(x.transpose(0, 3, 1, 2)), (7, 7))
        np.testing.assert_allclose(
            got, ref.numpy().transpose(0, 2, 3, 1), rtol=1e-5, atol=1e-6)


def test_mobilenet_v2_param_count_matches_torchvision():
    assert models.build("mobilenet_v2",
                        num_classes=1000).param_count() == 3_504_872


def test_mobilenet_v2_forward_and_train_mode():
    model_def = models.build("mobilenet_v2")
    params, state = model_def.init(jax.random.PRNGKey(0))
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 32, 32, 3)),
                    jnp.float32)
    out, _ = model_def.apply(params, state, x, train=False,
                             rng=jax.random.PRNGKey(0))
    assert out.shape == (2, 10)
    out_t, new_state = model_def.apply(params, state, x, train=True,
                                       rng=jax.random.PRNGKey(1))
    assert np.isfinite(np.asarray(out_t)).all()
    changed = jax.tree.map(
        lambda a, b: bool(np.any(np.asarray(a) != np.asarray(b))),
        state, new_state)
    assert any(jax.tree.leaves(changed))
