"""Sequence/context parallelism tests: ring attention and Ulysses all-to-all
attention (`parallel/ring.py`) verified EXACT against dense attention on the
virtual 8-device CPU mesh, both as raw kernels and end-to-end through the
`transformer-classifier` model (`models/transformer.py`)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from byzantinemomentum_tpu.parallel.mesh import shard_map

from byzantinemomentum_tpu import losses, ops
from byzantinemomentum_tpu.engine import EngineConfig, build_engine
from byzantinemomentum_tpu.models import build as build_model
from byzantinemomentum_tpu.parallel import (
    dense_attention, ring_attention, ulysses_attention)

B, H, L, DH = 2, 8, 32, 4
P_SEQ = 8  # sequence-axis size = the virtual device count


def seq_mesh():
    devices = np.asarray(jax.devices()[:P_SEQ])
    return Mesh(devices, ("seq",))


def rand_qkv(seed=0):
    rng = np.random.default_rng(seed)
    return tuple(rng.normal(size=(B, H, L, DH)).astype(np.float32)
                 for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    q, k, v = rand_qkv(0)
    want = np.asarray(dense_attention(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=causal))
    mesh = seq_mesh()
    fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq", causal=causal),
        mesh=mesh, in_specs=(P(None, None, "seq"),) * 3,
        out_specs=P(None, None, "seq"))
    got = np.asarray(jax.jit(fn)(jnp.asarray(q), jnp.asarray(k),
                                 jnp.asarray(v)))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(causal):
    q, k, v = rand_qkv(1)
    want = np.asarray(dense_attention(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=causal))
    mesh = seq_mesh()
    fn = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "seq", causal=causal),
        mesh=mesh, in_specs=(P(None, None, "seq"),) * 3,
        out_specs=P(None, None, "seq"))
    got = np.asarray(jax.jit(fn)(jnp.asarray(q), jnp.asarray(k),
                                 jnp.asarray(v)))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_ring_attention_gradients_match_dense():
    """Backprop through the ring (ppermute + fori_loop online softmax) must
    agree with dense attention's gradients — training under sequence
    sharding is exact, not just inference."""
    q, k, v = rand_qkv(2)
    t = np.random.default_rng(3).normal(size=(B, H, L, DH)).astype(np.float32)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) * t)

    mesh = seq_mesh()
    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq", causal=True),
        mesh=mesh, in_specs=(P(None, None, "seq"),) * 3,
        out_specs=P(None, None, "seq"))

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) * t)

    args = (jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    g_want = jax.grad(loss_dense, argnums=(0, 1, 2))(*args)
    g_got = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(*args)
    for got, want in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=5e-5, atol=5e-6)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_transformer_sequence_sharded_matches_dense(impl):
    """The full transformer-classifier under sequence sharding (rows of the
    image sharded over the mesh) reproduces the single-device logits: local
    positional slices, per-chunk blocks and the psum'd mean pool compose
    exactly."""
    kwargs = dict(depth=2, dim=32, heads=8, num_classes=10,
                  input_shape=(32, 32, 3))
    dense_model = build_model("transformer-classifier", **kwargs)
    shard_model = build_model("transformer-classifier", attn_impl=impl,
                              **kwargs)
    params, _ = dense_model.init(jax.random.PRNGKey(4))
    x = np.random.default_rng(5).normal(
        size=(3, 32, 32, 3)).astype(np.float32)

    want, _ = dense_model.apply(params, {}, jnp.asarray(x), train=False)
    mesh = seq_mesh()
    fn = shard_map(
        lambda p, xb: shard_model.apply(p, {}, xb, train=False)[0],
        mesh=mesh, in_specs=(P(), P(None, "seq")), out_specs=P())
    got = jax.jit(fn)(params, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.slow
def test_transformer_engine_step():
    """transformer-classifier plugs into the full engine (vmapped workers,
    GAR, momentum) like any registered model."""
    model_def = build_model("transformer-classifier", depth=1, dim=16,
                            heads=2, input_shape=(28, 28, 1))
    cfg = EngineConfig(nb_workers=3, nb_decl_byz=1, nb_real_byz=0,
                       nb_for_study=0, momentum=0.9, momentum_at="update")
    engine = build_engine(
        cfg=cfg, model_def=model_def, loss=losses.Loss("nll"),
        criterion=losses.Criterion("top-k"),
        defenses=[(ops.gars["median"], 1.0, {})])
    state = engine.init(jax.random.PRNGKey(6))
    rng = np.random.default_rng(7)
    xs = jnp.asarray(rng.normal(size=(3, 4, 28, 28, 1)).astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 10, size=(3, 4)).astype(np.int32))
    new_state, _ = engine.train_step(state, xs, ys, jnp.float32(0.01))
    assert np.isfinite(np.asarray(new_state.theta)).all()
    assert int(new_state.steps) == 1


def test_ring_attention_bf16():
    """Low-precision inputs must trace (f32 accumulator carry) and match the
    f32 result to bf16 tolerance, with output dtype following the input."""
    q, k, v = rand_qkv(4)
    want = np.asarray(dense_attention(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=True))
    mesh = seq_mesh()
    fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq", causal=True),
        mesh=mesh, in_specs=(P(None, None, "seq"),) * 3,
        out_specs=P(None, None, "seq"))
    qb, kb, vb = (jnp.asarray(t).astype(jnp.bfloat16) for t in (q, k, v))
    got = jax.jit(fn)(qb, kb, vb)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got.astype(jnp.float32)), want,
                               rtol=5e-2, atol=5e-2)


def test_transformer_short_sequence_mean_pool():
    """Dense-path mean pool divides by the actual token count when the input
    is shorter than the configured seq_len."""
    model = build_model("transformer-classifier", depth=1, dim=16, heads=2,
                        input_shape=(28, 28, 1))
    params, state = model.init(jax.random.PRNGKey(0))
    x_short = np.random.default_rng(0).normal(
        size=(2, 14, 28, 1)).astype(np.float32)
    out_short, _ = model.apply(params, state, jnp.asarray(x_short))
    # Same tokens fed with seq_len=14 configured: identical pooled logits
    model14 = build_model("transformer-classifier", depth=1, dim=16, heads=2,
                          input_shape=(14, 28, 1))
    out14, _ = model14.apply(params, state, jnp.asarray(x_short))
    np.testing.assert_allclose(np.asarray(out_short), np.asarray(out14),
                               rtol=1e-5, atol=1e-6)
