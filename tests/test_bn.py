"""BatchNorm-under-vmap parity tests — SURVEY §7 hard-part #2.

The reference runs the S sampled workers *sequentially through one torch
module*, so BatchNorm running stats fold worker-after-worker within a step
(reference `experiments/model.py:246-248`, `models/empire.py:36-47`). The
TPU engine computes all workers under `jax.vmap` (every chain starts from
the shared pre-step stats) and reconstructs the sequential result with
`compose_bn_updates` (`engine/step.py`). These tests pin that algebra:

1. against a float64 numpy sequential fold (incl. multi-local-step chains),
2. against a live `torch.nn.BatchNorm2d` driven worker-by-worker,
3. end-to-end through the engine on `empire-cnn` vs a sequential re-apply,
4. train/eval smoke for `empire-cnn` and forward/step for `wide_resnet`.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
import torch

from byzantinemomentum_tpu import losses, ops
from byzantinemomentum_tpu.engine import EngineConfig, build_engine
from byzantinemomentum_tpu.engine.step import compose_bn_updates
from byzantinemomentum_tpu.models import build as build_model
from byzantinemomentum_tpu.models.core import (
    BN_MOMENTUM, batchnorm_apply, batchnorm_init)


def _sequential_fold(r0, stats, m=BN_MOMENTUM):
    """Reference-semantics oracle: fold batch stats one worker at a time,
    in float64 (reference `experiments/model.py:246-248`)."""
    r = np.asarray(r0, np.float64)
    for s in stats:
        r = (1.0 - m) * r + m * np.asarray(s, np.float64)
    return r


def test_compose_algebra_matches_sequential_fold():
    rng = np.random.default_rng(0)
    C, S = 4, 7
    m = BN_MOMENTUM
    r0 = rng.normal(size=(C,)).astype(np.float32)
    stats = rng.normal(size=(S, C)).astype(np.float32)
    # What each vmapped worker reports: its own one-step chain from r0
    per_worker = (1.0 - m) * r0 + m * stats
    out = compose_bn_updates(
        {"r": jnp.asarray(r0)}, {"r": jnp.asarray(per_worker)}, S)
    np.testing.assert_allclose(
        np.asarray(out["r"]), _sequential_fold(r0, stats),
        rtol=1e-5, atol=1e-6)


def test_compose_algebra_matches_sequential_fold_local_steps():
    """Multi-local-step chains: worker w's scan yields the running states
    new[w, 0..k-1], each chained from the previous within the worker but all
    rooted at the shared r0; the composed result must equal the worker-major
    sequential fold over all S*k batch stats."""
    rng = np.random.default_rng(1)
    C, S, K = 3, 4, 3
    m = BN_MOMENTUM
    r0 = rng.normal(size=(C,)).astype(np.float32)
    stats = rng.normal(size=(S, K, C)).astype(np.float32)
    chains = np.empty_like(stats)
    for w in range(S):
        prev = r0
        for j in range(K):
            prev = (1.0 - m) * prev + m * stats[w, j]
            chains[w, j] = prev
    out = compose_bn_updates(
        {"r": jnp.asarray(r0)}, {"r": jnp.asarray(chains)}, S, K)
    np.testing.assert_allclose(
        np.asarray(out["r"]),
        _sequential_fold(r0, stats.reshape(S * K, C)),
        rtol=1e-5, atol=1e-6)


def test_vmapped_bn_compose_matches_torch_sequential():
    """Drive a live torch BatchNorm2d worker-by-worker (exactly what the
    reference's per-worker backprops do to the module) and check both the
    per-worker normalized outputs and the final running stats."""
    rng = np.random.default_rng(2)
    S, B, H, W, C = 5, 6, 3, 3, 4
    x = rng.normal(size=(S, B, H, W, C)).astype(np.float32)
    gamma = rng.normal(size=(C,)).astype(np.float32)
    beta = rng.normal(size=(C,)).astype(np.float32)
    r_mean0 = rng.normal(size=(C,)).astype(np.float32)
    r_var0 = rng.uniform(0.5, 2.0, size=(C,)).astype(np.float32)

    params = {"gamma": jnp.asarray(gamma), "beta": jnp.asarray(beta)}
    state = {"mean": jnp.asarray(r_mean0), "var": jnp.asarray(r_var0)}
    outs, new_states = jax.vmap(
        lambda xb: batchnorm_apply(params, state, xb, train=True))(
            jnp.asarray(x))
    composed = compose_bn_updates(state, new_states, S)

    bn = torch.nn.BatchNorm2d(C, eps=1e-5, momentum=BN_MOMENTUM)
    with torch.no_grad():
        bn.weight.copy_(torch.from_numpy(gamma))
        bn.bias.copy_(torch.from_numpy(beta))
        bn.running_mean.copy_(torch.from_numpy(r_mean0))
        bn.running_var.copy_(torch.from_numpy(r_var0))
    bn.train()
    for w in range(S):
        xt = torch.from_numpy(x[w].transpose(0, 3, 1, 2))  # NCHW
        out_t = bn(xt).detach().numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(outs[w]), out_t,
                                   rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(composed["mean"]),
                               bn.running_mean.detach().numpy(),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(composed["var"]),
                               bn.running_var.detach().numpy(),
                               rtol=1e-4, atol=1e-6)


def _cnn_engine(nb_workers=4, nb_real_byz=1, nb_for_study=5, **kw):
    cfg = EngineConfig(
        nb_workers=nb_workers, nb_decl_byz=1, nb_real_byz=nb_real_byz,
        nb_for_study=nb_for_study, nb_for_study_past=1,
        momentum=0.9, momentum_at="update", gradient_clip=5.0, **kw)
    from byzantinemomentum_tpu import attacks
    engine = build_engine(
        cfg=cfg, model_def=build_model("empire-cnn"),
        loss=losses.Loss("nll"), criterion=losses.Criterion("top-k"),
        defenses=[(ops.gars["average"], 1.0, {})],
        attack=attacks.attacks["empire"], attack_kwargs={"factor": 1.1})
    return cfg, engine


@pytest.mark.slow
def test_empire_cnn_step_composes_bn_exactly():
    """One engine step on empire-cnn (with S = nb_for_study > nb_honests
    study extras, all of which update BN stats in the reference,
    `attack.py:764, 786`) must produce the same net_state as sequentially
    re-applying the model worker-by-worker with the same inputs and
    per-worker dropout keys."""
    cfg, engine = _cnn_engine()
    state = engine.init(jax.random.PRNGKey(3))
    S, B = cfg.nb_sampled, 4
    assert S > cfg.nb_honests  # the study-extra case is exercised
    rng = np.random.default_rng(4)
    xs = jnp.asarray(rng.normal(size=(S, B, 32, 32, 3)).astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 10, size=(S, B)).astype(np.int32))

    # Capture oracle inputs BEFORE the step: train_step donates its state
    _, _, *wkeys = jax.random.split(state.rng, S + 2)
    params = engine.unravel(jnp.copy(state.theta))
    st = jax.tree.map(jnp.copy, state.net_state)

    new_state, _ = engine.train_step(state, xs, ys, jnp.float32(0.01))

    # Sequential oracle: same per-worker keys as the engine's split
    for w in range(S):
        _, st = engine.model_def.apply(params, st, xs[w], train=True,
                                       rng=wkeys[w])
    for leaf_seq, leaf_eng in zip(jax.tree.leaves(st),
                                  jax.tree.leaves(new_state.net_state)):
        np.testing.assert_allclose(np.asarray(leaf_eng), np.asarray(leaf_seq),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_empire_cnn_local_steps_compose_bn_exactly():
    """Same oracle with nb_local_steps=2: stats must fold worker-major over
    every local step's batch (the capability the reference gates off,
    reference `attack.py:796-798`)."""
    cfg, engine = _cnn_engine(nb_local_steps=2)
    state = engine.init(jax.random.PRNGKey(5))
    S, K, B = cfg.nb_sampled, 2, 3
    lr = jnp.float32(0.01)
    rng = np.random.default_rng(6)
    xs = jnp.asarray(rng.normal(size=(S, K, B, 32, 32, 3)).astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 10, size=(S, K, B)).astype(np.int32))

    _, _, *wkeys = jax.random.split(state.rng, S + 2)
    st = jax.tree.map(jnp.copy, state.net_state)
    theta0 = jnp.copy(state.theta)

    new_state, _ = engine.train_step(state, xs, ys, lr)

    for w in range(S):
        # Replicate the local-step scan: theta descends locally, state chains
        th = theta0
        rngs = jax.random.split(wkeys[w], K)
        for j in range(K):
            def scalar_loss(t, x=xs[w, j], y=ys[w, j], r=rngs[j], s=st):
                out, new_s = engine.model_def.apply(
                    engine.unravel(t), s, x, train=True, rng=r)
                return engine.loss(out, y, t), new_s
            (_, st), g = jax.value_and_grad(scalar_loss, has_aux=True)(th)
            th = th - lr * g
    for leaf_seq, leaf_eng in zip(jax.tree.leaves(st),
                                  jax.tree.leaves(new_state.net_state)):
        np.testing.assert_allclose(np.asarray(leaf_eng), np.asarray(leaf_seq),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_empire_cnn_train_eval_smoke():
    """empire-cnn learns the synthetic CIFAR prototypes well above chance,
    and eval consumes the composed running stats without blowing up."""
    from byzantinemomentum_tpu import data
    cfg = EngineConfig(nb_workers=4, nb_decl_byz=1, nb_real_byz=0,
                       nb_for_study=0, momentum=0.9, momentum_at="update",
                       gradient_clip=5.0)
    engine = build_engine(
        cfg=cfg, model_def=build_model("empire-cnn"),
        loss=losses.Loss("nll"), criterion=losses.Criterion("top-k"),
        defenses=[(ops.gars["average"], 1.0, {})])
    trainset, testset = data.make_datasets("cifar10", 16, 64, seed=0)
    state = engine.init(jax.random.PRNGKey(7))
    for _ in range(30):
        xs, ys = zip(*(trainset.sample() for _ in range(cfg.nb_sampled)))
        state, _ = engine.train_step(
            state, jnp.asarray(np.stack(xs)), jnp.asarray(np.stack(ys)),
            jnp.float32(0.05))
    x, y = testset.sample()
    res = engine.eval_step(state.theta, state.net_state,
                           jnp.asarray(x), jnp.asarray(y))
    acc = float(res[0]) / float(res[1])
    assert np.isfinite(acc) and acc > 0.3  # 10 classes, chance = 0.1
    # Running stats did move off their init values
    assert not np.allclose(np.asarray(state.net_state["b1"]["mean"]), 0.0)


@pytest.mark.slow
def test_wide_resnet_forward_and_step():
    """wide_resnet builds, runs forward with the right output shape, and
    takes one finite training step (small depth/width for CI speed)."""
    model_def = build_model("wide_resnet-Wide_ResNet",
                           depth=10, widen_factor=1, dropout_rate=0.3,
                           num_classes=10)
    params, net_state = model_def.init(jax.random.PRNGKey(8))
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    out, _ = model_def.apply(params, net_state, x, train=False,
                             rng=jax.random.PRNGKey(0))
    assert out.shape == (2, 10)
    # Log-softmax outputs: rows sum to 1 in probability space
    np.testing.assert_allclose(np.exp(np.asarray(out)).sum(axis=1), 1.0,
                               rtol=1e-5)

    cfg = EngineConfig(nb_workers=3, nb_decl_byz=1, nb_real_byz=0,
                       nb_for_study=0, momentum=0.9, momentum_at="update")
    engine = build_engine(
        cfg=cfg, model_def=model_def, loss=losses.Loss("nll"),
        criterion=losses.Criterion("top-k"),
        defenses=[(ops.gars["median"], 1.0, {})])
    state = engine.init(jax.random.PRNGKey(9))
    rng = np.random.default_rng(10)
    xs = jnp.asarray(rng.normal(size=(3, 2, 32, 32, 3)).astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 10, size=(3, 2)).astype(np.int32))
    new_state, _ = engine.train_step(state, xs, ys, jnp.float32(0.01))
    assert np.isfinite(np.asarray(new_state.theta)).all()
    assert int(new_state.steps) == 1


def test_bn_new_state_keeps_state_dtype():
    """ADVICE r4 regression: train-mode BN must return `new_state` leaves in
    the state dtype (the f32 statistics used to leak through the running-stat
    fold, breaking the --nb-local-steps lax.scan carry under --dtype bf16)."""
    from byzantinemomentum_tpu.models.core import grouped_batchnorm_apply
    rng = np.random.default_rng(11)
    for dt in (jnp.bfloat16, jnp.float16, jnp.float32):
        params = {"gamma": jnp.ones((4,), dt), "beta": jnp.zeros((4,), dt)}
        state = {"mean": jnp.zeros((4,), dt), "var": jnp.ones((4,), dt)}
        x = jnp.asarray(rng.normal(size=(5, 3, 3, 4)).astype(np.float32), dt)
        _, new_state = batchnorm_apply(params, state, x, train=True)
        assert new_state["mean"].dtype == dt and new_state["var"].dtype == dt
        gp = {"gamma": jnp.ones((2, 4), dt), "beta": jnp.zeros((2, 4), dt)}
        xg = jnp.asarray(
            rng.normal(size=(5, 3, 3, 2, 4)).astype(np.float32), dt)
        _, new_g = grouped_batchnorm_apply(gp, state, xg, train=True)
        assert new_g["mean"].dtype == dt and new_g["var"].dtype == dt


@pytest.mark.slow
def test_empire_cnn_bf16_local_steps_carry():
    """ADVICE r4 regression (the reproduced failure): a BN model under
    --dtype bf16 with --nb-local-steps > 1 must trace — the scan carry's
    net_state dtype has to survive the per-local-step BN fold."""
    cfg, engine = _cnn_engine(nb_local_steps=2, dtype="bfloat16")
    state = engine.init(jax.random.PRNGKey(12))
    S, K, B = cfg.nb_sampled, 2, 2
    rng = np.random.default_rng(13)
    xs = jnp.asarray(rng.normal(size=(S, K, B, 32, 32, 3)).astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 10, size=(S, K, B)).astype(np.int32))
    new_state, _ = engine.train_step(state, xs, ys, jnp.float32(0.01))
    assert new_state.theta.dtype == jnp.bfloat16
    for leaf in jax.tree.leaves(new_state.net_state):
        assert leaf.dtype == jnp.bfloat16
        assert np.isfinite(np.asarray(leaf, np.float32)).all()


def test_bn_f64_statistics_stay_f64():
    """ADVICE r4: under x64, f64 activations get f64 (centered two-pass)
    batch statistics — not silently-f32 one-pass ones. An f32 run of the
    same values differs from the f64 oracle by ~1e-8; the f64 run must agree
    to ~1e-12."""
    from byzantinemomentum_tpu.models.core import _bn_train
    # `jax.enable_x64` is top-level only on recent jax; older releases
    # ship the same context manager under jax.experimental
    enable_x64 = getattr(jax, "enable_x64", None)
    if enable_x64 is None:
        from jax.experimental import enable_x64
    with enable_x64(True):
        rng = np.random.default_rng(14)
        # Ill-conditioned regime: |mean| >> std, where one-pass f32 cancels
        x = (1000.0 + rng.normal(size=(64, 4), scale=1e-2)).astype(np.float64)
        gamma = np.ones((4,), np.float64)
        beta = np.zeros((4,), np.float64)
        _, mean, var = _bn_train(1)(jnp.asarray(gamma), jnp.asarray(beta),
                                    jnp.asarray(x))
        assert mean.dtype == jnp.float64 and var.dtype == jnp.float64
        np.testing.assert_allclose(np.asarray(mean), x.mean(axis=0), rtol=1e-12)
        np.testing.assert_allclose(np.asarray(var), x.var(axis=0), rtol=1e-9)


@pytest.mark.parametrize("n_param_dims,shape", [
    (1, (6, 5, 5, 7)),        # per-worker BN: x (B, H, W, C)
    (2, (6, 5, 5, 3, 7)),     # grouped BN: x (B, H, W, S, C)
])
def test_bn_custom_vjp_matches_autodiff(n_param_dims, shape):
    """The hand-written BN backward (`models/core.py::_bn_train`) equals
    autodiff of an equivalent straight-line implementation — INCLUDING the
    mean/var primal outputs' cotangent terms, which the training step never
    exercises (new_state is an aux output there) but the VJP must still get
    right for any other consumer."""
    from byzantinemomentum_tpu.models.core import BN_EPS, _bn_train

    pshape = shape[-n_param_dims:]
    rng = np.random.default_rng(3)
    gamma = jnp.asarray(rng.normal(1.0, 0.1, pshape).astype(np.float32))
    beta = jnp.asarray(rng.normal(0.0, 0.1, pshape).astype(np.float32))
    x = jnp.asarray(rng.normal(size=shape).astype(np.float32))

    def plain(gamma, beta, x):
        axes = tuple(range(x.ndim - n_param_dims))
        cnt = np.prod(shape[:len(axes)])
        mean = jnp.sum(x, axis=axes) / cnt
        var = jnp.maximum(jnp.sum(x * x, axis=axes) / cnt - mean * mean, 0.0)
        inv = jax.lax.rsqrt(var + BN_EPS)
        return (x - mean) * inv * gamma + beta, mean, var

    # Scalar consumer touching ALL THREE primal outputs with distinct
    # weights, so dy, dmean and dvar cotangents are all nonzero
    def consume(fn):
        def f(gamma, beta, x):
            out, mean, var = fn(gamma, beta, x)
            return (jnp.sum(jnp.sin(out)) + 2.0 * jnp.sum(mean * mean)
                    + 3.0 * jnp.sum(jnp.cos(var)))
        return f

    g_ref = jax.grad(consume(plain), argnums=(0, 1, 2))(gamma, beta, x)
    g_got = jax.grad(consume(_bn_train(n_param_dims)), argnums=(0, 1, 2))(
        gamma, beta, x)
    for a, b, name in zip(g_got, g_ref, ("dgamma", "dbeta", "dx")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5, err_msg=name)
