"""Concurrency-contract suite: the BMT-T lock-set rules (violating +
clean fixture pair per rule, role/lock-set inference details, the noqa
contract, the repo-wide clean gate, CLI exit codes) and the
deterministic interleaving harness (`analysis/schedule.py`): replayable
schedules, exhaustive bounded-preemption exploration, deadlock
detection, the planted serve-counter lost-update regression, and the
schedule models of the real `MicroBatcher` flush/submit surface and the
real `ClientSuspicionStore` admission-hold invariant.

Everything here is host-only (no jax import): the T-rules are pure AST
and the harness is pure stdlib, so this file runs even where no backend
initializes.
"""

import pathlib

import numpy as np
import pytest

from byzantinemomentum_tpu.analysis import concurrency, lint, schedule
from byzantinemomentum_tpu.analysis.__main__ import main as analysis_main
from byzantinemomentum_tpu.obs.forensics import ClientSuspicionStore

ROOT = pathlib.Path(__file__).resolve().parent.parent


# --------------------------------------------------------------------------- #
# BMT-T fixtures: one violating + one clean pair per rule. The T01 pair
# is the REAL pre-fix `serve/service.py` counter pattern (PR 8-13): the
# submitter bumps `_requests`, the escaped resolver callback bumps
# `_served`, the heartbeat thread reads both — no lock anywhere.

T_FIXTURES = {
    "BMT-T01": (
        """
import threading

class AggregationService:
    def __init__(self, batcher_cls):
        self._requests = 0
        self._served = 0
        self.batcher = batcher_cls(self._resolve)
        self._beat_thread = threading.Thread(target=self._beat_loop,
                                             daemon=True)
        self._beat_thread.start()

    def submit(self, request):
        self._requests += 1
        return self.batcher.submit(request)

    def _resolve(self, out, requests):
        for _ in requests:
            self._served += 1

    def stats(self):
        return {"requests": self._requests, "served": self._served}

    def _beat_loop(self):
        while True:
            self._write_heartbeat()

    def _write_heartbeat(self):
        return self.stats()
""",
        """
import threading

class AggregationService:
    def __init__(self, batcher_cls):
        self._stats_lock = threading.Lock()
        self._requests = 0
        self._served = 0
        self.batcher = batcher_cls(self._resolve)
        self._beat_thread = threading.Thread(target=self._beat_loop,
                                             daemon=True)
        self._beat_thread.start()

    def submit(self, request):
        with self._stats_lock:
            self._requests += 1
        return self.batcher.submit(request)

    def _resolve(self, out, requests):
        for _ in requests:
            with self._stats_lock:
                self._served += 1

    def stats(self):
        with self._stats_lock:
            return {"requests": self._requests, "served": self._served}

    def _beat_loop(self):
        while True:
            self._write_heartbeat()

    def _write_heartbeat(self):
        return self.stats()
""",
    ),
    "BMT-T02": (
        """
import threading

class Store:
    def __init__(self):
        self._read_lock = threading.Lock()
        self._write_lock = threading.Lock()
        self._count = 0
        threading.Thread(target=self._worker, daemon=True).start()

    def bump(self):
        with self._read_lock:
            self._count += 1

    def _worker(self):
        with self._write_lock:
            self._count += 1
""",
        """
import threading

class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        threading.Thread(target=self._worker, daemon=True).start()

    def bump(self):
        with self._lock:
            self._count += 1

    def _worker(self):
        with self._lock:
            self._count += 1
""",
    ),
    "BMT-T03": (
        """
import threading

class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        threading.Thread(target=self._worker, daemon=True).start()

    def forward(self):
        with self._a:
            with self._b:
                return 1

    def _worker(self):
        with self._b:
            with self._a:
                return 2
""",
        """
import threading

class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        threading.Thread(target=self._worker, daemon=True).start()

    def forward(self):
        with self._a:
            with self._b:
                return 1

    def _worker(self):
        with self._a:
            with self._b:
                return 2
""",
    ),
    "BMT-T04": (
        """
import threading
import time

class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0
        threading.Thread(target=self._worker, daemon=True).start()

    def read(self):
        with self._lock:
            time.sleep(0.1)
            return self._value

    def _worker(self):
        with self._lock:
            self._value += 1
""",
        """
import threading
import time

class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0
        threading.Thread(target=self._worker, daemon=True).start()

    def read(self):
        time.sleep(0.1)
        with self._lock:
            return self._value

    def _worker(self):
        with self._lock:
            self._value += 1
""",
    ),
    "BMT-T05": (
        """
import threading

def spawn(fn):
    t = threading.Thread(target=fn)
    t.start()
    return t
""",
        """
import threading

def spawn(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()
    return t
""",
    ),
}


@pytest.mark.parametrize("rule_id", sorted(T_FIXTURES))
def test_t_rule_fixture_pair(rule_id):
    """Every T-rule fires on its violating fixture and stays silent on
    the clean one (and the clean one trips no OTHER rule either)."""
    bad, good = T_FIXTURES[rule_id]
    hits = {v.rule for v in lint.lint_source(bad)}
    assert rule_id in hits, f"{rule_id} missed its violating fixture"
    clean = lint.lint_source(good)
    assert clean == [], f"clean fixture not clean: {clean}"


def test_t01_names_the_race_precisely():
    """The T01 report carries the class, attribute, writing method, its
    role, and the other roles touching the attribute — the triage facts."""
    bad, _ = T_FIXTURES["BMT-T01"]
    hits = [v for v in lint.lint_source(bad) if v.rule == "BMT-T01"]
    attrs = {v.message.split()[0] for v in hits}
    assert attrs == {"AggregationService._requests",
                     "AggregationService._served"}
    served = next(v for v in hits if "_served" in v.message)
    assert "escape:_resolve" in served.message
    assert "thread:_beat_loop" in served.message


def test_t05_joined_thread_is_clean():
    """The non-daemon form is fine when the owner joins it (the join is
    the shutdown path)."""
    src = """
import threading

class Owner:
    def start(self):
        self._worker = threading.Thread(target=self._run)
        self._worker.start()

    def _run(self):
        return 1

    def close(self):
        self._worker.join()
"""
    assert lint.lint_source(src) == []


# --------------------------------------------------------------------------- #
# Role / lock-set inference details

def _classes(src):
    return concurrency.module_classes(lint.Module("<t>", src))


def test_escaped_callback_role_and_propagation():
    """A bound method handed out by reference gets its own role, and
    roles propagate along same-class calls — the `serve/service.py`
    shape that motivated the analysis."""
    src, _ = T_FIXTURES["BMT-T01"]
    (cls,) = _classes(src)
    assert "escape:_resolve" in cls.roles["_resolve"]
    assert "thread:_beat_loop" in cls.roles["_beat_loop"]
    # stats is public (caller) AND reachable from the heartbeat thread
    assert {"caller", "thread:_beat_loop"} <= cls.roles["stats"]
    assert "thread:_beat_loop" in cls.roles["_write_heartbeat"]


def test_inherited_locks_through_call_sites():
    """A helper only ever called under `with self._cond:` is analyzed as
    guarded — the `MicroBatcher._due` idiom must not false-positive."""
    src = """
import collections
import threading

class Batcher:
    def __init__(self):
        self._cond = threading.Condition()
        self._queues = {}
        self._flusher = threading.Thread(target=self._flush_loop,
                                         daemon=True)
        self._flusher.start()

    def submit(self, request):
        with self._cond:
            self._queues.setdefault(request.cell,
                                    collections.deque()).append(request)
            self._cond.notify()

    def depth(self):
        with self._cond:
            return sum(len(q) for q in self._queues.values())

    def _due(self):
        for cell, q in self._queues.items():
            if q:
                return cell
        return None

    def _flush_loop(self):
        while True:
            with self._cond:
                cell = self._due()
                if cell is None:
                    self._cond.wait()
"""
    assert lint.lint_source(src) == []
    (cls,) = _classes(src)
    assert cls.inherited["_due"] == {"_cond"}
    # And the Condition counts as the majority guard of _queues
    accs = cls.accesses["_queues"]
    assert all("_cond" in locks for _, _, locks, _, _ in accs)


def test_queue_attr_is_exempt():
    """`queue.Queue` attributes carry their own lock: cross-thread
    put/get on one is not a T01."""
    src = """
import queue
import threading

class Pipe:
    def __init__(self):
        self._inflight = queue.Queue()
        threading.Thread(target=self._drain, daemon=True).start()

    def push(self, item):
        self._inflight.put(item)

    def _drain(self):
        while True:
            self._inflight.get()
"""
    assert lint.lint_source(src) == []


def test_unthreaded_module_is_skipped():
    """A module that never imports threading/socketserver analyzes to
    nothing — shared-looking attributes in it are single-threaded."""
    src = """
class Accumulator:
    def __init__(self):
        self.total = 0

    def bump(self):
        self.total += 1
"""
    assert lint.lint_source(src) == []
    assert _classes(src) == []


def test_handler_class_role():
    """`handle` of a RequestHandler subclass is a per-connection thread
    under ThreadingTCPServer: its unguarded writes against caller reads
    are T01."""
    src = """
import socketserver

class Handler(socketserver.StreamRequestHandler):
    served = 0

    def handle(self):
        type(self).served += 1

class Counter:
    def __init__(self, server):
        self.server = server
"""
    # type(self).served is a class-attribute write — out of the self.*
    # surface, so this exact shape is NOT flagged (documented limit)...
    assert all(v.rule != "BMT-T01" for v in lint.lint_source(src))
    # ...but a self-attribute version is:
    src2 = """
import socketserver

class Handler(socketserver.StreamRequestHandler):
    def handle(self):
        self.hits = getattr(self, "hits", 0) + 1
        self.report()

    def report(self):
        return self.hits
"""
    (cls,) = _classes(src2)
    assert "handler" in cls.roles["handle"]


def test_t_noqa_contract():
    """T suppressions follow the PR 5 contract: a reasoned noqa
    suppresses, a reasonless one is BMT-E00 (and does not suppress),
    a rotten one is BMT-E09."""
    bad, good = T_FIXTURES["BMT-T04"]
    annotated = bad.replace(
        "time.sleep(0.1)",
        "time.sleep(0.1)  # bmt: noqa[BMT-T04] poller cadence IS the contract here")
    assert lint.lint_source(annotated) == []
    reasonless = bad.replace("time.sleep(0.1)",
                             "time.sleep(0.1)  # bmt: noqa[BMT-T04]")
    rules = {v.rule for v in lint.lint_source(reasonless)}
    assert rules == {"BMT-E00", "BMT-T04"}
    rotten = good.replace(
        "with self._lock:\n            return self._value",
        "with self._lock:\n            return self._value  # bmt: noqa[BMT-T04] sleep holds the lock")
    assert {v.rule for v in lint.lint_source(rotten)} == {"BMT-E09"}


def test_repo_thread_surface_is_t_clean():
    """The whole package + scripts pass the T-rules with zero
    unannotated hits — the day-one findings (the serve counter races)
    are fixed, everything else is reasoned."""
    t_rules = {r for r in lint.RULES if r.startswith("BMT-T")}
    violations = lint.lint_paths(
        [ROOT / "byzantinemomentum_tpu", ROOT / "scripts"],
        rules=t_rules | {"BMT-E00"})
    assert violations == [], lint.format_human(violations)


def test_cli_exit_code_on_t_hit(tmp_path, capsys):
    """The analysis CLI exits 1 on a T violation, and --rules lists the
    E-, H-, and T-families in one table."""
    dirty = tmp_path / "dirty.py"
    dirty.write_text(T_FIXTURES["BMT-T01"][0])
    assert analysis_main([str(dirty)]) == 1
    clean = tmp_path / "clean.py"
    clean.write_text(T_FIXTURES["BMT-T01"][1])
    assert analysis_main([str(clean)]) == 0
    capsys.readouterr()
    assert analysis_main(["--rules"]) == 0
    table = capsys.readouterr().out
    for rule_id in ("BMT-E01", "BMT-H01", "BMT-T01", "BMT-T05"):
        assert rule_id in table, f"--rules table is missing {rule_id}"


# --------------------------------------------------------------------------- #
# The interleaving harness

def test_schedule_replay_is_deterministic():
    r = schedule.explore(schedule.lost_update_model, max_preemptions=3)
    assert r.failures, "bounded exploration must find the lost update"
    witness = r.failures[0]
    again = schedule.run_schedule(schedule.lost_update_model,
                                  witness.schedule)
    assert again.schedule == witness.schedule
    assert again.error == witness.error
    assert "lost update" in again.error


def test_lost_update_found_within_one_preemption():
    """The planted race needs exactly one preemption (the `+=` window):
    the cheapest possible exploration already finds it."""
    r = schedule.explore(schedule.lost_update_model, max_preemptions=1)
    assert r.failures and r.exhausted
    assert min(f.preemptions for f in r.failures) == 1


def test_fixed_counter_is_schedule_clean():
    """The stats-lock pattern (the PR 14 `AggregationService` fix)
    survives EXHAUSTIVE 2-thread/3-preemption exploration."""
    r = schedule.explore(schedule.fixed_counter_model, max_preemptions=3)
    assert r.exhausted and not r.failures
    assert r.runs > 1  # the lock still leaves schedule choices


def test_unpreempted_schedule_passes_even_prefix():
    """Serial execution of the pre-fix pattern is correct — the bug IS
    the interleaving, which is why it hid until the harness."""
    serial = schedule.run_schedule(schedule.lost_update_model, "")
    assert serial.ok and serial.preemptions == 0


def test_deadlock_detection_with_schedule():
    def abba(sched):
        a, b = sched.lock(), sched.lock()

        def t0():
            with a:
                with b:
                    pass

        def t1():
            with b:
                with a:
                    pass

        return [t0, t1], lambda: None

    r = schedule.explore(abba, max_preemptions=2)
    deadlocks = [f for f in r.failures if "DeadlockError" in f.error]
    assert deadlocks, "ABBA must deadlock under some schedule"
    # The failing schedule replays to the same deadlock
    again = schedule.run_schedule(abba, deadlocks[0].schedule)
    assert "DeadlockError" in again.error


def test_random_walks_are_seeded():
    a = schedule.random_walks(schedule.lost_update_model, runs=50, seed=7)
    b = schedule.random_walks(schedule.lost_update_model, runs=50, seed=7)
    assert [f.schedule for f in a.failures] == \
        [f.schedule for f in b.failures]
    assert a.failures, "50 seeded walks find the 1-preemption race"


def test_straggle_window_claim_race_found_and_shipped_fix_clean():
    """The `StraggleResumer` disposition contract under the harness:
    the unguarded check-then-act shape lets a cancelled window still
    SIGCONT (double disposition) within ONE preemption, the witness
    replays, serial orders pass (the bug IS the interleaving), and the
    shipped claim-under-lock pattern is exhaustively clean at a deeper
    bound."""
    r = schedule.explore(schedule.straggle_claim_unguarded_model,
                         max_preemptions=1)
    assert r.failures and r.exhausted
    assert min(f.preemptions for f in r.failures) == 1
    witness = r.failures[0]
    again = schedule.run_schedule(
        schedule.straggle_claim_unguarded_model, witness.schedule)
    assert again.schedule == witness.schedule
    assert again.error == witness.error
    assert "disposed 2 times" in again.error
    serial = schedule.run_schedule(
        schedule.straggle_claim_unguarded_model, "")
    assert serial.ok and serial.preemptions == 0
    clean = schedule.explore(schedule.straggle_claim_model,
                             max_preemptions=3)
    assert clean.exhausted and not clean.failures
    assert clean.runs > 1  # the lock still leaves schedule choices


def test_selfcheck_proves_the_pair_quickly():
    report = schedule.selfcheck()
    assert report["ok"]
    assert report["lost_update_found"] and report["fixed_clean"]
    assert report["straggle_fixed_clean"]
    assert report["exhausted"]
    assert report["seconds"] < 10.0, "the tier smoke must stay cheap"
    # The witness is a replayable schedule string
    replay = schedule.run_schedule(schedule.lost_update_model,
                                   report["witness"])
    assert not replay.ok


# --------------------------------------------------------------------------- #
# The harness applied to the real thread surfaces

def _microbatcher_model(sched):
    """The `serve/batching.py` flush/submit surface, reduced to its race
    skeleton: per-cell deques guarded by ONE condition, a flusher that
    drains due cells, submitters that append and notify, close() as the
    shutdown handshake. Invariant: every submitted request is flushed
    exactly once, and the flusher terminates."""
    cond = sched.condition()
    state = {"queues": [], "closed": False, "flushed": []}

    def submitter():
        for i in range(2):
            with cond:
                state["queues"].append(i)
                cond.notify()
        with cond:
            state["closed"] = True
            cond.notify()

    def flusher():
        while True:
            with cond:
                while not state["queues"] and not state["closed"]:
                    cond.wait()
                batch, state["queues"] = state["queues"], []
                done = state["closed"] and not state["queues"]
            if batch:
                state["flushed"].extend(batch)   # dispatch: outside the lock
            if done and not batch:
                return

    def check():
        assert state["flushed"] == [0, 1], state["flushed"]
        assert state["closed"]

    return [submitter, flusher], check


def test_microbatcher_flush_submit_surface_is_schedule_clean():
    r = schedule.explore(_microbatcher_model, max_preemptions=2)
    assert r.exhausted and not r.failures, r.failures[:3]
    assert r.runs > 10  # the surface has real interleavings to survive


def _unlocked_microbatcher_model(sched):
    """The same surface WITHOUT the condition: the check-then-drain on
    the shared queue loses submissions under preemption — the harness
    finds it (the negative control for the model above)."""
    state = {"queues": [], "flushed": [], "submitted": 0}

    def submitter():
        for i in range(2):
            queued = state["queues"]          # read
            sched.point()                     # ... preempted ...
            state["queues"] = queued + [i]    # write-back loses the drain
            state["submitted"] += 1

    def flusher():
        for _ in range(3):
            sched.point()
            batch, state["queues"] = state["queues"], []
            state["flushed"].extend(batch)

    def check():
        lost = state["submitted"] - len(state["flushed"]) \
            - len(state["queues"])
        assert lost == 0, f"{lost} submission(s) lost"

    return [submitter, flusher], check


def test_unlocked_queue_loses_submissions():
    r = schedule.explore(_unlocked_microbatcher_model, max_preemptions=2)
    assert r.failures, "the unguarded queue swap must lose a submission"
    assert any("lost" in f.error for f in r.failures)


def _store_model(sched):
    """The REAL `ClientSuspicionStore` under the service's
    `_suspicion_lock` discipline: two submitter threads fold cohorts in
    under one lock, with client "c2" admission-masked. Invariants (on
    every schedule): every observe landed (no lost EWMA update — each
    client's observation count is exact) and the admission-hold
    contract: the masked client's collusion EWMA stays EXACTLY zero
    while colluding clients c0/c1 accumulate evidence."""
    store = ClientSuspicionStore(weights=(0.4, 0.2, 0.2, 0.2), min_obs=1)
    lock = sched.lock()
    clients = ("c0", "c1", "c2")
    # c0/c1 are near-duplicates (colluding); c2 sits far away and is
    # admission-masked, so its collusion EWMA must HOLD, not decay
    dist = np.array([[np.inf, 0.01, 1.0],
                     [0.01, np.inf, 1.0],
                     [1.0, 1.0, np.inf]])
    selection = np.array([1.0, 1.0, 0.0])
    active = np.array([True, True, False])

    def submitter():
        for _ in range(2):
            with lock:
                store.observe(clients, selection,
                              distances=np.array([0.5, 0.5, 1.0]),
                              active=active, dist=dist)
            sched.point()

    def check():
        for client in clients:
            verdict = store.verdict(client)
            assert verdict["observations"] == 4, (client, verdict)
        assert store.verdict("c2")["collusion"] == 0.0
        assert store.verdict("c0")["collusion"] > 0.0
        assert store.verdict("c1")["collusion"] > 0.0
        assert store.requests == 4

    return [submitter, submitter], check


def test_suspicion_store_admission_hold_under_schedules():
    r = schedule.explore(_store_model, max_preemptions=2)
    assert r.exhausted and not r.failures, r.failures[:3]


def _fixed_service_stats_model(sched):
    """The FIXED `AggregationService` stats path end to end: a submitter
    bumps `_requests` under the stats lock and hands work over; the
    resolver bumps `_served` under the same lock; a reader snapshots
    under the lock. Coherence: within one snapshot `served <= requests`,
    and the final counts are exact — race-free under the same schedules
    that break the pre-fix pattern."""
    class Service:
        def __init__(self):
            self._stats_lock = sched.lock()
            self._cond = sched.condition()   # the batcher hand-off
            self._requests = 0
            self._served = 0
            self.pending = 0

        def submit(self):
            with self._stats_lock:
                value = self._requests
                sched.point()
                self._requests = value + 1
            with self._cond:
                self.pending += 1
                self._cond.notify()

        def resolve_loop(self):
            resolved = 0
            while resolved < 2:
                with self._cond:
                    while self.pending == 0:
                        self._cond.wait()
                    self.pending -= 1
                with self._stats_lock:
                    value = self._served
                    sched.point()
                    self._served = value + 1
                resolved += 1

        def stats(self):
            with self._stats_lock:
                return {"requests": self._requests, "served": self._served}

    svc = Service()
    snapshots = []

    def submitter():
        svc.submit()
        svc.submit()

    def resolver():
        svc.resolve_loop()

    def reader():
        for _ in range(2):
            snapshots.append(svc.stats())
            sched.point()

    def check():
        for snap in snapshots:
            assert snap["served"] <= snap["requests"], snap
        # All threads are done: read the final state directly (the
        # instrumented lock only exists for the scheduled threads)
        assert (svc._requests, svc._served) == (2, 2), vars(svc)

    return [submitter, resolver, reader], check


def test_fixed_service_stats_model_is_race_free():
    r = schedule.explore(_fixed_service_stats_model, max_preemptions=2,
                         max_runs=3000)
    assert not r.failures, r.failures[:3]
    assert r.runs > 50  # three threads: a real schedule space was covered


# --------------------------------------------------------------------------- #
# r19 causal-plane surfaces: incident capture + router splice

def test_torn_incident_bundle_found_then_fixed():
    """The unlocked index claim loses a bundle under one preemption
    (two coinciding edges overwrite the same `incident-<n>.json`), the
    witness replays deterministically, and the shipped claim-under-lock
    pattern survives the same exhaustive exploration clean."""
    torn = schedule.explore(schedule.incident_bundle_torn_model,
                            max_preemptions=2)
    assert torn.failures, "the torn claim must be found"
    witness = torn.failures[0]
    again = schedule.run_schedule(schedule.incident_bundle_torn_model,
                                  witness.schedule)
    assert not again.ok and again.schedule == witness.schedule
    # serial orders pass — only a preemption exposes it
    serial = schedule.run_schedule(schedule.incident_bundle_torn_model,
                                   "")
    assert serial.ok and serial.preemptions == 0
    clean = schedule.explore(schedule.incident_bundle_model,
                             max_preemptions=2)
    assert clean.exhausted and not clean.failures, clean.failures[:3]
    assert clean.runs > 1


def test_lost_router_splice_found_then_fixed():
    """The unlocked read-extend-rebind ring drops a joined record under
    one preemption (the critical-path histogram undercounts the convoy
    exactly when two connection threads splice together); the shipped
    TraceBuffer append-under-lock is exhaustively clean."""
    lost = schedule.explore(schedule.router_splice_lost_model,
                            max_preemptions=2)
    assert lost.failures, "the lost splice must be found"
    again = schedule.run_schedule(schedule.router_splice_lost_model,
                                  lost.failures[0].schedule)
    assert not again.ok
    serial = schedule.run_schedule(schedule.router_splice_lost_model, "")
    assert serial.ok
    clean = schedule.explore(schedule.router_splice_model,
                             max_preemptions=2)
    assert clean.exhausted and not clean.failures, clean.failures[:3]


def test_selfcheck_covers_the_causal_plane():
    report = schedule.selfcheck()
    assert report["ok"]
    assert report["incident_bundle_torn_found"]
    assert report["router_splice_lost_found"]
    assert report["incident_fixed_clean"]
    assert report["schedules_incident"] > 4
    # both witnesses replay: the report is actionable, not a boolean
    for model, key in ((schedule.incident_bundle_torn_model,
                        "incident_bundle_torn_witness"),
                       (schedule.router_splice_lost_model,
                        "router_splice_lost_witness")):
        assert not schedule.run_schedule(model, report[key]).ok
