"""Arena tests: the closed defense loop (`byzantinemomentum_tpu/arena/`),
the adaptive red team (`attacks/alie.py`/`warmup.py`/`framing.py` + the
registry's stateful hook), the quarantine policy's eviction/hysteresis/
budget contracts, the tournament scoreboard, and the engine threading of
adaptive-attack state through `TrainState`."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu import attacks as attacks_mod, checkpoint, ops
from byzantinemomentum_tpu.arena import QuarantinePolicy
from byzantinemomentum_tpu.arena.loop import ArenaCell, noniid_batches
from byzantinemomentum_tpu.arena.quarantine import quarantine_defense_kernel
from byzantinemomentum_tpu.arena import tournament
from byzantinemomentum_tpu.attacks.alie import zmax
from byzantinemomentum_tpu.obs.forensics import (
    SuspicionTracker, collusion_partners)


def _honest(h=8, d=16, seed=0):
    return jnp.asarray(np.random.default_rng(seed).normal(
        size=(h, d)).astype(np.float32))


# --------------------------------------------------------------------------- #
# Adaptive attacks + the registry state hook

def test_alie_rows_sit_on_the_variance_envelope():
    G = _honest()
    rows = attacks_mod.attacks["alie"].checked(G, 3, 3, defense=None)
    assert rows.shape == (3, G.shape[1])
    mu = np.mean(np.asarray(G), axis=0)
    sigma = np.std(np.asarray(G), axis=0, ddof=1)
    expected = mu + zmax(G.shape[0] + 3, 3) * sigma
    np.testing.assert_allclose(np.asarray(rows[0]), expected, rtol=1e-5)
    # All f rows identical (the collusion signature the defense reads)
    np.testing.assert_array_equal(np.asarray(rows[0]), np.asarray(rows[1]))


def test_alie_z_override_and_jitter_decorrelate():
    G = _honest()
    tight = attacks_mod.attacks["alie"].checked(G, 3, 2, defense=None, z=0.1)
    wide = attacks_mod.attacks["alie"].checked(G, 3, 2, defense=None, z=2.0)
    mu = np.mean(np.asarray(G), axis=0)
    assert (np.linalg.norm(np.asarray(wide[0]) - mu)
            > np.linalg.norm(np.asarray(tight[0]) - mu))
    jittered = attacks_mod.attacks["alie"].checked(
        G, 3, 2, defense=None, z=0.5, jitter=0.2)
    assert not np.array_equal(np.asarray(jittered[0]),
                              np.asarray(jittered[1]))


def test_zmax_closed_form():
    # n=11, f=4: s=2, q=5/7 -> Phi^-1(0.714...) ~ 0.566 (Baruch et al.)
    assert zmax(11, 4) == pytest.approx(0.566, abs=5e-3)
    assert zmax(11, 2) == pytest.approx(0.1397, abs=5e-3)
    assert zmax(4, 3) == 0.0  # degenerate majority clamps to the mean


def test_warmup_attack_is_stateful_and_time_coupled():
    atk = attacks_mod.attacks["alie-warmup"]
    assert atk.stateful
    G = _honest()
    rows0, state = atk.checked(G, 2, 2, defense=None, window=2, burst=10.0)
    assert int(state) == 1
    mu = np.mean(np.asarray(G), axis=0)
    np.testing.assert_allclose(np.asarray(rows0[0]), -10.0 * mu, rtol=1e-5)
    _, state = atk.checked(G, 2, 2, defense=None, state=state, window=2)
    rows2, state = atk.checked(G, 2, 2, defense=None, state=state, window=2)
    assert int(state) == 3
    # Past the window the rows hide inside the envelope (near the mean)
    assert (np.linalg.norm(np.asarray(rows2[0]) - mu)
            < np.linalg.norm(np.asarray(rows0[0]) - mu))


def test_static_attacks_keep_the_stateless_interface():
    atk = attacks_mod.attacks["empire"]
    assert not atk.stateful
    out = atk.checked(_honest(), 2, 2, defense=lambda gradients, f:
                      jnp.mean(gradients, axis=0))
    assert out.shape == (2, 16)  # a bare matrix, no state tuple


def test_framing_attack_clusters_away_from_victim():
    G = _honest()
    rows = attacks_mod.attacks["framing"].checked(
        G, 3, 3, defense=None, victim=2, push=1.0)
    others = (np.sum(np.asarray(G), axis=0) - np.asarray(G[2])) / 7
    np.testing.assert_allclose(
        np.asarray(rows[0]), others + (others - np.asarray(G[2])),
        rtol=1e-4)
    assert attacks_mod.attacks["framing"].check(
        grad_honests=G, f_real=1, defense=None, victim=99) is not None


# --------------------------------------------------------------------------- #
# Collusion channel + quarantine policy

def test_collusion_partners_relative_threshold():
    dist = np.full((4, 4), 10.0)
    np.fill_diagonal(dist, np.inf)
    dist[2, 3] = dist[3, 2] = 0.5  # well under 0.2 * median(10)
    partners = collusion_partners(dist)
    assert partners[2, 3] and partners[3, 2]
    assert partners.sum() == 2
    # Non-finite rows never partner
    dist[0, 1] = dist[1, 0] = np.nan
    assert not collusion_partners(dist)[0, 1]


def test_tracker_weight_arity():
    with pytest.raises(ValueError):
        SuspicionTracker(4, weights=(1.0, 1.0))
    three = SuspicionTracker(4)               # 3-weight form unchanged
    assert len(three.weights) == 3
    four = SuspicionTracker(4, weights=(0.35, 0.25, 0.1, 0.3))
    dist = np.full((4, 4), 10.0)
    np.fill_diagonal(dist, np.inf)
    dist[0, 1] = dist[1, 0] = 0.1
    four.update(0, np.ones(4), dist_matrix=dist)
    assert four.collusion[0] > 0 and four.collusion[2] == 0


def test_policy_evicts_colluding_pair_keeps_one_and_respects_budget():
    n = 8
    policy = QuarantinePolicy(n, 2, max_evictions=1)
    sel = np.ones(n)
    sel[5:] = 0.0
    dmat = np.full((n, n), 5.0)
    np.fill_diagonal(dmat, np.inf)
    for i in (5, 6, 7):
        for j in (5, 6, 7):
            if i != j:
                dmat[i, j] = 0.01  # a 3-clique of near-duplicates
    for t in range(40):
        mask = policy.update(t, sel, dist_matrix=dmat)
    # Budget 1: exactly one eviction despite three saturated colluders
    assert policy.evictions_total == 1
    assert int(mask.sum()) == n - 1
    assert sorted(policy.evicted_at) and min(policy.evicted_at) >= 5


def test_policy_collusion_dedup_keeps_lowest_history_member():
    n = 6
    policy = QuarantinePolicy(n, 3)
    sel = np.ones(n)
    dmat = np.full((n, n), 5.0)
    np.fill_diagonal(dmat, np.inf)
    dmat[4, 5] = dmat[5, 4] = 0.01
    for t in range(40):
        policy.update(t, sel, dist_matrix=dmat)
    # The pair saturates together; the dedup keeps the lower index
    assert sorted(policy.evicted_at) == [5]
    assert policy.f_reclaimed() == 1


def test_policy_framing_stream_never_evicts():
    """The hysteresis contract: a starved victim at the single-outlier
    distance bound (z self-limits at sqrt(n-1)) stays below the eviction
    threshold forever."""
    n = 11
    policy = QuarantinePolicy(n, 3)
    sel = np.ones(n)
    sel[0] = 0.0
    dist = np.ones(n)
    dist[0] = 100.0
    clean = np.full((n, n), 5.0)
    np.fill_diagonal(clean, np.inf)
    for t in range(120):
        policy.update(t, sel, distances=dist, dist_matrix=clean)
    assert policy.evictions_total == 0
    assert policy.tracker.suspicion[0] < policy.evict_threshold


def test_policy_validation():
    with pytest.raises(ValueError, match="4-tuple"):
        QuarantinePolicy(4, 1, tracker={"weights": (0.5, 0.3, 0.2)})
    with pytest.raises(ValueError, match="undercut"):
        QuarantinePolicy(4, 1, evict_threshold=0.1)


def test_quarantine_kernel_masks_and_reclaims_quorum():
    G = np.array(_honest(11, 16), copy=True)
    G[9] = np.nan  # sanitize must fold corrupt rows into the mask
    kernel = quarantine_defense_kernel(ops.gars["krum"], f=3)
    active = np.ones(11, dtype=bool)
    active[10] = False
    out = kernel(jnp.asarray(G), jnp.asarray(active), jnp.int32(0))
    assert not bool(out["active"][9]) and not bool(out["active"][10])
    assert int(out["f_eff"]) == 3
    credited = kernel(jnp.asarray(G), jnp.asarray(active), jnp.int32(2))
    assert int(credited["f_eff"]) == 1  # the eviction credit shrinks f
    assert np.isfinite(np.asarray(credited["aggregate"])).all()
    # Masked rows read +inf worker distance and zero selection
    assert np.isinf(np.asarray(out["worker_dist"])[9:]).all()
    assert np.asarray(out["selection"])[9:].sum() == 0


# --------------------------------------------------------------------------- #
# Engine threading of adaptive-attack state

def test_engine_threads_attack_state_and_checkpoints_it(tmp_path):
    from byzantinemomentum_tpu import losses
    from byzantinemomentum_tpu.engine import EngineConfig, build_engine
    from byzantinemomentum_tpu.arena.loop import probe_loss, probe_model_def

    cfg = EngineConfig(nb_workers=6, nb_decl_byz=2, nb_real_byz=2,
                       nb_for_study=0, momentum=0.0, momentum_at="update")
    engine = build_engine(
        cfg=cfg, model_def=probe_model_def(8), loss=probe_loss(),
        criterion=losses.Criterion("sigmoid"),
        defenses=[(ops.gars["median"], 1.0, {})],
        attack=attacks_mod.attacks["alie-warmup"],
        attack_kwargs={"window": 3})
    state = engine.init(jax.random.PRNGKey(0))
    assert int(state.attack_state) == 0
    xs = jnp.asarray(np.random.default_rng(0).normal(
        size=(4, 2, 8)).astype(np.float32))
    ys = jnp.zeros((4, 2), jnp.float32)
    for expected in (1, 2):
        state, _ = engine.train_step(state, xs, ys, jnp.float32(0.1))
        assert int(state.attack_state) == expected
    # The counter survives a checkpoint round-trip (resume keeps the
    # attack's schedule aligned with the step counter)
    path = checkpoint.save(tmp_path / "ck.bin", state)
    restored = checkpoint.load(path, engine.init(jax.random.PRNGKey(1)))
    assert int(restored.attack_state) == 2


# --------------------------------------------------------------------------- #
# The closed loop end to end

@pytest.fixture(scope="module")
def krum_alie_cell():
    return ArenaCell("krum", "alie", n=11, f_decl=3, f_real=3, d=32)


def test_closed_loop_evicts_attackers_not_honests(krum_alie_cell):
    row = krum_alie_cell.run(quarantine=True, steps=60, seed=1,
                             warm_recompile_check=True)
    assert row["evicted_honest"] == 0
    assert row["evicted_byz"] >= 1
    assert row["time_to_quarantine"] is not None
    assert row["time_to_quarantine"] <= 40
    assert row["f_reclaimed"] >= 1


def test_closed_loop_on_off_share_one_compiled_program(krum_alie_cell):
    """Quarantine on/off — and every mask update in between — run the
    SAME executable: after the warm on-run, the off-run compiles
    nothing."""
    from byzantinemomentum_tpu.analysis import contracts

    krum_alie_cell.run(quarantine=True, steps=12, seed=3)  # warm
    with contracts.count_compiles() as log:
        off = krum_alie_cell.run(quarantine=False, steps=12, seed=3)
    assert log.count == 0, log.events
    assert off["evicted_byz"] == 0 and off["active_final"] == 11


def test_closed_loop_quarantine_dominates_steady_state(krum_alie_cell):
    on = krum_alie_cell.run(quarantine=True, steps=80, seed=0)
    off = krum_alie_cell.run(quarantine=False, steps=80, seed=0)
    assert on["agg_err_last10"] < off["agg_err_last10"]


def test_framing_cell_zero_honest_evictions():
    cell = ArenaCell("krum", "framing", n=11, f_decl=3, f_real=3, d=32)
    row = cell.run(quarantine=True, steps=80, seed=0)
    assert row["evicted_honest"] == 0


def test_mimic_rows_are_byte_copies_of_the_victim():
    rng = np.random.default_rng(3)
    honests = jnp.asarray(rng.normal(size=(6, 16)).astype(np.float32))
    rows = attacks_mod.attacks["mimic"].checked(
        honests, f_decl=3, f_real=3, defense=lambda **kw: None, victim=2)
    assert rows.shape == (3, 16)
    assert (np.asarray(rows) == np.asarray(honests[2])).all()
    # jitter decorrelates the copies (the collusion-threshold probe knob)
    blurred = attacks_mod.attacks["mimic"].checked(
        honests, f_decl=3, f_real=3, defense=lambda **kw: None, victim=2,
        jitter=0.5)
    assert not (np.asarray(blurred[0]) == np.asarray(blurred[1])).all()
    # Contract errors stay readable
    assert "victim" in attacks_mod.attacks["mimic"].check(
        grad_honests=honests, f_decl=3, f_real=3,
        defense=lambda **kw: None, victim=9)


def test_mimic_cell_zero_honest_evictions_dedup_keeps_victim():
    """The tournament regression the fielded mimicry attack pins
    (ROADMAP arena rung 1): byte-copies of an honest victim's row form a
    collusion cluster CONTAINING the victim — dedup must evict the
    copies (quorum reclaimed: a copy adds no adversarial dimension) and
    keep the victim, on the attacker's schedule or any other. Zero
    honest evictions, every Byzantine copy out."""
    cell = ArenaCell("krum", "mimic", n=11, f_decl=3, f_real=3, d=32)
    row = cell.run(quarantine=True, steps=60, seed=0)
    assert row["evicted_honest"] == 0
    assert row["evicted_byz"] == 3
    assert row["f_reclaimed"] == 3  # dedup evictions reclaim quorum
    assert row["time_to_quarantine"] is not None


def test_mimic_rides_the_tournament_grid():
    """The registry-driven roster fields mimic automatically; it stays
    OFF the dominance list (honest-valued rows never bias the
    aggregate — its acceptance metric is the eviction regression
    above)."""
    labels = [label for label, *_ in tournament.train_roster()]
    assert "mimic" in labels
    assert "mimic" not in tournament.ADAPTIVE_ATTACKS


def test_noniid_batches_skew_moves_worker_means():
    rng = np.random.default_rng(0)
    optimum = np.zeros(16, np.float32)
    iid = noniid_batches(rng, steps=4, workers=6, batch=64,
                         optimum=optimum, sigma=0.5, skew=0.0)
    assert iid.shape == (4, 6, 64, 16)
    skewed = noniid_batches(np.random.default_rng(0), steps=4, workers=6,
                            batch=64, optimum=optimum, sigma=0.5, skew=2.0)
    worker_means = skewed.mean(axis=(0, 2))
    spread = np.linalg.norm(worker_means, axis=1)
    assert (spread > 0.5).all()  # each worker's optimum fanned out
    assert np.linalg.norm(iid.mean(axis=(0, 2)), axis=1).max() < 0.2


def test_tournament_scoreboard_schema_and_digests():
    roster = [("alie", "alie", {}, 0.0)]
    sb = tournament.run_tournament(
        gars=("median",), roster=roster, steps=24, seed=0,
        serve_requests=8, serve_gar="median")
    assert sb["kind"] == "tournament"
    assert len(sb["train_cells"]) == 2  # one cell x on/off
    assert {c["quarantine"] for c in sb["train_cells"]} == {True, False}
    for c in sb["train_cells"]:
        for key in ("final_err", "agg_err_mean", "agg_err_last10",
                    "evicted_honest", "evicted_byz",
                    "time_to_quarantine", "f_reclaimed"):
            assert key in c
    assert len(sb["serve_cells"]) == 2
    summary = sb["summary"]
    assert summary["dominance_metric"] == "agg_err_last10"
    assert "framing_honest_evictions" in summary
    assert "sybil" in summary and "detection_rate" in summary["sybil"]
