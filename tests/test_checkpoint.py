"""Checkpoint durability tests: atomic writes with the CRC integrity
footer, `find_latest_valid` walking past torn/corrupt tails, the retention
GC + restart-counter manifest, and every `load` validation branch (version
mismatch, missing field, shape mismatch, negative counters) plus the
`fault_buffer` cold-start path — none of which were exercised before."""

import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import serialization

from byzantinemomentum_tpu import checkpoint, utils
from byzantinemomentum_tpu.engine.state import TrainState


def tiny_state(d=4, steps=0, fault_rows=0, past=2):
    """A hand-built TrainState small enough to checkpoint in microseconds."""
    return TrainState(
        theta=jnp.arange(d, dtype=jnp.float32),
        net_state={"bn": {"mean": jnp.zeros((2,), jnp.float32)}},
        opt_state=(),
        momentum_server=jnp.zeros((d,), jnp.float32),
        momentum_workers=jnp.zeros((0, d), jnp.float32),
        origin=jnp.zeros((d,), jnp.float32),
        past_grads=jnp.zeros((past, d), jnp.float32),
        past_norms=jnp.zeros((past,), jnp.float32),
        past_count=jnp.int32(0),
        steps=jnp.int32(steps),
        datapoints=jnp.int32(steps * 10),
        rng=jax.random.PRNGKey(7),
        fault_buffer=jnp.zeros((fault_rows, d), jnp.float32),
    )


def write_mutated(path, state, mutate, seal=True):
    """Serialize `state` the way `save` does, apply `mutate` to the payload
    dict, and write it (with or without the integrity footer)."""
    state = jax.device_get(state)
    payload = {"version": checkpoint.VERSION,
               "state": {name: serialization.to_state_dict(value)
                         for name, value in state._asdict().items()}}
    mutate(payload)
    data = serialization.msgpack_serialize(payload)
    if seal:
        data = checkpoint.seal(data)
    pathlib.Path(path).write_bytes(data)
    return pathlib.Path(path)


# --------------------------------------------------------------------------- #
# Round trip, footer, atomicity artifacts


def test_roundtrip_footer_and_no_tmp_left(tmp_path):
    state = tiny_state(steps=3)
    path = checkpoint.save(tmp_path / "checkpoint-3", state,
                           data_state={"train": {"pos": 1}, "test": {"pos": 2}})
    raw = path.read_bytes()
    assert raw[-8:-4] == checkpoint.MAGIC
    assert not list(tmp_path.glob("*.tmp"))  # atomic: tmp renamed away
    loaded, data = checkpoint.load(path, tiny_state(), return_data=True)
    assert int(loaded.steps) == 3
    np.testing.assert_array_equal(np.asarray(loaded.theta),
                                  np.asarray(state.theta))
    np.testing.assert_array_equal(np.asarray(loaded.rng),
                                  np.asarray(state.rng))
    assert data == {"train": {"pos": 1}, "test": {"pos": 2}}
    assert checkpoint.verify(path)


def test_legacy_footerless_checkpoint_still_loads(tmp_path):
    path = write_mutated(tmp_path / "checkpoint-0", tiny_state(),
                         lambda p: None, seal=False)
    assert checkpoint.verify(path)
    loaded = checkpoint.load(path, tiny_state())
    assert int(loaded.steps) == 0


# --------------------------------------------------------------------------- #
# load() validation branches


def test_load_version_mismatch(tmp_path):
    def bump(payload):
        payload["version"] = checkpoint.VERSION + 1
    path = write_mutated(tmp_path / "checkpoint-0", tiny_state(), bump)
    with pytest.raises(utils.UserException, match="version"):
        checkpoint.load(path, tiny_state())


def test_load_missing_state_payload(tmp_path):
    def drop(payload):
        del payload["state"]
    path = write_mutated(tmp_path / "checkpoint-0", tiny_state(), drop)
    with pytest.raises(utils.UserException, match="missing state payload"):
        checkpoint.load(path, tiny_state())


def test_load_missing_field(tmp_path):
    def drop(payload):
        del payload["state"]["theta"]
    path = write_mutated(tmp_path / "checkpoint-0", tiny_state(), drop)
    with pytest.raises(utils.UserException, match="missing field 'theta'"):
        checkpoint.load(path, tiny_state())


def test_load_shape_mismatch(tmp_path):
    path = checkpoint.save(tmp_path / "checkpoint-0", tiny_state(d=4))
    with pytest.raises(utils.UserException, match="shape"):
        checkpoint.load(path, tiny_state(d=5))


def test_load_negative_counters(tmp_path):
    for field in ("steps", "datapoints"):
        def corrupt(payload, field=field):
            payload["state"][field] = -3
        path = write_mutated(tmp_path / f"checkpoint-{field}-0",
                             tiny_state(), corrupt)
        with pytest.raises(utils.UserException,
                           match=f"invalid {field} counter"):
            checkpoint.load(path, tiny_state())


def test_load_fault_buffer_cold_start(tmp_path):
    """A pre-faults checkpoint (no `fault_buffer` field, same VERSION)
    resumed under a fresh fault plan starts the straggler buffer at the
    template's zeros (`checkpoint.load`'s documented cold-start)."""
    def drop(payload):
        del payload["state"]["fault_buffer"]
    path = write_mutated(tmp_path / "checkpoint-0", tiny_state(), drop)
    loaded = checkpoint.load(path, tiny_state(fault_rows=3))
    assert loaded.fault_buffer.shape == (3, 4)
    assert not np.asarray(loaded.fault_buffer).any()


# --------------------------------------------------------------------------- #
# Integrity detection + resume scanning


def test_crc_detects_corruption(tmp_path):
    path = checkpoint.save(tmp_path / "checkpoint-0", tiny_state())
    raw = bytearray(path.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    path.write_bytes(bytes(raw))
    assert not checkpoint.verify(path)
    with pytest.raises(utils.UserException, match="integrity"):
        checkpoint.load(path, tiny_state())


def test_find_latest_valid_skips_truncated_tail(tmp_path):
    """The tier-1 chaos check: a checkpoint truncated mid-byte (torn
    non-atomic write, bad copy) is skipped, not crashed on — resume walks
    back to the newest intact file."""
    checkpoint.save(tmp_path / "checkpoint-2", tiny_state(steps=2))
    torn = checkpoint.save(tmp_path / "checkpoint-4", tiny_state(steps=4))
    raw = torn.read_bytes()
    torn.write_bytes(raw[:len(raw) // 2])
    found = checkpoint.find_latest_valid(tmp_path)
    assert found is not None and found.name == "checkpoint-2"
    # ... and the survivor actually loads
    assert int(checkpoint.load(found, tiny_state()).steps) == 2
    # Garbage under a checkpoint name must not shadow the valid tail either
    (tmp_path / "checkpoint-9").write_bytes(b"\x00" * 64)
    assert checkpoint.find_latest_valid(tmp_path).name == "checkpoint-2"


def test_find_latest_valid_ignores_noise(tmp_path):
    assert checkpoint.find_latest_valid(tmp_path / "absent") is None
    assert checkpoint.find_latest_valid(tmp_path) is None
    checkpoint.save(tmp_path / "checkpoint-6", tiny_state(steps=6))
    (tmp_path / "checkpoint-8.tmp").write_bytes(b"torn mid-write")
    (tmp_path / "checkpoint-7").mkdir()  # a directory, not a file
    (tmp_path / "checkpoint-notastep").write_bytes(b"nope")
    assert checkpoint.find_latest_valid(tmp_path).name == "checkpoint-6"


def test_checkpoint_step_parsing():
    assert checkpoint.checkpoint_step("results/run/checkpoint-1200") == 1200
    assert checkpoint.checkpoint_step("checkpoint-0") == 0
    assert checkpoint.checkpoint_step("checkpoints.json") is None
    assert checkpoint.checkpoint_step("checkpoint-4.tmp") is None


# --------------------------------------------------------------------------- #
# Manifest: retention GC + restart counter


def test_retention_gc_keeps_newest(tmp_path):
    for step in (0, 2, 4, 6):
        checkpoint.save(tmp_path / f"checkpoint-{step}",
                        tiny_state(steps=step), keep=2)
    names = sorted(p.name for p in tmp_path.glob("checkpoint-*"))
    assert names == ["checkpoint-4", "checkpoint-6"]
    manifest = checkpoint.read_manifest(tmp_path)
    assert [e["step"] for e in manifest["checkpoints"]] == [4, 6]
    assert checkpoint.find_latest_valid(tmp_path).name == "checkpoint-6"


def test_restart_counter_survives_saves(tmp_path):
    checkpoint.save(tmp_path / "checkpoint-0", tiny_state())
    assert checkpoint.read_manifest(tmp_path)["restarts"] == 0
    assert checkpoint.bump_restarts(tmp_path) == 1
    assert checkpoint.bump_restarts(tmp_path) == 2
    checkpoint.save(tmp_path / "checkpoint-2", tiny_state(steps=2))
    assert checkpoint.read_manifest(tmp_path)["restarts"] == 2


def test_manifest_tolerates_garbage(tmp_path):
    (tmp_path / checkpoint.MANIFEST_NAME).write_text("{not json")
    manifest = checkpoint.read_manifest(tmp_path)
    assert manifest["checkpoints"] == [] and manifest["restarts"] == 0
    # and a save over the garbage repairs it
    checkpoint.save(tmp_path / "checkpoint-0", tiny_state())
    assert checkpoint.read_manifest(tmp_path)["checkpoints"]
