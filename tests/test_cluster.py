"""Multi-host cluster runtime (`byzantinemomentum_tpu/cluster/`): the
consensus manifest, the heartbeat-aggregated liveness view, the
system-scope fault driver, off-slice checkpoint mirroring, bounded
unavailability, and — slow-marked — the real multi-process fleets: the
kill-one-host recovery proof (bit-identical resumed study CSV) and the
Jobs supervisor driving the launcher through the seedless service-job
form."""

import json
import os
import pathlib
import subprocess
import sys
import time

import pytest
from flax import serialization

from byzantinemomentum_tpu import checkpoint
from byzantinemomentum_tpu.cluster import (
    HostSpec, SystemFaultDriver, agree_restart_step, liveness_view,
    read_cluster_manifest, update_cluster_manifest, write_cluster_manifest)
from byzantinemomentum_tpu.cluster import elastic
from byzantinemomentum_tpu.cluster.chaos import StraggleResumer
from byzantinemomentum_tpu.cluster.runtime import (
    ClusterUnavailable, UNAVAILABLE_RC, cluster_mesh, free_port)
from byzantinemomentum_tpu.cluster.straggler import (
    DEFAULT_WAIT_S, StragglerPolicy, resolve_wait_bound)
from byzantinemomentum_tpu.faults import FaultPlan
from byzantinemomentum_tpu.faults.plan import (
    corrupt_gradient, device_loss, drop_worker, straggle)
from byzantinemomentum_tpu.obs.heartbeat import (
    host_heartbeat_path, read_host_heartbeats, write_host_heartbeat)

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _fake_checkpoint(directory, step):
    """A minimal file `checkpoint.verify` accepts (version + state dict +
    integrity footer) — enough for the resume-scan machinery without
    building an engine."""
    directory = pathlib.Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    payload = {"version": checkpoint.VERSION, "state": {"steps": step}}
    path = directory / f"checkpoint-{step}"
    path.write_bytes(checkpoint.seal(
        serialization.msgpack_serialize(payload)))
    return path


# --------------------------------------------------------------------------- #
# Runtime spec + port probing

def test_host_spec_validation():
    with pytest.raises(ValueError, match="process count"):
        HostSpec("127.0.0.1:1", 0, 0)
    with pytest.raises(ValueError, match="outside"):
        HostSpec("127.0.0.1:1", 2, 2)
    with pytest.raises(ValueError, match="timeout"):
        HostSpec("127.0.0.1:1", 2, 1, connect_timeout=0)
    spec = HostSpec("127.0.0.1:1", 4, 3)
    assert spec.connect_timeout == 60.0


def test_free_port_is_bindable():
    import socket

    port = free_port()
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", port))


# --------------------------------------------------------------------------- #
# Cluster manifest: the consensus artifact

def test_manifest_roundtrip_and_defaults(tmp_path):
    manifest = read_cluster_manifest(tmp_path)
    assert manifest["restart_step"] is None
    assert manifest["fired_faults"] == []
    manifest["restart_step"] = 4
    manifest["fired_faults"] = [0]
    write_cluster_manifest(tmp_path, manifest)
    again = read_cluster_manifest(tmp_path)
    assert again["restart_step"] == 4 and again["fired_faults"] == [0]
    update_cluster_manifest(tmp_path, status="recovering", attempt=2)
    final = read_cluster_manifest(tmp_path)
    assert final["status"] == "recovering" and final["attempt"] == 2
    assert final["restart_step"] == 4  # update merges, never clobbers


def test_manifest_torn_file_means_defaults(tmp_path):
    (tmp_path / "cluster.json").write_text("{ torn")
    assert read_cluster_manifest(tmp_path)["attempt"] == 0


def test_agree_restart_step_reads_only_the_mirror(tmp_path):
    mirror = tmp_path / "mirror"
    assert agree_restart_step(mirror) == (None, None)
    _fake_checkpoint(mirror, 2)
    newest = _fake_checkpoint(mirror, 6)
    # A torn newer file must be walked past, not adopted
    torn = mirror / "checkpoint-8"
    torn.write_bytes(newest.read_bytes()[:10])
    step, path = agree_restart_step(mirror)
    assert step == 6 and path.name == "checkpoint-6"


# --------------------------------------------------------------------------- #
# Per-host heartbeats -> liveness view

def test_host_heartbeats_roundtrip(tmp_path):
    write_host_heartbeat(tmp_path, 0, {"step": 3, "status": "running"})
    write_host_heartbeat(tmp_path, 2, {"step": 5, "status": "running"})
    # A torn heartbeat is skipped, not fatal
    host_heartbeat_path(tmp_path, 1).write_text("{ torn")
    beats = read_host_heartbeats(tmp_path)
    assert sorted(beats) == [0, 2]
    assert beats[0]["host"] == 0 and beats[0]["step"] == 3
    assert beats[2]["pid"] == os.getpid()  # stamped, self-describing


def test_liveness_view_statuses(tmp_path):
    now = time.time()
    write_host_heartbeat(tmp_path, 0, {"step": 4, "resume_step": 2})
    write_host_heartbeat(tmp_path, 1, {"step": 3})
    view = liveness_view(tmp_path, 4, stale_after=30.0,
                         running={0: True, 1: True, 2: True, 3: False},
                         now=now)
    assert view["hosts"][0]["status"] == "alive"
    assert view["hosts"][0]["resume_step"] == 2
    assert view["hosts"][1]["status"] == "alive"
    assert view["hosts"][2]["status"] == "unknown"  # no signal yet
    assert view["hosts"][3]["status"] == "dead"     # process table wins
    assert view["alive"] == [0, 1]
    assert view["min_step"] == 3 and view["max_step"] == 4
    # A fresh-looking heartbeat from a dead process is still dead
    write_host_heartbeat(tmp_path, 3, {"step": 9})
    view = liveness_view(tmp_path, 4, running={0: True, 1: True, 2: True,
                                               3: False}, now=now)
    assert view["hosts"][3]["status"] == "dead"
    assert view["max_step"] == 4  # dead hosts' steps never count


def test_liveness_view_staleness(tmp_path):
    write_host_heartbeat(tmp_path, 0, {"step": 1})
    later = time.time() + 100.0
    view = liveness_view(tmp_path, 1, stale_after=30.0,
                         running={0: True}, now=later)
    assert view["hosts"][0]["status"] == "stale"
    assert view["alive"] == []


def test_liveness_view_carries_health_block(tmp_path):
    """A host heartbeat's flight-recorder `health` block (obs/health,
    PR 15) rides the liveness view, so the fleet exposes training-
    dynamics state next to liveness — hosts without one simply have no
    key."""
    health = {"steps": 7, "anomaly": True, "anomalies_total": 1,
              "last_anomaly": {"channel": "var_ratio", "step": 6,
                               "rule": "spike"},
              "var_ratio_ewma": 0.42}
    write_host_heartbeat(tmp_path, 0, {"step": 7, "health": health})
    write_host_heartbeat(tmp_path, 1, {"step": 7})
    view = liveness_view(tmp_path, 2, running={0: True, 1: True},
                         now=time.time())
    assert view["hosts"][0]["health"]["anomaly"] is True
    assert view["hosts"][0]["health"]["var_ratio_ewma"] == 0.42
    assert "health" not in view["hosts"][1]


def test_liveness_dead_heartbeat_never_resurrects(tmp_path):
    """The process table outranks every heartbeat, in BOTH consumers:
    once `running[host]` is False the view says dead no matter how
    fresh (or future-stepped) the beat on disk looks, and the straggler
    policy DROPS its suspicion of a dead host instead of killing a
    corpse — death is the launcher's jurisdiction, not the policy's."""
    now = time.time()
    write_host_heartbeat(tmp_path, 0, {"step": 99})
    view = liveness_view(tmp_path, 1, stale_after=30.0,
                         running={0: False}, now=now)
    assert view["hosts"][0]["status"] == "dead"
    assert view["alive"] == []
    policy = _armed_policy([0], wait_s=1.0)
    policy.observe({"hosts": {0: _lv_row("stale", 2, 3.0)}}, 10.0)
    # Way past the bound — but the host died first: no kill, ever
    assert policy.observe({"hosts": {0: _lv_row("dead", 2, 99.0)}},
                          50.0) == []
    assert policy.observe({"hosts": {0: _lv_row("dead", 2, 99.0)}},
                          60.0) == []
    assert policy.kills == []


def test_cluster_mesh_refuses_a_width_mismatch():
    """`expected_workers` pins the mesh's workers axis to the fleet
    width the launcher derived (elastic shrink re-derives it): a host
    whose runtime sees a DIFFERENT device count than the membership
    says must fail as `ClusterUnavailable` (-> `UNAVAILABLE_RC`), never
    train on a silently mis-shaped mesh."""
    width = len(__import__("jax").devices())
    with cluster_mesh(expected_workers=width) as mesh:
        assert mesh.shape["workers"] == width
    with pytest.raises(ClusterUnavailable, match="expects"):
        with cluster_mesh(expected_workers=width + 1):
            pass


# --------------------------------------------------------------------------- #
# System-scope fault plans

def test_validate_system_scope():
    plan = FaultPlan(events=(device_loss(1, 3),))
    assert plan.validate_system(2) is None
    assert "only" in FaultPlan(events=(drop_worker(1, 3),)
                               ).validate_system(2)
    assert "only" in FaultPlan(events=(corrupt_gradient(1, 3),)
                               ).validate_system(4)
    assert "2 hosts" in FaultPlan(events=(device_loss(2, 3),)
                                  ).validate_system(2)
    assert "coordinator" in FaultPlan(events=(device_loss(0, 3),)
                                      ).validate_system(2)


def test_system_fault_driver_fires_once():
    plan = FaultPlan(events=(device_loss(1, 3), device_loss(2, 5)))
    driver = SystemFaultDriver(plan, 4)
    assert driver.due(None) == []          # no heartbeat yet
    assert driver.due(2) == []
    due = driver.due(3)
    assert [(i, e.worker) for i, e in due] == [(0, 1)]
    driver.mark(0)
    assert driver.due(4) == []             # fired events never re-fire
    assert not driver.exhausted()
    due = driver.due(9)                    # late poll catches up
    assert [(i, e.worker) for i, e in due] == [(1, 2)]
    driver.mark(1)
    assert driver.exhausted() and driver.fired() == [0, 1]
    # A relaunched launcher rebuilds from the persisted record
    again = SystemFaultDriver(plan, 4, fired=driver.fired())
    assert again.due(99) == []


def test_system_fault_driver_rejects_bad_plans():
    with pytest.raises(ValueError, match="system scope"):
        SystemFaultDriver(FaultPlan(events=(drop_worker(1, 1),)), 2)


def test_straggle_events_are_system_scope_only():
    """`straggle` (SIGSTOP window) exists at SYSTEM scope: legal in a
    system plan (window preserved through JSON), refused by the in-step
    validator, and refused without a positive window."""
    plan = FaultPlan(events=(straggle(1, 3, 2.5),))
    assert plan.validate_system(2) is None
    assert "coordinator" in FaultPlan(
        events=(straggle(0, 3, 2.5),)).validate_system(2)
    assert "SYSTEM scope" in plan.validate(nb_workers=4, nb_honests=3)
    with pytest.raises(ValueError, match="window"):
        straggle(1, 3, 0.0)
    raw = json.loads(plan.to_json())
    assert raw["events"][0]["window_s"] == 2.5
    loaded = FaultPlan.from_json(plan.to_json())
    assert loaded.events[0].kind == "straggle"
    assert loaded.events[0].window_s == 2.5


# --------------------------------------------------------------------------- #
# Elastic shrink arithmetic (cluster/elastic.py)

def test_static_f_ceiling_matches_traced_quorum():
    """The launcher-side static table and the in-jit traced clamp
    (`faults/quorum.py::effective_f`) must never drift apart — a shrink
    that re-declares f above what the per-step quorum would grant (or
    below) would silently change the aggregation contract."""
    from byzantinemomentum_tpu.faults import quorum

    names = ("krum", "native-krum", "bulyan", "brute", "trmean",
             "phocas", "meamed", "median", "average")
    for name in names:
        for n in range(1, 13):
            for f_decl in range(0, 6):
                assert elastic.static_effective_f(name, n, f_decl) == int(
                    quorum.effective_f(name, n, f_decl)), (name, n, f_decl)


def test_shrunk_spec_holds_shares_and_reclamps_quorum():
    base = {"hosts": 4, "nb_workers": 8, "nb_decl_byz": 3,
            "nb_real_byz": 2, "nb_for_study": 8, "gar": "krum"}
    # Full width is the identity on totals (f already at krum's ceiling
    # for n=8: (8-3)//2 = 2 < declared 3, so even THIS re-clamps)
    full = elastic.shrunk_spec(base, 4)
    assert full == {"hosts": 4, "nb_workers": 8, "nb_decl_byz": 2,
                    "nb_real_byz": 2, "nb_for_study": 8}
    spec = elastic.shrunk_spec(base, 3)
    # Per-host shares constant: 2 workers + 2 study slots per host
    assert spec == {"hosts": 3, "nb_workers": 6, "nb_decl_byz": 1,
                    "nb_real_byz": 2, "nb_for_study": 6}
    with pytest.raises(ValueError, match="split evenly"):
        elastic.shrunk_spec(dict(base, nb_workers=7), 3)
    with pytest.raises(ValueError, match="outside"):
        elastic.shrunk_spec(base, 5)
    # Ragged sampled split: honests no longer divisible by the mesh axis
    ragged = {"hosts": 3, "nb_workers": 6, "nb_decl_byz": 1,
              "nb_real_byz": 1, "nb_for_study": 3, "gar": "median"}
    with pytest.raises(ValueError, match="workers mesh axis"):
        elastic.shrunk_spec(ragged, 2)


def test_elastic_precheck_proves_every_survivor_width():
    base = {"hosts": 4, "nb_workers": 8, "nb_decl_byz": 2,
            "nb_real_byz": 2, "nb_for_study": 8, "gar": "median"}
    assert elastic.precheck(base, 1) is None
    # Legal at launch, dead-ends at 3 survivors (honests=5 not divisible
    # by the 3-wide mesh) — refused AT LAUNCH, not mid-incident …
    bad = {"hosts": 4, "nb_workers": 12, "nb_decl_byz": 1,
           "nb_real_byz": 4, "nb_for_study": 4, "gar": "median"}
    assert "3 hosts" in elastic.precheck(bad, 1)
    # … unless the floor keeps the shrink path above the bad width
    assert elastic.precheck(bad, 4) is None
    assert "exceeds" in elastic.precheck(base, 9)


# --------------------------------------------------------------------------- #
# Straggler policy (cluster/straggler.py)

def _lv_row(status, step=None, age=0.0, health=None):
    row = {"status": status, "step": step, "age": age}
    if health is not None:
        row["health"] = health
    return row


def test_straggler_policy_arms_only_past_warm_step():
    policy = StragglerPolicy(5.0)
    # Cold start: first observed step, then a stall — compile-shaped.
    # The Jobs watchdog's jurisdiction, NEVER the policy's.
    assert policy.observe({"hosts": {0: _lv_row("alive", 1)}}, 0.0) == []
    assert policy.observe({"hosts": {0: _lv_row("stale", 1, 90.0)}},
                          100.0) == []
    assert policy.observe({"hosts": {0: _lv_row("stale", 1, 990.0)}},
                          1000.0) == []  # however long it stalls
    # A step PAST the first proves the loop is warm: arm, then suspect
    assert policy.observe({"hosts": {0: _lv_row("alive", 2)}},
                          1001.0) == []
    events = policy.observe({"hosts": {0: _lv_row("stale", 2, 3.0)}},
                            1004.0)
    assert [e["event"] for e in events] == ["suspect"]
    assert events[0]["host"] == 0 and events[0]["reason"] == "stale"


def _armed_policy(hosts, wait_s=5.0, t0=0.0, **kwargs):
    policy = StragglerPolicy(wait_s, **kwargs)
    policy.observe({"hosts": {h: _lv_row("alive", 1) for h in hosts}}, t0)
    policy.observe({"hosts": {h: _lv_row("alive", 2) for h in hosts}},
                   t0 + 1.0)
    return policy


def test_straggler_policy_recovers_on_fresh_heartbeat():
    policy = _armed_policy([0])
    policy.observe({"hosts": {0: _lv_row("stale", 2, 3.0)}}, 10.0)
    events = policy.observe({"hosts": {0: _lv_row("alive", 3)}}, 12.0)
    assert [e["event"] for e in events] == ["recovered"]
    assert events[0]["suspect_s"] == 2.0
    assert policy.kills == []
    assert policy.recoveries[0]["host"] == 0
    assert policy.summary()["suspects_entered"] == 1


def test_straggler_policy_kills_the_not_scheduling_host_once():
    """At the bound every wedged host looks suspect; the one observed
    NOT SCHEDULING (SIGSTOP'd) is blamed regardless of suspicion order,
    exactly once per attempt — the hostages come back on relaunch."""
    policy = _armed_policy([0, 1, 2], wait_s=5.0)
    # Host 0 goes suspect FIRST (would win the duration tie-break) …
    policy.observe({"hosts": {0: _lv_row("stale", 2, 3.0),
                              1: _lv_row("alive", 3),
                              2: _lv_row("alive", 3)}}, 10.0)
    stale_all = {0: _lv_row("stale", 2, 5.0), 1: _lv_row("stale", 3, 4.0),
                 2: _lv_row("stale", 3, 4.5)}
    policy.observe({"hosts": stale_all}, 12.0)
    # … but host 2 is the one the process table says is stopped
    events = policy.observe({"hosts": stale_all}, 20.0,
                            stopped=frozenset({2}))
    kills = [e for e in events if e["event"] == "kill"]
    assert len(kills) == 1
    assert kills[0]["host"] == 2
    assert kills[0]["not_scheduling"] is True
    assert kills[0]["wait_s"] == 5.0
    # One kill per attempt: the still-expired hostages survive the next
    # polls (the teardown takes a poll or two to surface)
    assert policy.observe({"hosts": stale_all}, 21.0,
                          stopped=frozenset()) == []
    assert len(policy.kills) == 1


def test_straggler_policy_blames_longest_suspect_without_proc_evidence():
    policy = _armed_policy([0, 1], wait_s=5.0)
    policy.observe({"hosts": {0: _lv_row("alive", 3),
                              1: _lv_row("stale", 2, 3.0)}}, 10.0)
    policy.observe({"hosts": {0: _lv_row("stale", 3, 2.0),
                              1: _lv_row("stale", 2, 5.0)}}, 12.0)
    events = policy.observe({"hosts": {0: _lv_row("stale", 3, 10.0),
                                       1: _lv_row("stale", 2, 13.0)}},
                            20.0)
    kills = [e for e in events if e["event"] == "kill"]
    assert [k["host"] for k in kills] == [1]  # suspect longest
    assert kills[0]["not_scheduling"] is False


def test_straggler_policy_health_quarantine_hysteresis():
    """The arena's quarantine hysteresis at host scope: `anomaly_enter`
    consecutive anomalous polls to enter SUSPECT, `anomaly_clear` clean
    polls to leave — one bad window is not a verdict, one good window is
    not absolution."""
    bad = {"anomaly": True}
    policy = _armed_policy([0], wait_s=50.0, quarantine=True,
                           anomaly_enter=3, anomaly_clear=2)
    t = 10.0
    for _ in range(2):
        assert policy.observe(
            {"hosts": {0: _lv_row("alive", 3, health=bad)}}, t) == []
        t += 1.0
    events = policy.observe(
        {"hosts": {0: _lv_row("alive", 3, health=bad)}}, t)
    assert [e["event"] for e in events] == ["suspect"]
    assert events[0]["reason"] == "health"
    # A single clean poll does not clear it …
    assert policy.observe(
        {"hosts": {0: _lv_row("alive", 4, health={"anomaly": False})}},
        t + 1.0) == []
    # … the second does
    events = policy.observe(
        {"hosts": {0: _lv_row("alive", 5, health={"anomaly": False})}},
        t + 2.0)
    assert [e["event"] for e in events] == ["recovered"]
    # Without --quarantine the same stream is invisible to the policy
    blind = _armed_policy([0], wait_s=50.0)
    t = 10.0
    for _ in range(5):
        assert blind.observe(
            {"hosts": {0: _lv_row("alive", 3, health=bad)}}, t) == []
        t += 1.0


def test_straggler_policy_reset_keeps_lifetime_counters():
    policy = _armed_policy([0], wait_s=2.0)
    policy.observe({"hosts": {0: _lv_row("stale", 2, 3.0)}}, 10.0)
    events = policy.observe({"hosts": {0: _lv_row("stale", 2, 9.0)}},
                            16.0)
    assert [e["event"] for e in events] == ["kill"]
    assert len(policy.kills) == 1
    policy.reset()
    # Per-attempt state gone: the relaunched host starts cold (unarmed),
    # so an immediate stall is compile-shaped again, not suspect
    assert policy.observe({"hosts": {0: _lv_row("stale", 4, 9.0)}},
                          30.0) == []
    # Lifetime counters survive for the artifact
    summary = policy.summary()
    assert len(summary["kills"]) == 1
    assert summary["suspects_entered"] == 1


def test_resolve_wait_bound_precedence(tmp_path):
    assert resolve_wait_bound(7.5, None) == (7.5, "flag")
    edges = tmp_path / "edges.json"
    edges.write_text(json.dumps({
        "recommended_wait_s": 3.0,
        "recommendation": {"wait_s": 2.5, "basis": "p95_recoveries"}}))
    assert resolve_wait_bound(None, edges) == (2.5,
                                               "stale-edges:p95_recoveries")
    # The flag still wins over the file
    assert resolve_wait_bound(9.0, edges) == (9.0, "flag")
    # Legacy summaries without the block fall back to the flat key
    edges.write_text(json.dumps({"recommended_wait_s": 4.0}))
    assert resolve_wait_bound(None, edges) == (
        4.0, "stale-edges:recommended_wait_s")
    # A summary with NO recommendation is an error, not a silent default
    edges.write_text(json.dumps({
        "recommendation": {"wait_s": None, "basis": None}}))
    with pytest.raises(ValueError, match="no recommendation"):
        resolve_wait_bound(None, edges)
    assert resolve_wait_bound(None, None) == (DEFAULT_WAIT_S, "default")


class _FakeProc:
    def __init__(self):
        self.signals = []

    def send_signal(self, sig):
        self.signals.append(sig)

    def poll(self):
        return None


def test_straggle_resumer_disposes_each_window_exactly_once():
    import signal as signal_mod

    resumer = StraggleResumer()
    try:
        quick, parked = _FakeProc(), _FakeProc()
        resumer.schedule(1, quick, 0.05)
        deadline = time.time() + 5.0
        while not resumer.resumed() and time.time() < deadline:
            time.sleep(0.01)
        assert [h for h, _ in resumer.resumed()] == [1]
        assert quick.signals == [signal_mod.SIGCONT]
        # A pending window cancelled (straggler kill) NEVER gets its
        # SIGCONT; cancel reports it claimed the disposition
        resumer.schedule(2, parked, 60.0)
        assert resumer.cancel(2) == 1
        assert resumer.cancel(2) == 0  # already disposed
        stats = resumer.stats()
        assert stats == {"pending": 0, "resumed": 1, "cancelled": 1}
        assert parked.signals == []
    finally:
        resumer.stop()
    assert parked.signals == []  # stop() resumes nothing cancelled


# --------------------------------------------------------------------------- #
# Off-slice checkpoint mirroring

def test_find_latest_valid_any_prefers_newest_across_dirs(tmp_path):
    local = tmp_path / "local"
    mirror = tmp_path / "mirror"
    _fake_checkpoint(local, 4)
    _fake_checkpoint(mirror, 6)
    found = checkpoint.find_latest_valid_any((local, mirror))
    assert found.parent == mirror and checkpoint.checkpoint_step(found) == 6
    # Losing the whole local directory costs nothing
    found = checkpoint.find_latest_valid_any((tmp_path / "gone", mirror))
    assert checkpoint.checkpoint_step(found) == 6
    # None entries (no mirror configured) are skipped
    found = checkpoint.find_latest_valid_any((local, None))
    assert checkpoint.checkpoint_step(found) == 4
    assert checkpoint.find_latest_valid_any((None, None)) is None


def test_save_mirror_writes_both_copies(tmp_path):
    import jax

    from byzantinemomentum_tpu import losses, ops
    from byzantinemomentum_tpu.arena.loop import probe_loss, probe_model_def
    from byzantinemomentum_tpu.engine import EngineConfig, build_engine

    engine = build_engine(
        cfg=EngineConfig(nb_workers=3, nb_decl_byz=0, nb_real_byz=0,
                         nb_for_study=0),
        model_def=probe_model_def(4), loss=probe_loss(),
        criterion=losses.Criterion("sigmoid"),
        defenses=[(ops.gars["average"], 1.0, {})])
    state = engine.init(jax.random.PRNGKey(0))
    local = tmp_path / "local"
    mirror = tmp_path / "mirror"
    local.mkdir()
    checkpoint.save(local / "checkpoint-0", state, mirror=mirror)
    assert (local / "checkpoint-0").read_bytes() == \
        (mirror / "checkpoint-0").read_bytes()
    # Both directories carry their own manifest entry
    assert checkpoint.read_manifest(local)["checkpoints"][0]["step"] == 0
    assert checkpoint.read_manifest(mirror)["checkpoints"][0]["step"] == 0
    # And both copies verify + load independently
    assert checkpoint.verify(mirror / "checkpoint-0")
    restored = checkpoint.load(mirror / "checkpoint-0", state)
    assert int(restored.steps) == 0


# --------------------------------------------------------------------------- #
# Bounded unavailability (the MULTICHIP_r05 lesson, satellite)

def test_unreachable_coordinator_is_a_clean_bounded_exit(tmp_path):
    """A follower whose coordinator never answers must exit with the
    reserved UNAVAILABLE_RC within its bounded timeout — a clean
    machine-readable line, never an rc=124 CI hang."""
    port = free_port()  # probed then released: nothing listens on it
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "byzantinemomentum_tpu.cluster.host",
         "--procs", "2", "--proc-id", "1",
         "--coordinator", f"127.0.0.1:{port}",
         "--connect-timeout", "2",
         "--result-directory", str(tmp_path / "run"),
         "--mirror", str(tmp_path / "mirror")],
        cwd=ROOT, capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    elapsed = time.monotonic() - t0
    assert proc.returncode == UNAVAILABLE_RC, proc.stderr[-2000:]
    assert "cluster-host: unavailable:" in proc.stdout
    assert elapsed < 90  # bounded: the 2s timeout plus process overhead


# --------------------------------------------------------------------------- #
# Driver integration: --checkpoint-mirror resumes through the mirror

def test_driver_checkpoint_mirror_survives_local_loss(tmp_path,
                                                      monkeypatch):
    """`cli/attack.py --checkpoint-mirror`: checkpoints land in both
    directories, and after the run directory's local checkpoints are
    destroyed, `--auto-resume` restarts from the mirror's copy."""
    monkeypatch.setenv("BMT_SYNTH_TRAIN", "256")
    monkeypatch.setenv("BMT_SYNTH_TEST", "64")
    from byzantinemomentum_tpu.cli.attack import main

    resdir = tmp_path / "run"
    mirror = tmp_path / "offslice"
    argv = ["--nb-steps", "4", "--batch-size", "8",
            "--batch-size-test", "32", "--batch-size-test-reps", "1",
            "--evaluation-delta", "0", "--checkpoint-delta", "2",
            "--model", "simples-full", "--seed", "7", "--gar", "median",
            "--nb-for-study", "0", "--auto-resume",
            "--result-directory", str(resdir),
            "--checkpoint-mirror", str(mirror)]
    assert main(argv) == 0
    assert (resdir / "checkpoint-2").is_file()
    assert (mirror / "checkpoint-2").is_file()
    # The local slice dies; the mirror is the only surviving copy
    for path in resdir.glob("checkpoint-*"):
        path.unlink()
    assert main(argv) == 0
    from byzantinemomentum_tpu import obs

    records = obs.load_records(resdir)
    restarts = [r for r in records if r.get("name") == "restart"]
    assert restarts and restarts[-1]["data"]["step"] >= 2


# --------------------------------------------------------------------------- #
# The real fleets (slow): recovery proof + Jobs supervision

def _smoke_env():
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", BMT_SYNTH_TRAIN="512",
               BMT_SYNTH_TEST="128")
    return env


@pytest.mark.slow
def test_cluster_kill_one_host_recovery_is_bit_identical(tmp_path):
    """The chaos acceptance at CI size: 2-host fleet, one host SIGKILLed
    mid-step by the system FaultPlan, launcher-recovered through the
    manifest + mirror; the resumed study CSV equals the uninterrupted
    fleet's byte for byte and the consensus trail is on the timeline."""
    proc = subprocess.run(
        [sys.executable, "scripts/cluster_smoke.py", "--smoke",
         "--workdir", str(tmp_path)],
        cwd=ROOT, env=_smoke_env(), capture_output=True, text=True,
        timeout=1100)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines()
            if l.startswith("cluster-smoke: ")][-1]
    payload = json.loads(line[len("cluster-smoke: "):])
    assert payload["status"] == "ok"
    assert payload["bit_identical"] is True
    assert payload["recovery_steps"] >= 1
    artifact = json.loads((tmp_path / "CLUSTER.json").read_text())
    assert artifact["kind"] == "cluster" and artifact["hosts"] == 2
    assert artifact["census"]["ok"] is True
    assert artifact["zero_recompile"]["asserted"] is True
    # The consensus trail: the chaos fleet's manifest fired the fault
    # once and recorded the agreed restart step; the relaunched hosts
    # reported unanimous adoption (restart_agreed on the timeline)
    manifest = json.loads((tmp_path / "chaos" / "cluster.json").read_text())
    assert manifest["fired_faults"] == [0]
    assert manifest["recoveries"][0]["restart_step"] is not None
    events = [json.loads(l)["name"]
              for l in (tmp_path / "chaos"
                        / "telemetry.jsonl").read_text().splitlines()
              if '"kind":"event"' in l]
    assert "fault_injected" in events
    assert "host_dead" in events
    assert "restart_agreed" in events
    # Fleet-wide attribution (PR 13, obs/trace/fleet.py): the run dir's
    # launcher + per-host telemetry streams join into ONE causally
    # ordered timeline — the fired fault, the host death and the agreed
    # restart step must read as ordered events, host clock offsets
    # estimated from the heartbeat handshake
    from byzantinemomentum_tpu.obs.trace import (
        estimate_offsets, fleet_timeline, load_fleet)
    chaos_dir = tmp_path / "chaos"
    fleet = load_fleet(chaos_dir)
    assert sorted(fleet["hosts"]) == [0, 1]  # every host left a stream
    assert estimate_offsets(fleet["launcher"])  # handshake estimates
    timeline = fleet_timeline(chaos_dir)
    names = [entry["name"] for entry in timeline]
    assert names.index("fault_injected") < names.index("host_dead") \
        < names.index("restart_agreed")
    # Host streams interleave: the killed host started, the relaunch
    # adopted the agreed step (host_resume), and liveness edges are
    # first-class events
    sources = {entry["source"] for entry in timeline}
    assert {"launcher", "host-0", "host-1"} <= sources
    assert "host_resume" in names and "liveness_transition" in names
    # The one-pager renders the same ordered story for the run dir
    from byzantinemomentum_tpu.obs.report import render_report
    from byzantinemomentum_tpu.obs.trace import render_fleet_report
    assert "fleet timeline" in render_report(chaos_dir)
    full = "\n".join(render_fleet_report(chaos_dir, limit=1000))
    assert full.index("fault_injected") < full.index("host_dead") \
        < full.index("restart_agreed")


@pytest.mark.slow
def test_jobs_supervises_cluster_launcher_service_job(tmp_path,
                                                      monkeypatch):
    """Satellite: the Jobs watchdog consumes the launcher's AGGREGATED
    cluster heartbeat through the seedless service-job form. The wedge
    hook kills the fleet and silences the launcher mid-run; the watchdog
    must SIGKILL the launcher and the retry (with --auto-resume, in the
    same pending dir) must resume the whole fleet to a study CSV
    bit-identical to an uninterrupted fleet's."""
    from byzantinemomentum_tpu.utils.jobs import Jobs

    env = _smoke_env()
    for key, value in env.items():
        monkeypatch.setenv(key, value)
    # Reference fleet: uninterrupted
    full = tmp_path / "full"
    proc = subprocess.run(
        [sys.executable, "-m", "byzantinemomentum_tpu.cluster",
         "--hosts", "2", "--result-directory", str(full),
         "--nb-steps", "4", "--checkpoint-delta", "2", "--poll", "0.1"],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-1000:]

    # Supervised fleet: wedges at step 2 on the first attempt only (the
    # fuse file lives in the pending dir the retry shares)
    monkeypatch.setenv("BMT_CHAOS_CLUSTER_WEDGE_AT", "2")
    grid = tmp_path / "grid"
    command = [sys.executable, "-m", "byzantinemomentum_tpu.cluster",
               "--hosts", "2", "--nb-steps", "4",
               "--checkpoint-delta", "2", "--poll", "0.1",
               "--fleet-retries", "0"]
    jobs = Jobs(grid, seeds=(None,), max_retries=1, retry_backoff=0,
                heartbeat_timeout=5.0)
    jobs.submit("fleet", command)
    jobs.wait()
    done = grid / "fleet"
    assert done.is_dir(), list(grid.iterdir())
    assert (done / "wedge.fired").exists()  # the first attempt really hung
    assert (done / "study").read_bytes() == (full / "study").read_bytes()
    artifact = json.loads((done / "CLUSTER.json").read_text())
    assert artifact["status"] == "ok"
