"""Numerics flight recorder (PR 15): in-jit health stats, the SPC
monitor, and the early-warning rollback trigger.

Covers the ISSUE 15 acceptance surface: the sharded-vs-unsharded
health-BUCKET bit-exactness oracle (mesh2, width-aware masks, f in
{1, 2, 3}, planted NaN rows), monitor unit behavior (warm-up,
hysteresis, blackbox ring bounding), the zero-recompile budget with
health ON, and the e2e anomaly -> rollback story under empire at
momentum-at-worker — including the headline claim: on a planted gradual
divergence the SPC anomaly fires at least 2 steps BEFORE the isfinite
flag, and `--rollback-on-anomaly` rolls back (and, budget spent, gives
up) without the state ever going non-finite.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byzantinemomentum_tpu import attacks, losses, models, obs, ops
from byzantinemomentum_tpu.engine import (EngineConfig, HEALTH_COLUMNS,
                                          build_engine)
from byzantinemomentum_tpu.engine import health
from byzantinemomentum_tpu.obs.health import HealthMonitor, load_blackbox
from byzantinemomentum_tpu.parallel import make_mesh

DRIVER_BASE = ["--batch-size", "8", "--batch-size-test", "32",
               "--batch-size-test-reps", "2", "--evaluation-delta", "0",
               "--model", "simples-full", "--seed", "11",
               "--nb-for-study", "11", "--nb-for-study-past", "2"]


@pytest.fixture(autouse=True)
def small_synth(monkeypatch):
    monkeypatch.setenv("BMT_SYNTH_TRAIN", "512")
    monkeypatch.setenv("BMT_SYNTH_TEST", "128")


def _vector(var=0.5, upd=1e-3, weight=6.0, nonfinite=0):
    return {"var_ratio": var, "update_ratio": upd, "weight_norm": weight,
            "nonfinite": nonfinite, "norm_hist": [0.0] * health.HIST_BINS}


# --------------------------------------------------------------------------- #
# In-jit stats


def test_norm_histogram_routing():
    """Exact zeros -> underflow bin, non-finite -> overflow bin, finite
    norms -> their log2 bucket; counts always sum to the row count."""
    norms = jnp.asarray([0.0, 2.0 ** health.HIST_LO, 1.0, 2.0 ** 19,
                         np.inf, np.nan], jnp.float32)
    hist = np.asarray(health.norm_histogram(norms))
    assert hist.sum() == len(norms)
    assert hist[0] == 2.0               # the exact zero + the underflow edge
    assert hist[-1] == 3.0              # inf + nan + the 2^19 overflow bucket
    mid = (0 - health.HIST_LO) // health.HIST_WIDTH
    assert hist[mid] == 1.0             # norm 1.0 -> log2 0


def test_health_metrics_values_and_nonfinite():
    rng = np.random.default_rng(0)
    d = 64
    Gh = rng.normal(size=(6, d)).astype(np.float32)
    Ga = rng.normal(size=(2, d)).astype(np.float32)
    Ga[0] = np.nan
    gd = rng.normal(size=(d,)).astype(np.float32)
    t0 = rng.normal(size=(d,)).astype(np.float32)
    t1 = t0 - 0.1 * gd
    out = health.health_metrics(*map(jnp.asarray, (Gh, Ga, gd, t0, t1)))
    assert set(out) == set(HEALTH_COLUMNS)
    assert float(out["Nonfinite submitted"]) == 1.0
    assert float(out["Nonfinite aggregate"]) == 0.0
    assert float(out["Nonfinite state"]) == 0.0
    np.testing.assert_allclose(float(out["Weight norm"]),
                               np.linalg.norm(t1), rtol=1e-5)
    np.testing.assert_allclose(float(out["Update norm"]),
                               np.linalg.norm(t0 - t1), rtol=1e-5)
    # Var ratio == the forensic Var/norm ratio definition
    from byzantinemomentum_tpu.ops import diag
    np.testing.assert_allclose(float(out["Var ratio"]),
                               float(diag.var_norm_ratio(jnp.asarray(Gh))),
                               rtol=1e-5)
    hist = np.asarray(out["Norm hist"])
    assert hist.sum() == 8 and hist[-1] >= 1.0  # the NaN row in overflow


@pytest.mark.parametrize("f", [1, 2, 3])
def test_sharded_health_buckets_bit_identical(f):
    """The d-sharded health stats (mesh2, width-aware real-column masks,
    non-dividing d so the facade pads a zero column) reproduce the
    single-device BUCKET counts and non-finite counts BIT-exactly with f
    planted NaN rows; the continuous scalars match to psum-vs-full-width
    reduction rounding."""
    mesh = make_mesh(2, model_parallel=2)
    n, d = 4 * f + 4, 67  # 67 % 2 != 0: one divisibility-padding column
    rng = np.random.default_rng(10 * f)
    G = (rng.normal(size=(n, d)) * rng.uniform(1e-3, 1e3)).astype(np.float32)
    G[-f:] = np.nan
    Gh, Ga = map(jnp.asarray, (G[: n - f], G[n - f:]))
    gd = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))
    t0 = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))
    t1 = t0 - 0.05 * gd
    u = health.health_metrics(Gh, Ga, gd, t0, t1)
    s = health.sharded_health_metrics(mesh)(Gh, Ga, gd, t0, t1)
    assert np.array_equal(np.asarray(u["Norm hist"]),
                          np.asarray(s["Norm hist"]))
    for key in ("Nonfinite submitted", "Nonfinite aggregate",
                "Nonfinite state"):
        assert float(u[key]) == float(s[key]), key
    assert float(s["Nonfinite submitted"]) == float(f)
    for key in ("Var ratio", "Weight norm", "Update norm", "Update/weight"):
        np.testing.assert_allclose(float(u[key]), float(s[key]),
                                   rtol=1e-5, err_msg=key)


def _smoke_engine(health_on, **overrides):
    cfg = EngineConfig(nb_workers=7, nb_decl_byz=2, nb_real_byz=2,
                       nb_for_study=7, nb_for_study_past=2, momentum=0.9,
                       momentum_at="worker", health=health_on, **overrides)
    engine = build_engine(
        cfg=cfg, model_def=models.build("simples-full"),
        loss=losses.Loss("nll"), criterion=losses.Criterion("top-k"),
        defenses=[(ops.gars["krum"], 1.0, {})],
        attack=attacks.attacks["empire"], attack_kwargs={"factor": 1.1})
    return engine, engine.init(jax.random.PRNGKey(0))


def test_engine_health_columns_ride_the_metrics():
    engine, state = _smoke_engine(True)
    S, B = engine.cfg.nb_sampled, 4
    xs = jnp.zeros((S, B, 28, 28, 1), jnp.float32)
    ys = jnp.zeros((S, B), jnp.int32)
    state, metrics = engine.train_step(state, xs, ys, jnp.float32(0.05))
    for column in HEALTH_COLUMNS:
        assert column in metrics, column
    assert float(np.asarray(metrics["Norm hist"]).sum()) == engine.cfg.nb_workers

    engine_off, state_off = _smoke_engine(False)
    state_off, metrics_off = engine_off.train_step(
        state_off, xs, ys, jnp.float32(0.05))
    assert not any(c in metrics_off for c in HEALTH_COLUMNS)


def test_engine_health_zero_recompiles_warm_loop():
    """Health ON keeps the engine's zero-recompile budget: the health
    vector is extra outputs of the SAME compiled step, never a retrace."""
    from byzantinemomentum_tpu.analysis.contracts import (
        assert_recompile_budget)

    engine, state = _smoke_engine(True)
    S, B = engine.cfg.nb_sampled, 4
    rng = np.random.default_rng(1)

    def step(state):
        xs = jnp.asarray(rng.normal(size=(S, B, 28, 28, 1))
                         .astype(np.float32))
        ys = jnp.asarray(rng.integers(0, 10, size=(S, B)).astype(np.int32))
        return engine.train_step(state, xs, ys, jnp.float32(0.05))

    state, _ = step(state)  # warm-up compile outside the budget window
    holder = [state]

    def warm():
        holder[0], metrics = step(holder[0])
        return metrics

    assert_recompile_budget(warm, steps=3, budget=0,
                            label="health-on warm loop")


# --------------------------------------------------------------------------- #
# Monitor units


def test_monitor_warmup_gates_statistical_rules():
    mon = HealthMonitor(warmup=50)
    # A wild stream inside warm-up must not fire the statistical rules
    for step in range(40):
        mon.update(step, _vector(var=0.5 * (10.0 ** (step % 3))))
    assert mon.anomalies_total == 0


def test_monitor_nonfinite_rule_is_warmup_exempt():
    mon = HealthMonitor(warmup=50)
    mon.update(0, _vector())
    assert mon.update(1, _vector(nonfinite=2))
    assert mon.anomaly and mon.last_anomaly["channel"] == "nonfinite"


def test_monitor_hysteresis_clears_after_clean_run():
    mon = HealthMonitor(warmup=10, clear_after=5)
    for step in range(30):
        mon.update(step, _vector())
    # Spike episode, then a clean stream: the channel must clear only
    # after `clear_after` consecutive in-control observations
    assert mon.update(30, _vector(var=5e4))
    cleared_at = None
    for step in range(31, 50):
        active = mon.update(step, _vector())
        if not active and cleared_at is None:
            cleared_at = step
    assert cleared_at is not None and cleared_at - 30 >= 5
    assert any(e["kind"] == "health_cleared" for e in mon.blackbox("t")["edges"])


def test_monitor_baseline_freezes_while_anomalous():
    """The envelope must not adapt to the failure it is flagging: a
    sustained 1000x collapse stays anomalous (a live EWMA would absorb
    it and self-clear)."""
    mon = HealthMonitor(warmup=10, clear_after=5)
    for step in range(30):
        mon.update(step, _vector())
    for step in range(30, 80):
        mon.update(step, _vector(var=5e-4))
    assert mon.anomaly


def test_monitor_rollback_pending_consume_once():
    mon = HealthMonitor(warmup=5)
    for step in range(20):
        mon.update(step, _vector())
    mon.update(20, _vector(var=1e5))
    assert mon.rollback_pending()
    assert not mon.rollback_pending()  # consumed: one rollback per episode
    mon.note_rollback()
    assert not mon.anomaly


def test_monitor_blackbox_ring_bounded_and_dump(tmp_path):
    mon = HealthMonitor(ring=16)
    for step in range(100):
        mon.update(step, _vector())
    box = mon.blackbox("test")
    assert len(box["ring"]) == 16
    assert box["ring"][-1]["step"] == 99
    path = mon.dump_blackbox(tmp_path, "test")
    assert path is not None
    loaded = load_blackbox(tmp_path)
    assert loaded["reason"] == "test" and len(loaded["ring"]) == 16
    json.dumps(loaded)  # JSON-safe end to end


def test_monitor_validation():
    with pytest.raises(ValueError, match="alpha"):
        HealthMonitor(alpha=0.0)
    with pytest.raises(ValueError, match="warmup"):
        HealthMonitor(warmup=0)
    with pytest.raises(ValueError, match="ring"):
        HealthMonitor(ring=0)
    with pytest.raises(ValueError, match="z_clear"):
        HealthMonitor(z_clear=5.0, z_run4=2.0)


def test_monitor_nonfinite_channel_value_never_folds():
    """A NaN channel VALUE (e.g. Var ratio after gradients vanished) must
    not poison the baseline; the non-finite COUNT rule covers the hard
    case."""
    mon = HealthMonitor(warmup=5)
    for step in range(20):
        mon.update(step, _vector())
    before = mon.summary()["channels"]["var_ratio"]["mean_log10"]
    mon.update(20, _vector(var=float("nan")))
    after = mon.summary()["channels"]["var_ratio"]["mean_log10"]
    assert before == after


# --------------------------------------------------------------------------- #
# Driver e2e: empire at momentum-at-worker, early-warning acceptance


def _ew_args(resdir, extra):
    return DRIVER_BASE + [
        "--gar", "krum", "--nb-real-byz", "2", "--attack", "empire",
        "--attack-args", "factor:1.1", "--momentum-at", "worker",
        "--nb-steps", "48", "--checkpoint-delta", "5",
        "--steps-per-program", "1", "--rollback-budget", "1",
        "--result-directory", str(resdir)] + extra


def test_driver_anomaly_leads_isfinite_flag(tmp_path, monkeypatch):
    """The acceptance headline: on a planted gradual divergence
    (BMT_CHAOS_BLOWUP) under empire at momentum-at-worker, the SPC
    anomaly fires >= 2 steps before the isfinite flag, the blackbox is
    written, and obs_report renders the health line."""
    from byzantinemomentum_tpu.cli.attack import main
    from byzantinemomentum_tpu.obs.report import render_report

    monkeypatch.setenv("BMT_CHAOS_BLOWUP_AT_STEP", "36")
    monkeypatch.setenv("BMT_CHAOS_BLOWUP_FACTOR", "1e6")
    resdir = tmp_path / "lead"
    rc = main(_ew_args(resdir, ["--health"]))
    assert rc == 1  # budget 1, the blow-up repeats: divergence give-up
    records = obs.load_records(resdir)
    anomalies = [r for r in records if r["name"] == "health_anomaly"]
    flags = [r for r in records if r["name"] == "health_flag"
             and r["data"]["trigger"] == "non-finite"]
    assert anomalies and flags
    lead = (min(r["data"]["step"] for r in flags)
            - min(r["data"]["step"] for r in anomalies))
    assert lead >= 2, f"anomaly must lead the isfinite flag, lead={lead}"
    box = load_blackbox(resdir)
    assert box is not None and box["reason"] == "divergence_giveup"
    assert box["ring"] and box["edges"]
    report = render_report(resdir)
    assert "health:" in report and "blackbox" in report


def test_driver_rollback_on_anomaly_fires_before_nonfinite(tmp_path,
                                                           monkeypatch):
    """--rollback-on-anomaly upgrades the trigger: the rollback (and the
    eventual budget-spent give-up) happens on the ANOMALY edge — the
    state never reaches the non-finite flag."""
    from byzantinemomentum_tpu.cli.attack import main

    monkeypatch.setenv("BMT_CHAOS_BLOWUP_AT_STEP", "36")
    monkeypatch.setenv("BMT_CHAOS_BLOWUP_FACTOR", "1e6")
    resdir = tmp_path / "anomaly"
    rc = main(_ew_args(resdir, ["--rollback-on-anomaly"]))
    assert rc == 1
    records = obs.load_records(resdir)
    rollbacks = [r for r in records if r["name"] == "rollback"]
    assert rollbacks and rollbacks[0]["data"]["trigger"] == "anomaly"
    flags = [r["data"]["trigger"] for r in records
             if r["name"] == "health_flag"]
    assert flags and all(t == "anomaly" for t in flags)
    assert any(r["name"] == "divergence_giveup" for r in records)
    heartbeat = obs.read_heartbeat(resdir)
    assert "health" in heartbeat
    assert heartbeat["health"]["anomalies_total"] >= 1


def test_driver_clean_run_health_columns_no_false_positives(tmp_path):
    """A clean short run with --health: health columns land in the study
    CSV, the heartbeat carries the health block, the blackbox dumps with
    reason run_end — and the monitor stays quiet."""
    from byzantinemomentum_tpu.cli.attack import main

    resdir = tmp_path / "clean"
    rc = main(DRIVER_BASE + ["--gar", "median", "--nb-steps", "40",
                             "--steps-per-program", "2", "--health",
                             "--result-directory", str(resdir)])
    assert rc == 0
    header = (resdir / "study").read_text().splitlines()[0]
    for column in HEALTH_COLUMNS:
        assert column in header, column
    records = obs.load_records(resdir)
    assert not [r for r in records if r["name"] == "health_anomaly"]
    summary = [r for r in records if r["name"] == "health_summary"]
    assert summary and summary[-1]["data"]["anomalies_total"] == 0
    box = load_blackbox(resdir)
    assert box is not None and box["reason"] == "run_end"
    assert len(box["ring"]) == 40
    heartbeat = obs.read_heartbeat(resdir)
    assert heartbeat["health"]["var_ratio_ewma"] is not None


def test_driver_flag_validation(tmp_path, capsys):
    """--health without the study pipeline warns and disables;
    --rollback-on-anomaly without a rollback budget warns and disables
    (but keeps --health)."""
    from byzantinemomentum_tpu.cli.attack import main

    assert main(DRIVER_BASE + ["--nb-steps", "0", "--health"]) == 0
    err = capsys.readouterr().err
    assert "needs the study pipeline" in err

    resdir = tmp_path / "nobudget"
    rc = main(DRIVER_BASE + ["--nb-steps", "2", "--rollback-on-anomaly",
                             "--result-directory", str(resdir)])
    assert rc == 0
    err = capsys.readouterr().err
    assert "rollback-budget" in err
    # --health stayed on (implied) even though the trigger was disabled
    assert "Var ratio" in (resdir / "study").read_text().splitlines()[0]


# --------------------------------------------------------------------------- #
# Study renderings


def test_study_health_plots(tmp_path):
    from byzantinemomentum_tpu.cli.attack import main
    import study

    resdir = tmp_path / "plots"
    rc = main(DRIVER_BASE + ["--gar", "median", "--nb-steps", "8",
                             "--steps-per-program", "2", "--health",
                             "--result-directory", str(resdir)])
    assert rc == 0
    sess = study.Session(resdir)
    plot = study.variance_envelope(sess)
    plot.save(tmp_path / "envelope.png")
    plot.close()
    plot = study.health_timeline(sess)
    plot.save(tmp_path / "timeline.png")
    plot.close()
    assert (tmp_path / "envelope.png").stat().st_size > 0
    assert (tmp_path / "timeline.png").stat().st_size > 0

    # A health-less run raises the documented UserException
    from byzantinemomentum_tpu import utils
    bare = tmp_path / "bare"
    assert main(DRIVER_BASE + ["--gar", "median", "--nb-steps", "2",
                               "--result-directory", str(bare)]) == 0
    with pytest.raises(utils.UserException, match="--health"):
        study.variance_envelope(study.Session(bare))
