"""PyTorch-CPU oracles for differential tests.

These re-state the *behavior* of the reference's aggregation rules and
attacks (reference `/root/reference/aggregators/`, `/root/reference/attacks/`)
in independent torch code, used only as test fixtures: the framework's jnp
kernels must agree with them on identical inputs (within f32 tolerance).

The single deliberate divergence: "median" here means the sort-based lower
median with NaN-last ordering (the semantics the reference documents and its
original CUDA runtime provided), because modern torch-CPU `median` propagates
NaN — see `byzantinemomentum_tpu/ops/_common.py`.
"""

import itertools
import math

import torch


def lower_median(stack):
    n = stack.shape[0]
    return stack.sort(dim=0).values[(n - 1) // 2]


def pairwise_dist_matrix(stack):
    n = stack.shape[0]
    dist = torch.full((n, n), math.inf, dtype=stack.dtype)
    for i in range(n):
        for j in range(i + 1, n):
            val = (stack[i] - stack[j]).norm().item()
            if not math.isfinite(val):
                val = math.inf
            dist[i, j] = dist[j, i] = val
    return dist


def gar_average(stack, f=None):
    return stack.mean(dim=0)


def gar_median(stack, f=None):
    return lower_median(stack)


def gar_trmean(stack, f):
    n = stack.shape[0]
    return stack.sort(dim=0).values[f:n - f].mean(dim=0)


def _closest_mean(stack, center, m):
    dev = (stack - center).abs()
    idx = dev.argsort(dim=0, stable=True)[:m]
    return stack.gather(0, idx).mean(dim=0)


def gar_phocas(stack, f):
    return _closest_mean(stack, gar_trmean(stack, f), stack.shape[0] - f)


def gar_meamed(stack, f):
    return _closest_mean(stack, lower_median(stack), stack.shape[0] - f)


def krum_scores(stack, f):
    n = stack.shape[0]
    dist = pairwise_dist_matrix(stack)
    scores = []
    for i in range(n):
        row = sorted(dist[i, j].item() for j in range(n) if j != i)
        scores.append(sum(row[:n - f - 1]))
    return scores


def gar_krum(stack, f, m=None):
    n = stack.shape[0]
    if m is None:
        m = n - f - 2
    scores = krum_scores(stack, f)
    order = sorted(range(n), key=lambda i: scores[i])
    return stack[order[:m]].mean(dim=0)


def gar_bulyan(stack, f, m=None):
    n = stack.shape[0]
    m_max = n - f - 2
    if m is None:
        m = m_max
    dist = pairwise_dist_matrix(stack)
    # Bulyan scores: sum of the m smallest neighbor distances per row
    # (self-distance is +inf so it never enters for m <= n-1).
    scores = []
    for i in range(n):
        row = sorted(dist[i, j].item() for j in range(n))
        scores.append(sum(row[:m]))
    scores = list(scores)
    rounds = n - 2 * f - 2
    selected = torch.empty((rounds, stack.shape[1]), dtype=stack.dtype)
    for i in range(rounds):
        m_i = min(m, m_max - i)
        order = sorted(range(n), key=lambda g: scores[g])
        selected[i] = stack[order[:m_i]].mean(dim=0)
        scores[order[0]] = math.inf  # effective reference pruning (dead update)
    m2 = rounds - 2 * f
    return _closest_mean(selected, lower_median(selected), m2)


def gar_aksel(stack, f, mode="mid"):
    n = stack.shape[0]
    med = lower_median(stack)
    sqd = []
    for i in range(n):
        val = (stack[i] - med).pow(2).sum().item()
        sqd.append(val if math.isfinite(val) else math.inf)
    c = (n + 1) // 2 if mode == "mid" else n - f
    order = sorted(range(n), key=lambda i: sqd[i])
    return stack[order[:c]].mean(dim=0)


def gar_cge(stack, f):
    n = stack.shape[0]
    norms = []
    for i in range(n):
        val = stack[i].norm().item()
        norms.append(val if math.isfinite(val) else math.inf)
    order = sorted(range(n), key=lambda i: norms[i])
    return stack[order[:n - f]].mean(dim=0)


def gar_brute(stack, f):
    n = stack.shape[0]
    dist = pairwise_dist_matrix(stack)
    best_set, best_diam = None, None
    for combo in itertools.combinations(range(n), n - f):
        diam = 0.0
        ok = True
        for x, y in itertools.combinations(combo, 2):
            val = dist[x, y].item()
            if not math.isfinite(val):
                ok = False
                break
            diam = max(diam, val)
        if ok and (best_set is None or diam < best_diam):
            best_set, best_diam = combo, diam
    return stack[list(best_set)].mean(dim=0)


def line_maximize(scape, evals=16, start=0.0, delta=1.0, ratio=0.8):
    """Reference search schedule (reference `tools/misc.py:468-514`)."""
    best_x = start
    best_y = scape(best_x)
    evals -= 1
    prop_x = best_x
    while evals > 0:
        prop_x = best_x + delta
        prop_y = scape(prop_x)
        evals -= 1
        if prop_y > best_y:
            best_x, best_y = prop_x, prop_y
            delta *= 2
        else:
            delta *= ratio
            break
    while evals > 0:
        if prop_x < best_x:
            prop_x += delta
        else:
            x = prop_x - delta
            while x < 0:
                x = (x + prop_x) / 2
            prop_x = x
        prop_y = scape(prop_x)
        evals -= 1
        if prop_y > best_y:
            best_x, best_y = prop_x, prop_y
        delta *= ratio
    return best_x
