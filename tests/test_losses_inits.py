"""Differential tests for the loss registry and the named-init registry.

Strategy (SURVEY.md §4): every loss is pinned against its `torch.nn.functional`
counterpart on random inputs; every named init against `torch.nn.init`
(exactly where deterministic, distributionally where random). The registries
are also checked name-for-name against what the reference's auto-registration
would expose (reference `experiments/loss.py:87-109`,
`experiments/model.py:92-113`).
"""

import math

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu import losses as L
from byzantinemomentum_tpu.models import core

RNG = np.random.default_rng(7)


def _logits(n=16, c=10):
    return RNG.normal(size=(n, c)).astype(np.float32)


def test_loss_registry_matches_reference_names():
    """Every name the reference's torch auto-registration exposes resolves
    here too — except `ctc`, whose 4-argument forward never fit the
    reference's own (output, target) wrapper (documented in losses.py)."""
    ref_names = set()
    for name in dir(torch.nn.modules.loss):
        if len(name) < 5 or name[0] == "_" or name[-4:] != "Loss":
            continue
        if isinstance(getattr(torch.nn.modules.loss, name), type):
            ref_names.add(name[:-4].lower())
    ref_names -= {"ctc", "linearcrossentropy"}  # documented exclusions
    ref_names |= {"l1", "l2"}  # the reference's own replacements
    missing = ref_names - set(L.losses)
    assert not missing, f"loss names missing vs reference registry: {missing}"


def test_init_registry_matches_reference_names():
    """Every `torch.nn.init.*_` name the reference registers (stripped of the
    trailing underscore, `experiments/model.py:92-113`) resolves here."""
    import types
    ref_names = set()
    for name in dir(torch.nn.init):
        if not name or name[0] == "_" or name[-1] != "_":
            continue
        if isinstance(getattr(torch.nn.init, name), types.FunctionType):
            ref_names.add(name[:-1])
    missing = ref_names - set(core.inits)
    assert not missing, f"init names missing vs reference registry: {missing}"


# --------------------------------------------------------------------------- #
# Loss differentials vs torch.nn.functional

def _check(name, out_np, tgt_np, torch_val, **kwargs):
    if isinstance(out_np, tuple):
        out = tuple(jnp.asarray(o) for o in out_np)
    else:
        out = jnp.asarray(out_np)
    got = float(L.Loss(name, **kwargs)(out, jnp.asarray(tgt_np), jnp.zeros(3)))
    np.testing.assert_allclose(got, float(torch_val), rtol=1e-5, atol=1e-6,
                               err_msg=name)


def test_nll():
    x = np.log(RNG.dirichlet(np.ones(10), size=16)).astype(np.float32)
    t = RNG.integers(0, 10, 16)
    _check("nll", x, t, F.nll_loss(torch.from_numpy(x), torch.from_numpy(t)))


def test_crossentropy():
    x, t = _logits(), RNG.integers(0, 10, 16)
    _check("crossentropy", x, t,
           F.cross_entropy(torch.from_numpy(x), torch.from_numpy(t)))


def test_mse_l1loss_smoothl1_huber():
    x = _logits()
    y = RNG.normal(size=x.shape).astype(np.float32)
    tx, ty = torch.from_numpy(x), torch.from_numpy(y)
    _check("mse", x, y, F.mse_loss(tx, ty))
    _check("l1loss", x, y, F.l1_loss(tx, ty))
    _check("smoothl1", x, y, F.smooth_l1_loss(tx, ty, beta=0.7), beta=0.7)
    _check("huber", x, y, F.huber_loss(tx, ty, delta=1.0), beta=1.0)


def test_bce_and_bcewithlogits():
    x = _logits()
    p = 1.0 / (1.0 + np.exp(-x))
    t = RNG.integers(0, 2, x.shape).astype(np.float32)
    _check("bce", p, t, F.binary_cross_entropy(torch.from_numpy(p),
                                               torch.from_numpy(t)))
    _check("bcewithlogits", x, t,
           F.binary_cross_entropy_with_logits(torch.from_numpy(x),
                                              torch.from_numpy(t)))


def test_kldiv():
    x = np.log(RNG.dirichlet(np.ones(10), size=16)).astype(np.float32)
    t = RNG.dirichlet(np.ones(10), size=16).astype(np.float32)
    _check("kldiv", x, t,
           F.kl_div(torch.from_numpy(x), torch.from_numpy(t),
                    reduction="batchmean"))


def test_hingeembedding_softmargin():
    x = _logits()
    t = (RNG.integers(0, 2, x.shape) * 2 - 1).astype(np.float32)
    tx, tt = torch.from_numpy(x), torch.from_numpy(t)
    _check("hingeembedding", x, t, F.hinge_embedding_loss(tx, tt, margin=1.0))
    _check("softmargin", x, t, F.soft_margin_loss(tx, tt))


def test_poissonnll():
    x = _logits()
    t = RNG.poisson(3.0, x.shape).astype(np.float32)
    tx, tt = torch.from_numpy(x), torch.from_numpy(t)
    _check("poissonnll", x, t, F.poisson_nll_loss(tx, tt))
    _check("poissonnll", x, t, F.poisson_nll_loss(tx, tt, full=True),
           full=True)
    xp = np.abs(x) + 0.1
    _check("poissonnll", xp, t,
           F.poisson_nll_loss(torch.from_numpy(xp), tt, log_input=False),
           log_input=False)


def test_multimargin():
    x, t = _logits(), RNG.integers(0, 10, 16)
    tx, tt = torch.from_numpy(x), torch.from_numpy(t)
    _check("multimargin", x, t, F.multi_margin_loss(tx, tt))
    _check("multimargin", x, t, F.multi_margin_loss(tx, tt, p=2, margin=0.5),
           p=2, margin=0.5)


def test_multilabelmargin():
    x = _logits(8, 6)
    # index rows terminated by -1 (torch's packed multilabel format)
    t = np.full((8, 6), -1, np.int64)
    for i in range(8):
        k = RNG.integers(1, 4)
        t[i, :k] = RNG.choice(6, size=k, replace=False)
    _check("multilabelmargin", x, t,
           F.multilabel_margin_loss(torch.from_numpy(x), torch.from_numpy(t)))


def test_multilabelsoftmargin():
    x = _logits(8, 6)
    t = RNG.integers(0, 2, x.shape).astype(np.float32)
    _check("multilabelsoftmargin", x, t,
           F.multilabel_soft_margin_loss(torch.from_numpy(x),
                                         torch.from_numpy(t)))


def test_cosineembedding_marginranking():
    x1 = RNG.normal(size=(12, 5)).astype(np.float32)
    x2 = RNG.normal(size=(12, 5)).astype(np.float32)
    t = (RNG.integers(0, 2, 12) * 2 - 1).astype(np.float32)
    _check("cosineembedding", (x1, x2), t,
           F.cosine_embedding_loss(torch.from_numpy(x1), torch.from_numpy(x2),
                                   torch.from_numpy(t), margin=0.2),
           margin=0.2)
    s1 = RNG.normal(size=12).astype(np.float32)
    s2 = RNG.normal(size=12).astype(np.float32)
    _check("marginranking", (s1, s2), t,
           F.margin_ranking_loss(torch.from_numpy(s1), torch.from_numpy(s2),
                                 torch.from_numpy(t), margin=0.1),
           margin=0.1)


def test_tripletmargin():
    a = RNG.normal(size=(12, 5)).astype(np.float32)
    p = RNG.normal(size=(12, 5)).astype(np.float32)
    n = RNG.normal(size=(12, 5)).astype(np.float32)
    ta, tp, tn = map(torch.from_numpy, (a, p, n))
    _check("tripletmargin", (a, p, n), np.zeros(12, np.float32),
           F.triplet_margin_loss(ta, tp, tn))
    _check("tripletmargin", (a, p, n), np.zeros(12, np.float32),
           F.triplet_margin_loss(ta, tp, tn, swap=True), swap=True)
    _check("tripletmarginwithdistance", (a, p, n), np.zeros(12, np.float32),
           F.triplet_margin_with_distance_loss(ta, tp, tn, margin=0.5),
           margin=0.5)


def test_gaussiannll():
    mu = RNG.normal(size=(12, 3)).astype(np.float32)
    var = (np.abs(RNG.normal(size=(12, 3))) + 0.1).astype(np.float32)
    t = RNG.normal(size=(12, 3)).astype(np.float32)
    _check("gaussiannll", (mu, var), t,
           F.gaussian_nll_loss(torch.from_numpy(mu), torch.from_numpy(t),
                               torch.from_numpy(var)))
    _check("gaussiannll", (mu, var), t,
           F.gaussian_nll_loss(torch.from_numpy(mu), torch.from_numpy(t),
                               torch.from_numpy(var), full=True), full=True)


def test_param_norm_regularizers():
    theta = RNG.normal(size=37).astype(np.float32)
    out = np.zeros((2, 2), np.float32)
    got1 = float(L.Loss("l1")(jnp.asarray(out), jnp.zeros(2), jnp.asarray(theta)))
    got2 = float(L.Loss("l2")(jnp.asarray(out), jnp.zeros(2), jnp.asarray(theta)))
    np.testing.assert_allclose(got1, np.abs(theta).sum(), rtol=1e-6)
    np.testing.assert_allclose(got2, np.sqrt((theta ** 2).sum()), rtol=1e-6)


# --------------------------------------------------------------------------- #
# Named-init differentials vs torch.nn.init

def test_eye_matches_torch():
    got = np.asarray(core.inits["eye"](jax.random.PRNGKey(0), (5, 8)))
    want = torch.nn.init.eye_(torch.empty(5, 8)).numpy()
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("groups", [1, 2])
def test_dirac_matches_torch(groups):
    """HWIO dirac == torch's OIHW dirac permuted — and a dirac conv is the
    channel identity."""
    kh = kw = 3
    cin = cout = 4
    got = np.asarray(core.inits["dirac"](jax.random.PRNGKey(0),
                                         (kh, kw, cin, cout), groups=groups))
    want = torch.nn.init.dirac_(torch.empty(cout, cin // 1, kh, kw),
                                groups=groups).numpy()
    # OIHW -> HWIO
    np.testing.assert_array_equal(got, want.transpose(2, 3, 1, 0))
    if groups == 1:
        x = jnp.asarray(RNG.normal(size=(2, 6, 6, cin)).astype(np.float32))
        out = jax.lax.conv_general_dilated(
            x, jnp.asarray(got), (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-6)


def test_trunc_normal_bounds_and_moments():
    key = jax.random.PRNGKey(1)
    got = np.asarray(core.inits["trunc_normal"](key, (20000,),
                                                mean=0.5, std=0.2,
                                                a=0.1, b=0.9))
    assert got.min() >= 0.1 and got.max() <= 0.9
    # Same distribution as torch's (both are N(mean, std) truncated to [a,b])
    want = torch.nn.init.trunc_normal_(torch.empty(20000), mean=0.5, std=0.2,
                                       a=0.1, b=0.9).numpy()
    assert abs(got.mean() - want.mean()) < 0.01
    assert abs(got.std() - want.std()) < 0.01


def test_sparse_structure():
    rows, cols, sparsity = 20, 7, 0.25
    got = np.asarray(core.inits["sparse"](jax.random.PRNGKey(2),
                                          (rows, cols), sparsity=sparsity))
    nz = math.ceil(sparsity * rows)
    # torch `sparse_`: exactly ceil(sparsity*rows) zeros per column
    zeros_per_col = (got == 0.0).sum(axis=0)
    assert (zeros_per_col == nz).all(), zeros_per_col
    nonzero = got[got != 0.0]
    assert abs(nonzero.std() - 0.01) < 0.005


def test_apply_named_init_routes_by_ndim():
    params = {"w": jnp.ones((4, 4)), "b": jnp.ones((4,))}
    out = core.apply_named_init(params, jax.random.PRNGKey(0),
                                init_multi="eye",
                                init_mono="constant",
                                init_mono_args={"val": 3.0})
    np.testing.assert_array_equal(np.asarray(out["w"]), np.eye(4))
    np.testing.assert_array_equal(np.asarray(out["b"]), np.full(4, 3.0))
