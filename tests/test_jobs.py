"""Jobs supervisor tests: retry with backoff, adoption of stale
`.pending`/`.failed` directories holding valid checkpoints (resume instead
of cold-start), the heartbeat watchdog, and race-free version rotation."""

import sys
import time

from byzantinemomentum_tpu import checkpoint
from byzantinemomentum_tpu.utils.jobs import Jobs

from tests.test_checkpoint import tiny_state

# Attempt counting lives OUTSIDE the pending dir (it is renamed on
# success/failure); `--result-directory` locates it through the parent.
_COUNTING = (
    "import sys, pathlib\n"
    "d = pathlib.Path(sys.argv[sys.argv.index('--result-directory') + 1])\n"
    "m = d.parent / 'attempts.txt'\n"
    "n = int(m.read_text()) if m.exists() else 0\n"
    "m.write_text(str(n + 1))\n")


def test_retry_until_success(tmp_path):
    """A run failing its first attempt is retried in the SAME pending
    directory and can complete on the second attempt."""
    script = _COUNTING + (
        "if n == 0:\n"
        "    sys.exit(7)\n"
        "(d / 'out.txt').write_text('done')\n")
    jobs = Jobs(tmp_path, seeds=(1,), max_retries=2, retry_backoff=0)
    jobs.submit("flaky", [sys.executable, "-c", script])
    jobs.wait()
    assert (tmp_path / "flaky-1" / "out.txt").read_text() == "done"
    assert (tmp_path / "attempts.txt").read_text() == "2"
    assert not (tmp_path / "flaky-1.failed").exists()


def test_gives_up_after_max_retries(tmp_path):
    script = _COUNTING + "sys.exit(3)\n"
    jobs = Jobs(tmp_path, seeds=(1,), max_retries=1, retry_backoff=0)
    jobs.submit("doomed", [sys.executable, "-c", script])
    jobs.wait()
    assert (tmp_path / "doomed-1.failed" / "stderr.log").exists()
    assert (tmp_path / "attempts.txt").read_text() == "2"  # 1 + max_retries


def test_adopts_failed_attempt_with_checkpoint(tmp_path):
    """A previous scheduler's `.failed` directory holding a valid
    checkpoint is adopted and resumed (the reference parks it forever): the
    dispatched command sees the resume flag AND the old checkpoint."""
    failed = tmp_path / "run-1.failed"
    failed.mkdir(parents=True)
    checkpoint.save(failed / "checkpoint-3", tiny_state(steps=3))
    script = (
        "import sys, pathlib\n"
        "assert '--auto-resume' in sys.argv\n"
        "d = pathlib.Path(sys.argv[sys.argv.index('--result-directory') + 1])\n"
        "assert (d / 'checkpoint-3').is_file()\n"
        "(d / 'out.txt').write_text('resumed')\n")
    jobs = Jobs(tmp_path, seeds=(1,), max_retries=0, retry_backoff=0)
    jobs.submit("run", [sys.executable, "-c", script])
    jobs.wait()
    assert (tmp_path / "run-1" / "out.txt").read_text() == "resumed"
    assert (tmp_path / "run-1" / "checkpoint-3").is_file()
    assert not failed.exists()


def test_adopts_stale_pending_with_checkpoint(tmp_path):
    """A stale `.pending` left by a killed scheduler is reused in place
    when it holds a valid checkpoint (instead of being rotated away)."""
    pending = tmp_path / "run-1.pending"
    pending.mkdir(parents=True)
    checkpoint.save(pending / "checkpoint-5", tiny_state(steps=5))
    script = (
        "import sys, pathlib\n"
        "d = pathlib.Path(sys.argv[sys.argv.index('--result-directory') + 1])\n"
        "assert (d / 'checkpoint-5').is_file()\n"
        "(d / 'out.txt').write_text('adopted')\n")
    jobs = Jobs(tmp_path, seeds=(1,), max_retries=0, retry_backoff=0)
    jobs.submit("run", [sys.executable, "-c", script])
    jobs.wait()
    assert (tmp_path / "run-1" / "out.txt").read_text() == "adopted"
    assert not list(tmp_path.glob("run-1.pending*"))


def test_stale_pending_without_checkpoint_is_rotated(tmp_path):
    pending = tmp_path / "run-1.pending"
    pending.mkdir(parents=True)
    (pending / "junk.txt").write_text("stale")
    script = (
        "import sys, pathlib\n"
        "d = pathlib.Path(sys.argv[sys.argv.index('--result-directory') + 1])\n"
        "assert not (d / 'junk.txt').exists()\n"
        "(d / 'out.txt').write_text('fresh')\n")
    jobs = Jobs(tmp_path, seeds=(1,), max_retries=0, retry_backoff=0)
    jobs.submit("run", [sys.executable, "-c", script])
    jobs.wait()
    assert (tmp_path / "run-1" / "out.txt").read_text() == "fresh"
    assert (tmp_path / "run-1.pending.0" / "junk.txt").read_text() == "stale"


def test_heartbeat_watchdog_kills_stalled_run(tmp_path):
    """A subprocess whose study CSV never advances is SIGKILLed after the
    heartbeat timeout instead of blocking its device slot forever."""
    script = "import time; time.sleep(60)"
    jobs = Jobs(tmp_path, seeds=(1,), max_retries=0, retry_backoff=0,
                heartbeat_timeout=0.5)
    jobs.submit("hung", [sys.executable, "-c", script])
    start = time.monotonic()
    jobs.wait()
    assert time.monotonic() - start < 30
    assert (tmp_path / "hung-1.failed").is_dir()


def test_heartbeat_watchdog_spares_advancing_run(tmp_path):
    """A run that keeps writing its study CSV is NOT killed even when it
    takes several heartbeat windows to finish."""
    script = (
        "import sys, time, pathlib\n"
        "d = pathlib.Path(sys.argv[sys.argv.index('--result-directory') + 1])\n"
        "study = d / 'study'\n"
        "for i in range(8):\n"
        "    study.open('a').write(f'row {i}\\n')\n"
        "    time.sleep(0.25)\n"
        "(d / 'out.txt').write_text('done')\n")
    jobs = Jobs(tmp_path, seeds=(1,), max_retries=0, retry_backoff=0,
                heartbeat_timeout=1.0)
    jobs.submit("steady", [sys.executable, "-c", script])
    jobs.wait()
    assert (tmp_path / "steady-1" / "out.txt").read_text() == "done"


def test_heartbeat_watchdog_prefers_heartbeat_json(tmp_path):
    """A run that never writes a study CSV but keeps refreshing its atomic
    heartbeat.json (the obs telemetry signal) is NOT killed — the watchdog
    consumes the heartbeat instead of inferring liveness from CSV mtime."""
    script = (
        "import sys, time, json, os, pathlib\n"
        "d = pathlib.Path(sys.argv[sys.argv.index('--result-directory') + 1])\n"
        "for i in range(8):\n"
        "    tmp = d / 'heartbeat.json.tmp'\n"
        "    tmp.write_text(json.dumps({'step': i, 'updated': time.time()}))\n"
        "    os.replace(tmp, d / 'heartbeat.json')\n"
        "    time.sleep(0.25)\n"
        "(d / 'out.txt').write_text('done')\n")
    jobs = Jobs(tmp_path, seeds=(1,), max_retries=0, retry_backoff=0,
                heartbeat_timeout=1.0)
    jobs.submit("beating", [sys.executable, "-c", script])
    jobs.wait()
    assert (tmp_path / "beating-1" / "out.txt").read_text() == "done"


def test_heartbeat_watchdog_kills_stale_heartbeat(tmp_path):
    """A heartbeat.json that stops updating is a stall signal like any
    other: the subprocess is killed once it goes stale past the timeout."""
    script = (
        "import sys, time, json, pathlib\n"
        "d = pathlib.Path(sys.argv[sys.argv.index('--result-directory') + 1])\n"
        "(d / 'heartbeat.json').write_text("
        "json.dumps({'step': 0, 'updated': time.time()}))\n"
        "time.sleep(60)\n")
    jobs = Jobs(tmp_path, seeds=(1,), max_retries=0, retry_backoff=0,
                heartbeat_timeout=0.5)
    jobs.submit("stale", [sys.executable, "-c", script])
    start = time.monotonic()
    jobs.wait()
    assert time.monotonic() - start < 30
    assert (tmp_path / "stale-1.failed").is_dir()


def test_watchdog_poll_floor(tmp_path):
    """The poll interval is clamped to [0.05, 0.5]: a tiny
    `heartbeat_timeout` (< 0.2) must not busy-spin the watchdog, a huge
    one must not make stall detection lazier than 0.5 s."""
    def poll(timeout):
        return Jobs(tmp_path, seeds=(1,),
                    heartbeat_timeout=timeout)._poll_interval()
    assert poll(0.01) == 0.05
    assert poll(0.1) == 0.05
    assert poll(1.0) == 0.25
    assert poll(100.0) == 0.5
    import pytest
    with pytest.raises(ValueError, match="heartbeat timeout"):
        Jobs(tmp_path, seeds=(1,), heartbeat_timeout=0)


def test_rotation_skips_existing_versions(tmp_path):
    """`_rotate_away` never clobbers previous rotations: with `.0`/`.1`
    already present (each non-empty), the next rotation lands on `.2`."""
    jobs = Jobs(tmp_path, seeds=(1,))
    target = tmp_path / "run-1.failed"
    for name in ("run-1.failed", "run-1.failed.0", "run-1.failed.1"):
        d = tmp_path / name
        d.mkdir()
        (d / "keep.txt").write_text(name)
    rotated = jobs._rotate_away(target)
    assert rotated.name == "run-1.failed.2"
    assert (rotated / "keep.txt").read_text() == "run-1.failed"
    for name in ("run-1.failed.0", "run-1.failed.1"):
        assert (tmp_path / name / "keep.txt").read_text() == name


def test_seedless_service_job(tmp_path):
    """`seeds=(None,)` queues ONE run under the bare name with no
    `--seed` flag — the service-job form the aggregation server uses
    (`python -m byzantinemomentum_tpu.serve --result-directory ...`),
    so long-lived serving processes get the same watchdog/retry
    supervision as training runs."""
    script = (
        "import sys, pathlib, json\n"
        "d = pathlib.Path(sys.argv[sys.argv.index('--result-directory') + 1])\n"
        "(d / 'argv.json').write_text(json.dumps(sys.argv))\n")
    jobs = Jobs(tmp_path, seeds=(None,), max_retries=0, retry_backoff=0)
    jobs.submit("server", [sys.executable, "-c", script])
    jobs.wait()
    import json
    argv = json.loads((tmp_path / "server" / "argv.json").read_text())
    assert "--seed" not in argv
    assert "--result-directory" in argv and "--device" in argv
    assert not (tmp_path / "server-None").exists()
