"""Sharded-fleet tests (`byzantinemomentum_tpu/serve/fleet/`): the
consistent-hash ring battery (cross-process determinism, the minimal
remap bound, vnode balance, versioned-membership monotonicity replayed
from a persisted `fleet.json`), the batched suspicion-resolve
equivalence (per-batch folds byte-identical to sequential — the verdict
contract the service's one-lock-per-batch optimization rides on), the
in-process 2-shard router (ownership-exact stores, the suspicion parity
oracle vs a single-process per-shard substream, dead-arc policy,
kill/readmit with the re-warm bound), and the subprocess launcher's
kill-safe failover + orphan discipline (slow tier).

The ring/membership/store tests are jax-free by construction (`ring.py`
is stdlib-only); the router tests pay two warm `AggregationService`
builds and stay at d=32.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from byzantinemomentum_tpu.obs.forensics import ClientSuspicionStore
from byzantinemomentum_tpu.serve.fleet.ring import (
    DEFAULT_VNODES, FLEET_MANIFEST_NAME, HashRing, Membership, hash_point,
    read_fleet_manifest, write_fleet_manifest)

KEYS = [f"client-{i}" for i in range(4096)]


# --------------------------------------------------------------------------- #
# Hash ring

def test_hash_point_cross_process_determinism():
    """The ring must be a pure function of the membership snapshot in
    EVERY process — sha1-derived points, never the builtin `hash()`
    whose PYTHONHASHSEED salt differs per process. A child interpreter
    (with a different, explicit hash seed) must compute identical points
    and identical owners."""
    shards = [f"shard-{i}" for i in range(4)]
    probe = KEYS[:64]
    child = subprocess.run(
        [sys.executable, "-c",
         "import json, sys\n"
         "from byzantinemomentum_tpu.serve.fleet.ring import "
         "HashRing, hash_point\n"
         "shards, probe = json.loads(sys.stdin.read())\n"
         "ring = HashRing(shards)\n"
         "print(json.dumps({'points': [hash_point(k) for k in probe],\n"
         "                  'owners': [ring.owner(k) for k in probe]}))"],
        input=json.dumps([shards, probe]), capture_output=True, text=True,
        env={**os.environ, "PYTHONHASHSEED": "12345",
             "PYTHONPATH": os.pathsep.join(
                 [str(p) for p in sys.path if p])},
        check=True)
    remote = json.loads(child.stdout)
    ring = HashRing(shards)
    assert remote["points"] == [hash_point(k) for k in probe]
    assert remote["owners"] == [ring.owner(k) for k in probe]


def test_remap_bound_on_shard_loss():
    """Removing K of N shards may remap ONLY the clients the removed
    shards owned — every survivor-owned client keeps its owner (and its
    suspicion history); the moved fraction stays under (K+1)/N."""
    shards = [f"shard-{i}" for i in range(4)]
    ring = HashRing(shards)
    before = {k: ring.owner(k) for k in KEYS}
    ring.remove("shard-2")
    moved = 0
    for k in KEYS:
        after = ring.owner(k)
        if before[k] == "shard-2":
            moved += 1
            assert after != "shard-2"
        else:
            assert after == before[k], \
                f"{k} moved {before[k]} -> {after} though its owner " \
                f"survived"
    assert moved / len(KEYS) <= 2 / 4
    # and losing a second shard obeys the same bound against the
    # ORIGINAL ring: K=2 of N=4 remaps at most 3/4
    ring.remove("shard-0")
    moved = sum(1 for k in KEYS if ring.owner(k) != before[k])
    assert moved / len(KEYS) <= 3 / 4
    for k in KEYS:
        if before[k] not in ("shard-0", "shard-2"):
            assert ring.owner(k) == before[k]


def test_vnode_balance_bound():
    """At `DEFAULT_VNODES` virtual points per shard the arcs are even
    enough that no shard owns more than 1.5x (or less than half) the
    mean load over a large uniform key population."""
    ring = HashRing([f"shard-{i}" for i in range(4)],
                    vnodes=DEFAULT_VNODES)
    counts = ring.spread(KEYS)
    mean = len(KEYS) / 4
    assert max(counts.values()) / mean <= 1.5
    assert min(counts.values()) / mean >= 0.5


def test_ownership_is_liveness_blind():
    """`mark_dead` flips the arc's policy bit without moving a single
    client: a killed shard restarts on the same port owning exactly its
    old arc, so suspicion never leaks across shards."""
    ring = HashRing(["a", "b", "c"])
    before = {k: ring.owner(k) for k in KEYS[:512]}
    ring.mark_dead("b")
    assert ring.dead == ("b",)
    assert not ring.alive("b") and ring.alive("a")
    for k, owner in before.items():
        assert ring.owner(k) == owner
        shard, alive = ring.route(k)
        assert shard == owner and alive == (owner != "b")
    ring.mark_alive("b")
    assert ring.dead == ()


def test_ring_membership_validation():
    ring = HashRing(["a"])
    with pytest.raises(ValueError):
        ring.add("a")
    with pytest.raises(KeyError):
        ring.remove("zz")
    with pytest.raises(KeyError):
        ring.mark_dead("zz")
    with pytest.raises(ValueError):
        HashRing(vnodes=0)
    with pytest.raises(LookupError):
        HashRing().owner("anyone")


# --------------------------------------------------------------------------- #
# Versioned membership + manifest

def test_membership_versions_and_manifest_roundtrip(tmp_path):
    """Every change bumps the version exactly once and is REPLAYABLE
    from the persisted history: a `fleet.json` written before the change
    took effect reconstructs the live ring exactly."""
    membership = Membership(vnodes=16)
    for i in range(3):
        assert membership.bump("add", f"shard-{i}", host="127.0.0.1",
                               port=7700 + i) == i + 1
    assert membership.bump("dead", "shard-1") == 4
    assert membership.bump("alive", "shard-1", pid=4242) == 5
    path = write_fleet_manifest(tmp_path, membership,
                                router="127.0.0.1:7699")
    assert path.name == FLEET_MANIFEST_NAME
    payload = read_fleet_manifest(tmp_path)
    assert payload["version"] == 5
    assert payload["router"] == "127.0.0.1:7699"
    assert Membership.from_dict(payload).as_dict() == membership.as_dict()

    replayed = Membership.replay(payload)
    assert replayed.version == membership.version
    assert sorted(replayed.shards) == sorted(membership.shards)
    live, again = membership.ring(), replayed.ring()
    for k in KEYS[:512]:
        assert live.owner(k) == again.owner(k)
    for shard in membership.shards:
        assert live.alive(shard) == again.alive(shard)
    # the replay also recovers the non-liveness fields from the snapshot
    assert replayed.shards["shard-1"]["pid"] == 4242
    assert replayed.shards["shard-0"]["port"] == 7700


def test_membership_replay_rejects_non_monotonic_history():
    membership = Membership()
    membership.bump("add", "a")
    membership.bump("add", "b")
    payload = membership.as_dict()
    payload["history"][1]["version"] = 7  # torn/hand-edited manifest
    with pytest.raises(ValueError, match="non-monotonic"):
        Membership.replay(payload)


def test_read_fleet_manifest_absent_or_torn(tmp_path):
    assert read_fleet_manifest(tmp_path) is None
    (tmp_path / FLEET_MANIFEST_NAME).write_text("{not json")
    assert read_fleet_manifest(tmp_path) is None


# --------------------------------------------------------------------------- #
# Batched suspicion resolve (jax-free: store level)

def _suspicion_items(rng, batch, population=6, n=4):
    items = []
    for _ in range(batch):
        chosen = rng.choice(population, size=n, replace=False)
        items.append(dict(
            client_ids=[f"c{int(i)}" for i in chosen],
            selection=rng.random(n),
            distances=rng.random(n) * 3.0,
            active=(rng.random(n) > 0.2).astype(np.float64)))
    return items


def test_observe_batch_matches_sequential_fold():
    """`observe_batch` must be byte-identical to per-item `observe`
    calls — same cohort z-scores, same as-of-fold population mean, same
    float arithmetic order. The one-lock-per-batch service optimization
    is only allowed to move WHERE the lock is taken, never a verdict."""
    kwargs = dict(alpha=0.3, threshold=0.5, clear=0.2, min_obs=2,
                  max_clients=32)
    seq, bat = ClientSuspicionStore(**kwargs), ClientSuspicionStore(**kwargs)
    rng = np.random.default_rng(7)
    batches = [_suspicion_items(rng, batch) for batch in (1, 3, 4, 2, 5)]
    for batch in batches:
        expected = [seq.observe(**item) for item in batch]
        got = bat.observe_batch(batch)
        assert got == expected
    assert seq.summary() == bat.summary()
    assert seq.clients() == bat.clients()


def test_store_clients_listing():
    store = ClientSuspicionStore()
    store.observe(["b", "a"], selection=[1.0, 0.0])
    assert store.clients() == ["a", "b"]


# --------------------------------------------------------------------------- #
# In-process router (2 shards, real sockets end to end)

def _fleet(shards=2, **kwargs):
    from byzantinemomentum_tpu.serve.fleet.local import LocalFleet
    return LocalFleet(shards, service={"max_batch": 4,
                                       "max_delay_ms": 2.0}, **kwargs)


def _payload(base, rng, n=5, d=32):
    return {"op": "aggregate", "gar": "median", "f": 1,
            "vectors": rng.standard_normal((n, d)).astype(
                np.float32).tolist(),
            "clients": [base] + [f"{base}.{j}" for j in range(1, n)]}


def test_fleet_ownership_split_and_suspicion_parity():
    """The parity oracle: a shard's verdict stream through the routed
    fleet is byte-identical to a single-process service fed that shard's
    substream directly — sharding must change WHERE suspicion lives,
    never what it says. Also pins the ownership split: each shard's
    store holds EXACTLY the clients the ring routes to it."""
    from byzantinemomentum_tpu.serve import AggregationService

    rng = np.random.default_rng(3)
    bases = [f"par-{i}" for i in range(10)]
    stream = [_payload(b, rng) for b in bases for _ in range(3)]
    with _fleet(2) as fleet:
        for svc in fleet.services.values():
            svc.warmup([("median", 5, 1, 32, True)])
        owners = {b: fleet.owner(b) for b in bases}
        assert len(set(owners.values())) == 2, \
            "10 bases should spread over both shards"
        fleet_verdicts = []
        for request in stream:
            reply = fleet.ask(request)
            assert reply["ok"], reply
            fleet_verdicts.append(reply["verdicts"])
        # ownership exactness, straight from each shard's store
        for shard in fleet.shards:
            expected = sorted(
                c for request in stream
                if owners[request["clients"][0]] == shard
                for c in request["clients"])
            assert fleet.suspicion_clients(shard) == \
                tuple(sorted(set(expected)))
        target = fleet.shards[0]
    # the single-process oracle: one fresh service, fed ONLY the
    # substream the ring routed to `target`, in the same order
    with AggregationService(max_batch=4, max_delay_ms=2.0) as direct:
        direct.warmup([("median", 5, 1, 32, True)])
        for request, through_fleet in zip(stream, fleet_verdicts):
            if owners[request["clients"][0]] != target:
                continue
            result = direct.aggregate(
                np.asarray(request["vectors"], dtype=np.float32),
                gar="median", f=1, client_ids=request["clients"])
            # the fleet's copy crossed two json hops; normalize the
            # oracle's the same way before the byte-for-byte compare
            assert through_fleet == json.loads(json.dumps(result.verdicts))


def test_fleet_dead_arc_error_policy_and_readmit():
    """`on_dead="error"`: a line routed to a dead arc fails FAST with
    the owner named (no parking); the restarted shard serves again —
    with a fresh store, so the returning client re-warms from scratch,
    exactly as fast as a fresh id (no suspicion shortcut through
    death)."""
    rng = np.random.default_rng(5)
    with _fleet(2, on_dead="error") as fleet:
        for svc in fleet.services.values():
            svc.warmup([("median", 5, 1, 32, True)])
        base = "victim-client"
        victim = fleet.owner(base)
        for _ in range(3):
            reply = fleet.ask(_payload(base, rng))
            assert reply["ok"]
        assert reply["verdicts"][base]["observations"] == 3
        fleet.kill(victim)
        dead_reply = fleet.ask(_payload(base, rng))
        assert not dead_reply["ok"]
        assert victim in dead_reply["error"]
        # the OTHER arc keeps serving through the outage
        other = next(f"ok{k}" for k in range(10_000)
                     if fleet.owner(f"ok{k}") != victim)
        assert fleet.ask(_payload(other, rng))["ok"]
        fleet.restart(victim)
        back = fleet.ask(_payload(base, rng))
        assert back["ok"]
        fresh = next(f"fresh{k}" for k in range(10_000)
                     if fleet.owner(f"fresh{k}") == victim)
        fresh_reply = fleet.ask(_payload(fresh, rng))
        assert back["verdicts"][base]["observations"] == \
            fresh_reply["verdicts"][fresh]["observations"] == 1


def test_router_stats_and_round_robin_anonymous():
    """Lines with no client ids spread round-robin (no owner to honor);
    the router's stats surface names both shards and the routed
    counts."""
    rng = np.random.default_rng(9)
    with _fleet(2) as fleet:
        for svc in fleet.services.values():
            svc.warmup([("median", 5, 1, 32, True)])
        for _ in range(8):
            payload = _payload("x", rng)
            del payload["clients"]
            assert fleet.ask(payload)["ok"]
        stats = fleet.ask({"op": "stats"})
        assert stats["ok"]
        per_shard = stats["shards"]
        assert sorted(per_shard) == list(fleet.shards)
        # ping/stats answer at the router; only the 8 aggregates routed
        assert sum(row["routed"] for row in per_shard.values()) == 8
        assert all(row["alive"] for row in per_shard.values())
        ping = fleet.ask({"op": "ping"})
        assert ping["ok"] and ping["router"] and ping["alive"] == 2


def test_membership_replay_shrink_then_regrow_history():
    """An elastic incident's full life in the change log: grow to 4,
    shard dies, shrink past it (remove), later regrow under the same
    name — `Membership.replay` folds the HISTORY alone back into the
    identical ring, and a tampered (non-monotonic) log is refused."""
    m = Membership()
    for i in range(4):
        m.bump("add", f"shard-{i}", host="127.0.0.1", port=7000 + i)
    m.bump("dead", "shard-3")
    m.bump("remove", "shard-3")
    m.bump("add", "shard-3", host="127.0.0.1", port=7103)
    assert m.version == 7
    assert [h["change"] for h in m.history] == \
        ["add"] * 4 + ["dead", "remove", "add"]
    replayed = Membership.replay(m.as_dict())
    assert replayed.version == m.version
    assert sorted(replayed.shards) == sorted(m.shards)
    for key in KEYS[:64]:
        assert replayed.ring().owner(key) == m.ring().owner(key)
    # the regrown shard is ALIVE (the old dead mark died with the
    # remove), and the snapshot's fields survived the fold
    assert replayed.shards["shard-3"]["alive"] is True
    assert replayed.shards["shard-3"]["port"] == 7103
    tampered = m.as_dict()
    tampered["history"][5]["version"] = 99
    with pytest.raises(ValueError, match="non-monotonic"):
        Membership.replay(tampered)


def test_fleet_parked_line_is_bounded():
    """`on_dead="queue"` with `max_parked=1`: the forwarder holds one
    parked batch while it retries the dead arc, ONE more line may wait
    in the queue behind it, and the next fails FAST naming the full
    parked line (counted in the router's stats). Both parked lines are
    served after the restart — at-most-once, never re-sent."""
    rng = np.random.default_rng(11)
    with _fleet(2, on_dead="queue", max_parked=1) as fleet:
        for svc in fleet.services.values():
            svc.warmup([("median", 5, 1, 32, True)])
        base = "park-client"
        victim = fleet.owner(base)
        assert fleet.ask(_payload(base, rng))["ok"]
        fleet.kill(victim)
        parked = []
        lines = []

        def _park():
            parked.append(fleet.ask(_payload(base, rng)))

        # Line A: dequeued and HELD by the forwarder while it retries
        # the dead arc. Wait until A has demonstrably ROUTED and left
        # the queue, stable across two polls — merely seeing an empty
        # queue is not enough (that is also what "A not asked yet"
        # looks like, and proceeding early inverts the line order).
        routed0 = fleet.router.stats()["shards"][victim]["routed"]
        lines.append(threading.Thread(target=_park))
        lines[-1].start()
        deadline = time.monotonic() + 30.0
        stable = 0
        while stable < 2:
            assert time.monotonic() < deadline, \
                f"forwarder never parked line A: {fleet.router.stats()}"
            stats = fleet.router.stats()
            if (stats["shards"][victim]["routed"] > routed0
                    and not stats["shards"][victim]["alive"]
                    and stats["queued"][victim] == 0):
                stable += 1
            else:
                stable = 0
            time.sleep(0.02)
        # Line B: fills the single parked slot in the queue itself (the
        # forwarder never drains the queue while its held batch retries)
        lines.append(threading.Thread(target=_park))
        lines[-1].start()
        deadline = time.monotonic() + 30.0
        while (fleet.router.stats()["queued"][victim] < 1
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert fleet.router.stats()["queued"][victim] >= 1
        # Line C: past the cap — fail fast, no unbounded amplification
        overflow = fleet.ask(_payload(base, rng))
        assert not overflow["ok"]
        assert "parked line is full" in overflow["error"]
        stats = fleet.router.stats()
        assert stats["max_parked"] == 1
        assert stats["parked_rejected"] == 1
        fleet.restart(victim)
        for line in lines:
            line.join(timeout=60)
            assert not line.is_alive()
        assert len(parked) == 2 and all(r["ok"] for r in parked), parked


# --------------------------------------------------------------------------- #
# Cross-process span join over the live router (r19)

def test_fleet_joined_records_tile_and_name_the_hot_arc():
    """Every reply's wire trace record splices under the router envelope
    (`joined_completed` grows once per line), the joined spans TILE the
    recv->reply wall clock-free, and a deterministic hot-key mix shows
    up as routing-count skew with `shard_queue` a first-class per-arc
    column — the zipf convoy's signature, measured where it happens."""
    from byzantinemomentum_tpu.obs.trace import JOINED_HOPS

    rng = np.random.default_rng(13)
    with _fleet(2) as fleet:
        for svc in fleet.services.values():
            svc.warmup([("median", 5, 1, 32, True)])
        hot = "hot-client"
        hot_owner = fleet.owner(hot)
        cold = next(f"cold{k}" for k in range(10_000)
                    if fleet.owner(f"cold{k}") != hot_owner)
        bases = [hot] * 30 + [cold] * 10
        before = fleet.router.joined_completed
        for base in bases:
            assert fleet.ask(_payload(base, rng))["ok"]
        grown = fleet.router.joined_completed - before
        assert grown == len(bases), "every reply must splice"
        records = fleet.router.joined_records()[-grown:]
        queue_by_shard = {}
        for record in records:
            spans = record["spans_ms"]
            assert set(spans) <= set(JOINED_HOPS)
            assert "shard_queue" in spans and "wire_residual" in spans
            # clock-free tiling: shard durations + wire residual sum to
            # the router-measured envelope (exact up to rounding — the
            # residual is DEFINED as what the nesting leaves over)
            assert sum(spans.values()) == pytest.approx(
                record["total_ms"], abs=0.01)
            queue_by_shard.setdefault(record["shard"], []).append(
                spans["shard_queue"])
        # the hot key's owner took exactly its 3/4 of the traffic —
        # count skew is deterministic (WHICH arc waits longest on a
        # loaded 1-core host is not, so assert routing, not p99 rank)
        counts = {s: len(v) for s, v in queue_by_shard.items()}
        assert counts == {hot_owner: 30, fleet.owner(cold): 10}
        # the router's own stats surface the joined summary
        joined = fleet.router.stats().get("joined")
        assert joined and joined["completed"] >= grown
        assert "shard_queue" in joined["phases_ms"]
        assert sum(joined["critical_path"].values()) >= grown


def test_parked_span_attribution_after_kill_recovery():
    """A line parked through a dead arc (`--on-dead queue`) replays
    after the restart with its outage attributed to a `parked` hop —
    dominant, bracketing the recovery wait — instead of polluting the
    wire-residual column. The joined record still tiles."""
    rng = np.random.default_rng(17)
    with _fleet(2, on_dead="queue", max_parked=4) as fleet:
        for svc in fleet.services.values():
            svc.warmup([("median", 5, 1, 32, True)])
        base = "park-trace"
        victim = fleet.owner(base)
        assert fleet.ask(_payload(base, rng))["ok"]
        fleet.kill(victim)
        replies = []
        line = threading.Thread(
            target=lambda: replies.append(fleet.ask(_payload(base, rng))))
        routed0 = fleet.router.stats()["shards"][victim]["routed"]
        line.start()
        # wait until the forwarder demonstrably HOLDS the line against
        # the dead arc (routed grew, arc marked dead), stable across
        # two polls — the same discipline as the bounded-park test
        deadline = time.monotonic() + 30.0
        stable = 0
        while stable < 2:
            assert time.monotonic() < deadline, \
                f"line never parked: {fleet.router.stats()}"
            stats = fleet.router.stats()
            if (stats["shards"][victim]["routed"] > routed0
                    and not stats["shards"][victim]["alive"]):
                stable += 1
            else:
                stable = 0
            time.sleep(0.02)
        time.sleep(0.2)   # a park dwell long enough to dominate
        fleet.restart(victim)
        line.join(timeout=60)
        assert not line.is_alive()
        assert replies and replies[0]["ok"], replies
        parked = [r for r in fleet.router.joined_records()
                  if "parked" in r["spans_ms"]]
        assert parked, "replayed line must carry a parked hop"
        record = parked[-1]
        assert record["shard"] == victim
        assert record["spans_ms"]["parked"] >= 50.0
        assert record["dominant"] == "parked"
        assert sum(record["spans_ms"].values()) == pytest.approx(
            record["total_ms"], abs=0.01)


# --------------------------------------------------------------------------- #
# Subprocess launcher (slow tier: real processes, real SIGKILL)

@pytest.mark.slow
def test_launcher_kill_restart_and_orphan_discipline(tmp_path):
    """The full failover story against real processes: SIGKILL a shard
    mid-stream — the router errors or parks the uncertain in-flight
    line (at-most-once: never re-sent), the launcher restarts the shard
    on the SAME port, the membership history lands dead -> alive with
    monotonic versions, the returning client re-warms no faster than a
    fresh id, and killing the launcher itself reaps every shard through
    the held stdin pipe (no orphans)."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, "-m", "byzantinemomentum_tpu.serve.fleet",
         "--shards", "2", "--port", "0", "--result-directory",
         str(tmp_path), "--warmup", "median:5:32:1", "--max-batch", "4",
         "--ready-timeout", "240"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, env=env)
    try:
        deadline = time.monotonic() + 300
        info = None
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            assert line, f"launcher exited early (rc={proc.poll()})"
            if line.startswith("fleet: "):
                info = json.loads(line[len("fleet: "):])
                break
        assert info is not None, "no fleet: line before timeout"
        host, port = info["router"].rsplit(":", 1)

        def ask(request, timeout=60):
            with socket.create_connection((host, int(port)),
                                          timeout=timeout) as conn:
                fd = conn.makefile("rwb")
                fd.write(json.dumps(request).encode() + b"\n")
                fd.flush()
                return json.loads(fd.readline())

        rng = np.random.default_rng(1)
        request = _payload("smoke-a", rng)
        first = ask(request)
        assert first["ok"]
        assert first["verdicts"]["smoke-a"]["observations"] == 1

        manifest = read_fleet_manifest(tmp_path)
        owner = Membership.from_dict(manifest).ring().owner("smoke-a")
        os.kill(manifest["shards"][owner]["pid"], signal.SIGKILL)

        deadline = time.monotonic() + 240
        while True:
            reply = ask(request, timeout=240)
            if reply.get("ok"):
                break
            assert time.monotonic() < deadline, "recovery timed out"
            time.sleep(0.5)
        # fresh store on the restarted shard: the client re-warmed
        assert reply["verdicts"]["smoke-a"]["observations"] == 1

        after = read_fleet_manifest(tmp_path)
        changes = [(h["change"], h["shard"]) for h in after["history"]]
        assert ("dead", owner) in changes and ("alive", owner) in changes
        versions = [h["version"] for h in after["history"]]
        assert versions == sorted(set(versions))
        Membership.replay(after)  # monotonic by construction

        # r19: the kill-failover left replayable incident bundles (the
        # capture worker is async — poll briefly for the drain)
        from byzantinemomentum_tpu.obs.trace import load_incidents
        deadline = time.monotonic() + 30
        reasons = set()
        while time.monotonic() < deadline:
            reasons = {b["reason"] for b in load_incidents(tmp_path)}
            if {"arc_dead", "failover"} <= reasons:
                break
            time.sleep(0.5)
        assert {"arc_dead", "failover"} <= reasons, reasons
        for bundle in load_incidents(tmp_path):
            assert bundle["kind"] == "incident"
            assert "membership" in bundle["context"]

        shard_pids = [row["pid"] for row in after["shards"].values()]
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if not any(os.path.exists(f"/proc/{pid}")
                       for pid in shard_pids):
                break
            time.sleep(0.2)
        orphans = [pid for pid in shard_pids
                   if os.path.exists(f"/proc/{pid}")]
        assert not orphans, f"shards leaked past the launcher: {orphans}"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
