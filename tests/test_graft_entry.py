"""Regression tests for the driver integration points (`__graft_entry__`).

The MULTICHIP_r05 rc=124 hang: `dryrun_multichip`'s PARENT-side
`jax.devices()` probe used to initialize whatever accelerator platform the
environment registers (this container's sitecustomize force-registers the
axon TPU platform), and a broken TPU tunnel turns that into an indefinite
backend-setup stall. The fix pins the parent to the CPU backend exactly as
the re-exec'd child always did; these tests prove the dryrun completes on
the virtual CPU mesh without the parent touching an accelerator backend.
"""

import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_dryrun_multichip_completes_on_virtual_cpu_mesh():
    """End-to-end: a parent WITHOUT the conftest's JAX_PLATFORMS pin (the
    MULTICHIP harness environment) must finish the 4-device dryrun inside
    a bounded window — the code-level CPU pin is what keeps the probe off
    the TPU tunnel. Runs in a subprocess: the probe hazard is the parent
    process's own backend initialization, which an in-process call from
    the (already CPU-pinned) test process could never reproduce."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # the un-pinned-parent scenario
    # Drop the conftest's virtual device count: the parent must see fewer
    # devices than requested and take the re-exec path (the shipped one)
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = " ".join(
        part for part in flags.split()
        if "xla_force_host_platform_device_count" not in part)
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__\n"
         "__graft_entry__.dryrun_multichip(4)\n"
         "print('DRYRUN_OK')\n"],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=240)  # far under the harness's ~10 min rc=124 ceiling
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DRYRUN_OK" in proc.stdout


def test_dryrun_parent_pins_cpu_platform():
    """The parent process's probe must run on the CPU backend even when an
    accelerator platform is importable: after `dryrun_multichip` returns,
    the parent's own backend is CPU (cheap sub-second check — no training
    step compiles in the parent when it re-execs)."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = " ".join(
        part for part in flags.split()
        if "xla_force_host_platform_device_count" not in part)
    code = (
        "import __graft_entry__, jax, os\n"
        "import unittest.mock as mock\n"
        "# Stub the subprocess re-exec: this test only certifies the\n"
        "# PARENT's probe platform, not the child's step (covered above)\n"
        "with mock.patch.object(__graft_entry__.subprocess, 'run') as run:\n"
        "    __graft_entry__.dryrun_multichip(64)\n"
        "assert run.called\n"
        "assert jax.devices()[0].platform == 'cpu', jax.devices()\n"
        "print('PARENT_CPU_OK')\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "PARENT_CPU_OK" in proc.stdout
