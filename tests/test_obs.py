"""Telemetry subsystem tests (tier-1, no TPU): recorder invariants (span
nesting, JSONL schema, counter monotonicity), atomic heartbeat replace,
perf helpers, the report renderer, the `--selfcheck` entry point, the
bench.py backend fallback, and the driver wiring end to end (telemetry
files from a real run, restart/rollback events on the timeline)."""

import json
import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from byzantinemomentum_tpu import obs
from byzantinemomentum_tpu.obs import recorder as obs_recorder
from byzantinemomentum_tpu.obs.report import render_report

ROOT = pathlib.Path(__file__).resolve().parent.parent

REQUIRED_KEYS = {"t", "kind", "name"}
PER_KIND_KEYS = {"span": {"id", "parent", "dur"},
                 "counter": {"value", "inc"},
                 "gauge": {"value"},
                 "event": set()}


# --------------------------------------------------------------------------- #
# Recorder

def test_jsonl_schema(tmp_path):
    """Every record carries t/kind/name plus its kind's fields, and the
    file is valid JSONL (one object per line)."""
    with obs.Telemetry(tmp_path) as t:
        t.event("run_start", seed=3)
        with t.span("outer"):
            t.counter("recompiles")
        t.gauge("steps_per_sec", 12.5, step=10)
    for line in (tmp_path / obs.TELEMETRY_NAME).read_text().splitlines():
        record = json.loads(line)
        assert REQUIRED_KEYS <= set(record), record
        assert record["kind"] in PER_KIND_KEYS
        assert PER_KIND_KEYS[record["kind"]] <= set(record), record
        assert isinstance(record["t"], float)


def test_span_nesting(tmp_path):
    with obs.Telemetry(tmp_path) as t:
        with t.span("a"):
            with t.span("b"):
                with t.span("c"):
                    pass
            with t.span("d"):
                pass
        with t.span("e"):
            pass
    spans = {r["name"]: r for r in obs.load_records(tmp_path)
             if r["kind"] == "span"}
    assert spans["a"]["parent"] is None
    assert spans["b"]["parent"] == spans["a"]["id"]
    assert spans["c"]["parent"] == spans["b"]["id"]
    assert spans["d"]["parent"] == spans["a"]["id"]  # sibling of b
    assert spans["e"]["parent"] is None              # a is closed
    assert all(s["dur"] >= 0 for s in spans.values())
    # Exit-ordered: inner spans are written before their parents
    names = [r["name"] for r in obs.load_records(tmp_path)]
    assert names.index("c") < names.index("b") < names.index("a")


def test_counter_monotonicity(tmp_path):
    with obs.Telemetry(tmp_path) as t:
        assert t.counter("x") == 1
        assert t.counter("x", 4) == 5
        assert t.counter("x", 0) == 5
        assert t.counter("y") == 1
        with pytest.raises(ValueError):
            t.counter("x", -1)
    values = [r["value"] for r in obs.load_records(tmp_path)
              if r["kind"] == "counter" and r["name"] == "x"]
    assert values == sorted(values) == [1, 5, 5]


def test_closed_recorder_drops_silently(tmp_path):
    t = obs.Telemetry(tmp_path)
    t.event("before")
    t.close()
    t.event("after")          # must not raise (listener races at shutdown)
    t.counter("after_count")
    names = [r["name"] for r in obs.load_records(tmp_path)]
    assert names == ["before"]
    t.close()                 # idempotent


def test_module_level_no_ops_when_inactive(tmp_path):
    obs.deactivate()
    obs.emit("nobody_listening")
    assert obs.counter("nothing") is None
    with obs.span("still_fine"):
        pass
    telem = obs.activate(obs.Telemetry(tmp_path))
    try:
        obs.emit("heard", step=1)
        obs.counter("seen", 2)
        with obs.span("scoped"):
            pass
    finally:
        obs.deactivate()
        telem.close()
    names = {r["name"] for r in obs.load_records(tmp_path)}
    assert {"heard", "seen", "scoped"} <= names


def test_load_records_skips_torn_tail(tmp_path):
    with obs.Telemetry(tmp_path) as t:
        t.event("one")
        t.event("two")
    path = tmp_path / obs.TELEMETRY_NAME
    with path.open("a") as fd:
        fd.write('{"t": 1.0, "kind": "event", "name": "torn by SIGKI')
    records = obs.load_records(tmp_path)
    assert [r["name"] for r in records] == ["one", "two"]
    assert obs.load_records(tmp_path / "missing") == []


def test_compile_listener_counts_backend_compiles(tmp_path):
    monitoring = pytest.importorskip("jax.monitoring")
    record_fn = getattr(monitoring, "record_event_duration_secs", None)
    if record_fn is None:
        pytest.skip("jax.monitoring has no duration-event recording")
    with obs.Telemetry(tmp_path) as t:
        if not obs.install_compile_listener(t):
            pytest.skip("jax.monitoring has no duration listeners")
        before = t.counters.get("recompiles", 0)
        record_fn("/test/backend_compile_duration", 0.25)
        record_fn("/jax/core/compile/jaxpr_trace_duration", 0.01)  # ignored
        assert t.counters.get("recompiles", 0) == before + 1


# --------------------------------------------------------------------------- #
# Heartbeat

def test_heartbeat_atomic_replace(tmp_path):
    for step in range(5):
        obs.write_heartbeat(tmp_path, {"step": step, "status": "running"})
    heartbeat = obs.read_heartbeat(tmp_path)
    assert heartbeat["step"] == 4
    assert heartbeat["version"] == 1
    assert heartbeat["pid"] == os.getpid()
    assert heartbeat["updated"] > 0
    # The tmp staging file never survives a completed write
    assert not (tmp_path / (obs.HEARTBEAT_NAME + ".tmp")).exists()


def test_heartbeat_read_never_raises(tmp_path):
    assert obs.read_heartbeat(tmp_path) is None              # absent
    (tmp_path / obs.HEARTBEAT_NAME).write_text("{torn")
    assert obs.read_heartbeat(tmp_path) is None              # corrupt
    (tmp_path / obs.HEARTBEAT_NAME).write_text("[1, 2]")
    assert obs.read_heartbeat(tmp_path) is None              # wrong shape


# --------------------------------------------------------------------------- #
# Perf helpers

def test_sliding_rate_window():
    rate = obs.SlidingRate(window_s=10.0)
    assert rate.rate() is None
    rate.update(0, now=0.0)
    rate.update(10, now=2.0)
    assert rate.rate() == pytest.approx(5.0)
    # Old points age out of the window
    rate.update(110, now=22.0)
    assert rate.rate() == pytest.approx((110 - 10) / 20.0)


def test_step_timer_measures_between_barriers():
    timer = obs.StepTimer()
    token = np.arange(8)
    timer.start(token)
    elapsed = timer.stop(token)
    assert elapsed >= 0.0
    timer.start(token)
    second = timer.stop(token)
    assert second >= 0.0
    assert timer.total == pytest.approx(elapsed + second)


def test_peak_flops_and_mfu():
    assert obs.peak_flops("TPU v4 chip") == 275e12
    assert obs.peak_flops("cpu") is None
    assert obs.mfu(1e12, 100.0, 275e12) == pytest.approx(1e14 / 275e12)
    assert obs.mfu(None, 100.0, 275e12) is None
    assert obs.mfu(1e12, 100.0, None) is None


def test_logical_flops_counts_a_jitted_program():
    import jax.numpy as jnp
    flops = obs.logical_flops(lambda a, b: a @ b,
                              jnp.ones((64, 64)), jnp.ones((64, 64)))
    if flops is None:
        pytest.skip("backend reports no cost analysis")
    assert flops > 0
    assert obs.logical_flops(lambda: "not jittable") is None


def test_host_rss_mb():
    rss = obs.host_rss_mb()
    assert rss is None or rss > 0


# --------------------------------------------------------------------------- #
# Report + selfcheck

def test_render_report(tmp_path):
    with obs.Telemetry(tmp_path) as t:
        t.event("run_start", seed=1)
        t.event("restart", step=4, count=1)
        t.counter("faults_injected", 3)
        t.counter("rollbacks")
        with t.span("checkpoint_save", step=4):
            pass
        t.gauge("steps_per_sec", 9.0, step=4)
        t.event("run_end", status="completed")
        t.heartbeat(step=4, steps_per_sec=9.0)
    report = render_report(tmp_path)
    for needle in ("step 4", "faults_injected=3", "rollbacks=1",
                   "checkpoint_save", "steps_per_sec", "restart",
                   "run_end"):
        assert needle in report, report


def test_render_report_empty_dir(tmp_path):
    report = render_report(tmp_path)
    assert "(none)" in report and "no telemetry.jsonl" in report


def test_selfcheck_entry_point():
    proc = subprocess.run(
        [sys.executable, "-m", "byzantinemomentum_tpu.obs", "--selfcheck"],
        cwd=ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "obs selfcheck: OK" in proc.stdout


# --------------------------------------------------------------------------- #
# bench.py backend fallback (satellite: a down TPU tunnel must yield a
# parseable JSON with a marker, not exit 1)

def test_bench_backend_fallback(monkeypatch):
    import bench
    calls = {"n": 0}

    def flaky_devices(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError(
                "Unable to initialize backend 'axon': UNAVAILABLE: TPU "
                "backend setup/compile error (Unavailable).")
        return ["cpu0"]

    monkeypatch.setattr(bench.jax, "devices", flaky_devices)
    assert bench._ensure_backend() == "cpu-fallback"
    assert calls["n"] == 2


def test_bench_backend_fallback_at_dispatch(monkeypatch):
    """The BENCH_r05 crash shape: `jax.devices()` answers (the old probe
    passed) but the first dispatch — `device_put` resolving the default
    backend via `local_devices()` — raises the UNAVAILABLE. The probe
    must catch that path too and fall back tagged, not exit 1."""
    import bench
    calls = {"n": 0}

    def flaky_device_put(x, *args, **kwargs):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError(
                "Unable to initialize backend 'axon': UNAVAILABLE: TPU "
                "backend setup/compile error (Unavailable).")
        return x

    monkeypatch.setattr(bench.jax, "device_put", flaky_device_put)
    assert bench._ensure_backend() == "cpu-fallback"
    assert calls["n"] == 2  # the retry probe dispatches again on CPU


def test_bench_backend_default(monkeypatch):
    import bench
    monkeypatch.setattr(bench.jax, "devices", lambda *a, **k: ["cpu0"])
    assert bench._ensure_backend() == "default"


def test_bench_backend_unrelated_error_propagates(monkeypatch):
    import bench

    def broken_devices(*args, **kwargs):
        raise RuntimeError("something else entirely")

    monkeypatch.setattr(bench.jax, "devices", broken_devices)
    with pytest.raises(RuntimeError, match="something else"):
        bench._ensure_backend()


# --------------------------------------------------------------------------- #
# Driver wiring end to end (in-process `main`, CPU, synthetic data)

DRIVER_BASE = ["--nb-steps", "6", "--batch-size", "8",
               "--batch-size-test", "32", "--batch-size-test-reps", "2",
               "--evaluation-delta", "2", "--checkpoint-delta", "2",
               "--model", "simples-full", "--seed", "11", "--gar", "median",
               "--nb-for-study", "11", "--nb-for-study-past", "2",
               "--telemetry-interval", "2"]


@pytest.fixture(autouse=True)
def small_synth(monkeypatch):
    monkeypatch.setenv("BMT_SYNTH_TRAIN", "512")
    monkeypatch.setenv("BMT_SYNTH_TEST", "128")


def _names(records, kind):
    return [r["name"] for r in records if r["kind"] == kind]


def test_driver_records_telemetry_and_restart(tmp_path):
    """A run with a result directory records the timeline by default; a
    second run over the same directory with --auto-resume stamps the
    restart event with the resume step (the acceptance signal for
    supervised chaos runs)."""
    from byzantinemomentum_tpu.cli.attack import main
    resdir = tmp_path / "run"
    argv = DRIVER_BASE + ["--result-directory", str(resdir)]
    assert main(argv) == 0
    records = obs.load_records(resdir)
    events = _names(records, "event")
    assert "run_start" in events and "run_end" in events
    spans = _names(records, "span")
    assert "eval" in spans and "checkpoint_save" in spans
    gauges = _names(records, "gauge")
    assert "device_step_ms" in gauges
    end = [r for r in records if r["name"] == "run_end"][-1]
    assert end["data"]["status"] == "completed"
    assert end["data"]["step"] == 6
    heartbeat = obs.read_heartbeat(resdir)
    assert heartbeat["step"] == 6 and heartbeat["status"] == "completed"
    assert heartbeat["counters"].get("recompiles", 0) > 0

    # Resume pass: same command line + --auto-resume
    assert main(argv + ["--auto-resume"]) == 0
    records = obs.load_records(resdir)
    restarts = [r for r in records if r["name"] == "restart"]
    assert restarts, "auto-resume must stamp a restart event"
    assert restarts[-1]["data"]["step"] == 6
    assert "checkpoint_load" in _names(records, "span")


def test_driver_records_rollback_event(tmp_path, monkeypatch):
    """The divergence-rollback path lands on the timeline: a rollback
    event with the restored checkpoint, the rollbacks counter, and a
    run_end that still says completed."""
    from byzantinemomentum_tpu.cli.attack import main
    monkeypatch.setenv("BMT_CHAOS_NAN_AT_STEP", "3")
    resdir = tmp_path / "roll"
    rc = main(DRIVER_BASE + ["--rollback-budget", "2",
                             "--result-directory", str(resdir)])
    assert rc == 0
    records = obs.load_records(resdir)
    rollback = [r for r in records if r["name"] == "rollback"]
    assert rollback and "restored" in rollback[-1]["data"]
    counters = [r for r in records if r["kind"] == "counter"
                and r["name"] == "rollbacks"]
    assert counters and counters[-1]["value"] == 1
    end = [r for r in records if r["name"] == "run_end"][-1]
    assert end["data"]["rollbacks"] == 1
    report = render_report(resdir)
    assert "rollbacks=1" in report


def test_driver_no_telemetry_flag(tmp_path):
    from byzantinemomentum_tpu.cli.attack import main
    resdir = tmp_path / "quiet"
    assert main(DRIVER_BASE + ["--no-telemetry",
                               "--result-directory", str(resdir)]) == 0
    assert not (resdir / obs.TELEMETRY_NAME).exists()
    assert not (resdir / obs.HEARTBEAT_NAME).exists()


def test_driver_telemetry_flag_validation():
    from byzantinemomentum_tpu import utils
    from byzantinemomentum_tpu.cli.attack import main
    with pytest.raises(utils.UserException, match="mutually exclusive"):
        main(["--telemetry", "--no-telemetry", "--nb-steps", "0"])
    with pytest.raises(utils.UserException, match="telemetry interval"):
        main(["--telemetry-interval", "0", "--nb-steps", "0"])
