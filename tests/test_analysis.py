"""Static-analysis suite tests: one violating + one clean fixture per
jaxlint rule, the noqa/reason contract, the repo-wide clean gate, the
recompile-budget and transfer-guard contracts on the CPU smoke config,
and the StableHLO golden workflow (bless idempotency + planted drift)."""

import json
import pathlib
import subprocess
import sys

import pytest

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu import losses, ops
from byzantinemomentum_tpu.analysis import contracts, lattice, lint, lowering
from byzantinemomentum_tpu.analysis.__main__ import main as analysis_main
from byzantinemomentum_tpu.engine import EngineConfig, build_engine

ROOT = pathlib.Path(__file__).resolve().parent.parent


# --------------------------------------------------------------------------- #
# jaxlint: one violating + one clean fixture per rule

# rule id -> (violating source, clean source)
FIXTURES = {
    "BMT-E01": (
        """
import jax
def f(key):
    a = jax.random.uniform(key)
    b = jax.random.normal(key)
    return a + b
""",
        """
import jax
def f(key):
    k1, k2 = jax.random.split(key)
    a = jax.random.uniform(k1)
    b = jax.random.normal(k2)
    return a + b
""",
    ),
    "BMT-E02": (
        """
import jax
@jax.jit
def f(x):
    return float(x) + x.sum().item()
""",
        """
import jax, jax.numpy as jnp
@jax.jit
def f(x):
    return x.astype(jnp.float32) + jnp.sum(x)
""",
    ),
    "BMT-E03": (
        """
import jax
def f(xs):
    out = []
    for x in xs:
        out.append(jax.jit(lambda v: v + 1)(x))
    return out
""",
        """
import jax
_step = jax.jit(lambda v: v + 1)
def f(xs):
    return [_step(x) for x in xs]
""",
    ),
    "BMT-E04": (
        """
import jax
def run(update, state, x):
    step = jax.jit(update, donate_argnums=(0,))
    new = step(state, x)
    return state + new
""",
        """
import jax
def run(update, state, x):
    step = jax.jit(update, donate_argnums=(0,))
    new = step(state, x)
    return new
""",
    ),
    "BMT-E05": (
        """
def f(path):
    try:
        return open(path).read()
    except Exception:
        return None
""",
        """
def f(path):
    try:
        return open(path).read()
    except OSError:
        return None
""",
    ),
    "BMT-E06": (
        """
import jax, time
@jax.jit
def f(x):
    return x + time.time()
""",
        """
import jax, time
def f(step, x):
    t0 = time.time()
    y = step(x)
    return y, time.time() - t0
""",
    ),
    "BMT-E07": (
        """
import jax.numpy as jnp
def f(gs):
    return jnp.stack([jnp.asarray(g) for g in gs])
""",
        """
import jax.numpy as jnp
def f(gs):
    return jnp.stack(gs)
""",
    ),
    "BMT-E08": (
        """
import jax
@jax.jit
def f(x, step):
    with jax.named_scope(f"phase_{step}"):
        return x * 2
""",
        """
import jax
@jax.jit
def f(x, step):
    with jax.named_scope("honest"):
        return x * 2
""",
    ),
    "BMT-E10": (
        """
import threading
def serve(requests):
    for r in requests:
        lock = threading.Lock()
        with lock:
            r.handle()
""",
        """
import threading
_LOCK = threading.Lock()
def serve(requests):
    for r in requests:
        with _LOCK:
            r.handle()
""",
    ),
    "BMT-E09": (
        # The suppression names a rule that does NOT fire on the line —
        # the annotation rotted (here: the except was narrowed but the
        # noqa stayed behind)
        """
def f(path):
    try:
        return open(path).read()
    except OSError:  # bmt: noqa[BMT-E05] reads may race the GC
        return None
""",
        """
def f(path):
    try:
        return open(path).read()
    except Exception:  # bmt: noqa[BMT-E05] probe helper must survive anything
        return None
""",
    ),
}


@pytest.mark.parametrize("rule_id", sorted(FIXTURES))
def test_rule_fixture_pair(rule_id):
    """Every rule fires on its violating fixture and stays silent on the
    clean one (and on the clean one no OTHER rule fires either)."""
    bad, good = FIXTURES[rule_id]
    hits = {v.rule for v in lint.lint_source(bad)}
    assert rule_id in hits, f"{rule_id} missed its violating fixture"
    clean = lint.lint_source(good)
    assert clean == [], f"clean fixture not clean: {clean}"


def test_shard_map_body_traced_scope():
    """BMT-E02/E06 see through `shard_map` bodies — positional AND
    keyword-passed (the ROADMAP stranded rung): the compat wrapper
    (`parallel/mesh.py`) takes the body positionally, but a call site
    naming it (`shard_map(f=kernel, ...)`) must not hide the scope."""
    violating = """
import time
import numpy as np
from byzantinemomentum_tpu.parallel.mesh import shard_map
def outer(g, mesh, in_specs, out_specs):
    def kernel(g_local):
        scale = time.time()
        return np.square(g_local) * scale
    return shard_map(f=kernel, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs)(g)
"""
    hits = {v.rule for v in lint.lint_source(violating)}
    assert "BMT-E02" in hits and "BMT-E06" in hits, hits
    clean = """
import jax.numpy as jnp
from byzantinemomentum_tpu.parallel.mesh import shard_map
def outer(g, mesh, in_specs, out_specs):
    def kernel(g_local):
        return jnp.square(g_local)
    return shard_map(f=kernel, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs)(g)
"""
    assert lint.lint_source(clean) == []
    # The positional `parallel/sharded.py` idiom is traced the same way
    positional = """
import time
from byzantinemomentum_tpu.parallel.mesh import shard_map
def outer(g, mesh, in_specs, out_specs):
    def kernel(g_local):
        return g_local * time.monotonic()
    return shard_map(kernel, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs)(g)
"""
    assert any(v.rule == "BMT-E06" for v in lint.lint_source(positional))


def test_rule_registry_complete():
    """Every registered E-rule id has a fixture pair here (E00, the
    suppression-hygiene rule, is proven by the noqa tests below; E11's
    pair lives in tests/test_locks.py beside the lock family); the
    BMT-T concurrency family shares the registry (so noqa/E00/E09 apply
    to it) and has its fixture pairs in tests/test_concurrency.py; the
    BMT-L family registers here for --rules/noqa but fires from the
    whole-program locks.build sweep (fixtures in tests/test_locks.py)."""
    e_rules = {r for r in lint.RULES if r.startswith("BMT-E")}
    t_rules = {r for r in lint.RULES if r.startswith("BMT-T")}
    l_rules = {r for r in lint.RULES if r.startswith("BMT-L")}
    assert e_rules == set(FIXTURES) | {"BMT-E00", "BMT-E11"}
    assert t_rules == {f"BMT-T0{i}" for i in range(1, 6)}
    assert l_rules == {f"BMT-L0{i}" for i in range(1, 7)}
    assert e_rules | t_rules | l_rules == set(lint.RULES)
    for rule_id, rule in lint.RULES.items():
        assert rule.summary


def test_dead_noqa_details():
    """BMT-E09 edges: a dead suppression is reported per dead rule id,
    a LIVE suppression is not dead, and a rule that was not run this
    pass is never declared dead (subset runs must not cry rot)."""
    dead, live = FIXTURES["BMT-E09"]
    hits = lint.lint_source(dead)
    assert [v.rule for v in hits] == ["BMT-E09"]
    assert "BMT-E05" in hits[0].message
    assert lint.lint_source(live) == []
    # Subset run without E05: its noqa cannot be judged dead
    assert lint.lint_source(dead, rules={"BMT-E09", "BMT-E01"}) == []
    # Two ids, one dead one live: only the dead one is reported
    mixed = """
import jax, time
@jax.jit
def f(x):
    return x + time.time()  # bmt: noqa[BMT-E06, BMT-E02] trace-time stamp wanted
"""
    hits = lint.lint_source(mixed)
    assert [v.rule for v in hits] == ["BMT-E09"]
    assert "BMT-E02" in hits[0].message


def test_key_reuse_in_loop_and_branches():
    """The loop form of key reuse fires; mutually exclusive branches and
    early returns do not (the `models/core.py` dropout idiom)."""
    loop = """
import jax
def g(key, n):
    out = []
    for _ in range(n):
        out.append(jax.random.normal(key))
    return out
"""
    assert any(v.rule == "BMT-E01" for v in lint.lint_source(loop))
    branches = """
import jax
def f(rng, keep, shape):
    if keep == 0.5:
        return jax.random.bits(rng, shape)
    return jax.random.bernoulli(rng, keep, shape)
"""
    assert lint.lint_source(branches) == []
    rebind = """
import jax
def g(key, n):
    out = []
    for _ in range(n):
        key, sub = jax.random.split(key)
        out.append(jax.random.normal(sub))
    return out
"""
    assert lint.lint_source(rebind) == []


def test_e07_cross_family_is_not_redundant():
    """`jnp.asarray(np.stack(...))` is a host->device move, not a double
    conversion; dtype= makes the outer call a cast."""
    src = """
import numpy as np
import jax.numpy as jnp
def f(xs):
    a = jnp.asarray(np.stack(xs))
    b = jnp.asarray(jnp.arange(4), dtype=jnp.bfloat16)
    return a, b
"""
    assert lint.lint_source(src) == []
    nested = "import jax.numpy as jnp\nx = jnp.asarray(jnp.stack([1, 2]))\n"
    assert any(v.rule == "BMT-E07" for v in lint.lint_source(nested))


# --------------------------------------------------------------------------- #
# noqa: suppression requires a reason

def test_noqa_with_reason_suppresses():
    src = """
def f(path):
    try:
        return open(path).read()
    except Exception:  # bmt: noqa[BMT-E05] probe helper must survive anything
        return None
"""
    assert lint.lint_source(src) == []


def test_noqa_without_reason_is_a_violation():
    src = """
def f(path):
    try:
        return open(path).read()
    except Exception:  # bmt: noqa[BMT-E05]
        return None
"""
    rules = {v.rule for v in lint.lint_source(src)}
    # The unexplained suppression is flagged AND does not suppress
    assert rules == {"BMT-E00", "BMT-E05"}


def test_noqa_unknown_rule_id_flagged():
    src = "x = 1  # bmt: noqa[BMT-E99] no such rule\n"
    violations = lint.lint_source(src)
    assert [v.rule for v in violations] == ["BMT-E00"]
    assert "unknown rule" in violations[0].message


def test_noqa_in_docstring_is_prose():
    src = '''
def f():
    """Suppress with `# bmt: noqa[BMT-E05]` and a reason."""
    return 1
'''
    assert lint.lint_source(src) == []


def test_json_and_human_output():
    bad, _ = FIXTURES["BMT-E05"]
    violations = lint.lint_source(bad, path="x.py")
    human = lint.format_human(violations)
    assert "x.py:5" in human and "BMT-E05" in human
    payload = json.loads(lint.format_json(violations, files_checked=1))
    assert payload["counts"] == {"BMT-E05": 1}
    assert payload["files"] == 1
    assert payload["violations"][0]["line"] == 5


# --------------------------------------------------------------------------- #
# The repo itself is the acceptance fixture

def test_repo_is_lint_clean():
    """`python -m byzantinemomentum_tpu.analysis byzantinemomentum_tpu/
    scripts/` exits 0: every pre-existing violation is fixed or carries a
    reasoned annotation."""
    violations = lint.lint_paths(
        [ROOT / "byzantinemomentum_tpu", ROOT / "scripts"])
    assert violations == [], lint.format_human(violations)


def test_cli_exit_codes(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert analysis_main([str(clean)]) == 0
    dirty = tmp_path / "dirty.py"
    dirty.write_text(FIXTURES["BMT-E05"][0])
    assert analysis_main([str(dirty)]) == 1
    assert analysis_main(["--rules"]) == 0


# --------------------------------------------------------------------------- #
# Runtime contracts on the CPU smoke config

def _probe_engine(**cfg_kwargs):
    """The tiny 6-d probe engine (same scheme as `test_engine.py` /
    `test_diag.py`) — the CPU smoke config for the contract tests."""
    from byzantinemomentum_tpu.models import ModelDef

    D = 6

    def init(key):
        return {"w": jnp.zeros((D,), jnp.float32)}, {}

    def apply(params, state, x, train=False, rng=None):
        return x, state

    loss = losses.Loss(lambda output, target, params:
                       jnp.dot(params, jnp.mean(output, axis=0)))
    cfg = EngineConfig(nb_workers=8, nb_decl_byz=1, nb_real_byz=0,
                       nb_for_study=8, nb_for_study_past=2, **cfg_kwargs)
    engine = build_engine(
        cfg=cfg, model_def=ModelDef("probe", init, apply, (D,)),
        loss=loss, criterion=losses.Criterion("sigmoid"),
        defenses=[(ops.gars["krum"], 1.0, {})])
    return cfg, engine


def _warm_engine():
    cfg, engine = _probe_engine()
    S = cfg.nb_sampled
    state = engine.init(jax.random.PRNGKey(0),
                        params={"w": jnp.zeros((6,))}, net_state={})
    xs = jax.device_put(jnp.zeros((S, 4, 6), jnp.float32))
    ys = jax.device_put(jnp.zeros((S, 4), jnp.float32))
    lr = jax.device_put(jnp.float32(0.1))
    state, metrics = engine.train_step(state, xs, ys, lr)  # compile
    jax.block_until_ready(metrics)
    return engine, state, xs, ys, lr


def test_recompile_budget_warm_loop_is_zero():
    """The engine's warm training loop compiles nothing: the declared
    budget of the CPU smoke config is zero, and any retrace (shape drift,
    scalar cache churn) trips it."""
    engine, state, xs, ys, lr = _warm_engine()
    holder = {"state": state}

    def step():
        holder["state"], metrics = engine.train_step(
            holder["state"], xs, ys, lr)
        return metrics

    assert contracts.assert_recompile_budget(step, steps=3, budget=0) == 0


def test_recompile_budget_trips_on_retrace():
    """Shape drift inside the window raises RecompileBudgetError, and the
    error names the compile events."""
    f = jax.jit(lambda x: x * 2)
    f(jnp.zeros((2,)))  # warm one shape
    shapes = iter([(2,), (3,), (4,)])

    def step():
        return f(jnp.zeros(next(shapes)))

    with pytest.raises(contracts.RecompileBudgetError) as err:
        contracts.assert_recompile_budget(step, steps=3, budget=0)
    assert "backend compile" in str(err.value)


def test_count_compiles_window_and_unregister():
    with contracts.count_compiles() as log:
        jax.jit(lambda x: x + 3)(jnp.zeros((5,)))
    inside = log.count
    assert inside > 0
    jax.jit(lambda x: x + 4)(jnp.zeros((6,)))  # after the window
    assert log.count == inside


def test_transfer_guard_engine_step():
    """One warm engine step with device-resident operands performs zero
    implicit device<->host transfers."""
    engine, state, xs, ys, lr = _warm_engine()
    with contracts.no_implicit_transfers():
        state, metrics = engine.train_step(state, xs, ys, lr)
    assert jax.block_until_ready(metrics) is not None


def test_transfer_guard_catches_scalar_argument():
    """A Python scalar argument is an implicit host->device transfer —
    exactly the hot-loop leak the guard exists to catch."""
    f = jax.jit(lambda x: x * 2)
    f(jnp.zeros(()))
    with pytest.raises(Exception, match="[Dd]isallow"):
        with contracts.no_implicit_transfers():
            f(3.0)


# --------------------------------------------------------------------------- #
# Lowering goldens: bless workflow + drift gate

SMALL_GRID = ("krum", "average")


def _small_lattice(monkeypatch, meshes=(), serve=()):
    """Shrink the enumerated lattice for the workflow tests (the
    enumerator reads the module attributes at call time)."""
    monkeypatch.setattr(lattice, "CELL_GARS", SMALL_GRID)
    monkeypatch.setattr(lattice, "MESH_AXES", meshes)
    monkeypatch.setattr(lattice, "SERVE_CELLS", serve)


def test_bless_idempotent_and_check_ok(tmp_path, monkeypatch):
    _small_lattice(monkeypatch)
    path = tmp_path / "lowerings.json"
    lowering.bless(path)
    first = path.read_bytes()
    lowering.bless(path)
    assert path.read_bytes() == first  # byte-idempotent
    report = lowering.check(path)
    # 2 GARs x (plain/diag/masked + the r10 masked-bucket cell + the
    # r11 quarantine cell)
    assert report["status"] == "ok" and report["checked"] == 10


def test_planted_gar_edit_trips_drift_gate(tmp_path, monkeypatch):
    """An (algebraically neutral) edit to a GAR kernel changes its
    StableHLO and the gate names exactly the drifted cells."""
    _small_lattice(monkeypatch)
    path = tmp_path / "lowerings.json"
    lowering.bless(path)
    gar = ops.gars["krum"]
    orig = gar.unchecked
    monkeypatch.setattr(gar, "unchecked",
                        lambda G, **kw: orig(G, **kw) + 0.0)
    report = lowering.check(path)
    assert report["status"] == "drift"
    assert "krum/plain" in report["drifted"]
    assert not any(c.startswith("average/") for c in report["drifted"])


def test_check_incomparable_and_missing(tmp_path):
    missing = lowering.check(tmp_path / "nope.json")
    assert missing["status"] == "missing"
    stale = tmp_path / "stale.json"
    stale.write_text(json.dumps(
        {"jax": "0.0.0", "backend": "tpu", "cells": {}}))
    assert lowering.check(stale)["status"] == "incomparable"


def test_repo_goldens_match_current_lowerings():
    """The committed goldens are current — the lint tier's drift gate is
    green at HEAD."""
    report = lowering.check()
    assert report["status"] == "ok", report


@pytest.mark.slow
def test_bless_script_idempotent_subprocess(tmp_path):
    """The bless script round-trips through its CLI: second run reports
    (unchanged), a planted stale key is pruned AND named in the output,
    and the module gate accepts the result."""
    out = tmp_path / "goldens.json"
    for expect in ("(changed)", "(unchanged)"):
        proc = subprocess.run(
            [sys.executable, "scripts/bless_lowerings.py", "--out", str(out)],
            cwd=ROOT, capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
        assert expect in proc.stdout
    # Plant a stale cell: re-blessing prunes it and reports the key
    data = json.loads(out.read_text())
    data["cells"]["retired/stale"] = "0" * 64
    out.write_text(json.dumps(data))
    proc = subprocess.run(
        [sys.executable, "scripts/bless_lowerings.py", "--out", str(out)],
        cwd=ROOT, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    assert "pruned 1 stale cell(s)" in proc.stdout
    assert "pruned: retired/stale" in proc.stdout
    assert "retired/stale" not in json.loads(out.read_text())["cells"]
    check = subprocess.run(
        [sys.executable, "scripts/bless_lowerings.py", "--out", str(out),
         "--check"], cwd=ROOT, capture_output=True, text=True)
    assert check.returncode == 0, check.stdout + check.stderr
