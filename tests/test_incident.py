"""SLO-triggered incident bundles (`obs/trace/incident.py`, r19): the
atomic capture door (tmp -> fsync -> replace, no torn bundle ever
readable), per-reason cooldown and the bounded directory ring, the
provider-failure cell discipline (evidence gathering never kills the
capture), index numbering that survives restarts, the fleet-scope merge
across per-process incident trees, the ordered causal-story rendering
(`edge -> dominant hop -> membership`), and the `obs_report` incidents
section riding the same loader.

Stdlib + pytest only — every test is deterministic (synchronous
`capture` with explicit wall times; the worker-thread test only checks
drain-on-stop)."""

import json
import os

import pytest

from byzantinemomentum_tpu.obs.trace import (
    IncidentRecorder, load_incidents, merge_fleet_incidents,
    render_incidents)
from byzantinemomentum_tpu.obs.trace.incident import (
    FLEET_INDEX_NAME, INCIDENTS_DIRNAME)


def _trace_context():
    """A router-stats-shaped trace cell whose joined summary names
    `shard_queue` as the dominant hop (4 of 6 traces)."""
    return {"joined": {"critical_path": {"shard_queue": 4,
                                         "wire_residual": 2}}}


# --------------------------------------------------------------------------- #
# Capture: atomicity, schema, provider discipline


def test_capture_writes_atomic_schema_complete_bundle(tmp_path):
    recorder = IncidentRecorder(
        tmp_path, source="launcher", cooldown_s=0.0,
        providers={"trace": _trace_context,
                   "membership": lambda: {"version": 3, "dead": []}})
    path = recorder.capture("slo_burn", {"slo": "avail",
                                         "burn_fast": 120.0,
                                         "burn_slow": 15.0}, t=100.0)
    assert path is not None and path.parent.name == INCIDENTS_DIRNAME
    bundle = json.loads(path.read_text())
    assert bundle["kind"] == "incident"
    assert bundle["n"] == 1 and bundle["t"] == 100.0
    assert bundle["reason"] == "slo_burn"
    assert bundle["data"]["slo"] == "avail"
    assert bundle["source"] == "launcher"
    assert bundle["context"]["membership"]["version"] == 3
    # atomic door: no orphan tmp after a clean capture
    assert not list(path.parent.glob("*.tmp"))
    assert recorder.summary()["captured"] == 1


def test_provider_failure_forfeits_its_cell_not_the_bundle(tmp_path):
    def broken():
        raise RuntimeError("scrape lost the socket")

    recorder = IncidentRecorder(
        tmp_path, cooldown_s=0.0,
        providers={"metrics": broken, "membership": lambda: {"v": 1}})
    path = recorder.capture("arc_dead", {"shard": "shard-1"})
    bundle = json.loads(path.read_text())
    assert bundle["context"]["membership"] == {"v": 1}
    assert "RuntimeError" in bundle["context"]["metrics"]["error"]
    # the report marks the failed cell without dropping the bundle
    lines = render_incidents(tmp_path)
    assert any("evidence: membership (failed: metrics)" in line
               for line in lines)


def test_cooldown_dedupes_flapping_reason_only(tmp_path):
    recorder = IncidentRecorder(tmp_path, cooldown_s=60.0)
    assert recorder.capture("slo_burn") is not None
    assert recorder.capture("slo_burn") is None      # inside the window
    assert recorder.capture("arc_dead") is not None  # distinct reason
    summary = recorder.summary()
    assert summary["captured"] == 2 and summary["dropped"] == 1


def test_directory_ring_and_restart_safe_numbering(tmp_path):
    recorder = IncidentRecorder(tmp_path, limit=3, cooldown_s=0.0)
    for k in range(5):
        recorder.capture(f"edge-{k}", t=float(k))
    names = sorted(os.listdir(tmp_path / INCIDENTS_DIRNAME))
    assert names == ["incident-3.json", "incident-4.json",
                     "incident-5.json"]
    # a restarted process resumes PAST the surviving evidence — a
    # fresh recorder must never overwrite a prior incarnation's bundle
    reborn = IncidentRecorder(tmp_path, limit=3, cooldown_s=0.0)
    path = reborn.capture("post-restart")
    assert path.name == "incident-6.json"


def test_trigger_worker_drains_on_stop(tmp_path):
    recorder = IncidentRecorder(tmp_path, cooldown_s=0.0).start()
    recorder.trigger("slo_burn", slo="avail")
    recorder.trigger("arc_dead", shard="shard-0")
    recorder.stop()
    reasons = sorted(b["reason"] for b in load_incidents(tmp_path))
    assert reasons == ["arc_dead", "slo_burn"]
    recorder.stop()  # idempotent


# --------------------------------------------------------------------------- #
# Loading: torn tolerance, fleet-scope crawl, ordering


def test_loader_skips_torn_files_and_orders_by_time(tmp_path):
    recorder = IncidentRecorder(tmp_path, cooldown_s=0.0)
    recorder.capture("late", t=200.0)
    recorder.capture("early", t=50.0)
    directory = tmp_path / INCIDENTS_DIRNAME
    # a SIGKILL mid-write leaves exactly these shapes behind
    (directory / "incident-9.json.tmp").write_text('{"kind": "inci')
    (directory / "incident-7.json").write_text('{"kind": "incident", ')
    (directory / "incident-8.json").write_text('[1, 2]')  # not a dict
    bundles = load_incidents(tmp_path)
    assert [b["reason"] for b in bundles] == ["early", "late"]


def test_fleet_crawl_tags_sources_and_merge_orders_rows(tmp_path):
    IncidentRecorder(tmp_path, source="launcher", cooldown_s=0.0,
                     providers={"trace": _trace_context}).capture(
        "slo_burn", {"slo": "avail", "burn_fast": 40.0,
                     "burn_slow": 12.0}, t=10.0)
    IncidentRecorder(tmp_path / "shards" / "shard-1",
                     cooldown_s=0.0).capture(
        "arc_dead", {"shard": "shard-1"}, t=5.0)
    IncidentRecorder(tmp_path / "hosts" / "h2", cooldown_s=0.0).capture(
        "straggler_kill", {"host": "h2", "why": "stale"}, t=20.0)
    bundles = load_incidents(tmp_path)
    # per-process writers that did not stamp a source get their
    # directory name; wall-time order joins the trees
    assert [(b["reason"], b["source"]) for b in bundles] == [
        ("arc_dead", "shard-1"), ("slo_burn", "launcher"),
        ("straggler_kill", "h2")]
    index = merge_fleet_incidents(tmp_path)
    assert index.name == FLEET_INDEX_NAME
    payload = json.loads(index.read_text())
    assert payload["kind"] == "incident_index"
    assert payload["incidents"] == 3
    rows = payload["rows"]
    assert [row["reason"] for row in rows] == ["arc_dead", "slo_burn",
                                               "straggler_kill"]
    # the merged headline carries the dominant hop when the bundle's
    # trace context names one
    assert rows[1]["dominant_hop"] == "shard_queue"
    assert "dominant_hop" not in rows[0]
    assert merge_fleet_incidents(tmp_path / "empty") is None


# --------------------------------------------------------------------------- #
# Rendering: the ordered causal story


def test_render_replays_the_causal_story(tmp_path):
    IncidentRecorder(
        tmp_path, source="launcher", cooldown_s=0.0,
        providers={"trace": _trace_context,
                   "membership": lambda: {"version": 4,
                                          "dead": ["shard-1"]}}).capture(
        "slo_burn", {"slo": "avail", "burn_fast": 120.5,
                     "burn_slow": 15.25}, t=30.0)
    lines = render_incidents(tmp_path)
    assert lines[0].startswith("incidents: 1 bundle (1 launcher)")
    story = next(line for line in lines if "story:" in line)
    # edge -> dominant hop -> membership transition, in that order
    assert "slo_burn[avail] fast=120.50 slow=15.25" in story
    assert story.index("slo_burn") < story.index("dominant hop "
                                                 "shard_queue (4/6")
    assert story.index("shard_queue") < story.index(
        "membership v4 dead=['shard-1']")


def test_render_elides_past_limit_and_empty_dir(tmp_path):
    assert render_incidents(tmp_path) == []
    recorder = IncidentRecorder(tmp_path, cooldown_s=0.0)
    for k in range(5):
        recorder.capture(f"edge-{k}", t=float(k))
    lines = render_incidents(tmp_path, limit=2)
    assert lines[0].startswith("incidents: 5 bundles")
    shown = [line for line in lines if line.startswith("  incident-")]
    assert len(shown) == 2 and "incident-5" in shown[-1]
    assert lines[-1] == "  ... 3 older bundle(s) not shown"


def test_obs_report_grows_an_incidents_section(tmp_path):
    from byzantinemomentum_tpu.obs.report import render_report

    IncidentRecorder(tmp_path, source="launcher", cooldown_s=0.0,
                     providers={"trace": _trace_context}).capture(
        "failover", {"shard": "shard-0", "restarts": 1}, t=7.0)
    report = render_report(tmp_path)
    assert "incidents: 1 bundle" in report
    assert "story: failover[shard-0] -> dominant hop shard_queue" \
        in report


def test_recorder_rejects_bad_limit(tmp_path):
    with pytest.raises(ValueError):
        IncidentRecorder(tmp_path, limit=0)
