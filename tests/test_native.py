"""Native C++ tier tests: differential against the jnp kernels (which are
themselves differentially tested against the torch oracles), including NaN
resilience, and the `cpp-<gar>` pure_callback registry path."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from byzantinemomentum_tpu import native, ops

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def rand(n, d, seed=0, nan_rows=0):
    rng = np.random.default_rng(seed)
    g = rng.normal(size=(n, d)).astype(np.float32)
    g[:nan_rows] = np.nan
    return g


@pytest.mark.parametrize("nan_rows", [0, 2])
def test_median_matches_jnp(nan_rows):
    g = rand(11, 33, seed=1, nan_rows=nan_rows)
    got = native.median.aggregate(g)
    want = np.asarray(ops.gars["median"].unchecked(jnp.asarray(g)))
    np.testing.assert_allclose(got, want, atol=1e-6)
    assert np.isfinite(got).all()


@pytest.mark.parametrize("m", [None, 2])
@pytest.mark.parametrize("nan_rows", [0, 2])
def test_krum_matches_jnp(m, nan_rows):
    g = rand(13, 24, seed=2, nan_rows=nan_rows)
    got = native.krum.aggregate(g, 3, m)
    want = np.asarray(ops.gars["krum"].unchecked(jnp.asarray(g), f=3, m=m))
    np.testing.assert_allclose(got, want, atol=1e-5)


@pytest.mark.parametrize("nan_rows", [0, 2])
def test_bulyan_matches_jnp(nan_rows):
    g = rand(13, 24, seed=3, nan_rows=nan_rows)
    got = native.bulyan.aggregate(g, 2)
    want = np.asarray(ops.gars["bulyan"].unchecked(jnp.asarray(g), f=2))
    np.testing.assert_allclose(got, want, atol=1e-5)


@pytest.mark.parametrize("nan_rows", [0, 2])
def test_brute_matches_jnp(nan_rows):
    g = rand(9, 16, seed=4, nan_rows=nan_rows)
    got = native.brute.aggregate(g, 2)
    want = np.asarray(ops.gars["brute"].unchecked(jnp.asarray(g), f=2))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_cpp_registry_entries_under_jit():
    g = jnp.asarray(rand(13, 20, seed=5))
    for name, kwargs in (("cpp-median", {}), ("cpp-krum", {}),
                         ("cpp-bulyan", {"f": 2}), ("cpp-brute", {})):
        f = kwargs.get("f", 3)
        got = jax.jit(
            lambda G, name=name, f=f: ops.gars[name].unchecked(G, f=f))(g)
        want = ops.gars[name.removeprefix("cpp-")].unchecked(g, f=f)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)


def test_large_d_consistency():
    """The native tier must agree on realistic gradient sizes too."""
    g = rand(11, 5000, seed=6)
    got = native.krum.aggregate(g, 2)
    want = np.asarray(ops.gars["krum"].unchecked(jnp.asarray(g), f=2))
    np.testing.assert_allclose(got, want, atol=1e-4)
