"""Whole-program lock-discipline suite: the BMT-L rule family over the
interprocedural lock-order graph (`analysis/locks.py`) — violating +
clean fixture pair per rule, the planted two-thread inversion the graph
must catch through the call graph, the L01-vs-L04 role split, the noqa
contract, the blessed-hierarchy round trip (`scripts/bless_locks.py`),
the runtime-edges-subset-of-static cross-check
(`contracts.record_lock_edges` + `utils/locking.NamedLock`), the
repo-wide clean gates (BMT-L and the BMT-E11 traced-scope lazy-init
rule), and the CLI exit codes.

Everything here is host-only (no jax import at module scope): the sweep
is pure AST and the named-lock runtime is pure stdlib, so this file
runs even where no backend initializes.
"""

import json
import pathlib
import subprocess
import sys

import pytest

from byzantinemomentum_tpu.analysis import contracts, lint, locks
from byzantinemomentum_tpu.analysis.__main__ import main as analysis_main
from byzantinemomentum_tpu.utils import locking

ROOT = pathlib.Path(__file__).resolve().parent.parent


# --------------------------------------------------------------------------- #
# BMT-L fixtures: one violating + one clean pair per rule. The L01 pair
# is the planted version of the PR 17 router liveness surface: a flip
# thread takes ring -> manifest while a persist thread takes
# manifest -> ring, each through a helper method — the inversion is only
# visible interprocedurally.

L_FIXTURES = {
    "BMT-L01": (
        """
import threading

class Router:
    def __init__(self):
        self._ring = threading.Lock()  # bmt: noqa[BMT-L06] planted fixture
        self._manifest = threading.Lock()
        t1 = threading.Thread(target=self._flip_loop, daemon=True)
        t2 = threading.Thread(target=self._persist_loop, daemon=True)
        t1.start(); t2.start()

    def _write_manifest(self):
        with self._manifest:
            pass

    def _flip_loop(self):
        while True:
            with self._ring:
                self._write_manifest()

    def _persist_loop(self):
        while True:
            with self._manifest:
                self._read_ring()

    def _read_ring(self):
        with self._ring:
            pass
""",
        """
import threading

class Router:
    def __init__(self):
        self._ring = threading.Lock()  # bmt: noqa[BMT-L06] planted fixture
        self._manifest = threading.Lock()
        t1 = threading.Thread(target=self._flip_loop, daemon=True)
        t2 = threading.Thread(target=self._persist_loop, daemon=True)
        t1.start(); t2.start()

    def _write_manifest(self):
        with self._manifest:
            pass

    def _flip_loop(self):
        while True:
            with self._ring:
                self._write_manifest()

    def _persist_loop(self):
        while True:
            with self._ring:
                self._write_manifest()
""",
    ),
    "BMT-L02": (
        """
import threading
import time

class Poller:
    def __init__(self):
        self._lock = threading.Lock()  # bmt: noqa[BMT-L06] planted fixture
        self.count = 0

    def tick(self):
        with self._lock:
            self._pause()
            self.count += 1

    def _pause(self):
        time.sleep(0.1)
""",
        """
import threading
import time

class Poller:
    def __init__(self):
        self._lock = threading.Lock()  # bmt: noqa[BMT-L06] planted fixture
        self.count = 0

    def tick(self):
        self._pause()
        with self._lock:
            self.count += 1

    def _pause(self):
        time.sleep(0.1)
""",
    ),
    "BMT-L03": (
        """
import threading

class Store:
    def __init__(self, on_change_hook):
        self._lock = threading.Lock()  # bmt: noqa[BMT-L06] planted fixture
        self._hook = on_change_hook
        self.value = 0

    def set(self, value):
        with self._lock:
            self.value = value
            self._hook(value)
""",
        """
import threading

class Store:
    def __init__(self, on_change_hook):
        self._lock = threading.Lock()  # bmt: noqa[BMT-L06] planted fixture
        self._hook = on_change_hook
        self.value = 0

    def set(self, value):
        with self._lock:
            self.value = value
        self._hook(value)
""",
    ),
    "BMT-L04": (
        """
import threading

class Mover:
    def __init__(self):
        self._src = threading.Lock()  # bmt: noqa[BMT-L06] planted fixture
        self._dst = threading.Lock()
        t = threading.Thread(target=self._loop, daemon=True)
        t.start()

    def _loop(self):
        while True:
            self._forward()
            self._backward()

    def _forward(self):
        with self._src:
            with self._dst:
                pass

    def _backward(self):
        with self._dst:
            with self._src:
                pass
""",
        """
import threading

class Mover:
    def __init__(self):
        self._src = threading.Lock()  # bmt: noqa[BMT-L06] planted fixture
        self._dst = threading.Lock()
        t = threading.Thread(target=self._loop, daemon=True)
        t.start()

    def _loop(self):
        while True:
            self._forward()
            self._backward()

    def _forward(self):
        with self._src:
            with self._dst:
                pass

    def _backward(self):
        with self._src:
            with self._dst:
                pass
""",
    ),
    "BMT-L05": (
        """
import threading

_ENGINE = None

def get_engine():
    global _ENGINE
    if _ENGINE is None:
        _ENGINE = object()
    return _ENGINE

def loop():
    while True:
        get_engine()

_t = threading.Thread(target=loop, daemon=True)  # bmt: noqa[BMT-L06] planted fixture
""",
        """
import threading

_ENGINE = None
_ENGINE_LOCK = threading.Lock()  # bmt: noqa[BMT-L06] planted fixture

def get_engine():
    global _ENGINE
    with _ENGINE_LOCK:
        if _ENGINE is None:
            _ENGINE = object()
        return _ENGINE

def loop():
    while True:
        get_engine()

_t = threading.Thread(target=loop, daemon=True)
""",
    ),
    "BMT-L06": (
        """
import threading

def spawn(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()
    return t
""",
        """
import threading

def spawn(fn):
    t = threading.Thread(target=fn, daemon=True)  # bmt: noqa[BMT-L06] bare spawn helper for tests; callers own the interleavings
    t.start()
    return t
""",
    ),
}


def _sweep(tmp_path, source, name="fixture.py"):
    path = tmp_path / name
    path.write_text(source)
    return locks.build(paths=[path])


@pytest.mark.parametrize("rule_id", sorted(L_FIXTURES))
def test_l_rule_fixture_pair(rule_id, tmp_path):
    """Every L-rule fires on its violating fixture and stays silent on
    the clean one (and the clean one trips no OTHER L-rule either)."""
    bad, good = L_FIXTURES[rule_id]
    hits = {v.rule for v in _sweep(tmp_path, bad, "bad.py").violations}
    assert rule_id in hits, f"{rule_id} missed its violating fixture"
    clean = _sweep(tmp_path, good, "good.py").violations
    assert clean == [], f"clean fixture not clean: {clean}"


def test_l01_inversion_is_interprocedural():
    """The planted two-lock/two-thread inversion is only visible through
    the call graph (each second acquisition happens inside a helper
    method); the report names both locks, both thread roles, and a
    file:line witness for each direction of the cycle."""
    import tempfile
    bad, _ = L_FIXTURES["BMT-L01"]
    tmp = pathlib.Path(tempfile.mkdtemp())
    graph = _sweep(tmp, bad, "router.py")
    assert graph.cycles == [["Router._manifest", "Router._ring"]]
    hit = next(v for v in graph.violations if v.rule == "BMT-L01")
    for needle in ("Router._ring", "Router._manifest",
                   "thread:_flip_loop", "thread:_persist_loop",
                   "router.py:"):
        assert needle in hit.message, (needle, hit.message)
    # Both directions carry a witness line, each inside a helper the
    # entry loop never textually contains.
    assert hit.message.count("router.py:") >= 2


def test_l04_single_role_is_not_a_deadlock(tmp_path):
    """Both orders on ONE thread role is latent (L04), not a deadlock
    (L01): a single thread cannot deadlock against itself, but the next
    refactor that adds a second role makes the inversion live."""
    bad, _ = L_FIXTURES["BMT-L04"]
    rules = {v.rule for v in _sweep(tmp_path, bad).violations}
    assert "BMT-L04" in rules
    assert "BMT-L01" not in rules


def test_l02_noqa_reason_contract(tmp_path):
    """A reasoned noqa suppresses the L02 (and counts as suppressed); a
    reasonless one does NOT suppress — and the lint pass flags the empty
    reason itself (BMT-E00), so there is no silent escape hatch."""
    bad, _ = L_FIXTURES["BMT-L02"]
    annotated = bad.replace(
        "time.sleep(0.1)",
        "time.sleep(0.1)  # bmt: noqa[BMT-L02] fixture poller cadence")
    graph = _sweep(tmp_path, annotated, "annotated.py")
    assert graph.violations == []
    assert graph.suppressed >= 1
    reasonless = bad.replace(
        "time.sleep(0.1)",
        "time.sleep(0.1)  # bmt: noqa[BMT-L02]")
    graph = _sweep(tmp_path, reasonless, "reasonless.py")
    assert "BMT-L02" in {v.rule for v in graph.violations}
    assert "BMT-E00" in {v.rule for v in lint.lint_source(reasonless)}


def test_census_orders_the_hierarchy(tmp_path):
    """The census renders edges as `held -> taken` and the topo order
    puts every held lock before what it nests."""
    _, good = L_FIXTURES["BMT-L04"]
    path = tmp_path / "mover.py"
    path.write_text(good)
    census = locks.census(paths=[path])
    assert "Mover._src -> Mover._dst" in census["edges"]
    order = census["order"]
    assert order.index("Mover._src") < order.index("Mover._dst")
    assert census["python"] == f"{sys.version_info[0]}.{sys.version_info[1]}"


# --------------------------------------------------------------------------- #
# The blessed hierarchy: golden statuses + the bless script round trip

def test_check_statuses(tmp_path):
    """missing -> fail; blessed-under-other-python -> incomparable (not
    a drift failure); tampered census -> drift with the delta named."""
    report = locks.check(path=tmp_path / "absent.json")
    assert report["status"] == "missing" and not report["ok"]

    golden = tmp_path / "locks.json"
    locks.bless(path=golden)
    assert locks.check(path=golden)["status"] == "ok"

    payload = json.loads(golden.read_text())
    payload["python"] = "0.0"
    golden.write_text(json.dumps(payload))
    report = locks.check(path=golden)
    assert report["status"] == "incomparable" and report["ok"]

    payload["python"] = f"{sys.version_info[0]}.{sys.version_info[1]}"
    payload["locks"] = payload["locks"] + ["ghost.lock"]
    golden.write_text(json.dumps(payload))
    report = locks.check(path=golden)
    assert report["status"] == "drift" and not report["ok"]
    assert report["drift"]["locks_removed"] == ["ghost.lock"]


@pytest.mark.slow
def test_bless_script_round_trip(tmp_path):
    """`scripts/bless_locks.py` is idempotent (second bless byte-
    identical), prunes stale names with a report, and `--check` gates."""
    golden = tmp_path / "locks.json"
    script = ROOT / "scripts" / "bless_locks.py"
    run = lambda *args: subprocess.run(
        [sys.executable, str(script), *args],
        capture_output=True, text=True, cwd=ROOT)

    first = run("--out", str(golden))
    assert first.returncode == 0, first.stderr
    blessed_bytes = golden.read_bytes()
    second = run("--out", str(golden))
    assert second.returncode == 0 and "(unchanged)" in second.stdout
    assert golden.read_bytes() == blessed_bytes

    checked = run("--out", str(golden), "--check")
    assert checked.returncode == 0, checked.stdout

    payload = json.loads(golden.read_text())
    payload["locks"] = payload["locks"] + ["ghost.lock"]
    golden.write_text(json.dumps(payload))
    assert run("--out", str(golden), "--check").returncode == 1
    reblessed = run("--out", str(golden))
    assert reblessed.returncode == 0
    assert "pruned: ghost.lock" in reblessed.stdout
    assert golden.read_bytes() == blessed_bytes


# --------------------------------------------------------------------------- #
# Runtime cross-check: NamedLock edge recording vs the static graph

def test_named_lock_records_nesting_edges():
    a = locking.NamedLock("router.membership")
    b = locking.NamedLock("router.ring")
    with contracts.record_lock_edges() as edges:
        with a:
            assert locking.held_locks() == ("router.membership",)
            with b:
                assert locking.held_locks() == ("router.membership",
                                                "router.ring")
    assert edges == {("router.membership", "router.ring")}
    # The window closed: acquisitions no longer record.
    with a:
        with b:
            pass
    assert edges == {("router.membership", "router.ring")}


def test_named_condition_wait_releases_the_name():
    """A consumer parked in `wait()` must not appear to hold the
    condition — the wait pops the name and re-records on wake."""
    cond = locking.NamedCondition("batcher.cond")
    seen = []
    with contracts.record_lock_edges():
        with cond:
            assert locking.held_locks() == ("batcher.cond",)
            cond.wait_for(
                lambda: (seen.append(locking.held_locks()), True)[1])
            assert locking.held_locks() == ("batcher.cond",)
    assert seen == [()]


def test_recorder_mid_hold_stays_balanced():
    """A recorder installed while a lock is already held must not see a
    phantom pop at that hold's release, and the NEXT acquisition records
    normally (the `_noted` protocol)."""
    lock = locking.NamedLock("service.stats")
    lock.acquire()
    with contracts.record_lock_edges() as edges:
        lock.release()          # un-noted hold: no stack underflow
        assert locking.held_locks() == ()
        with lock:
            assert locking.held_locks() == ("service.stats",)
        assert locking.held_locks() == ()
    assert edges == set()


def test_runtime_edges_subset_of_static():
    """The edge the serve fleet actually exercises (membership -> ring)
    is in the static graph; the inverted order is not, and fails with
    both names in the error."""
    static = locks.static_edges()
    assert ("router.membership", "router.ring") in static
    assert contracts.assert_lock_edges_subset(
        {("router.membership", "router.ring")}, static) == 1
    # Self-edges are distinct instances sharing a role name — ignored.
    assert contracts.assert_lock_edges_subset(
        {("metrics.counter", "metrics.counter")}, static) == 0
    with pytest.raises(contracts.LockOrderError) as err:
        contracts.assert_lock_edges_subset(
            {("router.ring", "router.membership")}, static)
    assert "router.ring -> router.membership" in str(err.value)


# --------------------------------------------------------------------------- #
# Repo-wide gates + CLI

def test_repo_lock_surface_is_clean():
    """The committed hierarchy is green: zero unannotated L violations
    and the census matches `tests/goldens/locks.json` exactly."""
    report = locks.check()
    assert report["violations"] == [], report["violations"]
    assert report["status"] == "ok", report
    assert report["ok"]


def test_repo_is_e11_clean():
    """No traced scope in the package lazily initializes a module
    global (BMT-E11) — the pattern bakes first-call state into the
    jaxpr and races under concurrent tracing."""
    violations = lint.lint_paths(
        [ROOT / "byzantinemomentum_tpu", ROOT / "scripts"],
        rules={"BMT-E11", "BMT-E00"})
    assert violations == [], lint.format_human(violations)


E11_BAD = """
import jax

_TABLE = None

@jax.jit
def lookup(x):
    global _TABLE
    if _TABLE is None:
        _TABLE = build_table()
    return x + _TABLE[0]
"""

E11_BAD_CACHE = """
import jax

_CACHE = {}

@jax.jit
def solve(x, k):
    if k not in _CACHE:
        _CACHE[k] = precompute(k)
    return x * _CACHE[k]
"""

E11_GOOD = """
import jax

_TABLE = None

def _ensure_table():
    global _TABLE
    if _TABLE is None:
        _TABLE = build_table()
    return _TABLE

@jax.jit
def lookup(x):
    return x + lookup_const(x)
"""


def test_e11_fixture_pair():
    """BMT-E11 fires on both lazy-init shapes inside a traced scope
    (`is None` global and `key not in dict` memo) and stays silent when
    the init happens outside the trace."""
    assert {v.rule for v in lint.lint_source(E11_BAD)} == {"BMT-E11"}
    assert {v.rule for v in lint.lint_source(E11_BAD_CACHE)} == {"BMT-E11"}
    assert lint.lint_source(E11_GOOD) == []


def test_cli_check_locks(tmp_path, capsys):
    """`--check-locks` exits 0 on the committed green hierarchy, 1 when
    pointed at a missing golden, and `--rules` lists the L-family."""
    assert analysis_main(["--check-locks"]) == 0
    capsys.readouterr()
    assert analysis_main(["--check-locks", "--goldens",
                          str(tmp_path / "absent.json")]) == 1
    out = capsys.readouterr().out
    assert "missing" in out
    capsys.readouterr()
    assert analysis_main(["--rules"]) == 0
    table = capsys.readouterr().out
    for rule_id in ("BMT-L01", "BMT-L02", "BMT-L03", "BMT-L04",
                    "BMT-L05", "BMT-L06", "BMT-E11"):
        assert rule_id in table, f"--rules table is missing {rule_id}"
