"""Lattice-wide lowering contracts: the builder-derived golden
enumeration (legacy-key reproduction, virtual-mesh + serve coverage),
the BMT-H structural linter (fixture pair per rule, planted all-gather
census), the sharded-diagnostics oracle, the virtual-mesh runtime
contracts (zero-recompile warm loop + transfer guard — the
`parallel/sharded.py` kernels' first such coverage), and the
stale-golden prune workflow."""

import json
import pathlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from byzantinemomentum_tpu import ops
from byzantinemomentum_tpu.analysis import (
    contracts, hlolint, lattice, lowering)
from byzantinemomentum_tpu.parallel import make_mesh
from byzantinemomentum_tpu.parallel.mesh import MODEL, shard_map
from byzantinemomentum_tpu.parallel.sharded import shard_defense_list

ROOT = pathlib.Path(__file__).resolve().parent.parent


# --------------------------------------------------------------------------- #
# The enumerator vs the retired hand-list

def _legacy_cell_text(gar, variant):
    """The PR 5 hand-listed cell recipe, inlined as the oracle: the
    enumerator must reproduce every previously blessed cell key with a
    byte-identical fingerprint (the program-builder collapse re-blesses
    NOTHING)."""
    from byzantinemomentum_tpu.faults import quorum

    N, D, F = lattice.N, lattice.D, lattice.F
    if variant == "plain":
        fn = lambda G: gar.unchecked(G, f=F)
    elif variant == "diag":
        fn = lambda G: gar.diagnosed(G, f=F)
    else:
        fn = lambda G, active: quorum.masked_aggregate(
            gar, G, active, f_decl=F, dynamic=True)
    spec = jax.ShapeDtypeStruct((N, D), jnp.float32)
    mask = jax.ShapeDtypeStruct((N,), jnp.bool_)
    args = (spec,) if variant != "masked" else (spec, mask)
    return jax.jit(fn).lower(*args).as_text()


def test_enumerator_reproduces_legacy_cells():
    """Every (GAR x plain/diag/masked) key of the retired hand-list is
    enumerated, and its fingerprint equals the legacy recipe's — the
    trace-equivalence proof behind the no-re-bless criterion."""
    cells = {c.key: c for c in lattice.enumerate_cells(meshes=(), serve=())}
    for name in lattice.CELL_GARS:
        for variant in lattice.VARIANTS:
            key = f"{name}/{variant}"
            assert key in cells, f"enumerator dropped legacy cell {key}"
            got = lowering.fingerprint(cells[key].lower())
            want = lowering.fingerprint(
                _legacy_cell_text(ops.gars[name], variant))
            assert got == want, f"{key} fingerprint drifted from legacy"


def test_lattice_covers_mesh_serve_and_update_axes():
    """The full enumeration at least doubles the legacy surface and
    includes virtual-mesh sharded cells, masked-bucket cells, serve cells
    and the donated update-contract cell."""
    keys = [c.key for c in lattice.enumerate_cells()]
    assert len(keys) == len(set(keys)), "duplicate cell keys"
    assert len(keys) >= 60
    legacy = [k for k in keys if "/" in k and "@" not in k
              and not k.endswith(("/masked-bucket", "/quarantine"))
              and not k.startswith(("serve/", "engine/"))]
    assert len(legacy) == 30
    for k in lattice.MESH_AXES:
        assert f"krum/plain@mesh{k}" in keys
    assert "krum/diag@mesh2" in keys  # the sharded-diagnostics axis
    assert any(k.startswith("serve/") for k in keys)
    assert "engine/sgd-update@donate" in keys
    # The r10 bucket axis: every rule's traced-count masked kernel at a
    # padded serving shape, incl. the scan/enumeration holdouts
    for name in lattice.CELL_GARS:
        assert f"{name}/masked-bucket" in keys
    assert "serve/bulyan/n16f2d32b2" in keys
    assert "serve/brute/n8f2d32b2+diag" in keys
    # The r11 quarantine axis: the closed defense loop's per-rule
    # defense-plus-aux program with the runtime mask/credit operands
    for name in lattice.CELL_GARS:
        assert f"{name}/quarantine" in keys


def test_masked_bucket_cells_hold_h01_h02():
    """The BMT-H census of the traced-count masked kernels: zero
    collectives AND no worker-matrix-scale gather — bulyan's inert-round
    scan, brute's one-hot unranking and the rank-predicate rules must
    never fall back to dynamic row gathers of the padded matrix."""
    for name in ("bulyan", "brute", "phocas", "meamed", "aksel", "cge"):
        cell = next(c for c in lattice.enumerate_cells(meshes=(), serve=())
                    if c.key == f"{name}/masked-bucket")
        key, text, expect = lattice.lower_cell(cell)
        assert expect.psums == 0
        assert expect.gather_limit == lattice.N_BUCKET * lattice.D - 1
        assert hlolint.lint_module(text, expect, key) == [], key


def test_quarantine_cells_hold_h01_h02():
    """The r11 quarantine call-site programs (`arena/quarantine.py` —
    masked-quorum kernel + dynamic f_eff + suspicion aux, with the
    active mask and the reclaimed-quorum credit as runtime operands):
    zero collectives, no worker-matrix-scale gather — an eviction is a
    bool flip over one program, structurally."""
    for name in ("krum", "bulyan", "brute", "median"):
        cell = next(c for c in lattice.enumerate_cells(meshes=(), serve=())
                    if c.key == f"{name}/quarantine")
        key, text, expect = lattice.lower_cell(cell)
        assert expect.psums == 0
        assert hlolint.lint_module(text, expect, key) == [], key


def test_committed_goldens_are_the_enumeration():
    """The committed goldens file holds exactly the enumerated PINNED
    keys (no stale keys can linger: the file IS the enumeration;
    structural-only cells are linted every check but never blessed)."""
    blessed = json.loads(
        (ROOT / "tests" / "goldens" / "lowerings.json").read_text())
    assert set(blessed["cells"]) == {
        c.key for c in lattice.enumerate_cells() if c.pin}
    assert blessed["spec"]["meshes"] == list(lattice.MESH_AXES)


def test_full_step_cell_is_structural_only():
    """The workers-axis grouped honest phase finally has lowering
    coverage: the FULL fused mesh step is enumerated, its census is
    pinned (exactly the one Gram psum, no explicit worker-matrix
    all_gather), and its high-churn fingerprint is NOT blessed
    (`pin=False`) — so engine refactors re-lower it through the BMT-H
    gate without a re-bless treadmill."""
    cells = {c.key: c for c in lattice.enumerate_cells()}
    cell = cells["engine/full-step@mesh2x2"]
    assert cell.pin is False
    assert cell.expect.psums == 1
    assert cell.expect.gather_limit is not None
    # Not fingerprinted: compute_cells skips it, check() lints it
    assert "engine/full-step@mesh2x2" not in lowering.compute_cells(
        [cells["engine/full-step@mesh2x2"],
         cells["engine/sgd-update@donate"]])


@pytest.mark.slow
def test_full_step_cell_census_holds():
    """Lower the full fused step over the (2, 2) virtual mesh and run
    the census: the grouped honest phase's shard_map must stay
    collective-free (worker rows are data parallel), krum's psum'd Gram
    must stay the ONLY explicit collective, and nothing may all-gather
    the worker matrix."""
    cell = next(c for c in lattice.enumerate_cells()
                if c.key == "engine/full-step@mesh2x2")
    key, text, expect = lattice.lower_cell(cell)
    assert hlolint.lint_module(text, expect, key) == [], key
    # The census is exact, not vacuous: the text really contains the one
    # explicit all_reduce of the d-sharded Gram
    assert text.count("stablehlo.all_reduce") >= 1


def test_multiprocess_cells_need_a_fleet_but_build_single_process():
    """`multiprocess_cells` refuses to silently degrade to one process;
    with the guard lowered (the builder-shape path tests use) the cells
    lower on the virtual platform and hold their census — the SAME
    cells every cluster host lowers for the launcher's cross-host
    fingerprint agreement (`cluster/host.py::_run_census`)."""
    with pytest.raises(RuntimeError, match="fleet"):
        lattice.multiprocess_cells()
    cells = lattice.multiprocess_cells(min_processes=1)
    keys = [c.key for c in cells]
    assert keys == [f"{name}/plain@proc1"
                    for name in lattice.MULTIPROC_GARS]
    for cell in cells:
        assert cell.pin is False  # consensus-checked, never blessed
        key, text, expect = lattice.lower_cell(cell)
        assert hlolint.lint_module(text, expect, key) == [], key
        wants_psum = cell.key.split("/")[0] in lattice.GRAM_RULES
        assert expect.psums == (1 if wants_psum else 0)


# --------------------------------------------------------------------------- #
# hlolint: violating + clean lowered fixture per BMT-H rule

N, D = lattice.N, lattice.D


@pytest.fixture(scope="module")
def mesh2():
    return make_mesh(2, model_parallel=2)


def _gram_cell_text(mesh, gathered):
    """The sharded Gram distance kernel — real (one psum of the tiny
    (n, n) partial Gram) or the planted all-gather variant (the whole
    (n, d) worker matrix crosses the interconnect)."""
    from byzantinemomentum_tpu.ops import _common

    def real(g_local):
        part = jnp.matmul(g_local, g_local.T,
                          precision=jax.lax.Precision.HIGHEST)
        return _common.distances_from_sq_gram(lax.psum(part, MODEL))

    def planted(g_local):
        g_full = lax.all_gather(g_local, MODEL, axis=1, tiled=True)
        gram = jnp.matmul(g_full, g_full.T,
                          precision=jax.lax.Precision.HIGHEST)
        return _common.distances_from_sq_gram(gram)

    # check_vma=False on BOTH variants: the planted all-gather defeats
    # the replication checker (that is not the failure mode under test)
    fn = shard_map(planted if gathered else real, mesh=mesh,
                   in_specs=P(None, MODEL), out_specs=P(None, None),
                   check_vma=False)
    spec = jax.ShapeDtypeStruct((N, D), jnp.float32)
    return jax.jit(fn).lower(spec).as_text()


def test_census_fails_planted_all_gather_and_passes_real_kernel(mesh2):
    """The acceptance fixture: BMT-H01 (and the worker-matrix-gather
    rule) fail on an all-gather variant of the sharded Gram and pass the
    real psum kernel."""
    expect = hlolint.Expect(psums=1, gather_limit=N * D - 1)
    assert hlolint.lint_module(
        _gram_cell_text(mesh2, gathered=False), expect, "real") == []
    hits = hlolint.lint_module(
        _gram_cell_text(mesh2, gathered=True), expect, "planted")
    rules = {v.rule for v in hits}
    assert "BMT-H01" in rules, hits   # 0 psums where 1 was declared
    assert "BMT-H02" in rules, hits   # the (n, d) matrix was gathered
    gather = next(v for v in hits if v.rule == "BMT-H02")
    assert str(N * D) in gather.message or "176" in gather.message


def test_h02_tolerates_small_gathers(mesh2):
    """An all_gather BELOW the worker-matrix budget (a tiny replicated
    vector) is legal — the rule targets the (n, d) matrix, not every
    collective."""
    def kernel(g_local):
        norms = jnp.sum(g_local * g_local, axis=0)        # (d_shard,)
        return lax.all_gather(norms, MODEL, axis=0, tiled=True)

    fn = shard_map(kernel, mesh=mesh2, in_specs=P(None, MODEL),
                   out_specs=P(MODEL))
    text = jax.jit(fn).lower(
        jax.ShapeDtypeStruct((N, D), jnp.float32)).as_text()
    expect = hlolint.Expect(psums=0, gather_limit=N * D - 1)
    assert hlolint.lint_module(text, expect, "small-gather") == []


def test_h03_donation_fixture_pair():
    """Honored donation (matching output shape -> aliasing recorded)
    passes; an unusable donation request (no matching output) fails."""
    spec = jax.ShapeDtypeStruct((8,), jnp.float32)
    honored = jax.jit(lambda s, g: s - 0.1 * g,
                      donate_argnums=(0,)).lower(spec, spec).as_text()
    expect = hlolint.Expect(donated=(0,))
    assert hlolint.lint_module(honored, expect, "honored") == []
    dropped = jax.jit(lambda s: jnp.sum(s),
                      donate_argnums=(0,)).lower(spec).as_text()
    hits = hlolint.lint_module(dropped, expect, "dropped")
    assert [v.rule for v in hits] == ["BMT-H03"]


def test_h04_f64_fixture_pair():
    spec32 = jax.ShapeDtypeStruct((4,), jnp.float32)
    clean = jax.jit(lambda x: x * 2.5).lower(spec32).as_text()
    assert hlolint.lint_module(clean, None, "f32") == []
    from jax.experimental import enable_x64
    with enable_x64():
        spec64 = jax.ShapeDtypeStruct((4,), jnp.float64)
        hot = jax.jit(lambda x: x * 2.5).lower(spec64).as_text()
    hits = hlolint.lint_module(hot, None, "f64")
    assert [v.rule for v in hits] == ["BMT-H04"]


def test_h05_host_callback_fixture_pair():
    spec = jax.ShapeDtypeStruct((4,), jnp.float32)

    def chatty(x):
        jax.debug.print("x={x}", x=x)
        return x * 2

    hot = jax.jit(chatty).lower(spec).as_text()
    hits = hlolint.lint_module(hot, None, "chatty")
    assert [v.rule for v in hits] == ["BMT-H05"]
    clean = jax.jit(lambda x: x * 2).lower(spec).as_text()
    assert hlolint.lint_module(clean, None, "quiet") == []


def test_check_reports_structural_violations(tmp_path, monkeypatch):
    """A cell whose declared census stops matching reports status
    `lint` (fingerprints alone cannot say WHY a program is wrong)."""
    monkeypatch.setattr(lattice, "CELL_GARS", ("median",))
    monkeypatch.setattr(lattice, "MESH_AXES", (2,))
    monkeypatch.setattr(lattice, "SERVE_CELLS", ())
    lowering.bless(tmp_path / "g.json")
    # Declare median a Gram rule: its mesh cells now expect 1 psum but
    # lower with 0 — same fingerprints, broken structure
    monkeypatch.setattr(lattice, "GRAM_RULES", frozenset({"median"}))
    report = lowering.check(tmp_path / "g.json")
    assert report["status"] == "lint"
    assert any(v["rule"] == "BMT-H01" for v in report["violations"])


# --------------------------------------------------------------------------- #
# Sharded diagnostics oracle (the builder's diag-under-mesh axis)

def _aux_equal(got, want):
    for key in want:
        g, w = np.asarray(got[key]), np.asarray(want[key])
        assert g.shape == w.shape, key
        assert (np.isfinite(g) == np.isfinite(w)).all(), key
        np.testing.assert_allclose(
            np.where(np.isfinite(g), g, 0.0),
            np.where(np.isfinite(w), w, 0.0),
            rtol=1e-4, atol=1e-4, err_msg=key)


@pytest.mark.parametrize("name", ["krum", "bulyan", "brute"])
@pytest.mark.parametrize("f", [1, 2, 3])
def test_sharded_diag_aux_matches_unsharded(name, f):
    """The d-sharded diagnostics kernels (psum'd-Gram aux) reproduce the
    single-device native aux — f in {1, 2, 3}, with f planted NaN rows
    riding the +inf distance convention across shards."""
    mesh = make_mesh(4, model_parallel=4)
    n, d = 4 * f + 4, 64  # satisfies every rule's contract up to f=3
    rng = np.random.default_rng(10 * f + len(name))
    g = rng.normal(size=(n, d)).astype(np.float32)
    g[-f:] = np.nan  # planted corrupt rows, within the declared tolerance
    g = jnp.asarray(g)
    gar = ops.gars[name]
    agg_u, aux_u = gar.diagnosed(g, f=f)
    facade = shard_defense_list([(gar, 1.0, {})], mesh, f=f)[0][0]
    assert facade._diag_fn is not None  # the native sharded path engaged
    agg_s, aux_s = facade.diagnosed(g, f=f)
    np.testing.assert_allclose(np.asarray(agg_s), np.asarray(agg_u),
                               rtol=1e-4, atol=1e-5)
    _aux_equal(aux_s, aux_u)


@pytest.mark.parametrize("name", ["trmean", "phocas", "meamed", "median"])
@pytest.mark.parametrize("f", [1, 2, 3])
def test_sharded_coord_diag_aux_matches_unsharded(name, f):
    """The coordinate-wise sharded diagnostics (r10 for the trim rules,
    r11 for median's was-median fraction — ROADMAP lattice rung 3):
    trim fractions and deviation scores from d-local partial sums psum'd
    with shard widths accounted — oracle-tested against the unsharded
    NATIVE aux, with planted NaN rows and a non-dividing d (divisibility
    padding must not dilute the per-coordinate means)."""
    mesh = make_mesh(4, model_parallel=4)
    n, d = 4 * f + 4, 66  # 66 % 4 != 0: the facade pads two zero columns
    rng = np.random.default_rng(20 * f + len(name))
    g = rng.normal(size=(n, d)).astype(np.float32)
    g[-f:] = np.nan
    g = jnp.asarray(g)
    gar = ops.gars[name]
    agg_u, aux_u = gar.diagnosed(g, f=f)
    facade = shard_defense_list([(gar, 1.0, {})], mesh, f=f)[0][0]
    assert facade._diag_fn is not None  # the native sharded path engaged
    agg_s, aux_s = facade.diagnosed(g, f=f)
    np.testing.assert_allclose(np.asarray(agg_s), np.asarray(agg_u),
                               rtol=1e-4, atol=1e-5)
    _aux_equal(aux_s, aux_u)


def test_sharded_diag_generic_fallback_for_coordinate_rules():
    """Rules without a native sharded aux (average, since r11 the last
    ones standing are the index-selection rules aksel/cge and average)
    keep the generic geometry fallback; median — the former holdout —
    now routes natively."""
    mesh = make_mesh(2, model_parallel=2)
    facade = shard_defense_list(
        [(ops.gars["average"], 1.0, {})], mesh, f=2)[0][0]
    assert facade._diag_fn is None
    g = jnp.asarray(np.random.default_rng(3).normal(
        size=(11, 16)).astype(np.float32))
    agg, aux = facade.diagnosed(g, f=2)
    assert set(aux) == {"scores", "selection", "dist", "trim_frac"}
    np.testing.assert_allclose(
        np.asarray(agg),
        np.asarray(ops.gars["average"].unchecked(g, f=2)),
        rtol=1e-4, atol=1e-5)
    native = shard_defense_list(
        [(ops.gars["median"], 1.0, {})], mesh, f=2)[0][0]
    assert native._diag_fn is not None


# --------------------------------------------------------------------------- #
# Virtual-mesh runtime contracts: the sharded kernels' first recompile
# budget and transfer guard

def test_sharded_kernel_zero_recompile_and_no_transfers(mesh2):
    """A warm d-sharded GAR kernel compiles nothing and moves nothing
    implicitly — the same discipline the engine step has had since PR 5,
    now on the `parallel/sharded.py` surface via a virtual CPU mesh."""
    from jax.sharding import NamedSharding

    facade = shard_defense_list(
        [(ops.gars["krum"], 1.0, {})], mesh2, f=2)[0][0]
    step = jax.jit(lambda G: facade.unchecked(G, f=2))
    # Commit the operand in the kernel's own layout: an UNcommitted input
    # would be resharded implicitly — exactly what the guard flags
    g = jax.device_put(
        jnp.asarray(np.random.default_rng(7).normal(
            size=(N, D)).astype(np.float32)),
        NamedSharding(mesh2, P(None, MODEL)))
    jax.block_until_ready(step(g))  # warm
    assert contracts.assert_recompile_budget(
        lambda: step(g), steps=3, budget=0,
        label="warm sharded krum kernel") == 0
    with contracts.no_implicit_transfers():
        jax.block_until_ready(step(g))


def test_sharded_diag_kernel_zero_recompile(mesh2):
    """The diag-under-mesh axis holds the same budget."""
    from jax.sharding import NamedSharding

    facade = shard_defense_list(
        [(ops.gars["bulyan"], 1.0, {})], mesh2, f=2)[0][0]
    step = jax.jit(lambda G: facade.diagnosed(G, f=2))
    g = jax.device_put(
        jnp.asarray(np.random.default_rng(8).normal(
            size=(N, D)).astype(np.float32)),
        NamedSharding(mesh2, P(None, MODEL)))
    jax.block_until_ready(step(g))
    assert contracts.assert_recompile_budget(
        lambda: step(g), steps=3, budget=0,
        label="warm sharded bulyan diag kernel") == 0
    with contracts.no_implicit_transfers():
        jax.block_until_ready(step(g))


def test_process_scope_transfer_guard_covers_threads():
    """`no_implicit_transfers(scope="process")` guards OTHER threads (the
    serve flusher/resolver discipline) and restores the previous config."""
    import threading

    before = jax.config.jax_transfer_guard
    f = jax.jit(lambda x: x * 2)
    f(jnp.zeros(()))  # warm (compilation is not a transfer)
    caught = []

    def worker():
        try:
            f(3.0)  # implicit host->device transfer on another thread
        except Exception as err:  # bmt: noqa[BMT-E05] the probe wants whatever the guard raises
            caught.append(err)

    with contracts.no_implicit_transfers(scope="process"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert caught, "the process-scope guard missed a cross-thread transfer"
    assert jax.config.jax_transfer_guard == before
    jax.block_until_ready(f(3.0))  # guard is gone


# --------------------------------------------------------------------------- #
# Stale-golden pruning

def test_bless_prunes_stale_cells(tmp_path, monkeypatch):
    """Keys the enumerator no longer produces disappear on re-bless, and
    the gate names them as `removed` before the re-bless."""
    monkeypatch.setattr(lattice, "CELL_GARS", ("average",))
    monkeypatch.setattr(lattice, "MESH_AXES", ())
    monkeypatch.setattr(lattice, "SERVE_CELLS", ())
    path = tmp_path / "g.json"
    lowering.bless(path)
    data = json.loads(path.read_text())
    data["cells"]["retired/stale"] = "0" * 64
    path.write_text(json.dumps(data))
    report = lowering.check(path)
    assert report["status"] == "drift"
    assert report["removed"] == ["retired/stale"]
    lowering.bless(path)
    assert "retired/stale" not in json.loads(path.read_text())["cells"]
