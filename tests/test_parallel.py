"""Multi-chip sharding tests on the virtual 8-device CPU mesh (conftest sets
`--xla_force_host_platform_device_count=8`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byzantinemomentum_tpu import losses, models, ops
from byzantinemomentum_tpu.engine import EngineConfig, build_engine
from byzantinemomentum_tpu.ops._common import pairwise_distances
from byzantinemomentum_tpu.parallel import (
    make_mesh, pairwise_distances_sharded, shard_gar, sharded_train_step)


import os

from byzantinemomentum_tpu.cli.attack import main as attack_main


@pytest.fixture(autouse=True)
def small_synth(monkeypatch):
    monkeypatch.setenv("BMT_SYNTH_TRAIN", "512")
    monkeypatch.setenv("BMT_SYNTH_TEST", "128")


@pytest.fixture(scope="module")
def mesh2d():
    return make_mesh(8, model_parallel=2)


@pytest.fixture(scope="module")
def mesh1d():
    return make_mesh(8, model_parallel=8)


def test_make_mesh_shapes():
    m = make_mesh(8, model_parallel=2)
    assert m.devices.shape == (4, 2)
    assert m.axis_names == ("workers", "model")
    with pytest.raises(ValueError):
        make_mesh(8, model_parallel=3)
    with pytest.raises(ValueError):
        make_mesh(999)


def test_pairwise_distances_sharded_matches_local(mesh1d):
    rng = np.random.default_rng(0)
    g = jnp.asarray(rng.normal(size=(6, 64)).astype(np.float32))
    expected = pairwise_distances(g)
    got = pairwise_distances_sharded(g, mesh1d)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("name,kwargs", [
    ("median", {}), ("trmean", {}), ("phocas", {}), ("meamed", {}),
    ("average", {}),
    pytest.param("krum", {}, marks=pytest.mark.slow),
    pytest.param("bulyan", {}, marks=pytest.mark.slow),
    pytest.param("brute", {}, marks=pytest.mark.slow),
])
def test_shard_gar_matches_single_device(mesh1d, name, kwargs):
    rng = np.random.default_rng(1)
    n, f, d = 11, 2, 96  # d divisible by 8 shards; bulyan needs n >= 4f+3
    g = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    gar = ops.gars[name]
    expected = gar.unchecked(g, f=f, **kwargs)
    sharded = shard_gar(gar, mesh1d, f=f, **kwargs)
    got = sharded(g)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name", [
    "median",
    pytest.param("krum", marks=pytest.mark.slow),
    pytest.param("bulyan", marks=pytest.mark.slow),
    pytest.param("brute", marks=pytest.mark.slow)])
def test_shard_gar_nan_rows_match_single_device(mesh1d, name):
    """f NaN rows: the d-sharded kernels reproduce the single-device result
    (the psum'd distances carry the +inf convention across shards)."""
    rng = np.random.default_rng(4)
    n, f, d = 11, 2, 96
    g = rng.normal(size=(n, d)).astype(np.float32)
    g[-f:] = np.nan
    g = jnp.asarray(g)
    gar = ops.gars[name]
    expected = gar.unchecked(g, f=f)
    got = shard_gar(gar, mesh1d, f=f)(g)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name", [
    "median", "trmean", pytest.param("bulyan", marks=pytest.mark.slow)])
def test_shard_gar_pallas_engaged_matches(mesh1d, name, monkeypatch):
    """With `BMT_PALLAS_INTERPRET=1` the shard-local bodies run the REAL
    Pallas sorting-network kernels (interpret mode off-TPU) inside
    `shard_map` — `pallas_sort.allowed()` must re-enable them even while the
    surrounding trace holds `disabled()` — and match the jnp result."""
    from byzantinemomentum_tpu.ops import pallas_sort
    rng = np.random.default_rng(5)
    n, f, d = 11, 2, 96
    g = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    gar = ops.gars[name]
    expected = gar.unchecked(g, f=f)  # jnp path (no env var yet)
    monkeypatch.setenv("BMT_PALLAS_INTERPRET", "1")
    with pallas_sort.disabled():  # what the sharded step trace holds
        got = shard_gar(gar, mesh1d, f=f)(g)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_shard_gar_pads_indivisible_d(mesh1d):
    """The engine-facing facade pads d up to the model-axis size and slices
    back — results match on a d that does NOT divide the 8 shards."""
    from byzantinemomentum_tpu.parallel.sharded import _ShardedGar
    rng = np.random.default_rng(6)
    n, f, d = 11, 2, 83  # prime-ish, not divisible by 8
    g = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    for name in ("median", "krum", "bulyan"):
        gar = ops.gars[name]
        facade = _ShardedGar(gar, shard_gar(gar, mesh1d, f=f), 8)
        got = facade.unchecked(g, f=f)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(gar.unchecked(g, f=f)),
            rtol=1e-4, atol=1e-5, err_msg=name)


def test_sharded_train_step_executes(mesh2d):
    cfg = EngineConfig(nb_workers=8, nb_decl_byz=1, nb_real_byz=0,
                       nb_for_study=8, nb_for_study_past=2,
                       momentum=0.9, momentum_at="update")
    engine = build_engine(
        cfg=cfg, model_def=models.build("simples-full"),
        loss=losses.Loss("nll"), criterion=losses.Criterion("top-k"),
        defenses=[(ops.gars["median"], 1.0, {})])
    state = engine.init(jax.random.PRNGKey(0))
    step = sharded_train_step(engine, mesh2d, state)
    rng = np.random.default_rng(2)
    xs = jnp.asarray(rng.normal(size=(8, 4, 28, 28, 1)).astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 10, size=(8, 4)).astype(np.int32))
    state, metrics = step(state, xs, ys, jnp.float32(0.1))
    assert int(state.steps) == 1
    assert np.isfinite(float(metrics["Defense gradient norm"]))


def test_sharded_step_matches_unsharded():
    """The sharded program must compute the same step as the single-device
    one (same state in, same state out, modulo f32 reduction order)."""
    cfg = EngineConfig(nb_workers=8, nb_decl_byz=1, nb_real_byz=0,
                       nb_for_study=0, momentum=0.9, momentum_at="update")
    engine = build_engine(
        cfg=cfg, model_def=models.build("simples-full"),
        loss=losses.Loss("nll"), criterion=losses.Criterion("top-k"),
        defenses=[(ops.gars["trmean"], 1.0, {})])
    rng = np.random.default_rng(3)
    xs = jnp.asarray(rng.normal(size=(8, 4, 28, 28, 1)).astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 10, size=(8, 4)).astype(np.int32))

    s1 = engine.init(jax.random.PRNGKey(5))
    s1, _ = engine.train_step(s1, xs, ys, jnp.float32(0.1))

    mesh = make_mesh(8, model_parallel=2)
    s2 = engine.init(jax.random.PRNGKey(5))
    step = sharded_train_step(engine, mesh, s2)
    s2, _ = step(s2, xs, ys, jnp.float32(0.1))

    np.testing.assert_allclose(np.asarray(s1.theta), np.asarray(s2.theta),
                               rtol=1e-4, atol=1e-6)


def test_sharded_step_matches_unsharded_bulyan():
    """The explicit distributed bulyan kernel inside the sharded step (the
    headline GAR) matches the single-device trajectory."""
    cfg = EngineConfig(nb_workers=12, nb_decl_byz=2, nb_real_byz=0,
                       nb_for_study=0, momentum=0.9, momentum_at="update")
    engine = build_engine(
        cfg=cfg, model_def=models.build("simples-full"),
        loss=losses.Loss("nll"), criterion=losses.Criterion("top-k"),
        defenses=[(ops.gars["bulyan"], 1.0, {})])
    rng = np.random.default_rng(8)
    xs = jnp.asarray(rng.normal(size=(12, 4, 28, 28, 1)).astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 10, size=(12, 4)).astype(np.int32))

    s1 = engine.init(jax.random.PRNGKey(5))
    s1, _ = engine.train_step(s1, xs, ys, jnp.float32(0.1))

    mesh = make_mesh(8, model_parallel=2)
    s2 = engine.init(jax.random.PRNGKey(5))
    step = sharded_train_step(engine, mesh, s2)
    s2, _ = step(s2, xs, ys, jnp.float32(0.1))

    np.testing.assert_allclose(np.asarray(s1.theta), np.asarray(s2.theta),
                               rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_sharded_step_grouped_cnn_matches_unsharded():
    """The shard-mapped grouped honest phase (`grouped_sharded`): empire-cnn
    (grouped convs + per-worker BN batch stats + per-worker dropout keys)
    under a (4, 2) mesh reproduces the single-device grouped trajectory."""
    cfg = EngineConfig(nb_workers=8, nb_decl_byz=1, nb_real_byz=0,
                       nb_for_study=0, momentum=0.9, momentum_at="update",
                       gradient_clip=2.0)
    engine = build_engine(
        cfg=cfg, model_def=models.build("empire-cnn"),
        loss=losses.Loss("nll"), criterion=losses.Criterion("top-k"),
        defenses=[(ops.gars["median"], 1.0, {})])
    assert engine.model_def.apply_grouped is not None
    rng = np.random.default_rng(11)
    xs = jnp.asarray(rng.normal(size=(8, 3, 32, 32, 3)).astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 10, size=(8, 3)).astype(np.int32))

    s1 = engine.init(jax.random.PRNGKey(5))
    s1, _ = engine.train_step(s1, xs, ys, jnp.float32(0.05))

    mesh = make_mesh(8, model_parallel=2)
    s2 = engine.init(jax.random.PRNGKey(5))
    step = sharded_train_step(engine, mesh, s2)
    s2, _ = step(s2, xs, ys, jnp.float32(0.05))

    np.testing.assert_allclose(np.asarray(s1.theta), np.asarray(s2.theta),
                               rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree.leaves(s1.net_state),
                    jax.tree.leaves(s2.net_state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_sharded_step_grouped_worker_nesterov_matches_unsharded():
    """Worker-placement momentum with Nesterov lookahead builds a genuinely
    per-worker parameter stack (theta_axis=0); the shard-mapped grouped
    phase must reshard and reproduce the single-device trajectory."""
    cfg = EngineConfig(nb_workers=8, nb_decl_byz=1, nb_real_byz=0,
                       nb_for_study=0, momentum=0.9, momentum_at="worker",
                       nesterov=True, gradient_clip=2.0)
    engine = build_engine(
        cfg=cfg, model_def=models.build("simples-full"),
        loss=losses.Loss("nll"), criterion=losses.Criterion("top-k"),
        defenses=[(ops.gars["trmean"], 1.0, {})])
    rng = np.random.default_rng(13)
    xs = jnp.asarray(rng.normal(size=(8, 4, 28, 28, 1)).astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 10, size=(8, 4)).astype(np.int32))

    s1 = engine.init(jax.random.PRNGKey(5))
    for _ in range(2):
        s1, _ = engine.train_step(s1, xs, ys, jnp.float32(0.1))

    mesh = make_mesh(8, model_parallel=2)
    s2 = engine.init(jax.random.PRNGKey(5))
    step = sharded_train_step(engine, mesh, s2)
    for _ in range(2):
        s2, _ = step(s2, xs, ys, jnp.float32(0.1))

    np.testing.assert_allclose(np.asarray(s1.theta), np.asarray(s2.theta),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(s1.momentum_workers),
                               np.asarray(s2.momentum_workers),
                               rtol=1e-4, atol=1e-6)


def test_sharded_eval_matches_unsharded(mesh2d):
    """`sharded_eval_many` (batches sharded along "workers", theta d-sharded)
    returns exactly the unsharded criterion sums."""
    from byzantinemomentum_tpu.parallel import sharded_eval_many
    cfg = EngineConfig(nb_workers=8, nb_decl_byz=1, nb_real_byz=0,
                       nb_for_study=0)
    engine = build_engine(
        cfg=cfg, model_def=models.build("simples-full"),
        loss=losses.Loss("nll"), criterion=losses.Criterion("top-k"),
        defenses=[(ops.gars["median"], 1.0, {})])
    state = engine.init(jax.random.PRNGKey(1))
    rng = np.random.default_rng(9)
    xs = jnp.asarray(rng.normal(size=(3, 16, 28, 28, 1)).astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 10, size=(3, 16)).astype(np.int32))
    want = np.asarray(engine.eval_many(state.theta, state.net_state, xs, ys))
    got = np.asarray(sharded_eval_many(engine, mesh2d, state)(
        state.theta, state.net_state, xs, ys))
    np.testing.assert_allclose(got, want)


def test_cli_mesh_indivisible_test_batch_falls_back(tmp_path):
    """`--batch-size-test` not dividing the worker axis must not crash the
    run at the first milestone — eval falls back to the replicated program
    (the train-side divisibility check does not cover the eval batch)."""
    resdir = tmp_path / "m"
    rc = attack_main(["--nb-steps", "2", "--batch-size", "8",
               "--batch-size-test", "100", "--batch-size-test-reps", "1",
               "--evaluation-delta", "2", "--model", "simples-full",
               "--seed", "3", "--gar", "median", "--nb-workers", "8",
               "--nb-decl-byz", "2", "--mesh", "4x2", "--nb-for-study", "8",
               "--result-directory", str(resdir)])
    assert rc == 0
    assert (resdir / "eval").is_file()


@pytest.mark.slow
def test_graft_entry_and_dryrun():
    import __graft_entry__ as graft
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 10)
    graft.dryrun_multichip(8)


@pytest.mark.slow
def test_cli_mesh_flag_matches_unsharded(tmp_path):
    """`--mesh 4x2` runs the driver's sharded path on the virtual 8-device
    mesh; the trajectory matches the unsharded run up to collective
    reduction-order rounding."""
    base = ["--nb-steps", "3", "--batch-size", "8", "--batch-size-test", "32",
            "--batch-size-test-reps", "1", "--evaluation-delta", "3",
            "--model", "simples-full", "--seed", "9", "--gar", "krum",
            "--attack", "empire", "--attack-args", "factor:1.1",
            "--nb-workers", "11", "--nb-decl-byz", "3", "--nb-real-byz", "3",
            "--nb-for-study", "8", "--nb-for-study-past", "2"]
    rows = {}
    for name, extra in (("plain", []), ("mesh", ["--mesh", "4x2"])):
        resdir = tmp_path / name
        rc = attack_main(base + extra + ["--result-directory", str(resdir)])
        assert rc == 0
        lines = (resdir / "study").read_text().split(os.linesep)
        rows[name] = [l.split("\t") for l in lines[1:] if l]
    assert len(rows["mesh"]) == len(rows["plain"]) == 3
    for rp, rm in zip(rows["plain"], rows["mesh"]):
        assert rp[0] == rm[0]
        a = np.array([float(x) for x in rp[2:]])
        b = np.array([float(x) for x in rm[2:]])
        np.testing.assert_allclose(b, a, rtol=2e-3, atol=1e-5)


def test_cli_mesh_flag_rejects_indivisible():
    from byzantinemomentum_tpu import utils
    with pytest.raises(utils.UserException, match="divide evenly"):
        attack_main(["--nb-steps", "1", "--model", "simples-full",
              "--nb-workers", "11", "--mesh", "4"])


def test_cli_mesh_flag_rejects_nonpositive():
    from byzantinemomentum_tpu import utils
    for spec in ("0", "-4", "2x0"):
        with pytest.raises(utils.UserException, match="Invalid '--mesh"):
            attack_main(["--nb-steps", "1", "--model", "simples-full",
                  "--nb-workers", "8", "--mesh", spec])


def test_cli_mesh_with_coordinatewise_gar(tmp_path):
    """Coordinate-wise GARs under --mesh run as shard-local `shard_gar`
    kernels (Pallas-capable on TPU; jnp bodies on the CPU test mesh); the
    run must complete."""
    resdir = tmp_path / "m"
    rc = attack_main(["--nb-steps", "2", "--batch-size", "8",
               "--batch-size-test", "32", "--batch-size-test-reps", "1",
               "--evaluation-delta", "2", "--model", "simples-full",
               "--seed", "3", "--gar", "median", "--nb-workers", "8",
               "--nb-decl-byz", "2", "--mesh", "4x2", "--nb-for-study", "8",
               "--result-directory", str(resdir)])
    assert rc == 0
    assert (resdir / "eval").is_file()


def test_pallas_disabled_context():
    from byzantinemomentum_tpu.ops import pallas_sort
    import jax.numpy as jnp
    g = jnp.zeros((8, 64), jnp.float32)
    assert pallas_sort.supported(g, interpret=True)
    with pallas_sort.disabled():
        assert not pallas_sort.supported(g, interpret=True)
    assert pallas_sort.supported(g, interpret=True)


@pytest.mark.slow
def test_cli_mesh_checkpoint_resume(tmp_path):
    """Checkpoint + resume through the sharded path: sharded device arrays
    serialize (gather on save) and the resumed mesh run continues exactly
    (study rows AND evaluations - the test-sampler snapshot is the fragile
    part)."""
    base = ["--batch-size", "8", "--batch-size-test", "32",
            "--batch-size-test-reps", "1", "--evaluation-delta", "2",
            "--model", "simples-full", "--seed", "13", "--gar", "krum",
            "--nb-workers", "11", "--nb-decl-byz", "3", "--nb-real-byz", "3",
            "--nb-for-study", "8", "--nb-for-study-past", "2",
            "--mesh", "4x2"]
    full = tmp_path / "full"
    assert attack_main(base + ["--nb-steps", "4",
                               "--result-directory", str(full)]) == 0
    part = tmp_path / "part"
    assert attack_main(base + ["--nb-steps", "2", "--checkpoint-delta", "2",
                               "--result-directory", str(part)]) == 0
    resumed = tmp_path / "resumed"
    assert attack_main(base + ["--nb-steps", "2",
                               "--load-checkpoint", str(part / "checkpoint-2"),
                               "--result-directory", str(resumed)]) == 0
    full_rows = [l for l in (full / "study").read_text().split(os.linesep)[1:] if l]
    res_rows = [l for l in (resumed / "study").read_text().split(os.linesep)[1:] if l]
    assert res_rows == [r for r in full_rows if int(r.split("\t")[0]) >= 2]
    full_eval = [l for l in (full / "eval").read_text().split(os.linesep)[1:] if l]
    res_eval = [l for l in (resumed / "eval").read_text().split(os.linesep)[1:] if l]
    assert res_eval == [r for r in full_eval if int(r.split("\t")[0]) >= 2]
