"""Fault-injection subsystem tests (`byzantinemomentum_tpu/faults/`).

Layers under test:
* plan — JSON round-trip, validation, seeded deterministic generation;
* schedule — event lowering, horizon clamp, device-loss persistence;
* quorum — masked dynamic-(n, f) aggregation differentially checked
  against the static kernels on the compacted active subset;
* engine — injection exactness (straggler/duplicate/corruption) on the
  linear probe model, dynamic quorum under drops, NaN-quarantine keeping
  the step finite (and `average` without it visibly diverging), empty-plan
  zero-overhead contract;
* driver — `--fault-plan` end-to-end through `cli/attack.py`, with the
  `Faults injected` / `Workers active` / `Quorum f` study columns.
"""

import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from byzantinemomentum_tpu import faults, losses, ops
from byzantinemomentum_tpu.engine import (
    EngineConfig, FAULT_COLUMNS, STUDY_COLUMNS, build_engine)
from byzantinemomentum_tpu.models import ModelDef

D = 6


# --------------------------------------------------------------------------- #
# Plan: declaration, JSON, determinism


def sample_plan():
    return faults.FaultPlan(events=(
        faults.straggler(0, step=2, delay_steps=3),
        faults.drop_worker(2, step=1, duration=2),
        faults.corrupt_gradient(4, step=1, mode="scale", scale=0.25),
        faults.corrupt_gradient(5, step=3, mode="nan"),
        faults.duplicate_submission(1, step=0, source=3),
        faults.device_loss(6, step=4),
    ), policy=faults.FaultPolicy(nan_quarantine=True, fetch_attempts=2),
        seed=17)


def test_plan_json_round_trip(tmp_path):
    plan = sample_plan()
    again = faults.FaultPlan.from_json(plan.to_json())
    assert again == plan
    path = plan.save(tmp_path / "plan.json")
    assert faults.FaultPlan.load(path) == plan
    # The JSON is plain data (hand-editable): a dict per event
    raw = json.loads(path.read_text())
    assert {e["kind"] for e in raw["events"]} == {
        "straggler", "drop_worker", "corrupt_gradient",
        "duplicate_submission", "device_loss"}
    assert raw["policy"]["nan_quarantine"] is True


def test_plan_validation_contracts():
    with pytest.raises(ValueError, match="Unknown fault kind"):
        faults.FaultEvent("meteor_strike", 0, 0)
    with pytest.raises(ValueError, match="duration"):
        faults.drop_worker(0, step=0, duration=0)
    with pytest.raises(ValueError, match="Unknown fault-plan fields"):
        faults.FaultPlan.from_dict({"event": []})
    plan = faults.FaultPlan(events=(faults.drop_worker(10, step=0),))
    assert plan.validate(11, 11) is None
    assert "only 8 workers" in plan.validate(8, 8)
    # Mutating faults cannot target attack-synthesized rows
    plan = faults.FaultPlan(events=(faults.corrupt_gradient(9, step=0),))
    assert "attack-synthesized" in plan.validate(11, 8)
    plan = faults.FaultPlan(
        events=(faults.duplicate_submission(1, step=0, source=1),))
    assert "copies itself" in plan.validate(4, 4)


def test_plan_generation_is_seed_deterministic():
    kw = dict(nb_workers=11, nb_steps=50,
              rates={"drop_worker": 0.02, "corrupt_gradient": 0.01,
                     "straggler": 0.01})
    a = faults.FaultPlan.generate(seed=5, **kw)
    b = faults.FaultPlan.generate(seed=5, **kw)
    c = faults.FaultPlan.generate(seed=6, **kw)
    assert a.to_json() == b.to_json()
    assert a.to_json() != c.to_json()
    assert len(a.events) > 0
    assert a.validate(11, 11) is None


# --------------------------------------------------------------------------- #
# Schedule: event lowering and in-graph lookup


def test_schedule_masks_and_horizon():
    sched = faults.build_schedule(sample_plan(), nb_workers=8, nb_honests=8)
    # Same plan -> identical compiled masks (the determinism contract)
    again = faults.build_schedule(sample_plan(), nb_workers=8, nb_honests=8)
    for name in ("stale", "nan", "zero", "scale", "dup", "drop",
                 "lost_from"):
        np.testing.assert_array_equal(getattr(sched, name),
                                      getattr(again, name))
    sf = sched.step_faults(jnp.int32(1))
    assert bool(sf.drop[2]) and not bool(sf.drop[3])
    assert float(sf.scale[4]) == 0.25
    sf = sched.step_faults(jnp.int32(3))
    assert bool(sf.nan[5]) and not bool(sf.drop[2])  # drop window over
    # Beyond the horizon: everything neutral EXCEPT the permanent loss
    sf = sched.step_faults(jnp.int32(1000))
    assert bool(sf.drop[6])
    assert not bool(jnp.any(sf.stale)) and not bool(jnp.any(sf.nan))
    assert float(jnp.sum(sf.drop)) == 1.0


def test_empty_plan_compiles_to_none():
    assert faults.build_schedule(faults.FaultPlan(), nb_workers=4,
                                 nb_honests=4) is None
    assert faults.build_schedule(None, nb_workers=4, nb_honests=4) is None


# --------------------------------------------------------------------------- #
# Quorum: masked dynamic-(n, f) kernels vs static kernels on the compacted
# active subset


def test_masked_aggregation_matches_static_compaction():
    from byzantinemomentum_tpu.faults import quorum

    rng = np.random.default_rng(3)
    n, f_decl = 11, 4
    G = jnp.asarray(rng.normal(size=(n, 16)).astype(np.float32))
    active_np = np.ones(n, bool)
    active_np[[2, 5, 7]] = False  # 3 absent -> n_eff = 8
    active = jnp.asarray(active_np)
    compact = G[active_np]

    cases = {
        # gar name -> (effective f at n_eff = 8, oracle on the compacted
        # stack; median/average ignore f)
        "average": (3, lambda g, f: jnp.mean(g, axis=0)),      # (8-1)//2
        "median": (3, lambda g, f: ops._common.lower_median(g)),
        "krum": (2, lambda g, f: ops.krum.aggregate(g, f)),    # (8-3)//2
        "trmean": (3, lambda g, f: ops.trmean.trmean(g, f)),   # (8-1)//2
        # The r10 traced-count kernels: every remaining first-tier rule
        "bulyan": (1, lambda g, f: ops.bulyan.aggregate(g, f)),  # (8-3)//4
        "phocas": (3, lambda g, f: ops.trmean.aggregate_phocas(g, f)),
        "meamed": (3, lambda g, f: ops.trmean.aggregate_meamed(g, f)),
        "aksel": (3, lambda g, f: ops.aksel.aggregate(g, f)),
        "cge": (3, lambda g, f: ops.cge.aggregate(g, f)),
        "brute": (3, lambda g, f: ops.brute.aggregate(g, f)),
    }
    for name, (f_eff, oracle) in cases.items():
        got, f_used = quorum.masked_aggregate(
            ops.gars[name], G, active, f_decl=f_decl)
        want = oracle(compact, f_eff)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=f"masked {name}")
        assert int(f_used) == f_eff, name


def test_masked_krum_never_selects_inactive_or_nan_rows():
    from byzantinemomentum_tpu.faults import quorum, sanitize

    rng = np.random.default_rng(4)
    n = 11
    G = rng.normal(size=(n, 8)).astype(np.float32)
    G[3] = np.nan                      # corrupt but "present"
    G[6] += 1000.0                     # outlier, present and finite
    active = np.ones(n, bool)
    active[[0, 9]] = False             # dropped
    act, quarantined = sanitize.quarantine(
        jnp.asarray(G), jnp.asarray(active))
    assert int(quarantined) == 1
    got, f_used = quorum.masked_aggregate(
        ops.gars["krum"], jnp.asarray(G), act, f_decl=4)
    assert bool(jnp.all(jnp.isfinite(got)))
    # n_eff = 8 -> f_eff = 2, m = 8 - 2 - 2 = 4: the far outlier is never
    # among the 4 selected, so the aggregate stays near the inlier mean
    inliers = np.delete(G, [0, 3, 6, 9], axis=0)
    assert np.linalg.norm(np.asarray(got) - inliers.mean(0)) \
        < np.linalg.norm(np.asarray(got) - G[6])


# --------------------------------------------------------------------------- #
# Engine integration (linear probe model: per-worker gradient == the mean
# of its batch rows, same technique as tests/test_engine.py)


def probe_model():
    def init(key):
        return {"w": jnp.zeros((D,), jnp.float32)}, {}

    def apply(params, state, x, train=False, rng=None):
        return x, state

    return ModelDef("probe", init, apply, (D,))


def probe_loss():
    return losses.Loss(lambda output, target, params:
                       jnp.dot(params, jnp.mean(output, axis=0)))


def make_engine(plan=None, gar="average", n=5, f=1, **cfg_kwargs):
    cfg_kwargs.setdefault("nb_workers", n)
    cfg_kwargs.setdefault("nb_decl_byz", f)
    cfg_kwargs.setdefault("nb_for_study", cfg_kwargs["nb_workers"])
    cfg = EngineConfig(**cfg_kwargs)
    sched = faults.build_schedule(
        plan, nb_workers=cfg.nb_workers, nb_honests=cfg.nb_honests)
    engine = build_engine(
        cfg=cfg, model_def=probe_model(), loss=probe_loss(),
        criterion=losses.Criterion("sigmoid"),
        defenses=[(ops.gars[gar], 1.0, {})], faults=sched)
    return cfg, engine


def run_steps(engine, grads, lr=0.1):
    """grads: [steps][n workers] of per-worker gradient vectors; returns
    (thetas after each step, metrics of each step)."""
    state = engine.init(jax.random.PRNGKey(0),
                        params={"w": jnp.zeros((D,))}, net_state={})
    thetas, all_metrics = [], []
    for step_grads in grads:
        xs = jnp.asarray(np.stack(step_grads)[:, None, :])  # batch of 1 row
        ys = jnp.zeros(xs.shape[:2], jnp.float32)
        state, metrics = engine.train_step(state, xs, ys, jnp.float32(lr))
        thetas.append(np.asarray(state.theta))
        all_metrics.append(metrics)
    return thetas, all_metrics


def test_straggler_replays_prewindow_gradient():
    rng = np.random.default_rng(0)
    grads = rng.normal(size=(4, 5, D)).astype(np.float32)
    plan = faults.FaultPlan(
        events=(faults.straggler(0, step=1, delay_steps=2),))
    _, engine = make_engine(plan, momentum=0.0)
    thetas, metrics = run_steps(engine, grads)
    # Steps 1 and 2: worker 0 submits its step-0 gradient; step 3 is fresh
    submitted = grads.copy()
    submitted[1, 0] = grads[0, 0]
    submitted[2, 0] = grads[0, 0]
    theta = np.zeros(D, np.float32)
    for t in range(4):
        theta = theta - 0.1 * submitted[t].mean(0)
        np.testing.assert_allclose(thetas[t], theta, rtol=1e-5, atol=1e-6)
    assert [int(m["Faults injected"]) for m in metrics] == [0, 1, 1, 0]


def test_duplicate_and_scale_corruption_exact():
    rng = np.random.default_rng(1)
    grads = rng.normal(size=(2, 5, D)).astype(np.float32)
    plan = faults.FaultPlan(events=(
        faults.duplicate_submission(1, step=1, source=3),
        faults.corrupt_gradient(4, step=1, mode="scale", scale=0.5),
    ))
    _, engine = make_engine(plan, momentum=0.0)
    thetas, metrics = run_steps(engine, grads)
    submitted = grads.copy()
    submitted[1, 1] = grads[1, 3]
    submitted[1, 4] *= 0.5
    theta = -0.1 * submitted[0].mean(0)
    np.testing.assert_allclose(thetas[0], theta, rtol=1e-5, atol=1e-6)
    theta = theta - 0.1 * submitted[1].mean(0)
    np.testing.assert_allclose(thetas[1], theta, rtol=1e-5, atol=1e-6)
    assert int(metrics[1]["Faults injected"]) == 2


def test_drop_worker_shrinks_quorum_for_krum_and_median():
    rng = np.random.default_rng(2)
    n = 11
    grads = rng.normal(size=(3, n, D)).astype(np.float32)
    plan = faults.FaultPlan(events=(
        faults.drop_worker(2, step=1),
        faults.drop_worker(8, step=1),
        faults.corrupt_gradient(5, step=1, mode="nan"),
    ))
    for gar, f_eff_faulted in (("krum", 2), ("median", 3)):
        _, engine = make_engine(plan, gar=gar, n=n, f=4)
        thetas, metrics = run_steps(engine, grads)
        assert all(np.isfinite(t).all() for t in thetas), gar
        assert int(metrics[0]["Workers active"]) == n
        assert int(metrics[0]["Quorum f"]) == 4
        # Step 1: 2 dropped + 1 quarantined -> n_eff = 8, f re-clamped
        assert int(metrics[1]["Workers active"]) == 8, gar
        assert int(metrics[1]["Quorum f"]) == f_eff_faulted, gar
        assert int(metrics[1]["Faults injected"]) == 3
        assert int(metrics[2]["Workers active"]) == n


def test_nan_quarantine_keeps_average_finite_and_its_absence_diverges():
    rng = np.random.default_rng(5)
    grads = rng.normal(size=(3, 5, D)).astype(np.float32)
    plan = faults.FaultPlan(
        events=(faults.corrupt_gradient(1, step=1, mode="nan"),))
    _, engine = make_engine(plan, momentum=0.0, fault_quarantine=True)
    thetas, metrics = run_steps(engine, grads)
    assert np.isfinite(thetas[-1]).all()
    assert int(metrics[1]["Workers active"]) == 4  # quarantined out
    # Quarantine is also exact: the step-1 update is the clean-row mean
    expect = -0.1 * (grads[0].mean(0) + np.delete(grads[1], 1, 0).mean(0))
    np.testing.assert_allclose(thetas[1], expect, rtol=1e-5, atol=1e-6)
    # Without quarantine the NaN row poisons the average permanently
    _, engine = make_engine(plan, momentum=0.0, fault_quarantine=False)
    thetas, metrics = run_steps(engine, grads)
    assert np.isnan(thetas[1]).all() and np.isnan(thetas[2]).all()
    assert int(metrics[1]["Workers active"]) == 5  # nobody masked


def test_faulted_run_is_deterministic():
    rng = np.random.default_rng(6)
    grads = rng.normal(size=(4, 11, D)).astype(np.float32)
    plan = faults.FaultPlan.generate(
        nb_workers=11, nb_steps=4, seed=9,
        rates={"drop_worker": 0.1, "corrupt_gradient": 0.1,
               "straggler": 0.1})
    runs = []
    for _ in range(2):
        _, engine = make_engine(plan, gar="krum", n=11, f=4)
        thetas, _ = run_steps(engine, grads)
        runs.append(np.stack(thetas))
    np.testing.assert_array_equal(runs[0], runs[1])


def test_fault_free_engine_state_has_no_buffer_and_same_trajectory():
    """The zero-overhead contract: no plan (or an empty one) means no
    fault state and the exact fault-free trajectory; a plan without
    stragglers carries no stale buffer either."""
    rng = np.random.default_rng(7)
    grads = rng.normal(size=(2, 5, D)).astype(np.float32)
    _, plain = make_engine(None)
    state = plain.init(jax.random.PRNGKey(0),
                       params={"w": jnp.zeros((D,))}, net_state={})
    assert state.fault_buffer.shape == (0, D)
    base, _ = run_steps(plain, grads)
    # Plan whose only event lies in the future AND needs no buffer: no
    # stale state, and the pre-fault trajectory matches the plain engine
    # to rounding (the masked-mean kernel may associate differently from
    # jnp.mean; bitwise identity is only claimed for EMPTY plans, whose
    # schedule is None and whose program is literally the plain one)
    plan = faults.FaultPlan(events=(faults.drop_worker(0, step=50),))
    _, faulted = make_engine(plan)
    fstate = faulted.init(jax.random.PRNGKey(0),
                          params={"w": jnp.zeros((D,))}, net_state={})
    assert fstate.fault_buffer.shape == (0, D)
    got, _ = run_steps(faulted, grads)
    np.testing.assert_allclose(np.stack(base), np.stack(got),
                               rtol=1e-6, atol=1e-8)


def test_checkpoint_without_fault_buffer_loads_with_cold_buffer(tmp_path):
    """Pre-faults checkpoints lack the `fault_buffer` field; they must
    load against a faults-era template with the buffer cold-started."""
    from flax import serialization

    from byzantinemomentum_tpu import checkpoint

    _, engine = make_engine(
        faults.FaultPlan(events=(faults.straggler(0, step=1),)))
    state = engine.init(jax.random.PRNGKey(0),
                        params={"w": jnp.zeros((D,))}, net_state={})
    assert state.fault_buffer.shape[0] > 0
    path = checkpoint.save(tmp_path / "ckpt", state)
    data = path.read_bytes()
    if data[-8:-4] == checkpoint.MAGIC:  # strip the PR 2 integrity footer
        data = data[:-8]
    raw = serialization.msgpack_restore(data)
    del raw["state"]["fault_buffer"]  # what an old checkpoint looks like
    path.write_bytes(serialization.msgpack_serialize(raw))  # footer-less too
    loaded = checkpoint.load(path, state)
    np.testing.assert_array_equal(np.asarray(loaded.theta),
                                  np.asarray(state.theta))
    assert loaded.fault_buffer.shape == state.fault_buffer.shape
    np.testing.assert_array_equal(np.asarray(loaded.fault_buffer), 0.0)


# --------------------------------------------------------------------------- #
# Ring-attention peer loss (`parallel/ring.py:drop_blocks`)


def test_ring_attention_survives_dropped_peer_blocks():
    from jax.sharding import Mesh, PartitionSpec as P

    from byzantinemomentum_tpu.parallel import dense_attention, ring_attention
    from byzantinemomentum_tpu.parallel.mesh import shard_map

    p = 8
    b, h, L, dh = 2, 4, 32, 4
    lc = L // p
    rng = np.random.default_rng(8)
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, L, dh)).astype(np.float32))
               for _ in range(3))
    lost = np.zeros(p, bool)
    lost[[2, 5]] = True
    key_mask = jnp.asarray(~np.repeat(lost, lc))
    mesh = Mesh(np.asarray(jax.devices()[:p]), ("seq",))
    for causal in (False, True):
        want = dense_attention(q, k, v, causal=causal, key_mask=key_mask)
        fn = shard_map(
            lambda q, k, v: ring_attention(
                q, k, v, "seq", causal=causal,
                drop_blocks=jnp.asarray(lost)),
            mesh=mesh, in_specs=(P(None, None, "seq"),) * 3,
            out_specs=P(None, None, "seq"))
        got = jax.jit(fn)(q, k, v)
        # Causal queries inside a lost block still see their own positions
        # in the dense oracle; compare only queries on surviving chips
        alive_rows = np.repeat(~lost, lc)
        np.testing.assert_allclose(
            np.asarray(got)[:, :, alive_rows],
            np.asarray(want)[:, :, alive_rows], rtol=2e-5, atol=2e-6)


# --------------------------------------------------------------------------- #
# Driver end-to-end (`--fault-plan` through cli/attack.py)


@pytest.fixture
def small_synth(monkeypatch):
    monkeypatch.setenv("BMT_SYNTH_TRAIN", "512")
    monkeypatch.setenv("BMT_SYNTH_TEST", "128")


CLI_BASE = ["--nb-steps", "4", "--batch-size", "8", "--batch-size-test",
            "32", "--batch-size-test-reps", "1", "--evaluation-delta", "0",
            "--model", "simples-full", "--seed", "11", "--nb-workers", "11",
            "--nb-decl-byz", "4", "--nb-for-study", "11",
            "--nb-for-study-past", "2"]

DEMO_PLAN = faults.FaultPlan(events=(
    faults.device_loss(3, step=2),
    faults.drop_worker(6, step=2, duration=2),
    faults.corrupt_gradient(9, step=2, mode="nan", duration=2),
))


def _fault_rows(resdir):
    lines = (resdir / "study").read_text().split(os.linesep)
    assert lines[0] == "# " + "\t".join(STUDY_COLUMNS + FAULT_COLUMNS)
    rows = []
    for line in lines[1:]:
        if line:
            f = line.split("\t")
            assert len(f) == len(STUDY_COLUMNS) + len(FAULT_COLUMNS)
            rows.append({"loss": float(f[2]), "injected": int(f[-3]),
                         "active": int(f[-2]), "quorum_f": int(f[-1])})
    return rows


def test_cli_fault_plan_smoke(tmp_path, small_synth):
    from byzantinemomentum_tpu.cli.attack import main

    plan_path = DEMO_PLAN.save(tmp_path / "plan.json")
    resdir = tmp_path / "run"
    rc = main(CLI_BASE + ["--gar", "krum", "--fault-plan", str(plan_path),
                          "--result-directory", str(resdir)])
    assert rc == 0
    cfg = json.loads((resdir / "config.json").read_text())
    assert cfg["fault_plan"] == str(plan_path)
    rows = _fault_rows(resdir)
    assert [r["injected"] for r in rows] == [0, 0, 3, 3]
    assert [r["active"] for r in rows] == [11, 11, 8, 8]
    assert [r["quorum_f"] for r in rows] == [4, 4, 2, 2]
    assert all(np.isfinite(r["loss"]) for r in rows)


@pytest.mark.slow
def test_cli_acceptance_demo_resilient_gars_vs_bare_average(tmp_path,
                                                            small_synth):
    """The subsystem's acceptance scenario: 2 dropped workers + 1
    NaN-corrupting worker out of n = 11. krum and median (quarantine +
    dynamic quorum) finish with finite loss; `average` with quarantine
    disabled visibly diverges."""
    from byzantinemomentum_tpu.cli.attack import main

    plan_path = DEMO_PLAN.save(tmp_path / "plan.json")
    bare = faults.FaultPlan(
        events=DEMO_PLAN.events,
        policy=faults.FaultPolicy(nan_quarantine=False))
    bare_path = bare.save(tmp_path / "plan_bare.json")

    for gar, path, f_eff in (("krum", plan_path, 2),
                             ("median", plan_path, 3)):
        resdir = tmp_path / f"run_{gar}"
        assert main(CLI_BASE + ["--gar", gar, "--fault-plan", str(path),
                                "--result-directory", str(resdir)]) == 0
        rows = _fault_rows(resdir)
        assert all(np.isfinite(r["loss"]) for r in rows), gar
        assert rows[-1]["active"] == 8 and rows[-1]["quorum_f"] == f_eff

    resdir = tmp_path / "run_average"
    assert main(CLI_BASE + ["--gar", "average", "--fault-plan",
                            str(bare_path),
                            "--result-directory", str(resdir)]) == 0
    rows = _fault_rows(resdir)
    assert np.isfinite(rows[1]["loss"])      # clean until the faults hit
    assert np.isnan(rows[-1]["loss"])        # then visibly diverged
    assert rows[-1]["active"] == 9           # drops masked, NaN row not
