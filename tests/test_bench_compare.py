"""Unit tests for `scripts/bench_compare.py` (benchmark artifact diffing:
per-cell deltas, the --tolerance regression gate, and the incomparability
rules for crashed / cpu-fallback runs)."""

import importlib.util
import json
import pathlib
import sys

SCRIPT = (pathlib.Path(__file__).resolve().parent.parent
          / "scripts" / "bench_compare.py")
_spec = importlib.util.spec_from_file_location("bench_compare", SCRIPT)
bench_compare = importlib.util.module_from_spec(_spec)
sys.modules.setdefault("bench_compare", bench_compare)
_spec.loader.exec_module(bench_compare)


def _artifact(tmp_path, name, value, cells=None, rc=0, backend=None,
              parsed=True):
    payload = {"metric": "sim_steps_per_sec", "value": value,
               "unit": "steps/s"}
    if cells is not None:
        payload["cells"] = cells
    if backend is not None:
        payload["backend"] = backend
    data = {"n": 1, "rc": rc, "parsed": payload if parsed else None}
    path = tmp_path / name
    path.write_text(json.dumps(data))
    return path


def test_improvement_and_within_tolerance_pass(tmp_path, capsys):
    old = _artifact(tmp_path, "old.json", 10.0,
                    cells={"krum": {"steps_per_sec_bf16_mixed": 50.0}})
    new = _artifact(tmp_path, "new.json", 11.0,
                    cells={"krum": {"steps_per_sec_bf16_mixed": 49.0}})
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "+10.00%" in out and "-2.00%" in out
    assert "REGRESSED" not in out


def test_regression_past_tolerance_fails(tmp_path, capsys):
    old = _artifact(tmp_path, "old.json", 10.0,
                    cells={"krum": {"steps_per_sec_bf16_mixed": 50.0}})
    new = _artifact(tmp_path, "new.json", 10.0,
                    cells={"krum": {"steps_per_sec_bf16_mixed": 40.0}})
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "REGRESSED" in out and "-20.00%" in out


def test_cpu_fallback_is_incomparable_not_regressed(tmp_path, capsys):
    old = _artifact(tmp_path, "old.json", 50.0)
    new = _artifact(tmp_path, "new.json", 1.0, backend="cpu-fallback")
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "INCOMPARABLE" in out and "cpu fallback" in out.lower()


def test_crashed_run_is_incomparable(tmp_path, capsys):
    old = _artifact(tmp_path, "old.json", 50.0)
    new = _artifact(tmp_path, "new.json", 0.0, rc=1, parsed=False)
    rc = bench_compare.main([str(old), str(new)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "INCOMPARABLE" in out


def test_raw_payload_accepted(tmp_path):
    """Raw bench.py output (no harness wrapper) compares too."""
    raw = tmp_path / "raw.json"
    raw.write_text(json.dumps({"metric": "m", "value": 20.0}))
    payload, reason = bench_compare.load_artifact(raw)
    assert reason is None and payload["value"] == 20.0


def test_compare_only_common_cells():
    rows, regressions = bench_compare.compare(
        {"metric": "m", "value": 10.0,
         "cells": {"a": {"steps_per_sec_f32": 1.0}}},
        {"metric": "m", "value": 10.0,
         "cells": {"b": {"steps_per_sec_f32": 1.0}}},
        tolerance=0.05)
    names = [r[0] for r in rows]
    assert names == ["m"] and not regressions
