"""Unit tests for `scripts/bench_compare.py` (benchmark artifact diffing:
per-cell deltas, the --tolerance regression gate, and the incomparability
rules for crashed / cpu-fallback runs)."""

import importlib.util
import json
import pathlib
import sys

import pytest

SCRIPT = (pathlib.Path(__file__).resolve().parent.parent
          / "scripts" / "bench_compare.py")
_spec = importlib.util.spec_from_file_location("bench_compare", SCRIPT)
bench_compare = importlib.util.module_from_spec(_spec)
sys.modules.setdefault("bench_compare", bench_compare)
_spec.loader.exec_module(bench_compare)


def _artifact(tmp_path, name, value, cells=None, rc=0, backend=None,
              parsed=True):
    payload = {"metric": "sim_steps_per_sec", "value": value,
               "unit": "steps/s"}
    if cells is not None:
        payload["cells"] = cells
    if backend is not None:
        payload["backend"] = backend
    data = {"n": 1, "rc": rc, "parsed": payload if parsed else None}
    path = tmp_path / name
    path.write_text(json.dumps(data))
    return path


def test_improvement_and_within_tolerance_pass(tmp_path, capsys):
    old = _artifact(tmp_path, "old.json", 10.0,
                    cells={"krum": {"steps_per_sec_bf16_mixed": 50.0}})
    new = _artifact(tmp_path, "new.json", 11.0,
                    cells={"krum": {"steps_per_sec_bf16_mixed": 49.0}})
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "+10.00%" in out and "-2.00%" in out
    assert "REGRESSED" not in out


def test_regression_past_tolerance_fails(tmp_path, capsys):
    old = _artifact(tmp_path, "old.json", 10.0,
                    cells={"krum": {"steps_per_sec_bf16_mixed": 50.0}})
    new = _artifact(tmp_path, "new.json", 10.0,
                    cells={"krum": {"steps_per_sec_bf16_mixed": 40.0}})
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "REGRESSED" in out and "-20.00%" in out


def test_cpu_fallback_is_incomparable_not_regressed(tmp_path, capsys):
    old = _artifact(tmp_path, "old.json", 50.0)
    new = _artifact(tmp_path, "new.json", 1.0, backend="cpu-fallback")
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "INCOMPARABLE" in out and "cpu fallback" in out.lower()


def test_crashed_run_is_incomparable(tmp_path, capsys):
    old = _artifact(tmp_path, "old.json", 50.0)
    new = _artifact(tmp_path, "new.json", 0.0, rc=1, parsed=False)
    rc = bench_compare.main([str(old), str(new)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "INCOMPARABLE" in out


def test_raw_payload_accepted(tmp_path):
    """Raw bench.py output (no harness wrapper) compares too."""
    raw = tmp_path / "raw.json"
    raw.write_text(json.dumps({"metric": "m", "value": 20.0}))
    payload, reason = bench_compare.load_artifact(raw)
    assert reason is None and payload["value"] == 20.0


def test_compare_only_common_cells():
    rows, regressions = bench_compare.compare(
        {"metric": "m", "value": 10.0,
         "cells": {"a": {"steps_per_sec_f32": 1.0}}},
        {"metric": "m", "value": 10.0,
         "cells": {"b": {"steps_per_sec_f32": 1.0}}},
        tolerance=0.05)
    names = [r[0] for r in rows]
    assert names == ["m"] and not regressions


# --------------------------------------------------------------------------- #
# Phase-budget gating over attribution.json artifacts (obs/attrib, PR 6)

def _attribution(tmp_path, name, honest_ms, gar_ms, relayout_ms,
                 host_gap_frac, backend="tpu"):
    phases = {
        "honest": {"ms": honest_ms, "ops": 100},
        "gar": {"ms": gar_ms, "ops": 20},
        "host": {"ms": 0.2, "ops": 0},
    }
    device = honest_ms + gar_ms
    payload = {
        "kind": "attribution", "backend": backend, "steps": 8,
        "phases": phases,
        "op_classes": {"mxu": honest_ms, "relayout": relayout_ms,
                       "memory": device - honest_ms - relayout_ms},
        "total_ms": device + 0.2,
        "host_gap_fraction": host_gap_frac,
    }
    path = tmp_path / name
    path.write_text(json.dumps(payload))
    return path


def test_attribution_within_budget_passes(tmp_path, capsys):
    old = _attribution(tmp_path, "old.json", 10.0, 2.0, 0.5, 0.05)
    new = _attribution(tmp_path, "new.json", 10.2, 1.9, 0.51, 0.05)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "phase.honest.ms" in out and "REGRESSED" not in out


def test_attribution_relayout_regrowth_fails(tmp_path, capsys):
    """The gate the tentpole exists for: relayout copies regrowing past
    the tolerance fail CI even when total steps/s would still pass."""
    old = _attribution(tmp_path, "old.json", 10.0, 2.0, 0.5, 0.05)
    new = _attribution(tmp_path, "new.json", 10.0, 2.0, 2.5, 0.05)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "class.relayout.ms" in out and "REGRESSED" in out


def test_attribution_host_gap_growth_fails(tmp_path, capsys):
    old = _attribution(tmp_path, "old.json", 10.0, 2.0, 0.5, 0.02)
    new = _attribution(tmp_path, "new.json", 10.0, 2.0, 0.5, 0.20)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "host_gap_fraction" in out and "REGRESSED" in out


def test_attribution_noise_floor_tolerated(tmp_path, capsys):
    """Sub-noise budgets (< the absolute floor) cannot flake the gate
    even at huge relative growth."""
    old = _attribution(tmp_path, "old.json", 10.0, 2.0, 0.001, 0.05)
    new = _attribution(tmp_path, "new.json", 10.0, 2.0, 0.04, 0.05)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    assert rc == 0, capsys.readouterr().out


def test_attribution_mixed_kinds_incomparable(tmp_path, capsys):
    bench = _artifact(tmp_path, "bench.json", 50.0)
    att = _attribution(tmp_path, "att.json", 10.0, 2.0, 0.5, 0.05)
    rc = bench_compare.main([str(bench), str(att)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "INCOMPARABLE" in out


def test_attribution_backend_mismatch_incomparable(tmp_path, capsys):
    old = _attribution(tmp_path, "old.json", 10.0, 2.0, 0.5, 0.05,
                       backend="tpu")
    new = _attribution(tmp_path, "new.json", 40.0, 8.0, 2.0, 0.05,
                       backend="cpu")
    rc = bench_compare.main([str(old), str(new)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "INCOMPARABLE" in out and "backends" in out


# --------------------------------------------------------------------------- #
# bench_history: the per-cell trajectory over rounds

def _bench_history():
    import importlib.util
    import pathlib
    import sys
    script = (pathlib.Path(__file__).resolve().parent.parent
              / "scripts" / "bench_history.py")
    spec = importlib.util.spec_from_file_location("bench_history", script)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("bench_history", mod)
    spec.loader.exec_module(mod)
    return mod


def test_bench_history_table_and_incomparable_rounds(tmp_path, capsys):
    bench_history = _bench_history()
    _artifact(tmp_path, "BENCH_r01.json", 10.0)
    _artifact(tmp_path, "BENCH_r02.json", 12.0,
              cells={"krum": {"steps_per_sec_bf16_mixed": 50.0}})
    _artifact(tmp_path, "BENCH_r03.json", 0.0, rc=1, parsed=False)  # crash
    _artifact(tmp_path, "BENCH_r04.json", 1.0, backend="cpu-fallback")
    # The working tree's machine-readable sibling becomes `current`
    (tmp_path / "BENCH_cells.json").write_text(json.dumps(
        {"metric": "sim_steps_per_sec", "value": 13.0,
         "cells": {"krum": {"steps_per_sec_bf16_mixed": 55.0}}}))
    rc = bench_history.main(["--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    for needle in ("r01", "r02", "r03", "r04", "current",
                   "krum.steps_per_sec_bf16_mixed", "INCOMPARABLE"):
        assert needle in out, out
    # Crashed and cpu-fallback rounds are dashes, not numbers or failures
    r03_line = [l for l in out.splitlines() if l.startswith("r03")][0]
    assert set(r03_line.split()[1:]) == {"-"}
    history = bench_history.collect_history(tmp_path)
    assert [label for label, *_ in history] == [
        "r01", "r02", "r03", "r04", "current"]
    assert history[2][1] is None and "rc=1" in history[2][2]
    assert history[3][1] is None and "cpu" in history[3][2].lower()
    assert history[4][1]["krum.steps_per_sec_bf16_mixed"] == 55.0


def _attribution_artifact(path, gar_ms, masked_ms=0.0, backend="tpu"):
    path.write_text(json.dumps({
        "kind": "attribution", "backend": backend, "steps": 20,
        "phases": {"honest": {"ms": 10.0, "ops": 5},
                   "gar": {"ms": gar_ms, "ops": 3},
                   "gar_masked": {"ms": masked_ms, "ops": 1}},
    }))


def test_bench_history_gar_phase_column(tmp_path, capsys):
    """The `gar ms/step` column renders from per-round ATTRIB_r*.json
    artifacts (sum of the gar/gar_masked/gar_diag phase budgets) next to
    steps/s; rounds without an artifact show `-`, non-TPU artifacts get a
    backend note, and an attribution next to a CRASHED bench round still
    renders (independent instruments)."""
    bench_history = _bench_history()
    _artifact(tmp_path, "BENCH_r01.json", 10.0)
    _artifact(tmp_path, "BENCH_r02.json", 12.0)
    _artifact(tmp_path, "BENCH_r03.json", 0.0, rc=1, parsed=False)  # crash
    _attribution_artifact(tmp_path / "ATTRIB_r02.json", 2.25, 0.25)
    _attribution_artifact(tmp_path / "ATTRIB_r03.json", 3.0, backend="cpu")
    (tmp_path / "BENCH_cells.json").write_text(json.dumps(
        {"metric": "sim_steps_per_sec", "value": 13.0}))
    _attribution_artifact(tmp_path / "attribution.json", 1.5)

    history = bench_history.collect_history(tmp_path)
    by_label = {label: gar for label, _, _, gar in history}
    assert by_label["r01"] is None
    assert by_label["r02"] == (2.5, "tpu")
    assert by_label["r03"] == (3.0, "cpu")
    assert by_label["current"] == (1.5, "tpu")

    rc = bench_history.main(["--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert bench_history.GAR_COLUMN in out
    r01 = [l for l in out.splitlines() if l.startswith("r01")][0]
    assert r01.split()[-1] == "-"
    r02 = [l for l in out.splitlines() if l.startswith("r02")][0]
    assert r02.split()[-1] == "2.500"
    # The crashed round renders its (independent) attribution number and
    # the backend mismatch is flagged in the notes
    r03 = [l for l in out.splitlines() if l.startswith("r03")][0]
    assert r03.split()[-1] == "3.000"
    assert "backend=cpu attribution" in out

    rc = bench_history.main(["--root", str(tmp_path), "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert payload[1]["gar_ms_per_step"] == 2.5
    assert payload[0]["gar_ms_per_step"] is None


def test_bench_history_gar_column_absent_without_artifacts(tmp_path, capsys):
    bench_history = _bench_history()
    _artifact(tmp_path, "BENCH_r01.json", 10.0)
    rc = bench_history.main(["--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert bench_history.GAR_COLUMN not in out


def test_bench_history_json_mode(tmp_path, capsys):
    bench_history = _bench_history()
    _artifact(tmp_path, "BENCH_r01.json", 10.0)
    rc = bench_history.main(["--root", str(tmp_path), "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert payload[0]["round"] == "r01"
    assert payload[0]["rates"]["sim_steps_per_sec"] == 10.0


def test_bench_history_empty_dir(tmp_path, capsys):
    bench_history = _bench_history()
    rc = bench_history.main(["--root", str(tmp_path)])
    assert rc == 0
    assert "no BENCH_r*.json" in capsys.readouterr().out


def test_bench_history_over_repo_artifacts(capsys):
    """The committed BENCH_r01..r05 trajectory renders: r05 (the down-
    tunnel crash) INCOMPARABLE, the r03->r04 packing-era cells present."""
    bench_history = _bench_history()
    rc = bench_history.main([])
    out = capsys.readouterr().out
    assert rc == 0
    assert "r05: INCOMPARABLE" in out
    assert "wrn28x10.steps_per_sec_bf16_mixed" in out


# --------------------------------------------------------------------------- #
# wrn_pack_ab: the packing-escape A/B harness


@pytest.mark.slow
def test_wrn_pack_ab_smoke(tmp_path, capsys):
    """`--smoke` proves the harness end to end off-TPU: a JSON payload
    with per-mode steps/s, the preferred pick, and the backend/smoke
    markers the INCOMPARABLE discipline keys on."""
    import importlib.util
    import pathlib
    import sys
    script = (pathlib.Path(__file__).resolve().parent.parent
              / "scripts" / "wrn_pack_ab.py")
    spec = importlib.util.spec_from_file_location("wrn_pack_ab", script)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("wrn_pack_ab", mod)
    spec.loader.exec_module(mod)

    out_path = tmp_path / "ab.json"
    rc = mod.main(["--smoke", "--modes", "baseline", "--dtypes", "f32",
                   "--out", str(out_path)])
    assert rc == 0
    payload = json.loads(out_path.read_text())
    assert payload["kind"] == "wrn_pack_ab"
    assert payload["smoke"] is True
    assert payload["results"]["baseline"]["f32"]["steps_per_sec"] > 0
    assert payload["preferred"]["mode"] == "baseline"


# --------------------------------------------------------------------------- #
# serve gate: BENCH_serve.json latency/throughput comparison

def _serve_artifact(path, p99=5.0, p50=2.0, rate=4000.0, speedup=4.0,
                    backend="cpu", compiles=None):
    payload = {
        "kind": "serve", "backend": backend,
        "cells": {
            "serve.open_loop": {"p50_ms": p50, "p99_ms": p99,
                                "agg_per_sec": rate * 0.5},
            "serve.batched": {"p50_ms": p50 * 20, "p99_ms": p99 * 20,
                              "agg_per_sec": rate},
            "serve.sequential": {"p50_ms": 0.5, "p99_ms": 1.0,
                                 "agg_per_sec": rate / speedup},
        },
        "speedup_batched_vs_sequential": speedup,
    }
    if compiles is not None:
        payload["compiles"] = {"distinct_cells": compiles,
                               "distinct_programs": compiles * 4,
                               "warm_compiles": 0,
                               "per_nd_policy_cells": compiles * 6,
                               "reduction_vs_per_nd": 6.0}
    path.write_text(json.dumps(payload))
    return path


def test_serve_gate_within_tolerance_passes(tmp_path, capsys):
    old = _serve_artifact(tmp_path / "old.json")
    new = _serve_artifact(tmp_path / "new.json", p99=5.1)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "serve.open_loop.p99_ms" in out
    assert "REGRESSED" not in out


def test_serve_gate_p99_growth_fails(tmp_path, capsys):
    old = _serve_artifact(tmp_path / "old.json", p99=5.0)
    new = _serve_artifact(tmp_path / "new.json", p99=9.0)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.10"])
    out = capsys.readouterr().out
    assert rc == 1
    line = [l for l in out.splitlines()
            if "serve.open_loop.p99_ms" in l][0]
    assert "REGRESSED" in line


def test_serve_gate_throughput_drop_fails(tmp_path, capsys):
    old = _serve_artifact(tmp_path / "old.json", rate=4000.0, speedup=4.0)
    new = _serve_artifact(tmp_path / "new.json", rate=2000.0, speedup=2.0)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.10"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "serve.batched.agg_per_sec" in out
    assert "speedup_batched_vs_sequential" in out


def test_serve_gate_baseline_driven_speedup_drop_passes(tmp_path, capsys):
    """A speedup-ratio drop caused by the SEQUENTIAL baseline getting
    faster (batched capacity improved) is not a serving regression — the
    ratio's components are gated on their own and a faster baseline can
    never fail."""
    old = _serve_artifact(tmp_path / "old.json", rate=4000.0, speedup=4.0)
    new = _serve_artifact(tmp_path / "new.json", rate=4500.0, speedup=3.0)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.10"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "REGRESSED" not in out
    assert "speedup_batched_vs_sequential" in out  # still rendered


def test_serve_gate_compiles_growth_fails(tmp_path, capsys):
    """The r10 `compiles` column: ANY growth in the heterogeneous
    workload's distinct compiled-program count fails — no tolerance, no
    floor (a compile is a ladder hole, not noise)."""
    old = _serve_artifact(tmp_path / "old.json", compiles=4)
    new = _serve_artifact(tmp_path / "new.json", compiles=5)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.50"])
    out = capsys.readouterr().out
    assert rc == 1
    line = [l for l in out.splitlines() if "compiles.distinct_cells" in l][0]
    assert "REGRESSED" in line


def test_serve_gate_compiles_flat_passes_and_legacy_pair_skips(
        tmp_path, capsys):
    """Equal compile counts pass; a legacy (r08) artifact without the
    field simply has no common compiles metric — the gate skips it
    rather than failing the pair."""
    old = _serve_artifact(tmp_path / "old.json", compiles=4)
    new = _serve_artifact(tmp_path / "new.json", compiles=4)
    assert bench_compare.main([str(old), str(new)]) == 0
    capsys.readouterr()
    legacy = _serve_artifact(tmp_path / "legacy.json")  # no compiles field
    current = _serve_artifact(tmp_path / "current.json", compiles=4)
    assert bench_compare.main([str(legacy), str(current)]) == 0
    assert "compiles" not in capsys.readouterr().out


def test_serve_gate_sub_floor_growth_is_noise(tmp_path, capsys):
    """Latency growth below the absolute floor never fails the gate even
    when the relative delta is large (the phase-budget discipline)."""
    def sub_floor(path, p99):
        path.write_text(json.dumps({
            "kind": "serve", "backend": "cpu",
            "cells": {"serve.open_loop": {"p50_ms": p99 / 2,
                                          "p99_ms": p99,
                                          "agg_per_sec": 1000.0}}}))
        return path
    old = sub_floor(tmp_path / "old.json", 0.10)
    new = sub_floor(tmp_path / "new.json", 0.35)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    assert rc == 0
    assert "REGRESSED" not in capsys.readouterr().out


def test_serve_gate_cross_backend_incomparable(tmp_path, capsys):
    old = _serve_artifact(tmp_path / "old.json", backend="cpu")
    new = _serve_artifact(tmp_path / "new.json", p99=50.0, backend="tpu")
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "INCOMPARABLE" in out and "backend" in out


def test_serve_gate_mixed_kind_incomparable(tmp_path, capsys):
    serve = _serve_artifact(tmp_path / "serve.json")
    bench = _artifact(tmp_path, "bench.json", 10.0)
    rc = bench_compare.main([str(serve), str(bench), "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "INCOMPARABLE" in out


def test_bench_history_serve_columns(tmp_path, capsys):
    """Serve p50/p99/agg-per-s columns render from BENCH_serve_r*.json
    (working tree `BENCH_serve.json` as `current`), rounds without an
    artifact dash out, and non-TPU load reports get a backend note."""
    bench_history = _bench_history()
    _artifact(tmp_path, "BENCH_r01.json", 10.0)
    _serve_artifact(tmp_path / "BENCH_serve_r02.json", p99=6.0, rate=5000.0)
    _serve_artifact(tmp_path / "BENCH_serve.json", p99=5.5, rate=5200.0,
                    compiles=4)
    (tmp_path / "BENCH_cells.json").write_text(json.dumps(
        {"metric": "sim_steps_per_sec", "value": 12.0}))

    serve = bench_history.collect_serve(tmp_path, ["r01", "r02", "current"])
    assert "r01" not in serve
    assert serve["r02"]["p99"] == 6.0 and serve["r02"]["rate"] == 5000.0
    assert serve["r02"]["compiles"] is None  # pre-r10 artifact
    assert serve["current"]["p99"] == 5.5
    assert serve["current"]["compiles"] == 16  # distinct_programs

    rc = bench_history.main(["--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    for column in bench_history.SERVE_COLUMNS:
        assert column in out
    r01 = [l for l in out.splitlines() if l.startswith("r01")][0]
    assert r01.split()[-1] == "-"
    r02 = [l for l in out.splitlines() if l.startswith("r02")][0]
    assert r02.split()[-4:] == ["2.000", "6.000", "5000.000", "-"]
    current = [l for l in out.splitlines() if l.startswith("current")][0]
    assert current.split()[-1] == "16"
    assert "backend=cpu load report" in out

    rc = bench_history.main(["--root", str(tmp_path), "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    by_round = {row["round"]: row for row in payload}
    assert by_round["r02"]["serve"]["p99"] == 6.0
    assert by_round["r01"]["serve"] is None


def test_bench_history_tournament_columns(tmp_path, capsys):
    """Time-to-quarantine / evicted-honest columns render from committed
    TOURNAMENT_r*.json scoreboards; rounds without one dash out, and a
    tournament-only round still gets a row."""
    bench_history = _bench_history()
    _artifact(tmp_path, "BENCH_r01.json", 10.0)
    cells = [
        {"gar": "krum", "attack": "alie", "quarantine": True,
         "time_to_quarantine": 15, "evicted_honest": 0},
        {"gar": "krum", "attack": "alie", "quarantine": False,
         "time_to_quarantine": None, "evicted_honest": 0},
        {"gar": "cge", "attack": "nan", "quarantine": True,
         "time_to_quarantine": 11, "evicted_honest": 0},
    ]
    (tmp_path / "TOURNAMENT_r02.json").write_text(json.dumps({
        "kind": "tournament", "train_cells": cells,
        "summary": {"honest_evictions_total": 0}}))

    stats = bench_history.collect_tournament(tmp_path, ["r01", "r02"])
    assert "r01" not in stats
    assert stats["r02"]["ttq_median"] == 15  # median of [11, 15], upper
    assert stats["r02"]["evicted_honest"] == 0
    assert stats["r02"]["cells"] == 3

    rc = bench_history.main(["--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    for column in bench_history.TOURNAMENT_COLUMNS:
        assert column in out
    r02 = [l for l in out.splitlines() if l.startswith("r02")][0]
    assert r02.split()[-2:] == ["15", "0"]
    r01 = [l for l in out.splitlines() if l.startswith("r01")][0]
    assert r01.split()[-1] == "-"

    rc = bench_history.main(["--root", str(tmp_path), "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    by_round = {row["round"]: row for row in payload}
    assert by_round["r02"]["tournament"]["ttq_median"] == 15
    assert by_round["r01"]["tournament"] is None


# --------------------------------------------------------------------------- #
# Cluster gate + history columns (multi-host CLUSTER_r*.json artifacts)

def _cluster_artifact(tmp_path, name, rate, hosts=2, status="ok",
                      backend="cpu", recovery_steps=1, events=1):
    payload = {"kind": "cluster", "backend": backend, "status": status,
               "hosts": hosts, "steps": 12, "steps_per_sec": rate,
               "recovery": {"events": events,
                            "recovery_steps": recovery_steps,
                            "attempts": events + 1}}
    path = tmp_path / name
    path.write_text(json.dumps(payload))
    return path


def test_cluster_gate_within_tolerance_passes(tmp_path, capsys):
    old = _cluster_artifact(tmp_path, "CLUSTER_r12.json", 1.00)
    new = _cluster_artifact(tmp_path, "CLUSTER_r13.json", 0.98,
                            recovery_steps=3)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "cluster.steps_per_sec" in out
    # recovery rows render for trend but never gate
    assert "recovery.recovery_steps (info)" in out


def test_cluster_gate_throughput_drop_fails(tmp_path, capsys):
    old = _cluster_artifact(tmp_path, "CLUSTER_r12.json", 1.00)
    new = _cluster_artifact(tmp_path, "CLUSTER_r13.json", 0.80)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "REGRESSED" in out


def test_cluster_gate_incomparable_pairs(tmp_path, capsys):
    ok = _cluster_artifact(tmp_path, "CLUSTER_r12.json", 1.0)
    # Different backend (the CPU-simulated fleet vs a native one)
    other = _cluster_artifact(tmp_path, "CLUSTER_native.json", 3.0,
                              backend="native")
    assert bench_compare.main([str(ok), str(other)]) == 0
    assert "INCOMPARABLE" in capsys.readouterr().out
    # Different fleet size
    wide = _cluster_artifact(tmp_path, "CLUSTER_wide.json", 1.4, hosts=4)
    assert bench_compare.main([str(ok), str(wide)]) == 0
    assert "fleet sizes" in capsys.readouterr().out
    # An unavailable round carries no comparable throughput
    unavail = _cluster_artifact(tmp_path, "CLUSTER_un.json", None,
                                status="unavailable")
    assert bench_compare.main([str(ok), str(unavail)]) == 0
    assert "INCOMPARABLE" in capsys.readouterr().out
    # Mixed kinds
    bench = _artifact(tmp_path, "BENCH_r09.json", 10.0)
    assert bench_compare.main([str(ok), str(bench)]) == 0
    assert "INCOMPARABLE" in capsys.readouterr().out


def test_bench_history_cluster_columns(tmp_path, capsys):
    """hosts / cluster steps-per-s / recovery-steps columns render from
    committed CLUSTER_r*.json artifacts; a cluster-only round still gets
    a row, non-ok rounds dash out, and --json carries the dict."""
    bench_history = _bench_history()
    _artifact(tmp_path, "BENCH_r01.json", 10.0)
    _cluster_artifact(tmp_path, "CLUSTER_r02.json", 0.9, hosts=4,
                      recovery_steps=2)
    _cluster_artifact(tmp_path, "CLUSTER_r03.json", None,
                      status="unavailable")

    stats = bench_history.collect_cluster(tmp_path, ["r01", "r02", "r03"])
    assert "r01" not in stats and "r03" not in stats
    assert stats["r02"]["hosts"] == 4
    assert stats["r02"]["recovery_steps"] == 2

    rc = bench_history.main(["--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    for column in bench_history.CLUSTER_COLUMNS:
        assert column in out
    r02 = [l for l in out.splitlines() if l.startswith("r02")][0]
    assert r02.split()[-3:] == ["4", "0.900", "2"]
    assert "backend=cpu fleet" in out  # flagged: CPU-simulated fleet

    rc = bench_history.main(["--root", str(tmp_path), "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    by_round = {row["round"]: row for row in payload}
    assert by_round["r02"]["cluster"]["rate"] == 0.9
    assert by_round["r01"]["cluster"] is None


# --------------------------------------------------------------------------- #
# serve-attribution gate: ATTRIB_serve*.json per-phase comparison (PR 13)

def _serve_attrib_artifact(path, queue_p99=5.0, device_p99=0.3,
                           resolve_p99=1.0, backend="cpu",
                           overhead=0.02):
    def cell(p99):
        return {"p50_ms": round(p99 / 3.0, 3), "p90_ms": round(p99 / 1.5, 3),
                "p99_ms": p99, "mean_ms": round(p99 / 2.5, 3),
                "max_ms": p99 * 1.1}

    payload = {
        "kind": "serve_attribution", "backend": backend,
        "phases": {"validate": cell(0.05), "queue": cell(queue_p99),
                   "pack": cell(0.06), "dispatch": cell(1.0),
                   "resolver_wake": cell(0.4), "device": cell(device_p99),
                   "resolve": cell(resolve_p99)},
        "latency": cell(queue_p99 + 2.0),
        "tile": {"error_frac": 0.01, "within_tolerance": True},
        "queue_depth": {"p50": 4.0, "p99": 9.0, "mean": 4.5, "max": 12.0},
        "batch_occupancy": {"p50": 1.0, "p99": 1.0, "mean": 0.97,
                            "max": 1.0},
        "overhead": {"frac": overhead},
    }
    path.write_text(json.dumps(payload))
    return path


def test_serve_attrib_gate_within_tolerance_passes(tmp_path, capsys):
    old = _serve_attrib_artifact(tmp_path / "old.json", queue_p99=5.0)
    new = _serve_attrib_artifact(tmp_path / "new.json", queue_p99=5.1)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.10"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "phase.queue.p99_ms" in out and "latency.p99_ms" in out
    assert "overhead.frac (info)" in out
    assert "REGRESSED" not in out


def test_serve_attrib_gate_phase_p99_growth_fails(tmp_path, capsys):
    old = _serve_attrib_artifact(tmp_path / "old.json", resolve_p99=1.0)
    new = _serve_attrib_artifact(tmp_path / "new.json", resolve_p99=2.5)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.10"])
    out = capsys.readouterr().out
    assert rc == 1
    line = [l for l in out.splitlines() if "phase.resolve.p99_ms" in l][0]
    assert "REGRESSED" in line


def test_serve_attrib_gate_sub_floor_growth_is_noise(tmp_path, capsys):
    """A phase that doubles from 0.1 to 0.2 ms is scheduler noise on a
    1-core host — the absolute floor keeps it out of the gate."""
    old = _serve_attrib_artifact(tmp_path / "old.json", device_p99=0.10)
    new = _serve_attrib_artifact(tmp_path / "new.json", device_p99=0.20)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.10"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "REGRESSED" not in out


def test_serve_attrib_gate_overhead_is_informational(tmp_path, capsys):
    old = _serve_attrib_artifact(tmp_path / "old.json", overhead=0.01)
    new = _serve_attrib_artifact(tmp_path / "new.json", overhead=0.05)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.10"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "overhead.frac (info)" in out and "REGRESSED" not in out


def test_serve_attrib_gate_incomparable_pairs(tmp_path, capsys):
    attrib = _serve_attrib_artifact(tmp_path / "a.json")
    # Cross-backend
    native = _serve_attrib_artifact(tmp_path / "b.json", backend="tpu")
    assert bench_compare.main([str(attrib), str(native)]) == 0
    assert "INCOMPARABLE" in capsys.readouterr().out
    # Mixed kinds: a serve load report is NOT a serve attribution
    serve = _serve_artifact(tmp_path / "c.json")
    assert bench_compare.main([str(attrib), str(serve)]) == 0
    assert "INCOMPARABLE" in capsys.readouterr().out
    bench = _artifact(tmp_path, "BENCH_r09.json", 10.0)
    assert bench_compare.main([str(attrib), str(bench)]) == 0
    assert "INCOMPARABLE" in capsys.readouterr().out


def test_bench_history_serve_phase_columns(tmp_path, capsys):
    """queue-wait / device / resolve ms columns render from committed
    ATTRIB_serve_r*.json rounds; an attribution-only round still gets a
    row and the CPU backend is flagged in the notes."""
    bench_history = _bench_history()
    _artifact(tmp_path, "BENCH_r01.json", 10.0)
    _serve_attrib_artifact(tmp_path / "ATTRIB_serve_r02.json",
                           queue_p99=3.0, device_p99=0.3, resolve_p99=1.5)

    stats = bench_history.collect_serve_attrib(tmp_path, ["r01", "r02"])
    assert "r01" not in stats
    assert stats["r02"]["queue"] == 1.0   # p50 = p99 / 3 per the helper
    assert stats["r02"]["resolve"] == 0.5

    rc = bench_history.main(["--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    for column in bench_history.SERVE_ATTRIB_COLUMNS:
        assert column in out
    r02 = [l for l in out.splitlines() if l.startswith("r02")][0]
    assert r02.split()[-3:] == ["1.000", "0.100", "0.500"]
    assert "backend=cpu trace report" in out

    rc = bench_history.main(["--root", str(tmp_path), "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    by_round = {row["round"]: row for row in payload}
    assert by_round["r02"]["serve_attrib"]["queue"] == 1.0
    assert by_round["r01"]["serve_attrib"] is None


# --------------------------------------------------------------------------- #
# Flight-recorder overhead gate (`BENCH_health*.json`, PR 15)

def _health_artifact(tmp_path, name, overhead, off=22.0, backend="cpu",
                     smoke=False):
    payload = {"kind": "health_overhead", "backend": backend,
               "steps_per_sec_off": off,
               "steps_per_sec_on": off * (1.0 - overhead),
               "overhead_frac": overhead,
               "overhead_ok": overhead <= 0.03}
    if smoke:
        payload["smoke"] = True
    path = tmp_path / name
    path.write_text(json.dumps(payload))
    return path


def test_health_gate_within_tolerance_passes(tmp_path, capsys):
    old = _health_artifact(tmp_path, "BENCH_health_r15.json", 0.015)
    new = _health_artifact(tmp_path, "BENCH_health_r16.json", 0.018)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "overhead_frac" in out and "REGRESSED" not in out


def test_health_gate_overhead_growth_fails(tmp_path, capsys):
    old = _health_artifact(tmp_path, "BENCH_health_r15.json", 0.015)
    new = _health_artifact(tmp_path, "BENCH_health_r16.json", 0.045)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "overhead_frac" in out and "REGRESSED" in out


def test_health_gate_sub_floor_growth_is_noise(tmp_path, capsys):
    # +0.4 points of overhead is under the 1-point absolute floor: noise
    old = _health_artifact(tmp_path, "BENCH_health_r15.json", 0.010)
    new = _health_artifact(tmp_path, "BENCH_health_r16.json", 0.014)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    assert rc == 0
    assert "REGRESSED" not in capsys.readouterr().out


def test_health_gate_rate_drop_fails(tmp_path, capsys):
    old = _health_artifact(tmp_path, "BENCH_health_r15.json", 0.015,
                           off=22.0)
    new = _health_artifact(tmp_path, "BENCH_health_r16.json", 0.015,
                           off=18.0)
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    assert rc == 1
    assert "steps_per_sec" in capsys.readouterr().out


def test_health_gate_incomparable_pairs(tmp_path, capsys):
    ok = _health_artifact(tmp_path, "BENCH_health_r15.json", 0.015)
    other = _health_artifact(tmp_path, "BENCH_health_tpu.json", 0.002,
                             backend="tpu")
    assert bench_compare.main([str(ok), str(other)]) == 0
    assert "INCOMPARABLE" in capsys.readouterr().out
    smoke = _health_artifact(tmp_path, "BENCH_health_smoke.json", 0.2,
                             smoke=True)
    assert bench_compare.main([str(ok), str(smoke)]) == 0
    assert "smoke" in capsys.readouterr().out
    bench = _artifact(tmp_path, "BENCH_r09.json", 10.0)
    assert bench_compare.main([str(ok), str(bench)]) == 0
    assert "INCOMPARABLE" in capsys.readouterr().out


def test_bench_history_health_column(tmp_path, capsys):
    """The health-overhead column renders from committed
    BENCH_health_r*.json artifacts; a health-only round still gets a
    row, smoke artifacts are skipped, and --json carries the dict."""
    bench_history = _bench_history()
    _artifact(tmp_path, "BENCH_r01.json", 10.0)
    _health_artifact(tmp_path, "BENCH_health_r02.json", 0.0151)
    _health_artifact(tmp_path, "BENCH_health_r03.json", 0.2, smoke=True)

    stats = bench_history.collect_health(tmp_path, ["r01", "r02", "r03"])
    assert "r01" not in stats and "r03" not in stats
    assert stats["r02"]["overhead_frac"] == 0.0151

    rc = bench_history.main(["--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "health ovh %" in out
    r02 = [line for line in out.splitlines() if line.startswith("r02")][0]
    assert r02.split()[-1] == "1.51"
    assert "backend=cpu measurement" in out


# --------------------------------------------------------------------------- #
# serve-fleet gate: BENCH_serve_fleet.json per-(scenario, shard-count) cells

def _fleet_artifact(path, *, rates=None, cores=1, isolation="in_process",
                    backend="cpu", shard_counts=(1, 2), recovery=True,
                    flags=(True, True, True)):
    rates = rates or {"rotation": {"1": 300.0, "2": 280.0},
                      "zipf": {"1": 310.0, "2": 290.0}}
    payload = {
        "kind": "serve_fleet", "backend": backend, "host_cores": cores,
        "isolation": isolation,
        "config": {"shard_counts": list(shard_counts)},
        "scenarios": {name: {count: {"agg_per_sec": rate}
                             for count, rate in rows.items()}
                      for name, rows in rates.items()},
        "recovery": ({"killed": "shard-0",
                      "parked_line_recovered": flags[0],
                      "survivor_monotonic": flags[1],
                      "rewarm_no_faster_than_fresh": flags[2]}
                     if recovery else None),
        "fleet_speedup": 0.95,
    }
    path.write_text(json.dumps(payload))
    return path


def test_fleet_gate_within_tolerance_passes(tmp_path, capsys):
    old = _fleet_artifact(tmp_path / "old.json")
    new = _fleet_artifact(tmp_path / "new.json",
                          rates={"rotation": {"1": 295.0, "2": 285.0},
                                 "zipf": {"1": 320.0, "2": 288.0}})
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "rotation.shards_2.agg_per_sec" in out
    assert "fleet_speedup (info)" in out
    assert "REGRESSED" not in out


def test_fleet_gate_rate_drop_fails_per_shard_count(tmp_path, capsys):
    old = _fleet_artifact(tmp_path / "old.json")
    new = _fleet_artifact(tmp_path / "new.json",
                          rates={"rotation": {"1": 300.0, "2": 180.0},
                                 "zipf": {"1": 310.0, "2": 290.0}})
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.10"])
    out = capsys.readouterr().out
    assert rc == 1
    line = [l for l in out.splitlines()
            if "rotation.shards_2.agg_per_sec" in l][0]
    assert "REGRESSED" in line
    assert "rotation.shards_1" not in out.split("REGRESSED")[-1]


def test_fleet_gate_recovery_flag_flip_fails(tmp_path, capsys):
    """A fleet that corrupts a survivor's verdict stream during failover
    is wrong at any speed: any recovery invariant flipping false fails
    regardless of tolerance or throughput."""
    old = _fleet_artifact(tmp_path / "old.json")
    new = _fleet_artifact(tmp_path / "new.json",
                          flags=(True, False, True))
    rc = bench_compare.main([str(old), str(new), "--tolerance", "0.50"])
    out = capsys.readouterr().out
    assert rc == 1
    line = [l for l in out.splitlines()
            if "recovery.survivor_monotonic" in l][0]
    assert "REGRESSED" in line


def test_fleet_gate_incomparable_pairs(tmp_path, capsys):
    """Different fleet sizes, host core counts, isolation modes,
    backends, and mixed kinds are all INCOMPARABLE (exit 0) — a 4-shard
    rate on an 8-core host says nothing about a 2-shard rate on 1."""
    base = _fleet_artifact(tmp_path / "base.json")
    sizes = _fleet_artifact(tmp_path / "sizes.json",
                            shard_counts=(1, 2, 4),
                            rates={"rotation": {"1": 300.0, "2": 280.0,
                                                "4": 260.0}})
    assert bench_compare.main([str(base), str(sizes)]) == 0
    assert "different fleet sizes" in capsys.readouterr().out
    cores = _fleet_artifact(tmp_path / "cores.json", cores=8)
    assert bench_compare.main([str(base), str(cores)]) == 0
    assert "core counts" in capsys.readouterr().out
    iso = _fleet_artifact(tmp_path / "iso.json", isolation="external")
    assert bench_compare.main([str(base), str(iso)]) == 0
    assert "isolation" in capsys.readouterr().out
    tpu = _fleet_artifact(tmp_path / "tpu.json", backend="tpu")
    assert bench_compare.main([str(base), str(tpu)]) == 0
    assert "different backends" in capsys.readouterr().out
    bench = _artifact(tmp_path, "BENCH_r09.json", 10.0)
    assert bench_compare.main([str(base), str(bench)]) == 0
    assert "INCOMPARABLE" in capsys.readouterr().out


def test_bench_history_fleet_columns(tmp_path, capsys):
    """The fleet columns render from committed BENCH_serve_fleet_r*.json
    artifacts: rotation agg/s at the round's largest shard count, the
    count itself, and the recovery-invariants bit."""
    bench_history = _bench_history()
    _artifact(tmp_path, "BENCH_r01.json", 10.0)
    _fleet_artifact(tmp_path / "BENCH_serve_fleet_r02.json",
                    rates={"rotation": {"1": 300.0, "2": 281.25,
                                        "4": 260.0}},
                    shard_counts=(1, 2, 4))

    stats = bench_history.collect_fleet(tmp_path, ["r01", "r02"])
    assert "r01" not in stats
    assert stats["r02"] == {"shards": 4, "rate": 260.0,
                            "recovery_ok": True, "backend": "cpu"}

    rc = bench_history.main(["--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "fleet agg/s" in out and "fleet shards" in out
    r02 = [line for line in out.splitlines() if line.startswith("r02")][0]
    assert r02.split()[-3:] == ["4", "260.000", "1"]
    assert "backend=cpu fleet run" in out
