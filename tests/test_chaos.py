"""Chaos E2E: SIGKILL a live training run (including mid-checkpoint-write),
auto-resume it, and require the concatenated study/eval CSVs to be
bit-identical to an uninterrupted run — closing the reference's documented
"resumed runs are not reproducible" limitation (reference `README.md:105`)
end to end. Plus the in-process divergence-rollback loop
(`--rollback-budget`): non-finite state detection, restore from the last
good checkpoint, CSV truncation, budget exhaustion."""

import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from byzantinemomentum_tpu import checkpoint
from byzantinemomentum_tpu.cli.attack import main
from byzantinemomentum_tpu.engine import RECOVERY_COLUMNS, STUDY_COLUMNS

ROOT = pathlib.Path(__file__).resolve().parent.parent

BASE = ["--seed", "11", "--model", "simples-full",
        "--batch-size", "8", "--batch-size-test", "32",
        "--batch-size-test-reps", "2", "--evaluation-delta", "2",
        "--checkpoint-delta", "2", "--nb-for-study", "11",
        "--nb-for-study-past", "2", "--gar", "median", "--attack", "empire",
        "--attack-args", "factor:1.1", "--nb-real-byz", "4",
        "--nb-steps", "8", "--auto-resume"]


@pytest.fixture(autouse=True)
def small_synth(monkeypatch):
    monkeypatch.setenv("BMT_SYNTH_TRAIN", "512")
    monkeypatch.setenv("BMT_SYNTH_TEST", "128")


def _rows(path):
    return [line for line in path.read_text().split(os.linesep)[1:] if line]


def _strip_recovery(rows):
    """Drop the RECOVERY_COLUMNS tail: the Restarts counter legitimately
    differs between an interrupted and an uninterrupted run — everything
    else must match bit-for-bit."""
    return [row.rsplit("\t", len(RECOVERY_COLUMNS))[0] for row in rows]


# --------------------------------------------------------------------------- #
# Subprocess chaos: real SIGKILL semantics (cannot run in-process)

def _spawn(resdir, **extra_env):
    """One driver subprocess (`--device cpu`: the subprocess does not
    inherit conftest's in-process platform pin)."""
    env = dict(os.environ)
    env.update(BMT_SYNTH_TRAIN="512", BMT_SYNTH_TEST="128",
               JAX_PLATFORMS="cpu")
    env.update({key: str(value) for key, value in extra_env.items()})
    cmd = ([sys.executable, str(ROOT / "attack.py"), "--device", "cpu"]
           + BASE + ["--result-directory", str(resdir)])
    return subprocess.run(cmd, env=env, cwd=str(ROOT), capture_output=True)


@pytest.mark.slow
def test_sigkill_autoresume_is_bit_identical(tmp_path):
    """Kill a run mid-training (SIGKILL — no cleanup, no flush), corrupt
    the newest surviving checkpoint for good measure, auto-resume with the
    SAME command line: the concatenated study/eval output must equal an
    uninterrupted run's, bit for bit (modulo the Restarts counter)."""
    full = tmp_path / "full"
    proc = _spawn(full)
    assert proc.returncode == 0, proc.stderr.decode()

    part = tmp_path / "part"
    proc = _spawn(part, BMT_CHAOS_KILL_AT_STEP=5)
    assert proc.returncode != 0  # died by SIGKILL
    newest = checkpoint.find_latest_valid(part)
    assert newest is not None  # the torn run left checkpoints behind
    # Corrupt the newest valid checkpoint: resume must walk past it to the
    # previous one, not crash on it
    raw = newest.read_bytes()
    newest.write_bytes(raw[:len(raw) // 2])
    survivor = checkpoint.find_latest_valid(part)
    assert survivor is not None and survivor.name != newest.name

    proc = _spawn(part)
    assert proc.returncode == 0, proc.stderr.decode()
    assert _rows(part / "eval") == _rows(full / "eval")
    part_rows = _rows(part / "study")
    assert _strip_recovery(part_rows) == _strip_recovery(_rows(full / "study"))
    # Rows before the resume keep Restarts=0, rows after carry the bump
    restarts = [row.split("\t")[-1] for row in part_rows]
    assert restarts[0] == "0" and restarts[-1] == "1"
    assert set(restarts) == {"0", "1"}
    # Telemetry acceptance: the per-record-flushed timeline survives the
    # SIGKILL, and the resumed process stamps the restart event with the
    # step it restarted from; the heartbeat reflects the completed run
    from byzantinemomentum_tpu import obs
    records = obs.load_records(part)
    restart_events = [r for r in records if r.get("name") == "restart"]
    assert restart_events, "resumed run must stamp a restart event"
    resume_step = restart_events[-1]["data"]["step"]
    assert resume_step == checkpoint.checkpoint_step(survivor)
    assert sum(1 for r in records if r.get("name") == "run_start") == 2
    heartbeat = obs.read_heartbeat(part)
    assert heartbeat["step"] == 8 and heartbeat["status"] == "completed"


@pytest.mark.slow
def test_sigkill_mid_checkpoint_write_is_bit_identical(tmp_path):
    """Die IN THE MIDDLE of a checkpoint write (half the bytes flushed to
    the tmp file): the atomic-rename protocol must leave only intact
    checkpoints under final names, and the resumed output must still be
    bit-identical to the uninterrupted run's."""
    full = tmp_path / "full"
    proc = _spawn(full)
    assert proc.returncode == 0, proc.stderr.decode()

    part = tmp_path / "part"
    proc = _spawn(part, BMT_CHAOS_TORN_CHECKPOINT_STEP=6)
    assert proc.returncode != 0
    # The torn write stayed under the .tmp name; final names all verify
    assert (part / "checkpoint-6.tmp").is_file()
    assert not (part / "checkpoint-6").exists()
    assert checkpoint.find_latest_valid(part).name == "checkpoint-4"

    proc = _spawn(part)
    assert proc.returncode == 0, proc.stderr.decode()
    assert _rows(part / "eval") == _rows(full / "eval")
    assert (_strip_recovery(_rows(part / "study"))
            == _strip_recovery(_rows(full / "study")))


@pytest.mark.slow
def test_jobs_supervisor_resumes_killed_run(tmp_path):
    """The acceptance loop end to end: `Jobs` dispatches a run that gets
    SIGKILLed mid-training, retries it with backoff, and the retry resumes
    from the pending dir's newest valid checkpoint — the final directory
    holds one contiguous bit-exact trajectory."""
    from byzantinemomentum_tpu.utils.jobs import Jobs

    full = tmp_path / "full"
    proc = _spawn(full)
    assert proc.returncode == 0, proc.stderr.decode()

    grid = tmp_path / "grid"
    env_backup = os.environ.get("BMT_CHAOS_KILL_AT_STEP")
    # The kill hook must only fire on the FIRST attempt: arm it through a
    # file the subprocess consumes (env would re-kill every retry)
    os.environ["BMT_CHAOS_KILL_AT_STEP"] = ""
    try:
        script = (
            "import os, pathlib, runpy, sys\n"
            "fuse = pathlib.Path(sys.argv[sys.argv.index("
            "'--result-directory') + 1]).parent / 'fuse'\n"
            "if not fuse.exists():\n"
            "    fuse.write_text('blown')\n"
            "    os.environ['BMT_CHAOS_KILL_AT_STEP'] = '5'\n"
            "else:\n"
            "    os.environ.pop('BMT_CHAOS_KILL_AT_STEP', None)\n"
            "sys.argv = ['attack.py'] + sys.argv[1:]\n"
            f"sys.path.insert(0, {str(ROOT)!r})\n"
            f"runpy.run_path({str(ROOT / 'attack.py')!r}, "
            "run_name='__main__')\n")
        command = [sys.executable, "-c", script, "--device", "cpu"] + BASE[:-1]
        # BASE[:-1] drops --auto-resume: the supervisor appends it itself
        assert command[-1] != "--auto-resume"
        jobs = Jobs(grid, seeds=(11,), max_retries=1, retry_backoff=0)
        # The driver overrides --seed via BASE's "--seed 11"; the Jobs seed
        # suffix only names the run directory
        env = dict(BMT_SYNTH_TRAIN="512", BMT_SYNTH_TEST="128",
                   JAX_PLATFORMS="cpu")
        for key, value in env.items():
            os.environ[key] = value
        jobs.submit("cell", command)
        jobs.wait()
    finally:
        if env_backup is None:
            os.environ.pop("BMT_CHAOS_KILL_AT_STEP", None)
        else:
            os.environ["BMT_CHAOS_KILL_AT_STEP"] = env_backup
    done = grid / "cell-11"
    assert done.is_dir(), list(grid.iterdir())
    assert (grid / "fuse").exists()  # first attempt really was killed
    assert _rows(done / "eval") == _rows(full / "eval")
    assert (_strip_recovery(_rows(done / "study"))
            == _strip_recovery(_rows(full / "study")))


# --------------------------------------------------------------------------- #
# In-process divergence rollback (`--rollback-budget`)

ROLL_BASE = ["--nb-steps", "6", "--batch-size", "8",
             "--batch-size-test", "32", "--batch-size-test-reps", "2",
             "--evaluation-delta", "2", "--checkpoint-delta", "2",
             "--model", "simples-full", "--seed", "11", "--gar", "median",
             "--nb-for-study", "11", "--nb-for-study-past", "2"]


def test_divergence_rollback_recovers(tmp_path, monkeypatch):
    """Parameters poisoned to NaN mid-run (chaos hook): the watchdog rolls
    back to the last good checkpoint, truncates the CSVs, and the run
    completes with one contiguous, finite trajectory; the Rollbacks column
    records the event."""
    monkeypatch.setenv("BMT_CHAOS_NAN_AT_STEP", "3")
    resdir = tmp_path / "roll"
    rc = main(ROLL_BASE + ["--rollback-budget", "2",
                           "--result-directory", str(resdir)])
    assert rc == 0
    rows = _rows(resdir / "study")
    header = (resdir / "study").read_text().split(os.linesep)[0]
    assert header == "# " + "\t".join(STUDY_COLUMNS + RECOVERY_COLUMNS)
    # One contiguous duplicate-free trajectory with finite losses
    assert [row.split("\t")[0] for row in rows] == [str(i) for i in range(6)]
    assert all(np.isfinite(float(row.split("\t")[2])) for row in rows)
    rollbacks = [row.split("\t")[-2] for row in rows]
    assert rollbacks[0] == "0" and rollbacks[-1] == "1"


def test_divergence_rollback_tighten_quorum(tmp_path, monkeypatch):
    """The optional quorum tightening: the rebuild path (f+1, recompiled
    step program) completes the run after a rollback."""
    monkeypatch.setenv("BMT_CHAOS_NAN_AT_STEP", "3")
    resdir = tmp_path / "tight"
    rc = main(ROLL_BASE + ["--rollback-budget", "2",
                           "--rollback-tighten-quorum",
                           "--result-directory", str(resdir)])
    assert rc == 0
    rows = _rows(resdir / "study")
    assert [row.split("\t")[0] for row in rows] == [str(i) for i in range(6)]
    assert all(np.isfinite(float(row.split("\t")[2])) for row in rows)


def test_rollback_budget_exhaustion_fails_the_run(tmp_path, monkeypatch):
    """A run that re-diverges after every rollback gives up once the budget
    is spent, with a FAILING exit code (so a supervisor retries it) — it
    must not spin forever or exit 0 with garbage."""
    monkeypatch.setenv("BMT_CHAOS_NAN_AT_STEP", "1")
    monkeypatch.setenv("BMT_CHAOS_NAN_REPEAT", "1")
    rc = main(ROLL_BASE + ["--rollback-budget", "1",
                           "--result-directory", str(tmp_path / "doom")])
    assert rc == 1


def test_rollback_budget_requires_checkpoints():
    from byzantinemomentum_tpu.cli.attack import (
        _postprocess, process_commandline)
    args = _postprocess(process_commandline(
        ["--rollback-budget", "2", "--nb-steps", "1"]))
    assert args.rollback_budget == 0  # warned + disabled, not fatal


def test_auto_resume_flag_validation(tmp_path):
    from byzantinemomentum_tpu import utils
    with pytest.raises(utils.UserException, match="auto-resume"):
        main(["--auto-resume", "--nb-steps", "1"])
    with pytest.raises(utils.UserException, match="mutually exclusive"):
        main(["--auto-resume", "--load-checkpoint", "x", "--nb-steps", "1",
              "--result-directory", str(tmp_path / "r")])


def test_auto_resume_completed_run_is_idempotent(tmp_path):
    """Re-issuing the same command line over a COMPLETED run resumes at the
    final checkpoint, re-runs only the final milestone, and leaves every
    result file byte-identical — the supervisor can always re-dispatch."""
    resdir = tmp_path / "run"
    argv = ROLL_BASE + ["--nb-steps", "4", "--auto-resume",
                        "--result-directory", str(resdir)]
    assert main(argv) == 0
    before = {name: (resdir / name).read_bytes()
              for name in ("study", "eval")}
    assert main(argv) == 0
    after = {name: (resdir / name).read_bytes()
             for name in ("study", "eval")}
    assert after == before
