"""Aggregation-service tests (`byzantinemomentum_tpu/serve/`): the
two-axis shape-bucket policy, padded-masked correctness against the
direct GAR kernels, the per-rule padded-(n, d)-bucket-vs-exact-cell
bit-equality oracle grid (all 9 first-tier rules, f in {1,2,3}, planted
NaN rows and duplicate-row ties), the warm-loop zero-recompile
acceptance (100+ mixed-cell requests, zero backend compiles), per-client
suspicion verdicts, rejection/telemetry paths, the line-JSON socket
front end, and the load generator's machine-readable artifact."""

import json
import socket

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu import ops, utils
from byzantinemomentum_tpu.analysis import contracts
from byzantinemomentum_tpu.obs.forensics import ClientSuspicionStore
from byzantinemomentum_tpu.obs.heartbeat import read_heartbeat
from byzantinemomentum_tpu.serve import (
    AggregationService, OversizeRequest, D_PAD_EXACT, N_BUCKETS)
from byzantinemomentum_tpu.serve.frontend import AggregationServer
from byzantinemomentum_tpu.serve.programs import (
    Cell, _build, batch_bucket, col_bucket, row_bucket)


def _cohort(n, d, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, d)).astype(np.float32)


# The shared warm service: one per module so program compiles are paid
# once (the jit cache keys on the per-service program closures).
CELLS = (("krum", 11, 2, 32, True), ("median", 5, 1, 32, True),
         ("trmean", 9, 2, 32, False))


@pytest.fixture(scope="module")
def service():
    with AggregationService(max_batch=4, max_delay_ms=2.0) as svc:
        svc.warmup(CELLS)
        yield svc


# --------------------------------------------------------------------------- #
# Shape buckets

def test_row_bucket_policy():
    """EVERY registered rule rounds up the ladder now that the traced
    -count masked kernels are universal; the one exception is brute at an
    infeasible worst-case rank space (the masked enumeration must
    provision `C(bucket, f)` statically), which gets an exact row cell;
    beyond the ladder is an oversize rejection."""
    assert row_bucket("krum", 11) == 16
    assert row_bucket("krum", 16) == 16
    assert row_bucket("native-krum", 3) == 4
    assert row_bucket("median", 33) == 64
    assert row_bucket("bulyan", 11, f=2) == 16   # masked-bucketed since r10
    assert row_bucket("phocas", 11, f=2) == 16
    assert row_bucket("aksel", 5, f=1) == 8
    assert row_bucket("cge", 17, f=1) == 32
    assert row_bucket("brute", 7, f=2) == 8      # C(8, 2) feasible
    assert row_bucket("brute", 40, f=5) == 40    # C(64, 5) > cap: exact
    with pytest.raises(OversizeRequest):
        row_bucket("krum", N_BUCKETS[-1] + 1)
    with pytest.raises(OversizeRequest):
        row_bucket("bulyan", N_BUCKETS[-1] + 1)
    with pytest.raises(utils.UserException):
        row_bucket("krum", 0)


def test_col_bucket_policy():
    """Columns round up the d-ladder (doubling past its top) for every
    rule whose zero-padding proof holds — all of them today — and an
    unproven rule routes to exact-d."""
    assert col_bucket("krum", 17) == 32
    assert col_bucket("bulyan", 128) == 128
    assert col_bucket("brute", 129) == 256
    assert col_bucket("median", 5000) == 8192    # doubling past the ladder
    assert all(D_PAD_EXACT[g] for g in D_PAD_EXACT)  # today: all proven
    with pytest.raises(utils.UserException):
        col_bucket("krum", 0)


def test_col_bucket_unproven_rule_routes_exact(monkeypatch):
    """The registry is load-bearing: a rule whose d-padding proof fails
    must serve exact-d cells."""
    from byzantinemomentum_tpu.serve import programs
    monkeypatch.setitem(programs.D_PAD_EXACT, "krum", False)
    assert col_bucket("krum", 17) == 17
    assert col_bucket("native-krum", 17) == 17


def test_batch_bucket():
    assert [batch_bucket(b, 8) for b in (1, 2, 3, 5, 8)] == [1, 2, 4, 8, 8]
    assert batch_bucket(7, 4) == 4


# --------------------------------------------------------------------------- #
# Padded-masked correctness: the bucket program equals the direct kernel

@pytest.mark.parametrize("gar,n,f", (("krum", 11, 2), ("median", 5, 1),
                                     ("trmean", 9, 2)))
def test_padded_bucket_matches_direct_gar(service, gar, n, f):
    """A request served from a padded bucket aggregates exactly like the
    direct (unpadded) kernel on the submitted rows — the masked-quorum
    variants' contract, end to end through packing and dispatch."""
    G = _cohort(n, 32, seed=n)
    G[1, :4] = np.nan  # quarantine-worthy row rides along
    result = service.aggregate(G, gar=gar, f=f, diagnostics=(gar != "trmean"))
    direct = np.asarray(ops.gars[gar].unchecked(jnp.asarray(G), f=f))
    np.testing.assert_allclose(
        np.nan_to_num(result.aggregate, nan=7e9),
        np.nan_to_num(direct, nan=7e9), rtol=5e-5, atol=5e-6)
    assert result.cell.n_bucket == row_bucket(gar, n)
    assert result.f_eff == f
    assert result.n == n


def test_bulyan_brute_serve_from_padded_buckets(service):
    """The r10 holdout rules (bulyan's stage-1 scan, brute's subset
    enumeration) now serve from padded buckets: bucketed cell, aggregate
    equal to the direct kernel on the submitted rows."""
    for gar, n, f in (("bulyan", 11, 2), ("brute", 9, 2)):
        G = _cohort(n, 32, seed=3)
        result = service.aggregate(G, gar=gar, f=f, diagnostics=False)
        direct = np.asarray(ops.gars[gar].unchecked(jnp.asarray(G), f=f))
        np.testing.assert_allclose(result.aggregate, direct, rtol=5e-5,
                                   atol=5e-6)
        assert result.cell.n_bucket == 16


# --------------------------------------------------------------------------- #
# The tentpole oracle grid: for EVERY first-tier rule, the padded-(n, d)
# bucket program is BIT-identical to the exact cell program — f in
# {1, 2, 3}, with a planted NaN row (within f) and a duplicate-row tie

ALL_GARS = ("average", "median", "trmean", "phocas", "meamed", "krum",
            "bulyan", "aksel", "cge", "brute")


def _run_cell_program(cell, G, n):
    """One request through a cell's compiled program at batch 1."""
    Gp = np.zeros((1, cell.n_bucket, cell.d_bucket), np.float32)
    Gp[0, :n, :G.shape[1]] = G
    active = np.zeros((1, cell.n_bucket), bool)
    active[0, :n] = True
    out = _build(cell)(jax.device_put(Gp), jax.device_put(active))
    return {k: np.asarray(v)[0] for k, v in out.items()}


@pytest.mark.parametrize("gar", ALL_GARS)
@pytest.mark.parametrize("f", (1, 2, 3))
def test_padded_nd_bucket_bit_identical_to_exact_cell(gar, f):
    """The two-axis bucket ladder is exact, not approximate: the padded
    (n-bucket, d-bucket) program and the exact (n, d) cell produce
    bit-identical aggregates, f_eff AND serve aux for every rule —
    including a planted NaN row (worst-case routing) and a duplicated
    row (stable tie-breaking must not read the padding)."""
    n = 4 * f + 3          # satisfies every rule's contract up to f=3
    d = 19                 # off-ladder width -> real column padding
    rng = np.random.default_rng(100 * f + len(gar))
    G = rng.standard_normal((n, d)).astype(np.float32)
    G[1] = G[0]            # duplicate-row tie
    G[-1, :4] = np.nan     # corrupt-but-present row, within f
    exact = _run_cell_program(Cell(gar, n, f, d, True), G, n)
    from byzantinemomentum_tpu.serve.programs import ProgramCache
    bucket_cell = ProgramCache().cell(gar, n, f, d, True)
    assert bucket_cell.n_bucket > n and bucket_cell.d_bucket > d
    padded = _run_cell_program(bucket_cell, G, n)
    for key in exact:
        e = np.asarray(exact[key])
        p = np.asarray(padded[key])
        if key == "aggregate":
            p = p[:d]
        elif p.ndim == 1 and p.shape != e.shape:
            p = p[:n]
        elif p.ndim == 2 and p.shape != e.shape:
            p = p[:n, :n]  # the pairwise matrix of the real rows
        np.testing.assert_array_equal(
            np.nan_to_num(e, nan=7e9, posinf=8e9),
            np.nan_to_num(p, nan=7e9, posinf=8e9),
            err_msg=f"{gar} f={f} output {key!r} not bit-identical "
                    f"across the bucket padding")


def test_brute_infeasible_bucket_serves_exact_row_cell(service):
    """Brute beyond its masked rank-space cap gets an exact row cell —
    the documented routing reason in `serve/programs.py::row_bucket` —
    and still aggregates correctly through the quorum fallback."""
    n, f = 40, 5           # C(64, 5) = 7.6M > MASKED_MAX_SUBSETS
    from byzantinemomentum_tpu.ops import brute as brute_mod
    assert brute_mod.masked_rank_space(64, f) is None
    assert row_bucket("brute", n, f=f) == n


# --------------------------------------------------------------------------- #
# The acceptance criterion: a warm serving loop compiles ZERO new
# programs across >= 100 mixed-cell requests

def test_warm_loop_zero_recompiles_across_mixed_cells(service):
    rng = np.random.default_rng(7)
    group = 10

    def step():
        futures = []
        for k in range(group):
            gar, n, f, d, diag = CELLS[k % len(CELLS)]
            clients = [f"c{i}" for i in range(n)] if diag else None
            futures.append(service.submit(
                rng.standard_normal((n, d)).astype(np.float32), gar=gar,
                f=f, client_ids=clients, diagnostics=diag))
        for fut in futures:
            fut.result(timeout=60)

    observed = contracts.assert_recompile_budget(
        step, steps=11, budget=0,
        label="warm serving loop (110 mixed-cell requests)")
    assert observed == 0
    stats = service.stats()
    assert stats["served"] >= 110
    assert stats["cache"]["hits"] > 0


# --------------------------------------------------------------------------- #
# Suspicion verdicts ride the response

def test_outlier_client_suspicion_rides_response(service):
    rng = np.random.default_rng(11)
    verdicts = None
    for _ in range(15):
        G = rng.standard_normal((11, 32)).astype(np.float32)
        G[0] += 30.0
        clients = ["attacker"] + [f"ok{i}" for i in range(10)]
        verdicts = service.aggregate(G, gar="krum", f=2,
                                     client_ids=clients).verdicts
    assert verdicts["attacker"]["suspicion"] > verdicts["ok0"]["suspicion"]
    assert verdicts["attacker"]["suspect"]
    assert not verdicts["ok0"]["suspect"]
    assert verdicts["attacker"]["observations"] >= 15
    assert "attacker" in service.suspicion.suspects


def test_client_store_hysteresis_and_eviction():
    store = ClientSuspicionStore(alpha=0.5, threshold=0.5, clear=0.2,
                                 min_obs=2, max_clients=3)
    # one client never selected, far away -> suspect after warm-up
    for step in range(6):
        verdicts = store.observe(
            ["bad", "g1", "g2"], selection=[0.0, 1.0, 1.0],
            distances=[50.0, 1.0, 1.1], step=step)
    assert verdicts["bad"]["suspect"]
    # recovery: selected, central -> falls below clear and un-suspects
    for step in range(12):
        verdicts = store.observe(
            ["bad", "g1", "g2"], selection=[1.0, 1.0, 1.0],
            distances=[1.0, 1.0, 1.1], step=10 + step)
    assert not verdicts["bad"]["suspect"]
    # eviction keeps the most recently observed max_clients entries
    store.observe(["d", "e", "f"], selection=[1.0, 1.0, 1.0])
    store.observe(["g", "h"], selection=[1.0, 1.0])
    assert len(store) == 3
    verdict = store.observe(["bad", "x", "y"],
                            selection=[0.0, 1.0, 1.0])["bad"]
    assert verdict["observations"] == 1  # evicted history restarted


def test_client_store_validation():
    with pytest.raises(ValueError):
        ClientSuspicionStore(alpha=0.0)
    with pytest.raises(ValueError):
        ClientSuspicionStore(threshold=0.3, clear=0.4)
    with pytest.raises(ValueError):
        ClientSuspicionStore(max_clients=0)


# --------------------------------------------------------------------------- #
# Rejection paths

def test_oversize_and_invalid_requests_rejected(service):
    with pytest.raises(OversizeRequest):
        service.submit(_cohort(N_BUCKETS[-1] + 1, 8), gar="median", f=1)
    with pytest.raises(utils.UserException):
        service.submit(_cohort(5, 8), gar="no-such-rule", f=1)
    with pytest.raises(utils.UserException):
        service.submit(_cohort(5, 8), gar="krum", f=4)  # krum needs 2f+3
    with pytest.raises(utils.UserException):
        service.submit(np.zeros((3,), np.float32), gar="median", f=1)
    with pytest.raises(utils.UserException):  # ids without diagnostics
        service.submit(_cohort(5, 8), gar="median", f=1,
                       client_ids=["a"] * 5, diagnostics=False)
    with pytest.raises(utils.UserException):  # id/row mismatch
        service.submit(_cohort(5, 8), gar="median", f=1, client_ids=["a"])
    assert service.stats()["rejected"] >= 6


# --------------------------------------------------------------------------- #
# Socket front end

def test_socket_frontend_roundtrip(service):
    with AggregationServer(("127.0.0.1", 0), service) as server:
        server.serve_background()
        with socket.create_connection(("127.0.0.1", server.port),
                                      timeout=10) as conn:
            fd = conn.makefile("rwb")

            def ask(payload):
                fd.write(json.dumps(payload).encode() + b"\n")
                fd.flush()
                return json.loads(fd.readline())

            assert ask({"op": "ping"}) == {"ok": True, "op": "ping"}
            G = _cohort(5, 16, seed=9)
            response = ask({"op": "aggregate", "gar": "median", "f": 1,
                            "vectors": G.tolist(),
                            "clients": [f"s{i}" for i in range(5)]})
            assert response["ok"] and len(response["aggregate"]) == 16
            direct = np.asarray(ops.gars["median"].unchecked(
                jnp.asarray(G), f=1))
            np.testing.assert_allclose(response["aggregate"], direct,
                                       rtol=5e-5, atol=5e-6)
            assert set(response["verdicts"]) == {f"s{i}" for i in range(5)}
            # malformed line answers an error WITHOUT severing the stream
            fd.write(b"this is not json\n")
            fd.flush()
            assert not json.loads(fd.readline())["ok"]
            # bad request (unknown gar) same
            bad = ask({"op": "aggregate", "gar": "nope",
                       "vectors": G.tolist()})
            assert not bad["ok"] and "nope" in bad["error"]
            stats = ask({"op": "stats"})
            assert stats["ok"] and stats["stats"]["served"] >= 1
        server.shutdown()


# --------------------------------------------------------------------------- #
# Heartbeat supervision surface

def test_service_writes_supervisable_heartbeat(tmp_path):
    with AggregationService(max_batch=2, max_delay_ms=1.0,
                            directory=tmp_path,
                            heartbeat_interval=0.05) as svc:
        svc.aggregate(_cohort(5, 8, seed=1), gar="median", f=1,
                      diagnostics=False)
        import time
        deadline = time.monotonic() + 5.0
        beat = None
        while time.monotonic() < deadline:
            beat = read_heartbeat(tmp_path)
            if beat is not None and beat.get("step", 0) >= 1:
                break
            time.sleep(0.05)
    assert beat is not None
    assert beat["status"] == "serving"
    assert beat["step"] >= 1          # the Jobs watchdog's progress field
    assert "queue_depth" in beat
    # telemetry landed in the run directory alongside
    assert (tmp_path / "telemetry.jsonl").exists()


# --------------------------------------------------------------------------- #
# Load generator (smoke scale: mechanics, not measurement)

@pytest.mark.slow
def test_loadgen_smoke_payload(tmp_path):
    import importlib.util
    import pathlib
    import sys
    script = (pathlib.Path(__file__).resolve().parent.parent
              / "scripts" / "serve_loadgen.py")
    spec = importlib.util.spec_from_file_location("serve_loadgen", script)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("serve_loadgen", mod)
    spec.loader.exec_module(mod)
    payload = mod.run_loadgen(requests=40, n=7, d=32, f=1, max_batch=4,
                              max_delay_ms=2.0, repeats=1,
                              hetero_repeats=1)
    assert payload["kind"] == "serve"
    cells = payload["cells"]
    assert set(cells) == {"serve.sequential", "serve.batched",
                          "serve.open_loop", "serve.hetero"}
    for cell in cells.values():
        assert cell["p50_ms"] <= cell["p99_ms"]
        assert cell["agg_per_sec"] > 0
    assert payload["speedup_batched_vs_sequential"] > 0
    assert payload["stats"]["served"] >= 120  # the main phases resolved
    # The r10 heterogeneous workload: >= 4x fewer distinct compiled
    # cells than the per-(n, d) PR 8 policy, zero warm compiles, and the
    # cold phase's compile count matches the distinct program count of
    # its sequential (batch-1) pass
    compiles = payload["compiles"]
    assert compiles["warm_compiles"] == 0
    assert compiles["reduction_vs_per_nd"] >= 4.0
    assert compiles["distinct_cells"] < compiles["per_nd_policy_cells"]
    assert payload["cold_start"]["compiles"] > 0
    assert payload["cold_start"]["p99_ms"] >= payload["cold_start"]["p50_ms"]


# --------------------------------------------------------------------------- #
# Admission control (PR 11): verdicts gate rows at submit time

class _StubStore:
    """A verdict table standing in for the suspicion store in policy
    unit tests."""

    def __init__(self, verdicts):
        self._verdicts = verdicts

    def verdict(self, client):
        return self._verdicts.get(client)


def test_admission_policy_decisions_and_cap():
    from byzantinemomentum_tpu.serve.admission import AdmissionPolicy

    store = _StubStore({
        "bad": {"suspicion": 0.8, "suspect": True, "observations": 20,
                "collusion": 0.1},
        "syb": {"suspicion": 0.2, "suspect": False, "observations": 5,
                "collusion": 0.9},
        "new": {"suspicion": 0.1, "suspect": False, "observations": 1,
                "collusion": 0.0},
    })
    policy = AdmissionPolicy("mask")
    ids = ("bad", "syb", "new", "unseen")
    admitted, flagged = policy.decide(ids, store)
    assert not admitted[0] and not admitted[1]
    assert admitted[2] and admitted[3]
    assert flagged["bad"]["reason"] == "suspect"
    assert flagged["syb"]["reason"] == "collusion"
    # The max_frac cap readmits the WEAKEST evidence first
    capped = AdmissionPolicy("mask", max_frac=0.25)
    admitted, flagged = capped.decide(ids, store)
    assert int((~admitted).sum()) == 1
    assert not admitted[1]  # collusion 0.9 is the strongest evidence
    assert flagged["bad"]["action"] == "readmitted"
    with pytest.raises(ValueError):
        AdmissionPolicy("reject")


def test_admission_downweight_blends_toward_admitted_mean():
    from byzantinemomentum_tpu.serve.admission import AdmissionPolicy

    policy = AdmissionPolicy("downweight", downweight=0.25)
    matrix = np.stack([np.zeros(4, np.float32),
                       np.zeros(4, np.float32),
                       np.full(4, 8.0, np.float32)])
    flagged = {"s0": {"reason": "collusion", "action": "downweight",
                      "suspicion": 0.2, "collusion": 0.9}}
    out = policy.apply(matrix, np.ones(3, bool), flagged,
                       ("h0", "h1", "s0"))
    np.testing.assert_allclose(out[2], np.full(4, 2.0))  # 0.25 * 8
    np.testing.assert_array_equal(out[:2], matrix[:2])


def test_diagnostics_cells_expose_the_distance_matrix():
    program = _build(Cell("median", 8, 1, 32, True))
    G = jnp.zeros((1, 8, 32), jnp.float32)
    out = program(G, jnp.ones((1, 8), bool))
    assert out["dist"].shape == (1, 8, 8)


def test_store_collusion_channel_and_readonly_verdict():
    from byzantinemomentum_tpu.serve.admission import ADMISSION_WEIGHTS

    store = ClientSuspicionStore(weights=ADMISSION_WEIGHTS, min_obs=3,
                                 alpha=0.2)
    dist = np.full((4, 4), 10.0)
    np.fill_diagonal(dist, np.inf)
    dist[2, 3] = dist[3, 2] = 0.05
    ids = ("h0", "h1", "s0", "s1")
    for _ in range(6):
        verdicts = store.observe(ids, np.ones(4), dist=dist)
    assert verdicts["s0"]["collusion"] > 0.4
    assert verdicts["h0"]["collusion"] == 0.0
    # Same-client near-duplicates are NOT collusion evidence
    solo = ClientSuspicionStore(weights=ADMISSION_WEIGHTS)
    v = solo.observe(("h0", "h1", "same", "same"), np.ones(4), dist=dist)
    assert v["same"]["collusion"] == 0.0
    # The admission peek never advances observation counts
    before = store.verdict("s0")["observations"]
    store.verdict("s0")
    assert store.verdict("s0")["observations"] == before
    assert store.verdict("unknown") is None


def test_admission_masks_suspects_and_counts(tmp_path):
    """End-to-end: a client the store distrusts gets its rows masked out
    (f_eff recomputes), the rejection counters tick, and the provenance
    rides the response."""
    with AggregationService(max_batch=1, max_delay_ms=0.5,
                            suspicion={"alpha": 0.25},
                            admission={"mode": "mask",
                                       "collusion_min_obs": 2}) as svc:
        rng = np.random.default_rng(0)
        ids = tuple(f"h{i}" for i in range(6)) + ("s0", "s1")
        result = None
        for _ in range(8):
            matrix = rng.standard_normal((8, 32)).astype(np.float32)
            # s0/s1 submit the same vector: a cross-client duplicate
            matrix[7] = matrix[6]
            result = svc.aggregate(matrix, gar="median", f=2,
                                   client_ids=ids, timeout=30.0)
        assert result.admission and set(result.admission) == {"s0", "s1"}
        assert all(a["action"] == "mask"
                   for a in result.admission.values())
        assert result.f_eff == 2  # 6 active rows keep the declared f
        stats = svc.stats()
        assert stats["admission"]["enabled"]
        assert stats["admission"]["masked_rows"] >= 2


def test_sybil_regression_pair():
    """The Sybil split attack slips past per-client thresholds with
    admission OFF (sustained aggregate shift, nobody suspect by the
    blended per-client score alone crossing into masking) and is caught
    with admission ON (tail shift collapses, every sybil id masked, no
    honest collateral)."""
    from byzantinemomentum_tpu.arena.sybil import run_sybil_cell

    off = run_sybil_cell(gar="krum", admission=False, requests=18, seed=0)
    on = run_sybil_cell(gar="krum", admission=True, requests=18, seed=0)
    assert off["masked_rows_total"] == 0
    assert off["agg_shift_tail"] > 1.0          # the attack lands
    assert on["agg_shift_tail"] < off["agg_shift_tail"] / 2
    assert on["detection_rate"] >= 0.8
    assert on["honest_masked"] == 0 and on["honest_flagged"] == 0
    assert on["masked_rows_total"] > 0
