#!/usr/bin/env python3
"""Reproduce the appendix WideResNet-28-10 grid
(reference `reproduce-appendix.py`; same constants,
reference `reproduce-appendix.py:122-158`): CIFAR-10, n=11, f in {4, 2},
batch 20, crossentropy, piecewise lr schedule, Nesterov momentum 0.99,
20000 steps, GARs krum/median/bulyan.

Usage mirrors `reproduce.py` (idempotent result directories, `--devices`,
`--supercharge`).
"""

import argparse
import pathlib
import signal
import sys

from byzantinemomentum_tpu import utils
from byzantinemomentum_tpu.utils.jobs import DEFAULT_SEEDS, Jobs, dict_to_cmdlist

GARS = ("krum", "median", "bulyan")
ATTACKS = (("little", ("factor:1.5", "negative:True")),
           ("empire", "factor:1.1"))

ATTACK_PY = str(pathlib.Path(__file__).resolve().parent / "attack.py")


def make_command(params):
    return [sys.executable, ATTACK_PY] + dict_to_cmdlist(params)


def submit(jobs):
    base = {
        "batch-size": 20,
        "model": "wide_resnet-Wide_ResNet",
        "model-args": ("depth:28", "widen_factor:10", "dropout_rate:0.3",
                       "num_classes:10"),
        "learning-rate-schedule": "0.02,8000,0.004,16000,0.0008",
        "gradient-clip": 5, "loss": "crossentropy", "momentum": 0.99,
        "momentum-nesterov": True, "l2-regularize": 5e-4,
        "evaluation-delta": 100, "nb-steps": 20000, "nb-for-study": 1,
        "nb-for-study-past": 1, "nb-workers": 11,
    }
    for ds in ("cifar10",):
        for f, fm in ((4, 1), (2, 0)):
            params = dict(base, dataset=ds)
            params["nb-workers"] = base["nb-workers"] - f
            jobs.submit(f"{ds}-average-n_{params['nb-workers']}-lr_pow-nesterov",
                        make_command(params))
            for gar in GARS[:len(GARS) - fm]:
                for attack, attargs in ATTACKS:
                    for momentum in ("update", "worker"):
                        params = dict(base, dataset=ds)
                        params["nb-decl-byz"] = f
                        params["nb-real-byz"] = f
                        params["gar"] = gar
                        params["attack"] = attack
                        params["attack-args"] = attargs
                        params["momentum-at"] = momentum
                        jobs.submit(
                            f"{ds}-{attack}-{gar}-f_{f}-lr_pow"
                            f"-at_{momentum}-nesterov",
                            make_command(params))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--data-directory", type=str,
                        default="results-data-appendix")
    parser.add_argument("--plot-directory", type=str,
                        default="results-plot-appendix")
    parser.add_argument("--devices", type=str, default="auto")
    parser.add_argument("--supercharge", type=int, default=1)
    args = parser.parse_args()

    exit_trigger, exit_is_requested = utils.onetime(None)
    signal.signal(signal.SIGINT, lambda *_: exit_trigger())
    signal.signal(signal.SIGTERM, lambda *_: exit_trigger())

    jobs = Jobs(pathlib.Path(args.data_directory),
                devices=args.devices.split(","),
                supercharge=args.supercharge, seeds=DEFAULT_SEEDS)
    with utils.Context("experiments", "info"):
        submit(jobs)
        jobs.wait(exit_is_requested)

    # Same data-driven analysis/plots as the main grid (the reference's
    # appendix plotting loops, `reproduce-appendix.py:160-354`, are the
    # reproduce.py ones with 'lr_pow' name tokens — `analyze` derives its
    # groups from the result dirs, so it covers both)
    if not exit_is_requested():
        from reproduce import analyze
        analyze(pathlib.Path(args.data_directory),
                pathlib.Path(args.plot_directory))


if __name__ == "__main__":
    main()
