#!/usr/bin/env python3
"""Headline benchmark — prints ONE JSON line.

North-star metric (BASELINE.json): simulated-distributed steps/sec on the
CIFAR-10 configuration n=25, f=11, Bulyan vs empire(1.1), empire-cnn,
batch 50, momentum 0.99 at update, clip 5, with the full 24-column study
pipeline on (matching how the reference's `reproduce.py` actually runs its
grid, reference `reproduce.py:165-209`).

`vs_baseline` divides by the PyTorch-CPU steps/sec of the reference-style
loop measured by `scripts/measure_torch_baseline.py` (recorded in
`BASELINE_MEASURED.json`; the reference itself cannot run here — it imports
torchvision, which is absent).
"""

import json
import os
import pathlib
import time

# Keep the synthetic fallback light: the benchmark needs batches, not epochs
os.environ.setdefault("BMT_SYNTH_TRAIN", "5000")
os.environ.setdefault("BMT_SYNTH_TEST", "500")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from byzantinemomentum_tpu import attacks, data, losses, models, ops  # noqa: E402
from byzantinemomentum_tpu.engine import EngineConfig, build_engine  # noqa: E402

N_WORKERS = 25
F = 11
BATCH = 50
WARMUP_STEPS = 2
MIN_MEASURE_S = 5.0
MAX_MEASURE_STEPS = 200


def main():
    cfg = EngineConfig(
        nb_workers=N_WORKERS, nb_decl_byz=F, nb_real_byz=F,
        nb_for_study=N_WORKERS, nb_for_study_past=1,
        momentum=0.99, momentum_at="update", gradient_clip=5.0)
    model_def = models.build("empire-cnn")
    engine = build_engine(
        cfg=cfg, model_def=model_def, loss=losses.Loss("nll"),
        criterion=losses.Criterion("top-k"),
        defenses=[(ops.gars["bulyan"], 1.0, {})],
        attack=attacks.attacks["empire"], attack_kwargs={"factor": 1.1})

    state = engine.init(jax.random.PRNGKey(0))
    trainset, _ = data.make_datasets("cifar10", BATCH, BATCH, seed=0)
    from byzantinemomentum_tpu.data.device import DeviceData
    train_data = DeviceData(trainset)
    engine.attach_data(train_data)
    S = cfg.nb_sampled
    lr = jnp.float32(0.01)

    def batches():
        idx, flips = train_data.sample_indices(S)
        return jnp.asarray(idx), jnp.asarray(flips)

    for _ in range(WARMUP_STEPS):
        idx, flips = batches()
        state, metrics = engine.train_step_indexed(state, idx, flips, lr)
    jax.block_until_ready(state.theta)

    steps = 0
    start = time.monotonic()
    while True:
        idx, flips = batches()
        state, metrics = engine.train_step_indexed(state, idx, flips, lr)
        steps += 1
        if steps >= MAX_MEASURE_STEPS:
            break
        if steps % 5 == 0:
            jax.block_until_ready(state.theta)
            if time.monotonic() - start >= MIN_MEASURE_S:
                break
    jax.block_until_ready(state.theta)
    elapsed = time.monotonic() - start
    steps_per_sec = steps / elapsed

    baseline_path = pathlib.Path(__file__).resolve().parent / "BASELINE_MEASURED.json"
    vs_baseline = None
    if baseline_path.is_file():
        baseline = json.loads(baseline_path.read_text())
        ref = baseline.get("torch_cpu_steps_per_sec")
        if ref:
            vs_baseline = steps_per_sec / ref

    print(json.dumps({
        "metric": "sim_steps_per_sec_cifar10_n25_f11_bulyan",
        "value": steps_per_sec,
        "unit": "steps/s",
        "vs_baseline": vs_baseline,
    }))


if __name__ == "__main__":
    main()
