#!/usr/bin/env python3
"""Headline benchmark — prints ONE JSON line.

North-star metric (BASELINE.json): simulated-distributed steps/sec on the
CIFAR-10 configuration n=25, f=5, Bulyan vs empire(1.1), empire-cnn,
batch 50, momentum 0.99 at update, clip 5, nb-for-study=1, with the full
24-column study pipeline on (the reference's `reproduce.py` CIFAR grid runs
exactly this cell — f=5 is the largest f for which Bulyan's n >= 4f+3
constraint holds at n=25, and the grid excludes Bulyan at f=11; reference
`reproduce.py:165-209`, `aggregators/bulyan.py:102-117`; see BASELINE.md's
correction note for why the r01 metric name said f=11).

Two modes are measured: default f32, and TPU mixed precision
(`--compute-dtype bfloat16`: bf16 forward/backward on the MXU, f32 master
weights/momentum/GAR space). The headline `value` is the faster mode;
per-mode numbers, FLOPs/step (XLA `cost_analysis`) and MFU (vs the chip's
bf16 peak) ride along in the same JSON line.

Companion cells (same JSON line, `cells` object):
- `krum_f11`: n=25, f=11, Krum — the valid carrier of the f=11 column
  (coordinate-wise/Krum rules only need n >= 2f+3).
- `wrn28x10`: the appendix model (`reproduce-appendix.py` grid shape:
  WRN-28-10, n=11, f=2, batch 20, crossentropy, Nesterov momentum), f32 and
  bf16-mixed.

Both sides validate the GAR constraint up front and assert a finite defense
gradient every measured step, so a degenerate (NaN) run cannot be timed.

`vs_baseline` divides by the PyTorch-CPU steps/sec of the reference-style
loop measured by `scripts/measure_torch_baseline.py` (recorded in
`BASELINE_MEASURED.json`; the reference itself cannot run here — it imports
torchvision, which is absent).

A failed accelerator-backend init (down TPU tunnel: "Unable to initialize
backend ... UNAVAILABLE", the BENCH_r05.json crash) falls back to the CPU
backend with a `"backend": "cpu-fallback"` marker in the JSON, so the
artifact stays parseable instead of the run exiting 1.
"""

import json
import os
import pathlib
import time

# Keep the synthetic fallback light: the benchmark needs batches, not epochs
os.environ.setdefault("BMT_SYNTH_TRAIN", "5000")
os.environ.setdefault("BMT_SYNTH_TEST", "500")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from byzantinemomentum_tpu import attacks, data, losses, models, ops  # noqa: E402
from byzantinemomentum_tpu.engine import EngineConfig, build_engine  # noqa: E402
# Peak-FLOPs table and cost_analysis extraction live in obs/perf.py now
# (shared with the driver's telemetry MFU gauge)
from byzantinemomentum_tpu.obs.perf import flops_of_compiled, peak_flops  # noqa: E402

N_WORKERS = 25
F = 5
BATCH = 50
WARMUP_STEPS = 2
MIN_MEASURE_S = 5.0
MAX_MEASURE_STEPS = 400
STEPS_PER_PROGRAM = 20  # the driver's fused-dispatch path (lax.scan of steps)


def _peak_flops():
    kind = jax.devices()[0].device_kind.lower()
    return peak_flops(kind), kind


def _probe_backend():
    """Fail fast on every path a down TPU tunnel can surface on: the
    device enumeration (`jax.devices()`, the BENCH_r05-era probe) AND the
    first dispatch. BENCH_r05 proved the probe alone is not enough — its
    `jax.devices()` answered while the first `device_put` then resolved
    the default backend via `xla_bridge.local_devices()` and raised the
    UNAVAILABLE there, exiting 1 anyway. `jax.device_put` walks exactly
    that `get_default_device -> local_devices` path."""
    jax.devices()
    jax.device_put(np.zeros((1,), np.float32))


def _ensure_backend():
    """Probe the configured backend; on an init failure (e.g. the
    "Unable to initialize backend ... UNAVAILABLE" crash a down TPU tunnel
    produces — see BENCH_r05.json) fall back to the CPU backend so the
    benchmark still yields a parseable JSON line with a
    `"backend": "cpu-fallback"` marker instead of exiting 1. The probe
    covers both the enumeration path and the first-dispatch path (the
    BENCH_r05 crash raised at `device_put`, after `jax.devices()` had
    already answered).

    Returns "default" or "cpu-fallback"; re-raises when even the CPU
    fallback cannot initialize (nothing left to measure on)."""
    try:
        _probe_backend()
        return "default"
    except RuntimeError as err:
        message = str(err)
        if "nitialize backend" not in message and "UNAVAILABLE" not in message:
            raise
        print(f"bench: backend init failed ({message.splitlines()[0]}); "
              f"falling back to CPU", flush=True)
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        # jax_platforms carries an update hook that clears cached backends,
        # so flipping it after a failed init retries cleanly
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    _probe_backend()  # still broken -> raise: nothing left to measure on
    return "cpu-fallback"


def _run_mode(compute_dtype, train_data, *, gar_name="bulyan", n=N_WORKERS,
              f=F, model="empire-cnn", model_args=None, loss="nll",
              nesterov=False, windows=2, min_measure_s=MIN_MEASURE_S,
              flops_hint=None):
    """Build + time one (cell, precision mode); returns (steps/s, flops/step).

    `flops_hint`: reuse a FLOP count already computed for this cell (the
    logical FLOPs are mode-independent to <0.1%, and each computation costs
    a full throwaway compile — see below)."""
    gar = ops.gars[gar_name]
    message = gar.check(gradients=jnp.zeros((n, 1)), f=f)
    if message is not None:
        raise SystemExit(f"Invalid benchmark configuration: {message}")

    cfg = EngineConfig(
        nb_workers=n, nb_decl_byz=f, nb_real_byz=f,
        nb_for_study=1, nb_for_study_past=1,
        momentum=0.99, momentum_at="update", nesterov=nesterov,
        gradient_clip=5.0, compute_dtype=compute_dtype)
    model_def = models.build(model, **(model_args or {}))
    engine = build_engine(
        cfg=cfg, model_def=model_def, loss=losses.Loss(loss),
        criterion=losses.Criterion("top-k"),
        defenses=[(gar, 1.0, {})],
        attack=attacks.attacks["empire"], attack_kwargs={"factor": 1.1})

    state = engine.init(jax.random.PRNGKey(0))
    engine.attach_data(train_data)
    S = cfg.nb_sampled
    M = STEPS_PER_PROGRAM
    lrs = jnp.full((M,), 0.01, jnp.float32)

    def batches():
        idx, flips = train_data.sample_indices(S * M)
        return (jnp.asarray(idx.reshape((M, S) + idx.shape[1:])),
                jnp.asarray(flips.reshape((M, S) + flips.shape[1:])))

    # LOGICAL FLOPs of the step, before any donation invalidates the sample
    # state (lowering only inspects avals). Counted on a throwaway jit of
    # the program with worker packing disabled: the packed convs carry
    # block-diagonal zero blocks whose FLOPs XLA's cost_analysis would
    # count (~1.6x inflation on the headline cell), and MFU must divide by
    # the algorithm's work, not the padding's. The throwaway jit has its
    # own cache, so the measured (packed) program is untouched.
    flops = flops_hint
    if flops is None:
        try:
            idx0, flips0 = batches()
            prior = os.environ.get("BMT_NO_WORKER_PACK")
            os.environ["BMT_NO_WORKER_PACK"] = "1"
            try:
                unpacked = jax.jit(
                    lambda st, i, fl, l: engine._train_multi_indexed(
                        st, i, fl, l))
                compiled = unpacked.lower(state, idx0, flips0, lrs).compile()
            finally:
                # Restore (not pop): a user-set kill switch must survive
                # into the measured traces (the A/B workflow)
                if prior is None:
                    os.environ.pop("BMT_NO_WORKER_PACK", None)
                else:
                    os.environ["BMT_NO_WORKER_PACK"] = prior
            # XLA cost_analysis counts a lax.scan body ONCE (verified:
            # the M-step program reports the same flops as the
            # single-step one), so this is already per-step
            flops = flops_of_compiled(compiled)
        except Exception:
            pass

    for _ in range(WARMUP_STEPS):
        idx, flips = batches()
        state, metrics = engine.train_multi_indexed(state, idx, flips, lrs)
    # Sync via a tiny host transfer: on tunneled backends
    # `block_until_ready` can return before execution has actually finished,
    # while a device->host copy of the (M,)-sized metrics cannot
    np.asarray(metrics["Defense gradient norm"])

    # Multiple measurement windows, best-of taken: the remote-TPU tunnel's
    # throughput varies ±10-30% between windows, and the benchmark's job is
    # to report the hardware's capability, not the tunnel's mood.
    best = 0.0
    for _ in range(windows):
        steps = 0
        # Defense-norm device arrays are collected without syncing (so
        # dispatch stays pipelined) and checked after the timed loop — every
        # measured step is asserted finite, ruling out timing a degenerate
        # (NaN) run.
        defense_norms = []
        pending = []
        start = time.monotonic()
        while True:
            idx, flips = batches()
            state, metrics = engine.train_multi_indexed(state, idx, flips, lrs)
            pending.append(metrics["Defense gradient norm"])  # (M,)
            steps += M
            if steps >= MAX_MEASURE_STEPS:
                break
            # Depth-2 pipeline: sync the PREVIOUS chunk's metrics while the
            # just-dispatched chunk executes, so the device never idles
            # waiting on the host round trip (on tunneled backends a sync is
            # a ~100 ms round trip, and `block_until_ready` can return
            # before execution has finished — the (M,)-sized host transfer
            # below is the reliable sync). The wall-clock check only sees
            # executed steps: every synced chunk gates the clock read.
            if len(pending) >= 2:
                defense_norms.append(np.asarray(pending.pop(0), np.float32))
                if time.monotonic() - start >= min_measure_s:
                    break
        defense_norms.extend(np.asarray(p, np.float32) for p in pending)
        elapsed = time.monotonic() - start

        norms = np.concatenate(defense_norms)
        if not np.isfinite(norms).all():
            bad = int(np.argmax(~np.isfinite(norms)))
            raise SystemExit(
                f"Non-finite defense gradient at measured step {bad} "
                f"({gar_name}, compute_dtype={compute_dtype}): the benchmark "
                f"timed a degenerate run")
        best = max(best, steps / elapsed)
    return best, flops


def main():
    backend = _ensure_backend()
    trainset, _ = data.make_datasets("cifar10", BATCH, BATCH, seed=0)
    from byzantinemomentum_tpu.data.device import DeviceData
    train_data = DeviceData(trainset)
    # Data provenance rides in the JSON itself (throughput is
    # pixel-independent, but the artifact must say what it ran on)
    synthetic = bool(trainset.synthetic)

    sps_f32, flops_f32 = _run_mode(None, train_data)
    sps_bf16, flops_bf16 = _run_mode("bfloat16", train_data,
                                     flops_hint=flops_f32)

    if sps_bf16 > sps_f32:
        headline, mode = sps_bf16, "bf16-mixed"
    else:
        headline, mode = sps_f32, "f32"
    # Identical per mode (flops_hint) — but the f32 cost_analysis can fail
    # (falling back to None) while the bf16 pass succeeds; either count
    # keeps the MFU headline alive
    flops = flops_f32 or flops_bf16
    peak, device_kind = _peak_flops()
    mfu = (flops * headline / peak) if (flops and peak) else None

    # Companion cells (shorter windows; recorded, not the headline).
    cells = {}
    krum_f32, krum_flops32 = _run_mode(None, train_data, gar_name="krum",
                                       f=11, windows=1, min_measure_s=2.5)
    krum_bf16, krum_flops16 = _run_mode("bfloat16", train_data,
                                        gar_name="krum", f=11,
                                        windows=1, min_measure_s=2.5,
                                        flops_hint=krum_flops32)
    krum_best = max(krum_f32, krum_bf16)
    # flops_hint makes the per-mode counts identical by construction;
    # either survives the other's cost_analysis failure
    krum_flops = krum_flops32 or krum_flops16
    cells["krum_f11"] = {
        "steps_per_sec_f32": krum_f32,
        "steps_per_sec_bf16_mixed": krum_bf16,
        "flops_per_step": krum_flops,
        "mfu": (krum_flops * krum_best / peak) if (krum_flops and peak) else None,
        "n": N_WORKERS, "f": 11, "gar": "krum", "batch": BATCH,
        "synthetic_data": synthetic,
    }

    wrn_train, _ = data.make_datasets("cifar10", 20, 20, seed=0)
    wrn_data = DeviceData(wrn_train)
    wrn_kw = dict(gar_name="bulyan", n=11, f=2,
                  model="wide_resnet-Wide_ResNet",
                  model_args={"depth": 28, "widen_factor": 10,
                              "dropout_rate": 0.3, "num_classes": 10},
                  loss="crossentropy", nesterov=True,
                  windows=1, min_measure_s=2.5)
    wrn_f32, wrn_flops32 = _run_mode(None, wrn_data, **wrn_kw)
    wrn_bf16, wrn_flops16 = _run_mode("bfloat16", wrn_data,
                                      flops_hint=wrn_flops32, **wrn_kw)
    wrn_best = max(wrn_f32, wrn_bf16)
    # identical per mode (flops_hint); either survives a failed analysis
    wrn_flops = wrn_flops32 or wrn_flops16
    cells["wrn28x10"] = {
        "steps_per_sec_f32": wrn_f32,
        "steps_per_sec_bf16_mixed": wrn_bf16,
        "flops_per_step": wrn_flops,
        "mfu": (wrn_flops * wrn_best / peak) if (wrn_flops and peak) else None,
        "n": 11, "f": 2, "gar": "bulyan", "batch": 20,
        "synthetic_data": bool(wrn_train.synthetic),
    }

    baseline_path = pathlib.Path(__file__).resolve().parent / "BASELINE_MEASURED.json"
    vs_baseline = None
    if baseline_path.is_file():
        baseline = json.loads(baseline_path.read_text())
        ref = baseline.get("torch_cpu_steps_per_sec")
        if ref:
            vs_baseline = headline / ref

    payload = {
        "metric": "sim_steps_per_sec_cifar10_n25_f5_bulyan",
        "value": headline,
        "unit": "steps/s",
        "vs_baseline": vs_baseline,
        "mode": mode,
        "steps_per_sec_f32": sps_f32,
        "steps_per_sec_bf16_mixed": sps_bf16,
        "flops_per_step": flops,
        "mfu": mfu,
        "backend": backend,
        "device_kind": device_kind,
        "synthetic_data": synthetic,
        "cells": cells,
    }
    # Machine-readable sibling of the harness's stdout-tail BENCH_r*.json
    # wrapper: the per-cell trajectory tooling (scripts/bench_history.py)
    # reads this directly instead of re-parsing captured stdout
    cells_path = pathlib.Path(__file__).resolve().parent / "BENCH_cells.json"
    cells_path.write_text(json.dumps(payload, indent=2) + "\n")
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
