#!/usr/bin/env python3
"""Reproduce the paper's experiment grid: schedule every (dataset, GAR,
attack, f, lr, momentum-at, nesterov, seed) driver run, then analyze and
plot (reference `reproduce.py`; same grid constants,
reference `reproduce.py:109-213`).

Usage:
  python3 reproduce.py [--data-directory results-data]
                       [--plot-directory results-plot]
                       [--devices auto[,auto...]] [--supercharge N]
                       [--subset smoke|faults|mnist|cifar|all]

The grid is idempotent: completed result directories are skipped, failed
ones are kept as `<name>.failed` (reference `tools/jobs.py:126-146`).
`--subset smoke` runs a tiny 2-run sanity grid (not part of the paper);
`--subset faults` runs the scheduled fault-plan grid (generated
`FaultPlan`s at increasing rates) and renders the per-run degradation
timelines plus the cross-run fault-rate sweep.
"""

import argparse
import pathlib
import signal
import sys

from byzantinemomentum_tpu import utils
from byzantinemomentum_tpu.utils.jobs import DEFAULT_SEEDS, Jobs, dict_to_cmdlist

# The paper's GAR list (reference `reproduce.py:109`)
GARS = ("krum", "median", "trmean", "phocas", "meamed", "bulyan")
# The paper's attacks (reference `reproduce.py:151`)
ATTACKS = (("little", ("factor:1.5", "negative:True")),
           ("empire", "factor:1.1"))

ATTACK_PY = str(pathlib.Path(__file__).resolve().parent / "attack.py")


def make_command(params):
    return [sys.executable, ATTACK_PY] + dict_to_cmdlist(params)


def submit_mnist(jobs):
    """(Fashion-)MNIST grid (reference `reproduce.py:121-162`)."""
    base = {
        "batch-size": 83, "model": "simples-full", "loss": "nll",
        "learning-rate-decay-delta": 300, "momentum": 0.9,
        "l2-regularize": 1e-4, "evaluation-delta": 5, "gradient-clip": 2,
        "nb-steps": 300, "nb-for-study": 1, "nb-for-study-past": 150,
        "nb-workers": 51,
    }
    for ds in ("mnist", "fashionmnist"):
        for f, fm in ((24, 1), (12, 0)):
            for lr in (0.5, 0.02):
                for nesterov in (False, True):
                    suffix = "-nesterov" if nesterov else ""
                    params = dict(base, dataset=ds)
                    params["nb-workers"] = base["nb-workers"] - f
                    params["learning-rate"] = lr
                    params["momentum-nesterov"] = nesterov
                    jobs.submit(
                        f"{ds}-average-n_{params['nb-workers']}-lr_{lr}{suffix}",
                        make_command(params))
                    for gar in GARS[:len(GARS) - fm]:
                        for attack, attargs in ATTACKS:
                            for momentum in ("update", "worker"):
                                params = dict(base, dataset=ds)
                                params["learning-rate"] = lr
                                params["nb-decl-byz"] = f
                                params["nb-real-byz"] = f
                                params["gar"] = gar
                                params["attack"] = attack
                                params["attack-args"] = attargs
                                params["momentum-at"] = momentum
                                params["momentum-nesterov"] = nesterov
                                jobs.submit(
                                    f"{ds}-{attack}-{gar}-f_{f}-lr_{lr}"
                                    f"-at_{momentum}{suffix}",
                                    make_command(params))


def submit_cifar(jobs):
    """CIFAR-10/100 grid (reference `reproduce.py:164-209`)."""
    base = {
        "batch-size": 50, "model": "empire-cnn", "loss": "nll",
        "learning-rate-decay": 167, "momentum": 0.99, "l2-regularize": 1e-2,
        "evaluation-delta": 100, "gradient-clip": 5, "nb-steps": 3000,
        "nb-for-study": 1, "nb-for-study-past": 25, "nb-workers": 25,
    }
    for ds, mp in (("cifar10", "cifar100:False"), ("cifar100", "cifar100:True")):
        for f, fm in ((11, 1), (5, 0)):
            for lr, dd in ((0.01, 1500), (0.001, 3000)):
                for nesterov in (False, True):
                    suffix = "-nesterov" if nesterov else ""
                    params = dict(base, dataset=ds)
                    params["model-args"] = mp
                    params["nb-workers"] = base["nb-workers"] - f
                    params["learning-rate"] = lr
                    params["learning-rate-decay-delta"] = dd
                    params["momentum-nesterov"] = nesterov
                    jobs.submit(
                        f"{ds}-average-n_{params['nb-workers']}-lr_{lr}{suffix}",
                        make_command(params))
                    for gar in GARS[:len(GARS) - fm]:
                        for attack, attargs in ATTACKS:
                            for momentum in ("update", "worker"):
                                params = dict(base, dataset=ds)
                                params["model-args"] = mp
                                params["learning-rate"] = lr
                                params["learning-rate-decay-delta"] = dd
                                params["nb-decl-byz"] = f
                                params["nb-real-byz"] = f
                                params["gar"] = gar
                                params["attack"] = attack
                                params["attack-args"] = attargs
                                params["momentum-at"] = momentum
                                params["momentum-nesterov"] = nesterov
                                jobs.submit(
                                    f"{ds}-{attack}-{gar}-f_{f}-lr_{lr}"
                                    f"-at_{momentum}{suffix}",
                                    make_command(params))


# Scheduled fault-plan grid (ROADMAP open item: wire the PR 2
# `fault_timeline`/`fault_rate_sweep` study stubs into the pipeline):
# per-worker-per-step probabilities of the deterministic chaos kinds, one
# run per rate, plus the rate-0 baseline. Plans are generated once into
# `<data-dir>/fault-plans/` (seeded: byte-identical JSON per rerun).
FAULT_RATES = (0.0, 0.005, 0.01, 0.02)


def submit_faults(jobs, data_dir):
    """Fault-resilience grid: the smoke-scale MNIST config under krum with
    generated fault plans of increasing rate — no Byzantine attack, so
    what the sweep isolates is the system-fault degradation policy
    (dynamic quorum, NaN-quarantine). The analysis stage renders each
    run's `fault_timeline` and the cross-run `fault_rate_sweep`."""
    from byzantinemomentum_tpu.faults import FaultPlan

    base = {
        "batch-size": 16, "model": "simples-full", "loss": "nll",
        "momentum": 0.9, "evaluation-delta": 10, "nb-steps": 30,
        "nb-for-study": 9, "nb-for-study-past": 3, "nb-workers": 9,
        "batch-size-test": 32, "batch-size-test-reps": 2,
        "learning-rate": 0.5, "gar": "krum", "nb-decl-byz": 2,
    }
    plan_dir = data_dir / "fault-plans"
    plan_dir.mkdir(parents=True, exist_ok=True)
    for rate in FAULT_RATES:
        params = dict(base)
        if rate > 0.0:
            plan = FaultPlan.generate(
                nb_workers=base["nb-workers"], nb_steps=base["nb-steps"],
                rates={"straggler": rate, "drop_worker": rate,
                       "corrupt_gradient": rate / 2},
                seed=int(rate * 10000))
            plan_path = plan_dir / f"rate_{rate}.json"
            plan.save(plan_path)
            params["fault-plan"] = str(plan_path)
        jobs.submit(f"mnist-faults-krum-r_{rate}", make_command(params))


def submit_smoke(jobs):
    """Tiny sanity grid (non-paper) to validate the pipeline end-to-end,
    incl. the analysis: names follow the full-grid convention so the bucket
    statistics and comparison plots exercise on it."""
    base = {
        "batch-size": 16, "model": "simples-full", "loss": "nll",
        "momentum": 0.9, "evaluation-delta": 2, "nb-steps": 4,
        "nb-for-study": 9, "nb-for-study-past": 3, "nb-workers": 9,
        "batch-size-test": 32, "batch-size-test-reps": 2,
        "learning-rate": 0.5,
        # Flight recorder on: the smoke grid exercises the health columns
        # end to end and the analysis stage renders the variance-envelope
        # and health-timeline plots off them
        "health": True,
    }
    f = 2
    params = dict(base)
    params["nb-workers"] = base["nb-workers"] - f
    params["nb-for-study"] = params["nb-workers"]
    jobs.submit(f"mnist-average-n_{params['nb-workers']}-lr_0.5",
                make_command(dict(params, dataset="mnist")))
    for gar in ("median", "krum"):
        for momentum in ("update", "worker"):
            params = dict(base, dataset="mnist", gar=gar)
            params["nb-decl-byz"] = f
            params["nb-real-byz"] = f
            params["attack"] = "empire"
            params["attack-args"] = "factor:1.1"
            params["momentum-at"] = momentum
            jobs.submit(f"mnist-empire-{gar}-f_{f}-lr_0.5-at_{momentum}",
                        make_command(params))


def _session(cache, path):
    """Load (or fetch the cached) Session for a result dir, with per-run
    error isolation: a corrupt directory warns and yields None instead of
    aborting the whole analysis (the reference wraps every experiment in
    try/except, reference `reproduce.py:469-483`)."""
    import study

    if path not in cache:
        try:
            sess = study.Session(path)
            if sess.data is not None:
                try:
                    sess.compute_ratio(nowarn=True)
                except Exception as err:
                    utils.warning(f"Unable to compute ratios for "
                                  f"{path.name!r}: {err}")
            cache[path] = sess
        except Exception as err:
            utils.warning(f"Unable to process {path.name!r}: {err}")
            cache[path] = None
    return cache[path]


def _avg_err(paths, *cols, cache):
    """Mean and population-std of the selected columns across seed runs —
    one DataFrame per column with `<col>` and `<col>-err`
    (reference `reproduce.py:383-407` `compute_avg_err`)."""
    import pandas

    frames = []
    for p in paths:
        sess = _session(cache, p)
        if sess is not None and sess.data is not None:
            frames.append(sess.data)
    out = {}
    for col in cols:
        subs = [f[col].dropna() for f in frames if col in f.columns]
        subs = [s for s in subs if len(s)]
        if not subs:
            continue
        joined = pandas.concat(subs, axis=1)
        out[col] = pandas.DataFrame({
            col: joined.mean(axis=1),
            col + "-err": joined.std(axis=1, ddof=0).fillna(0.0)})
    return out


def _select_ymax(*ratio_frames):
    """Bucketed y-limit for ratio plots (reference `reproduce.py:445-456`)."""
    vmax = 0.0
    for frame, col in ratio_frames:
        if frame is not None and col in frame.columns:
            m = frame[col].max()
            if m == m:
                vmax = max(vmax, float(m))
    for ymax in (1., 2., 6., 12.):
        if vmax < ymax:
            return ymax
    return 20.


def _run_info(sess):
    """(dataset, attack, gar, f, lr-token, momentum_at, nesterov, seed) of an
    attacked run, or None — read from config.json rather than re-parsing the
    name (more robust than the reference's `get_reference_accuracy` split,
    reference `reproduce.py:229-255`). The lr token comes from the run NAME
    (`lr_0.01`, or `lr_pow` for the appendix's schedule runs) so grouping and
    baseline lookup follow the grid's naming."""
    import re

    j = sess.json
    if not j or j.get("nb_real_byz", 0) <= 0:
        return None
    seed = sess.name.rsplit("-", 1)[-1]
    m = re.search(r"-lr_([^-]+)", sess.name)
    lr = m.group(1) if m else str(j["learning_rate"])
    return {
        "dataset": j["dataset"], "attack": j["attack"], "gar": j["gar"],
        "f": j["nb_real_byz"], "lr": lr,
        "at": j["momentum_at"], "nesterov": bool(j.get("momentum_nesterov")),
        "honests": j["nb_workers"] - j["nb_real_byz"], "seed": seed,
        "steps": j.get("nb_steps"),
    }


def _baseline_name(info):
    """Result-dir name of the matching unattacked run
    (reference `reproduce.py:244-250`)."""
    suffix = "-nesterov" if info["nesterov"] else ""
    return (f"{info['dataset']}-average-n_{info['honests']}"
            f"-lr_{info['lr']}{suffix}-{info['seed']}")


# Bucket subsets (reference `reproduce.py:293`; 'cifar10-' keeps the dash so
# it does not match cifar100 names)
BUCKET_SUBSETS = (None, "mnist", "cifar", "fashion", "f_24", "f_12",
                  "cifar10-", "cifar100", "f_11", "f_5")


def _bucket_stats(maxaccs, infos):
    """Attack-effectiveness / defense-gain buckets over max accuracies
    (reference `reproduce.py:293-366`): for every at_worker run with an
    at_update sibling and an unattacked baseline, classify the attack's
    effectiveness (baseline - at_update) and the momentum-at-worker gain
    (at_worker - at_update) at the 10/20/40% thresholds."""
    for subset in BUCKET_SUBSETS:
        with utils.Context("everything" if subset is None else subset, None):
            total = 0
            effect = {10: 0, 20: 0, 40: 0}
            above = {10: 0, 20: 0, 40: 0}
            bad0 = bad02 = bad05 = loss05 = loss10 = 0
            for name, info in infos.items():
                if info is None or info["at"] != "worker":
                    continue
                if subset is not None and subset not in name:
                    continue
                update_name = name.replace("at_worker", "at_update")
                ref_name = _baseline_name(info)
                if update_name not in maxaccs or ref_name not in maxaccs:
                    continue
                ref = maxaccs[ref_name]
                ats = maxaccs[update_name]
                atw = maxaccs[name]
                total += 1
                loss = ref - ats
                gain = atw - ats
                if gain < 0:
                    bad0 += 1
                    if gain < -0.02:
                        bad02 += 1
                    if gain < -0.05:
                        bad05 += 1
                    if ref - atw > 0.05:
                        loss05 += 1
                    if ref - atw > 0.1:
                        loss10 += 1
                for pct in (10, 20, 40):
                    if loss > pct / 100.:
                        effect[pct] += 1
                        if gain > pct / 100.:
                            above[pct] += 1
            if total == 0:
                utils.info("<no data>")
                continue
            for pct in (10, 20, 40):
                utils.info(f"#experiments with effective attack ({pct}%): "
                           f"{effect[pct]:4d}/{total:4d} "
                           f"({effect[pct] / total * 100.:.2f}%)")
            for pct in (10, 20, 40):
                if effect[pct] > 0:
                    utils.info(
                        f"#experiments with defense gain above {pct}%: "
                        f"{above[pct]:4d}/{effect[pct]:4d} "
                        f"({above[pct] / effect[pct] * 100.:.2f}%)")
                else:
                    utils.info(f"#experiments with defense gain above {pct}%:"
                               f"    N/A")
            utils.info(f"#experiments with >0% performance loss:   "
                       f"{bad0:4d}/{total:4d} ({bad0 / total * 100.:.2f}%)")
            utils.info(f"#experiments with >2% performance loss:   "
                       f"{bad02:4d}/{total:4d} ({bad02 / total * 100.:.2f}%)")
            utils.info(f"#experiments with >5% performance loss:   "
                       f"{bad05:4d}/{total:4d} ({bad05 / total * 100.:.2f}%)")
            utils.info(f"#experiments with >5% \"optimality\" loss:  "
                       f"{loss05:4d}/{total:4d} ({loss05 / total * 100.:.2f}%)")
            utils.info(f"#experiments with >10% \"optimality\" loss: "
                       f"{loss10:4d}/{total:4d} ({loss10 / total * 100.:.2f}%)")


# Overview plot x-labels (reference `reproduce.py:380-382`)
OVERVIEW_NAMES = {"update": "Standard\nformulation", "worker": "Our\nformulation"}


def _comparison_plots(paths, infos, maxaccs, plot_dir, cache):
    """Baseline-vs-attacked comparison plots per (dataset, attack, f, lr,
    nesterov): per-momentum accuracy and loss curves with per-GAR mean±std
    bands plus the unattacked baseline, per-GAR sampled/honest
    variance-norm-ratio curves for the at_worker runs, and the
    update-vs-worker max-accuracy overview box plots
    (reference `reproduce.py:459-635` — line, ratio and overview plots; the
    reference re-enumerates the grid, here the groups derive from the result
    dirs present, so partial grids and the smoke subset plot whatever
    completed)."""
    import statistics

    import study

    by_name = {p.name: p for p in paths}
    # (ds, attack, f, lr, nesterov) -> momentum-at -> gar -> [seed paths]
    groups = {}
    for p in paths:
        info = infos.get(p.name)
        if info is None:
            continue
        key = (info["dataset"], info["attack"], info["f"], info["lr"],
               info["nesterov"])
        groups.setdefault(key, {}).setdefault(info["at"], {}) \
              .setdefault(info["gar"], []).append(p)
    for (ds, attack, f, lr, nesterov), by_at in sorted(groups.items()):
        suffix = "-nesterov" if nesterov else ""
        baseline_paths = []
        for by_gar in by_at.values():
            for gar_paths in by_gar.values():
                for p in gar_paths:
                    ref = by_name.get(_baseline_name(infos[p.name]))
                    if ref is not None and ref not in baseline_paths:
                        baseline_paths.append(ref)
        noattack = _avg_err(baseline_paths, "Cross-accuracy", "Average loss",
                            cache=cache)
        any_gar = next(iter(by_at.values()))
        xmax = infos[next(iter(any_gar.values()))[0].name].get("steps")
        ymax_acc = 0.9 if ds.startswith("cifar") else 1.0
        for at, by_gar in sorted(by_at.items()):
            # One pass per GAR fetches every plotted column
            per_gar = {gar: _avg_err(by_gar[gar], "Cross-accuracy",
                                     "Average loss", "Sampled ratio",
                                     "Honest ratio", cache=cache)
                       for gar in sorted(by_gar)}
            # Top-1 cross-accuracy and average-loss comparison plots
            for col, kind, ylabel, ymin, ymax in (
                    ("Cross-accuracy", "", "Top-1 cross-accuracy", 0, ymax_acc),
                    ("Average loss", "-loss", "Average loss", 0, None)):
                plot = study.LinePlot()
                legend = []
                if col in noattack:
                    plot.include(noattack[col], col, errs="-err", lalp=0.8,
                                 label="No attack")
                    legend.append("No attack")
                for gar, data in per_gar.items():
                    if col not in data:
                        continue
                    plot.include(data[col], col, errs="-err", lalp=0.8,
                                 label=gar.capitalize())
                    legend.append(gar.capitalize())
                if not legend:
                    plot.close()
                    continue
                plot.finalize(None, "Step number", ylabel, xmin=0, xmax=xmax,
                              ymin=ymin, ymax=ymax)
                plot.save(plot_dir / f"{ds}-{attack}-f_{f}-lr_{lr}-at_{at}"
                                     f"{suffix}{kind}.png", xsize=3, ysize=1.5)
                plot.close()
            # Variance-norm ratio plots (submit vs sample, at_worker runs
            # only, reference `reproduce.py:509-518`) — both curves share
            # ONE y-axis (axkey), as in the reference
            if at != "worker":
                continue
            for gar, data in per_gar.items():
                if "Sampled ratio" not in data or "Honest ratio" not in data:
                    continue
                plot = study.LinePlot()
                plot.include(data["Sampled ratio"], "Sampled ratio",
                             errs="-err", lalp=0.5, ccnt=0, axkey="ratio",
                             label=f"{gar.capitalize()} \"sample\"")
                plot.include(data["Honest ratio"], "Honest ratio",
                             errs="-err", lalp=0.5, ccnt=4, axkey="ratio",
                             label=f"{gar.capitalize()} \"submit\"")
                plot.finalize(None, "Step number", "Variance-norm ratio",
                              xmin=0, xmax=xmax, ymin=0,
                              ymax=_select_ymax(
                                  (data["Sampled ratio"], "Sampled ratio"),
                                  (data["Honest ratio"], "Honest ratio")))
                plot.save(plot_dir / f"{ds}-{attack}-{gar}-f_{f}-lr_{lr}"
                                     f"{suffix}-ratio.png", xsize=3, ysize=1.5)
                plot.close()
        # Overview box plots: max top-1 cross-accuracy pooled over GARs and
        # seeds, one box per momentum placement, hline at the median
        # unattacked max accuracy (reference `reproduce.py:599-635`)
        pooled = {}
        for at, by_gar in sorted(by_at.items()):
            accs = [maxaccs[p.name] for gar_paths in by_gar.values()
                    for p in gar_paths
                    if p.name in maxaccs and maxaccs[p.name] == maxaccs[p.name]]
            if accs:
                pooled[at] = accs
        base_accs = [maxaccs[p.name] for p in baseline_paths
                     if p.name in maxaccs and maxaccs[p.name] == maxaccs[p.name]]
        if pooled:
            plot = study.BoxPlot()
            for at, accs in sorted(pooled.items()):
                plot.include(accs, OVERVIEW_NAMES.get(at, f"At {at}"))
            if base_accs:
                plot.hline(statistics.median(base_accs))
            plot.finalize(None, "Max. top-1 cross-accuracy", ymin=0, ymax=1)
            plot.save(plot_dir / f"overview-{ds}-{attack}-f_{f}-lr_{lr}"
                                 f"{suffix}.png", xsize=1.5, ysize=1.5)
            plot.close()


def analyze(data_dir, plot_dir):
    """Summary statistics + plots over completed result directories
    (reference `reproduce.py:258-366`, `459-635`)."""
    import study

    paths = sorted(p for p in data_dir.iterdir() if p.is_dir()
                   and ".failed" not in p.name and ".pending" not in p.name)
    if not paths:
        utils.warning("No completed result directory to analyze")
        return
    plot_dir.mkdir(parents=True, exist_ok=True)

    # Per-run max accuracy + ratio-condition counting (reference
    # `reproduce.py:264-291`; the reference's summary line reuses loop-leaked
    # variables — documented bug, fixed here by printing the stored best)
    cache = {}  # path -> Session (each run's CSVs parsed once)
    maxaccs = {}
    infos = {}
    expwith = expzero = 0
    best_ratio = None
    with utils.Context("analysis", "info"):
        for path in paths:
            sess = _session(cache, path)
            if sess is None or sess.data is None:
                continue
            acc = (sess.data["Cross-accuracy"].max()
                   if "Cross-accuracy" in sess.data.columns else float("nan"))
            maxaccs[path.name] = float(acc)
            infos[path.name] = _run_info(sess)
            line = f"{path.name}: max accuracy {acc:.4f}"
            if (sess.has_known_ratio()
                    and "Average loss" in sess.data.columns
                    and "Ratio enough for GAR?" in sess.data.columns):
                expwith += 1
                data = sess.data
                # Count steps where the ratio condition held AND the model
                # was not already "killed" (loss above its initial value) —
                # reference `reproduce.py:277-281`, incl. its nbtotal
                # convention of excluding the final eval-only row
                minloss = data["Average loss"].dropna().iloc[0]
                nbtotal = max(len(data) - 1, 1)
                ratio_ok = data["Ratio enough for GAR?"].fillna(False)
                nbvalid = int(((data["Average loss"] <= minloss)
                               & ratio_ok).sum())
                pct = nbvalid / nbtotal * 100.0
                if nbvalid == 0:
                    expzero += 1
                elif best_ratio is None or pct > best_ratio[2]:
                    best_ratio = (nbvalid, nbtotal, pct)
                line += f"; ratio ok {nbvalid}/{nbtotal} ({pct:.2f}%)"
            utils.info(line)
        if expwith:
            utils.info(f"#experiments with ratio never validated: "
                       f"{expzero}/{expwith} ({expzero / expwith * 100.:.2f}%)")
        if best_ratio is not None:
            utils.info(f"Maximum #steps with ratio validated: "
                       f"{best_ratio[0]}/{best_ratio[1]} ({best_ratio[2]:.2f}%)")

    # Attack-effectiveness / defense-gain buckets
    with utils.Context("buckets", "info"):
        _bucket_stats(maxaccs, infos)

    with utils.Context("plotting", "info"):
        # Baseline-vs-attacked comparison plots (the paper's figures)
        _comparison_plots(paths, infos, maxaccs, plot_dir, cache)
        # Per-experiment accuracy curves with mean±std bands across seeds
        groups = {}
        for path in paths:
            stem = path.name.rsplit("-", 1)[0]  # strip the -<seed> suffix
            groups.setdefault(stem, []).append(path)
        for stem, members in groups.items():
            data = _avg_err(members, "Cross-accuracy", cache=cache)
            if "Cross-accuracy" not in data:
                continue
            plot = study.LinePlot()
            plot.include(data["Cross-accuracy"], "Cross-accuracy",
                         errs="-err", label=stem)
            plot.finalize(stem, "Step number", "Cross-accuracy", ymin=0.0,
                          ymax=1.0)
            plot.save(plot_dir / f"{stem}.png", xsize=4, ysize=3)
            plot.close()
        # Fault-resilience plots (the '--subset faults' grid; any run that
        # recorded the --fault-plan study columns participates): one
        # degradation timeline per faulted run, then the cross-run
        # fault-rate sweep — the per-rate summary the ROADMAP called for.
        # Rate-0 baselines join the sweep through their '-faults-' name.
        sweep = []
        for path in paths:
            sess = _session(cache, path)
            if sess is None or sess.data is None:
                continue
            faulted = "Workers active" in sess.data.columns
            if faulted:
                try:
                    plot = study.fault_timeline(sess)
                    plot.save(plot_dir / f"fault-timeline-{path.name}.png",
                              xsize=4, ysize=3)
                    plot.close()
                except Exception as err:
                    utils.warning(f"Unable to plot the fault timeline of "
                                  f"{path.name!r}: {err}")
            if faulted or "-faults-" in path.name:
                sweep.append(sess)
        if len(sweep) >= 2:
            for metric in ("Average loss", "Cross-accuracy"):
                try:
                    frame, plot = study.fault_rate_sweep(sweep, metric=metric)
                    if len(frame):
                        slug = metric.lower().replace(" ", "-")
                        plot.save(plot_dir / f"fault-rate-sweep-{slug}.png",
                                  xsize=4, ysize=3)
                    plot.close()
                except Exception as err:
                    utils.warning(f"Unable to plot the fault-rate sweep "
                                  f"for {metric!r}: {err}")
        # Forensics plots (--gar-diagnostics runs): the paper's mechanism
        # — who the GAR trusts over time — next to its accuracy curves
        for path in paths:
            sess = _session(cache, path)
            if sess is None or sess.data is None \
                    or "Sel workers" not in sess.data.columns:
                continue
            try:
                plot = study.worker_heatmap(sess)
                plot.save(plot_dir / f"worker-heatmap-{path.name}.png",
                          xsize=5, ysize=3)
                plot.close()
                plot = study.suspicion_timeline(sess)
                plot.save(plot_dir / f"suspicion-{path.name}.png",
                          xsize=4, ysize=3)
                plot.close()
            except Exception as err:
                utils.warning(f"Unable to plot the forensics of "
                              f"{path.name!r}: {err}")
        # Flight-recorder plots (--health runs): the variance envelope —
        # the paper's observable as a first-class timeline — and the
        # norm/ratio health timeline with anomaly edges
        for path in paths:
            sess = _session(cache, path)
            if sess is None or sess.data is None \
                    or "Var ratio" not in sess.data.columns:
                continue
            try:
                plot = study.variance_envelope(sess)
                plot.save(plot_dir / f"variance-envelope-{path.name}.png",
                          xsize=4, ysize=3)
                plot.close()
                plot = study.health_timeline(sess)
                plot.save(plot_dir / f"health-timeline-{path.name}.png",
                          xsize=4, ysize=3)
                plot.close()
            except Exception as err:
                utils.warning(f"Unable to plot the health timeline of "
                              f"{path.name!r}: {err}")
        utils.info(f"Plots written to {plot_dir}")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--data-directory", type=str, default="results-data")
    parser.add_argument("--plot-directory", type=str, default="results-plot")
    parser.add_argument("--devices", type=str, default="auto",
                        help="Comma-separated device list, one job slot each")
    parser.add_argument("--supercharge", type=int, default=1,
                        help="Concurrent runs per device")
    parser.add_argument("--subset", type=str, default="all",
                        choices=("smoke", "faults", "mnist", "cifar", "all"))
    args = parser.parse_args()

    exit_trigger, exit_is_requested = utils.onetime(None)
    signal.signal(signal.SIGINT, lambda *_: exit_trigger())
    signal.signal(signal.SIGTERM, lambda *_: exit_trigger())

    data_dir = pathlib.Path(args.data_directory)
    jobs = Jobs(data_dir, devices=args.devices.split(","),
                supercharge=args.supercharge,
                seeds=(1,) if args.subset in ("smoke", "faults")
                else DEFAULT_SEEDS)
    with utils.Context("experiments", "info"):
        if args.subset == "smoke":
            submit_smoke(jobs)
        if args.subset == "faults":
            submit_faults(jobs, data_dir)
        if args.subset in ("mnist", "all"):
            submit_mnist(jobs)
        if args.subset in ("cifar", "all"):
            submit_cifar(jobs)
        jobs.wait(exit_is_requested)

    if not exit_is_requested():
        analyze(data_dir, pathlib.Path(args.plot_directory))


if __name__ == "__main__":
    main()
