#!/usr/bin/env python3
"""Reproduce the paper's experiment grid: schedule every (dataset, GAR,
attack, f, lr, momentum-at, nesterov, seed) driver run, then analyze and
plot (reference `reproduce.py`; same grid constants,
reference `reproduce.py:109-213`).

Usage:
  python3 reproduce.py [--data-directory results-data]
                       [--plot-directory results-plot]
                       [--devices auto[,auto...]] [--supercharge N]
                       [--subset smoke|mnist|cifar|all]

The grid is idempotent: completed result directories are skipped, failed
ones are kept as `<name>.failed` (reference `tools/jobs.py:126-146`).
`--subset smoke` runs a tiny 2-run sanity grid (not part of the paper).
"""

import argparse
import pathlib
import signal
import sys

from byzantinemomentum_tpu import utils
from byzantinemomentum_tpu.utils.jobs import DEFAULT_SEEDS, Jobs, dict_to_cmdlist

# The paper's GAR list (reference `reproduce.py:109`)
GARS = ("krum", "median", "trmean", "phocas", "meamed", "bulyan")
# The paper's attacks (reference `reproduce.py:151`)
ATTACKS = (("little", ("factor:1.5", "negative:True")),
           ("empire", "factor:1.1"))

ATTACK_PY = str(pathlib.Path(__file__).resolve().parent / "attack.py")


def make_command(params):
    return [sys.executable, ATTACK_PY] + dict_to_cmdlist(params)


def submit_mnist(jobs):
    """(Fashion-)MNIST grid (reference `reproduce.py:121-162`)."""
    base = {
        "batch-size": 83, "model": "simples-full", "loss": "nll",
        "learning-rate-decay-delta": 300, "momentum": 0.9,
        "l2-regularize": 1e-4, "evaluation-delta": 5, "gradient-clip": 2,
        "nb-steps": 300, "nb-for-study": 1, "nb-for-study-past": 150,
        "nb-workers": 51,
    }
    for ds in ("mnist", "fashionmnist"):
        for f, fm in ((24, 1), (12, 0)):
            for lr in (0.5, 0.02):
                for nesterov in (False, True):
                    suffix = "-nesterov" if nesterov else ""
                    params = dict(base, dataset=ds)
                    params["nb-workers"] = base["nb-workers"] - f
                    params["learning-rate"] = lr
                    params["momentum-nesterov"] = nesterov
                    jobs.submit(
                        f"{ds}-average-n_{params['nb-workers']}-lr_{lr}{suffix}",
                        make_command(params))
                    for gar in GARS[:len(GARS) - fm]:
                        for attack, attargs in ATTACKS:
                            for momentum in ("update", "worker"):
                                params = dict(base, dataset=ds)
                                params["learning-rate"] = lr
                                params["nb-decl-byz"] = f
                                params["nb-real-byz"] = f
                                params["gar"] = gar
                                params["attack"] = attack
                                params["attack-args"] = attargs
                                params["momentum-at"] = momentum
                                params["momentum-nesterov"] = nesterov
                                jobs.submit(
                                    f"{ds}-{attack}-{gar}-f_{f}-lr_{lr}"
                                    f"-at_{momentum}{suffix}",
                                    make_command(params))


def submit_cifar(jobs):
    """CIFAR-10/100 grid (reference `reproduce.py:164-209`)."""
    base = {
        "batch-size": 50, "model": "empire-cnn", "loss": "nll",
        "learning-rate-decay": 167, "momentum": 0.99, "l2-regularize": 1e-2,
        "evaluation-delta": 100, "gradient-clip": 5, "nb-steps": 3000,
        "nb-for-study": 1, "nb-for-study-past": 25, "nb-workers": 25,
    }
    for ds, mp in (("cifar10", "cifar100:False"), ("cifar100", "cifar100:True")):
        for f, fm in ((11, 1), (5, 0)):
            for lr, dd in ((0.01, 1500), (0.001, 3000)):
                for nesterov in (False, True):
                    suffix = "-nesterov" if nesterov else ""
                    params = dict(base, dataset=ds)
                    params["model-args"] = mp
                    params["nb-workers"] = base["nb-workers"] - f
                    params["learning-rate"] = lr
                    params["learning-rate-decay-delta"] = dd
                    params["momentum-nesterov"] = nesterov
                    jobs.submit(
                        f"{ds}-average-n_{params['nb-workers']}-lr_{lr}{suffix}",
                        make_command(params))
                    for gar in GARS[:len(GARS) - fm]:
                        for attack, attargs in ATTACKS:
                            for momentum in ("update", "worker"):
                                params = dict(base, dataset=ds)
                                params["model-args"] = mp
                                params["learning-rate"] = lr
                                params["learning-rate-decay-delta"] = dd
                                params["nb-decl-byz"] = f
                                params["nb-real-byz"] = f
                                params["gar"] = gar
                                params["attack"] = attack
                                params["attack-args"] = attargs
                                params["momentum-at"] = momentum
                                params["momentum-nesterov"] = nesterov
                                jobs.submit(
                                    f"{ds}-{attack}-{gar}-f_{f}-lr_{lr}"
                                    f"-at_{momentum}{suffix}",
                                    make_command(params))


def submit_smoke(jobs):
    """Tiny sanity grid (non-paper) to validate the pipeline end-to-end."""
    base = {
        "batch-size": 16, "model": "simples-full", "loss": "nll",
        "momentum": 0.9, "evaluation-delta": 2, "nb-steps": 4,
        "nb-for-study": 11, "nb-for-study-past": 3, "nb-workers": 11,
        "batch-size-test": 32, "batch-size-test-reps": 2,
    }
    for gar, f in (("median", 4), ("krum", 3)):
        params = dict(base, gar=gar)
        params["nb-decl-byz"] = f
        params["nb-real-byz"] = f
        params["attack"] = "empire"
        params["attack-args"] = "factor:1.1"
        jobs.submit(f"smoke-{gar}-f_{f}", make_command(params))


def analyze(data_dir, plot_dir):
    """Summary statistics + plots over completed result directories
    (reference `reproduce.py:258-366`, `459-635`)."""
    import numpy as np

    import study

    paths = sorted(p for p in data_dir.iterdir() if p.is_dir()
                   and ".failed" not in p.name and ".pending" not in p.name)
    if not paths:
        utils.warning("No completed result directory to analyze")
        return
    plot_dir.mkdir(parents=True, exist_ok=True)

    # Per-run max accuracy + ratio-condition counting
    expwith = expzero = 0
    best_ratio = None
    with utils.Context("analysis", "info"):
        for path in paths:
            sess = study.Session(path)
            if sess.data is None:
                continue
            acc = (sess.data["Cross-accuracy"].max()
                   if "Cross-accuracy" in sess.data.columns else float("nan"))
            line = f"{path.name}: max accuracy {acc:.4f}"
            if sess.has_known_ratio():
                expwith += 1
                data = sess.compute_ratio(nowarn=True).data
                valid = data["Ratio enough for GAR?"].fillna(False)
                nbvalid = int(valid.sum())
                nbtotal = max(int(data["Ratio enough for GAR?"].notna().sum()), 1)
                pct = nbvalid / nbtotal * 100.0
                if nbvalid == 0:
                    expzero += 1
                elif best_ratio is None or pct > best_ratio[2]:
                    best_ratio = (nbvalid, nbtotal, pct)
                line += f"; ratio ok {nbvalid}/{nbtotal} ({pct:.2f}%)"
            utils.info(line)
        if expwith:
            utils.info(f"#experiments with ratio never validated: "
                       f"{expzero}/{expwith} ({expzero / expwith * 100.:.2f}%)")
        if best_ratio is not None:
            utils.info(f"Maximum #steps with ratio validated: "
                       f"{best_ratio[0]}/{best_ratio[1]} ({best_ratio[2]:.2f}%)")

    # Accuracy curves with mean±std bands across seeds
    groups = {}
    for path in paths:
        stem = path.name.rsplit("-", 1)[0]  # strip the -<seed> suffix
        groups.setdefault(stem, []).append(path)
    with utils.Context("plotting", "info"):
        for stem, members in groups.items():
            frames = []
            for path in members:
                sess = study.Session(path)
                if sess.data is not None and "Cross-accuracy" in sess.data.columns:
                    frames.append(sess.data["Cross-accuracy"].dropna())
            if not frames:
                continue
            import pandas
            joined = pandas.concat(frames, axis=1)
            mean = joined.mean(axis=1)
            std = joined.std(axis=1)
            frame = pandas.DataFrame({
                "Cross-accuracy": mean, "Cross-accuracy (std)": std})
            plot = study.LinePlot()
            plot.include(frame, "Cross-accuracy", errs=" (std)",
                         label=stem)
            plot.finalize(stem, "Step number", "Cross-accuracy", ymin=0.0,
                          ymax=1.0)
            plot.save(plot_dir / f"{stem}.png", xsize=4, ysize=3)
            plot.close()
        utils.info(f"Plots written to {plot_dir}")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--data-directory", type=str, default="results-data")
    parser.add_argument("--plot-directory", type=str, default="results-plot")
    parser.add_argument("--devices", type=str, default="auto",
                        help="Comma-separated device list, one job slot each")
    parser.add_argument("--supercharge", type=int, default=1,
                        help="Concurrent runs per device")
    parser.add_argument("--subset", type=str, default="all",
                        choices=("smoke", "mnist", "cifar", "all"))
    args = parser.parse_args()

    exit_trigger, exit_is_requested = utils.onetime(None)
    signal.signal(signal.SIGINT, lambda *_: exit_trigger())
    signal.signal(signal.SIGTERM, lambda *_: exit_trigger())

    data_dir = pathlib.Path(args.data_directory)
    jobs = Jobs(data_dir, devices=args.devices.split(","),
                supercharge=args.supercharge,
                seeds=(1,) if args.subset == "smoke" else DEFAULT_SEEDS)
    with utils.Context("experiments", "info"):
        if args.subset == "smoke":
            submit_smoke(jobs)
        if args.subset in ("mnist", "all"):
            submit_mnist(jobs)
        if args.subset in ("cifar", "all"):
            submit_cifar(jobs)
        jobs.wait(exit_is_requested)

    if not exit_is_requested():
        analyze(data_dir, pathlib.Path(args.plot_directory))


if __name__ == "__main__":
    main()
