#!/usr/bin/env python3
"""Result-analysis library (NOT a main module — import it from an analysis
script or notebook, reference `study.py:18-19`).

Capability parity with the reference's `study.py`:
* `Session` — loads one result directory (config, config.json, study CSV,
  eval CSV) into a joined pandas DataFrame (reference `study.py:185-242`);
  derived columns: epoch number, reconstructed learning rate, the
  (deviation/norm)² ratio columns and the "Ratio enough for GAR?" check
  against the GAR's theoretical `upper_bound(n, f, d)`
  (reference `study.py:295-396`).
* `LinePlot` / `BoxPlot` — thin matplotlib wrappers with mean±error bands,
  dual y-axes, and box/violin overviews (reference `study.py:403-749`).
"""

import json
import pathlib

import pandas

from byzantinemomentum_tpu import models, ops, utils

__all__ = ["Session", "LinePlot", "BoxPlot", "HeatmapPlot", "display",
           "select", "discard",
           "fault_timeline", "fault_rate_sweep",
           "load_telemetry", "run_health", "throughput_sweep",
           "selection_matrix", "worker_heatmap", "suspicion_timeline",
           "load_fleet_timeline", "fleet_health"]

# Training-set sizes for epoch derivation (reference `study.py:309`)
TRAINING_SIZES = {"mnist": 60000, "fashionmnist": 60000, "kmnist": 60000,
                  "cifar10": 50000, "cifar100": 50000}


class Session:
    """Loaded results of one run directory."""

    def __init__(self, path_results):
        path_results = pathlib.Path(path_results)
        if not path_results.exists():
            raise utils.UserException(
                f"Result directory {str(path_results)} cannot be accessed or "
                f"does not exist")
        self.name = path_results.name
        self.path = path_results
        self.config = self._read_text(path_results / "config")
        self.json = self._read_json(path_results / "config.json")
        data_study = self._read_csv(path_results / "study")
        data_eval = self._read_csv(path_results / "eval")
        if data_study is not None and data_eval is not None:
            self.data = data_study.join(data_eval, how="outer")
        else:
            self.data = data_study if data_study is not None else data_eval
        self.thresh = None

    @staticmethod
    def _read_text(path):
        try:
            return path.read_text().strip()
        except Exception as err:
            utils.warning(f"{path}: unable to read ({err})")
            return None

    @staticmethod
    def _read_json(path):
        try:
            return json.loads(path.read_text())
        except Exception as err:
            utils.warning(f"{path}: unable to read ({err})")
            return None

    @staticmethod
    def _read_csv(path):
        """Parse the '# '-prefixed tab-separated result format
        (reference `attack.py:403-448` writer, `study.py:216-229` reader)."""
        try:
            data = pandas.read_csv(path, sep="\t", index_col=0)
            data.index.name = "Step number"
            return data
        except Exception as err:
            utils.warning(f"{path}: unable to read ({err})")
            return None

    # ------------------------------------------------------------- #

    def get(self, *only_columns):
        """The DataFrame, optionally restricted to the given columns."""
        if not only_columns:
            return self.data
        return self.data[list(only_columns)]

    def has_known_ratio(self):
        """Whether the run's GAR has a theoretical ratio bound."""
        return self.calc_max_ratio(nowarn=True) is not None

    def compute_all(self, nowarn=False):
        """All derived columns (chainable)."""
        self.compute_epoch()
        self.compute_lr()
        self.compute_ratio(nowarn=nowarn)
        return self

    def compute_epoch(self):
        """Epoch number = training point count / train-set size
        (reference `study.py:295-315`)."""
        if "Epoch number" in self.data.columns:
            return self
        if self.json is None or "dataset" not in self.json:
            utils.warning("No valid JSON configuration, cannot compute the "
                          "epoch number")
            return self
        size = TRAINING_SIZES.get(self.json["dataset"])
        if size is None:
            utils.warning(f"Unknown dataset {self.json['dataset']!r}, cannot "
                          f"compute the epoch number")
            return self
        self.data["Epoch number"] = self.data["Training point count"] / size
        return self

    def compute_lr(self):
        """Reconstruct the per-step learning rate from the config
        (reference `study.py:317-342`; schedules supported here, which the
        reference leaves as a warning)."""
        if "Learning rate" in self.data.columns:
            return self
        if self.json is None or "learning_rate" not in self.json:
            utils.warning("No valid JSON configuration, cannot compute the "
                          "learning rate")
            return self
        schedule = self.json.get("learning_rate_schedule")
        steps = self.data.index
        if schedule is None:
            lr = self.json["learning_rate"]
            decay = self.json.get("learning_rate_decay", 0)
            delta = self.json.get("learning_rate_decay_delta", 1)
            if decay > 0:
                self.data["Learning rate"] = lr / (
                    (steps // delta * delta) / decay + 1)
            else:
                self.data["Learning rate"] = lr
        else:
            flat = schedule.split(",")
            pairs = [(0, float(flat[0]))]
            for i in range(1, len(flat), 2):
                pairs.append((int(flat[i]), float(flat[i + 1])))

            def lr_at(step):
                current = pairs[0][1]
                for boundary, value in pairs:
                    if boundary <= step:
                        current = value
                return current
            self.data["Learning rate"] = [lr_at(s) for s in steps]
        return self

    def calc_max_ratio(self, nowarn=False):
        """The GAR's theoretical max std-dev/norm ratio `upper_bound(n, f, d)`
        with d = the model's parameter count (reference `study.py:344-374`)."""
        if self.thresh is not None:
            return None if self.thresh < 0 else self.thresh
        if self.json is None or not all(
                k in self.json for k in ("gar", "nb_workers", "nb_decl_byz")):
            utils.warning("No valid JSON configuration, cannot compute the "
                          "maximum variance-norm ratio")
            return None
        rule = ops.gars.get(self.json["gar"])
        if rule is None or rule.upper_bound is None:
            if not nowarn:
                utils.warning(f"GAR {self.json['gar']!r} has no known ratio "
                              f"threshold")
            self.thresh = -1
            return None
        n = self.json["nb_workers"]
        f = self.json["nb_decl_byz"]
        model_args = self.json.get("model_args") or {}
        d = models.build(self.json["model"], **model_args).param_count()
        self.thresh = rule.upper_bound(n, f, d)
        return self.thresh

    def compute_ratio(self, nowarn=False):
        """(deviation/norm)² ratio columns + the per-step check against the
        GAR bound (reference `study.py:376-396`)."""
        for clsname in ("Sampled", "Honest"):
            column = f"{clsname} ratio"
            if column not in self.data.columns:
                self.data[column] = (
                    self.data[f"{clsname} gradient deviation"]
                    / self.data[f"{clsname} gradient norm"]) ** 2
        if "Ratio enough for GAR?" not in self.data.columns:
            max_ratio = self.calc_max_ratio(nowarn=nowarn)
            if max_ratio is not None:
                self.data["Ratio enough for GAR?"] = (
                    self.data["Honest ratio"] < max_ratio ** 2)
        return self

    def __repr__(self):
        return f"Session({self.name!r})"


def select(data, *only_columns):
    """Case-insensitive substring column selection
    (reference `study.py:83-105`): `select(sess, "ratio")` returns every
    column whose name contains "ratio"; no arguments returns everything."""
    if isinstance(data, Session):
        data = data.data
    if not only_columns:
        return data
    columns = []
    for only_column in only_columns:
        only_column = only_column.lower()
        for column in data.columns:
            if column not in columns and only_column in column.lower():
                columns.append(column)
    return data[columns]


def discard(data, *only_columns):
    """Case-insensitive substring column discarding
    (reference `study.py:107-126`)."""
    if isinstance(data, Session):
        data = data.data
    if not only_columns:
        return data
    data = data[:]
    for only_column in only_columns:
        only_column = only_column.lower()
        for column in list(data.columns):
            if only_column in column.lower():
                del data[column]
    return data


# --------------------------------------------------------------------------- #
# Fault-resilience analysis (ROADMAP open item: sweep plots off the
# `Faults injected` / `Workers active` / `Quorum f` columns the study CSV
# gains under `--fault-plan`). Stubs of the multi-host chaos dashboards:
# one run's degradation timeline, and the cross-run fault-rate sweep.

def _as_frame(data):
    return data.data if isinstance(data, Session) else data


def fault_timeline(session):
    """LinePlot of one faulted run's resilience counters over steps:
    `Workers active` on the left axis against `Faults injected` on the
    right — the shape of the run's degradation under its fault plan."""
    data = _as_frame(session)
    missing = [c for c in ("Faults injected", "Workers active")
               if c not in data.columns]
    if missing:
        raise utils.UserException(
            f"No fault columns {missing} in the study data; the run must "
            f"be recorded with --fault-plan")
    sub = data.dropna(subset=["Workers active"])
    plot = LinePlot()
    plot.include(sub, "Workers active")
    plot.include(sub, "Faults injected")
    plot.finalize("Fault timeline", "Step number", "Workers active",
                  zlabel="Faults injected")
    return plot


def fault_rate_sweep(sessions, metric="Average loss", reducer="last"):
    """One point per run: the observed fault rate (mean `Faults injected`
    per recorded step; 0 for fault-free baselines) against the run's final
    (`reducer="last"`) or mean (`reducer="mean"`) `metric` value.

    `sessions`: an iterable of `Session`s (or raw DataFrames). Returns
    `(frame, plot)` — the rate-indexed DataFrame and a ready LinePlot —
    so grids can be compared without re-deriving the reduction.
    """
    if reducer not in ("last", "mean"):
        raise utils.UserException(
            f"Unknown reducer {reducer!r}, expected 'last' or 'mean'")
    points = []
    for session in sessions:
        data = _as_frame(session)
        if metric not in data.columns:
            utils.warning(f"{session}: no {metric!r} column; skipped")
            continue
        series = data[metric].dropna()
        if not len(series):
            utils.warning(f"{session}: no {metric!r} values; skipped")
            continue
        rate = 0.0
        if "Faults injected" in data.columns:
            faults = data["Faults injected"].dropna()
            if len(faults):
                rate = float(faults.mean())
        value = float(series.iloc[-1]) if reducer == "last" \
            else float(series.mean())
        points.append((rate, value))
    points.sort(key=lambda p: p[0])
    frame = pandas.DataFrame(
        {metric: [v for _, v in points]},
        index=pandas.Index([r for r, _ in points], name="Fault rate"))
    plot = LinePlot()
    plot.include(frame, metric)
    plot.finalize(f"{metric} vs fault rate", "Faults injected per step",
                  metric)
    return frame, plot


# --------------------------------------------------------------------------- #
# Run-health analysis (PR 3, `byzantinemomentum_tpu/obs/`): the system
# timeline — telemetry.jsonl's spans/events/counters/gauges — turned into
# the plots an operator reads first when a run looks sick.

def _session_dir(run):
    """Result-directory Path of a Session / path-like."""
    if isinstance(run, Session):
        return run.path
    return pathlib.Path(run)


def load_telemetry(run):
    """One run's `telemetry.jsonl` as a DataFrame (one row per record;
    columns: t, kind, name, value, dur, id, parent, step, data). `step` is
    lifted out of gauge records' data so timeline plots can index by step
    like every study plot. Raises when the run has no telemetry."""
    from byzantinemomentum_tpu.obs import load_records
    records = load_records(_session_dir(run))
    if not records:
        raise utils.UserException(
            f"No telemetry.jsonl under {str(_session_dir(run))!r}; the run "
            f"must be recorded with telemetry on (the default with "
            f"'--result-directory')")
    rows = []
    for record in records:
        row = dict(record)
        data = row.pop("data", None)
        if isinstance(data, dict):
            row["step"] = data.get("step")
            row["data"] = data
        else:
            row["step"] = None
        rows.append(row)
    return pandas.DataFrame(rows)


def run_health(run):
    """One run's health timeline: device-honest step time (ms, left axis)
    and steps/s (right axis) over steps, with the resilience events —
    rollbacks, restarts, divergence give-ups — marked as vertical lines
    and the fault counter's running total noted in the title."""
    frame = load_telemetry(run)
    gauges = frame[frame["kind"] == "gauge"]
    plot = LinePlot()
    plotted = False
    for name, axkey in (("device_step_ms", "ms"), ("steps_per_sec", "sps")):
        series = gauges[gauges["name"] == name].dropna(subset=["step"])
        if not len(series):
            continue
        sub = pandas.DataFrame({name: series["value"].values},
                               index=pandas.Index(series["step"].values,
                                                  name="Step number"))
        plot.include(sub, name, axkey=axkey)
        plotted = True
    if not plotted:
        raise utils.UserException(
            "No step-time/throughput gauges in the telemetry; was the run "
            "long enough to reach a telemetry sample?")
    events = frame[frame["kind"] == "event"]
    for name, color in (("rollback", "red"), ("restart", "orange"),
                        ("divergence_giveup", "black")):
        for _, event in events[events["name"] == name].iterrows():
            data = event.get("data")
            step = data.get("step") if isinstance(data, dict) else None
            if step is not None:
                plot.vline(step, color=color, label=name)
    counters = frame[frame["kind"] == "counter"]
    faults = counters[counters["name"] == "faults_injected"]
    suffix = (f" ({int(faults['value'].iloc[-1])} faults injected)"
              if len(faults) else "")
    plot.finalize("Run health" + suffix, "Step number",
                  "Device step time (ms)", zlabel="Steps/s")
    return plot


def throughput_sweep(sessions, reducer="mean"):
    """One point per run: the run's steps/s (mean or final telemetry
    gauge) indexed by run name — the cross-run companion of `run_health`
    (does a config change cost throughput?). Returns `(frame, plot)` like
    `fault_rate_sweep`. Runs without telemetry or throughput gauges are
    skipped with a warning."""
    if reducer not in ("last", "mean"):
        raise utils.UserException(
            f"Unknown reducer {reducer!r}, expected 'last' or 'mean'")
    names, values = [], []
    for session in sessions:
        try:
            frame = load_telemetry(session)
        except utils.UserException as err:
            utils.warning(f"{session}: {err}; skipped")
            continue
        gauges = frame[(frame["kind"] == "gauge")
                       & (frame["name"] == "steps_per_sec")]
        if not len(gauges):
            utils.warning(f"{session}: no throughput gauges; skipped")
            continue
        series = gauges["value"]
        values.append(float(series.iloc[-1]) if reducer == "last"
                      else float(series.mean()))
        names.append(session.name if isinstance(session, Session)
                     else pathlib.Path(session).name)
    frame = pandas.DataFrame(
        {"Steps/s": values}, index=pandas.Index(names, name="Run"))
    plot = BoxPlot()
    for name, value in zip(names, values):
        plot.include([value], name)
    plot.finalize("Throughput sweep", "Steps/s")
    return frame, plot


# --------------------------------------------------------------------------- #
# Fleet health (PR 13, `obs/trace/fleet.py`): a cluster run's launcher +
# per-host telemetry streams joined into one clock-aligned timeline — the
# multi-host companion of `run_health`.

def load_fleet_timeline(run):
    """One cluster run's joined fleet timeline as a DataFrame (columns:
    t, rel_s (seconds since the first entry), source, kind, name, data)
    — launcher supervision events and every host's lifecycle events,
    host clocks shifted by the launcher's heartbeat-handshake offset
    estimates so ordering is causal. Raises when the directory carries
    no fleet telemetry at all."""
    from byzantinemomentum_tpu.obs.trace import fleet_timeline
    entries = fleet_timeline(_session_dir(run))
    if not entries:
        raise utils.UserException(
            f"No fleet telemetry under {str(_session_dir(run))!r}; expected "
            f"a cluster run directory (launcher telemetry.jsonl + "
            f"hosts/host-*.telemetry.jsonl)")
    t0 = entries[0]["t"]
    rows = [dict(entry, rel_s=entry["t"] - t0) for entry in entries]
    return pandas.DataFrame(rows)


def fleet_health(run):
    """One cluster run's health timeline: per-host step progress over
    wall time (clock-aligned), with the supervision story — fired
    faults, host deaths, liveness transitions, restart agreement —
    marked as vertical lines. The `obs_report` fleet section, as a
    plot."""
    from byzantinemomentum_tpu.obs.trace import host_progress
    run_dir = _session_dir(run)
    progress = host_progress(run_dir)
    frame = load_fleet_timeline(run)
    if not progress:
        raise utils.UserException(
            f"No per-host step gauges under {str(run_dir)!r}; the fleet "
            f"must run with PR 13+ host telemetry")
    t0 = min(series[0][0] for series in progress.values())
    t0 = min(t0, float(frame["t"].iloc[0]))
    plot = LinePlot()
    for host, series in sorted(progress.items()):
        sub = pandas.DataFrame(
            {f"host-{host} step": [step for _, step in series]},
            index=pandas.Index([t - t0 for t, _ in series],
                               name="Run time (s)"))
        plot.include(sub, f"host-{host} step", axkey="step")
    events = frame[frame["kind"] == "event"]
    for name, color in (("fault_injected", "red"), ("host_dead", "black"),
                        ("restart_agreed", "green"), ("wedge", "orange")):
        for _, event in events[events["name"] == name].iterrows():
            plot.vline(float(event["t"]) - t0, color=color, label=name)
    plot.finalize("Fleet health", "Run time (s)", "Host step")
    return frame, plot


# --------------------------------------------------------------------------- #
# Aggregation forensics (`--gar-diagnostics`): the GAR's per-step worker
# selection and the host-side suspicion scores, rendered as the paper's
# MECHANISM — which workers the robust rule trusts over time — rather than
# its downstream accuracy curves.

def selection_matrix(session):
    """`(sel, steps, nb_honests)` from a diagnostics run's study CSV:
    `sel` is a (nb_workers, T) 0/1 float matrix of the GAR's per-step
    selection (parsed from the ';'-joined 'Sel workers' column), `steps`
    the T step numbers, and `nb_honests` the honest row count (rows >=
    nb_honests are the attack-synthesized workers)."""
    import numpy as np

    data = _as_frame(session)
    if "Sel workers" not in data.columns:
        raise utils.UserException(
            "No 'Sel workers' column in the study data; the run must be "
            "recorded with --gar-diagnostics")
    if not isinstance(session, Session) or not session.json:
        raise utils.UserException(
            "worker selection needs the run's config.json (worker counts)")
    n = int(session.json["nb_workers"])
    honests = n - int(session.json.get("nb_real_byz", 0))
    rows = data["Sel workers"].dropna()
    sel = np.zeros((n, len(rows)))
    for t, cell in enumerate(rows):
        cell = str(cell).strip()
        if cell in ("", "-"):
            continue
        for token in cell.split(";"):
            sel[int(token), t] = 1.0
    return sel, np.asarray(rows.index), honests


def worker_heatmap(session, window=None):
    """Selection frequency × worker × time heatmap of one diagnostics run.

    Each cell is the worker's selection frequency over a sliding `window`
    of steps (default: ~T/50, min 1 — raw 0/1 selection for short runs);
    attack workers (rows >= nb_honests) are bannered with a red frame +
    axis marker so the paper's mechanism — the robust GAR learning to
    exclude them as worker momentum shrinks the variance ratio — reads
    directly off the figure. Returns a `HeatmapPlot` (``.save``/
    ``.close``)."""
    import numpy as np

    sel, steps, honests = selection_matrix(session)
    n, T = sel.shape
    if T == 0:
        raise utils.UserException("No 'Sel workers' rows to plot")
    if window is None:
        window = max(1, T // 50)
    if window > 1:
        kernel = np.ones(window) / window
        freq = np.apply_along_axis(
            lambda r: np.convolve(r, kernel, mode="same"), 1, sel)
    else:
        freq = sel
    plot = HeatmapPlot()
    plot.render(freq, x=steps, title="Worker selection frequency",
                xlabel="Step number", ylabel="Worker",
                clabel="Selection frequency", banner_from=honests,
                banner_label="attack workers")
    return plot


def suspicion_timeline(session):
    """One diagnostics run's forensic timeline: the max per-worker
    suspicion score (`obs/forensics.py` EWMA, the 'Suspicion max' study
    column) over steps, with the run's `suspect_worker` /
    `suspect_cleared` telemetry events marked as vertical lines when a
    timeline is available."""
    data = _as_frame(session)
    if "Suspicion max" not in data.columns:
        raise utils.UserException(
            "No 'Suspicion max' column in the study data; the run must be "
            "recorded with --gar-diagnostics")
    sub = data.dropna(subset=["Suspicion max"])
    plot = LinePlot()
    plot.include(sub, "Suspicion max")
    try:
        frame = load_telemetry(session)
    except utils.UserException:
        frame = None
    if frame is not None:
        events = frame[frame["kind"] == "event"]
        for name, color in (("suspect_worker", "red"),
                            ("suspect_cleared", "green")):
            for _, event in events[events["name"] == name].iterrows():
                data_ = event.get("data")
                step = data_.get("step") if isinstance(data_, dict) else None
                if step is not None:
                    plot.vline(step, color=color, label=name)
    plot.finalize("Suspicion timeline", "Step number", "Suspicion max")
    return plot


def health_timeline(run):
    """One --health run's flight-recorder timeline: the weight and update
    norms (left axis, the blow-up channels) against the update-to-weight
    ratio (right axis), with the run's `health_anomaly`/`health_cleared`
    telemetry events marked as vertical lines and the per-phase
    non-finite total noted in the title."""
    session = run
    data = _as_frame(run)
    missing = [c for c in ("Weight norm", "Update/weight")
               if c not in data.columns]
    if missing:
        raise utils.UserException(
            f"No health columns {missing} in the study data; the run must "
            f"be recorded with --health")
    sub = data.dropna(subset=["Weight norm"])
    plot = LinePlot()
    plot.include(sub, "Weight norm", axkey="norm")
    if "Update norm" in sub.columns:
        plot.include(sub, "Update norm", axkey="norm")
    plot.include(sub, "Update/weight", axkey="ratio")
    try:
        frame = load_telemetry(session)
    except utils.UserException:
        frame = None
    if frame is not None:
        events = frame[frame["kind"] == "event"]
        for name, color in (("health_anomaly", "red"),
                            ("health_cleared", "green"),
                            ("health_flag", "black")):
            for _, event in events[events["name"] == name].iterrows():
                data_ = event.get("data")
                step = data_.get("step") if isinstance(data_, dict) else None
                if step is not None:
                    plot.vline(step, color=color, label=name)
    nonfinite = 0
    for column in ("Nonfinite submitted", "Nonfinite aggregate",
                   "Nonfinite state"):
        if column in data.columns:
            series = data[column].dropna()
            if len(series):
                nonfinite += int(series.sum())
    suffix = f" ({nonfinite} non-finite entries)" if nonfinite else ""
    plot.finalize("Health timeline" + suffix, "Step number", "L2 norm",
                  zlabel="Update/weight")
    return plot


def variance_envelope(run):
    """The paper's observable as a first-class plot: one --health run's
    Var ratio (the variance-to-norm ratio of the honest submissions) over
    steps, with anomaly edges marked — ALIE-style attacks live or die by
    whether they stay inside this envelope, and the SPC monitor's events
    show when the stream left its own history."""
    data = _as_frame(run)
    if "Var ratio" not in data.columns:
        raise utils.UserException(
            "No 'Var ratio' column in the study data; the run must be "
            "recorded with --health")
    sub = data.dropna(subset=["Var ratio"])
    plot = LinePlot()
    plot.include(sub, "Var ratio")
    try:
        frame = load_telemetry(run)
    except utils.UserException:
        frame = None
    if frame is not None:
        events = frame[frame["kind"] == "event"]
        for name, color in (("health_anomaly", "red"),
                            ("health_cleared", "green")):
            sel = events[events["name"] == name]
            for _, event in sel.iterrows():
                data_ = event.get("data")
                if not isinstance(data_, dict):
                    continue
                if data_.get("channel") not in (None, "var_ratio"):
                    continue
                step = data_.get("step")
                if step is not None:
                    plot.vline(step, color=color, label=name)
    plot.finalize("Variance envelope", "Step number", "Var ratio")
    return plot


def load_tournament(path):
    """Parse one tournament scoreboard artifact
    (`scripts/tournament.py` -> `TOURNAMENT_r*.json`)."""
    path = pathlib.Path(path)
    try:
        payload = json.loads(path.read_text())
    except (OSError, ValueError) as err:
        raise utils.UserException(
            f"Unable to read tournament artifact {str(path)!r}: {err}")
    if not isinstance(payload, dict) or payload.get("kind") != "tournament":
        raise utils.UserException(
            f"{str(path)!r} is not a tournament scoreboard "
            f"(kind != 'tournament')")
    return payload


def tournament_scoreboard(source, metric="agg_err_last10"):
    """Attack x GAR resilience heatmap from a tournament scoreboard:
    each cell is the PROTECTION RATIO `off / on` of `metric` (steady
    -state aggregate error by default) — above 1.0 the quarantine loop
    strictly helped against that attack on that rule, at 1.0 it was
    neutral. Returns `(matrix, attack_labels, gar_labels, HeatmapPlot)`.

    `source` is a scoreboard dict (`arena/tournament.py::run_tournament`)
    or an artifact path.
    """
    import numpy as np

    scoreboard = (source if isinstance(source, dict)
                  else load_tournament(source))
    cells = scoreboard.get("train_cells") or []
    if not cells:
        raise utils.UserException("Tournament scoreboard has no train cells")
    attacks = sorted({c["attack"] for c in cells})
    gars = sorted({c["gar"] for c in cells})
    value = {(c["attack"], c["gar"], bool(c["quarantine"])): c.get(metric)
             for c in cells}
    matrix = np.full((len(attacks), len(gars)), np.nan)
    for i, attack in enumerate(attacks):
        for j, gar in enumerate(gars):
            on = value.get((attack, gar, True))
            off = value.get((attack, gar, False))
            if on and off is not None:
                matrix[i, j] = off / on
    plot = HeatmapPlot()
    plot.render(np.nan_to_num(matrix, nan=0.0),
                title=f"Quarantine protection (off/on {metric})",
                xlabel="GAR", ylabel="attack",
                clabel="protection ratio (>1 = quarantine wins)",
                cmap="RdYlGn")
    # Name the grid axes (the generic renderer labels rows numerically)
    plot._ax.set_xticks(range(len(gars)))
    plot._ax.set_xticklabels(gars, rotation=45, ha="right", fontsize=7)
    plot._ax.set_yticks(range(len(attacks)))
    plot._ax.set_yticklabels(attacks, fontsize=7)
    plot._fig.tight_layout()
    return matrix, attacks, gars, plot


# --------------------------------------------------------------------------- #
# Interactive DataFrame viewer (reference `study.py:44-78`, `:129-180`:
# a GTK3 TreeView window, degrading to a warning when GTK is unavailable)

def _to_string(x):
    """Float-aware cell formatting (reference `study.py:133-143`)."""
    if type(x) is float:
        return f"{x:e}"
    return str(x).strip()


try:
    import gi
    gi.require_version("Gtk", "3.0")
    from gi.repository import Gtk, GLib  # noqa: F401

    import atexit
    import threading

    _gtk_lock = threading.Lock()
    _gtk_main = None

    def _gtk_run(closure):
        """Run a closure in the (lazily started) GTK main loop
        (reference `study.py:52-71`)."""
        global _gtk_main
        with _gtk_lock:
            if _gtk_main is None:
                def gtk_main():
                    atexit.register(Gtk.main_quit)
                    Gtk.main()
                _gtk_main = threading.Thread(
                    target=gtk_main, name="gtk_main", daemon=True)
                _gtk_main.start()
        GLib.idle_add(closure)

    class _DataFrameDisplayWindow(Gtk.Window):
        """Scrollable TreeView of a DataFrame (reference `study.py:130-175`)."""

        def __init__(self, data, title="Display data"):
            super().__init__(title=title)
            store = Gtk.ListStore(*([str] * (len(data.columns) + 1)))
            for row in data.itertuples():
                store.append([_to_string(x) for x in row])
            view = Gtk.TreeView(store)
            columns = [data.index.name] + list(data.columns)
            for i, cname in enumerate(columns):
                view.append_column(Gtk.TreeViewColumn(
                    cname, Gtk.CellRendererText(), text=i))
            scrolled = Gtk.ScrolledWindow()
            scrolled.set_hexpand(True)
            scrolled.set_vexpand(True)
            scrolled.add(view)
            self.add(scrolled)
            self.set_default_size(800, 600)

    def display(data, **kwargs):
        """Window-based display of a DataFrame (reference `study.py:177-184`)."""
        if isinstance(data, Session):
            data = data.data
        _gtk_run(lambda: _DataFrameDisplayWindow(data, **kwargs).show_all())

except Exception as _gtk_err:  # GTK unavailable: degrade exactly like the
    _gtk_reason = _gtk_err     # reference (warning, no viewer)

    def display(data, **kwargs):
        """Fallback when GTK 3.0 is unavailable: print a text rendering
        instead of opening a window (the reference only warns,
        reference `study.py:72-78`)."""
        utils.warning(f"GTK 3.0 is unavailable: {_gtk_reason}")
        if isinstance(data, Session):
            data = data.data
        if data is not None:
            print(data.to_string(max_rows=40))


# --------------------------------------------------------------------------- #
# Plotting

def _plt():
    import matplotlib
    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt
    return plt


LINESTYLES = ("-", "--", ":", "-.")


class LinePlot:
    """Line plot with optional ±error bands and up to two y-axes
    (reference `study.py:403-619`)."""

    def __init__(self, index=None):
        plt = _plt()
        self._fig, self._ax = plt.subplots()
        self._axs = {}
        self._tax = None
        self._idx = index
        self._cnt = 0
        self._fin = False

    def _get_ax(self, key):
        if key in self._axs:
            return self._axs[key]
        if len(self._axs) >= 2:
            raise RuntimeError("Line plot cannot have a 3rd y-axis")
        ax = self._ax if not self._axs else self._ax.twinx()
        if self._axs:
            self._tax = ax
        self._axs[key] = ax
        return ax

    def include(self, data, *cols, errs=None, lalp=1.0, label=None, ccnt=None,
                axkey=None):
        """Plot the given column(s) of a Session/DataFrame; a column named
        `<col><errs>` provides the ± band (reference `study.py:465-524`).
        `axkey` pins the y-axis: calls sharing an axkey share one axis even
        when their column names differ (the reference keys the axis by the
        column *query*, so e.g. both ratio curves land on one axis)."""
        if isinstance(data, Session):
            data = data.data
        x = data.index if self._idx is None else data[self._idx]
        for col in cols:
            ln = self._cnt if ccnt is None else ccnt
            style = LINESTYLES[ln % len(LINESTYLES)]
            color = f"C{ln}"
            ax = self._get_ax(axkey if axkey is not None else cols[0])
            y = data[col]
            ax.plot(x, y, style, color=color, alpha=lalp,
                    label=label or col)
            if errs is not None and (col + errs) in data.columns:
                e = data[col + errs]
                ax.fill_between(x, y - e, y + e, color=color, alpha=0.2 * lalp)
            self._cnt += 1
        return self

    def vline(self, x, color="gray", label=None):
        """Vertical event marker (telemetry overlays: rollbacks, restarts,
        faults on the `run_health` timeline). Repeated labels are legended
        once."""
        seen = getattr(self, "_vline_labels", set())
        self._vline_labels = seen
        self._ax.axvline(x, linestyle=":", color=color, linewidth=1,
                         label=None if label in seen else label)
        if label is not None:
            seen.add(label)
        return self

    def finalize(self, title, xlabel, ylabel, zlabel=None, xmin=None,
                 xmax=None, ymin=None, ymax=None, zmin=None, zmax=None,
                 legend=None):
        """Titles, labels, limits, legend (reference `study.py:526-579`)."""
        self._ax.set_title(title)
        self._ax.set_xlabel(xlabel)
        self._ax.set_ylabel(ylabel)
        self._ax.set_xlim(left=xmin, right=xmax)
        self._ax.set_ylim(bottom=ymin, top=ymax)
        if self._tax is not None:
            if zlabel is not None:
                self._tax.set_ylabel(zlabel)
            self._tax.set_ylim(bottom=zmin, top=zmax)
        handles, labels = self._ax.get_legend_handles_labels()
        if self._tax is not None:
            h2, l2 = self._tax.get_legend_handles_labels()
            handles += h2
            labels += l2
        if labels:
            self._ax.legend(handles, labels,
                            loc=legend if legend is not None else "best")
        self._fig.tight_layout()
        self._fin = True
        return self

    def display(self):
        self._fig.show()
        return self

    def save(self, path, dpi=200, xsize=3, ysize=2):
        self._fig.set_size_inches(xsize, ysize)
        self._fig.savefig(str(path), dpi=dpi, bbox_inches="tight")
        return self

    def close(self):
        import matplotlib.pyplot as plt
        plt.close(self._fig)


class HeatmapPlot:
    """Matrix heatmap (worker × time grids: `worker_heatmap`) with the
    same save/close surface as `LinePlot`/`BoxPlot`."""

    def __init__(self):
        plt = _plt()
        self._fig, self._ax = plt.subplots()

    def render(self, matrix, x=None, title=None, xlabel=None, ylabel=None,
               clabel=None, banner_from=None, banner_label=None,
               cmap="viridis"):
        """Draw `matrix` (rows × T) with one row per entity; `x` labels the
        columns (default 0..T-1). `banner_from` frames rows >= that index
        in red (the attack-worker banner) and tags them on the y-axis."""
        import numpy as np

        matrix = np.asarray(matrix, dtype=float)
        rows, T = matrix.shape
        x = np.arange(T) if x is None else np.asarray(x)
        extent = (float(x[0]) - 0.5, float(x[-1]) + 0.5, rows - 0.5, -0.5)
        im = self._ax.imshow(matrix, aspect="auto", interpolation="nearest",
                             cmap=cmap, vmin=0.0, extent=extent)
        cbar = self._fig.colorbar(im, ax=self._ax)
        if clabel is not None:
            cbar.set_label(clabel)
        if banner_from is not None and banner_from < rows:
            # Red frame around the attack-worker rows + a bracketed y-label
            self._ax.axhline(banner_from - 0.5, color="red", linewidth=1.5)
            labels = [str(r) if r < banner_from else f"{r}*"
                      for r in range(rows)]
            self._ax.set_yticks(range(rows))
            self._ax.set_yticklabels(labels)
            for tick, row in zip(self._ax.get_yticklabels(), range(rows)):
                if row >= banner_from:
                    tick.set_color("red")
            if banner_label:
                self._ax.text(
                    1.01, (banner_from + rows) / 2 / rows, banner_label,
                    transform=self._ax.transAxes, color="red", rotation=90,
                    va="center", ha="left", fontsize=8, clip_on=False)
        else:
            self._ax.set_yticks(range(rows))
        if title:
            self._ax.set_title(title)
        if xlabel:
            self._ax.set_xlabel(xlabel)
        if ylabel:
            self._ax.set_ylabel(ylabel)
        self._fig.tight_layout()
        return self

    def display(self):
        self._fig.show()
        return self

    def save(self, path, dpi=200, xsize=4, ysize=3):
        self._fig.set_size_inches(xsize, ysize)
        self._fig.savefig(str(path), dpi=dpi, bbox_inches="tight")
        return self

    def close(self):
        import matplotlib.pyplot as plt
        plt.close(self._fig)


class BoxPlot:
    """Box/violin overview across runs (reference `study.py:621-749`)."""

    def __init__(self, index=None):
        plt = _plt()
        self._fig, self._ax = plt.subplots()
        self._values = []
        self._labels = []
        self._hlines = []

    def include(self, data, label):
        """Add one distribution: a Session column selection, Series or
        array (reference `study.py:645-665`)."""
        if isinstance(data, Session):
            data = data.data
        values = getattr(data, "values", data)
        values = [v for v in list(values) if v == v]  # drop NaN
        self._values.append(values)
        self._labels.append(label)
        return self

    def hline(self, y):
        self._hlines.append(y)
        return self

    def finalize(self, title, ylabel, ymin=None, ymax=None, violin=False):
        if violin:
            self._ax.violinplot(self._values, showmedians=True)
            self._ax.set_xticks(range(1, len(self._labels) + 1))
            self._ax.set_xticklabels(self._labels, rotation=45, ha="right")
        else:
            self._ax.boxplot(self._values, tick_labels=self._labels)
            for tick in self._ax.get_xticklabels():
                tick.set_rotation(45)
                tick.set_ha("right")
        for y in self._hlines:
            self._ax.axhline(y, linestyle="--", color="gray", linewidth=1)
        self._ax.set_title(title)
        self._ax.set_ylabel(ylabel)
        self._ax.set_ylim(bottom=ymin, top=ymax)
        self._fig.tight_layout()
        return self

    def display(self):
        self._fig.show()
        return self

    def save(self, path, dpi=200, xsize=3, ysize=2):
        self._fig.set_size_inches(xsize, ysize)
        self._fig.savefig(str(path), dpi=dpi, bbox_inches="tight")
        return self

    def close(self):
        import matplotlib.pyplot as plt
        plt.close(self._fig)
