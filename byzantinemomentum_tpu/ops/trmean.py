"""Trimmed mean, Phocas and MeaMed GARs (reference `aggregators/trmean.py`).

All three are coordinate-wise rules over the stacked `(n, d)` matrix:
* trmean — sort each coordinate, average ranks [f, n-f)
  (reference `aggregators/trmean.py:24-33`).
* phocas — trmean center, then mean of the n-f coordinate-wise closest
  values (reference `aggregators/trmean.py:81-94`).
* meamed — median center, then mean of the n-f closest
  (reference `aggregators/trmean.py:96-109`).
"""

import jax.numpy as jnp

from byzantinemomentum_tpu.ops import diag, pallas_sort, register
from byzantinemomentum_tpu.ops._common import (
    closest_mean, lower_median, masked_closest_mean, masked_lower_median,
    masked_trmean, pairwise_distances, sanitize_inf)

__all__ = ["trmean", "aggregate_trmean", "aggregate_phocas",
           "aggregate_meamed", "diagnose_trmean", "masked_phocas",
           "masked_meamed"]


def trmean(g, f):
    """Coordinate-wise mean of sorted ranks [f, n-f)
    (reference `aggregators/trmean.py:24-33`). NaN sorts last, so up to f NaN
    rows are trimmed away."""
    if pallas_sort.supported(g):
        return pallas_sort.trimmed_mean(g, f)  # fused single-pass TPU kernel
    n = g.shape[0]
    return jnp.mean(jnp.sort(g, axis=0)[f:n - f], axis=0)


def aggregate_trmean(gradients, f, **kwargs):
    return trmean(gradients, f)


def aggregate_phocas(gradients, f, **kwargs):
    g = gradients
    return closest_mean(g, trmean(g, f), g.shape[0] - f)


def aggregate_meamed(gradients, f, **kwargs):
    g = gradients
    return closest_mean(g, lower_median(g), g.shape[0] - f)


def masked_phocas(gradients, active, n_eff, f_eff, **kwargs):
    """Traced-count phocas (`faults/quorum.py` dispatch): the trimmed-mean
    center and the closest-mean stage both run over the active rows with
    traced counts — `masked_trmean` then `masked_closest_mean` keeping
    `n_eff - f_eff` values per coordinate. Equals
    `aggregate_phocas(gradients[active], f_eff)` for finite active rows."""
    n = gradients.shape[0]
    center = masked_trmean(gradients, active, f_eff, n_eff)
    m = jnp.clip(n_eff - f_eff, 1, n)
    return masked_closest_mean(gradients, active, center, m)


def masked_meamed(gradients, active, n_eff, f_eff, **kwargs):
    """Traced-count meamed: the median center over the active rows, then
    the `n_eff - f_eff` coordinate-wise closest active values."""
    n = gradients.shape[0]
    center = masked_lower_median(gradients, active, n_eff)
    m = jnp.clip(n_eff - f_eff, 1, n)
    return masked_closest_mean(gradients, active, center, m)


def _coordinate_aux(g, agg, trim_frac):
    """Shared coordinate-wise-rule aux: distance-to-aggregate scores (the
    natural per-worker deviation statistic for rules with no row
    selection), full-mass selection, the distance geometry, and the rule's
    per-worker trim fraction."""
    n = g.shape[0]
    dev = g - agg[None, :]
    scores = sanitize_inf(jnp.sqrt(jnp.sum(dev * dev, axis=1)))
    return diag.make_aux(
        n, scores=scores, selection=jnp.ones((n,), jnp.float32),
        dist=pairwise_distances(g), trim_frac=trim_frac)


def diagnose_trmean(gradients, f, **kwargs):
    """Diagnostics kernel: the trimmed mean plus the forensics aux —
    `trim_frac[i]` is the fraction of worker i's coordinates whose value
    fell outside the kept ranks [f, n-f) (the per-coordinate clip
    fraction, read per worker)."""
    agg = trmean(gradients, f)
    kept = diag.rank_kept_fraction(gradients, f)
    return agg, _coordinate_aux(gradients, agg, 1.0 - kept)


def diagnose_phocas(gradients, f, **kwargs):
    """Diagnostics kernel for phocas: trim fraction of the closest-mean
    stage (n-f values kept per coordinate, measured against the trmean
    center by deviation threshold — same tie convention as the kernel)."""
    g = gradients
    n = g.shape[0]
    center = trmean(g, f)
    agg = closest_mean(g, center, n - f)
    dev = jnp.abs(g - center[None, :])
    kept = diag.rank_kept_fraction(dev, f, n_low=0, n_high=n - f)
    return agg, _coordinate_aux(g, agg, 1.0 - kept)


def diagnose_meamed(gradients, f, **kwargs):
    """Diagnostics kernel for meamed (median-centered closest mean)."""
    g = gradients
    n = g.shape[0]
    center = lower_median(g)
    agg = closest_mean(g, center, n - f)
    dev = jnp.abs(g - center[None, :])
    kept = diag.rank_kept_fraction(dev, f, n_low=0, n_high=n - f)
    return agg, _coordinate_aux(g, agg, 1.0 - kept)


def check(gradients, f, **kwargs):
    n = gradients.shape[0]
    if n < 1:
        return f"Expected at least one gradient to aggregate, got {n}"
    if not isinstance(f, int) or f < 1 or n < 2 * f + 1:
        return f"Invalid number of Byzantine gradients to tolerate, got f = {f!r}, expected 1 <= f <= {(n - 1) // 2}"


register("trmean", aggregate_trmean, check, diagnose=diagnose_trmean)
register("phocas", aggregate_phocas, check, diagnose=diagnose_phocas)
register("meamed", aggregate_meamed, check, diagnose=diagnose_meamed)
