"""Trimmed mean, Phocas and MeaMed GARs (reference `aggregators/trmean.py`).

All three are coordinate-wise rules over the stacked `(n, d)` matrix:
* trmean — sort each coordinate, average ranks [f, n-f)
  (reference `aggregators/trmean.py:24-33`).
* phocas — trmean center, then mean of the n-f coordinate-wise closest
  values (reference `aggregators/trmean.py:81-94`).
* meamed — median center, then mean of the n-f closest
  (reference `aggregators/trmean.py:96-109`).
"""

import jax.numpy as jnp

from byzantinemomentum_tpu.ops import pallas_sort, register
from byzantinemomentum_tpu.ops._common import closest_mean, lower_median

__all__ = ["trmean", "aggregate_trmean", "aggregate_phocas", "aggregate_meamed"]


def trmean(g, f):
    """Coordinate-wise mean of sorted ranks [f, n-f)
    (reference `aggregators/trmean.py:24-33`). NaN sorts last, so up to f NaN
    rows are trimmed away."""
    if pallas_sort.supported(g):
        return pallas_sort.trimmed_mean(g, f)  # fused single-pass TPU kernel
    n = g.shape[0]
    return jnp.mean(jnp.sort(g, axis=0)[f:n - f], axis=0)


def aggregate_trmean(gradients, f, **kwargs):
    return trmean(gradients, f)


def aggregate_phocas(gradients, f, **kwargs):
    g = gradients
    return closest_mean(g, trmean(g, f), g.shape[0] - f)


def aggregate_meamed(gradients, f, **kwargs):
    g = gradients
    return closest_mean(g, lower_median(g), g.shape[0] - f)


def check(gradients, f, **kwargs):
    n = gradients.shape[0]
    if n < 1:
        return f"Expected at least one gradient to aggregate, got {n}"
    if not isinstance(f, int) or f < 1 or n < 2 * f + 1:
        return f"Invalid number of Byzantine gradients to tolerate, got f = {f!r}, expected 1 <= f <= {(n - 1) // 2}"


register("trmean", aggregate_trmean, check)
register("phocas", aggregate_phocas, check)
register("meamed", aggregate_meamed, check)
