"""Aksel GAR (reference `aggregators/aksel.py`).

Coordinate-wise median center, rank workers by squared L2 distance to it,
average the c closest — c = (n+1)//2 in 'mid' mode, n-f in 'n-f' mode
(reference `aggregators/aksel.py:24-64`).
"""

import jax.numpy as jnp

from byzantinemomentum_tpu.ops import diag, register
from byzantinemomentum_tpu.ops._common import (
    lower_median, masked_lower_median, masked_rank_mean,
    pairwise_distances, row_sum_stable, sanitize_inf,
    selection_influence)

__all__ = ["aggregate", "aggregate_masked", "diagnose", "selection"]


def _count(n, f, mode):
    if mode == "mid":
        return (n + 1) // 2
    if mode == "n-f":
        return n - f
    raise NotImplementedError(f"Unknown aksel mode {mode!r}")


def selection(gradients, f, mode="mid", **kwargs):
    """Indices of the c gradients closest (squared L2) to the median
    (reference `aggregators/aksel.py:24-53`); non-finite distances rank last."""
    n = gradients.shape[0]
    med = lower_median(gradients)
    sqd = sanitize_inf(jnp.sum((gradients - med[None, :]) ** 2, axis=1))
    return jnp.argsort(sqd, stable=True)[:_count(n, f, mode)]


def aggregate(gradients, f, mode="mid", **kwargs):
    """Aksel rule (reference `aggregators/aksel.py:55-64`)."""
    return jnp.mean(gradients[selection(gradients, f, mode)], axis=0)


def aggregate_masked(gradients, active, n_eff, f_eff, mode="mid", **kwargs):
    """Traced-count aksel (`faults/quorum.py` dispatch): the median center
    over the active rows, squared distances with inactive rows forced to
    +inf, and the `c` closest active rows averaged with a traced count —
    `c = (n_eff + 1) // 2` ('mid') or `n_eff - f_eff` ('n-f'). The mean
    sums selected rows in index order (`_common.masked_rank_mean` note);
    equal to `aggregate(gradients[active], f_eff, mode)` up to summation
    order, bit-stable across paddings of the same active set."""
    n = gradients.shape[0]
    med = masked_lower_median(gradients, active, n_eff)
    # row_sum_stable: the d axis is the padded bucket axis in serving
    sqd = sanitize_inf(row_sum_stable((gradients - med[None, :]) ** 2))
    if mode == "mid":
        c = (n_eff + 1) // 2
    elif mode == "n-f":
        c = n_eff - f_eff
    else:
        raise NotImplementedError(f"Unknown aksel mode {mode!r}")
    return masked_rank_mean(gradients, sqd, active, jnp.clip(c, 1, n))


def diagnose(gradients, f, mode="mid", **kwargs):
    """Diagnostics kernel: the aksel aggregate plus the forensics aux —
    squared median distances as scores, the c-closest membership as the
    selection mask (the distance matrix is diagnostics-only here: the rule
    itself never needs it)."""
    n = gradients.shape[0]
    sel = selection(gradients, f, mode)
    agg = jnp.mean(gradients[sel], axis=0)
    med = lower_median(gradients)
    sqd = sanitize_inf(jnp.sum((gradients - med[None, :]) ** 2, axis=1))
    return agg, diag.make_aux(
        n, scores=sqd, selection=diag.selection_from_indices(n, sel),
        dist=pairwise_distances(gradients))


def check(gradients, f, mode="mid", **kwargs):
    n = gradients.shape[0]
    if n < 1:
        return f"Expected at least one gradient to aggregate, got {n}"
    if not isinstance(f, int) or f < 1 or n < 2 * f + 1:
        return f"Invalid number of Byzantine gradients to tolerate, got f = {f!r}, expected 1 <= f <= {(n - 1) // 2}"
    if mode not in ("mid", "n-f"):
        return f"Invalid operation mode {mode!r}"


# Fraction of selected gradients that are Byzantine (reference
# `aggregators/aksel.py:83-105`)
influence = selection_influence(selection)


register("aksel", aggregate, check, influence=influence, diagnose=diagnose)
