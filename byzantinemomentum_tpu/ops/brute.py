"""Brute-force minimum-diameter GAR (reference `aggregators/brute.py`).

Enumerate every size-(n-f) subset, compute its diameter (max pairwise
distance), select the subset with minimal diameter, average it (reference
`aggregators/brute.py:32-80`). Subsets containing a non-finite distance are
dropped (diameter +inf here — equivalent as long as one finite subset
exists, which the reference asserts).

TPU design: the C(n, n-f) subset enumeration is data-independent, so the
combination index matrix is precomputed on the host (lexicographic order =
`itertools.combinations` = the reference's tie-break order, since
`jnp.argmin` returns the first minimum) and the per-subset diameters become
one vectorized gather + max over the (n, n) distance matrix.
`native-brute` is the standalone-jitted fast tier (stands in for
`native.brute.aggregate`, reference `brute.py:82-91`).
"""

import functools
import itertools

import jax
import jax.numpy as jnp
import numpy as np

from byzantinemomentum_tpu.ops import register
from byzantinemomentum_tpu.ops._common import pairwise_distances, selection_influence

__all__ = ["aggregate", "selection"]


@functools.lru_cache(maxsize=None)
def _combo_pairs(n, k):
    """Host-precomputed (C, k) combination indices and (C, k*(k-1)/2, 2) pair
    indices for diameter gathering."""
    combos = np.array(list(itertools.combinations(range(n), k)), dtype=np.int32)
    pair_pos = np.array(list(itertools.combinations(range(k), 2)), dtype=np.int32)
    px = combos[:, pair_pos[:, 0]]  # (C, P)
    py = combos[:, pair_pos[:, 1]]  # (C, P)
    return combos, px, py


def selection(gradients, f, *, method="dot", **kwargs):
    """Indices (as a (n-f,) array) of the minimum-diameter subset
    (reference `aggregators/brute.py:32-68`)."""
    n = gradients.shape[0]
    combos, px, py = _combo_pairs(n, n - f)
    dist = pairwise_distances(gradients, method=method)
    diam = jnp.max(dist[px, py], axis=1)  # (C,) — +inf if any pair non-finite
    best = jnp.argmin(diam)  # first minimum = lexicographically-first subset
    return jnp.asarray(combos)[best]


def aggregate(gradients, f, *, method="dot", **kwargs):
    """Brute rule (reference `aggregators/brute.py:70-80`)."""
    return jnp.mean(gradients[selection(gradients, f, method=method)], axis=0)


_jitted = jax.jit(aggregate, static_argnames=("f", "method"))


def aggregate_native(gradients, f, **kwargs):
    """Compiled fast tier (TPU equivalent of `native.brute.aggregate`)."""
    return _jitted(gradients, f)


def check(gradients, f, **kwargs):
    n = gradients.shape[0]
    if n < 1:
        return f"Expected at least one gradient to aggregate, got {n}"
    if not isinstance(f, int) or f < 1 or n < 2 * f + 1:
        return f"Invalid number of Byzantine gradients to tolerate, got f = {f!r}, expected 1 <= f <= {(n - 1) // 2}"


def upper_bound(n, f, d):
    """Variance-norm ratio bound (reference `aggregators/brute.py:107-116`)."""
    import math
    return (n - f) / (math.sqrt(8) * f)


# Fraction of selected gradients that are Byzantine (reference
# `aggregators/brute.py:118-140`)
influence = selection_influence(selection)


register("brute", aggregate, check, upper_bound=upper_bound, influence=influence)
register("native-brute", aggregate_native, check, upper_bound=upper_bound)
