"""Brute-force minimum-diameter GAR (reference `aggregators/brute.py`).

Enumerate every size-(n-f) subset, compute its diameter (max pairwise
distance), select the subset with minimal diameter, average it (reference
`aggregators/brute.py:32-80`). Subsets containing a non-finite distance are
dropped (diameter +inf here — equivalent as long as one finite subset
exists, which the reference asserts).

TPU design: subsets are enumerated by *rank* in the combinatorial number
system and unranked in-graph (a `lax.scan` over the n elements with a
host-precomputed binomial table), so memory is O(chunk · n²) regardless of
C(n, n-f) — the paper-scale CIFAR config n=25, f=11 has C(25,14) ≈ 4.46M
subsets, which a materialized index matrix would blow ~1.6 GB on while this
streams in bounded chunks (~80 MB; 50 ms total at that cell on a v5e). Lexicographic rank order matches
`itertools.combinations` = the reference's iteration order, and the
first-minimum tie-break is preserved exactly: within a chunk `argmin` takes
the lowest rank, across chunks a strict `<` keeps the earliest chunk's
winner. `native-brute` is the standalone-jitted fast tier (stands in for
`native.brute.aggregate`, reference `brute.py:82-91`).
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from byzantinemomentum_tpu.ops import diag, pallas_gar, register
from byzantinemomentum_tpu.ops._common import pairwise_distances, selection_influence

__all__ = ["aggregate", "aggregate_masked", "diagnose", "selection",
           "best_subset_mask_from_dist", "best_subset_mask_masked",
           "masked_rank_space", "MASKED_MAX_SUBSETS"]

# Subsets evaluated per chunk of the streaming enumeration: memory is
# O(CHUNK * n^2) floats — ~80 MB at n=25 — independent of C(n, n-f).
# The chunk is deliberately wide: each chunk pays the 25-step sequential
# unranking scan's kernel-launch latency once, so fewer/wider chunks are
# almost free (4096 -> 32768 measured 3x faster at the paper-scale
# n=25, f=11 cell: 4.46M subsets, 137 chunks instead of 1090)
CHUNK = 32768


@functools.lru_cache(maxsize=None)
def _binom_table(n, k):
    """(n+1, k+1) table of C(m, j) as int64 numpy (host-side)."""
    tbl = np.zeros((n + 1, k + 1), dtype=np.int64)  # bmt: noqa[BMT-E02] static (n, k) table built host-side at trace time, lru_cached — never touches a tracer
    tbl[:, 0] = 1
    for m in range(1, n + 1):
        for j in range(1, min(m, k) + 1):
            tbl[m, j] = tbl[m - 1, j - 1] + tbl[m - 1, j]
    return tbl


def _unrank_masks(ranks, n, k, tbl):
    """Lexicographic unranking, vectorized over a chunk of ranks:
    `i32[c] -> bool[c, n]` membership masks.

    Walk the elements 0..n-1; at element e with `need` slots left, there are
    C(n-e-1, need-1) subsets that include e — include e iff the remaining
    rank is below that count, else skip e and subtract the count.

    The binomial row C(n-e-1, ·) is static per step (fed through the scan
    inputs); the per-lane dynamic column lookup is a one-hot contraction
    over the k+1 columns instead of a gather — TPU gathers run near-scalar,
    and this lookup executes chunk-lanes x n times per defense call
    (gather -> one-hot measured ~20x on the whole rule at the n=25, f=11
    cell: 989 ms -> 50 ms).
    """
    cols = jnp.arange(k + 1, dtype=jnp.int32)
    # rows[e] = C(n-e-1, ·): the counts consulted at element e
    rows = tbl[jnp.arange(n - 1, -1, -1, dtype=jnp.int32)]

    def body(carry, row):
        r, need = carry
        j = jnp.maximum(need - 1, 0)
        onehot = j[:, None] == cols[None, :]
        count = jnp.sum(jnp.where(onehot, row[None, :], 0), axis=1)
        count = jnp.where(need > 0, count, 0)
        take = (need > 0) & (r < count)
        r = jnp.where(take, r, r - count)
        need = need - take.astype(need.dtype)
        return (r, need), take

    (_, _), masks = lax.scan(
        body, (ranks, jnp.full(ranks.shape, k, jnp.int32)), rows)
    return masks.T  # (n, c) -> (c, n)


def best_subset_mask_from_dist(dist, f):
    """bool[n] mask of the minimum-diameter size-(n-f) subset, from the
    (n, n) distance matrix (+inf diagonal). Shared by the single-chip path
    and the d-sharded kernel (`parallel/sharded.py`), which feeds a psum'd
    distance matrix."""
    n = dist.shape[0]
    k = n - f
    tbl_np = _binom_table(n, k)
    total = int(tbl_np[n, k])
    if total > np.iinfo(np.int32).max:
        raise ValueError(
            f"brute cannot enumerate C({n}, {k}) = {total} subsets (exceeds "
            f"int32 rank space; the reference's Python loop is equally "
            f"infeasible at this scale)")
    tbl = jnp.asarray(np.minimum(tbl_np, np.iinfo(np.int32).max)  # bmt: noqa[BMT-E02] clamps the static host-side binomial table before upload — no tracer involved
                      .astype(np.int32))
    # Diagonal is +inf by convention (for per-row sorts); the diameter wants
    # it excluded instead
    offdiag = ~jnp.eye(n, dtype=bool)

    chunk = min(CHUNK, total)
    nchunks = -(-total // chunk)

    def chunk_best(i, carry):
        best_diam, best_rank = carry
        # Clamping the tail padding to the last rank only duplicates it —
        # same diameter, same rank, tie-break unaffected
        ranks = jnp.minimum(i * chunk + jnp.arange(chunk, dtype=jnp.int32),
                            total - 1)
        masks = _unrank_masks(ranks, n, k, tbl)  # (chunk, n)
        pair = masks[:, :, None] & masks[:, None, :] & offdiag[None]
        diam = jnp.max(jnp.where(pair, dist[None], -jnp.inf), axis=(1, 2))
        cmin = jnp.min(diam)
        crank = ranks[jnp.argmin(diam)]  # first minimum within the chunk
        better = cmin < best_diam  # strict: earlier chunks win ties
        return (jnp.where(better, cmin, best_diam),
                jnp.where(better, crank, best_rank))

    _, best_rank = lax.fori_loop(
        0, nchunks, chunk_best, (jnp.float32(jnp.inf), jnp.int32(0)))
    return _unrank_masks(best_rank[None], n, k, tbl)[0]


# Ceiling on the STATIC rank space a traced-count (masked/bucketed) brute
# program may enumerate: the masked walk cannot know n_eff/f_eff at trace
# time, so it sizes its chunk loop for the worst case C(n, min(f_decl,
# (n-1)//2)). Beyond this many subsets the masked kernel is declined
# (`masked_rank_space` returns None) and callers keep the NaN-routing
# fallback / an exact serve cell — the same infeasibility discipline as
# the exact kernel's int32 rank-space check, drawn earlier because every
# serve warm-up pays the compile. ~61 chunks at the cap.
MASKED_MAX_SUBSETS = 2_000_000


def masked_rank_space(n, f_decl):
    """The static worst-case subset count a traced-count brute program
    over `n` rows with declared tolerance `f_decl` must provision for —
    `C(n, min(f_decl, (n-1)//2))`, the maximum of `C(n_eff, f_eff)` over
    every reachable `(n_eff <= n, f_eff <= f_decl)` — or None when it
    exceeds `MASKED_MAX_SUBSETS` (callers must route around the masked
    kernel)."""
    k = min(int(f_decl), max((n - 1) // 2, 0))
    total = math.comb(n, k)
    return total if total <= MASKED_MAX_SUBSETS else None


def _unrank_masks_masked(ranks, active, after, need0, n, tbl):
    """Traced-count lexicographic unranking over the ACTIVE rows:
    `i32[c] -> bool[c, n]` membership masks of the rank-th size-`need0`
    combination of the active indices (lexicographic in the full index
    order, which is the static kernel's order restricted to the active
    subset).

    The walk visits all n elements statically; an INACTIVE element is a
    no-op (no rank consumed, no slot filled). At an active element with
    `need` slots left there are `C(after[e], need - 1)` completions that
    include it — `after[e]` is the traced count of active elements past
    `e`, so BOTH table coordinates are dynamic: the row is resolved by a
    one-hot contraction over the (n+1) table rows once per element (shared
    across lanes), the column per lane exactly as the static walk does.
    """
    cols = jnp.arange(n + 1, dtype=jnp.int32)
    rows_hot = jnp.arange(n + 1, dtype=jnp.int32)

    def body(carry, inputs):
        r, need = carry
        act_e, a_e = inputs
        row = jnp.sum(jnp.where((rows_hot == a_e)[:, None], tbl, 0), axis=0)
        j = jnp.maximum(need - 1, 0)
        onehot = j[:, None] == cols[None, :]
        count = jnp.sum(jnp.where(onehot, row[None, :], 0), axis=1)
        count = jnp.where(need > 0, count, 0)
        take = act_e & (need > 0) & (r < count)
        r = jnp.where(take | ~act_e, r, r - count)
        need = need - take.astype(need.dtype)
        return (r, need), take

    (_, _), masks = lax.scan(
        body, (ranks, jnp.zeros(ranks.shape, jnp.int32) + need0),
        (active, after))
    return masks.T  # (n, c) -> (c, n)


def best_subset_mask_masked(dist, active, n_eff, f_eff, total_max):
    """Traced-count `best_subset_mask_from_dist`: the minimum-diameter
    size-(n_eff - f_eff) subset of the ACTIVE rows, enumerated over a
    chunk loop sized for the STATIC worst case `total_max`
    (`masked_rank_space`) with the surplus rank lanes clamped to the last
    real subset — the same tail-duplication trick the static kernel uses,
    so tie-breaking (first minimum in lexicographic order) is preserved
    exactly. `dist` must already carry +inf on inactive pairs' entries or
    not — inactive pairs are forced to +inf here either way."""
    n = dist.shape[0]
    pair = active[:, None] & active[None, :]
    dist = jnp.where(pair, dist, jnp.inf)
    k_eff = jnp.clip(n_eff - f_eff, 1, n)
    # C(m, j) for every m <= n, j <= n: entries never consulted may clamp
    # (consulted counts are completion counts <= total_eff <= total_max)
    tbl_np = _binom_table(n, n)
    tbl = jnp.asarray(np.minimum(tbl_np, np.iinfo(np.int32).max)
                      .astype(np.int32))
    # after[e] = active rows strictly past e (the dynamic table row)
    after = (jnp.sum(active.astype(jnp.int32))
             - jnp.cumsum(active.astype(jnp.int32))).astype(jnp.int32)
    # total_eff = C(n_eff, k_eff), read off the same table dynamically
    row_hot = (jnp.arange(n + 1, dtype=jnp.int32) == n_eff)[:, None]
    col_hot = (jnp.arange(n + 1, dtype=jnp.int32) == k_eff)[None, :]
    total_eff = jnp.maximum(jnp.sum(jnp.where(row_hot & col_hot, tbl, 0)), 1)
    offdiag = ~jnp.eye(n, dtype=bool)

    chunk = min(CHUNK, total_max)
    nchunks = -(-total_max // chunk)

    def chunk_best(i, carry):
        best_diam, best_rank = carry
        ranks = jnp.minimum(i * chunk + jnp.arange(chunk, dtype=jnp.int32),
                            total_eff - 1)
        masks = _unrank_masks_masked(ranks, active, after, k_eff, n, tbl)
        pairm = masks[:, :, None] & masks[:, None, :] & offdiag[None]
        diam = jnp.max(jnp.where(pairm, dist[None], -jnp.inf), axis=(1, 2))
        cmin = jnp.min(diam)
        crank = ranks[jnp.argmin(diam)]
        better = cmin < best_diam  # strict: earlier chunks win ties
        return (jnp.where(better, cmin, best_diam),
                jnp.where(better, crank, best_rank))

    _, best_rank = lax.fori_loop(
        0, nchunks, chunk_best, (jnp.float32(jnp.inf), jnp.int32(0)))
    return _unrank_masks_masked(
        best_rank[None], active, after, k_eff, n, tbl)[0]


def aggregate_masked(gradients, active, n_eff, f_eff, f_decl, *,
                     method="dot", **kwargs):
    """Dynamic-quorum brute: minimum-diameter subset of the active rows,
    averaged with a traced divisor. `f_decl` (static) sizes the
    enumeration's worst-case rank space; callers must have verified
    feasibility via `masked_rank_space` (the quorum layer and the serve
    bucket policy both do)."""
    n = gradients.shape[0]
    total_max = masked_rank_space(n, f_decl)
    if total_max is None:
        raise ValueError(
            f"brute masked kernel over {n} rows at f_decl={f_decl} "
            f"exceeds MASKED_MAX_SUBSETS; callers must route around it "
            f"(masked_rank_space)")
    dist = pairwise_distances(gradients, method=method)
    mask = best_subset_mask_masked(dist, active, n_eff, f_eff, total_max)
    k_eff = jnp.clip(n_eff - f_eff, 1, n)
    kept = jnp.where((mask & active)[:, None], gradients, 0)
    return jnp.sum(kept, axis=0) / k_eff.astype(gradients.dtype)


def _best_subset_mask(gradients, f, *, method="dot"):
    """bool[n] mask of the minimum-diameter size-(n-f) subset."""
    return best_subset_mask_from_dist(
        pairwise_distances(gradients, method=method), f)


def selection(gradients, f, *, method="dot", **kwargs):
    """Indices (as a (n-f,) array) of the minimum-diameter subset
    (reference `aggregators/brute.py:32-68`)."""
    n = gradients.shape[0]
    mask = _best_subset_mask(gradients, f, method=method)
    return jnp.nonzero(mask, size=n - f, fill_value=0)[0]


def aggregate(gradients, f, *, method="dot", **kwargs):
    """Brute rule (reference `aggregators/brute.py:70-80`)."""
    n = gradients.shape[0]
    mask = _best_subset_mask(gradients, f, method=method)
    if pallas_gar.supported(gradients):
        # Fused tier: the distances behind `mask` came from one streamed
        # Gram pass; the subset mean is the only other read of the matrix
        return pallas_gar.masked_rows_mean(mask, gradients, n - f)
    # where (not mask @ G): excluded rows may be all-NaN and 0*NaN = NaN
    kept = jnp.where(mask[:, None], gradients, 0)
    return jnp.sum(kept, axis=0) / (n - f)


def diagnose(gradients, f, *, method="dot", **kwargs):
    """Diagnostics kernel: the brute aggregate plus the forensics aux.
    `selection` is the minimum-diameter subset membership; `scores` are
    each worker's maximal distance TO that winning subset (members of a
    tight subset score low, the excluded far rows score high) — the
    per-worker read-off of the diameter objective."""
    n = gradients.shape[0]
    dist = pairwise_distances(gradients, method=method)
    mask = best_subset_mask_from_dist(dist, f)
    if pallas_gar.supported(gradients):
        agg = pallas_gar.masked_rows_mean(mask, gradients, n - f)
    else:
        kept = jnp.where(mask[:, None], gradients, 0)
        agg = jnp.sum(kept, axis=0) / (n - f)
    in_subset = mask[None, :] & ~jnp.eye(n, dtype=bool)
    scores = jnp.max(jnp.where(in_subset, dist, -jnp.inf), axis=1)
    return agg, diag.make_aux(
        n, scores=scores, selection=mask.astype(jnp.float32), dist=dist)


_jitted = jax.jit(aggregate, static_argnames=("f", "method"))


def aggregate_native(gradients, f, **kwargs):
    """Compiled fast tier (TPU equivalent of `native.brute.aggregate`)."""
    return _jitted(gradients, f)


def check(gradients, f, **kwargs):
    n = gradients.shape[0]
    if n < 1:
        return f"Expected at least one gradient to aggregate, got {n}"
    if not isinstance(f, int) or f < 1 or n < 2 * f + 1:
        return f"Invalid number of Byzantine gradients to tolerate, got f = {f!r}, expected 1 <= f <= {(n - 1) // 2}"


def upper_bound(n, f, d):
    """Variance-norm ratio bound (reference `aggregators/brute.py:107-116`)."""
    return (n - f) / (math.sqrt(8) * f)


# Fraction of selected gradients that are Byzantine (reference
# `aggregators/brute.py:118-140`)
influence = selection_influence(selection)


register("brute", aggregate, check, upper_bound=upper_bound,
         influence=influence, diagnose=diagnose)
register("native-brute", aggregate_native, check, upper_bound=upper_bound,
         diagnose=diagnose)
