"""Shared helpers for the GAR diagnostics path (aggregation forensics).

Every GAR can be called with `diagnostics=True` (`ops/__init__.py::GAR`),
returning `(aggregate, aux)` where `aux` is a pytree with ONE schema across
all rules — so a `--gars` mixture can `lax.switch` over diagnostic branches
(identical output structures are a switch requirement) and downstream
consumers (`engine/step.py`, `obs/forensics.py`, `study.worker_heatmap`)
never need per-GAR cases:

  scores     f32[n]    per-worker score in the rule's own metric — Krum
                       scores, CGE norms, aksel squared median distances,
                       mean deviation for coordinate-wise rules. Lower is
                       always "more central/trusted" (rules that rank
                       descending are negated on the way out).
  selection  f32[n]    how much of the aggregate each worker contributed:
                       the averaging-weight mass (1/m per selected row for
                       krum, per-round mass for bulyan stage 1, kept-rank
                       fraction for trmean), normalized so a fully-selected
                       worker reads 1.0. Coordinate-wise rules report the
                       per-worker fraction of coordinates that survived.
  dist       f32[n,n]  pairwise distance matrix (+inf diagonal, non-finite
                       -> +inf) — the geometry the selection acted on.
                       Rules that don't need distances for aggregation
                       (median/trmean/cge/...) compute it here anyway: the
                       diagnostics path is opt-in and off the hot path.
  trim_frac  f32[n]    coordinate-wise rules: fraction of each worker's
                       coordinates trimmed/ignored by the rule (1 - the
                       kept fraction); zeros for selection-based rules
                       (selection already carries the information).

Everything is computed in-jit as extra outputs of the same traced call —
no host round-trips mid-step. The `diagnostics=False` call never routes
through this module (the kernels' non-diagnostic code paths are untouched,
see the HLO-identity test in `tests/test_diag.py`).
"""

import jax.numpy as jnp

__all__ = ["AUX_KEYS", "make_aux", "distance_summary", "var_norm_ratio",
           "selection_from_indices", "rank_kept_fraction",
           "rank_kept_mask", "masked_generic_aux", "worker_mean_distance"]

# The uniform aux schema (dict keys, all always present).
AUX_KEYS = ("scores", "selection", "dist", "trim_frac")


def make_aux(n, *, scores=None, selection=None, dist=None, trim_frac=None):
    """Fill the uniform aux dict, zeroing whatever a rule has no native
    notion of (so mixture `lax.switch` branches agree on structure AND
    shapes)."""
    aux = {
        "scores": jnp.zeros((n,), jnp.float32) if scores is None
        else scores.astype(jnp.float32),
        "selection": jnp.zeros((n,), jnp.float32) if selection is None
        else selection.astype(jnp.float32),
        "dist": jnp.zeros((n, n), jnp.float32) if dist is None
        else dist.astype(jnp.float32),
        "trim_frac": jnp.zeros((n,), jnp.float32) if trim_frac is None
        else trim_frac.astype(jnp.float32),
    }
    return aux


def selection_from_indices(n, indices):
    """`i32[m] -> f32[n]` 0/1 selection mask from selected indices (the
    index-returning rules: aksel, cge)."""
    return jnp.zeros((n,), jnp.float32).at[indices].set(1.0)


def distance_summary(dist, rows=None):
    """(min, lower-median, max) over the finite off-diagonal distances of
    `dist[:rows]` — the honest-vs-all summary when `rows` = the honest
    count (+inf entries — the diagonal and non-finite rows — sort last and
    are excluded from min/median by construction; max falls back to the
    overall max so a fully non-finite slice reads +inf, not -inf)."""
    n = dist.shape[0]
    sub = dist if rows is None else dist[:rows]
    offdiag = ~jnp.eye(n, dtype=bool)[: sub.shape[0]]
    vals = jnp.where(offdiag, sub, jnp.inf).reshape(-1)
    srt = jnp.sort(vals)  # +inf (diagonal / corrupt) last
    count = sub.shape[0] * (n - 1)  # static: off-diagonal entry count
    dmin = srt[0]
    dmed = srt[(count - 1) // 2]
    finite = jnp.isfinite(srt)
    dmax = jnp.max(jnp.where(finite, srt, -jnp.inf))
    dmax = jnp.where(jnp.any(finite), dmax, jnp.inf)
    return dmin, dmed, dmax


def var_norm_ratio(G):
    """The paper's headline quantity for a submission stack `f32[m, d]`:
    (sample std-dev of the per-row deviations / norm of the row average)²
    — exactly the study pipeline's "(deviation/norm)²" ratio
    (`engine/metrics.py::avg_dev_max` composition), computed in-jit per
    step. NaN for m < 2 (no sample deviation), like the CSV columns."""
    m = G.shape[0]
    if m < 2:
        return jnp.float32(jnp.nan)
    avg = jnp.mean(G, axis=0)
    norm2 = jnp.sum(avg * avg)
    dev = G - avg
    dev2 = jnp.sum(dev * dev) / (m - 1)
    return (dev2 / norm2).astype(jnp.float32)


def worker_mean_distance(dist):
    """Per-worker mean pairwise distance to the FINITE peers — the
    engine's `Worker dist` recipe (`engine/metrics.py`): a row with no
    finite peer distance (fully corrupt, or a padded/inactive row whose
    distances are all +inf) reads +inf, so downstream z-scoring treats it
    as maximally far. Sums through the padding-stable contraction
    (`_common.row_sum_stable`) because the serve aux computes this over
    bucket-padded matrices and must match the exact cell bitwise."""
    from byzantinemomentum_tpu.ops import _common

    n = dist.shape[0]
    offdiag = ~jnp.eye(n, dtype=bool)
    finite = jnp.isfinite(dist) & offdiag
    count = jnp.sum(finite.astype(jnp.int32), axis=1)
    mean_d = (_common.row_sum_stable(jnp.where(finite, dist, 0.0))
              / jnp.maximum(count, 1).astype(jnp.float32))
    return jnp.where(count > 0, mean_d, jnp.inf)


def masked_generic_aux(G, aggregate, active, f_eff):
    """Rule-agnostic diagnostics for a MASKED aggregate over the active
    rows (the aggregation-service path, `serve/programs.py`).

    The rule-native diagnose kernels assume the static single-device
    layout; a served request is padded up to its shape bucket with
    inactive rows, so this computes the generic geometry around whatever
    masked aggregate the quorum layer produced (which stays
    authoritative — the PR 4 fault-step discipline):

      scores      distance of each row to the aggregate (+inf for
                  inactive/non-finite rows — `_generic_diagnose`'s score).
      selection   0/1 mass over the `n_eff - f_eff` most central ACTIVE
                  rows by that score (value-threshold rank membership, the
                  `closest_mean` trick, so no argsort+scatter; boundary
                  ties over-select by their multiplicity).
      worker_dist per-row mean finite pairwise distance (the engine's
                  `Worker dist` vector feeding suspicion z-scores).

    Inactive rows are routed to NaN first, so every distance involving
    them is +inf and they can neither score centrally nor be selected —
    identical to the kernels' documented worst-case routing.
    """
    from byzantinemomentum_tpu.ops import _common

    n = G.shape[0]
    routed = jnp.where(active[:, None], G, jnp.asarray(jnp.nan, G.dtype))
    dist = _common.pairwise_distances(routed)
    dev = routed - aggregate[None, :]
    # row_sum_stable: d is the padded bucket axis (zero-padded columns of
    # an active row deviate by exactly 0 from the aggregate's zero padded
    # coordinates, and the stable contraction keeps the sum's bits)
    scores = _common.sanitize_inf(
        jnp.sqrt(_common.row_sum_stable(dev * dev)))
    n_eff = jnp.sum(active.astype(jnp.int32))
    keep = jnp.clip(n_eff - f_eff, 1, n)
    thresh = jnp.take(jnp.sort(scores), keep - 1)
    selection = (active & (scores <= thresh)).astype(jnp.float32)
    return {"scores": scores, "selection": selection,
            "worker_dist": worker_mean_distance(dist), "dist": dist}


def rank_kept_mask(g, f, n_low=None, n_high=None):
    """`bool[n, d]` coordinate-survival indicator of a coordinate-wise
    rank trim: kept iff the value lies within the sorted ranks
    `[n_low, n_high)` (defaults: trmean's `[f, n-f)`).

    Rank membership is decided by value thresholds (`sorted[n_low]` /
    `sorted[n_high - 1]` per coordinate) rather than a full (n, d) argsort
    + scatter: ties at the boundary count every tied worker as kept, which
    over-reports by at most the tie multiplicity and keeps the pass at one
    (n, d) sort — the same trick as `_common.closest_mean`. NaN coordinates
    never count as kept (comparisons with NaN are False). Shared by the
    single-device aux (`rank_kept_fraction`) and the d-sharded
    coordinate-wise diagnostics (`parallel/sharded.py` — each shard folds
    its local mask into width-aware partial counts).
    """
    n = g.shape[0]
    if n_low is None:
        n_low = f
    if n_high is None:
        n_high = n - f
    srt = jnp.sort(g, axis=0)  # NaN sorts last
    lo = srt[n_low]
    hi = srt[n_high - 1]
    return (g >= lo) & (g <= hi)


def rank_kept_fraction(g, f, n_low=None, n_high=None):
    """Per-worker fraction of coordinates surviving the rank trim
    (`rank_kept_mask` averaged over the coordinate axis)."""
    kept = rank_kept_mask(g, f, n_low=n_low, n_high=n_high)
    return jnp.mean(kept.astype(jnp.float32), axis=1)
