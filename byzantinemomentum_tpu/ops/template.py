"""Extension skeleton for a new GAR (parity with reference
`aggregators/template.py`; workflow documented in the reference
`README.md:151-159`).

Copy this file, rename the functions, and the rule self-registers at
import through the plugin loader (`ops/__init__.py`). A GAR kernel is a
pure function over the stacked gradient matrix; keep `f` and any other
structural arguments static (Python ints/strings) so jit can specialize.

Like the reference (`aggregators/template.py:59`), the skeleton itself
registers a runnable `"template"` entry whose `check` always fails with a
template message — `--gar template` resolves by name and then reports it is
template code, exactly as the reference does.
"""

__all__ = []


def aggregate(gradients, f, **kwargs):
    """Aggregate the (n, d) gradient matrix into a (d,) gradient.

    Args:
      gradients: f32[n, d] stacked worker gradients.
      f: static int, declared Byzantine tolerance.
      **kwargs: rule-specific arguments from `--gar-args` (auto-typed).
    Returns:
      f32[d] aggregated gradient.
    """
    raise NotImplementedError(
        "I am template code, please replace me with useful stuff")


def check(gradients, f, **kwargs):
    """Return None if the arguments are valid, an error message otherwise.

    The template always declines (reference `aggregators/template.py:33-42`)."""
    return "I am template code, you should not be using me"


def upper_bound(n, f, d):
    """Optional: the paper's variance-norm ratio bound for this rule."""
    raise NotImplementedError(
        "I am optional (but still template) code, please replace me with "
        "useful stuff or delete me")


def influence(honests, byzantines, f, **kwargs):
    """Optional: fraction of Byzantine gradients accepted by the rule."""
    return None


from byzantinemomentum_tpu.ops import register  # noqa: E402

register("template", aggregate, check, upper_bound=upper_bound)
