"""Shared primitives for the GAR kernels.

Semantics pinned against the reference implementation (PyTorch, circa 1.x):

* Sorting places NaN last (torch.sort and jnp.sort agree on this).
* "Median" means the *lower* median: `sorted[(n - 1) // 2]` — torch's
  convention for even n, and the NaN-resilient behavior the reference's
  median GAR documents (reference `aggregators/median.py:13`): with
  f < n/2 NaN rows, NaNs sort last and the lower median stays finite.
* Pairwise distances treat any non-finite value as +inf (reference
  `aggregators/krum.py:46-48`, `bulyan.py:51-53`).
* Selection ties resolve by stable sort order (Python's `list.sort` is
  stable; `jnp.argsort(stable=True)` matches index-order tie-breaking).
"""

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu.ops import pallas_gar, pallas_sort

__all__ = [
    "all_finite_from_dist",
    "averaged_median",
    "distances_from_sq_gram",
    "lower_median",
    "masked_closest_mean",
    "masked_lower_median",
    "masked_mean",
    "masked_rank_mean",
    "masked_trmean",
    "masked_weighted_rows_mean",
    "pairwise_distances",
    "closest_mean",
    "row_sum_stable",
    "sanitize_inf",
    "selection_influence",
    "weighted_rows_mean",
]


def row_sum_stable(x):
    """Row-wise sum over the minor axis, stable under appended zero
    columns: `f32[n, k] -> f32[n]`.

    XLA lowers `jnp.sum(x, axis=1)` to a reduce whose accumulation
    grouping depends on the STATIC width (SIMD lane splits), so the same
    real values summed at width k and at a zero-padded width k' can
    differ in the last ulp — which breaks the shape-bucket ladder's
    bit-exactness contract (`serve/programs.py`). A batched dot
    contraction (`einsum('nk,nk->n')`, precision=HIGHEST) accumulates
    its K loop sequentially on every backend we pin goldens for, so
    appended zeros are exact identities. Every traced-count masked
    kernel whose reduction crosses a PADDABLE axis (the n axis of rank
    -masked score sums, the d axis of deviation norms) sums through
    this instead of `jnp.sum`.
    """
    return jnp.einsum("nk,nk->n", x, jnp.ones_like(x),
                      precision=jax.lax.Precision.HIGHEST)


def weighted_rows_mean(w, gradients, all_finite=None, then=None):
    """`w @ gradients` with row-selection non-finite semantics.

    `w: f32[n] | f32[r, n]` holds averaging weights (0 on unselected rows).
    A dynamic row-gather + mean is the slow path on TPU, so selection-based
    GARs (krum, bulyan stage 1) express their selected-row averages as this
    matmul instead. Non-finite handling matches the gather-mean it replaces:
    unselected (zero-weight) non-finite rows are excluded (0 * NaN must not
    poison the product), while a non-finite entry in a SELECTED row — only
    possible beyond the f-contract — propagates NaN to exactly its
    coordinate(s). (The gather-mean would yield NaN or ±inf there depending
    on the entry; this normalizes to NaN.)

    The masking path costs ~5 extra full passes over the (n, d) matrix
    (zeroed copy, f32 indicator, second matmul) — at WRN scale (d = 36.5M)
    that is gigabytes of HBM traffic per defense call, paid on every
    healthy step for a beyond-contract degeneracy. `lax.cond` takes the
    plain-matmul branch whenever the matrix is all-finite (TPU executes
    only the taken branch), so the masking machinery runs exactly when a
    non-finite value is actually present.

    `all_finite`: optional precomputed bool predicate. Callers that already
    hold the pairwise-distance matrix derive it for free from its
    off-diagonal finiteness (`all_finite_from_dist`) instead of this
    function re-reading the whole (n, d) matrix. A conservative False
    (e.g. a legitimately huge row whose squared norm overflows) only means
    taking the exact masked path.

    `then`: optional continuation applied to the product INSIDE the cond
    branches, so only its (typically much smaller) result is the
    conditional's output instead of the (rounds, d) stack. Measured
    neutral on v5e at WRN scale (XLA already avoids a physical copy at
    the conditional boundary — a trace's `conditional` row double-counts
    its branch fusions); kept because it can only shrink the boundary
    value and reads more directly ("aggregate the selection" as one unit).
    """
    # Fused-kernel tier (`ops/pallas_gar.py`): one streamed read of the
    # (n, d) matrix, the masked form computed unconditionally in VMEM
    # (identical to the fast branch when all-finite — see the kernel), so
    # the `all_finite` predicate and the cond disappear. `then`
    # continuations keep the jnp path (the only such caller, bulyan,
    # routes to its own fully-fused kernel in `ops/bulyan.py`).
    if then is None and pallas_gar.supported(gradients):
        return pallas_gar.weighted_rows_mean(w, gradients)

    def fast(g):
        out = jnp.matmul(w, g, precision=jax.lax.Precision.HIGHEST)
        return then(out) if then is not None else out

    def masked(g):
        finite = jnp.where(jnp.isfinite(g), g, 0.0)
        out = jnp.matmul(w, finite, precision=jax.lax.Precision.HIGHEST)
        nonfin = (~jnp.isfinite(g)).astype(jnp.float32)
        sel = (w > 0).astype(jnp.float32)
        bad = jnp.matmul(sel, nonfin,
                         precision=jax.lax.Precision.HIGHEST) > 0
        out = jnp.where(bad, jnp.nan, out)
        return then(out) if then is not None else out

    if all_finite is None:
        all_finite = jnp.all(jnp.isfinite(gradients))
    return jax.lax.cond(all_finite, fast, masked, gradients)


def all_finite_from_dist(dist):
    """Whether every gradient row behind a `pairwise_distances` matrix is
    finite, read off the matrix itself: any non-finite coordinate in row i
    makes every dist[i, j] (j != i) non-finite-then-+inf (NaN products stay
    NaN, inf squares stay inf, `sanitize_inf` maps both to +inf), so the
    off-diagonal being finite certifies the rows are. Overflowing-but-
    finite rows may report False — conservative (the caller takes its exact
    masked path). O(n^2), replaces a full (n, d) isfinite reduction."""
    n = dist.shape[0]
    offdiag = jnp.where(jnp.eye(n, dtype=bool), 0.0, dist)
    return jnp.all(jnp.isfinite(offdiag))


def selection_influence(selection_fn):
    """Build the 'fraction of selected gradients that are Byzantine'
    influence helper for a selection-based GAR.

    The reference computes this per GAR by identity comparison over the
    selected tensors (e.g. `aggregators/krum.py:126-150`); on the stacked
    matrix it is index-range membership: a selected index >= len(honests)
    is a Byzantine row. `selection_fn(gradients, f, **kwargs) -> i32[m]`.
    """
    def influence(honests, byzantines, f, **kwargs):
        gradients = jnp.concatenate([honests, byzantines], axis=0)
        sel = selection_fn(gradients, f, **kwargs)
        return jnp.mean((sel >= honests.shape[0]).astype(jnp.float32))
    return influence


def lower_median(g):
    """Coordinate-wise lower median over axis 0 with NaN-last ordering.

    `f32[n, d] -> f32[d]`; equals torch's `median(dim=0)` index convention
    (`sorted[(n-1)//2]`) and is NaN-resilient for < n/2 NaN rows.
    """
    if pallas_sort.supported(g):
        return pallas_sort.lower_median(g)  # fused single-pass TPU kernel
    n = g.shape[0]
    return jnp.sort(g, axis=0)[(n - 1) // 2]


def sanitize_inf(x):
    """Replace non-finite entries by +inf (Byzantine-distance convention)."""
    return jnp.where(jnp.isfinite(x), x, jnp.inf)


# --------------------------------------------------------------------------- #
# Masked / dynamic-quorum variants (`faults/quorum.py`)
#
# When the fault subsystem drops workers mid-run, the row count the GAR
# semantically operates on becomes a TRACED value (`n_eff = sum(active)`)
# while the matrix shape stays static. The variants below reproduce the
# corresponding static kernels exactly on the active subset: inactive rows
# are routed to the sort-last/never-selected conventions already used for
# non-finite values, and every static slice bound becomes a rank predicate
# against the traced count. (No Pallas tier — the fused kernels bake static
# indices; fault steps are rare enough that the jnp path is the right cost.)


def masked_mean(g, active, n_eff=None):
    """Arithmetic mean over the active rows only.

    `g: f32[n, d], active: bool[n] -> f32[d]`; equals
    `jnp.mean(g[active], axis=0)` with a traced mask (returns NaN for an
    empty active set, as the gather-mean would).
    """
    if n_eff is None:
        n_eff = jnp.sum(active.astype(jnp.int32))
    kept = jnp.where(active[:, None], g, jnp.zeros((), g.dtype))
    return jnp.sum(kept, axis=0) / n_eff.astype(g.dtype)


def masked_lower_median(g, active, n_eff=None):
    """Coordinate-wise lower median over the active rows only.

    Inactive rows are sent to NaN — sorting last, exactly the kernel's
    NaN-resilience convention — and the lower-median index is computed from
    the traced active count: `sorted[(n_eff - 1) // 2]`. Equals
    `lower_median(g[active])` for finite active rows.
    """
    if n_eff is None:
        n_eff = jnp.sum(active.astype(jnp.int32))
    gm = jnp.where(active[:, None], g, jnp.asarray(jnp.nan, g.dtype))
    idx = jnp.maximum(n_eff - 1, 0) // 2
    return jnp.take(jnp.sort(gm, axis=0), idx, axis=0)


def masked_trmean(g, active, f, n_eff=None):
    """Coordinate-wise trimmed mean over the active rows only: mean of the
    sorted active ranks `[f, n_eff - f)` with a traced `f` and count
    (`ops/trmean.py` semantics on the active subset; callers guarantee
    `n_eff > 2 f`)."""
    if n_eff is None:
        n_eff = jnp.sum(active.astype(jnp.int32))
    gm = jnp.where(active[:, None], g, jnp.asarray(jnp.nan, g.dtype))
    srt = jnp.sort(gm, axis=0)
    ranks = jnp.arange(g.shape[0])[:, None]
    take = (ranks >= f) & (ranks < n_eff - f)
    kept = jnp.where(take, srt, jnp.zeros((), g.dtype))
    return jnp.sum(kept, axis=0) / (n_eff - 2 * f).astype(g.dtype)


def masked_closest_mean(g, active, c, m):
    """Coordinate-wise mean of the `m` active values closest to center `c`,
    with a TRACED count: `g: f32[n, d], active: bool[n], c: f32[d],
    m: i32[] -> f32[d]`.

    The traced-count form of `closest_mean`: inactive rows take NaN
    deviations (sorting last, never below/at the threshold), the m-th
    smallest deviation is read at a traced rank, and the value-threshold
    tie-fill runs unchanged — so for finite active rows this equals
    `closest_mean(g[active], c, m)` bit for bit (the padded rows only
    append zeros to the kept-sum, and `jnp.cumsum` over their False tie
    indicators is the identity). Fewer than m finite active values per
    coordinate yields NaN, exactly like the static kernel.
    """
    n = g.shape[0]
    m = jnp.clip(m, 1, n)
    dev = jnp.abs(g - c[None, :])
    dev = jnp.where(active[:, None], dev, jnp.asarray(jnp.nan, dev.dtype))
    thresh = jnp.take(jnp.sort(dev, axis=0), m - 1, axis=0)
    lt = dev < thresh
    eq = dev == thresh
    need = m - jnp.sum(lt, axis=0)
    take = lt | (eq & (jnp.cumsum(eq, axis=0) <= need))
    out = jnp.sum(jnp.where(take, g, 0.0), axis=0) / m.astype(g.dtype)
    return jnp.where(jnp.isnan(thresh), jnp.nan, out)


def masked_rank_mean(g, scores, active, count):
    """Mean of the `count` lowest-score ACTIVE rows, with a TRACED count:
    `g: f32[n, d], scores: f32[n], active: bool[n], count: i32[] ->
    f32[d]`.

    The selection is stable-argsort rank membership (index-order ties,
    matching the reference kernels' Python `list.sort`); inactive rows are
    forced to +inf scores and excluded from the membership mask outright.
    The mean sums the selected rows in INDEX order — a static kernel that
    gathers rows in score order (`jnp.mean(g[sel])`, aksel/cge) associates
    its sum differently, so parity with those is exact-value only up to
    summation order; parity with another call of THIS kernel (the serve
    exact cell vs its padded bucket) is bit-exact.
    """
    n = g.shape[0]
    count = jnp.clip(count, 1, n)
    scores = jnp.where(active, scores, jnp.inf)
    order = jnp.argsort(scores, stable=True)
    ranks = jnp.zeros((n,), jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32))
    sel = (ranks < count) & active
    kept = jnp.where(sel[:, None], g, jnp.zeros((), g.dtype))
    return jnp.sum(kept, axis=0) / count.astype(g.dtype)


def masked_weighted_rows_mean(w, g, active):
    """`w @ g` over the active rows with the `weighted_rows_mean`
    non-finite semantics computed UNCONDITIONALLY (no all-finite
    `lax.cond`): inactive rows are zeroed (their garbage/NaN payload must
    not poison zero-weight products), non-finite entries in selected
    (w > 0) rows propagate NaN to exactly their coordinates. When every
    active row is finite this is bit-identical to the plain matmul — the
    same argument as the fused Pallas kernel's unconditional masked form —
    so one traced program serves both the healthy and the degraded case,
    which is what a traced-count kernel needs (a cond on a traced
    predicate would still lower both branches)."""
    kept = jnp.where(active[:, None], g, jnp.zeros((), g.dtype))
    finite = jnp.where(jnp.isfinite(kept), kept, 0.0)
    out = jnp.matmul(w, finite, precision=jax.lax.Precision.HIGHEST)
    nonfin = (~jnp.isfinite(kept)).astype(jnp.float32)
    sel = (w > 0).astype(jnp.float32)
    bad = jnp.matmul(sel, nonfin, precision=jax.lax.Precision.HIGHEST) > 0
    return jnp.where(bad, jnp.nan, out)


def pairwise_distances(g, *, squared=False, method="dot"):
    """All-pairs Euclidean distances over rows of `g: f32[n, d]`.

    Non-finite distances map to +inf; the diagonal is forced to +inf so
    per-row sorts naturally exclude self-distances.

    Args:
      g: (n, d) gradient matrix.
      squared: return squared distances (aksel uses squared, krum/bulyan/brute
        use plain norms — reference `aggregators/krum.py:42-48`,
        `aksel.py:37-40`).
      method: 'dot' uses the Gram-matrix identity ||x-y||² = ||x||²+||y||²-2x·y
        — one MXU matmul, O(n²) memory, the TPU-native fast path; 'diff'
        computes the difference reduction directly (bit-closer to the
        reference's `sub().norm()`, O(n²·d) VPU work that XLA fuses without
        materializing the (n, n, d) intermediate).
    Returns:
      (n, n) distance matrix, +inf on the diagonal.
    """
    n = g.shape[0]
    if method == "dot":
        if pallas_gar.supported(g):
            # Fused tier: the Gram accumulates tile by tile in VMEM — one
            # streamed read of the (n, d) matrix, no padded materialization
            # (`ops/pallas_gar.py`); the (n, n) post-processing below is
            # shared, so downstream selection semantics are identical
            gram = pallas_gar.sq_gram(g)
        else:
            # precision=HIGHEST: TPU matmuls default to bf16-decomposed
            # passes; distance orderings feed selection decisions, so keep
            # full f32. The row norms are the Gram diagonal — reading them
            # there instead of a separate sum(g*g) saves one full pass
            # over the (n, d) matrix
            gram = jnp.matmul(g, g.T, precision=jax.lax.Precision.HIGHEST)
        return distances_from_sq_gram(gram, squared=squared)
    if method != "diff":
        raise ValueError(f"Unknown pairwise distance method {method!r}")
    d2 = jax.vmap(lambda gi: jnp.sum((g - gi[None, :]) ** 2, axis=1))(g)
    d2 = sanitize_inf(d2)
    d2 = jnp.where(jnp.eye(n, dtype=bool), jnp.inf, d2)
    if squared:
        return d2
    return sanitize_inf(jnp.sqrt(d2))


def distances_from_sq_gram(gram, *, squared=False):
    """The `(n, n)` distance post-processing shared by the jnp Gram, the
    fused Pallas Gram (`ops/pallas_gar.py`) and the d-sharded psum'd Gram
    (`parallel/sharded.py`): row norms read off the diagonal,
    ||x-y||^2 = ||x||^2 + ||y||^2 - 2 x.y clamped at 0, non-finite -> +inf
    and a +inf diagonal."""
    n = gram.shape[0]
    sq = jnp.diagonal(gram)
    d2 = sq[:, None] + sq[None, :] - 2.0 * gram
    d2 = jnp.maximum(d2, 0.0)
    d2 = sanitize_inf(d2)
    d2 = jnp.where(jnp.eye(n, dtype=bool), jnp.inf, d2)
    if squared:
        return d2
    return sanitize_inf(jnp.sqrt(d2))


def averaged_median(g, m):
    """Bulyan's stage-2 "averaged median": coordinate-wise mean of the `m`
    values closest to the coordinate-wise lower median (reference
    `aggregators/bulyan.py:77-84`). For m == 1 the closest value to the
    median IS the median (it is a row element, deviation 0; all-NaN columns
    return NaN either way), so the closest_mean pass is skipped entirely —
    hit by the appendix grid's n=11, f=2 cell.

    Beyond-contract caveat: if a column's lower median is +/-inf (a majority
    of the selected stack non-finite in that coordinate — only reachable
    past the f-contract), the shortcut returns that inf, while
    `closest_mean(g, med, 1)` would return the nearest FINITE row value
    (|finite - inf| = inf sorts before the inf row's NaN self-deviation).
    The shortcut's answer is the defensible one (the median of the selected
    stack), and the input is outside every GAR's guarantee, so the
    divergence is documented rather than branched on. Shared by the
    single-device
    rule (`ops/bulyan.py`) and the d-sharded kernel
    (`parallel/sharded.py`)."""
    med = lower_median(g)
    if m == 1:
        return med
    return closest_mean(g, med, m)


def closest_mean(g, c, m):
    """Coordinate-wise mean of the `m` values closest to center `c`.

    `g: f32[n, d], c: f32[d], m: static int -> f32[d]` — the shared helper
    behind phocas/meamed (reference `aggregators/trmean.py:35-50`) and
    Bulyan's averaged median (reference `aggregators/bulyan.py:77-84`).
    NaN deviations sort last, so NaN rows are excluded whenever m <= number
    of finite values per coordinate.
    """
    if pallas_sort.supported(g) and c.ndim == 1 and c.dtype == g.dtype:
        return pallas_sort.closest_mean(g, c, m)  # fused TPU kernel
    dev = jnp.abs(g - c[None, :])
    # Selection WITHOUT the (n, d) argsort + gather (which costs ~8x the
    # rest of Bulyan on TPU): per coordinate, take everything strictly below
    # the m-th smallest deviation, then fill the remainder from the ties at
    # that threshold in index order — exactly the stable-argsort semantics.
    # Only `dev` is sorted (values, no index materialization, no gather).
    thresh = jnp.sort(dev, axis=0)[m - 1]
    lt = dev < thresh
    eq = dev == thresh
    need = m - jnp.sum(lt, axis=0)
    take = lt | (eq & (jnp.cumsum(eq, axis=0) <= need))
    out = jnp.sum(jnp.where(take, g, 0.0), axis=0) / m
    # If fewer than m finite values exist, the stable argsort would select a
    # NaN row (NaN sorts last) and the mean would be NaN — preserve that
    return jnp.where(jnp.isnan(thresh), jnp.nan, out)
