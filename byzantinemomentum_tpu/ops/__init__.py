"""Gradient aggregation rules (GARs) — the algorithmic kernels.

Every GAR is a pure function over the *stacked* gradient matrix
`G: f32[n, d]` (one row per worker) with a static Byzantine tolerance `f`,
returning the aggregated gradient `f32[d]`. This is the TPU-native redesign
of the reference's list-of-flat-tensors contract
(reference `aggregators/__init__.py:15-31`): stacking lets XLA tile the
sorts / pairwise distances / reductions onto the VPU/MXU, and the whole GAR
inlines into the jitted training step.

Registry parity with the reference (`aggregators/__init__.py:42-97`): each
registered GAR exposes `.checked` (argument-validating wrapper), `.unchecked`
(raw kernel), `.check`, `.upper_bound` (variance-norm ratio bound consumed by
the study pipeline) and `.influence` (attack acceptation ratio). The registry
maps `name -> GAR` in the module-level `gars` dict; modules in this directory
self-register at import (same plugin pattern as the reference).

A second, compiled fast tier is registered under `native-<name>` for the four
GARs the reference accelerates natively (median, krum, bulyan, brute —
reference `aggregators/median.py:41-49` etc.): on TPU the "native" tier is the
jit-compiled kernel with the MXU-friendly dot-product distance path.
"""

import pathlib

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu import utils

__all__ = ["gars", "register", "GAR", "as_matrix"]

# Registry: name -> GAR
gars = {}


def as_matrix(gradients):
    """Coerce a list of flat gradients or an (n, d) array into an (n, d) jnp
    matrix (the canonical GAR input)."""
    if isinstance(gradients, (list, tuple)):
        return jnp.stack([jnp.asarray(g) for g in gradients])
    gradients = jnp.asarray(gradients)
    if gradients.ndim != 2:
        raise utils.UserException(
            f"Expected an (n, d) gradient matrix or a list of flat gradients, got shape {gradients.shape}")
    return gradients


class GAR:
    """A registered gradient aggregation rule.

    Calling the GAR object runs the checked path; `.unchecked` is the raw
    kernel (mirrors the reference's `__debug__` switch,
    `aggregators/__init__.py:60-61`, without requiring `python -OO`).
    """

    def __init__(self, name, unchecked, check, upper_bound=None, influence=None):
        self.name = name
        self.unchecked = unchecked
        self.check = check
        self.upper_bound = upper_bound
        self.influence = influence

    def checked(self, gradients, **kwargs):
        gradients = as_matrix(gradients)
        message = self.check(gradients=gradients, **kwargs)
        if message is not None:
            raise utils.UserException(f"Aggregation rule {self.name!r} cannot be used: {message}")
        result = self.unchecked(gradients, **kwargs)
        if result.shape != gradients.shape[1:]:
            raise utils.UserException(
                f"Aggregation rule {self.name!r} returned shape {result.shape}, expected {gradients.shape[1:]}")
        return result

    def __call__(self, gradients, **kwargs):
        return self.checked(gradients, **kwargs)

    def __repr__(self):
        return f"GAR({self.name!r})"


def register(name, unchecked, check, upper_bound=None, influence=None):
    """Register a GAR under `name` (reference `aggregators/__init__.py:42-86`).

    Args:
      name: registry key.
      unchecked: kernel `(G: f32[n,d], **kwargs) -> f32[d]`.
      check: `(gradients, **kwargs) -> None | str` validity test.
      upper_bound: optional `(n, f, d) -> float` theoretical ratio bound.
      influence: optional `(honests, byzantines, **kwargs) -> float` attack
        acceptation ratio.
    Returns:
      The GAR object.
    """
    if name in gars:
        utils.warning(f"Aggregation rule {name!r} registered twice; keeping the last")
    gar = GAR(name, unchecked, check, upper_bound=upper_bound, influence=influence)
    gars[name] = gar
    return gar


# Self-registering kernel modules (plugin pattern, reference
# `aggregators/__init__.py:91-97`)
utils.import_directory(__name__, pathlib.Path(__file__).parent)
