"""Gradient aggregation rules (GARs) — the algorithmic kernels.

Every GAR is a pure function over the *stacked* gradient matrix
`G: f32[n, d]` (one row per worker) with a static Byzantine tolerance `f`,
returning the aggregated gradient `f32[d]`. This is the TPU-native redesign
of the reference's list-of-flat-tensors contract
(reference `aggregators/__init__.py:15-31`): stacking lets XLA tile the
sorts / pairwise distances / reductions onto the VPU/MXU, and the whole GAR
inlines into the jitted training step.

Registry parity with the reference (`aggregators/__init__.py:42-97`): each
registered GAR exposes `.checked` (argument-validating wrapper), `.unchecked`
(raw kernel), `.check`, `.upper_bound` (variance-norm ratio bound consumed by
the study pipeline) and `.influence` (attack acceptation ratio). The registry
maps `name -> GAR` in the module-level `gars` dict; modules in this directory
self-register at import (same plugin pattern as the reference).

A second, compiled fast tier is registered under `native-<name>` for the four
GARs the reference accelerates natively (median, krum, bulyan, brute —
reference `aggregators/median.py:41-49` etc.): on TPU the "native" tier is the
jit-compiled kernel with the MXU-friendly dot-product distance path.
"""

import pathlib

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu import utils

__all__ = ["gars", "register", "GAR", "as_matrix"]

# Registry: name -> GAR
gars = {}


def as_matrix(gradients):
    """Coerce a list of flat gradients or an (n, d) array into an (n, d) jnp
    matrix (the canonical GAR input)."""
    if isinstance(gradients, (list, tuple)):
        # stack converts its inputs itself; a per-element asarray would be
        # a redundant conversion (jaxlint BMT-E07 keeps it out)
        return jnp.stack(gradients)
    gradients = jnp.asarray(gradients)
    if gradients.ndim != 2:
        raise utils.UserException(
            f"Expected an (n, d) gradient matrix or a list of flat gradients, got shape {gradients.shape}")
    return gradients


class GAR:
    """A registered gradient aggregation rule.

    Calling the GAR object runs the checked path; `.unchecked` is the raw
    kernel (mirrors the reference's `__debug__` switch,
    `aggregators/__init__.py:60-61`, without requiring `python -OO`).

    `gar(G, f=..., diagnostics=True)` returns `(aggregate, aux)` instead:
    the in-jit forensics path (`ops/diag.py` schema — per-worker scores,
    selection mass, pairwise distances, trim fractions). `diagnostics` is a
    TRACE-TIME Python switch, never a traced value: the False call routes
    through the exact pre-diagnostics kernel (`.unchecked`) so the hot path
    lowers to identical HLO (`tests/test_diag.py`). Rules without a native
    `diagnose` kernel fall back to `_generic_diagnose` (distance geometry +
    distance-to-aggregate scores around the unchecked result).
    """

    def __init__(self, name, unchecked, check, upper_bound=None,
                 influence=None, diagnose=None):
        self.name = name
        self.unchecked = unchecked
        self.check = check
        self.upper_bound = upper_bound
        self.influence = influence
        self.diagnose = diagnose

    def checked(self, gradients, *, diagnostics=False, **kwargs):
        gradients = as_matrix(gradients)
        message = self.check(gradients=gradients, **kwargs)
        if message is not None:
            raise utils.UserException(f"Aggregation rule {self.name!r} cannot be used: {message}")
        if diagnostics:
            result, aux = self.diagnosed(gradients, **kwargs)
        else:
            result = self.unchecked(gradients, **kwargs)
        if result.shape != gradients.shape[1:]:
            raise utils.UserException(
                f"Aggregation rule {self.name!r} returned shape {result.shape}, expected {gradients.shape[1:]}")
        return (result, aux) if diagnostics else result

    def diagnosed(self, gradients, **kwargs):
        """The raw diagnostics kernel: `(G, **kwargs) -> (f32[d], aux)`
        with the uniform `ops/diag.py` aux schema (native per-rule kernel,
        or the generic geometry fallback)."""
        if self.diagnose is not None:
            return self.diagnose(gradients, **kwargs)
        return _generic_diagnose(self.unchecked, gradients, **kwargs)

    def __call__(self, gradients, **kwargs):
        return self.checked(gradients, **kwargs)

    def __repr__(self):
        return f"GAR({self.name!r})"


def _generic_diagnose(unchecked, gradients, **kwargs):
    """Diagnostics for rules without a native kernel: the unchecked
    aggregate, the pairwise-distance geometry, and distance-to-aggregate
    as the per-worker score (selection mass unknown -> all ones)."""
    from byzantinemomentum_tpu.ops import _common, diag

    n = gradients.shape[0]
    result = unchecked(gradients, **kwargs)
    dist = _common.pairwise_distances(gradients)
    dev = gradients - result[None, :]
    scores = _common.sanitize_inf(jnp.sqrt(jnp.sum(dev * dev, axis=1)))
    return result, diag.make_aux(
        n, scores=scores, selection=jnp.ones((n,), jnp.float32), dist=dist)


def register(name, unchecked, check, upper_bound=None, influence=None,
             diagnose=None):
    """Register a GAR under `name` (reference `aggregators/__init__.py:42-86`).

    Args:
      name: registry key.
      unchecked: kernel `(G: f32[n,d], **kwargs) -> f32[d]`.
      check: `(gradients, **kwargs) -> None | str` validity test.
      upper_bound: optional `(n, f, d) -> float` theoretical ratio bound.
      influence: optional `(honests, byzantines, **kwargs) -> float` attack
        acceptation ratio.
      diagnose: optional `(G, **kwargs) -> (f32[d], aux)` diagnostics
        kernel (uniform `ops/diag.py` aux schema).
    Returns:
      The GAR object.
    """
    if name in gars:
        utils.warning(f"Aggregation rule {name!r} registered twice; keeping the last")
    gar = GAR(name, unchecked, check, upper_bound=upper_bound,
              influence=influence, diagnose=diagnose)
    gars[name] = gar
    return gar


# Self-registering kernel modules (plugin pattern, reference
# `aggregators/__init__.py:91-97`)
utils.import_directory(__name__, pathlib.Path(__file__).parent)
