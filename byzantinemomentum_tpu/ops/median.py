"""NaN-resilient coordinate-wise median GAR (reference `aggregators/median.py`).

Semantics: lower median with NaN-last ordering — `sorted[(n-1)//2]` per
coordinate. This matches the reference's documented NaN-resilience
(`aggregators/median.py:13`) and torch's lower-median index convention;
note that *modern* torch-CPU `median` propagates NaN instead, which would
make the GAR meaningless under the `nan` attack — we keep the documented,
sort-based semantics.

The `native-median` registration is the compiled fast tier standing in for
the reference's optional C++ `native.median.aggregate`
(`aggregators/median.py:41-49`): on TPU it is the same kernel jit-compiled
standalone.
"""

import math

import jax

from byzantinemomentum_tpu.ops import register
from byzantinemomentum_tpu.ops._common import lower_median

__all__ = ["aggregate"]


def aggregate(gradients, **kwargs):
    """NaN-resilient coordinate-wise lower median
    (reference `aggregators/median.py:31-39`)."""
    return lower_median(gradients)


_jitted = jax.jit(lower_median)


def aggregate_native(gradients, **kwargs):
    """Compiled fast tier (TPU equivalent of `native.median.aggregate`)."""
    return _jitted(gradients)


def check(gradients, **kwargs):
    if gradients.shape[0] < 1:
        return f"Expected at least one gradient to aggregate, got {gradients.shape[0]}"


def upper_bound(n, f, d):
    """Variance-norm ratio bound (reference `aggregators/median.py:62-71`)."""
    return 1 / math.sqrt(n - f)


register("median", aggregate, check, upper_bound=upper_bound)
register("native-median", aggregate_native, check, upper_bound=upper_bound)
