"""NaN-resilient coordinate-wise median GAR (reference `aggregators/median.py`).

Semantics: lower median with NaN-last ordering — `sorted[(n-1)//2]` per
coordinate. This matches the reference's documented NaN-resilience
(`aggregators/median.py:13`) and torch's lower-median index convention;
note that *modern* torch-CPU `median` propagates NaN instead, which would
make the GAR meaningless under the `nan` attack — we keep the documented,
sort-based semantics.

The `native-median` registration is the compiled fast tier standing in for
the reference's optional C++ `native.median.aggregate`
(`aggregators/median.py:41-49`): on TPU it is the same kernel jit-compiled
standalone.
"""

import math

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu.ops import diag, register
from byzantinemomentum_tpu.ops._common import (
    lower_median, pairwise_distances, sanitize_inf)

__all__ = ["aggregate", "diagnose"]


def aggregate(gradients, **kwargs):
    """NaN-resilient coordinate-wise lower median
    (reference `aggregators/median.py:31-39`)."""
    return lower_median(gradients)


_jitted = jax.jit(lower_median)


def aggregate_native(gradients, **kwargs):
    """Compiled fast tier (TPU equivalent of `native.median.aggregate`)."""
    return _jitted(gradients)


def diagnose(gradients, **kwargs):
    """Diagnostics kernel: the coordinate-wise median plus the forensics
    aux. `scores` are the per-worker L2 deviations from the median vector
    (the rule's natural deviation statistic); `trim_frac` is the fraction
    of each worker's coordinates that did NOT land on the selected median
    rank — for distinct values (n-1)/n everywhere, so the informative read
    is its complement: how often each worker WAS the median."""
    n = gradients.shape[0]
    agg = lower_median(gradients)
    dev = gradients - agg[None, :]
    scores = sanitize_inf(jnp.sqrt(jnp.sum(dev * dev, axis=1)))
    was_median = (gradients == agg[None, :]).astype(jnp.float32)
    return agg, diag.make_aux(
        n, scores=scores, selection=jnp.ones((n,), jnp.float32),
        dist=pairwise_distances(gradients),
        trim_frac=1.0 - jnp.mean(was_median, axis=1))


def check(gradients, **kwargs):
    if gradients.shape[0] < 1:
        return f"Expected at least one gradient to aggregate, got {gradients.shape[0]}"


def upper_bound(n, f, d):
    """Variance-norm ratio bound (reference `aggregators/median.py:62-71`)."""
    return 1 / math.sqrt(n - f)


register("median", aggregate, check, upper_bound=upper_bound,
         diagnose=diagnose)
register("native-median", aggregate_native, check, upper_bound=upper_bound,
         diagnose=diagnose)
