"""Pallas TPU kernels for the coordinate-wise GAR reductions.

The coordinate-wise rules (median, trmean, phocas, meamed, Bulyan's
averaged-median stage) all reduce to sorting the n rows of the `(n, d)`
gradient matrix independently per coordinate. XLA lowers `jnp.sort(axis=0)`
to a generic variadic sort that runs ~3x off the HBM bandwidth floor on
these shapes ((25, 1.3M): 5.3 ms vs a 1.8 ms copy floor on v5e); n is tiny
and static, so a Batcher odd-even mergesort network over the rows — each
compare-exchange a VPU select over a (tile,) column block held in VMEM —
reaches the floor. The fused variants below additionally write only the
reduced row(s) instead of the full sorted matrix, so each GAR becomes a
single read of `g` plus a `(d,)` write.

Ordering semantics match `jnp.sort`/torch exactly: NaN sorts last (the
NaN-resilience contract of the median GAR, reference
`aggregators/median.py:13`), ties keep values (a value sort — no indices).

Used automatically by `ops/_common.py` and `ops/trmean.py` when running on
TPU with n <= MAX_ROWS; every entry point has a jnp fallback and the
`BMT_NO_PALLAS=1` environment kill-switch. `tests/test_pallas.py` pins the
kernels against the jnp oracles (interpret mode off-TPU), NaN cases
included.
"""

import contextlib
import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["supported", "disabled", "colsort", "lower_median",
           "trimmed_mean", "closest_mean", "sort_values",
           "closest_mean_values"]

# Row counts beyond this fall back to XLA sort (network size grows
# O(n log^2 n) and VMEM holds fewer columns per block)
MAX_ROWS = 64

_SUPPORTED_DTYPES = (jnp.float32, jnp.bfloat16, jnp.float16)

# Trace-time kill switch: Mosaic kernels cannot be auto-partitioned, so a
# program jitted with multi-device shardings must trace the jnp fallback
# (`parallel/sharded.py` wraps its traces in `disabled()`)
_disabled_depth = 0


@contextlib.contextmanager
def disabled():
    """Force the jnp fallback for every dispatch made while tracing under
    this context (used by the multi-device sharded step, whose auto
    partitioner cannot split a Mosaic kernel)."""
    global _disabled_depth
    _disabled_depth += 1
    try:
        yield
    finally:
        _disabled_depth -= 1


@contextlib.contextmanager
def allowed():
    """Re-allow Pallas inside a `shard_map` body traced under `disabled()`.

    Only AUTO-partitioned traces must not see Mosaic kernels; inside a
    `shard_map` every operand is a manual per-device shard, so the kernels
    are legal again — `parallel/sharded.py` wraps its shard-local GAR
    bodies in this, which is how the sorting networks stay alive under
    `--mesh`."""
    global _disabled_depth
    saved = _disabled_depth
    _disabled_depth = 0
    try:
        yield
    finally:
        _disabled_depth = saved


def interpret_mode():
    """Trace-time knob: `BMT_PALLAS_INTERPRET=1` runs every kernel in Pallas
    interpret mode so off-TPU tests exercise the real kernel bodies."""
    return bool(os.environ.get("BMT_PALLAS_INTERPRET"))


def supported(g, interpret=False):
    """Whether the Pallas path applies to this operand (trace-time check)."""
    if _disabled_depth or os.environ.get("BMT_NO_PALLAS"):
        return False
    if g.ndim != 2 or not (1 <= g.shape[0] <= MAX_ROWS) or g.shape[1] < 1:
        return False
    if g.dtype not in _SUPPORTED_DTYPES:
        return False
    return interpret or interpret_mode() or jax.default_backend() == "tpu"


def _batcher_pairs(n):
    """Batcher odd-even mergesort compare-exchange schedule for n rows."""
    pairs = []
    p = 1
    while p < n:
        k = p
        while k >= 1:
            for j in range(k % p, n - k, 2 * k):
                for i in range(0, min(k, n - j - k)):
                    if (i + j) // (2 * p) == (i + j + k) // (2 * p):
                        pairs.append((i + j, i + j + k))
            k //= 2
        p *= 2
    return tuple(pairs)


def sort_values(rows):
    """Run the Batcher network over a list of equal-shape row values
    (NaN-last order, matching `jnp.sort`); returns the sorted list.
    Shared with the fused GAR pipeline (`ops/pallas_gar.py`), whose
    bulyan tail sorts in-VMEM stage-1 averages that never came from a
    ref."""
    rows = list(rows)
    for i, j in _batcher_pairs(len(rows)):
        a, b = rows[i], rows[j]
        swap = (b < a) | (jnp.isnan(a) & ~jnp.isnan(b))
        rows[i] = jnp.where(swap, b, a)
        rows[j] = jnp.where(swap, a, b)
    return rows


def _sorted_rows(in_ref):
    """Load the block's rows and run the sorting network (NaN-last order,
    matching `jnp.sort`)."""
    return sort_values([in_ref[i, :] for i in range(in_ref.shape[0])])


def _tile_for(n, buffers, itemsize):
    """Column-block width: keep `buffers` live (n, tile) buffers of the
    operand dtype within a ~10 MB VMEM budget (of 16 MB/core), in multiples
    of 128 lanes.

    The cap scales inversely with n so SMALL row counts get proportionally
    wider tiles: each grid step pays a fixed DMA/iteration latency, and at
    e.g. (5, 36.5M) a 16K-column cap meant ~2200 grid steps of mostly
    latency (measured ~12 ms/kernel; wide tiles bring it near the read
    floor). Tiles are multiples of 4096 columns — Mosaic requires 1-D
    output blocks divisible by the minor tiling (1024 f32 / 2048 half
    dtypes) — and the budget guarantees tile >= 6826 for every supported
    (n <= MAX_ROWS, buffers <= 6, itemsize <= 4) combination, so flooring
    to 4096 never degenerates."""
    tile = (10 * 2 ** 20) // (itemsize * buffers * n)
    # The 4096 floor keeps direct entry-point calls outside the
    # `supported()` domain (n > MAX_ROWS, wide dtypes) well-defined instead
    # of rounding to a zero-width grid
    return max(4096, min(131072, tile // 4096 * 4096))


def _grid_call(kernel, out_rows, g, extra_1d=(), *, buffers, interpret):
    """Common pallas_call wrapper: grid over column tiles of `g: (n, d)`,
    optional extra (d,) operands, output (out_rows, d) or (d,)."""
    n, d = g.shape
    tile = _tile_for(n, buffers, jnp.dtype(g.dtype).itemsize)
    grid = ((d + tile - 1) // tile,)
    in_specs = [pl.BlockSpec((n, tile), lambda i: (0, i),
                             memory_space=pltpu.VMEM)]
    for _ in extra_1d:
        in_specs.append(pl.BlockSpec((tile,), lambda i: (i,),
                                     memory_space=pltpu.VMEM))
    if out_rows is None:
        out_shape = jax.ShapeDtypeStruct((d,), g.dtype)
        out_spec = pl.BlockSpec((tile,), lambda i: (i,),
                                memory_space=pltpu.VMEM)
    else:
        out_shape = jax.ShapeDtypeStruct((out_rows, d), g.dtype)
        out_spec = pl.BlockSpec((out_rows, tile), lambda i: (0, i),
                                memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel, out_shape=out_shape, grid=grid,
        in_specs=in_specs, out_specs=out_spec,
        interpret=interpret or interpret_mode())(g, *extra_1d)


# --------------------------------------------------------------------------- #
# Kernels

def _colsort_kernel(in_ref, out_ref):
    for i, r in enumerate(_sorted_rows(in_ref)):
        out_ref[i, :] = r


def colsort(g, *, interpret=False):
    """`jnp.sort(g, axis=0)` (full sorted matrix)."""
    n = g.shape[0]
    return _grid_call(_colsort_kernel, n, g, buffers=6, interpret=interpret)


def _median_kernel(in_ref, out_ref):
    n = in_ref.shape[0]
    out_ref[:] = _sorted_rows(in_ref)[(n - 1) // 2]


def lower_median(g, *, interpret=False):
    """Coordinate-wise lower median `sorted[(n-1)//2]` — fused: one read of
    `g`, one `(d,)` write (`ops._common.lower_median` semantics)."""
    return _grid_call(_median_kernel, None, g, buffers=4, interpret=interpret)


def _trmean_kernel(f, in_ref, out_ref):
    n = in_ref.shape[0]
    rows = _sorted_rows(in_ref)
    acc = rows[f]
    for i in range(f + 1, n - f):
        acc = acc + rows[i]
    out_ref[:] = acc / (n - 2 * f)


def trimmed_mean(g, f, *, interpret=False):
    """Coordinate-wise mean of sorted ranks [f, n-f)
    (`ops.trmean.trmean` semantics)."""
    return _grid_call(functools.partial(_trmean_kernel, f), None, g,
                      buffers=4, interpret=interpret)


def closest_mean_values(g_rows, c, m):
    """Mean of the `m` row values closest to center `c`, over a list of
    equal-shape rows (`ops._common.closest_mean` semantics, NaN overflow
    included). Shared with `ops/pallas_gar.py`'s fused bulyan tail."""
    devs = [jnp.abs(r - c) for r in g_rows]
    # Sort the deviations (values only) to find the m-th smallest
    thresh = sort_values(devs)[m - 1]
    # Strictly-below plus index-order ties at the threshold — exactly the
    # stable-argsort selection (see `ops._common.closest_mean`)
    need = jnp.zeros_like(thresh)
    for dev in devs:
        need = need + jnp.where(dev < thresh, 1.0, 0.0)
    need = m - need
    acc = jnp.zeros_like(thresh)
    cum = jnp.zeros_like(thresh)
    for g_r, dev in zip(g_rows, devs):
        eq = dev == thresh
        cum = cum + jnp.where(eq, 1.0, 0.0)
        take = (dev < thresh) | (eq & (cum <= need))
        acc = acc + jnp.where(take, g_r, jnp.zeros_like(g_r))
    out = acc / m
    return jnp.where(jnp.isnan(thresh), jnp.nan, out)


def _closest_kernel(m, in_ref, c_ref, out_ref):
    n = in_ref.shape[0]
    g_rows = [in_ref[i, :] for i in range(n)]
    out_ref[:] = closest_mean_values(g_rows, c_ref[:], m)


def closest_mean(g, c, m, *, interpret=False):
    """Coordinate-wise mean of the m values closest to center `c` — fused
    single pass (`ops._common.closest_mean` semantics, NaN-overflow
    included)."""
    return _grid_call(functools.partial(_closest_kernel, m), None, g,
                      extra_1d=(c,), buffers=6, interpret=interpret)
