"""Comparative Gradient Elimination (CGE) GAR (reference `aggregators/cge.py`;
algorithm from Liu, Gupta, Vaidya 2021, cited reference `cge.py:14-18`).

Sort workers by gradient norm (non-finite -> +inf), average the n-f
smallest-norm gradients (reference `aggregators/cge.py:28-57`).
"""

import jax.numpy as jnp

from byzantinemomentum_tpu.ops import diag, register
from byzantinemomentum_tpu.ops._common import (
    masked_rank_mean, pairwise_distances, row_sum_stable, sanitize_inf,
    selection_influence)

__all__ = ["aggregate", "aggregate_masked", "diagnose", "selection"]


def norms(gradients):
    """Per-worker L2 norms with non-finite mapped to +inf
    (reference `aggregators/cge.py:28-40`)."""
    return sanitize_inf(jnp.sqrt(jnp.sum(gradients * gradients, axis=1)))


def selection(gradients, f, **kwargs):
    """Indices of the n-f smallest-norm gradients, stable-tie order."""
    n = gradients.shape[0]
    return jnp.argsort(norms(gradients), stable=True)[:n - f]


def aggregate(gradients, f, **kwargs):
    """CGE rule (reference `aggregators/cge.py:42-57`)."""
    return jnp.mean(gradients[selection(gradients, f)], axis=0)


def aggregate_masked(gradients, active, n_eff, f_eff, **kwargs):
    """Traced-count CGE (`faults/quorum.py` dispatch): inactive rows take
    +inf norms (never among the smallest), and the `n_eff - f_eff`
    smallest-norm active rows average with a traced count
    (`_common.masked_rank_mean` — index-order summation, bit-stable
    across paddings of the same active set)."""
    n = gradients.shape[0]
    # The plain kernel's `norms` reduces with jnp.sum, whose grouping
    # follows the static width; the masked form sums through the
    # padding-stable contraction so bucketed and exact cells agree bitwise
    nrm = sanitize_inf(jnp.sqrt(row_sum_stable(gradients * gradients)))
    return masked_rank_mean(gradients, nrm, active,
                            jnp.clip(n_eff - f_eff, 1, n))


def diagnose(gradients, f, **kwargs):
    """Diagnostics kernel: the CGE aggregate plus the forensics aux —
    per-worker norms as scores, the n-f smallest-norm membership as the
    selection mask."""
    n = gradients.shape[0]
    sel = selection(gradients, f)
    agg = jnp.mean(gradients[sel], axis=0)
    return agg, diag.make_aux(
        n, scores=norms(gradients),
        selection=diag.selection_from_indices(n, sel),
        dist=pairwise_distances(gradients))


def check(gradients, f=None, m=None, **kwargs):
    if gradients.shape[0] < 1:
        return f"Expected at least one gradient to aggregate, got {gradients.shape[0]}"


# Fraction of selected gradients that are Byzantine (reference
# `aggregators/cge.py:72-93`)
influence = selection_influence(selection)


register("cge", aggregate, check, influence=influence, diagnose=diagnose)
