"""Comparative Gradient Elimination (CGE) GAR (reference `aggregators/cge.py`;
algorithm from Liu, Gupta, Vaidya 2021, cited reference `cge.py:14-18`).

Sort workers by gradient norm (non-finite -> +inf), average the n-f
smallest-norm gradients (reference `aggregators/cge.py:28-57`).
"""

import jax.numpy as jnp

from byzantinemomentum_tpu.ops import diag, register
from byzantinemomentum_tpu.ops._common import (
    pairwise_distances, sanitize_inf, selection_influence)

__all__ = ["aggregate", "diagnose", "selection"]


def norms(gradients):
    """Per-worker L2 norms with non-finite mapped to +inf
    (reference `aggregators/cge.py:28-40`)."""
    return sanitize_inf(jnp.sqrt(jnp.sum(gradients * gradients, axis=1)))


def selection(gradients, f, **kwargs):
    """Indices of the n-f smallest-norm gradients, stable-tie order."""
    n = gradients.shape[0]
    return jnp.argsort(norms(gradients), stable=True)[:n - f]


def aggregate(gradients, f, **kwargs):
    """CGE rule (reference `aggregators/cge.py:42-57`)."""
    return jnp.mean(gradients[selection(gradients, f)], axis=0)


def diagnose(gradients, f, **kwargs):
    """Diagnostics kernel: the CGE aggregate plus the forensics aux —
    per-worker norms as scores, the n-f smallest-norm membership as the
    selection mask."""
    n = gradients.shape[0]
    sel = selection(gradients, f)
    agg = jnp.mean(gradients[sel], axis=0)
    return agg, diag.make_aux(
        n, scores=norms(gradients),
        selection=diag.selection_from_indices(n, sel),
        dist=pairwise_distances(gradients))


def check(gradients, f=None, m=None, **kwargs):
    if gradients.shape[0] < 1:
        return f"Expected at least one gradient to aggregate, got {gradients.shape[0]}"


# Fraction of selected gradients that are Byzantine (reference
# `aggregators/cge.py:72-93`)
influence = selection_influence(selection)


register("cge", aggregate, check, influence=influence, diagnose=diagnose)
