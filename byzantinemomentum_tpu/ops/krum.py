"""Multi-Krum GAR (reference `aggregators/krum.py`).

Score of worker i = sum of its n-f-1 smallest distances to the other
workers (plain Euclidean norms, non-finite -> +inf; reference
`aggregators/krum.py:42-60`); the aggregate is the average of the m
lowest-score gradients, default m = n-f-2 (reference `krum.py:65-80`).

TPU design: the pairwise-distance matrix comes from one Gram pass
(`ops/_common.pairwise_distances` — the fused streamed Pallas kernel of
`ops/pallas_gar.py` where supported, else one MXU matmul), per-row sorts
run on the VPU, and the whole kernel inlines into the jitted training
step. The selected-row average routes through the streamed
`weighted_rows_mean` kernel on the same gate, so the whole rule touches
the (n, d) matrix exactly twice with no padded materialization.
`native-krum` is the standalone-jitted fast tier (stands in for
`native.krum.aggregate`, reference `krum.py:82-96`).
"""

import math

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu.ops import diag, register
from byzantinemomentum_tpu.ops._common import (
    all_finite_from_dist, pairwise_distances, row_sum_stable,
    selection_influence, weighted_rows_mean)

__all__ = ["aggregate", "diagnose", "scores", "selection",
           "selection_weights", "selection_weights_masked"]


def scores_from_dist(dist, f):
    """Multi-Krum scores from the (n, n) distance matrix (+inf diagonal):
    per row, sum of the n-f-1 smallest distances
    (reference `aggregators/krum.py:49-60`)."""
    n = dist.shape[0]
    # Each row holds n-1 finite-or-inf off-diagonal distances plus the +inf
    # diagonal; ascending sort puts the diagonal last, so the first n-f-1
    # entries are exactly the smallest n-f-1 neighbor distances.
    return jnp.sum(jnp.sort(dist, axis=1)[:, :n - f - 1], axis=1)


def scores(gradients, f, *, method="dot"):
    """Multi-Krum scores. `f32[n,d] -> f32[n]`."""
    return scores_from_dist(pairwise_distances(gradients, method=method), f)


def selection_weights(dist, f, m=None):
    """Averaging weights `f32[n]` from the (n, n) distance matrix: 1/m on
    the m lowest-score rows (stable-tie order), 0 elsewhere. Shared by the
    single-chip path below and the d-sharded kernel (`parallel/sharded.py`),
    which feeds a psum'd distance matrix."""
    n = dist.shape[0]
    if m is None:
        m = n - f - 2
    order = jnp.argsort(scores_from_dist(dist, f), stable=True)
    ranks = jnp.zeros((n,), jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32))
    return jnp.where(ranks < m, 1.0 / m, 0.0)


def selection_weights_masked(dist, active, n_eff, f_eff, m=None):
    """Dynamic-quorum `selection_weights`: Multi-Krum over the active rows
    only, with TRACED effective counts (`faults/quorum.py`).

    Inactive rows ride the non-finite conventions — their distances are
    forced to +inf, so their scores are +inf and they are never selected —
    and the static slice bounds become rank predicates: each active row's
    score sums its `n_eff - f_eff - 1` smallest active-neighbor distances,
    and the aggregate averages the `m` (default `n_eff - f_eff - 2`)
    lowest-score rows. Matches `selection_weights(dist[active][:, active],
    f_eff, m)` re-expanded to the full row set.
    """
    n = dist.shape[0]
    pair = active[:, None] & active[None, :]
    dist = jnp.where(pair, dist, jnp.inf)
    # Beyond-quorum degeneracy guard (n_eff too small for the krum
    # contract): keep at least one neighbor / one selected row
    keep = jnp.clip(n_eff - f_eff - 1, 1, n)
    srt = jnp.sort(dist, axis=1)
    ranks = jnp.arange(n)[None, :]
    # row_sum_stable: the summed axis is the PADDED row axis when this
    # kernel serves a shape bucket — a plain reduce would regroup with
    # the bucket width and break the bucket-vs-exact-cell bit equality
    scores = row_sum_stable(jnp.where(ranks < keep, srt, 0.0))
    scores = jnp.where(active, scores, jnp.inf)
    if m is None:
        m = jnp.clip(n_eff - f_eff - 2, 1, n)
    else:
        m = jnp.clip(jnp.minimum(m, n_eff - f_eff - 2), 1, n)
    order = jnp.argsort(scores, stable=True)
    score_ranks = jnp.zeros((n,), jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32))
    w = jnp.where((score_ranks < m) & active, 1.0 / m, 0.0)
    return w.astype(jnp.float32)


def selection(gradients, f, m=None, *, method="dot", **kwargs):
    """Indices of the m selected (lowest-score) gradients, stable-tie order
    (reference sorts scores with Python's stable sort, `krum.py:61-63`)."""
    n = gradients.shape[0]
    if m is None:
        m = n - f - 2
    order = jnp.argsort(scores(gradients, f, method=method), stable=True)
    return order[:m]


def aggregate(gradients, f, m=None, *, method="dot", **kwargs):
    """Multi-Krum rule (reference `aggregators/krum.py:65-80`).

    The selected-row average is a weight-vector matmul rather than a row
    gather (dynamic gathers over the (n, d) matrix are the slow path on
    TPU — same reformulation as Bulyan's selection stack); non-finite
    semantics in `ops._common.weighted_rows_mean`."""
    dist = pairwise_distances(gradients, method=method)
    w = selection_weights(dist, f, m).astype(gradients.dtype)
    return weighted_rows_mean(w, gradients,
                              all_finite=all_finite_from_dist(dist))


def diagnose(gradients, f, m=None, *, method="dot", **kwargs):
    """Diagnostics kernel: the Multi-Krum aggregate plus the forensics aux
    (`ops/diag.py` schema) — Krum scores, the 1/m selection-weight mass,
    and the pairwise-distance geometry the selection acted on. Shares the
    distance matrix and weight vector with the aggregate, so the extra
    cost over `aggregate` is one O(n²) score read-off."""
    n = gradients.shape[0]
    if m is None:
        m = n - f - 2
    dist = pairwise_distances(gradients, method=method)
    w = selection_weights(dist, f, m)
    agg = weighted_rows_mean(w.astype(gradients.dtype), gradients,
                             all_finite=all_finite_from_dist(dist))
    return agg, diag.make_aux(
        n, scores=scores_from_dist(dist, f), selection=w * m, dist=dist)


_jitted = jax.jit(aggregate, static_argnames=("f", "m", "method"))


def aggregate_native(gradients, f, m=None, **kwargs):
    """Compiled fast tier (TPU equivalent of `native.krum.aggregate`)."""
    return _jitted(gradients, f, m)


def check(gradients, f, m=None, **kwargs):
    n = gradients.shape[0]
    if n < 1:
        return f"Expected at least one gradient to aggregate, got {n}"
    if not isinstance(f, int) or f < 1 or n < 2 * f + 3:
        return f"Invalid number of Byzantine gradients to tolerate, got f = {f!r}, expected 1 <= f <= {(n - 3) // 2}"
    if m is not None and (not isinstance(m, int) or m < 1 or m > n - f - 2):
        return f"Invalid number of selected gradients, got m = {m!r}, expected 1 <= m <= {n - f - 2}"


def upper_bound(n, f, d):
    """Variance-norm ratio bound (reference `aggregators/krum.py:115-124`)."""
    return 1 / math.sqrt(2 * (n - f + f * (n + f * (n - f - 2) - 2) / (n - 2 * f - 2)))


# Fraction of selected gradients that are Byzantine (reference
# `aggregators/krum.py:126-150`)
influence = selection_influence(selection)


register("krum", aggregate, check, upper_bound=upper_bound,
         influence=influence, diagnose=diagnose)
register("native-krum", aggregate_native, check, upper_bound=upper_bound,
         diagnose=diagnose)
