"""Budgeted 1-D arg-maximization on the non-negative reals — the engine of
the adaptive attacks (reference `tools/misc.py:468-514`).

The reference's algorithm is an expansion phase (double the step while the
objective improves) followed by a contraction phase (probe shrinking steps
around the incumbent), under a fixed evaluation budget. Because the budget
is static, the whole search compiles to a single `lax.while_loop` whose body
inlines the objective — so an adaptive attack that evaluates the live
defense up to ~16 times per step stays inside one XLA program instead of
16 host round-trips.
"""

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["line_maximize"]


def line_maximize(scape, evals=16, start=0.0, delta=1.0, ratio=0.8):
    """Best-effort arg-maximize `scape: R+ -> R` under an evaluation budget.

    Traceable port of the reference's exact control flow
    (`tools/misc.py:468-514`): same expansion/contraction schedule, same
    tie-breaking (strict improvement only), same negative-x guard (repeated
    halving toward the previous probe).

    Args:
      scape: traceable objective `f32[] -> f32[]`.
      evals: static positive int, total evaluation budget.
      start: initial x (non-negative).
      delta: initial step.
      ratio: contraction ratio in (0.5, 1).
    Returns:
      The best x found, as a traced f32 scalar.
    """
    start = jnp.float32(start)
    delta0 = jnp.float32(delta)
    ratio = jnp.float32(ratio)

    best_y0 = scape(start)

    # State: (phase, evals_left, best_x, best_y, prop_x, delta)
    # phase 0 = expansion, 1 = contraction.
    init = (jnp.int32(0), jnp.int32(evals - 1), start, best_y0, start, delta0)

    def cond(state):
        _, evals_left, *_ = state
        return evals_left > 0

    def body(state):
        phase, evals_left, best_x, best_y, prop_x, delta = state

        def expand(_):
            px = best_x + delta
            py = scape(px)
            better = py > best_y
            return (
                jnp.where(better, 0, 1).astype(jnp.int32),  # stay expanding iff improved
                evals_left - 1,
                jnp.where(better, px, best_x),
                jnp.where(better, py, best_y),
                px,
                jnp.where(better, delta * 2.0, delta * ratio),
            )

        def contract(_):
            # Probe on the other side of the incumbent, guarding x >= 0 by
            # halving toward the previous probe (reference `misc.py:499-506`).
            def neg_guard(x):
                return lax.while_loop(lambda v: v < 0, lambda v: (v + px_minus_src) / 2.0, x)

            px_minus_src = prop_x
            px = jnp.where(
                prop_x < best_x,
                prop_x + delta,
                neg_guard(prop_x - delta),
            )
            py = scape(px)
            better = py > best_y
            return (
                jnp.int32(1),
                evals_left - 1,
                jnp.where(better, px, best_x),
                jnp.where(better, py, best_y),
                px,
                delta * ratio,
            )

        return lax.cond(phase == 0, expand, contract, operand=None)

    _, _, best_x, _, _, _ = lax.while_loop(cond, body, init)
    return best_x
