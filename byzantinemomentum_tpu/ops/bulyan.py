"""Bulyan over Multi-Krum GAR (reference `aggregators/bulyan.py`).

Two stages:
1. Iteratively select n-2f-2 Multi-Krum averages: at each round, average the
   gradients with the m lowest scores (m shrinking as min(m, n-f-2-i)),
   then prune the current minimum-score gradient (reference
   `aggregators/bulyan.py:63-76`).
2. Coordinate-wise "averaged median" over the selected stack with
   m = |selected| - 2f (reference `bulyan.py:77-84`).

Parity note on the reference's pruning (reference `bulyan.py:72-76`): the
post-prune score-update loop there references an undefined variable and its
branch is unreachable, so the *effective* reference behavior is "prune = set
the minimum score to +inf, update nothing else". We reproduce that effective
behavior (documented in SURVEY.md §2.1), not the dead code.

Bulyan scores differ slightly from Krum's: sum of the m smallest neighbor
distances (m = n-f-2 by default), not n-f-1 (reference `bulyan.py:56-62`).
"""

import math

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu.ops import diag, pallas_gar, register
from byzantinemomentum_tpu.ops._common import (
    all_finite_from_dist, averaged_median, masked_closest_mean,
    masked_lower_median, masked_weighted_rows_mean, pairwise_distances,
    row_sum_stable, weighted_rows_mean)

__all__ = ["aggregate", "aggregate_masked", "diagnose", "selected_stack",
           "selection_weights", "selection_weights_masked"]


def selection_weights(dist, f, m=None):
    """Stage-1 averaging weights `(rounds, n)` from the `(n, n)` distance
    matrix (+inf diagonal).

    The sequential selection runs entirely on the (n,) score vector, emitting
    one averaging-weight row per round; callers touch the gradients once, by
    a single `(rounds, n) @ (n, d)` matmul — no per-round row gathers over
    the large matrix. Shared by the single-chip path below and the d-sharded
    kernel (`parallel/sharded.py`), which feeds a psum'd distance matrix.
    """
    n = dist.shape[0]
    m_max = n - f - 2
    if m is None:
        m = m_max
    scores = jnp.sum(jnp.sort(dist, axis=1)[:, :m], axis=1)
    rounds = n - 2 * f - 2
    m_is = jnp.asarray([min(m, m_max - i) for i in range(rounds)], jnp.int32)

    def body(scores, m_i):
        order = jnp.argsort(scores, stable=True)
        ranks = jnp.zeros((n,), jnp.int32).at[order].set(
            jnp.arange(n, dtype=jnp.int32))
        w = jnp.where(ranks < m_i, 1.0 / m_i.astype(jnp.float32), 0.0)
        return scores.at[order[0]].set(jnp.inf), w

    _, W = jax.lax.scan(body, scores, m_is)
    return W


def selection_weights_masked(dist, active, n_eff, f_eff, m=None):
    """Traced-count stage-1 weights: Bulyan's iterative Multi-Krum
    selection over the ACTIVE rows only, every static bound a traced
    quantity (`faults/quorum.py` discipline, the bulyan analogue of
    `ops/krum.py::selection_weights_masked`).

    The scan runs a STATIC `n - 2` rounds (the most any active subset of
    an n-row matrix can need) with the trailing rounds inert: an inert
    round emits a zero weight row and carries the score vector through
    unchanged, so the compiled program is one fixed-shape loop whose
    effective length `n_eff - 2 f_eff - 2` is data. Inactive rows ride the
    +inf conventions (masked pairwise distances, +inf scores) and are
    excluded from every round's averaging mask, exactly like the static
    kernel never selects a non-finite row.

    Returns `(W: f32[n - 2, n], round_active: bool[n - 2])` — the weight
    stack plus the mask of real rounds (stage 2 needs it to exclude the
    inert rows from its median).
    """
    n = dist.shape[0]
    pair = active[:, None] & active[None, :]
    dist = jnp.where(pair, dist, jnp.inf)
    m_max = jnp.clip(n_eff - f_eff - 2, 1, n)
    if m is None:
        m_sel = m_max
    else:
        m_sel = jnp.clip(jnp.minimum(m, m_max), 1, n)
    # Scores: sum of the m smallest active-neighbor distances, the static
    # slice bound turned into a rank predicate against the traced count
    # (row_sum_stable: the summed axis is the padded bucket axis)
    srt = jnp.sort(dist, axis=1)
    col = jnp.arange(n)[None, :]
    scores = row_sum_stable(jnp.where(col < m_sel, srt, 0.0))
    scores = jnp.where(active, scores, jnp.inf)

    rounds_max = max(n - 2, 1)
    rounds_eff = jnp.clip(n_eff - 2 * f_eff - 2, 1, rounds_max)
    i = jnp.arange(rounds_max, dtype=jnp.int32)
    m_is = jnp.clip(jnp.minimum(m_sel, m_max - i), 1, n)
    round_active = i < rounds_eff

    def body(scores, inputs):
        m_i, act_i = inputs
        order = jnp.argsort(scores, stable=True)
        ranks = jnp.zeros((n,), jnp.int32).at[order].set(
            jnp.arange(n, dtype=jnp.int32))
        w = jnp.where((ranks < m_i) & active & act_i,
                      1.0 / m_i.astype(jnp.float32), 0.0)
        pruned = scores.at[order[0]].set(jnp.inf)
        return jnp.where(act_i, pruned, scores), w

    _, W = jax.lax.scan(body, scores, (m_is, round_active))
    return W, round_active


def aggregate_masked(gradients, active, n_eff, f_eff, m=None, *,
                     method="dot", **kwargs):
    """Dynamic-quorum Bulyan: stage-1 traced-count selection over the
    active rows, stage 2 an averaged median over the REAL rounds only
    (`masked_lower_median` + `masked_closest_mean` with the traced stack
    height). Equals `aggregate(gradients[active], f_eff)` for finite
    active rows; the serve bucket programs rely on the stronger property
    that two calls of THIS kernel at different paddings of the same
    active set are bit-identical (`serve/programs.py`)."""
    dist = pairwise_distances(gradients, method=method)
    W, round_active = selection_weights_masked(
        dist, active, n_eff, f_eff, m)
    stack = masked_weighted_rows_mean(
        W.astype(gradients.dtype), gradients, active)
    rounds_eff = jnp.sum(round_active.astype(jnp.int32))
    med = masked_lower_median(stack, round_active, rounds_eff)
    m2 = jnp.clip(rounds_eff - 2 * f_eff, 1, stack.shape[0])
    # The static kernel's m == 1 shortcut (`_common.averaged_median`)
    # becomes a traced select: the closest value to the median IS the
    # median, and the select preserves the shortcut's documented
    # beyond-contract inf behavior
    closest = masked_closest_mean(stack, round_active, med, m2)
    return jnp.where(m2 == 1, med, closest)


def selected_stack(gradients, f, m=None, *, method="dot"):
    """The (n-2f-2, d) stack of iterative Multi-Krum averages
    (reference `aggregators/bulyan.py:63-76`, effective behavior).

    Rows with any non-finite coordinate carry +inf scores and are never
    selected under the n >= 4f+3 contract; beyond it, a selected non-finite
    entry propagates NaN to its coordinate of that round's average
    (`ops._common.weighted_rows_mean`)."""
    dist = pairwise_distances(gradients, method=method)  # diag = +inf
    W = selection_weights(dist, f, m)
    return weighted_rows_mean(W.astype(gradients.dtype), gradients,
                              all_finite=all_finite_from_dist(dist))


def aggregate(gradients, f, m=None, *, method="dot", **kwargs):
    """Bulyan over Multi-Krum (reference `aggregators/bulyan.py:31-86`).

    Stage 2 runs INSIDE stage 1's finiteness branches (the
    `weighted_rows_mean` `then` continuation): the conditional's output is
    the (d,) result rather than the (rounds, d) stack. (Measured neutral
    on v5e — XLA already avoided a boundary copy — but strictly smaller
    boundary state; see `_common.weighted_rows_mean`.)"""
    dist = pairwise_distances(gradients, method=method)  # diag = +inf
    W = selection_weights(dist, f, m)
    rounds = W.shape[0]
    if pallas_gar.supported(gradients):
        # Fused tier (`ops/pallas_gar.py`): the distances above came from
        # ONE streamed Gram pass, and this call is the only other touch of
        # the (n, d) matrix — stage-1 averages and the stage-2 averaged
        # median in a single read, the (rounds, d) stack never materialized
        return pallas_gar.selected_median_mean(W, gradients, rounds - 2 * f)
    return weighted_rows_mean(
        W.astype(gradients.dtype), gradients,
        all_finite=all_finite_from_dist(dist),
        then=lambda sel: averaged_median(sel, rounds - 2 * f))


def diagnose(gradients, f, m=None, *, method="dot", **kwargs):
    """Diagnostics kernel: the Bulyan aggregate plus the forensics aux.
    `selection` is each worker's total stage-1 averaging mass across the
    n-2f-2 Multi-Krum rounds, normalized by the round count (1.0 = the
    worker entered every round's average); `scores` are the Bulyan scores
    (sum of the m smallest neighbor distances) before any pruning."""
    n = gradients.shape[0]
    m_scores = n - f - 2 if m is None else m
    dist = pairwise_distances(gradients, method=method)
    W = selection_weights(dist, f, m)
    rounds = W.shape[0]
    if pallas_gar.supported(gradients):
        # Same fused tail as `aggregate`; the aux below reads only the
        # (n, n) geometry the streamed Gram already produced
        agg = pallas_gar.selected_median_mean(W, gradients, rounds - 2 * f)
    else:
        agg = weighted_rows_mean(
            W.astype(gradients.dtype), gradients,
            all_finite=all_finite_from_dist(dist),
            then=lambda sel: averaged_median(sel, rounds - 2 * f))
    scores = jnp.sum(jnp.sort(dist, axis=1)[:, :m_scores], axis=1)
    mass = jnp.sum((W > 0).astype(jnp.float32), axis=0) / rounds
    return agg, diag.make_aux(n, scores=scores, selection=mass, dist=dist)


_jitted = jax.jit(aggregate, static_argnames=("f", "m", "method"))


def aggregate_native(gradients, f, m=None, **kwargs):
    """Compiled fast tier (TPU equivalent of `native.bulyan.aggregate`)."""
    return _jitted(gradients, f, m)


def check(gradients, f, m=None, **kwargs):
    n = gradients.shape[0]
    if n < 1:
        return f"Expected at least one gradient to aggregate, got {n}"
    if not isinstance(f, int) or f < 1 or n < 4 * f + 3:
        return f"Invalid number of Byzantine gradients to tolerate, got f = {f!r}, expected 1 <= f <= {(n - 3) // 4}"
    if m is not None and (not isinstance(m, int) or m < 1 or m > n - f - 2):
        return f"Invalid number of selected gradients, got m = {m!r}, expected 1 <= m <= {n - f - 2}"


def upper_bound(n, f, d):
    """Variance-norm ratio bound (reference `aggregators/bulyan.py:119-128`)."""
    return 1 / math.sqrt(2 * (n - f + f * (n + f * (n - f - 2) - 2) / (n - 2 * f - 2)))


register("bulyan", aggregate, check, upper_bound=upper_bound,
         diagnose=diagnose)
register("native-bulyan", aggregate_native, check, upper_bound=upper_bound,
         diagnose=diagnose)
