"""Fused Pallas TPU pipeline for the geometry GARs (krum, bulyan, brute).

PERF_NOTES.md r5 attribution of the WRN-28-10 cell: the d-space bulyan at
d = 36.5M costs ~23 ms/step because the `(n, d)` f32 G matrix is
materialized with 11 -> 16 sublane row padding (2.3 GB physical, 7.5 ms to
write) and then READ TWICE MORE at the padded width (the HIGHEST-precision
Gram, 7 ms; the selection-stack matmul, 5.2 ms). The selection itself acts
on a tiny `(n, n)` summary — all the big-matrix traffic is streamable.

This module replaces the three padded touches with streamed kernels that
each read the worker stack EXACTLY ONCE in d-tiles through VMEM and write
only reduced results:

* `sq_gram`        — the pairwise `g @ g.T` Gram accumulated tile by tile
                     into a resident `(n, n)` VMEM block (one read of `g`,
                     one tiny write; the row norms are its diagonal, so no
                     separate norm pass either).
* selection        — krum / bulyan stage 1 / brute run UNCHANGED on the
                     `(n, n)` host-of-the-kernel result
                     (`ops/krum.py::selection_weights`,
                     `ops/bulyan.py::selection_weights`,
                     `ops/brute.py::best_subset_mask_from_dist` — single
                     source of truth with the jnp and d-sharded paths).
* `weighted_rows_mean` / `selected_median_mean` / `masked_rows_mean`
                   — the selected-row average as one more streamed pass:
                     krum's `w @ G`, bulyan's stage-1 stack FUSED with its
                     stage-2 averaged median (the `(rounds, d)` stack never
                     leaves VMEM registers — the kernel writes only the
                     final `(d,)` row), and brute's masked mean.

No `(n, d)` intermediate is ever materialized, so no 11 -> 16 row padding
is ever paid; the pipeline touches the stack twice total (Gram pass +
average pass) instead of one padded write + two padded reads, and its cost
stays flat in d.

Semantics are pinned to `ops/_common.py` bit for bit on the `(n, n)`
geometry: non-finite values poison their Gram entries, which the shared
`sanitize_inf` downstream maps to +inf distances; stable-sort
tie-breaking lives in the unchanged selection code; the averaging kernels
reproduce `weighted_rows_mean`'s non-finite contract (unselected
non-finite rows excluded, selected non-finite entries -> NaN at exactly
their coordinates) by computing its masked form unconditionally — when
every value is finite the masked form IS the fast form, operand for
operand, so no `lax.cond` is needed inside the kernel.

Dispatch mirrors `ops/pallas_sort.py`: automatic on TPU for f32 stacks
with n <= MAX_ROWS, `BMT_NO_PALLAS=1` kill switch, the
`pallas_sort.disabled()` trace context honored (auto-partitioned multi
-device traces and non-TPU `--device-gar` hops must not see Mosaic
kernels), `BMT_PALLAS_INTERPRET=1` for off-TPU kernel-body testing, and a
jnp fallback at every call site. `tests/test_pallas.py` pins the kernels
against the jnp oracles in interpret mode, NaN rows and distance ties
included.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from byzantinemomentum_tpu.ops import pallas_sort

__all__ = ["supported", "sq_gram", "weighted_rows_mean",
           "selected_median_mean", "masked_rows_mean"]

# Row-count cap, as `pallas_sort.MAX_ROWS`: beyond it the resident (n, n)
# Gram block and the per-row unrolled averaging stop being VMEM-friendly,
# and the jnp path is taken instead
MAX_ROWS = 64

# f32 only: distance orderings feed selection decisions, and the pinned
# semantics (`ops/_common.pairwise_distances` uses precision=HIGHEST) are
# an f32 contract — the engine's GAR space is f32 even under bf16-mixed
# compute (bf16 stacks take the jnp path)
_SUPPORTED_DTYPES = (jnp.float32,)


def supported(g, interpret=False):
    """Whether the fused pipeline applies to this operand (trace-time).

    Shares `pallas_sort`'s kill switches: the `BMT_NO_PALLAS=1`
    environment switch and the `pallas_sort.disabled()` trace context
    (multi-device auto-partitioned traces, non-TPU `--device-gar` hops),
    so every existing "no Mosaic here" site disables this module too.
    """
    if not pallas_sort.supported(g, interpret=interpret):
        return False
    return g.dtype in _SUPPORTED_DTYPES and g.shape[0] <= MAX_ROWS


def _tile(n, buffers, d, interp):
    """Column-tile width (`pallas_sort._tile_for` budget). In interpret
    mode the tile clamps to d: a padded wider block would reduce over
    extra zero columns, and the different accumulation-tree shape breaks
    the bit-equality with the jnp reference that the oracle tests (and
    the diagnostics aux) are pinned to. Compiled Mosaic keeps the aligned
    width — the final grid block is partial there, which Mosaic clips —
    because 1-D output blocks must stay divisible by the minor tiling."""
    tile = pallas_sort._tile_for(n, buffers, 4)  # f32 itemsize
    return min(tile, d) if interp else tile


# --------------------------------------------------------------------------- #
# One-pass pairwise Gram

def _gram_kernel(d, tile, in_ref, out_ref):
    i = pl.program_id(0)
    x = in_ref[...]
    if d % tile:
        # The final block runs past d; Pallas pads the operand with
        # unspecified bytes, which would corrupt the accumulation (and a
        # NaN pad would survive a multiply-by-zero) — select them to 0,
        # which is additive identity for the dot below
        cols = (jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
                + i * tile)
        x = jnp.where(cols < d, x, 0.0)
    # precision=HIGHEST keeps the f32 accumulation of the jnp reference
    # (`ops._common.pairwise_distances`) — selection orderings and the
    # diagnostics aux must match it bit for bit
    part = jax.lax.dot_general(x, x, (((1,), (1,)), ((), ())),
                               precision=jax.lax.Precision.HIGHEST,
                               preferred_element_type=jnp.float32)

    @pl.when(i == 0)
    def _():
        out_ref[...] = part

    @pl.when(i > 0)
    def _():
        out_ref[...] = out_ref[...] + part


def sq_gram(g, *, interpret=False):
    """`g @ g.T` (f32[n, d] -> f32[n, n]) in ONE streamed read of `g`.

    The `(n, n)` output block is grid-resident (constant index map), so
    each d-tile's partial dot accumulates in VMEM and only the final tiny
    result reaches HBM. Non-finite rows poison their Gram entries exactly
    as the jnp matmul does (NaN/inf propagate through the dot), which the
    shared distance post-processing maps to +inf.
    """
    n, d = g.shape
    interp = interpret or pallas_sort.interpret_mode()
    tile = _tile(n, 3, d, interp)
    grid = (pl.cdiv(d, tile),)
    return pl.pallas_call(
        functools.partial(_gram_kernel, d, tile),
        out_shape=jax.ShapeDtypeStruct((n, n), jnp.float32),
        grid=grid,
        in_specs=[pl.BlockSpec((n, tile), lambda i: (0, i),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((n, n), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        interpret=interp)(g)


# --------------------------------------------------------------------------- #
# Streamed selected-row averages

def _select_rows(w, g):
    """`w @ g` with `ops._common.weighted_rows_mean`'s non-finite contract,
    on in-VMEM blocks: the masked form computed unconditionally (identical
    to the fast matmul when everything is finite — `where` passes `g`
    through untouched and no `bad` flag fires)."""
    finite = jnp.isfinite(g)
    gz = jnp.where(finite, g, 0.0)
    out = jax.lax.dot_general(w, gz, (((1,), (0,)), ((), ())),
                              precision=jax.lax.Precision.HIGHEST,
                              preferred_element_type=jnp.float32)
    sel = (w > 0).astype(jnp.float32)
    nonfin = (~finite).astype(jnp.float32)
    bad = jax.lax.dot_general(sel, nonfin, (((1,), (0,)), ((), ())),
                              precision=jax.lax.Precision.HIGHEST,
                              preferred_element_type=jnp.float32) > 0
    return jnp.where(bad, jnp.nan, out)


def _wmean_kernel(in_ref, w_ref, out_ref):
    out_ref[...] = _select_rows(w_ref[...], in_ref[...])


def _call_rowavg(kernel, g, w, out_rows, *, buffers, interpret):
    """Shared pallas_call wrapper for the averaging kernels: grid over
    d-tiles of `g: (n, d)`, a tiny resident `(r, n)` weight operand, and a
    `(out_rows, d)` or `(d,)` output."""
    n, d = g.shape
    interp = interpret or pallas_sort.interpret_mode()
    tile = _tile(n, buffers, d, interp)
    grid = (pl.cdiv(d, tile),)
    in_specs = [
        pl.BlockSpec((n, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
        pl.BlockSpec(w.shape, lambda i: (0, 0), memory_space=pltpu.VMEM),
    ]
    if out_rows is None:
        out_shape = jax.ShapeDtypeStruct((d,), jnp.float32)
        out_spec = pl.BlockSpec((tile,), lambda i: (i,),
                                memory_space=pltpu.VMEM)
    else:
        out_shape = jax.ShapeDtypeStruct((out_rows, d), jnp.float32)
        out_spec = pl.BlockSpec((out_rows, tile), lambda i: (0, i),
                                memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel, out_shape=out_shape, grid=grid,
        in_specs=in_specs, out_specs=out_spec,
        interpret=interp)(g, w)


def weighted_rows_mean(w, g, *, interpret=False):
    """`ops._common.weighted_rows_mean(w, g)` as one streamed read of `g`
    (krum's selected-row average, the masked-quorum krum variant, bulyan
    callers that need the stage-1 stack itself). `w: f32[n] | f32[r, n]`."""
    squeeze = w.ndim == 1
    W = w[None, :] if squeeze else w
    out = _call_rowavg(_wmean_kernel, g, W.astype(jnp.float32),
                       W.shape[0], buffers=4, interpret=interpret)
    return out[0] if squeeze else out


def _bulyan_tail_kernel(m2, in_ref, w_ref, out_ref):
    """Bulyan stages 1+2 fused: the `(rounds, tile)` selection stack is
    computed in VMEM and consumed by the averaged median immediately —
    only the final `(tile,)` row is written."""
    sel = _select_rows(w_ref[...], in_ref[...])
    rounds = sel.shape[0]
    rows = [sel[i, :] for i in range(rounds)]
    med = pallas_sort.sort_values(rows)[(rounds - 1) // 2]
    if m2 == 1:
        # `ops._common.averaged_median`'s m == 1 shortcut: the closest
        # value to the median IS the median
        out_ref[...] = med
    else:
        out_ref[...] = pallas_sort.closest_mean_values(rows, med, m2)


def selected_median_mean(W, g, m2, *, interpret=False):
    """Bulyan over Multi-Krum's d-space tail in ONE streamed read of `g`:
    the stage-1 averages (`W: f32[rounds, n]` from
    `ops/bulyan.py::selection_weights`) and the stage-2 averaged median
    with static `m2 = rounds - 2 f`, without materializing the
    `(rounds, d)` stack (`ops._common.averaged_median` semantics, NaN
    overflow included)."""
    kernel = functools.partial(_bulyan_tail_kernel, m2)
    # The stack, its deviations and the sorting network live per-tile in
    # VMEM: ~3 extra row sets beyond the input block
    return _call_rowavg(kernel, g, W.astype(jnp.float32), None,
                        buffers=8, interpret=interpret)


def _masked_mean_kernel(k, in_ref, m_ref, out_ref):
    g = in_ref[...]
    keep = m_ref[...][0] > 0
    kept = jnp.where(keep[:, None], g, 0.0)
    out_ref[...] = jnp.sum(kept, axis=0) / k


def masked_rows_mean(mask, g, k, *, interpret=False):
    """Brute's subset mean in one streamed read:
    `sum(where(mask[:, None], g, 0), axis=0) / k` — the exact
    `ops/brute.py` contract (excluded non-finite rows zeroed; a selected
    non-finite entry propagates through the sum as the jnp path does,
    NOT normalized to NaN)."""
    w = mask.astype(jnp.float32)[None, :]
    return _call_rowavg(functools.partial(_masked_mean_kernel, k), g, w,
                        None, buffers=4, interpret=interpret)
