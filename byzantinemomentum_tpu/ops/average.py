"""Simple averaging GAR (reference `aggregators/average.py`)."""

import jax.numpy as jnp

from byzantinemomentum_tpu.ops import register

__all__ = ["aggregate"]


def aggregate(gradients, **kwargs):
    """Arithmetic mean over the worker axis
    (reference `aggregators/average.py:21-29`)."""
    return jnp.mean(gradients, axis=0)


def check(gradients, **kwargs):
    if gradients.shape[0] < 1:
        return f"Expected at least one gradient to aggregate, got {gradients.shape[0]}"


def influence(honests, byzantines, **kwargs):
    """Attack acceptation ratio = f_real / n
    (reference `aggregators/average.py:42-49`)."""
    h = honests.shape[0]
    b = byzantines.shape[0]
    return b / (h + b)


register("average", aggregate, check, influence=influence)
