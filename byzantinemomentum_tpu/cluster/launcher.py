"""The fleet launcher/coordinator — owner of the cluster manifest, the
aggregated heartbeat, and the system-level chaos driver.

`python -m byzantinemomentum_tpu.cluster --hosts N ...` spawns N host
processes (`cluster/host.py`, one `jax.distributed` controller each over a
local TCP coordinator on a probed free port) and supervises them:

* **liveness** — per-host atomic heartbeats aggregate into the cluster
  liveness view (`cluster/manifest.py::liveness_view`, process table +
  heartbeat freshness) and into ONE top-level `heartbeat.json`, so the
  `Jobs` watchdog supervises a whole fleet through the same file a
  single-process run writes (`Jobs(seeds=(None,))`, the seedless
  service-job form — SIGKILL of this launcher kills the fleet through the
  per-host stdin pipes, and the Jobs retry relaunches it with
  `--auto-resume`).
* **metrics plane** (`--metrics-interval`, `obs/metrics`) — the
  launcher folds the liveness view into its own registry (per-state
  host gauges, a transition counter), a scraper thread rings windowed
  snapshots into `metrics.jsonl` next to `heartbeat.json`, and a
  loopback `MetricsEndpoint` answers the same `{"op": "metrics"}` pull
  verb a serve shard speaks.
* **chaos** — a system-scope `FaultPlan` (`--fault-plan`,
  `cluster/chaos.py`) SIGKILLs the planned host the first time the
  observed cluster step reaches the event's step; fired events persist in
  the manifest BEFORE the kill so recovery replays training, never the
  kill.
* **recovery** — on host death the launcher tears the fleet down (a
  gloo fleet missing a peer can only wedge), agrees the restart step from
  the off-slice mirror into the manifest
  (`manifest.agree_restart_step` — the dead host's local state is never
  consulted), and relaunches with `--auto-resume` (up to
  `--fleet-retries` times in-process; an exhausted launcher exits
  non-zero so an outer Jobs supervisor takes over with the same
  semantics). Every relaunched host reports the restart step it adopted;
  the launcher requires unanimity before declaring `restart_agreed`.
* **elastic shrink** (`--elastic`) — the relaunch happens at the
  SURVIVOR count instead of full width: `cluster/elastic.py` re-splits
  `nb_workers`/`nb_for_study` across the shrunken fleet and re-clamps
  the declared quorum `f` to the GAR ceiling at the shrunk worker count
  (the static analogue of `faults/quorum.py`), the restart step still
  comes from the off-slice mirror, and the shrink lands as a VERSIONED
  membership event in `fleet.json` (the `serve/fleet/ring.py`
  Membership discipline, persisted before any respawn — a retried
  launcher replays the change log and adopts the shrunken width).
* **straggler policy** (`--straggler-wait` / `--straggler-edges` /
  `--quarantine`) — `cluster/straggler.py` folds the liveness view's
  stale/alive edges (and the health block's SPC anomalies, at host
  scope) into HEALTHY -> SUSPECT -> (recovered | KILLED): a host stale
  past the bounded wait is killed-and-shrunk instead of wedging the
  fleet until the watchdog fires. SIGSTOP/SIGCONT chaos windows
  (`straggle` events, `cluster/chaos.py::StraggleResumer`) exercise
  exactly this failure mode.
* **artifact** — the outcome lands in a `CLUSTER.json`-shape artifact
  (`--bench-out`, default `<result-directory>/CLUSTER.json`): hosts,
  steps/s, recovery-step count, the cross-host lattice census verdict
  and the zero-recompile bit. An unreachable runtime writes
  `"status": "unavailable"` and exits 0 — the bench.py cpu-fallback
  discipline, never an rc=124 hang (`cluster/runtime.py` bounds every
  bind/connect).
"""

import argparse
import json
import os
import pathlib
import signal
import sys
import time

__all__ = ["main", "process_commandline"]

from byzantinemomentum_tpu.cluster.runtime import UNAVAILABLE_RC, free_port

# The repo root (the package's parent): host subprocesses are spawned
# with it on PYTHONPATH so `-m byzantinemomentum_tpu.cluster.host`
# resolves regardless of the launcher's own working directory
_PKG_ROOT = pathlib.Path(__file__).resolve().parents[2]

# Host-run flags forwarded verbatim to every host process (the fleet's
# shared run spec; argparse dest -> flag)
_RUN_FLAGS = ("nb_steps", "seed", "nb_workers", "nb_decl_byz",
              "nb_real_byz", "gar", "attack", "model", "dataset",
              "batch_size", "nb_for_study", "nb_for_study_past",
              "learning_rate", "momentum", "checkpoint_delta",
              "connect_timeout")


def process_commandline(argv=None):
    parser = argparse.ArgumentParser(prog="cluster")
    add = parser.add_argument
    add("--hosts", type=int, default=2,
        help="Fleet size: one jax.distributed controller process per host")
    add("--result-directory", type=str, required=True)
    add("--mirror", type=str, default=None,
        help="Off-slice checkpoint mirror (default: "
             "<result-directory>/mirror). Restart steps are agreed from "
             "HERE, never from any host's local directory")
    add("--device", type=str, default="auto",
        help="Accepted for Jobs-supervisor compatibility; the fleet "
             "simulates hosts on the CPU backend unless "
             "BMT_CLUSTER_NATIVE=1")
    add("--seed", type=int, default=1)
    add("--auto-resume", action="store_true", default=False,
        help="Resume the fleet from the mirror's newest valid checkpoint "
             "(the Jobs supervisor appends this on retries)")
    add("--fleet-retries", type=int, default=2,
        help="In-process fleet relaunches after a host loss (0: exit "
             "non-zero immediately and let an outer supervisor retry)")
    add("--fault-plan", type=str, default=None,
        help="System-scope FaultPlan JSON: device_loss events SIGKILL "
             "the named HOST at the named step; straggle events SIGSTOP "
             "it for window_s seconds (cluster/chaos.py)")
    add("--elastic", action="store_true", default=False,
        help="On host loss, relaunch at the SURVIVOR count instead of "
             "full width: nb_workers/nb_for_study re-split, quorum f "
             "re-clamped (cluster/elastic.py), the shrink persisted as "
             "a versioned membership event in fleet.json before any "
             "respawn")
    add("--min-hosts", type=int, default=1,
        help="Elastic floor: never shrink below this many hosts (the "
             "launcher halts with status below_min_hosts instead)")
    add("--straggler-wait", type=float, default=None,
        help="Bounded-wait-then-kill straggler policy "
             "(cluster/straggler.py): seconds a SUSPECT host may stay "
             "stale before the launcher kills it and recovers")
    add("--straggler-edges", type=str, default=None,
        help="Path of a `scripts/stale_edges.py --json` summary; its "
             "machine-readable recommendation block sets the straggler "
             "wait bound (p95 of observed recoveries x 1.25)")
    add("--quarantine", action="store_true", default=False,
        help="Host-scope health quarantine: sustained SPC anomalies in "
             "a host's heartbeat health block (--health) make it "
             "SUSPECT under the same bounded wait — drain-by-kill and "
             "shrink/relaunch past it before it poisons the run")
    add("--quarantine-anomaly-polls", type=int, default=None,
        help="Consecutive anomalous polls before the quarantine arm "
             "turns a host SUSPECT (the arena's hysteresis shape at "
             "host scope: one bad window is not a verdict). Default: "
             "the --quarantine-rates recommendation when given, else 3")
    add("--quarantine-rates", type=str, default=None,
        help="Path of a `scripts/quarantine_rates.py --json` summary; "
             "its machine-readable recommendation block sets the "
             "quarantine enter-threshold from observed anomaly-episode "
             "lengths (an explicit --quarantine-anomaly-polls wins)")
    add("--metrics-interval", type=float, default=2.0,
        help="Metrics-plane snapshot cadence in seconds (obs/metrics): "
             "the launcher folds liveness state into its registry, "
             "appends merged snapshots to metrics.jsonl next to "
             "heartbeat.json, and answers {'op': 'metrics'} on a "
             "loopback exposition port; 0 disables the plane")
    add("--connect-timeout", type=float, default=60.0)
    add("--heartbeat-stale", type=float, default=60.0,
        help="Seconds without a host heartbeat update before the "
             "liveness view marks it stale")
    add("--poll", type=float, default=0.2,
        help="Supervision poll interval in seconds")
    add("--recompile-check", type=int, default=0)
    add("--lattice-census", action="store_true", default=False)
    add("--bench-out", type=str, default=None,
        help="Path of the CLUSTER.json outcome artifact (default: "
             "<result-directory>/CLUSTER.json)")
    add("--nb-steps", type=int, default=8)
    add("--nb-workers", type=int, default=8)
    add("--nb-decl-byz", type=int, default=2)
    add("--nb-real-byz", type=int, default=2)
    add("--gar", type=str, default="median")
    add("--attack", type=str, default="empire")
    add("--attack-args", nargs="*")
    add("--model", type=str, default="simples-full")
    add("--dataset", type=str, default="mnist")
    add("--batch-size", type=int, default=8)
    add("--nb-for-study", type=int, default=8)
    add("--nb-for-study-past", type=int, default=2)
    add("--learning-rate", type=float, default=0.05)
    add("--momentum", type=float, default=0.9)
    add("--checkpoint-delta", type=int, default=2)
    add("--health", action="store_true", default=False,
        help="Numerics flight recorder on every host (engine/health.py "
             "in-jit stats + per-host SPC monitor): each host's "
             "heartbeat gains a 'health' block the liveness view and "
             "the aggregated fleet heartbeat carry through")
    return parser.parse_args(sys.argv[1:] if argv is None else argv)


class _Fleet:
    """One fleet attempt: the host subprocesses plus their stdin pipes
    (held exclusively here — launcher death closes them and the hosts'
    parent-watch threads exit, so a SIGKILLed launcher never leaks a
    training fleet)."""

    def __init__(self, procs):
        self.procs = procs

    def running(self):
        return {i: p.poll() is None for i, p in enumerate(self.procs)}

    def returncodes(self):
        return [p.poll() for p in self.procs]

    def kill(self, host):
        try:
            self.procs[host].kill()
        except OSError:
            pass

    def stop(self, host):
        """SIGSTOP (straggle chaos): the host stays in the process table
        but stops stepping — alive-but-wedged, not dead."""
        try:
            self.procs[host].send_signal(signal.SIGSTOP)
        except OSError:
            pass

    def stopped_hosts(self):
        """Hosts whose process is NOT SCHEDULING (Linux state `T`:
        SIGSTOP'd / traced) — decisive straggler-blame evidence, since a
        wedged-but-runnable hostage never shows `T`. Empty wherever
        /proc is unreadable (non-Linux: the policy falls back to its
        suspect-duration ordering)."""
        stopped = set()
        for host, proc in enumerate(self.procs):
            if proc.poll() is not None:
                continue
            try:
                stat = pathlib.Path(f"/proc/{proc.pid}/stat").read_text()
                # Field 3, after the parenthesized comm (which may
                # itself contain spaces and parens)
                state = stat.rsplit(") ", 1)[1].split(" ", 1)[0]
            except (OSError, IndexError):
                continue
            if state in ("T", "t"):
                stopped.add(host)
        return frozenset(stopped)

    def teardown(self):
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except Exception:  # bmt: noqa[BMT-E05] a kill-then-wait that still fails means the OS is reaping it; teardown must not raise
                pass
            if p.stdin is not None:
                try:
                    p.stdin.close()
                except OSError:
                    pass


def _spawn_fleet(args, resdir, mirror, port):
    import subprocess

    hosts_dir = resdir / "hosts"
    hosts_dir.mkdir(parents=True, exist_ok=True)
    procs = []
    for host in range(args.hosts):
        cmd = [sys.executable, "-m", "byzantinemomentum_tpu.cluster.host",
               "--procs", str(args.hosts), "--proc-id", str(host),
               "--coordinator", f"127.0.0.1:{port}",
               "--result-directory", str(resdir),
               "--mirror", str(mirror),
               "--parent-pipe"]
        if args.auto_resume:
            cmd.append("--auto-resume")
        if args.recompile_check:
            cmd += ["--recompile-check", str(args.recompile_check)]
        if args.lattice_census:
            cmd.append("--lattice-census")
        if args.health:
            cmd.append("--health")
        if args.attack_args:
            cmd += ["--attack-args", *args.attack_args]
        for dest in _RUN_FLAGS:
            cmd += [f"--{dest.replace('_', '-')}",
                    str(getattr(args, dest))]
        out = (hosts_dir / f"host-{host}.out.log").open("ab")
        err = (hosts_dir / f"host-{host}.err.log").open("ab")
        env = dict(os.environ)
        env["PYTHONPATH"] = (str(_PKG_ROOT) + os.pathsep
                             + env.get("PYTHONPATH", "")).rstrip(os.pathsep)
        proc = subprocess.Popen(cmd, stdin=subprocess.PIPE, stdout=out,
                                stderr=err, cwd=str(_PKG_ROOT), env=env)
        out.close()
        err.close()
        procs.append(proc)
    return _Fleet(procs)


def _clear_host_signals(resdir, hosts):
    """Stale heartbeats/census from a previous attempt must not feed this
    attempt's liveness view or agreement checks."""
    from byzantinemomentum_tpu.obs.heartbeat import host_heartbeat_path

    for host in range(hosts):
        for path in (host_heartbeat_path(resdir, host),
                     resdir / "hosts" / f"host-{host}.census.json"):
            try:
                path.unlink()
            except OSError:
                pass


def _check_census(resdir, hosts):
    """Cross-host census verdict: every host lowered the same cells to
    the same fingerprints with zero BMT-H violations. Returns a dict (or
    None when no host wrote a census)."""
    artifacts = {}
    for host in range(hosts):
        path = resdir / "hosts" / f"host-{host}.census.json"
        try:
            artifacts[host] = json.loads(path.read_text())
        except (OSError, ValueError):
            continue
    if not artifacts:
        return None
    fingerprints = [
        {key: cell.get("fingerprint")
         for key, cell in (art.get("cells") or {}).items()}
        for art in artifacts.values()]
    violations = sum(int(art.get("violations") or 0)
                     for art in artifacts.values())
    agree = all(fp == fingerprints[0] for fp in fingerprints[1:])
    return {"hosts_reporting": sorted(artifacts),
            "cells": len(fingerprints[0]),
            "fingerprints_agree": bool(agree and fingerprints[0]),
            "violations": violations,
            "ok": bool(agree and fingerprints[0] and violations == 0)}


def main(argv=None):
    args = process_commandline(argv)
    if args.hosts < 1:
        print("cluster: need at least one host")
        return 2
    resdir = pathlib.Path(args.result_directory).resolve()
    resdir.mkdir(parents=True, exist_ok=True)
    mirror = pathlib.Path(args.mirror).resolve() if args.mirror \
        else resdir / "mirror"
    mirror.mkdir(parents=True, exist_ok=True)
    bench_out = (pathlib.Path(args.bench_out) if args.bench_out
                 else resdir / "CLUSTER.json")

    from byzantinemomentum_tpu.cluster import chaos as chaos_mod
    from byzantinemomentum_tpu.cluster import elastic as elastic_mod
    from byzantinemomentum_tpu.cluster import manifest as manifest_mod
    from byzantinemomentum_tpu.cluster import straggler as straggler_mod
    from byzantinemomentum_tpu.obs import Telemetry
    from byzantinemomentum_tpu.obs.heartbeat import write_heartbeat
    from byzantinemomentum_tpu.obs.metrics import (MetricsEndpoint,
                                                   MetricsRegistry,
                                                   MetricsScraper)
    from byzantinemomentum_tpu.obs.trace import ClockOffsetTracker
    from byzantinemomentum_tpu.serve.fleet import ring as ring_mod

    # The LAUNCH-width run shape: every elastic re-derivation starts from
    # here (args gets mutated in place on shrink so the spawn/liveness/
    # census paths follow automatically)
    initial_hosts = args.hosts
    elastic_base = {"hosts": args.hosts, "nb_workers": args.nb_workers,
                    "nb_decl_byz": args.nb_decl_byz,
                    "nb_real_byz": args.nb_real_byz,
                    "nb_for_study": args.nb_for_study, "gar": args.gar}

    plan = None
    if args.fault_plan is not None:
        from byzantinemomentum_tpu.faults import FaultPlan

        try:
            plan = FaultPlan.load(args.fault_plan)
        except (OSError, ValueError, TypeError) as err:
            print(f"cluster: unable to load fault plan "
                  f"{args.fault_plan!r}: {err}")
            return 2
        message = plan.validate_system(initial_hosts)
        if message is not None:
            print(f"cluster: fault plan rejected: {message}")
            return 2

    policy = None
    if (args.straggler_wait is not None or args.straggler_edges
            or args.quarantine):
        try:
            wait_s, wait_source = straggler_mod.resolve_wait_bound(
                args.straggler_wait, args.straggler_edges)
            polls, polls_source = straggler_mod.resolve_anomaly_polls(
                args.quarantine_anomaly_polls, args.quarantine_rates)
        except (OSError, ValueError) as err:
            print(f"cluster: straggler policy unavailable: {err}")
            return 2
        policy = straggler_mod.StragglerPolicy(
            wait_s, source=wait_source, quarantine=args.quarantine,
            anomaly_enter=polls, anomaly_source=polls_source)

    manifest = manifest_mod.read_cluster_manifest(resdir)
    membership = None
    shrinks = []
    if args.elastic:
        message = elastic_mod.precheck(elastic_base, args.min_hosts)
        if message is not None:
            print(f"cluster: elastic refused: {message}")
            return 2
        shrinks = list((manifest.get("elastic") or {}).get("shrinks")
                       or [])
        payload = ring_mod.read_fleet_manifest(resdir)
        if payload is not None:
            # Recovery-path proof: a retried launcher reconstructs the
            # fleet it must adopt from the persisted change LOG alone
            membership = ring_mod.Membership.replay(payload)
        else:
            membership = ring_mod.Membership(vnodes=1)
            for slot in range(args.hosts):
                membership.bump("add", slot, role="host")
            ring_mod.write_fleet_manifest(
                resdir, membership, initial_hosts=initial_hosts)
        width = len(membership.shards)
        if width < 1:
            print("cluster: elastic membership has no surviving hosts")
            return 2
        if width != args.hosts:
            spec = elastic_mod.shrunk_spec(elastic_base, width)
            for key, value in spec.items():
                setattr(args, key, value)
    manifest["hosts"] = args.hosts
    driver = (chaos_mod.SystemFaultDriver(
        plan, initial_hosts, fired=manifest.get("fired_faults") or ())
        if plan is not None else None)
    resumer = (chaos_mod.StraggleResumer()
               if plan is not None
               and any(e.kind == "straggle" for e in plan.events)
               else None)

    telem = Telemetry(resdir)
    telem.event("cluster_start", hosts=args.hosts, steps=args.nb_steps,
                auto_resume=bool(args.auto_resume),
                fault_events=(len(plan.events) if plan else 0))
    # The launcher's metrics plane (obs/metrics): training hosts expose
    # their numbers through heartbeats, the launcher folds the liveness
    # view into ITS registry (state gauges + transition counter), the
    # scraper rings the snapshots into metrics.jsonl next to
    # heartbeat.json, and the loopback endpoint answers the same
    # {"op": "metrics"} verb a serve shard does — one scrape protocol
    metrics = MetricsRegistry(source="launcher")
    m_polls = metrics.counter("cluster_liveness_polls")
    m_transitions = metrics.counter("cluster_liveness_transitions")
    m_hosts = {status: metrics.gauge(f"cluster_hosts_{status}")
               for status in ("alive", "stale", "dead", "unknown")}
    endpoint = scraper = None
    if args.metrics_interval > 0:
        endpoint = MetricsEndpoint(("127.0.0.1", 0), metrics.dump)
        endpoint.serve_background()
        scraper = MetricsScraper({}, resdir,
                                 interval=args.metrics_interval,
                                 local=metrics).start()
        telem.event("metrics_endpoint", host="127.0.0.1",
                    port=endpoint.port,
                    interval_s=args.metrics_interval)
    # A live signal BEFORE the slow part (spawn + jax imports + compile),
    # so an outer Jobs watchdog never kills a fleet for starting up
    write_heartbeat(resdir, {"step": None, "status": "launching",
                             "hosts": args.hosts})

    # The Jobs-watchdog chaos hook (tests/test_cluster.py): once the
    # fleet reaches the step, kill it and go silent — the aggregated
    # heartbeat stalls and the OUTER watchdog must SIGKILL this launcher
    wedge_at = os.environ.get("BMT_CHAOS_CLUSTER_WEDGE_AT")
    wedge_at = int(wedge_at) if wedge_at else None
    wedge_fuse = resdir / "wedge.fired"

    def aggregate(view, status):
        alive = view["alive"]
        payload = {
            "step": view["min_step"], "status": status,
            "hosts": args.hosts, "hosts_alive": len(alive),
            "host_steps": {str(h): view["hosts"][h]["step"]
                           for h in alive}}
        # Training-dynamics state rides the fleet heartbeat too: the
        # per-host flight-recorder blocks (obs/health via the driver's
        # heartbeat), so the Jobs watchdog sees anomaly state, not just
        # liveness
        health = {str(h): view["hosts"][h]["health"] for h in alive
                  if view["hosts"][h].get("health")}
        if health:
            payload["health"] = health
        write_heartbeat(resdir, payload)

    recoveries = list(manifest.get("recoveries") or [])
    attempt = int(manifest.get("attempt") or 0)
    outcome = None
    final_view = None
    steps_per_sec = None
    # Fleet-timeline substrate (obs/trace/fleet.py): per-host clock
    # offsets estimated from the heartbeat handshake on every poll, and
    # liveness edges emitted as first-class events (the raw per-host
    # heartbeats are overwritten in place — without the edge events the
    # joined timeline could not show WHEN a host went stale or died)
    clock = ClockOffsetTracker()
    last_status = {}

    # Incident bundles (obs/trace/incident.py): a straggler KILL is a
    # fleet edge event — freeze the evidence (registry dump, liveness
    # view + clock offsets, membership version) the moment the policy
    # pulls the trigger, not minutes later when someone reads the log.
    # trigger() is enqueue-only, so the supervision loop never blocks
    # on bundle I/O; the worker writes incidents/incident-<n>.json
    # atomically and obs_report replays the causal story
    from byzantinemomentum_tpu.obs.trace import (IncidentRecorder,
                                                 merge_fleet_incidents)
    incidents = IncidentRecorder(
        resdir, source="cluster-launcher",
        providers={
            "metrics": metrics.dump,
            "liveness": lambda: {"hosts": dict(last_status),
                                 "clock_offsets": clock.estimate()},
            "membership": lambda: (
                {"version": membership.version,
                 "hosts": sorted(membership.shards)}
                if membership is not None else {"elastic": False}),
        }).start()

    def observe_view(view, now):
        counts = dict.fromkeys(m_hosts, 0)
        for host, row in view["hosts"].items():
            if row.get("updated") is not None:
                clock.observe(host, row["updated"], now)
            status = row["status"]
            counts[status] = counts.get(status, 0) + 1
            if last_status.get(host) != status:
                if host in last_status or status != "unknown":
                    telem.event("liveness_transition", host=host,
                                **{"from": last_status.get(host),
                                   "to": status, "step": row.get("step")})
                    m_transitions.inc()
                last_status[host] = status
        m_polls.inc()
        for status, gauge in m_hosts.items():
            gauge.set(counts[status])

    while True:
        attempt += 1
        restart_step = None
        if args.auto_resume:
            restart_step, _ = manifest_mod.agree_restart_step(mirror)
        manifest.update(attempt=attempt, restart_step=restart_step,
                        status="launching",
                        fired_faults=(driver.fired() if driver else []))
        manifest_mod.write_cluster_manifest(resdir, manifest)
        _clear_host_signals(resdir, initial_hosts)
        port = free_port()
        telem.event("fleet_launch", attempt=attempt, hosts=args.hosts,
                    coordinator_port=port, restart_step=restart_step)
        fleet = _spawn_fleet(args, resdir, mirror, port)
        if policy is not None:
            # A fresh attempt's hosts share nothing with the wedged one
            policy.reset()
        agreed = False
        outcome = None
        killed_host = None
        killed_at = None
        while outcome is None:
            time.sleep(max(args.poll, 0.01))
            running = fleet.running()
            view = manifest_mod.liveness_view(
                resdir, args.hosts, stale_after=args.heartbeat_stale,
                running=running)
            observe_view(view, time.time())
            aggregate(view, "running")
            # Straggler policy: bounded wait on stale/anomalous hosts,
            # then kill the laggard — the kill flows into the ordinary
            # host_lost recovery (and the elastic shrink) below
            if policy is not None:
                for ev in policy.observe(view, time.time(),
                                         stopped=fleet.stopped_hosts()):
                    telem.event(
                        "straggler_" + ev["event"],
                        **{k: v for k, v in ev.items() if k != "event"})
                    if ev["event"] == "kill":
                        incidents.trigger(
                            "straggler_kill",
                            **{k: v for k, v in ev.items()
                               if k != "event"})
                        if resumer is not None:
                            # Claim any pending SIGCONT first: a killed
                            # host must never be resumed
                            resumer.cancel(ev["host"])
                        fleet.kill(ev["host"])
            # Restart agreement: once every host has reported, the
            # adopted steps must be unanimous and equal the manifest's
            if not agreed and restart_step is not None:
                reported = [view["hosts"][h].get("resume_step")
                            for h in range(args.hosts)
                            if view["hosts"][h]["step"] is not None]
                if len(reported) == args.hosts:
                    if any(r != restart_step for r in reported):
                        telem.event("restart_disagreement",
                                    manifest_step=restart_step,
                                    reported=reported)
                        outcome = "disagreement"
                        break
                    agreed = True
                    telem.event("restart_agreed", step=restart_step,
                                hosts=args.hosts)
            # System-level chaos: persist the fired record, THEN kill
            if driver is not None:
                for index, event in driver.due(view["max_step"]):
                    driver.mark(index)
                    manifest.update(fired_faults=driver.fired())
                    manifest_mod.write_cluster_manifest(resdir, manifest)
                    if event.worker >= args.hosts:
                        # An elastic shrink renumbered the fleet below
                        # this event's target; spend it rather than let
                        # it aim at a host that no longer exists
                        telem.event("fault_skipped", kind=event.kind,
                                    host=event.worker, reason="shrunk",
                                    hosts=args.hosts)
                        continue
                    telem.event("fault_injected", kind=event.kind,
                                host=event.worker,
                                at_step=view["max_step"],
                                plan_step=event.step,
                                **({"window_s": event.window_s}
                                   if event.kind == "straggle" else {}))
                    if event.kind == "straggle":
                        fleet.stop(event.worker)
                        resumer.schedule(event.worker,
                                         fleet.procs[event.worker],
                                         event.window_s)
                    else:
                        fleet.kill(event.worker)
            if wedge_at is not None and not wedge_fuse.exists() \
                    and view["max_step"] is not None \
                    and view["max_step"] >= wedge_at:
                wedge_fuse.write_text(str(view["max_step"]))
                telem.event("wedge", step=view["max_step"])
                if resumer is not None:
                    resumer.cancel()
                fleet.teardown()
                while True:  # silent: the outer watchdog must kill us
                    time.sleep(60)
            rcs = fleet.returncodes()
            if all(rc == 0 for rc in rcs):
                outcome = "completed"
            elif any(rc == UNAVAILABLE_RC for rc in rcs):
                outcome = "unavailable"
            elif any(rc not in (None, 0) for rc in rcs):
                outcome = "host_lost"
                killed_host = next(i for i, rc in enumerate(rcs)
                                   if rc not in (None, 0))
                killed_at = view["max_step"]
            final_view = view
        # Persist the clock-offset estimates BEFORE teardown: the
        # timeline join (obs/trace/fleet.py::estimate_offsets) reads
        # the newest clock_offsets event, and a relaunch keeps refining
        if clock.estimate():
            telem.event("clock_offsets", **clock.as_event_data())
        if resumer is not None:
            # Pending SIGCONT windows die with the fleet (a stopped
            # process takes SIGKILL just fine; resuming a recycled pid
            # later would not be fine)
            resumer.cancel()
        fleet.teardown()
        if outcome == "completed":
            break
        if outcome in ("unavailable", "disagreement"):
            break
        # host_lost: record the recovery, then relaunch or hand off
        telem.event("host_dead", host=killed_host, at_step=killed_at,
                    attempt=attempt)
        # Lost hardware loses its local disk with it: delete the dead
        # host's slice-local directory (its checkpoints included) so the
        # recovery path PROVABLY depends on the off-slice mirror alone
        import shutil

        shutil.rmtree(resdir / f"host-{killed_host}", ignore_errors=True)
        new_restart, _ = manifest_mod.agree_restart_step(mirror)
        recovery = {"host": killed_host, "died_at_step": killed_at,
                    "restart_step": new_restart,
                    "recovery_steps": (killed_at - new_restart
                                       if None not in (killed_at,
                                                       new_restart)
                                       else None)}
        if args.elastic:
            recovery["survivors"] = args.hosts - 1
        recoveries.append(recovery)
        if args.elastic:
            survivors = args.hosts - 1
            if survivors < max(args.min_hosts, 1):
                manifest.update(recoveries=recoveries, status="halted")
                manifest_mod.write_cluster_manifest(resdir, manifest)
                telem.event("fleet_halt", reason="below_min_hosts",
                            survivors=survivors, min_hosts=args.min_hosts)
                outcome = "below_min_hosts"
                break
            # The shrink is a versioned membership event, persisted
            # BEFORE any respawn: slot ids are the ORIGINAL fleet's host
            # indices; surviving slots keep their ids while the spawn
            # renumbers proc ids densely over the survivors
            slots = sorted(int(s) for s in membership.shards)
            slot = slots[killed_host]
            membership.bump("dead", slot, died_at_step=killed_at,
                            attempt=attempt)
            membership.bump("remove", slot)
            spec = elastic_mod.shrunk_spec(elastic_base, survivors)
            ring_mod.write_fleet_manifest(
                resdir, membership, initial_hosts=initial_hosts,
                config=spec)
            for key, value in spec.items():
                setattr(args, key, value)
            shrinks.append({"attempt": attempt, "from": survivors + 1,
                            "to": survivors, "killed_host": killed_host,
                            "slot": slot, "died_at_step": killed_at,
                            "membership_version": membership.version,
                            "config": spec})
            manifest["elastic"] = {"initial_hosts": initial_hosts,
                                   "hosts": args.hosts,
                                   "min_hosts": args.min_hosts,
                                   "shrinks": shrinks}
            telem.event("fleet_shrink", attempt=attempt,
                        survivors=survivors, killed_host=killed_host,
                        slot=slot,
                        membership_version=membership.version,
                        nb_workers=args.nb_workers,
                        nb_decl_byz=args.nb_decl_byz)
        manifest.update(recoveries=recoveries, status="recovering")
        manifest_mod.write_cluster_manifest(resdir, manifest)
        telem.event("fleet_teardown", attempt=attempt,
                    restart_step=new_restart)
        if attempt > args.fleet_retries:
            outcome = "retries_exhausted"
            break
        if not args.auto_resume:
            # Without resume a relaunch replays from step 0 AND re-frees
            # the fired faults' steps — hand off to the outer supervisor,
            # which appends --auto-resume on its retry
            outcome = "needs_resume"
            break

    # ---------------- outcome -> artifact + exit code ---------------- #
    if resumer is not None:
        resumer.stop()
    census = _check_census(resdir, args.hosts)
    if outcome == "completed":
        from byzantinemomentum_tpu.obs.heartbeat import (
            read_host_heartbeats)
        beats = read_host_heartbeats(resdir)
        rates = [b.get("steps_per_sec") for b in beats.values()
                 if isinstance(b.get("steps_per_sec"), (int, float))]
        # The fleet advances in lockstep (collectives synchronize), so
        # the slowest host's estimate is the honest cluster rate
        steps_per_sec = round(min(rates), 3) if rates else None

    recovery_steps = sum(r["recovery_steps"] for r in recoveries
                         if r.get("recovery_steps") is not None)
    import jax  # the launcher never initializes a backend: version only

    artifact = {
        "kind": "cluster",
        "backend": ("cpu" if not os.environ.get("BMT_CLUSTER_NATIVE")
                    else "native"),
        "jax": jax.__version__,
        "status": {"completed": "ok"}.get(outcome, outcome),
        "hosts": args.hosts,
        "steps": args.nb_steps,
        "steps_per_sec": steps_per_sec,
        "config": {"nb_workers": args.nb_workers, "gar": args.gar,
                   "attack": args.attack, "model": args.model,
                   "seed": args.seed,
                   "checkpoint_delta": args.checkpoint_delta},
        "recovery": {"events": len(recoveries),
                     "recoveries": recoveries,
                     "recovery_steps": recovery_steps,
                     "attempts": attempt},
        "elastic": ({"initial_hosts": initial_hosts,
                     "final_hosts": args.hosts,
                     "min_hosts": args.min_hosts,
                     "shrinks": shrinks,
                     "membership_version": membership.version}
                    if args.elastic else None),
        "straggler": (policy.summary() if policy is not None else None),
        "straggle_windows": (resumer.stats() if resumer is not None
                             else None),
        "census": census,
        "zero_recompile": ({"warm_steps": args.recompile_check,
                            "asserted": outcome == "completed"}
                           if args.recompile_check else None),
    }
    bench_out.parent.mkdir(parents=True, exist_ok=True)
    bench_out.write_text(json.dumps(artifact, indent="\t", sort_keys=True)
                         + "\n")
    status = artifact["status"]
    manifest.update(status=status)
    manifest_mod.write_cluster_manifest(resdir, manifest)
    telem.event("cluster_end", status=status,
                steps_per_sec=steps_per_sec,
                recovery_steps=recovery_steps, attempts=attempt)
    if scraper is not None:
        scraper.stop()
        scraper.scrape_once()  # the run's end-state lands in the ring
    if endpoint is not None:
        endpoint.shutdown()
        endpoint.server_close()
    incidents.stop()
    merge_fleet_incidents(resdir)  # host bundles -> incidents/fleet.json
    telem.close()
    final_status = {"ok": "completed"}.get(status, status)
    final_beat = {
        "step": (final_view or {}).get("min_step"),
        "status": final_status, "hosts": args.hosts}
    # Final training-dynamics state: completed hosts are no longer
    # "alive", so read their last heartbeats' flight-recorder blocks
    # directly — the fleet's post-mortem heartbeat carries the health
    # story, not just liveness
    from byzantinemomentum_tpu.obs.heartbeat import read_host_heartbeats
    health = {str(h): beat["health"]
              for h, beat in read_host_heartbeats(resdir).items()
              if isinstance(beat.get("health"), dict)}
    if health:
        final_beat["health"] = health
    write_heartbeat(resdir, final_beat)
    print("cluster: " + json.dumps(
        {"status": status, "hosts": args.hosts,
         "steps_per_sec": steps_per_sec,
         "recovery_steps": recovery_steps, "attempts": attempt,
         "census_ok": (census or {}).get("ok"),
         "artifact": str(bench_out)}), flush=True)
    if status == "ok":
        if args.lattice_census and not (census or {}).get("ok"):
            return 5  # the fleet trained but the program census failed
        return 0
    if status == "unavailable":
        # The bounded-timeout contract: a missing runtime is a clean,
        # machine-readable artifact and a zero exit — never an rc=124
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
