"""Multi-host cluster runtime: true multi-controller execution,
system-level chaos, and coordinated recovery.

Every resilience layer before this package — PR 1 fault injection, PR 2
resume/rollback, PR 3 heartbeats, PR 11 quarantine — ran inside ONE
process: "device loss" was a masked row, never lost hardware. Here the
fleet is real processes:

  runtime.py    `jax.distributed` per-host initialization over a local
                TCP coordinator (CPU-provable in CI via the gloo
                collectives; `BMT_CLUSTER_NATIVE=1` re-enables a real
                accelerator fleet), with every bind/connect bounded —
                unavailability is a clean exit code and artifact, never
                an rc=124 hang.
  host.py       one controller of the fleet: the mesh-sharded engine step
                over the global (workers, model) mesh — real cross-host
                collectives — deterministic cross-host sampling, per-host
                atomic heartbeats, local + off-slice-mirrored
                checkpoints, and a study CSV whose killed-and-resumed
                output is bit-identical to an uninterrupted run's.
  manifest.py   the per-run consensus artifact (`cluster.json`, single
                writer) and the heartbeat-aggregated cluster liveness
                view — the Ray-style split (PAPERS.md) between a central
                liveness record and per-host state ownership.
  chaos.py      the system-level `FaultPlan` driver: `device_loss`
                events SIGKILL real host processes, fire-once through
                the manifest so recovery replays training, not the kill.
  launcher.py   the fleet supervisor tying it together: spawn, liveness,
                chaos, teardown-on-host-death, restart-step agreement,
                relaunch with `--auto-resume`, and the `CLUSTER.json`
                outcome artifact. Supervisable itself by `utils/jobs.py`
                through the aggregated heartbeat (the seedless
                service-job form).

Entry point: `python -m byzantinemomentum_tpu.cluster --hosts N ...`.
"""

from byzantinemomentum_tpu.cluster.chaos import SystemFaultDriver
from byzantinemomentum_tpu.cluster.manifest import (
    CLUSTER_MANIFEST_NAME,
    agree_restart_step,
    liveness_view,
    read_cluster_manifest,
    update_cluster_manifest,
    write_cluster_manifest,
)
from byzantinemomentum_tpu.cluster.runtime import (
    UNAVAILABLE_RC,
    ClusterUnavailable,
    HostSpec,
    cluster_mesh,
    free_port,
    initialize,
    shutdown,
)

__all__ = [
    "CLUSTER_MANIFEST_NAME", "ClusterUnavailable", "HostSpec",
    "SystemFaultDriver", "UNAVAILABLE_RC", "agree_restart_step",
    "cluster_mesh", "free_port", "initialize", "liveness_view",
    "read_cluster_manifest", "shutdown", "update_cluster_manifest",
    "write_cluster_manifest",
]
