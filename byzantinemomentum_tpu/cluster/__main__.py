"""`python -m byzantinemomentum_tpu.cluster` — the fleet launcher CLI
(`cluster/launcher.py`)."""

import sys

from byzantinemomentum_tpu.cluster.launcher import main

if __name__ == "__main__":
    sys.exit(main())
