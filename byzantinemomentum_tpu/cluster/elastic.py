"""Elastic shrink arithmetic — how a fleet survives at the SURVIVOR count.

When a host dies the launcher no longer has to relaunch at full width:
this module re-derives the run configuration for the shrunken fleet.
Everything here is launcher-side, stdlib-only and STATIC — the traced
per-step quorum clamp lives in `faults/quorum.py::effective_f`; this is
its whole-fleet analogue, applied once per shrink so the relaunched
hosts compile a fresh `(n', f')` contract instead of masking rows
forever.

The re-split holds the PER-HOST shares constant (`nb_workers / hosts`
simulated workers and `nb_for_study / hosts` study slots per host) and
scales totals to the survivor count, because the host runtime shards the
sampled batch across the workers mesh axis and refuses ragged splits
(`cluster/host.py`: `nb_sampled % workers_ax == 0`). `precheck` proves
at LAUNCH time that every reachable survivor width down to the floor
yields a legal config, so a shrink decision made mid-incident can never
discover the arithmetic is impossible.
"""

__all__ = ["static_f_ceiling", "static_effective_f", "shrunk_spec",
           "precheck"]

# Static mirror of `faults/quorum.py::_F_CEILING` (same contracts: krum
# needs n >= 2f+3, bulyan n >= 4f+3, the trimmed family n >= 2f+1;
# generic minority bound otherwise). A parity test pins the two tables
# to each other so they cannot drift apart.
_F_CEILING = {
    "krum": lambda n: (n - 3) // 2,
    "bulyan": lambda n: (n - 3) // 4,
    "brute": lambda n: (n - 1) // 2,
    "trmean": lambda n: (n - 1) // 2,
    "phocas": lambda n: (n - 1) // 2,
    "meamed": lambda n: (n - 1) // 2,
}


def _base_name(name):
    return name[len("native-"):] if name.startswith("native-") else name


def static_f_ceiling(gar_name, n):
    """Largest f `gar_name` tolerates at worker count `n` (python int)."""
    ceiling = _F_CEILING.get(_base_name(gar_name), lambda m: (m - 1) // 2)
    return max(int(ceiling(int(n))), 0)


def static_effective_f(gar_name, n, f_decl):
    """The declared f clamped to the GAR's breakdown ceiling at `n` —
    `faults/quorum.py::effective_f` without the tracing."""
    return max(min(int(f_decl), static_f_ceiling(gar_name, n)), 0)


def shrunk_spec(base, survivors):
    """Re-derive the run config for `survivors` hosts.

    Args:
      base: mapping with the LAUNCH-width run shape — `hosts`,
        `nb_workers`, `nb_decl_byz`, `nb_real_byz`, `nb_for_study`,
        `gar`.
      survivors: host count after the shrink (1 <= survivors <= hosts).

    Returns:
      `{"hosts", "nb_workers", "nb_decl_byz", "nb_real_byz",
      "nb_for_study"}` for the shrunken fleet: per-host shares held
      constant, real Byzantine count clamped below the shrunk width,
      declared f clamped to the GAR ceiling at the shrunk worker count.

    Raises:
      ValueError: the shrink arithmetic is impossible (ragged per-host
        shares, no honest worker left, ragged sampled split).
    """
    hosts0 = int(base["hosts"])
    survivors = int(survivors)
    if not 1 <= survivors <= hosts0:
        raise ValueError(f"survivor count {survivors} outside "
                         f"[1, {hosts0}]")
    nb_workers = int(base["nb_workers"])
    nb_for_study = int(base["nb_for_study"])
    if nb_workers % hosts0:
        raise ValueError(f"nb_workers={nb_workers} does not split evenly "
                         f"across {hosts0} hosts")
    if nb_for_study % hosts0:
        raise ValueError(f"nb_for_study={nb_for_study} does not split "
                         f"evenly across {hosts0} hosts")
    n = (nb_workers // hosts0) * survivors
    study = (nb_for_study // hosts0) * survivors
    real = min(int(base["nb_real_byz"]), max(n - 1, 0))
    honests = n - real
    if honests < 1:
        raise ValueError(f"shrink to {survivors} hosts leaves no honest "
                         f"worker (n={n}, real byz={real})")
    f_decl = static_effective_f(base.get("gar", "average"),
                               n, base["nb_decl_byz"])
    sampled = max(honests, study)
    if sampled % survivors:
        raise ValueError(
            f"shrink to {survivors} hosts gives nb_sampled={sampled} not "
            f"divisible by the {survivors}-wide workers mesh axis")
    return {"hosts": survivors, "nb_workers": n, "nb_decl_byz": f_decl,
            "nb_real_byz": real, "nb_for_study": study}


def precheck(base, min_hosts=1):
    """Validate every reachable survivor width `min_hosts..hosts` at
    launch time. Returns None when all are legal, else a message naming
    the first width that is not — the launcher refuses to start an
    elastic fleet whose shrink path could dead-end mid-incident."""
    hosts0 = int(base["hosts"])
    floor = max(int(min_hosts), 1)
    if floor > hosts0:
        return (f"min_hosts={floor} exceeds the launch width {hosts0}")
    for survivors in range(floor, hosts0 + 1):
        try:
            shrunk_spec(base, survivors)
        except ValueError as err:
            return (f"elastic shrink to {survivors} hosts would be "
                    f"illegal: {err}")
    return None
