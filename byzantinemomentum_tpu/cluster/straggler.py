"""Launcher-side straggler policy: bounded wait, then kill.

A SIGSTOP'd (or livelocked, or swap-thrashing) host never exits, so the
process table says "up" forever while gloo wedges the whole fleet on its
next collective — without a policy the run hangs until the Jobs watchdog
fires at whole-run scope. This state machine turns the PR 13
`liveness_view` stale/alive edges (and the PR 15 health block riding the
heartbeat) into a bounded per-host decision:

    HEALTHY --stale / sustained anomaly--> SUSPECT
    SUSPECT --fresh heartbeat / anomaly cleared--> HEALTHY  (recovered)
    SUSPECT --wait bound exceeded--> KILLED

The wait bound defaults to the `scripts/stale_edges.py` recommendation
(p95 of observed stale->alive recoveries x 1.25): waiting that long
clears ~95% of transient stalls, so anything older is overwhelmingly a
corpse-in-waiting and the launcher kills it — the elastic shrink path
(`cluster/elastic.py`) then rebuilds the fleet one host smaller instead
of wedging.

Two details matter for correctness:

* **Kill the laggard, not its hostages.** A stopped host wedges its
  PEERS too (they block in the next collective and also go stale), so at
  the bound nearly every host looks suspect. The policy kills at most
  one host per fleet attempt's observation stream, preferring a host the
  launcher observed NOT SCHEDULING (Linux process state `T`, SIGSTOP'd —
  decisive evidence, since a wedged-but-runnable hostage is never `T`);
  among the remaining candidates, the one that has been suspect LONGEST,
  tie-broken by oldest heartbeat — the host that stopped stepping first
  is the culprit; its hostages come back on relaunch.
* **Arm only past a warm step.** Compilation of step 1 (and of the
  resume step after a relaunch) stalls heartbeats for tens of seconds —
  legitimately. A host only becomes eligible for suspicion after the
  policy has seen it ALIVE at a step beyond the first one it reported,
  i.e. after the loop is demonstrably warm. Cold-start hangs stay the
  Jobs watchdog's jurisdiction.

The quarantine arm replays the arena's worker-quarantine hysteresis at
host scope: `anomaly_enter` consecutive anomalous polls to enter SUSPECT
(one bad window is not a verdict), `anomaly_clear` clean polls to leave.
"""

import json
import pathlib

__all__ = ["DEFAULT_ANOMALY_POLLS", "DEFAULT_WAIT_S", "StragglerPolicy",
           "resolve_anomaly_polls", "resolve_wait_bound"]

DEFAULT_WAIT_S = 30.0
DEFAULT_ANOMALY_POLLS = 3

HEALTHY = "healthy"
SUSPECT = "suspect"


class StragglerPolicy:
    """Folds `liveness_view` polls into HEALTHY/SUSPECT/KILLED decisions.

    The policy is a pure fold over `(view, now)` observations — it never
    touches processes itself; the launcher acts on the returned `kill`
    events (and then calls `reset()` when it relaunches the fleet, since
    a fresh attempt's hosts share nothing with the wedged one).
    """

    def __init__(self, wait_s, *, source="flag", quarantine=False,
                 anomaly_enter=DEFAULT_ANOMALY_POLLS, anomaly_clear=2,
                 anomaly_source="flag"):
        self.wait_s = float(wait_s)
        self.source = str(source)
        self.quarantine = bool(quarantine)
        self.anomaly_enter = max(int(anomaly_enter), 1)
        self.anomaly_clear = max(int(anomaly_clear), 1)
        self.anomaly_source = str(anomaly_source)
        # Lifetime counters (survive reset(): the artifact reports them)
        self.kills = []
        self.recoveries = []
        self.suspects_entered = 0
        self.reset()

    def reset(self):
        """Forget per-attempt transient state (a relaunched fleet starts
        every host HEALTHY and cold — arming is per-attempt too)."""
        self._suspect = {}      # host -> {"since", "reason", "age"}
        self._first_step = {}   # host -> first step its heartbeat showed
        self._armed = set()     # hosts seen alive PAST their first step
        self._anomaly_streak = {}
        self._clean_streak = {}
        self._killed = set()

    def _arm(self, host, row):
        step = row.get("step")
        if not isinstance(step, int):
            return
        if host not in self._first_step:
            self._first_step[host] = step
        elif (row["status"] == "alive"
              and step > self._first_step[host]):
            self._armed.add(host)

    def _enter(self, host, reason, row, now, events):
        self._suspect[host] = {"since": now, "reason": reason,
                               "age": row.get("age")}
        self.suspects_entered += 1
        events.append({"event": "suspect", "host": host, "reason": reason,
                       "step": row.get("step"), "age": row.get("age")})

    def _recover(self, host, row, now, events):
        entry = self._suspect.pop(host)
        record = {"event": "recovered", "host": host,
                  "reason": entry["reason"], "step": row.get("step"),
                  "suspect_s": round(now - entry["since"], 3)}
        self.recoveries.append({k: v for k, v in record.items()
                                if k != "event"})
        events.append(record)

    def observe(self, view, now, stopped=frozenset()):
        """Fold one liveness poll. Returns the transition events — each
        `{"event": "suspect"|"recovered"|"kill", "host": ..., ...}` —
        with at most one `kill` per call; the launcher must act on it
        (SIGKILL + teardown + shrink/relaunch). `stopped` holds hosts
        the launcher observed not scheduling (process state `T`); they
        are blamed FIRST when the bound expires."""
        events = []
        for host, row in view["hosts"].items():
            status = row["status"]
            if host in self._killed:
                continue
            if status in ("dead", "unknown"):
                # Process-table death is the launcher's jurisdiction;
                # no-signal-yet is pre-arming by definition
                self._suspect.pop(host, None)
                self._anomaly_streak.pop(host, None)
                continue
            self._arm(host, row)
            if host not in self._armed:
                continue
            anomaly = bool(self.quarantine
                           and isinstance(row.get("health"), dict)
                           and row["health"].get("anomaly"))
            if status == "stale":
                if host not in self._suspect:
                    self._enter(host, "stale", row, now, events)
                continue
            # status == "alive"
            if anomaly:
                streak = self._anomaly_streak.get(host, 0) + 1
                self._anomaly_streak[host] = streak
                self._clean_streak[host] = 0
                if (host not in self._suspect
                        and streak >= self.anomaly_enter):
                    self._enter(host, "health", row, now, events)
                continue
            self._anomaly_streak[host] = 0
            if host not in self._suspect:
                continue
            if self._suspect[host]["reason"] == "stale":
                # A fresh heartbeat ends a stall immediately
                self._recover(host, row, now, events)
            else:
                clean = self._clean_streak.get(host, 0) + 1
                self._clean_streak[host] = clean
                if clean >= self.anomaly_clear:
                    self._recover(host, row, now, events)

        expired = [(host, entry) for host, entry in self._suspect.items()
                   if now - entry["since"] > self.wait_s]
        if expired and not self._killed:
            # One kill per ATTEMPT, not per poll: the teardown takes a
            # poll or two to surface as a dead process, and in that
            # window the hostages are still stale and past the bound —
            # without this gate the policy would massacre them one per
            # poll before the relaunch could save them.
            # One kill per observation stream: a host observed NOT
            # SCHEDULING is the laggard outright (its hostages are
            # runnable, merely blocked); otherwise the longest-suspect
            # host (oldest heartbeat breaks ties) — the host that
            # stopped stepping first. The rest come back on relaunch.
            def _blame(item):
                host, entry = item
                age = view["hosts"].get(host, {}).get("age")
                return (now - entry["since"],
                        age if age is not None else -1.0)

            pool = ([item for item in expired if item[0] in stopped]
                    or expired)
            host, entry = max(pool, key=_blame)
            self._suspect.pop(host)
            self._killed.add(host)
            record = {"event": "kill", "host": host,
                      "reason": entry["reason"],
                      "suspect_s": round(now - entry["since"], 3),
                      "wait_s": self.wait_s,
                      "not_scheduling": host in stopped}
            self.kills.append({k: v for k, v in record.items()
                               if k != "event"})
            events.append(record)
        return events

    def summary(self):
        """The artifact's straggler block."""
        return {"wait_s": self.wait_s, "source": self.source,
                "quarantine": self.quarantine,
                "anomaly_enter": self.anomaly_enter,
                "anomaly_source": self.anomaly_source,
                "suspects_entered": self.suspects_entered,
                "kills": list(self.kills),
                "recoveries": list(self.recoveries)}


def resolve_wait_bound(explicit=None, edges_path=None):
    """The wait bound and where it came from: an explicit
    `--straggler-wait` wins; else the machine-readable recommendation
    block of a `scripts/stale_edges.py --json` summary; else the
    conservative default. Returns `(wait_s, source)`."""
    if explicit is not None:
        return float(explicit), "flag"
    if edges_path:
        payload = json.loads(
            pathlib.Path(edges_path).read_text(encoding="utf-8"))
        rec = payload.get("recommendation") or {}
        wait = rec.get("wait_s", payload.get("recommended_wait_s"))
        if wait is not None:
            basis = rec.get("basis", "recommended_wait_s")
            return float(wait), f"stale-edges:{basis}"
        raise ValueError(f"{edges_path} carries no recommendation "
                         f"(no recoveries or deaths observed)")
    return DEFAULT_WAIT_S, "default"


def resolve_anomaly_polls(explicit=None, rates_path=None):
    """The quarantine enter-threshold and where it came from: an explicit
    `--quarantine-anomaly-polls` wins; else the recommendation block of a
    `scripts/quarantine_rates.py --json` summary (anomaly-episode-length
    calibration over observed `health_anomaly`/`health_cleared` edge
    streams); else the conservative default. Returns `(polls, source)` —
    the same precedence ladder as `resolve_wait_bound`."""
    if explicit is not None:
        return int(explicit), "flag"
    if rates_path:
        payload = json.loads(
            pathlib.Path(rates_path).read_text(encoding="utf-8"))
        rec = payload.get("recommendation") or {}
        polls = rec.get("anomaly_polls",
                        payload.get("recommended_anomaly_polls"))
        if polls is not None:
            basis = rec.get("basis", "recommended_anomaly_polls")
            return int(polls), f"quarantine-rates:{basis}"
        raise ValueError(f"{rates_path} carries no recommendation "
                         f"(no anomaly episodes observed)")
    return DEFAULT_ANOMALY_POLLS, "default"
