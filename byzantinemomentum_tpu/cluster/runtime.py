"""Multi-controller runtime glue — `jax.distributed` with bounded, CI-safe
initialization.

One process per "host": each calls `initialize(HostSpec(...))`, which pins
the CPU backend (unless `BMT_CLUSTER_NATIVE=1` opts a real accelerator
fleet back in), selects the gloo CPU collectives implementation, and joins
the coordinator over local TCP. After it returns, `jax.devices()` spans
EVERY process's devices and a `(workers, model)` mesh over them runs the
engine step with real cross-host collectives (`cluster/host.py`).

Timeout discipline (the MULTICHIP_r05 lesson — an unreachable backend must
degrade, never hang CI at rc=124): the coordinator bind and every
follower's connect are bounded by `HostSpec.connect_timeout`, and any
initialization failure raises `ClusterUnavailable` — which `host.py` turns
into the reserved `UNAVAILABLE_RC` exit code and the launcher turns into a
clean `"status": "unavailable"` artifact (bench.py's cpu-fallback
discipline), instead of a wedged fleet.
"""

import dataclasses
import os
import socket

__all__ = ["ClusterUnavailable", "HostSpec", "UNAVAILABLE_RC",
           "cluster_mesh", "free_port", "initialize", "shutdown"]

# Exit code a host process reserves for "the distributed runtime could not
# come up" (coordinator unreachable, bind refused, init timeout) — the
# launcher maps it to a clean `unavailable` outcome, distinct from a
# training failure or a SIGKILL
UNAVAILABLE_RC = 17


class ClusterUnavailable(RuntimeError):
    """The distributed runtime could not initialize within its bounds."""


@dataclasses.dataclass(frozen=True)
class HostSpec:
    """One host process's coordinates in the fleet.

    `coordinator` is `host:port` (process 0 binds it, everyone connects);
    `connect_timeout` bounds BOTH sides of that handshake in seconds.
    """

    coordinator: str
    num_processes: int
    process_id: int
    connect_timeout: float = 60.0

    def __post_init__(self):
        if self.num_processes < 1:
            raise ValueError(
                f"Non-positive process count {self.num_processes}")
        if not (0 <= self.process_id < self.num_processes):
            raise ValueError(
                f"Process id {self.process_id} outside the "
                f"{self.num_processes}-process fleet")
        if self.connect_timeout <= 0:
            raise ValueError(
                f"Non-positive connect timeout {self.connect_timeout}")


def free_port(host="127.0.0.1"):
    """An OS-assigned free TCP port (the launcher picks the coordinator
    port with this; the tiny bind-release race is re-tried by the fleet
    retry loop, never hung on)."""
    with socket.socket() as sock:
        sock.bind((host, 0))
        return sock.getsockname()[1]


def _await_coordinator(spec):
    """Bounded TCP probe of the coordinator BEFORE jax touches it: the
    XLA distributed client LOG(FATAL)s the whole process on a connect
    deadline (RegisterTask DEADLINE_EXCEEDED aborts with SIGABRT — no
    Python exception ever surfaces), so an unreachable coordinator must
    be detected here, where it can become a clean `ClusterUnavailable`.
    Followers WAIT for the coordinator to appear (host 0 binds it a
    beat after they start), retrying until the spec's deadline."""
    import time

    host, _, port = spec.coordinator.rpartition(":")
    deadline = time.monotonic() + spec.connect_timeout
    while True:
        try:
            with socket.create_connection((host or "127.0.0.1", int(port)),
                                          timeout=2.0):
                return
        except OSError as err:
            if time.monotonic() >= deadline:
                raise ClusterUnavailable(
                    f"coordinator {spec.coordinator} unreachable within "
                    f"{spec.connect_timeout}s ({err})") from err
            time.sleep(0.2)


def initialize(spec):
    """Join the fleet: pin the CPU backend (CI-provable; a real device
    fleet opts back in with `BMT_CLUSTER_NATIVE=1`), select gloo CPU
    collectives, and run `jax.distributed.initialize` under the spec's
    bounded timeout. Raises `ClusterUnavailable` on any failure."""
    import jax

    if spec.process_id != 0:
        _await_coordinator(spec)

    if not os.environ.get("BMT_CLUSTER_NATIVE"):
        # Same pin as `__graft_entry__.dryrun_multichip`: an un-pinned
        # probe on a host with a broken accelerator tunnel hangs backend
        # setup indefinitely (the MULTICHIP_r05 rc=124 failure mode)
        jax.config.update("jax_platforms", "cpu")
        # One simulated host = ONE device: an inherited
        # xla_force_host_platform_device_count (the test suite's virtual
        # 8-device platform) would multiply every host into a virtual
        # slice and break the fleet's worker-axis arithmetic. Effective
        # because the backend has not initialized yet (this runs before
        # any device use in the host process).
        flags = os.environ.get("XLA_FLAGS", "")
        flags = " ".join(
            part for part in flags.split()
            if "xla_force_host_platform_device_count" not in part)
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=1").strip()
    try:
        # Cross-process CPU collectives need the gloo implementation; the
        # knob predates its promotion to a stable name, hence the guard
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except AttributeError:
        pass  # newer jax: gloo is the multi-process CPU default
    try:
        jax.distributed.initialize(
            coordinator_address=spec.coordinator,
            num_processes=spec.num_processes,
            process_id=spec.process_id,
            # jaxlib's distributed client takes whole seconds only
            initialization_timeout=max(1, int(spec.connect_timeout)))
    except Exception as err:  # bmt: noqa[BMT-E05] the distributed client raises backend-specific types (RuntimeError, XlaRuntimeError, OSError); every one of them means the same bounded 'unavailable'
        raise ClusterUnavailable(
            f"distributed runtime unavailable (coordinator "
            f"{spec.coordinator}, process {spec.process_id}/"
            f"{spec.num_processes}, timeout {spec.connect_timeout}s): "
            f"{err}") from err
    if jax.process_count() != spec.num_processes:
        raise ClusterUnavailable(
            f"joined a {jax.process_count()}-process fleet but the spec "
            f"declares {spec.num_processes}")


def cluster_mesh(model_parallel=1, expected_workers=None):
    """The global `(workers, model)` mesh over EVERY process's devices.

    The default `model_parallel=1` keeps every state buffer fully
    replicated, so any process can read (and host 0 can checkpoint) the
    training state without cross-process gathers; `model_parallel > 1`
    d-shards the state ACROSS hosts — the lattice census covers that
    layout's collectives (`analysis/lattice.py::multiprocess_cells`), but
    checkpointing it needs a gather pass this runtime does not do yet.

    `expected_workers` pins the workers-axis extent to the fleet width
    the launcher spawned: the mesh spans whatever devices actually
    joined, so under an elastic shrink/relaunch a straggling old host
    that somehow rejoined would silently widen the axis — better a loud
    refusal than a program compiled for the wrong `(n, f)` contract.
    """
    import jax

    from byzantinemomentum_tpu.parallel import make_mesh

    if model_parallel != 1:
        raise ValueError(
            "cluster_mesh only supports model_parallel=1 for now: the "
            "host runtime reads and checkpoints the state from single "
            "processes, which requires it fully replicated")
    mesh = make_mesh(len(jax.devices()), model_parallel=model_parallel)
    if expected_workers is not None \
            and mesh.shape["workers"] != int(expected_workers):
        raise ClusterUnavailable(
            f"mesh workers axis spans {mesh.shape['workers']} devices "
            f"but the launcher expects a {expected_workers}-host fleet")
    return mesh


def shutdown():
    """Leave the fleet (best-effort: a process on its way out must never
    fail in teardown)."""
    import jax

    try:
        jax.distributed.shutdown()
    except Exception:  # bmt: noqa[BMT-E05] teardown races the coordinator's own exit; any error here is moot by definition
        pass
