"""System-level chaos: the `FaultPlan` pointed at the FLEET.

PR 1's fault plans inject masked rows inside the jitted step — "device
loss" there is arithmetic. Here the SAME declarative artifact drives real
process destruction: at system scope an event's `worker` indexes a HOST of
the multi-controller fleet, `device_loss` means the launcher SIGKILLs
that host's process the first time the cluster's observed step reaches
`event.step`, and `straggle` means SIGSTOP now / SIGCONT `window_s`
seconds later (`StraggleResumer`) — a host that is alive in the process
table but not stepping, the exact input the launcher's straggler policy
(`cluster/straggler.py`) exists to classify. Only
`faults.plan.SYSTEM_KINDS` are legal at this scope
(`FaultPlan.validate_system`).

Fire-once discipline: recovery REPLAYS training steps (the fleet resumes
below the kill step and passes it again), so a naively re-armed plan would
kill the fleet forever. The launcher persists each fired event's index in
the cluster manifest BEFORE sending the signal; a relaunched fleet (same
launcher retry loop, or a whole new launcher process under the Jobs
supervisor) rebuilds the driver with `fired=manifest["fired_faults"]` and
never re-injects. The plan stays deterministic data — `(plan, manifest)`
fully determine what has been and will be injected.
"""

import signal
import threading
import time

__all__ = ["StraggleResumer", "SystemFaultDriver"]


class SystemFaultDriver:
    """Interprets a `FaultPlan` at host scope for the cluster launcher.

    The launcher polls `due(step)` with the fleet's observed max step and
    SIGKILLs the returned hosts, calling `mark(index)` (and persisting the
    manifest) BEFORE each signal.
    """

    def __init__(self, plan, nb_hosts, *, fired=()):
        message = plan.validate_system(nb_hosts)
        if message is not None:
            raise ValueError(f"fault plan cannot run at system scope: "
                             f"{message}")
        self.plan = plan
        self.nb_hosts = int(nb_hosts)
        self._fired = set(int(i) for i in fired)

    def due(self, step):
        """`[(index, event)]` not yet fired whose step has been reached
        (None step — no host heartbeat yet — never fires anything)."""
        if step is None:
            return []
        return [(i, e) for i, e in enumerate(self.plan.events)
                if i not in self._fired and step >= e.step]

    def mark(self, index):
        """Record event `index` as injected (idempotent)."""
        self._fired.add(int(index))

    def fired(self):
        """Sorted fired-event indices — what the manifest persists."""
        return sorted(self._fired)

    def exhausted(self):
        """Whether every scheduled event has been injected (the launcher
        only declares a chaos run clean once the plan is spent)."""
        return len(self._fired) >= len(self.plan.events)


class StraggleResumer:
    """The SIGCONT side of a straggle window, on its own timer thread.

    The launcher's poll loop must keep observing the fleet while a host
    is stopped (that stall is the whole experiment), so the delayed
    SIGCONT cannot block it — a single daemon thread sleeps until the
    earliest pending window closes and resumes the host.

    Concurrency contract (modeled in `analysis/schedule.py::
    straggle_claim_model` / `straggle_claim_unguarded_model`): every
    scheduled entry is disposed EXACTLY once — `resumed` by this thread
    or `cancelled` by the launcher (straggler-policy kill, fleet
    teardown) — and the disposition is claimed under the lock BEFORE
    anyone signals, so a killed host can never receive a late SIGCONT
    and a resumed host is never double-signaled. All state transitions
    happen under `_cond`'s lock; the actual `send_signal` runs outside
    it (signaling a dying process can stall in the kernel).
    """

    def __init__(self, *, clock=time.monotonic):
        self._clock = clock
        self._cond = threading.Condition()  # bmt: noqa[BMT-L06] deterministic single-waiter timer (injected clock, exercised directly by tests/test_cluster_chaos.py) — no model needed
        self._pending = []    # [{"host", "proc", "at", "state"}]
        self._resumed = []
        self._cancelled = 0
        self._stopping = False
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="straggle-resumer")
        self._thread.start()

    def schedule(self, host, proc, window_s):
        """Arrange SIGCONT for `proc` (host `host`) in `window_s` s."""
        entry = {"host": int(host), "proc": proc,
                 "at": self._clock() + float(window_s), "state": "pending"}
        with self._cond:
            self._pending.append(entry)
            self._cond.notify()

    def cancel(self, host=None):
        """Cancel pending windows for `host` (None: all). Returns how
        many were still pending — 0 means the resumer already claimed
        them (the SIGCONT raced ahead; harmless before a SIGKILL)."""
        cancelled = 0
        with self._cond:
            for entry in self._pending:
                if (entry["state"] == "pending"
                        and (host is None or entry["host"] == int(host))):
                    entry["state"] = "cancelled"
                    cancelled += 1
            self._cancelled += cancelled
            self._cond.notify()
        return cancelled

    def resumed(self):
        """`[(host, resumed_at)]` windows this thread closed so far."""
        with self._cond:
            return list(self._resumed)

    def stats(self):
        with self._cond:
            pending = sum(1 for e in self._pending
                          if e["state"] == "pending")
            return {"pending": pending, "resumed": len(self._resumed),
                    "cancelled": self._cancelled}

    def stop(self):
        """Cancel everything and join the thread (launcher teardown)."""
        self.cancel()
        with self._cond:
            self._stopping = True
            self._cond.notify()
        self._thread.join(timeout=5.0)

    def _loop(self):
        while True:
            with self._cond:
                if self._stopping:
                    return
                now = self._clock()  # bmt: noqa[BMT-L03] the clock is a constructor-injected test seam (time.monotonic in production) — pure reads, never calls back in
                due = [e for e in self._pending
                       if e["state"] == "pending" and e["at"] <= now]
                for entry in due:
                    entry["state"] = "resumed"  # claimed under the lock
                self._pending = [e for e in self._pending
                                 if e["state"] == "pending"]
                if not due:
                    waits = [e["at"] - now for e in self._pending]
                    self._cond.wait(min(waits) if waits else None)
                    continue
            for entry in due:
                try:
                    entry["proc"].send_signal(signal.SIGCONT)
                except (OSError, ValueError):
                    pass  # the process died while stopped; moot
                with self._cond:
                    self._resumed.append((entry["host"], entry["at"]))
