"""System-level chaos: the `FaultPlan` pointed at the FLEET.

PR 1's fault plans inject masked rows inside the jitted step — "device
loss" there is arithmetic. Here the SAME declarative artifact drives real
process destruction: at system scope an event's `worker` indexes a HOST of
the multi-controller fleet, and `device_loss` means the launcher SIGKILLs
that host's process the first time the cluster's observed step reaches
`event.step`. Only `faults.plan.SYSTEM_KINDS` are legal at this scope
(`FaultPlan.validate_system`).

Fire-once discipline: recovery REPLAYS training steps (the fleet resumes
below the kill step and passes it again), so a naively re-armed plan would
kill the fleet forever. The launcher persists each fired event's index in
the cluster manifest BEFORE sending the signal; a relaunched fleet (same
launcher retry loop, or a whole new launcher process under the Jobs
supervisor) rebuilds the driver with `fired=manifest["fired_faults"]` and
never re-injects. The plan stays deterministic data — `(plan, manifest)`
fully determine what has been and will be injected.
"""

__all__ = ["SystemFaultDriver"]


class SystemFaultDriver:
    """Interprets a `FaultPlan` at host scope for the cluster launcher.

    The launcher polls `due(step)` with the fleet's observed max step and
    SIGKILLs the returned hosts, calling `mark(index)` (and persisting the
    manifest) BEFORE each signal.
    """

    def __init__(self, plan, nb_hosts, *, fired=()):
        message = plan.validate_system(nb_hosts)
        if message is not None:
            raise ValueError(f"fault plan cannot run at system scope: "
                             f"{message}")
        self.plan = plan
        self.nb_hosts = int(nb_hosts)
        self._fired = set(int(i) for i in fired)

    def due(self, step):
        """`[(index, event)]` not yet fired whose step has been reached
        (None step — no host heartbeat yet — never fires anything)."""
        if step is None:
            return []
        return [(i, e) for i, e in enumerate(self.plan.events)
                if i not in self._fired and step >= e.step]

    def mark(self, index):
        """Record event `index` as injected (idempotent)."""
        self._fired.add(int(index))

    def fired(self):
        """Sorted fired-event indices — what the manifest persists."""
        return sorted(self._fired)

    def exhausted(self):
        """Whether every scheduled event has been injected (the launcher
        only declares a chaos run clean once the plan is spent)."""
        return len(self._fired) >= len(self.plan.events)
