"""One host of the multi-controller fleet.

`python -m byzantinemomentum_tpu.cluster.host --procs N --proc-id I ...`
joins the `jax.distributed` fleet (`cluster/runtime.py`), builds the SAME
engine every other host builds, and drives the mesh-sharded training step
(`parallel/sharded.py::sharded_train_step` over the global
`(workers=N_devices, model=1)` mesh) — so every step's honest phase is
data-parallel across hosts and the aggregation's gathers/psums are real
cross-host collectives, not a masked row in a simulator.

Multi-controller discipline (the determinism contract everything else
stands on):

* every host seeds numpy/jax identically and constructs the same host
  dataset samplers, so all hosts sample byte-identical `(S, B, ...)`
  batches each step and `parallel.global_batch` materializes only this
  process's workers-axis shard of them;
* the training state is fully replicated (`cluster_mesh` pins
  model_parallel=1), so ANY host can read metrics/state —
  host 0 writes the study CSV and the checkpoints, every host writes its
  own atomic `hosts/host-<i>.heartbeat.json` liveness signal;
* checkpoints land in the host's LOCAL directory (`host-<i>/`, the
  stand-in for slice-local disk) and host 0 additionally mirrors them
  off-slice (`checkpoint.save(mirror=...)`); resume NEVER reads local
  copies — the launcher agrees the restart step via the cluster manifest
  (`cluster/manifest.py::agree_restart_step`, mirror-only) and every
  host loads the mirror's copy, validates it, and reports the adopted
  step in its first heartbeat for the launcher's unanimity check.

The study CSV follows the driver's exact semantics (`cli/attack.py`'s
`_ResultFiles`, reused): on resume the rows at or past the restart step
are truncated and regenerated, so a killed-and-resumed fleet's CSV is
bit-identical to an uninterrupted fleet's (`tests/test_cluster.py`,
`scripts/cluster_smoke.py`).

Contract hooks ridden by the cluster tier: `--recompile-check` asserts a
ZERO-compile warm loop on the multi-process step
(`analysis/contracts.py::count_compiles`), `--lattice-census` lowers the
multi-process lattice cells (`analysis/lattice.py::multiprocess_cells`)
and writes each host's fingerprints + BMT-H census to
`hosts/host-<i>.census.json` — the launcher requires the fingerprints to
agree across hosts (consensus on the PROGRAM, not just the state).

Fleet observability (`obs/trace/fleet.py`): besides the heartbeat, every
host records its own `hosts/host-<i>.telemetry.jsonl` — lifecycle events
(`host_start`/`host_resume`/`host_end`), a per-step `host_step` progress
gauge, and (through the active-recorder API) the checkpoint save/load
spans — the per-host stream the launcher's timeline join orders against
its own supervision events via the heartbeat clock-offset estimates.
"""

import argparse
import json
import os
import pathlib
import sys
import threading
import time

__all__ = ["main", "process_commandline", "UNAVAILABLE_RC"]

from byzantinemomentum_tpu.cluster.runtime import UNAVAILABLE_RC

# Exit code for "the manifest's restart step and the mirror disagree" —
# a consensus violation, distinct from unavailability and training faults
DISAGREE_RC = 21


def process_commandline(argv=None):
    parser = argparse.ArgumentParser(prog="cluster-host")
    add = parser.add_argument
    add("--procs", type=int, required=True, help="Fleet size")
    add("--proc-id", type=int, required=True, help="This host's index")
    add("--coordinator", type=str, required=True,
        help="host:port of the jax.distributed coordinator (host 0 binds)")
    add("--connect-timeout", type=float, default=60.0,
        help="Bounded seconds for the coordinator bind/connect handshake")
    add("--result-directory", type=str, required=True)
    add("--mirror", type=str, required=True,
        help="Off-slice checkpoint mirror directory (the consensus copy)")
    add("--auto-resume", action="store_true", default=False,
        help="Adopt the cluster manifest's restart_step (cold start when "
             "the manifest names none)")
    add("--parent-pipe", action="store_true", default=False,
        help="Exit when stdin reaches EOF (the launcher holds the write "
             "end: a dead launcher must never leak a training fleet)")
    add("--nb-steps", type=int, default=8,
        help="TOTAL steps from step 0 (resumed fleets stop where an "
             "uninterrupted one would)")
    add("--seed", type=int, default=1)
    add("--nb-workers", type=int, default=8)
    add("--nb-decl-byz", type=int, default=2)
    add("--nb-real-byz", type=int, default=2)
    add("--gar", type=str, default="median")
    add("--attack", type=str, default="empire")
    add("--attack-args", nargs="*")
    add("--model", type=str, default="simples-full")
    add("--dataset", type=str, default="mnist")
    add("--batch-size", type=int, default=8)
    add("--nb-for-study", type=int, default=8)
    add("--nb-for-study-past", type=int, default=2)
    add("--learning-rate", type=float, default=0.05)
    add("--momentum", type=float, default=0.9)
    add("--checkpoint-delta", type=int, default=2)
    add("--recompile-check", type=int, default=0,
        help="Assert ZERO backend compiles across this many warm steps "
             "of the multi-process program (0 disables)")
    add("--health", action="store_true", default=False,
        help="Numerics flight recorder: in-jit health stats in the "
             "sharded step + a per-host SPC monitor whose summary rides "
             "this host's heartbeat 'health' block")
    add("--lattice-census", action="store_true", default=False,
        help="Lower the multi-process lattice cells and write this "
             "host's fingerprint + BMT-H census artifact")
    return parser.parse_args(sys.argv[1:] if argv is None else argv)


def _watch_parent():
    """Die when the launcher does: the launcher holds this process's
    stdin pipe exclusively, so launcher death (any signal, any crash)
    closes it and the read returns EOF. SIGKILL leaves no other channel —
    an orphaned fleet would hold the coordinator port and the result
    directory forever."""
    def watch():
        # Raw os.read, NOT sys.stdin.buffer: a daemon thread blocked in
        # the buffered reader holds its lock across interpreter shutdown
        # and aborts an otherwise-clean exit ("_enter_buffered_busy")
        try:
            while os.read(0, 4096):
                pass
        except OSError:
            pass
        os._exit(3)

    threading.Thread(target=watch, name="parent-watch", daemon=True).start()  # bmt: noqa[BMT-L06] lock-free parent-death watch: blocks on pipe EOF then os._exit — it shares no state to interleave


def _run_census(resdir, proc_id):
    """Lower the multi-process cells, lint them, and write this host's
    census artifact. Every host lowers the SAME cells — the launcher's
    cross-host fingerprint comparison is the consensus check that all
    controllers are about to run the same programs."""
    import jax

    from byzantinemomentum_tpu.analysis import hlolint, lattice, lowering

    cells = {}
    violations = 0
    for cell in lattice.multiprocess_cells():
        key, text, expect = lattice.lower_cell(cell)
        found = hlolint.lint_module(text, expect, label=key)
        cells[key] = {
            "fingerprint": lowering.fingerprint(text),
            "violations": [v.as_dict() for v in found],
        }
        violations += len(found)
    artifact = {"host": proc_id, "processes": jax.process_count(),
                "cells": cells, "violations": violations}
    path = (pathlib.Path(resdir) / "hosts"
            / f"host-{proc_id}.census.json")
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(artifact, indent="\t", sort_keys=True)
                    + "\n")
    return artifact


def main(argv=None):
    args = process_commandline(argv)
    if args.parent_pipe:
        _watch_parent()

    from byzantinemomentum_tpu.cluster import manifest as manifest_mod
    from byzantinemomentum_tpu.cluster import runtime

    spec = runtime.HostSpec(
        coordinator=args.coordinator, num_processes=args.procs,
        process_id=args.proc_id, connect_timeout=args.connect_timeout)
    try:
        runtime.initialize(spec)
    except runtime.ClusterUnavailable as err:
        print(f"cluster-host: unavailable: {err}", flush=True)
        return UNAVAILABLE_RC

    import jax
    import jax.numpy as jnp
    import numpy as np

    from byzantinemomentum_tpu import attacks as attacks_mod
    from byzantinemomentum_tpu import checkpoint as checkpoint_mod
    from byzantinemomentum_tpu import data as data_mod
    from byzantinemomentum_tpu import losses as losses_mod
    from byzantinemomentum_tpu import models as models_mod
    from byzantinemomentum_tpu import ops as ops_mod
    from byzantinemomentum_tpu.cli.attack import _ResultFiles
    from byzantinemomentum_tpu.engine import (
        STUDY_COLUMNS, EngineConfig, build_engine)
    from byzantinemomentum_tpu.obs.heartbeat import write_host_heartbeat
    from byzantinemomentum_tpu.parallel import (
        global_batch, global_train_state, sharded_train_step)

    proc = args.proc_id
    lead = proc == 0
    resdir = pathlib.Path(args.result_directory).resolve()
    mirror = pathlib.Path(args.mirror).resolve()
    local_dir = resdir / f"host-{proc}"
    local_dir.mkdir(parents=True, exist_ok=True)
    if lead:
        mirror.mkdir(parents=True, exist_ok=True)

    # This host's own telemetry stream (obs/trace/fleet.py joins it with
    # the launcher's into the fleet timeline). ACTIVATED, so deep layers
    # — checkpoint save/load spans — land on this host's timeline too.
    from byzantinemomentum_tpu import obs

    (resdir / "hosts").mkdir(parents=True, exist_ok=True)
    telem = obs.activate(obs.Telemetry(
        resdir / "hosts", filename=f"host-{proc}.telemetry.jsonl"))
    telem.event("host_start", host=proc, procs=args.procs,
                seed=args.seed, auto_resume=bool(args.auto_resume))

    # Pin the workers axis to the width the launcher spawned: under an
    # elastic shrink the survivor fleet must compile the shrunken (n, f)
    # contract, never a mesh silently widened by a stray rejoiner
    try:
        mesh = runtime.cluster_mesh(expected_workers=args.procs)
    except runtime.ClusterUnavailable as err:
        print(f"cluster-host: unavailable: {err}", flush=True)
        return UNAVAILABLE_RC
    workers_ax = mesh.shape["workers"]

    # --- the same deterministic setup on every host --- #
    seed = max(args.seed, 0)
    np.random.seed(seed % 2**32)
    trainset, testset = data_mod.make_datasets(
        args.dataset, args.batch_size, args.batch_size,
        seed=seed % 2**32)
    from byzantinemomentum_tpu import utils as utils_mod
    attack = attacks_mod.attacks[args.attack]
    cfg = EngineConfig(
        nb_workers=args.nb_workers, nb_decl_byz=args.nb_decl_byz,
        nb_real_byz=args.nb_real_byz, nb_for_study=args.nb_for_study,
        nb_for_study_past=max(args.nb_for_study_past, 1),
        momentum=args.momentum, momentum_at="update",
        health=args.health)
    # Per-host flight recorder (obs/health): folds the in-jit health
    # vector this host reads off the replicated metrics; its summary
    # rides the host heartbeat's `health` block, which the liveness view
    # and the launcher's aggregated fleet heartbeat carry through
    monitor = (obs.HealthMonitor(metrics=obs.metrics.MetricsRegistry(
        source=f"host-{args.proc_id}")) if args.health else None)
    engine = build_engine(
        cfg=cfg, model_def=models_mod.build(args.model),
        loss=losses_mod.Loss("nll"), criterion=losses_mod.Criterion("top-k"),
        defenses=[(ops_mod.gars[args.gar], 1.0, {})], attack=attack,
        attack_kwargs=utils_mod.parse_keyval(args.attack_args))
    S = cfg.nb_sampled
    if S % workers_ax != 0:
        print(f"cluster-host: {S} sampled gradients do not divide the "
              f"{workers_ax}-way worker axis", flush=True)
        return 2

    state = engine.init(jax.random.PRNGKey(seed))

    # --- consensus resume: the manifest names the step, the mirror holds
    # the bytes, every host validates both --- #
    resume_step = None
    if args.auto_resume:
        cluster_manifest = manifest_mod.read_cluster_manifest(resdir)
        resume_step = cluster_manifest.get("restart_step")
        if resume_step is not None:
            found = mirror / f"checkpoint-{int(resume_step)}"
            if not checkpoint_mod.verify(found):
                print(f"cluster-host: manifest restart_step={resume_step} "
                      f"but {found.name} is missing/invalid in the mirror",
                      flush=True)
                return DISAGREE_RC
            state, data_state = checkpoint_mod.load(
                found, state, return_data=True)
            if data_state is not None:
                trainset.set_state(data_state["train"])
                testset.set_state(data_state["test"])
            resume_step = int(resume_step)
            telem.event("host_resume", host=proc, step=resume_step)

    write_host_heartbeat(resdir, proc, {
        "step": int(state.steps), "status": "starting",
        "resume_step": resume_step})

    if args.lattice_census:
        _run_census(resdir, proc)

    step_fn = sharded_train_step(engine, mesh, state,
                                 replicate_metrics=True)
    gstate = global_train_state(mesh, state)

    results = None
    fd_study = None
    if lead:
        results = _ResultFiles(resdir)
        results.make("study", *STUDY_COLUMNS, resume_step=resume_step)
        fd_study = results.get("study")
    float_format = "%.8e"

    steps_host = int(state.steps)
    datapoints_host = int(state.datapoints)
    inc = args.batch_size * cfg.nb_honests * cfg.nb_local_steps
    just_loaded = resume_step is not None
    nb_steps = args.nb_steps
    first_step = steps_host
    # (--recompile-check) one count_compiles window over the warm steps:
    # opened after the first chunk (which legitimately compiles), closed
    # after the requested number of further steps, asserted ZERO
    compile_window = None
    compile_window_log = None
    compiles_checked = 0
    compile_check_done = args.recompile_check <= 0
    rate_t0 = None
    rate_from = None

    def sample_batch():
        xs, ys = zip(*(trainset.sample() for _ in range(S)))
        return np.stack(xs), np.stack(ys)

    try:
        while steps_host < nb_steps:
            if (args.checkpoint_delta > 0
                    and steps_host % args.checkpoint_delta == 0
                    and not just_loaded):
                snapshot = {"train": trainset.get_state(),
                            "test": testset.get_state()}
                host_state = jax.device_get(gstate)
                # Every host keeps a local copy (its "slice-local disk");
                # ONLY host 0 commits the off-slice mirror the manifest
                # agreement reads — single writer, like the manifest
                checkpoint_mod.save(
                    local_dir / f"checkpoint-{steps_host}", host_state,
                    data_state=snapshot,
                    mirror=mirror if lead else None)
            just_loaded = False
            xs, ys = sample_batch()
            gx = global_batch(mesh, xs)
            gy = global_batch(mesh, ys)
            if (not compile_check_done and compile_window is None
                    and steps_host > first_step):
                # The program is warm (the first chunk carried its
                # compile): every further step must be a pure dispatch
                from byzantinemomentum_tpu.analysis import contracts
                compile_window = contracts.count_compiles()
                compile_window_log = compile_window.__enter__()
            gstate, metrics = step_fn(gstate, gx, gy,
                                      jnp.float32(args.learning_rate))
            steps = steps_host
            steps_host += 1
            datapoints = datapoints_host
            datapoints_host += inc
            if compile_window is not None:
                compiles_checked += 1
                if compiles_checked >= args.recompile_check:
                    compile_window.__exit__(None, None, None)
                    compile_check_done = True
                    count = compile_window_log.count
                    compile_window = None
                    if count != 0:
                        print(f"cluster-host: RECOMPILE in the warm "
                              f"multi-process loop ({count} over "
                              f"{compiles_checked} steps)", flush=True)
                        return 4
            if rate_t0 is None:
                rate_t0, rate_from = time.monotonic(), steps_host
            host_metrics = jax.device_get(metrics)
            if lead and fd_study is not None:
                row = [steps, datapoints]
                for column in STUDY_COLUMNS[2:-1]:
                    row.append(float_format % float(host_metrics[column]))
                row.append(float(host_metrics[
                    "Attack acceptation ratio"]))
                results.store(fd_study, *row)
            beat = {"step": steps_host, "status": "running",
                    "resume_step": resume_step}
            if monitor is not None:
                monitor.update(steps, {
                    "var_ratio": float(host_metrics["Var ratio"]),
                    "update_ratio": float(host_metrics["Update/weight"]),
                    "weight_norm": float(host_metrics["Weight norm"]),
                    "update_norm": float(host_metrics["Update norm"]),
                    "nonfinite": (
                        float(host_metrics["Nonfinite submitted"])
                        + float(host_metrics["Nonfinite aggregate"])
                        + float(host_metrics["Nonfinite state"])),
                    "norm_hist": [float(c) for c in
                                  np.asarray(host_metrics["Norm hist"])],
                })
                beat["health"] = monitor.summary()
            write_host_heartbeat(resdir, proc, beat)
            telem.gauge("host_step", steps_host)
    finally:
        if results is not None:
            results.close()

    elapsed = (time.monotonic() - rate_t0
               if rate_t0 is not None else None)
    warm_steps = steps_host - (rate_from or steps_host)
    rate = (warm_steps / elapsed if elapsed and warm_steps > 0 else None)
    summary = {
        "host": proc, "steps": steps_host,
        "steps_per_sec": (round(rate, 3) if rate else None),
        "resume_step": resume_step,
        "recompile_checked": (compiles_checked
                              if args.recompile_check else None),
    }
    final_beat = {
        "step": steps_host, "status": "completed",
        "resume_step": resume_step,
        "steps_per_sec": summary["steps_per_sec"]}
    if monitor is not None and monitor.steps > 0:
        final_beat["health"] = monitor.summary()
        monitor.dump_blackbox(local_dir, reason="run_end")
    write_host_heartbeat(resdir, proc, final_beat)
    telem.event("host_end", host=proc, steps=steps_host,
                steps_per_sec=summary["steps_per_sec"],
                resume_step=resume_step)
    obs.deactivate()
    telem.close()
    print("cluster-host: " + json.dumps(summary), flush=True)
    runtime.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
