"""The per-run cluster manifest — the fleet's consensus artifact — and the
liveness view aggregated from per-host heartbeats.

Ray's ownership argument (PAPERS.md) splits cluster state into a
centralized liveness record and per-object owners; the translation here:
the LAUNCHER owns `cluster.json` (single writer, atomic replace), and the
hosts own their training state. Everything the fleet must AGREE on flows
through the manifest:

  restart_step    the step the NEXT fleet attempt resumes from — computed
                  by the launcher as the newest valid checkpoint in the
                  off-slice MIRROR (`checkpoint.find_latest_valid`), never
                  from any host's local disk, so a dead host's lost local
                  state is irrelevant by construction. Every host reads
                  the same number from the same file, validates the
                  checkpoint it names (CRC + version), and reports the
                  step it actually adopted in its first heartbeat; the
                  launcher cross-checks the reports and only declares
                  `restart_agreed` when they are unanimous.
  fired_faults    indices of system-level FaultPlan events already
                  injected (`cluster/chaos.py`) — persisted BEFORE the
                  SIGKILL is sent, so a relaunched fleet replays the
                  training steps but never the kill (the same
                  determinism-with-recovery contract the in-step fault
                  schedule has).
  attempts /      the fleet-launch history: which attempt is running,
  recoveries      which host died when, and how many steps each recovery
                  re-executed (the `recovery_steps` the CLUSTER artifact
                  and bench_history report).

Liveness (`liveness_view`): each host writes an atomic
`hosts/host-<i>.heartbeat.json` every step (`obs/heartbeat.py`); the view
joins them with the child process table — a host is `alive` while its
process runs, `stale` when its heartbeat stops advancing (wedged
collective), `dead` once its process is gone. Heartbeats are a *signal*;
process exit is *ground truth* — the same two-tier design as the Jobs
watchdog.
"""

import json
import os
import pathlib
import time

from byzantinemomentum_tpu.obs.heartbeat import read_host_heartbeats

__all__ = ["CLUSTER_MANIFEST_NAME", "agree_restart_step", "liveness_view",
           "read_cluster_manifest", "update_cluster_manifest",
           "write_cluster_manifest"]

CLUSTER_MANIFEST_NAME = "cluster.json"
VERSION = 1


def _defaults():
    return {"version": VERSION, "hosts": None, "attempt": 0,
            "restart_step": None, "fired_faults": [], "recoveries": [],
            "status": "new"}


def read_cluster_manifest(directory):
    """The run's cluster manifest (defaults when absent/torn — like the
    checkpoint manifest, a fresh file must mean 'nothing agreed yet',
    never a crash)."""
    path = pathlib.Path(directory) / CLUSTER_MANIFEST_NAME
    try:
        manifest = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return _defaults()
    if not isinstance(manifest, dict):
        return _defaults()
    out = _defaults()
    out.update(manifest)
    return out


def write_cluster_manifest(directory, manifest):
    """Atomic single-writer replace (the launcher is the only writer;
    hosts only read)."""
    path = pathlib.Path(directory) / CLUSTER_MANIFEST_NAME
    tmp = path.with_name(path.name + ".tmp")
    with tmp.open("w", encoding="utf-8") as fd:
        fd.write(json.dumps(manifest, ensure_ascii=False, indent="\t"))
        fd.flush()
        os.fsync(fd.fileno())
    os.replace(tmp, path)
    return path


def update_cluster_manifest(directory, **fields):
    """Read-modify-write convenience for the single writer."""
    manifest = read_cluster_manifest(directory)
    manifest.update(fields)
    write_cluster_manifest(directory, manifest)
    return manifest


def agree_restart_step(mirror_dir):
    """The restart step the next fleet attempt must converge on: the
    newest VALID checkpoint in the off-slice mirror (None -> cold start).
    Returns `(step, path)`. Only the mirror counts — a host's local
    checkpoints may have died with the host."""
    from byzantinemomentum_tpu import checkpoint

    found = checkpoint.find_latest_valid(mirror_dir)
    if found is None:
        return None, None
    return checkpoint.checkpoint_step(found), found


def liveness_view(run_dir, nb_hosts, *, stale_after=None, running=None,
                  now=None):
    """The aggregated cluster liveness view.

    Args:
      run_dir: the fleet's result directory (per-host heartbeats live
        under its `hosts/`).
      nb_hosts: fleet size — hosts with no heartbeat yet still get a row.
      stale_after: seconds without a heartbeat update before a live host
        counts `stale` (None disables staleness).
      running: optional {host_id: bool} process-table truth from the
        launcher; hosts reported not-running are `dead` regardless of
        how fresh their last heartbeat looks.
      now: injected clock for tests.

    Returns `{"hosts": {id: {...}}, "alive": [...], "min_step": int|None,
    "max_step": int|None}` where per-host status is one of
    `alive`/`stale`/`dead`/`unknown` (no signal yet).
    """
    now = time.time() if now is None else now
    beats = read_host_heartbeats(run_dir)
    hosts = {}
    alive = []
    steps = []
    for host in range(int(nb_hosts)):
        beat = beats.get(host)
        process_up = None if running is None else bool(running.get(host))
        row = {"step": None, "age": None, "status": "unknown"}
        if beat is not None:
            row["step"] = beat.get("step")
            row["age"] = max(0.0, now - float(beat.get("updated", now)))
            # The raw host-clock write stamp: the launcher's clock-offset
            # estimator (obs/trace/fleet.py) reads it against its own
            # clock on every poll — the heartbeat handshake IS the
            # offset-measurement channel
            row["updated"] = beat.get("updated")
            if beat.get("resume_step") is not None:
                row["resume_step"] = beat.get("resume_step")
            if beat.get("status"):
                row["host_status"] = beat.get("status")
            if isinstance(beat.get("health"), dict):
                # Training-dynamics state (the flight recorder's
                # heartbeat block, obs/health): the liveness view carries
                # it through so the fleet exposes anomaly state next to
                # liveness, not just "the process is up"
                row["health"] = beat["health"]
        if process_up is False:
            row["status"] = "dead"
        elif beat is None:
            row["status"] = "unknown"
        elif stale_after is not None and row["age"] > stale_after:
            row["status"] = "stale"
        else:
            row["status"] = "alive"
        if row["status"] == "alive":
            alive.append(host)
            if isinstance(row["step"], int):
                steps.append(row["step"])
        hosts[host] = row
    return {"hosts": hosts, "alive": alive,
            "min_step": min(steps) if steps else None,
            "max_step": max(steps) if steps else None}
