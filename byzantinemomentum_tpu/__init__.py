"""ByzantineMomentum-TPU — a TPU-native (JAX/XLA/pjit/Pallas) framework for
Byzantine-resilient distributed SGD experiments.

Re-designed from scratch after the capabilities of LPD-EPFL/ByzantineMomentum
("Distributed Momentum for Byzantine-resilient Stochastic Gradient Descent",
El-Mhamdi, Guerraoui, Rouault — ICLR 2021; reference `README.md:1-8`).

This is NOT a port: where the reference simulates n workers by n sequential
PyTorch backprops on one model (reference `attack.py:786-795`), this framework
computes the whole `(n, d)` gradient matrix in one `jax.vmap`'d XLA program;
where the reference's aggregation rules operate on Python lists of flat
tensors, ours are pure jnp kernels over the stacked `(n, d)` matrix that XLA
fuses and tiles onto the MXU; and the per-step training loop — momentum
placements, attack, defense, model update and the 24-column metric pipeline —
is a single jit-compiled function.

Subpackages:
  ops       Gradient aggregation rules (GARs) — the algorithmic kernels.
  attacks   Byzantine gradient synthesis (adaptive line-searched attacks).
  models    Pure-pytree neural networks (init/apply pairs).
  data      Host datasets: loaders, samplers, synthetic fallbacks.
  engine    The jitted training step, metrics, train state.
  cli       The experiment driver (reference `attack.py` parity).
  parallel  Mesh construction, sharded training step, distributed GARs.
  native    Host C++ tier of the four accelerated GARs (ctypes).
  utils     Registries, logging, key:value mini-language.
"""

import os

__version__ = "0.1.0"

from byzantinemomentum_tpu import utils  # noqa: F401
from byzantinemomentum_tpu import ops  # noqa: F401
from byzantinemomentum_tpu import attacks  # noqa: F401

# Opportunistic native tier, mirroring the reference's optional `import
# native` (reference `aggregators/median.py:22-26`): adds `cpp-<gar>`
# registry entries when the host toolchain is available. `BMT_NO_NATIVE=1`
# skips the attempt (and the one-time g++ build).
if not os.environ.get("BMT_NO_NATIVE"):
    from byzantinemomentum_tpu import native as _native

    _native.register_cpp_gars()
