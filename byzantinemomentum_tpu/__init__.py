"""ByzantineMomentum-TPU — a TPU-native (JAX/XLA/pjit/Pallas) framework for
Byzantine-resilient distributed SGD experiments.

Re-designed from scratch after the capabilities of LPD-EPFL/ByzantineMomentum
("Distributed Momentum for Byzantine-resilient Stochastic Gradient Descent",
El-Mhamdi, Guerraoui, Rouault — ICLR 2021; reference `README.md:1-8`).

This is NOT a port: where the reference simulates n workers by n sequential
PyTorch backprops on one model (reference `attack.py:786-795`), this framework
computes the whole `(n, d)` gradient matrix in one `jax.vmap`'d XLA program;
where the reference's aggregation rules operate on Python lists of flat
tensors, ours are pure jnp kernels over the stacked `(n, d)` matrix that XLA
fuses and tiles onto the MXU; and the per-step training loop — momentum
placements, attack, defense, model update and the 24-column metric pipeline —
is a single jit-compiled function.

Subpackages:
  ops       Gradient aggregation rules (GARs) — the algorithmic kernels.
  attacks   Byzantine gradient synthesis (adaptive line-searched attacks).
  models    Pure-pytree neural networks (init/apply pairs).
  data      Device-staged datasets with in-graph batch sampling.
  train     The jitted training step, metrics, checkpointing, host loop.
  parallel  Mesh construction, sharded training step, distributed GARs.
  utils     Registries, logging, key:value mini-language, job scheduler.
"""

__version__ = "0.1.0"

from byzantinemomentum_tpu import utils  # noqa: F401
from byzantinemomentum_tpu import ops  # noqa: F401
from byzantinemomentum_tpu import attacks  # noqa: F401
