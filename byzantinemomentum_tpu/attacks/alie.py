"""ALIE — "A Little Is Enough" with an explicit z-margin (Baruch,
Baruch, Goldberg, NeurIPS 2019; PAPERS.md).

The Byzantine rows sit at `mean + z * std` of the honest submissions,
coordinate-wise: INSIDE the honest variance envelope, where distance- and
score-based GARs cannot distinguish them from legitimate noise. The
existing `little` attack (`attacks/identical.py`) line-searches its
factor against the live defense; this registration implements the
paper's CLOSED-FORM margin instead — the largest z such that enough
honest workers are expected farther from the mean than the attackers:

    s = floor(n/2) + 1 - f        (honest supporters the attack needs)
    z_max = Phi^-1((n - f - s) / (n - f))

with `n` the total worker count and `f` the declared tolerance — so the
attack needs NO defense evaluations at all (it reads only the paper's
published diagnostics assumption: honest gradients are i.i.d. roughly
normal per coordinate). The `z` kwarg overrides the margin — the arena's
tournament sweeps it to trace the stealth/damage frontier — and `jitter`
adds deterministic per-row noise (a fraction of the honest std) so the
f_real rows are not byte-identical, the knob an adaptive adversary turns
to dodge collusion/duplicate detection (`obs/forensics.py`).
"""

import statistics

import jax
import jax.numpy as jnp
from jax import lax

from byzantinemomentum_tpu.attacks import empty_byzantine, register

__all__ = ["attack", "zmax"]


def zmax(n, f):
    """The paper's closed-form margin for `n` total workers, `f` of them
    Byzantine (clamped non-negative: a grid where the attackers are the
    majority has no hiding margin and degenerates to the mean). Pure
    host-side math on the STATIC (n, f) — the margin must stay a trace
    -time constant inside the jitted step."""
    s = n // 2 + 1 - f
    if s <= 0:
        return 0.0  # attacker majority: no supporters needed, no margin
    denom = max(n - f, 1)
    q = min(max((n - f - s) / denom, 0.5), 1.0 - 1e-6)
    return statistics.NormalDist().inv_cdf(q)


def _row_key(grad_honests):
    """Deterministic PRNG key from the operand content (attacks are pure
    functions of their inputs — no ambient RNG), the same content-hash
    trick as the engine's per-call mixture draw (`engine/step.py`)."""
    bits = lax.bitcast_convert_type(
        grad_honests.astype(jnp.float32), jnp.uint32)
    mult = (jnp.arange(bits.size, dtype=jnp.uint32).reshape(bits.shape)
            * jnp.uint32(2654435761) | jnp.uint32(1))
    return jax.random.fold_in(jax.random.PRNGKey(0xA11E),
                              jnp.sum(bits * mult, dtype=jnp.uint32))


def attack(grad_honests, f_decl, f_real, defense, z=None, jitter=0.0,
           **kwargs):
    """Generate the f_real Byzantine rows at `mean + z * std` (sample
    std, ddof=1 — torch parity with `attacks/identical.py`)."""
    if f_real == 0:
        return empty_byzantine(grad_honests)
    h = grad_honests.shape[0]
    mu = jnp.mean(grad_honests, axis=0)
    sigma = jnp.sqrt(jnp.var(grad_honests, axis=0, ddof=1)) if h > 1 else (
        jnp.zeros_like(mu))
    z_eff = zmax(h + f_real, f_decl) if z is None else float(z)
    byz = mu + z_eff * sigma
    rows = jnp.tile(byz[None, :], (f_real, 1))
    if jitter:
        noise = jax.random.normal(_row_key(grad_honests), rows.shape,
                                  dtype=rows.dtype)
        rows = rows + float(jitter) * sigma[None, :] * noise
    return rows


def check(grad_honests, f_real, defense, z=None, jitter=0.0, **kwargs):
    if grad_honests.shape[0] == 0:
        return "Expected a non-empty list of honest gradients"
    if not isinstance(f_real, int) or f_real < 0:
        return (f"Expected a non-negative number of Byzantine gradients to "
                f"generate, got {f_real!r}")
    if z is not None and not isinstance(z, (int, float)):
        return f"Expected a number for the z-margin, got {z!r}"
    if not isinstance(jitter, (int, float)) or jitter < 0:
        return f"Expected a non-negative jitter fraction, got {jitter!r}"


register("alie", attack, check)
