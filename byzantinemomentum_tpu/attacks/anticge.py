"""CGE-targeted attack (reference `attacks/anticge.py`).

Exploits CGE's norm-sort: when f_real <= f_decl, submit the negated sum of
the would-be-selected honest gradients, scaled to sit just under the
(n - f_decl)-th smallest honest norm so every Byzantine gradient is
selected (reference `anticge.py:49-78`); when f_real > f_decl, a Byzantine
gradient is necessarily selected, so send NaN (reference `anticge.py:59-63`).
"""

import jax.numpy as jnp

from byzantinemomentum_tpu.attacks import empty_byzantine, register
from byzantinemomentum_tpu.ops._common import sanitize_inf

__all__ = ["attack"]


def attack(grad_honests, f_decl, f_real, **kwargs):
    """Generate the f_real Byzantine gradients (reference `anticge.py:49-78`)."""
    if f_real == 0:
        return empty_byzantine(grad_honests)
    d = grad_honests.shape[1]
    if f_real > f_decl:
        return jnp.full((f_real, d), jnp.nan, dtype=grad_honests.dtype)
    h = grad_honests.shape[0]
    norms = sanitize_inf(jnp.sqrt(jnp.sum(grad_honests * grad_honests, axis=1)))
    order = jnp.argsort(norms, stable=True)
    maxpos = h - f_decl
    # Strictly below the (maxpos)-th smallest norm (reference uses
    # math.nextafter toward 0, `anticge.py:66-69`).
    maxnorm = jnp.nextafter(norms[order[maxpos]], jnp.float32(0))
    # Reference quirk preserved: the accumulator starts as a CLONE of the
    # smallest-norm gradient and the sum loop then adds it AGAIN
    # (reference `anticge.py:71-73`), so the direction is
    # 2*g(0) + g(1) + ... + g(maxpos-1).
    vec = grad_honests[order[0]] + jnp.sum(grad_honests[order[:maxpos]], axis=0)
    attnorm = jnp.sqrt(jnp.sum(vec * vec))
    scale = jnp.where(attnorm > 0, -maxnorm / attnorm, 1.0)
    byz_grad = vec * scale
    return jnp.tile(byz_grad[None, :], (f_real, 1))


def check(grad_honests, f_real, f_decl, **kwargs):
    if grad_honests.shape[0] == 0:
        return "Expected a non-empty list of honest gradients"
    if not isinstance(f_real, int) or f_real < 0:
        return f"Expected a non-negative number of Byzantine gradients to generate, got {f_real!r}"


register("anticge", attack, check)
