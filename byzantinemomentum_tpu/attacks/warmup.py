"""Time-coupled attack exploiting the defense's EWMA warm-up window —
the first STATEFUL attack (the `attacks/__init__.py` state hook).

The host-side suspicion machinery (`obs/forensics.py`) gates its
verdicts behind a warm-up of `min_steps` observations and smooths every
signal with an EWMA, so evidence accumulated during the first steps is
both un-actionable (no events fire) and discounted later (the EWMA
forgets geometrically). This attack reads that published behavior: for
the first `window` steps it bursts at full amplitude (`-burst * mean`,
the Fall-of-Empires direction, wrecking the cold momentum trajectory),
then drops INSIDE the honest variance envelope (ALIE rows at a small
`z`) for the rest of the run — by the time the tracker can act, the
burst is history it never got to punish.

State: one i32 step counter, threaded through `TrainState.attack_state`
by the engine (or by the arena loop's carry), so the schedule survives
checkpoints/resume and stays inside the jitted step.
"""

import jax.numpy as jnp

from byzantinemomentum_tpu.attacks import empty_byzantine, register

__all__ = ["attack", "state_init"]


def state_init(f_real, d):
    """i32 step counter (the only history the schedule needs)."""
    return jnp.int32(0)


def attack(grad_honests, f_decl, f_real, defense, state=None, window=12,
           burst=20.0, z=0.3, jitter=0.0, **kwargs):
    """Burst for `window` steps, then hide at `mean + z * std`."""
    if f_real == 0:
        return empty_byzantine(grad_honests), state
    from byzantinemomentum_tpu.attacks import alie as alie_mod

    step = jnp.int32(0) if state is None else state
    mu = jnp.mean(grad_honests, axis=0)
    hot = -float(burst) * mu
    hidden = alie_mod.attack(grad_honests, f_decl, f_real, defense,
                             z=float(z), jitter=jitter)
    rows = jnp.where(step < window,
                     jnp.tile(hot[None, :], (f_real, 1)), hidden)
    return rows.astype(grad_honests.dtype), step + 1


def check(grad_honests, f_real, defense, window=12, burst=20.0, z=0.3,
          jitter=0.0, **kwargs):
    if grad_honests.shape[0] == 0:
        return "Expected a non-empty list of honest gradients"
    if not isinstance(f_real, int) or f_real < 0:
        return (f"Expected a non-negative number of Byzantine gradients to "
                f"generate, got {f_real!r}")
    if not isinstance(window, int) or window < 0:
        return f"Expected a non-negative warm-up window, got {window!r}"
    if not isinstance(burst, (int, float)):
        return f"Expected a number for the burst amplitude, got {burst!r}"
    if not isinstance(z, (int, float)):
        return f"Expected a number for the hidden z-margin, got {z!r}"


register("alie-warmup", attack, check, state_init=state_init)
