"""Extension skeleton for a new attack (parity with reference
`attacks/template.py`).

Copy this file and implement the two functions: the plugin loader
(`attacks/__init__.py`) imports every module in this directory at package
load and the module registers itself at the bottom.

Like the reference (`attacks/template.py:48`), the skeleton itself registers
a runnable `"template"` entry whose `check` always fails with a template
message — `--attack template` resolves by name and then reports it is
template code, exactly as the reference does.
"""

__all__ = []


def attack(grad_honests, f_decl, f_real, defense, **kwargs):
    """Generate the Byzantine gradients.

    Args:
      grad_honests: f32[h, d] honest gradient matrix.
      f_decl: static int, declared Byzantine count (what the defense tolerates).
      f_real: static int, number of gradients to actually generate.
      defense: live aggregation rule `(gradients=f32[n,d], f=int) -> f32[d]`.
      **kwargs: attack-specific arguments from `--attack-args` (auto-typed).
    Returns:
      f32[f_real, d] Byzantine gradient matrix.

    Stateful variant (ADAPTIVE attacks threading history across steps):
    register with `register(name, attack, check, state_init=fn)` where
    `state_init(f_real, d) -> pytree` builds the initial state; the
    attack then additionally receives `state=<pytree>` and returns
    `(f32[f_real, d], new_state)`. The engine threads the pytree through
    `TrainState.attack_state` inside the jitted step (so the state is
    donated, checkpointed and resume-safe); static attacks like this
    template never see a `state` kwarg. Example: `attacks/warmup.py`
    (a step counter driving a time-coupled perturbation).
    """
    raise NotImplementedError(
        "I am template code, please replace me with useful stuff")


def check(grad_honests, f_decl, f_real, defense, **kwargs):
    """Return None if the arguments are valid, an error message otherwise.

    The template always declines (reference `attacks/template.py:33-42`)."""
    return "I am template code, you should not be using me"


from byzantinemomentum_tpu.attacks import register  # noqa: E402

register("template", attack, check)
