"""Extension skeleton for a new attack (parity with reference
`attacks/template.py`).

Copy this file, implement the two functions, and uncomment the registration:
the plugin loader (`attacks/__init__.py`) imports every module in this
directory at package load.
"""

__all__ = []


def attack(grad_honests, f_decl, f_real, defense, **kwargs):
    """Generate the Byzantine gradients.

    Args:
      grad_honests: f32[h, d] honest gradient matrix.
      f_decl: static int, declared Byzantine count (what the defense tolerates).
      f_real: static int, number of gradients to actually generate.
      defense: live aggregation rule `(gradients=f32[n,d], f=int) -> f32[d]`.
      **kwargs: attack-specific arguments from `--attack-args` (auto-typed).
    Returns:
      f32[f_real, d] Byzantine gradient matrix.
    """
    raise NotImplementedError


def check(grad_honests, f_decl, f_real, defense, **kwargs):
    """Return None if the arguments are valid, an error message otherwise."""
    if grad_honests.shape[0] == 0:
        return "Expected a non-empty list of honest gradients"


# from byzantinemomentum_tpu.attacks import register
# register("template", attack, check)
