"""Mimicry attack — the attacker BYTE-COPIES a victim's row.

Analyzed in `arena/quarantine.py` (PR 11) and now fielded: every
Byzantine row submits an exact copy of honest worker `victim`'s fresh
gradient. The submission is perfectly in-envelope — no GAR can reject it
on geometry (it IS an honest gradient) — so the attack pressure is
entirely on the TRUST machinery:

* the duplicated mass biases mean-family rules toward the victim's draw
  and hands selection-family rules a self-certifying cluster (f_real + 1
  identical rows out-vote genuine neighborhoods in Krum-style scoring);
* the collusion detector sees a near-duplicate cluster CONTAINING THE
  VICTIM — a framing vector: naive dedup that evicts whole clusters
  would evict an honest worker on the attacker's schedule.

The quarantine policy's answer (the contract `tests/test_arena.py` pins
as the tournament regression): cluster dedup keeps the lowest-collusion
member with ties to the LOWEST ROW INDEX — honest rows precede attack
rows in the stacked matrix, and a mimicry victim's row is byte-identical
to its copies anyway, so the kept representative preserves the victim's
information regardless. The copies are evicted (collusion channel,
quorum reclaimed), the victim never is: zero honest evictions.

`jitter` (fraction of the honest std, like `framing`) blurs the copies to
probe the collusion detector's near-duplicate threshold — the crossover
knob of the arms-race rung (ROADMAP arena item).
"""

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu.attacks import empty_byzantine, register

__all__ = ["attack"]


def attack(grad_honests, f_decl, f_real, defense, victim=0, jitter=0.0,
           **kwargs):
    """f_real byte-copies of honest row `victim` (optionally jittered)."""
    if f_real == 0:
        return empty_byzantine(grad_honests)
    rows = jnp.tile(grad_honests[victim][None, :], (f_real, 1))
    if jitter:
        from byzantinemomentum_tpu.attacks import alie as alie_mod

        h = grad_honests.shape[0]
        sigma = (jnp.sqrt(jnp.var(grad_honests, axis=0, ddof=1)) if h > 1
                 else jnp.zeros_like(rows[0]))
        noise = jax.random.normal(alie_mod._row_key(grad_honests),
                                  rows.shape, dtype=rows.dtype)
        rows = rows + float(jitter) * sigma[None, :] * noise
    return rows


def check(grad_honests, f_real, defense, victim=0, jitter=0.0, **kwargs):
    if grad_honests.shape[0] == 0:
        return "Expected a non-empty list of honest gradients"
    if not isinstance(f_real, int) or f_real < 0:
        return (f"Expected a non-negative number of Byzantine gradients "
                f"to generate, got {f_real!r}")
    if not isinstance(victim, int) or not (
            0 <= victim < grad_honests.shape[0]):
        return (f"Expected a victim index within the "
                f"{grad_honests.shape[0]} honest rows, got {victim!r}")
    if not isinstance(jitter, (int, float)) or jitter < 0:
        return f"Expected a non-negative jitter fraction, got {jitter!r}"


register("mimic", attack, check)
