"""Framing attack — the adversary attacks the DEFENSE's trust model, not
the aggregate: it tries to get an honest worker auto-quarantined.

A suspicion-driven quarantine loop (`arena/quarantine.py`) turns
statistical evidence — selection deficit, distance z-scores — into
evictions. That creates a new attack surface: instead of biasing the
aggregate, the Byzantine rows can spend their mass making a chosen
honest `victim` look like the outlier. The rows here sit in a tight
cluster at the mean of the honests EXCLUDING the victim, pushed `push`
further away from the victim's row: the cluster (a) dominates the
selection of score-based GARs (its members certify each other, the
Krum/Bulyan colluder pattern), starving the victim's selection rate, and
(b) shifts the submission cloud so the victim's relative distance
z-score rises.

The quarantine policy's answer — eviction hysteresis, a max-evictions
budget, and the statistical channels capped below the eviction threshold
when unconfirmed by hard (collusion) evidence — is exactly what the
tournament's "zero honest evictions under framing" acceptance row
proves. The attackers themselves ARE mutually identical (a collusion
cluster), so the dedup channel evicts the cluster instead; `jitter`
(fraction of the honest std) is the knob to blur the cluster and trade
framing pressure against self-exposure.
"""

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu.attacks import empty_byzantine, register

__all__ = ["attack"]


def attack(grad_honests, f_decl, f_real, defense, victim=0, push=1.0,
           jitter=0.0, **kwargs):
    """f_real rows clustered at mean(honests \\ victim) + push * (that
    mean - victim's row)."""
    if f_real == 0:
        return empty_byzantine(grad_honests)
    h = grad_honests.shape[0]
    g_victim = grad_honests[victim]
    others = (jnp.sum(grad_honests, axis=0) - g_victim) / max(h - 1, 1)
    byz = others + float(push) * (others - g_victim)
    rows = jnp.tile(byz[None, :], (f_real, 1))
    if jitter:
        from byzantinemomentum_tpu.attacks import alie as alie_mod

        sigma = jnp.sqrt(jnp.var(grad_honests, axis=0, ddof=1)) if h > 1 \
            else jnp.zeros_like(byz)
        noise = jax.random.normal(alie_mod._row_key(grad_honests),
                                  rows.shape, dtype=rows.dtype)
        rows = rows + float(jitter) * sigma[None, :] * noise
    return rows


def check(grad_honests, f_real, defense, victim=0, push=1.0, jitter=0.0,
          **kwargs):
    if grad_honests.shape[0] == 0:
        return "Expected a non-empty list of honest gradients"
    if not isinstance(f_real, int) or f_real < 0:
        return (f"Expected a non-negative number of Byzantine gradients to "
                f"generate, got {f_real!r}")
    if not isinstance(victim, int) or not (
            0 <= victim < grad_honests.shape[0]):
        return (f"Expected a victim index within the {grad_honests.shape[0]} "
                f"honest rows, got {victim!r}")
    if not isinstance(push, (int, float)) or push < 0:
        return f"Expected a non-negative push factor, got {push!r}"


register("framing", attack, check)
