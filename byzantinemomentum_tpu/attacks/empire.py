"""Strict "Fall of Empires" attack: byz = -epsilon * honest_average
(reference `attacks/empire.py`; paper: Xie, Koyejo, Gupta, UAI 2019).

Negative `epsilon` triggers the adaptive search over the live defense with
`ceil(-epsilon)` evaluations (reference `empire.py:51-59`).
"""

import math

import jax.numpy as jnp

from byzantinemomentum_tpu.attacks import empty_byzantine, register
from byzantinemomentum_tpu.ops.linesearch import line_maximize

__all__ = ["attack"]


def attack(grad_honests, f_decl, f_real, defense, epsilon=1, **kwargs):
    """Generate the f_real Byzantine gradients (reference `empire.py:29-64`)."""
    if f_real == 0:
        return empty_byzantine(grad_honests)
    grad_avg = jnp.mean(grad_honests, axis=0)

    if epsilon < 0:
        def eval_epsilon(x):
            byz = grad_avg * (-x)
            stacked = jnp.concatenate([grad_honests, jnp.tile(byz[None, :], (f_real, 1))])
            aggregated = defense(gradients=stacked, f=f_decl) - grad_avg
            return jnp.dot(aggregated, aggregated)

        epsilon_eff = line_maximize(eval_epsilon, evals=math.ceil(-epsilon))
    else:
        epsilon_eff = epsilon

    byz_grad = grad_avg * (-epsilon_eff)
    return jnp.tile(byz_grad[None, :], (f_real, 1))


def check(grad_honests, f_real, defense, epsilon=1, **kwargs):
    if grad_honests.shape[0] == 0:
        return "Expected a non-empty list of honest gradients"
    if not isinstance(f_real, int) or f_real < 0:
        return f"Expected a non-negative number of Byzantine gradients to generate, got {f_real!r}"
    if not callable(defense):
        return f"Expected a callable for the aggregation rule, got {defense!r}"
    if not isinstance(epsilon, int) or epsilon == 0:
        return f"Expected a non-zero attack epsilon, got {epsilon!r}"


register("empire-strict", attack, check)
