"""Identical-gradient attack family: `empire`, `little`, `bulyan`
(reference `attacks/identical.py`; papers cited there: Fall of Empires,
A Little is Enough, The Hidden Vulnerability).

Each attack submits f_real copies of `avg + factor * direction`, where the
direction is attack-specific and the factor is either fixed (positive
`factor`) or found by line-searching the live defense's output displacement
`||GAR(honest + byz) - avg||^2` with `ceil(-factor)` evaluations when
`factor` is negative (reference `identical.py:66-77`).

TPU design: the line search is `ops.linesearch.line_maximize` — a
`lax.while_loop` whose body inlines the defense kernel, so the up-to-16
defense evaluations stay inside the jitted training step.
"""

import math

import jax.numpy as jnp

from byzantinemomentum_tpu.attacks import empty_byzantine, register
from byzantinemomentum_tpu.ops.linesearch import line_maximize

__all__ = ["make_attack"]


def make_attack(compute_direction):
    """Build the attack closure for a direction function
    `(grad_stack, grad_avg, **kwargs) -> f32[d]`
    (reference `attacks/identical.py:38-88`)."""

    def attack(grad_honests, f_decl, f_real, defense, factor=-16, negative=False, **kwargs):
        if f_real == 0:
            return empty_byzantine(grad_honests)
        grad_avg = jnp.mean(grad_honests, axis=0)
        grad_att = compute_direction(grad_honests, grad_avg, **kwargs)

        if factor < 0:
            # Adaptive factor: maximize the defense output displacement
            # (reference `identical.py:66-77`).
            def eval_factor(x):
                eff = -x if negative else x
                byz = grad_avg + eff * grad_att
                stacked = jnp.concatenate([grad_honests, jnp.tile(byz[None, :], (f_real, 1))])
                aggregated = defense(gradients=stacked, f=f_decl) - grad_avg
                return jnp.dot(aggregated, aggregated)

            factor_eff = line_maximize(eval_factor, evals=math.ceil(-factor))
            factor_eff = -factor_eff if negative else factor_eff
        else:
            factor_eff = -factor if negative else factor

        byz_grad = grad_avg + factor_eff * grad_att
        return jnp.tile(byz_grad[None, :], (f_real, 1))

    return attack


def check(grad_honests, f_real, defense, factor=-16, negative=False, **kwargs):
    """Parameter validity (reference `attacks/identical.py:91-108`)."""
    if grad_honests.shape[0] == 0:
        return "Expected a non-empty list of honest gradients"
    if not isinstance(f_real, int) or f_real < 0:
        return f"Expected a non-negative number of Byzantine gradients to generate, got {f_real!r}"
    if not callable(defense):
        return f"Expected a callable for the aggregation rule, got {defense!r}"
    if not ((isinstance(factor, float) and factor > 0) or (isinstance(factor, int) and factor != 0)):
        return f"Expected a positive number or a negative integer for the attack factor, got {factor!r}"
    if not isinstance(negative, bool):
        return f"Expected a boolean for optional parameter 'negative', got {negative!r}"


def direction_bulyan(grad_stack, grad_avg, target_idx=-1, **kwargs):
    """Single-coordinate (or all-ones) direction, "The Hidden Vulnerability"
    (reference `attacks/identical.py:114-127`)."""
    if target_idx == "all":
        return jnp.ones_like(grad_avg)
    if not isinstance(target_idx, int):
        raise ValueError(f'Expected an integer or "all" for target_idx, got {target_idx!r}')
    return jnp.zeros_like(grad_avg).at[target_idx].set(1.0)


def direction_empire(grad_stack, grad_avg, **kwargs):
    """Negated honest average, "Fall of Empires"
    (reference `attacks/identical.py:129-134`)."""
    return -grad_avg


def direction_little(grad_stack, grad_avg, **kwargs):
    """Coordinate-wise sample standard deviation, "A Little is Enough"
    (reference `attacks/identical.py:136-141`; torch `.var` is unbiased)."""
    return jnp.sqrt(jnp.var(grad_stack, axis=0, ddof=1))


for _name, _direction in (("bulyan", direction_bulyan), ("empire", direction_empire),
                          ("little", direction_little)):
    register(_name, make_attack(_direction), check)
